"""Expert-parallel GPT pretraining example (north-star extension).

No reference counterpart (NVIDIA Apex has no MoE); this is the usage
pattern for the TPU-native additions: ``GPTConfig.num_experts`` routes
every layer's FFN through ``transformer.moe`` (top-k capacity routing,
experts sharded over the dp(=ep) mesh axis via ``all_to_all``, TP-split
expert weights), with the router load-balance loss added by ``gpt_loss``.

Run (8 virtual devices, synthetic data):

    JAX_PLATFORMS=cpu python examples/moe_gpt/main.py --steps 20

On a real slice drop the platform pin and set --tp to taste; experts ride
the dp axis, so dp * tp = chip count and num_experts % dp == 0.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    replicate_loss,
)
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--experts", type=int, default=0,
                   help="0 = one expert per dp rank")
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-3)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    mesh = build_mesh(tp=args.tp, pp=1, sp=1)
    dp = mesh.shape["dp"]
    experts = args.experts or dp
    cfg = GPTConfig(vocab_size=1024, max_seq=args.seq, hidden=args.hidden,
                    num_layers=args.layers,
                    num_heads=max(args.hidden // 16, 1),
                    dtype=jnp.float32, num_experts=experts,
                    moe_top_k=args.top_k, hidden_dropout=0.1)
    try:
        cfg.validate(tp=args.tp)  # MoEConfig owns top_k/expert checks
    except ValueError as e:
        hint = (f" (on a {dp}-way dp mesh the default expert count is {dp}; "
                f"pass --experts / --top-k explicitly)"
                if "top_k" in str(e) else "")
        raise SystemExit(f"{e}{hint}") from e
    if experts % dp:
        raise SystemExit(f"--experts ({experts}) must divide dp ({dp})")

    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    specs = gpt_param_specs(cfg)
    opt = FusedAdam(lr=args.lr)
    opt_state = opt.init(params)

    def loss_fn(p, tok, tgt, dkey):
        # dkey is shared across dp ranks on purpose: the reference's RNG
        # policy gives data-parallel ranks the SAME dropout stream (only
        # tp/pp ranks diverge, tensor_parallel/random.py) — rank r's i-th
        # sample shares a mask with rank q's i-th sample, which Megatron
        # accepts as benign cross-sample correlation.
        def body(p, tok, tgt):
            return replicate_loss(gpt_loss(p, tok, tgt, cfg,
                                           dropout_key=dkey),
                                  mesh, masked_axis=None)

        return shard_map(body, mesh=mesh,
                         in_specs=(specs, P("dp"), P("dp")),
                         out_specs=P())(p, tok, tgt)

    @jax.jit
    def train_step(params, opt_state, tok, tgt, dkey):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt, dkey)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    key = jax.random.PRNGKey(1)
    print(f"mesh dp={dp} tp={args.tp}; {experts} experts "
          f"({experts // dp}/rank), top-{args.top_k}")
    t0 = time.perf_counter()
    for step in range(args.steps):
        key, kd, kb = jax.random.split(key, 3)
        tok = jax.random.randint(kb, (args.batch, args.seq), 0,
                                 cfg.vocab_size)
        tgt = jnp.roll(tok, -1, axis=1)
        params, opt_state, loss = train_step(params, opt_state, tok, tgt, kd)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss):.4f}  "
                  f"({time.perf_counter() - t0:.1f}s)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""T5-style encoder-decoder pretraining through the enc-dec pipeline.

Reference capability: ``ModelType.encoder_and_decoder`` training with a
pipeline split at ``pipeline_model_parallel_split_rank`` (apex
``transformer/pipeline_parallel/schedules/common.py:72-103``); the usage
pattern here drives the TPU re-design instead — the two-phase enc-dec
ring (``schedules.fwd_bwd_enc_dec``) over a pp×dp mesh, where every stage
holds one encoder AND one decoder chunk.

Run (8 virtual devices, synthetic span-corruption-shaped data):

    JAX_PLATFORMS=cpu python examples/t5_encdec/main.py --steps 10

On a real slice drop the platform pin; enc/dec layer counts must divide
--pp.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_enc_dec,
)
from apex_tpu.transformer.testing import (
    T5Config,
    t5_enc_dec_spec,
    t5_pipeline_params,
    t5_pipeline_specs_tree,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--pp", type=int, default=2)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--megatron-sp", action="store_true",
                   help="sequence-sharded activation regions over tp")
    p.add_argument("--relative-position-bias", action="store_true",
                   help="T5's bucketed relative position biases (in-kernel "
                        "flash-attention bias path) instead of learned "
                        "absolute positions")
    p.add_argument("--encoder-final-ln", action="store_true",
                   help="T5's encoder-exit LayerNorm (applied at decoder "
                        "memory consumption)")
    p.add_argument("--microbatches", type=int, default=2)
    p.add_argument("--batch", type=int, default=0,
                   help="global batch (0 = 2 * dp * microbatches)")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--enc-layers", type=int, default=2)
    p.add_argument("--dec-layers", type=int, default=2)
    p.add_argument("--seq-enc", type=int, default=32)
    p.add_argument("--seq-dec", type=int, default=16)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--dropout", type=float, default=0.0,
                   help="hidden-dropout rate routed through the enc-dec "
                        "schedule (per-microbatch keys; round-5 wiring)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        pipeline_model_parallel_size_=args.pp,
        # pp=1 runs encoder+decoder on the one stage: no split rank exists
        # (0 < split < pp is unsatisfiable), so pass None
        pipeline_model_parallel_split_rank_=(args.pp // 2 or None),
    )
    dp = mesh.shape["dp"]
    cfg = T5Config(vocab_size=1024, hidden=args.hidden,
                   num_heads=max(args.hidden // 16, 1),
                   enc_layers=args.enc_layers, dec_layers=args.dec_layers,
                   max_seq_enc=args.seq_enc, max_seq_dec=args.seq_dec,
                   dtype=jnp.float32, fused_loss=False,
                   megatron_sp=args.megatron_sp,
                   relative_position_bias=args.relative_position_bias,
                   encoder_final_ln=args.encoder_final_ln,
                   hidden_dropout=args.dropout)
    cfg.validate(tp=args.tp)
    params = t5_pipeline_params(jax.random.PRNGKey(0), cfg, pp=args.pp)
    spec = t5_enc_dec_spec(cfg, dropout=args.dropout > 0.0)
    specs_tree = t5_pipeline_specs_tree(cfg)
    opt = FusedAdam(lr=args.lr)
    opt_state = opt.init(params)
    M = args.microbatches
    batch = args.batch or 2 * dp * M

    @jax.jit
    def train_step(params, opt_state, enc_tok, dec_tok, tgt, dkey):
        loss, grads = forward_backward_pipelining_enc_dec(
            spec, params, (enc_tok, dec_tok, tgt), num_microbatches=M,
            mesh=mesh, params_specs=specs_tree,
            dropout_key=dkey if args.dropout > 0.0 else None)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    key = jax.random.PRNGKey(1)
    print(f"mesh dp={dp} pp={args.pp} tp={args.tp}"
          f"{' +megatron_sp' if args.megatron_sp else ''}; "
          f"enc {cfg.enc_layers}L / dec "
          f"{cfg.dec_layers}L, {M} microbatches, batch {batch}")
    t0 = time.perf_counter()
    for step in range(args.steps):
        key, ke, kd, kdrop = jax.random.split(key, 4)
        enc_tok = jax.random.randint(ke, (batch, args.seq_enc), 0,
                                     cfg.vocab_size)
        dec_tok = jax.random.randint(kd, (batch, args.seq_dec), 0,
                                     cfg.vocab_size)
        tgt = jnp.roll(dec_tok, -1, axis=1)
        params, opt_state, loss = train_step(params, opt_state, enc_tok,
                                             dec_tok, tgt, kdrop)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss):.4f}  "
                  f"({time.perf_counter() - t0:.1f}s)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

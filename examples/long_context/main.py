"""Long-context GPT pretraining over the ring-SP axis (north-star
extension).

No reference counterpart (NVIDIA Apex has no context parallelism); this
is the usage pattern for the TPU-native long-context stack: the sequence
is sharded over the ``sp`` mesh axis, attention runs as the K/V ring
(``transformer.sequence_parallel.ring_attention`` — exact global
attention, O(s_local²) peak score memory per device), and the full GPT-2
training config runs with BOTH dropouts on: hidden masks fold the
sp/tp ranks so every shard drops independent positions, attention masks
are keyed by GLOBAL positions so the ring drops exactly what a dense
kernel would with the same seed (sharding is invisible to the stream).

Run (8 virtual devices, synthetic data, global seq = 512 over sp=8;
raise --seq on real chips):

    JAX_PLATFORMS=cpu python examples/long_context/main.py --steps 10

On a real slice drop the platform pin; at sp=8 a 32k-token context fits
where dense attention cannot (see PERF.md's ring memory study and
benchmarks/long_seq_tpu.py for the measured rows). ``--tp`` composes
Megatron-TP with the ring (megatron_sp shards the LN/dropout regions by
sequence on top).

Reference parity note: ``apex.transformer`` stops at tensor/pipeline
parallelism (SURVEY.md §2.3); sequence parallelism of this form is the
capability the reference lacks.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    replicate_loss,
)
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--sp", type=int, default=8,
                   help="ring size: each device holds seq/sp tokens and "
                        "K/V chunks rotate sp times per attention")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--batch", type=int, default=2,
                   help="PER-dp-RANK batch (global batch = batch * dp; "
                        "the moe_gpt example's --batch is global)")
    p.add_argument("--seq", type=int, default=512,
                   help="GLOBAL sequence length (sharded over sp); the "
                        "CPU-smoke default is small — raise it on real "
                        "chips (32k fits at sp=8, see PERF.md)")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--attention-dropout", type=float, default=0.1)
    p.add_argument("--hidden-dropout", type=float, default=0.1)
    p.add_argument("--lr", type=float, default=1e-3)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.seq % args.sp:
        raise SystemExit(
            f"--seq ({args.seq}) must be divisible by --sp ({args.sp})")
    mesh = build_mesh(tp=args.tp, pp=1, sp=args.sp)
    dp = mesh.shape["dp"]
    cfg = GPTConfig(vocab_size=1024, max_seq=args.seq, hidden=args.hidden,
                    num_layers=args.layers,
                    num_heads=max(args.hidden // 16, 1),
                    dtype=jnp.float32, megatron_sp=args.tp > 1,
                    attention_dropout=args.attention_dropout,
                    hidden_dropout=args.hidden_dropout)
    cfg.validate(tp=args.tp)

    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    specs = gpt_param_specs(cfg)
    opt = FusedAdam(lr=args.lr)
    opt_state = opt.init(params)

    def loss_fn(p, tok, tgt, dkey):
        def body(p, tok, tgt):
            return replicate_loss(gpt_loss(p, tok, tgt, cfg,
                                           dropout_key=dkey),
                                  mesh, masked_axis=None)

        # data sharded (batch over dp) x (sequence over sp): each device
        # holds its shard's tokens; the ring rotates K/V, never the
        # full sequence
        return shard_map(body, mesh=mesh,
                         in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
                         out_specs=P())(p, tok, tgt)

    @jax.jit
    def train_step(params, opt_state, tok, tgt, dkey):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt, dkey)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    key = jax.random.PRNGKey(1)
    print(f"mesh dp={dp} sp={args.sp} tp={args.tp}; global seq {args.seq} "
          f"({args.seq // args.sp}/device), attn/hidden dropout "
          f"{args.attention_dropout}/{args.hidden_dropout}")
    t0 = time.perf_counter()
    for step in range(args.steps):
        key, kd, kb = jax.random.split(key, 3)
        tok = jax.random.randint(kb, (args.batch * dp, args.seq), 0,
                                 cfg.vocab_size)
        tgt = jnp.roll(tok, -1, axis=1)
        params, opt_state, loss = train_step(params, opt_state, tok, tgt,
                                             kd)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss):.4f}  "
                  f"({time.perf_counter() - t0:.1f}s)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Minimal DDP example (ref ``examples/simple/distributed/
distributed_data_parallel.py``): a linear model trained data-parallel over
every device with the bucketed-allreduce DDP helper, made fault-tolerant
with the ``resilience`` layer — an in-graph anomaly guard around the
update, atomic auto-resumed checkpoints, and a SIGTERM save-and-exit path.
Run directly; on a CPU-only machine set
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` to fake a mesh.
``--chaos-step K`` injects a NaN gradient at step K to watch the guard
absorb it."""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.monitor import Metrics
from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.parallel.mesh import DP_AXIS, build_mesh
from apex_tpu.resilience import (
    AnomalyGuard,
    CheckpointManager,
    GuardPolicy,
    PreemptionHandler,
    chaos,
)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--checkpoint-dir", default="",
                    help="atomic checkpoints + auto-resume + SIGTERM save")
    ap.add_argument("--save-freq", type=int, default=50)
    ap.add_argument("--chaos-step", type=int, default=-1,
                    help="inject a NaN gradient at this step (guard demo)")
    args = ap.parse_args(argv)

    # TPU matmuls default to bf16 accumulation; this toy regression needs f32
    jax.config.update("jax_default_matmul_precision", "highest")
    mesh = build_mesh(tp=1, pp=1, sp=1)
    dp = mesh.shape[DP_AXIS]
    ddp = DistributedDataParallel()
    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip", skip_budget=3))

    params = {"w": jnp.zeros((8,)), "b": jnp.zeros(())}
    n = 128  # fixed global sample count (divisible by any dp in 1..8)
    x = jax.random.normal(jax.random.PRNGKey(0), (n, 8))
    true_w = jnp.arange(8.0)
    y = x @ true_w + 0.5

    def body(params, gstate, metrics, x, y, it):
        def loss_fn(p):
            pred = x @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)

        grads = jax.grad(loss_fn)(ddp.replicate(params))
        grads = ddp.average_gradients(grads)
        if args.chaos_step >= 0:
            grads = chaos.inject_nonfinite(grads, it, args.chaos_step)
        proposed = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
        # guard: a non-finite grad never reaches the params — the bad step
        # is skipped (then rolled back / halted if it persists), and the
        # counters ride the Metrics pytree. axis_names makes both the flag
        # and the counters rank-uniform (every replica takes the same
        # branch and logs the same totals).
        bad, metrics = guard.check(grads=grads, metrics=metrics,
                                   axis_names=DP_AXIS)
        params, gstate, metrics = guard.apply(
            gstate, bad, proposed, params, metrics=metrics)
        return params, gstate, metrics

    gstate = guard.init(params)
    # pre-seed the counter names: the Metrics treedef stays fixed across
    # steps, so the jitted step never retraces (the monitor contract)
    metrics = Metrics({"anomalies_total": 0.0, "nonfinite_grads_total": 0.0,
                       "guard_skips_total": 0.0, "rollbacks_total": 0.0,
                       "guard_halted": 0.0})
    step = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), P(DP_AXIS), P(DP_AXIS), P()),
        out_specs=(P(), P(), P())))

    mgr = CheckpointManager(args.checkpoint_dir) if args.checkpoint_dir \
        else None
    pre = PreemptionHandler() if mgr is not None else None
    start = 0
    if mgr is not None and mgr.latest_valid() is not None:
        (params, gstate, metrics), start = mgr.restore(
            target=(params, gstate, metrics))
        print(f"=> auto-resumed at step {start}")

    for it in range(start, args.steps):
        params, gstate, metrics = step(params, gstate, metrics, x, y,
                                       jnp.asarray(it))
        guard.raise_if_halted(gstate)
        if pre is not None:
            save_at = pre.sync_save_step(it)
            if save_at is not None:
                mgr.save((params, gstate, metrics), save_at + 1, block=True)
                print(f"=> preempted: saved at step {save_at + 1}, exiting")
                return
        if mgr is not None and (it + 1) % args.save_freq == 0:
            mgr.save((params, gstate, metrics), it + 1)
    err = float(jnp.abs(params["w"] - true_w).max())
    stats = metrics.as_dict()
    print(f"w error after {args.steps} steps: {err:.4f}  "
          f"(anomalies={stats['anomalies_total']:.0f} "
          f"skips={stats['guard_skips_total']:.0f})")
    if mgr is not None:
        mgr.close()
    assert err < 0.05


if __name__ == "__main__":
    main()

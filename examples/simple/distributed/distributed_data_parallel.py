"""Minimal DDP example (ref ``examples/simple/distributed/
distributed_data_parallel.py``): a linear model trained data-parallel over
every device with the bucketed-allreduce DDP helper. Run directly; on a
CPU-only machine set ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
to fake a mesh."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.parallel.mesh import DP_AXIS, build_mesh


def main():
    # TPU matmuls default to bf16 accumulation; this toy regression needs f32
    jax.config.update("jax_default_matmul_precision", "highest")
    mesh = build_mesh(tp=1, pp=1, sp=1)
    dp = mesh.shape[DP_AXIS]
    ddp = DistributedDataParallel()

    params = {"w": jnp.zeros((8,)), "b": jnp.zeros(())}
    n = 128  # fixed global sample count (divisible by any dp in 1..8)
    x = jax.random.normal(jax.random.PRNGKey(0), (n, 8))
    true_w = jnp.arange(8.0)
    y = x @ true_w + 0.5

    def body(params, x, y):
        def loss_fn(p):
            pred = x @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)

        grads = jax.grad(loss_fn)(ddp.replicate(params))
        grads = ddp.average_gradients(grads)
        return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)

    step = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(), params), P(DP_AXIS), P(DP_AXIS)),
        out_specs=jax.tree.map(lambda _: P(), params)))

    for it in range(200):
        params = step(params, x, y)
    err = float(jnp.abs(params["w"] - true_w).max())
    print(f"w error after 200 steps: {err:.4f}")
    assert err < 0.05


if __name__ == "__main__":
    main()

"""Minimal distributed-training example (ref ``examples/simple/distributed/
distributed_data_parallel.py``): a linear model trained over every device,
with the parallelism strategy picked by ONE declarative
``ParallelismPlan`` preset instead of hand-wired flags:

* ``--plan ddp``    — replicated params, bucketed-allreduce DDP (plus the
  full resilience wiring: in-graph anomaly guard, atomic auto-resumed
  checkpoints, SIGTERM save-and-exit, ``--chaos-step`` NaN injection);
* ``--plan zero1``  — ``DistributedFusedAdam``: dp-sharded optimizer
  state, grads reduce-scattered, params all-gathered by the optimizer;
* ``--plan fsdp``   — ``apex_tpu.fsdp``: parameters sharded too; the
  forward gathers on demand and the backward reduce-scatters gradients
  straight into shard layout;
* ``--plan fsdp+tp`` — the same FSDP engine on a dp×tp mesh (this toy
  model defines no tensor-parallel layers, so tp only replicates compute —
  the point is that the PLAN resolves the composed mesh; see
  ``benchmarks/bench_fsdp.py`` for fsdp+tp on the TP GPT).

Run directly; on a CPU-only machine set
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` to fake a mesh.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.monitor import Metrics
from apex_tpu.parallel import ParallelismPlan
from apex_tpu.parallel.mesh import DP_AXIS
from apex_tpu.resilience import (
    AnomalyGuard,
    CheckpointManager,
    GuardPolicy,
    PreemptionHandler,
    TrainSupervisor,
    chaos,
    replicated_spec,
)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--plan", default="ddp",
                    choices=["ddp", "zero1", "fsdp", "fsdp+tp"],
                    help="ParallelismPlan preset (replaces the old "
                         "hand-wired DDP/ZeRO knobs)")
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--checkpoint-dir", default="",
                    help="atomic checkpoints + auto-resume + SIGTERM save")
    ap.add_argument("--save-freq", type=int, default=50)
    ap.add_argument("--chaos-step", type=int, default=-1,
                    help="inject a NaN gradient at this step "
                         "(guard demo; --plan ddp only)")
    ap.add_argument("--elastic", action="store_true",
                    help="drive the sharded loop through TrainSupervisor "
                         "with an elastic checkpoint spec: checkpoints "
                         "restore at a DIFFERENT --plan dp degree (the "
                         "restart manifest names the legal ones); needs "
                         "--checkpoint-dir and a zero1/fsdp plan")
    return ap.parse_args(argv)


def _data():
    n = 128  # fixed global sample count (divisible by any dp in 1..8)
    x = jax.random.normal(jax.random.PRNGKey(0), (n, 8))
    true_w = jnp.arange(8.0)
    y = x @ true_w + 0.5
    return x, y, true_w


def _loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _train_ddp(args, plan, mesh, params, x, y):
    """The original resilience-wired DDP loop, constructed from the plan."""
    ddp = plan.ddp()
    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip", skip_budget=3))

    def body(params, gstate, metrics, x, y, it):
        grads = jax.grad(_loss)(ddp.replicate(params), x, y)
        grads = ddp.average_gradients(grads)
        if args.chaos_step >= 0:
            grads = chaos.inject_nonfinite(grads, it, args.chaos_step)
        proposed = jax.tree.map(lambda p, g: p - args.lr * g, params, grads)
        # guard: a non-finite grad never reaches the params — the bad step
        # is skipped (then rolled back / halted if it persists), and the
        # counters ride the Metrics pytree. axis_names makes both the flag
        # and the counters rank-uniform.
        bad, metrics = guard.check(grads=grads, metrics=metrics,
                                   axis_names=DP_AXIS)
        params, gstate, metrics = guard.apply(
            gstate, bad, proposed, params, metrics=metrics)
        return params, gstate, metrics

    gstate = guard.init(params)
    # pre-seed the counter names: the Metrics treedef stays fixed across
    # steps, so the jitted step never retraces (the monitor contract)
    metrics = Metrics({"anomalies_total": 0.0, "nonfinite_grads_total": 0.0,
                       "guard_skips_total": 0.0, "rollbacks_total": 0.0,
                       "guard_halted": 0.0})
    step = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), P(DP_AXIS), P(DP_AXIS), P()),
        out_specs=(P(), P(), P())))

    mgr = CheckpointManager(args.checkpoint_dir) if args.checkpoint_dir \
        else None
    pre = PreemptionHandler() if mgr is not None else None
    start = 0
    if mgr is not None and mgr.latest_valid() is not None:
        (params, gstate, metrics), start = mgr.restore(
            target=(params, gstate, metrics))
        print(f"=> auto-resumed at step {start}")

    for it in range(start, args.steps):
        params, gstate, metrics = step(params, gstate, metrics, x, y,
                                       jnp.asarray(it))
        guard.raise_if_halted(gstate)
        if pre is not None:
            save_at = pre.sync_save_step(it)
            if save_at is not None:
                mgr.save((params, gstate, metrics), save_at + 1, block=True)
                print(f"=> preempted: saved at step {save_at + 1}, exiting")
                # None params = "no final state to validate": main skips
                # the convergence assert on this clean save-and-exit path
                return None, metrics
        if mgr is not None and (it + 1) % args.save_freq == 0:
            mgr.save((params, gstate, metrics), it + 1)
    if mgr is not None:
        mgr.close()
    return params, metrics


def _train_sharded(args, plan, mesh, params, x, y):
    """zero1 / fsdp / fsdp+tp: the sharded-optimizer loops, built entirely
    from the plan (no strategy-specific wiring beyond the state specs)."""
    opt = plan.build_optimizer(lr=args.lr)
    pspecs = jax.tree.map(lambda _: P(), params)
    shard = jax.tree.map(lambda _: P(DP_AXIS), params)

    if plan.data == "fsdp":
        from apex_tpu.fsdp import FSDPAdamState

        fsdp = plan.fsdp()
        meta = fsdp.meta(params)
        sspec = FSDPAdamState(count=P(), master=shard, mu=shard, nu=shard)

        def init_fn(p):
            return opt.init(p)

        def body(st, x, y):
            def loss_fn(master):
                return _loss(fsdp.gather(master, meta), x, y)

            l, g = jax.value_and_grad(loss_fn)(st.master)
            st = opt.step(g, st)
            return st, lax.pmean(l, DP_AXIS)

        def final_fn(st):
            return fsdp.gather(st.master, meta)
    else:  # zero1
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            DistAdamState,
        )

        sspec = (pspecs,
                 DistAdamState(count=P(), master=shard, mu=shard, nu=shard))

        def init_fn(p):
            return p, opt.init(p)

        def body(st, x, y):
            p, ostate = st
            l, g = jax.value_and_grad(_loss)(p, x, y)
            p, ostate = opt.step(g, ostate, p)
            return (p, ostate), lax.pmean(l, DP_AXIS)

        def final_fn(st):
            return st[0]

    init = jax.jit(jax.shard_map(
        init_fn, mesh=mesh, in_specs=(pspecs,), out_specs=sspec,
        check_vma=False))
    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(sspec, P(DP_AXIS), P(DP_AXIS)),
        out_specs=(sspec, P()), check_vma=False))
    finalize = jax.jit(jax.shard_map(
        final_fn, mesh=mesh, in_specs=(sspec,), out_specs=pspecs,
        check_vma=False))

    state = init(params)
    if args.elastic:
        return _run_elastic(args, plan, mesh, params, opt, state, step,
                            finalize, x, y)
    mgr = CheckpointManager(args.checkpoint_dir) if args.checkpoint_dir \
        else None
    start = 0
    if mgr is not None and mgr.latest_valid() is not None:
        state, start = mgr.restore(target=state)
        print(f"=> auto-resumed at step {start}")
    loss = None
    for it in range(start, args.steps):
        state, loss = step(state, x, y)
        if mgr is not None and (it + 1) % args.save_freq == 0:
            mgr.save(state, it + 1)
    if mgr is not None:
        mgr.close()
    if loss is None:
        print(f"=> nothing to run: resumed at step {start} "
              f">= --steps {args.steps}")
    else:
        print(f"final loss {float(loss):.6f}")
    return finalize(state), None


def _run_elastic(args, plan, mesh, params, opt, state, step, finalize, x, y):
    """The --elastic loop: TrainSupervisor + an elastic checkpoint spec.
    Saves are topology-portable — a later run with a different dp degree
    resumes from the restart manifest via the reshard path (the manifest's
    ``legal_resume_dp`` names the degrees that divide cleanly)."""
    dp = mesh.shape[DP_AXIS]
    # per-leaf reshard specs mirroring the state structure: the optimizer
    # knows its shard arithmetic; the replicated params tree (zero1's
    # first element) never reshards
    espec = opt.elastic_spec(params, dp)
    if plan.data != "fsdp":
        espec = (jax.tree.map(lambda _: replicated_spec(), params), espec)
    mgr = plan.checkpoint_manager(args.checkpoint_dir, allow_reshard=True)
    last = {}

    def step_fn(st, it):
        st, last["loss"] = step(st, x, y)
        return st

    sup = TrainSupervisor(step_fn, mgr, elastic=espec, dp_degree=dp,
                          save_freq=args.save_freq,
                          preemption=PreemptionHandler())
    start = 0
    info = TrainSupervisor.read_restart(args.checkpoint_dir)
    if info is not None or mgr.latest_valid() is not None:
        state, start = sup.resume(state)
        prev_dp = info.get("dp_degree") if info else dp
        print(f"=> elastic resume at step {start} "
              f"(checkpoint dp={prev_dp}, live dp={dp})")
    state, nxt = sup.run(state, start, max(0, args.steps - start))
    mgr.close()
    if sup.exited == "preempted":
        print(f"=> preempted: saved at step {nxt}, restart manifest "
              "written — rerun (any legal dp) to continue")
        return None, None
    if "loss" in last:
        print(f"final loss {float(last['loss']):.6f}")
    return finalize(state), None


def main(argv=None):
    args = parse_args(argv)
    plan = ParallelismPlan.preset(args.plan)
    if args.elastic and (plan.data == "ddp" or not args.checkpoint_dir):
        raise SystemExit("--elastic needs --checkpoint-dir and a sharded "
                         "plan (zero1/fsdp/fsdp+tp)")
    print(plan.describe())

    # TPU matmuls default to bf16 accumulation; this toy regression needs f32
    jax.config.update("jax_default_matmul_precision", "highest")
    mesh = plan.mesh()
    params = {"w": jnp.zeros((8,)), "b": jnp.zeros(())}
    x, y, true_w = _data()
    print("  modeled hbm_params_bytes:",
          {k: int(v) for k, v in plan.hbm_params_bytes(
              params, world=mesh.shape[DP_AXIS]).items()})

    if plan.data == "ddp":
        params, metrics = _train_ddp(args, plan, mesh, params, x, y)
        if metrics is not None:
            stats = metrics.as_dict()
            print(f"(anomalies={stats['anomalies_total']:.0f} "
                  f"skips={stats['guard_skips_total']:.0f})")
    else:
        params, _ = _train_sharded(args, plan, mesh, params, x, y)

    if params is None:
        return  # preempted: state saved for --resume, nothing to validate

    err = float(jnp.abs(params["w"] - true_w).max())
    print(f"w error after {args.steps} steps: {err:.4f}")
    assert err < 0.05


if __name__ == "__main__":
    main()

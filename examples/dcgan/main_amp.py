"""DCGAN with amp mixed precision — two models, three losses.

Reference: ``examples/dcgan/main_amp.py`` — the amp multi-model/multi-loss
exercise: netD trained on errD_real + errD_fake, netG on errG, each loss
with its own scaler (``loss_id`` 0-2, main_amp.py:214-253).

TPU version: same structure with three independent LossScaler states,
synthetic data. Run: ``python examples/dcgan/main_amp.py --iters 10``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import argparse
import time

import jax
import jax.numpy as jnp
import optax

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.models import Discriminator, Generator


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--opt-level", default="O1", choices=["O0", "O1", "O2"])
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def train(args):
    half = args.opt_level != "O0"
    dtype = jnp.bfloat16 if half else jnp.float32
    netG = Generator(isize=args.image_size, nz=args.nz, dtype=dtype)
    netD = Discriminator(isize=args.image_size, dtype=dtype)

    rng = jax.random.PRNGKey(args.seed)
    z0 = jnp.zeros((2, 1, 1, args.nz), dtype)
    x0 = jnp.zeros((2, args.image_size, args.image_size, 3), dtype)
    gv = netG.init(rng, z0)
    dv = netD.init(jax.random.fold_in(rng, 1), x0)

    optG = optax.adam(args.lr, b1=args.beta1)
    optD = optax.adam(args.lr, b1=args.beta1)
    sG = optG.init(gv["params"])
    sD = optD.init(dv["params"])
    # one scaler per loss (ref loss_id 0,1,2 + num_losses=3)
    scalers = [LossScaler("dynamic") for _ in range(3)]
    sc_states = [s.init_state() for s in scalers]

    def bce(logits, target):
        return jnp.mean(optax.sigmoid_binary_cross_entropy(
            logits.astype(jnp.float32), target))

    @jax.jit
    def step_d(gv, dv, sD, sc0, sc1, real, z):
        fake, g_updates = netG.apply(gv, z, mutable=["batch_stats"])

        # Two losses, each scaled by its own scaler and unscaled by its own
        # scale before the fp32 sum — the ref's two backward() calls that
        # accumulate correctly-unscaled grads (main_amp.py loss_id 0/1).
        def loss_real(p):
            dvars = {"params": p, "batch_stats": dv["batch_stats"]}
            lr_, upd1 = netD.apply(dvars, real, mutable=["batch_stats"])
            errD_real = bce(lr_, jnp.ones(real.shape[0]))
            return (scalers[0].scale_loss(errD_real, sc0),
                    (errD_real, upd1["batch_stats"]))

        grads_r, (errD_real, bs1) = jax.grad(
            loss_real, has_aux=True)(dv["params"])
        g32r, found0 = scalers[0].unscale(grads_r, sc0)

        def loss_fake(p):
            lf_, upd2 = netD.apply(
                {"params": p, "batch_stats": bs1},
                jax.lax.stop_gradient(fake), mutable=["batch_stats"])
            errD_fake = bce(lf_, jnp.zeros(real.shape[0]))
            return (scalers[1].scale_loss(errD_fake, sc1),
                    (errD_fake, upd2["batch_stats"]))

        grads_f, (errD_fake, new_bs) = jax.grad(
            loss_fake, has_aux=True)(dv["params"])
        g32f, found1 = scalers[1].unscale(grads_f, sc1)

        g32 = jax.tree.map(jnp.add, g32r, g32f)
        new_sc0, skip0 = scalers[0].update_scale(sc0, found0)
        new_sc1, skip1 = scalers[1].update_scale(sc1, found1)
        skip = jnp.logical_or(skip0, skip1)
        updates, stepped_sD = optD.update(g32, sD, dv["params"])
        # overflow skip must cover the optimizer moments too, or inf/nan
        # grads poison Adam m/v for every later step
        new_sD = jax.tree.map(lambda new, old: jnp.where(skip, old, new),
                              stepped_sD, sD)
        new_p = jax.tree.map(
            lambda p, u: jnp.where(skip, p, p + u.astype(p.dtype)),
            dv["params"], updates)
        return ({"params": new_p, "batch_stats": new_bs}, new_sD, new_sc0,
                new_sc1, errD_real + errD_fake)

    @jax.jit
    def step_g(gv, dv, sG, sc2, z):
        def loss_fn(p):
            gvars = {"params": p, "batch_stats": gv["batch_stats"]}
            fake, upd = netG.apply(gvars, z, mutable=["batch_stats"])
            logits, _ = netD.apply(dv, fake, mutable=["batch_stats"])
            errG = bce(logits, jnp.ones(fake.shape[0]))
            return scalers[2].scale_loss(errG, sc2), (errG, upd["batch_stats"])

        grads, (errG, new_bs) = jax.grad(loss_fn, has_aux=True)(gv["params"])
        g32, found = scalers[2].unscale(grads, sc2)
        new_sc2, skip = scalers[2].update_scale(sc2, found)
        updates, stepped_sG = optG.update(g32, sG, gv["params"])
        new_sG = jax.tree.map(lambda new, old: jnp.where(skip, old, new),
                              stepped_sG, sG)
        new_p = jax.tree.map(
            lambda p, u: jnp.where(skip, p, p + u.astype(p.dtype)),
            gv["params"], updates)
        return ({"params": new_p, "batch_stats": new_bs}, new_sG, new_sc2,
                errG)

    data_rng = jax.random.PRNGKey(args.seed + 1)
    t0 = time.perf_counter()
    out = []
    for it in range(args.iters):
        k = jax.random.fold_in(data_rng, it)
        real = jax.random.uniform(
            k, (args.batch_size, args.image_size, args.image_size, 3),
            dtype, -1, 1)
        z = jax.random.normal(jax.random.fold_in(k, 1),
                              (args.batch_size, 1, 1, args.nz), dtype)
        dv, sD, sc_states[0], sc_states[1], errD = step_d(
            gv, dv, sD, sc_states[0], sc_states[1], real, z)
        gv, sG, sc_states[2], errG = step_g(gv, dv, sG, sc_states[2], z)
        out.append((float(errD), float(errG)))
        print(f"iter {it:3d}  errD {out[-1][0]:.4f}  errG {out[-1][1]:.4f}")
    print(f"{args.iters / (time.perf_counter() - t0):.2f} it/s")
    return out


if __name__ == "__main__":
    train(parse_args())

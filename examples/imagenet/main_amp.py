"""ImageNet-style ResNet trainer — amp + DDP + SyncBN on the TPU mesh.

Reference: ``examples/imagenet/main_amp.py`` (543 LoC) — torchvision ResNet
under ``amp.initialize(opt_level=...)`` + apex DDP (+ ``--sync_bn``),
printing per-iteration loss and img/s; the L1 suite runs it twice with
``--deterministic`` and requires bitwise-equal loss curves
(``tests/L1/common/compare.py``).

TPU version: same knobs, synthetic data by default (no ImageNet in the
image); the train loop is one jitted step over a dp mesh. Run:

    python examples/imagenet/main_amp.py --arch resnet18 --iters 20 \
        --opt-level O2 --sync_bn --deterministic
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time
from typing import Any, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.amp import frontend as amp
from apex_tpu.amp.autocast import autocast
from apex_tpu.models import ResNet18, ResNet50
from apex_tpu.models.resnet import make_norm
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import ParallelismPlan
from apex_tpu.parallel.mesh import DP_AXIS


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--plan", default="ddp",
                   choices=["ddp", "zero1", "fsdp", "fsdp+tp"],
                   help="ParallelismPlan preset. 'ddp' is the reference "
                        "recipe (SGD + amp, replicated params); 'zero1' / "
                        "'fsdp' switch to the sharded Adam optimizers "
                        "(DistributedFusedAdam / FSDPAdam — the sharded "
                        "families are Adam/LAMB) and run fp32 (O0). "
                        "'fsdp+tp' resolves the dp×tp mesh; the ResNet "
                        "defines no TP layers, so tp replicates compute")
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet18", "resnet50"])
    p.add_argument("-b", "--batch-size", type=int, default=64,
                   help="GLOBAL batch (split over dp)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--opt-level", default="O2",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--loss-scale", default=None,
                   help="'dynamic' or a float (default: policy preset)")
    p.add_argument("--keep-batchnorm-fp32", default=None)
    p.add_argument("--sync_bn", action="store_true",
                   help="cross-device SyncBatchNorm (ref --sync_bn)")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--resume", default="", metavar="PATH",
                   help="path to a checkpoint to resume from (the "
                        "reference's --resume: restores model, optimizer, "
                        "amp and batch-norm state plus the iteration); "
                        "'auto' discovers the latest VALID checkpoint in "
                        "--checkpoint-dir (torn/corrupt ones are skipped)")
    p.add_argument("--checkpoint-dir", default="",
                   help="save the full train state here (end of run, plus "
                        "every --save-freq iters) — atomic, manifested "
                        "resilience.CheckpointManager checkpoints")
    p.add_argument("--save-freq", type=int, default=0,
                   help="checkpoint every N iters (0 = only at the end)")
    p.add_argument("--keep-last-n", type=int, default=3,
                   help="checkpoint retention (plus every --keep-every-k)")
    p.add_argument("--keep-every-k", type=int, default=0)
    p.add_argument("--async-save", action="store_true",
                   help="serialize checkpoints off the critical path")
    p.add_argument("--preempt-save", action="store_true",
                   help="on SIGTERM: save a checkpoint at the agreed step "
                        "and exit cleanly (requires --checkpoint-dir)")
    p.add_argument("--elastic", action="store_true",
                   help="drive the sharded loop through TrainSupervisor "
                        "with an elastic checkpoint spec: a checkpoint "
                        "saved here restores at a DIFFERENT --plan dp "
                        "degree (restart manifest names the legal ones); "
                        "needs --checkpoint-dir and a zero1/fsdp plan")
    return p.parse_args(argv)


# jitted-step cache keyed by every config knob the traced program depends
# on: repeat runs of one config (the L1 determinism double-run, the
# O0-vs-O2 comparison, baseline regeneration) reuse the SAME jit object and
# pay zero recompiles. Initial state is rebuilt per call (deterministic
# from the seed), so cached-step runs return identical losses.
_STEP_CACHE = {}


def _step_key(args):
    return (args.plan, args.arch, args.batch_size, args.image_size,
            args.num_classes, args.lr, args.momentum, args.weight_decay,
            args.opt_level, args.loss_scale, args.keep_batchnorm_fp32,
            args.sync_bn)


def train(args) -> List[float]:
    """Run the loop; returns the per-iteration loss list (the L1 contract)."""
    plan = ParallelismPlan.preset(args.plan)
    print(plan.describe())
    mesh = plan.mesh()
    dp = mesh.shape[DP_AXIS]
    if args.batch_size % dp != 0:
        raise ValueError(f"batch {args.batch_size} % dp {dp} != 0")

    arch = {"resnet18": ResNet18, "resnet50": ResNet50}[args.arch]
    model = arch(num_classes=args.num_classes,
                 norm=make_norm(sync_bn=args.sync_bn))

    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.zeros((2, args.image_size, args.image_size, 3))
    variables = model.init(rng, sample, use_running_average=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    print("  modeled hbm_params_bytes:",
          {k: int(v)
           for k, v in plan.hbm_params_bytes(params, world=dp).items()})

    if plan.data != "ddp":
        if args.opt_level != "O0":
            raise SystemExit(
                f"--plan {args.plan} runs the sharded fp32 Adam loop; "
                "pass --opt-level O0 (amp×FSDP composition is a "
                "benchmarks/bench_fsdp.py + GPT story)")
        return _train_sharded(args, plan, mesh, model, params, batch_stats)

    overrides = {}
    if args.loss_scale is not None:
        overrides["loss_scale"] = (
            "dynamic" if args.loss_scale == "dynamic"
            else float(args.loss_scale))
    if args.keep_batchnorm_fp32 is not None:
        overrides["keep_batchnorm_fp32"] = (
            args.keep_batchnorm_fp32 in ("True", "true", True))
    amp_state, policy = amp.initialize(params, args.opt_level, **overrides)

    tx = FusedSGD(lr=args.lr, momentum=args.momentum,
                  weight_decay=args.weight_decay)
    opt_state = tx.init(amp_state.master_params)
    ddp = plan.ddp()

    cached = _STEP_CACHE.get(_step_key(args))
    if cached is not None:
        return _run_loop(args, cached, amp_state, opt_state, batch_stats)

    # O1: per-op autocast transform around the model apply — whitelisted ops
    # (convs/matmuls) run in the compute dtype, reductions in fp32 (the ref's
    # monkey-patch casting; without this wrap O1 would train identically to
    # O0, params and inputs both being fp32)
    def apply_model(variables, images):
        return model.apply(variables, images, use_running_average=False,
                           mutable=["batch_stats"])

    if policy.compute_dtype is not None:
        apply_model = autocast(apply_model, policy.compute_dtype)

    def body(amp_state, opt_state, batch_stats, images, labels):
        def loss_fn(masters):
            model_p = ddp.replicate(amp.cast_params(
                masters, policy, amp_state.is_norm_param))
            logits, upd = apply_model(
                {"params": model_p, "batch_stats": batch_stats},
                amp.cast_inputs(images, policy))
            onehot = jax.nn.one_hot(labels, args.num_classes)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot, -1))
            return amp.scale_loss(loss, amp_state), (loss, upd["batch_stats"])

        grads, (loss, new_bs) = jax.grad(loss_fn, has_aux=True)(
            amp_state.master_params)
        grads = ddp.average_gradients(grads)
        new_amp, new_opt, _ = amp.apply_grads_with_optimizer(
            amp_state, grads, tx, opt_state)
        # Without --sync_bn each dp shard sees different batch stats (the
        # reference keeps per-rank stats and checkpoints rank 0's); here the
        # single program keeps their mean — a strictly better estimate.
        def pmean(s):
            if DP_AXIS not in jax.typeof(s).vma:
                s = jax.lax.pcast(s, DP_AXIS, to="varying")
            return jax.lax.pmean(s, DP_AXIS)

        new_bs = jax.tree_util.tree_map(pmean, new_bs)
        loss = pmean(loss)
        return new_amp, new_opt, new_bs, loss

    replicated = jax.tree_util.tree_map(lambda _: P(), amp_state)
    step = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(replicated,
                  jax.tree_util.tree_map(lambda _: P(), opt_state),
                  jax.tree_util.tree_map(lambda _: P(), batch_stats),
                  P(DP_AXIS), P(DP_AXIS)),
        out_specs=(replicated,
                   jax.tree_util.tree_map(lambda _: P(), opt_state),
                   jax.tree_util.tree_map(lambda _: P(), batch_stats),
                   P()),
    ))

    _STEP_CACHE[_step_key(args)] = step
    return _run_loop(args, step, amp_state, opt_state, batch_stats)


def _train_sharded(args, plan, mesh, model, params, batch_stats
                   ) -> List[float]:
    """zero1 / fsdp: the plan-built sharded-Adam loop (fp32). Replaces the
    old hand-threaded optimizer wiring with ``plan.build_optimizer``; the
    batch stats stay replicated and dp-meaned exactly like the ddp path."""
    from jax.sharding import PartitionSpec as P

    opt = plan.build_optimizer(lr=args.lr, weight_decay=args.weight_decay)
    pspecs = jax.tree_util.tree_map(lambda _: P(), params)
    bspecs = jax.tree_util.tree_map(lambda _: P(), batch_stats)
    shard = jax.tree_util.tree_map(lambda _: P(DP_AXIS), params)

    def loss_fn(model_p, bs, images, labels):
        logits, upd = model.apply(
            {"params": model_p, "batch_stats": bs}, images,
            use_running_average=False, mutable=["batch_stats"])
        onehot = jax.nn.one_hot(labels, args.num_classes)
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot, -1))
        return loss, upd["batch_stats"]

    def pmean(s):
        if hasattr(jax, "typeof") and DP_AXIS not in jax.typeof(s).vma:
            s = jax.lax.pcast(s, DP_AXIS, to="varying")
        return jax.lax.pmean(s, DP_AXIS)

    if plan.data == "fsdp":
        from apex_tpu.fsdp import FSDPAdamState

        fsdp = plan.fsdp()
        meta = fsdp.meta(params)
        sspec = (FSDPAdamState(count=P(), master=shard, mu=shard, nu=shard),
                 bspecs)

        def init_fn(p, bs):
            return opt.init(p), bs

        def body(st, images, labels):
            ostate, bs = st

            def wrapped(master):
                return loss_fn(fsdp.gather(master, meta), bs, images,
                               labels)

            (loss, new_bs), g = jax.value_and_grad(
                wrapped, has_aux=True)(ostate.master)
            ostate = opt.step(g, ostate)
            new_bs = jax.tree_util.tree_map(pmean, new_bs)
            return (ostate, new_bs), pmean(loss)
    else:  # zero1
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            DistAdamState,
        )

        sspec = (pspecs,
                 DistAdamState(count=P(), master=shard, mu=shard, nu=shard),
                 bspecs)

        def init_fn(p, bs):
            return p, opt.init(p), bs

        def body(st, images, labels):
            p, ostate, bs = st
            (loss, new_bs), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p, bs, images, labels)
            p, ostate = opt.step(g, ostate, p)
            new_bs = jax.tree_util.tree_map(pmean, new_bs)
            return (p, ostate, new_bs), pmean(loss)

    init = jax.jit(jax.shard_map(
        init_fn, mesh=mesh, in_specs=(pspecs, bspecs), out_specs=sspec,
        check_vma=False))
    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(sspec, P(DP_AXIS), P(DP_AXIS)),
        out_specs=(sspec, P()), check_vma=False))
    state = init(params, batch_stats)

    if args.elastic:
        return _run_elastic_sharded(args, plan, mesh, opt, params, state,
                                    step)

    mgr = _make_manager(args) if args.checkpoint_dir else None
    state, start_it = _resolve_resume(args, mgr, state)

    losses = []
    data_rng = jax.random.PRNGKey(args.seed + 1)
    t0 = time.perf_counter()
    for it in range(start_it, args.iters):
        k = jax.random.fold_in(data_rng, it)
        images = jax.random.normal(
            k, (args.batch_size, args.image_size, args.image_size, 3))
        labels = jax.random.randint(
            jax.random.fold_in(k, 1), (args.batch_size,), 0,
            args.num_classes)
        state, loss = step(state, images, labels)
        losses.append(float(loss))
        if it % args.print_freq == 0 or it == args.iters - 1:
            dt = time.perf_counter() - t0
            ips = args.batch_size * (it - start_it + 1) / dt
            print(f"iter {it:4d}  loss {losses[-1]:.6f}  {ips:,.1f} img/s")
        if mgr is not None and (
                it == args.iters - 1
                or (args.save_freq and (it + 1) % args.save_freq == 0)):
            p = mgr.save(state, it + 1)
            print(f"=> saved checkpoint '{p}' (iter {it + 1})")
    if mgr is not None:
        mgr.close()
    return losses


def _run_elastic_sharded(args, plan, mesh, opt, params, state,
                         step) -> List[float]:
    """The --elastic sharded loop: TrainSupervisor drives the step with an
    elastic spec stamped into every checkpoint, so a preempted/killed run
    relaunched on a different slice (different dp degree, new --plan mesh)
    resumes through the reshard path — the restart manifest's
    ``legal_resume_dp`` names the degrees the shard arithmetic divides."""
    from apex_tpu.parallel.mesh import DP_AXIS
    from apex_tpu.resilience import (
        PreemptionHandler,
        TrainSupervisor,
        replicated_spec,
    )

    dp = mesh.shape[DP_AXIS]
    ospec = opt.elastic_spec(params, dp)
    repl = lambda tree: jax.tree_util.tree_map(  # noqa: E731
        lambda _: replicated_spec(), tree)
    # mirror the state tuples _train_sharded builds: batch stats (and
    # zero1's replicated param copy) never reshard
    if plan.data == "fsdp":
        espec = (ospec, repl(state[1]))
    else:
        espec = (repl(params), ospec, repl(state[2]))
    mgr = plan.checkpoint_manager(
        args.checkpoint_dir, allow_reshard=True,
        keep_last_n=args.keep_last_n, keep_every_k=args.keep_every_k,
        async_save=args.async_save)

    losses: List[float] = []
    data_rng = jax.random.PRNGKey(args.seed + 1)

    def step_fn(st, it):
        k = jax.random.fold_in(data_rng, it)
        images = jax.random.normal(
            k, (args.batch_size, args.image_size, args.image_size, 3))
        labels = jax.random.randint(
            jax.random.fold_in(k, 1), (args.batch_size,), 0,
            args.num_classes)
        st, loss = step(st, images, labels)
        losses.append(float(loss))
        if it % args.print_freq == 0 or it == args.iters - 1:
            print(f"iter {it:4d}  loss {losses[-1]:.6f}")
        return st

    sup = TrainSupervisor(
        step_fn, mgr, elastic=espec, dp_degree=dp,
        save_freq=args.save_freq or args.iters,
        preemption=PreemptionHandler() if args.preempt_save else None)
    start_it = 0
    info = TrainSupervisor.read_restart(args.checkpoint_dir)
    if info is not None or mgr.latest_valid() is not None:
        state, start_it = sup.resume(state)
        prev_dp = info.get("dp_degree") if info else dp
        print(f"=> elastic resume at iter {start_it} "
              f"(checkpoint dp={prev_dp}, live dp={dp})")
        if start_it >= args.iters:
            raise SystemExit(
                f"checkpoint is already at iter {start_it} >= --iters "
                f"{args.iters}; nothing to resume (raise --iters)")
    state, nxt = sup.run(state, start_it, args.iters - start_it)
    if sup.exited != "killed":
        mgr.save(state, nxt, elastic=espec)
    mgr.close()
    if sup.exited == "preempted":
        print(f"=> preempted at iter {nxt}; restart manifest written")
    return losses


def _make_manager(args):
    from apex_tpu.resilience import CheckpointManager

    return CheckpointManager(
        args.checkpoint_dir, keep_last_n=args.keep_last_n,
        keep_every_k=args.keep_every_k, async_save=args.async_save)


def _resolve_resume(args, mgr, state):
    """The resume contract shared by the ddp and sharded loops: restore
    the train state and continue at the saved iteration. The manager
    re-hangs the flat leaves on the LIVE treedef after verifying the
    manifest fingerprint + per-leaf checksums — a torn or revision-skewed
    checkpoint is refused, not mis-bound. ``--resume auto`` is a standing
    relaunch flag: no checkpoint yet (first launch, or all torn) means
    start fresh, not die."""
    from apex_tpu.resilience import CheckpointError

    start_it = 0
    if not args.resume:
        return state, start_it
    restore_mgr = mgr or _make_manager(args)
    if args.resume == "auto":
        if not args.checkpoint_dir:
            raise SystemExit("--resume auto needs --checkpoint-dir")
        path = restore_mgr.latest_valid()
    else:
        path = args.resume
    if path is None:
        print(f"=> no valid checkpoint in '{args.checkpoint_dir}' yet; "
              "starting fresh")
        return state, start_it
    try:
        state, start_it = restore_mgr.restore(target=state, path=path)
    except CheckpointError as e:
        raise SystemExit(f"=> {e}")
    print(f"=> loaded checkpoint '{path}' (resuming at iter {start_it})")
    if start_it >= args.iters:
        raise SystemExit(
            f"checkpoint is already at iter {start_it} >= --iters "
            f"{args.iters}; nothing to resume (raise --iters)")
    return state, start_it


def _run_loop(args, step, amp_state, opt_state, batch_stats) -> List[float]:
    from apex_tpu.resilience import PreemptionHandler

    state = (amp_state, opt_state, batch_stats)
    mgr = _make_manager(args) if args.checkpoint_dir else None
    state, start_it = _resolve_resume(args, mgr, state)
    amp_state, opt_state, batch_stats = state

    pre = None
    if args.preempt_save:
        if mgr is None:
            raise SystemExit("--preempt-save needs --checkpoint-dir")
        pre = PreemptionHandler()

    def save(state, it):
        p = mgr.save(state, it)
        print(f"=> saved checkpoint '{p}' (iter {it})")

    losses = []
    data_rng = jax.random.PRNGKey(args.seed + 1)
    t0 = time.perf_counter()
    for it in range(start_it, args.iters):
        k = jax.random.fold_in(data_rng, it)
        images = jax.random.normal(
            k, (args.batch_size, args.image_size, args.image_size, 3))
        labels = jax.random.randint(
            jax.random.fold_in(k, 1), (args.batch_size,), 0,
            args.num_classes)
        amp_state, opt_state, batch_stats, loss = step(
            amp_state, opt_state, batch_stats, images, labels)
        losses.append(float(loss))
        if it % args.print_freq == 0 or it == args.iters - 1:
            dt = time.perf_counter() - t0
            ips = args.batch_size * (it - start_it + 1) / dt
            print(f"iter {it:4d}  loss {losses[-1]:.6f}  {ips:,.1f} img/s")
        if pre is not None:
            save_at = pre.sync_save_step(it)
            if save_at is not None:
                # preemption: all processes agreed on this step — save
                # synchronously inside the grace window and stop
                p = mgr.save((amp_state, opt_state, batch_stats),
                             save_at + 1, block=True)
                print(f"=> saved checkpoint '{p}' (iter {save_at + 1})")
                mgr.close()
                print(f"=> preempted at iter {save_at}; exiting after save")
                return losses
        if mgr is not None and (
                it == args.iters - 1
                or (args.save_freq and (it + 1) % args.save_freq == 0)):
            save((amp_state, opt_state, batch_stats), it + 1)
    if mgr is not None:
        mgr.close()  # drain async saves before the process exits
    return losses


def main(argv=None):
    args = parse_args(argv)
    losses = train(args)
    print(f"final loss: {losses[-1]:.6f}")
    return losses


if __name__ == "__main__":
    main()

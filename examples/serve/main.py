"""Continuous-batching GPT serving demo (apex_tpu.serve).

The serving counterpart of ``examples/simple/distributed`` — a complete
engine loop on one chip (or the CPU sim):

    python examples/serve/main.py                    # random 8M-class GPT
    python examples/serve/main.py --ckpt ckpts/      # serve a training
                                                     # job's latest VALID
                                                     # checkpoint
    python examples/serve/main.py --kv-quant int8 --temperature 0.8

Writes per-step engine telemetry (tokens/s, TTFT, slot occupancy, KV
bytes) AND per-request lifecycle events to ``--metrics`` as JSONL (the
monitor sink convention; ``python -m apex_tpu.monitor.view`` summarizes
it), optionally a Chrome trace to ``--trace`` (open in Perfetto: one
track per slot, one per request), and prints the per-request token
streams plus the goodput-under-SLO report when budgets are given
(``--ttft-budget`` / ``--tpot-budget`` ms). With ``--ckpt`` the
parameters load through ``resilience.CheckpointManager.latest_valid()``
— torn or corrupt saves are skipped, a checkpoint from a different model
revision is refused.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.monitor import (
    EventLog,
    JsonlSink,
    SloSpec,
    read_jsonl,
    write_chrome_trace,
)
from apex_tpu.serve import (
    InferenceEngine,
    Request,
    SamplingConfig,
    ServeConfig,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt", default=None,
                    help="checkpoint dir (resilience.CheckpointManager); "
                         "default: random init")
    ap.add_argument("--num-slots", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--kv-quant", default="none", choices=["none", "int8"])
    ap.add_argument("--prefill-chunk", type=int, default=32,
                    help="tokens per chunked-prefill step")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable content-addressed KV block reuse")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative draft length (0 = off; n-gram "
                         "prompt-lookup drafter)")
    ap.add_argument("--megakernel", default="auto",
                    choices=["auto", "on", "off"],
                    help="fused per-layer decode block (auto = only on "
                         "compiled TPU backends)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy")
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=1.0)
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--num-requests", type=int, default=8)
    ap.add_argument("--metrics", default="serve_metrics.jsonl")
    ap.add_argument("--trace", default=None,
                    help="also write a Chrome trace (Perfetto) here")
    ap.add_argument("--ttft-budget", type=float, default=None,
                    help="TTFT SLO budget in ms (enables goodput report)")
    ap.add_argument("--tpot-budget", type=float, default=None,
                    help="per-output-token SLO budget in ms")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--max-seq", type=int, default=256)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    cfg = GPTConfig(
        vocab_size=args.vocab, max_seq=args.max_seq, hidden=args.hidden,
        num_layers=args.layers, num_heads=args.heads,
        dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32)
    scfg = ServeConfig(
        num_slots=args.num_slots, block_size=args.block_size,
        kv_quant=args.kv_quant, prefill_chunk=args.prefill_chunk,
        prefix_cache=not args.no_prefix_cache, spec_k=args.spec_k,
        megakernel=args.megakernel,
        sampling=SamplingConfig(temperature=args.temperature,
                                top_k=args.top_k, top_p=args.top_p))
    template = init_gpt_params(jax.random.PRNGKey(0), cfg)
    slo = (SloSpec(ttft_ms=args.ttft_budget, tpot_ms=args.tpot_budget)
           if (args.ttft_budget or args.tpot_budget) else None)
    with JsonlSink(args.metrics, buffer_steps=8) as sink:
        events = EventLog(sink=sink)
        kw = dict(sink=sink, events=events, slo=slo)
        if args.ckpt:
            engine = InferenceEngine.from_checkpoint(
                args.ckpt, template, cfg, scfg, **kw)
            print(f"serving checkpoint step {engine.checkpoint_step} "
                  f"from {args.ckpt}")
        else:
            engine = InferenceEngine(template, cfg, scfg, **kw)
            print("serving random-init weights (pass --ckpt for a real "
                  "model)")
        rng = np.random.default_rng(0)
        requests = [
            Request(f"req{i}",
                    rng.integers(0, args.vocab,
                                 size=int(rng.integers(4, 48))).tolist(),
                    max_new_tokens=args.max_new_tokens)
            for i in range(args.num_requests)
        ]
        streams = engine.run(requests)
        for uid in sorted(streams):
            print(f"{uid}: tokens={streams[uid]}")
        stats = engine.stats()
        print(f"throughput: {engine.throughput():.1f} tokens/s | "
              f"ttft p50/p99: {stats['ttft_ms_p50']:.1f}/"
              f"{stats['ttft_ms_p99']:.1f} ms | "
              f"kv budget: {engine.kv_budget_bytes() / 1e6:.1f} MB | "
              f"compilations: {engine.compile_counts()} "
              f"(prefill chunk: {args.prefill_chunk}, megakernel: "
              f"{'on' if engine.megakernel_enabled else 'off'})")
        pc = stats["prefix_cache"]
        if pc["blocks_needed"]:
            print(f"prefix cache: {pc['blocks_hit']}/"
                  f"{pc['blocks_needed']} blocks reused "
                  f"(hit rate {pc['hit_rate']}), "
                  f"{pc['tokens_saved']} prefill tokens saved")
        sp = stats["speculative"]
        if sp["proposed"]:
            print(f"speculative: {sp['accepted']}/{sp['proposed']} drafts "
                  f"accepted (rate {sp['acceptance_rate']}) over "
                  f"{sp['verify_steps']} verify steps")
        if slo is not None:
            rep = stats["slo_report"]
            print(f"SLO {slo.to_dict()}: good {rep['good']}/"
                  f"{rep['completed']} goodput {rep['goodput_rps']} req/s "
                  f"violations {rep['violations']}")
    if args.trace:
        write_chrome_trace(args.trace, read_jsonl(args.metrics))
        print(f"chrome trace -> {args.trace} (open in Perfetto)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Disaggregated prefill/decode serving demo (apex_tpu.serve.cluster).

The multi-host counterpart of ``examples/serve/main.py`` — an SLO-aware
router in front of separate prefill and decode hosts (simulated
in-process on one chip/CPU; the same objects take a real ICI transport):

    python examples/serve/cluster_main.py                  # 1+1 hosts
    python examples/serve/cluster_main.py --prefill-hosts 2 \\
        --decode-hosts 2 --wire-mode int8                  # 4 hosts,
                                                           # quantized wire
    python examples/serve/cluster_main.py --ttft-budget 50 # force sheds

Prints per-request token streams (or their ``shed`` terminal state), the
router's per-tenant admission/shed accounting, transfer wire bytes
(measured == modeled), and the goodput-under-SLO report. ``--trace``
writes a Chrome trace where each request visibly hops hosts:
``queued → prefill → transfer → decode`` spans per request
(open in Perfetto).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.monitor import (
    EventLog,
    JsonlSink,
    SloSpec,
    read_jsonl,
    write_chrome_trace,
)
from apex_tpu.serve import (
    ClusterConfig,
    Request,
    RouterConfig,
    SamplingConfig,
    ServeCluster,
    ServeConfig,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--prefill-hosts", type=int, default=1)
    ap.add_argument("--decode-hosts", type=int, default=1)
    ap.add_argument("--num-slots", type=int, default=4,
                    help="decode slots per decode host")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--kv-quant", default="none", choices=["none", "int8"])
    ap.add_argument("--wire-mode", default="raw", choices=["raw", "int8"],
                    help="KV-block transfer codec (int8: ~3.6x fewer "
                         "wire bytes on a float pool)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--spec-k", type=int, default=0)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--num-requests", type=int, default=8)
    ap.add_argument("--ttft-budget", type=float, default=5000.0)
    ap.add_argument("--tpot-budget", type=float, default=500.0)
    ap.add_argument("--link-fixed-ms", type=float, default=0.0)
    ap.add_argument("--link-gib-per-s", type=float, default=0.0)
    ap.add_argument("--metrics", default="serve_cluster_metrics.jsonl")
    ap.add_argument("--trace", default=None,
                    help="write a Chrome trace here (open in Perfetto; "
                         "one track per host — requests visibly hop)")
    ap.add_argument("--flight-dir", default=None,
                    help="arm the flight-recorder dump dir (the black "
                         "box `python -m apex_tpu.monitor.postmortem` "
                         "reads); rings dump on exit too")
    ap.add_argument("--expose", action="store_true",
                    help="print one worker's Prometheus text exposition "
                         "at the end (the external-scraper surface)")
    ap.add_argument("--seed", type=int, default=0)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    on_tpu = jax.default_backend() == "tpu"
    cfg = GPTConfig(vocab_size=512, max_seq=256, hidden=128, num_layers=2,
                    num_heads=8,
                    dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)

    sink = JsonlSink(args.metrics, buffer_steps=64)
    events = EventLog(sink=sink)
    slo = SloSpec(ttft_ms=args.ttft_budget, tpot_ms=args.tpot_budget)
    ccfg = ClusterConfig(
        n_prefill=args.prefill_hosts, n_decode=args.decode_hosts,
        serve=ServeConfig(
            num_slots=args.num_slots, block_size=args.block_size,
            kv_quant=args.kv_quant, prefill_chunk=args.prefill_chunk,
            spec_k=args.spec_k, prefix_cache=False,
            sampling=SamplingConfig(temperature=args.temperature)),
        router=RouterConfig(slo=slo,
                            tenant_weights={"free": 1.0, "paid": 3.0}),
        wire_mode=args.wire_mode,
        link_fixed_ms=args.link_fixed_ms,
        link_gib_per_s=args.link_gib_per_s,
        flight_dir=args.flight_dir)
    cluster = ServeCluster(params, cfg, ccfg, events=events)

    rng = np.random.default_rng(args.seed)
    requests = []
    for i in range(args.num_requests):
        plen = int(rng.integers(4, 48))
        requests.append(Request(
            f"req{i:03d}",
            rng.integers(0, cfg.vocab_size, size=plen).tolist(),
            max_new_tokens=args.max_new_tokens,
            tenant="paid" if i % 2 else "free"))
    streams = cluster.run(requests, max_steps=100_000)

    for r in requests:
        if r.uid in cluster.shed:
            d = cluster.shed[r.uid]
            print(f"{r.uid} [{r.tenant}] SHED ({d.reason}, predicted "
                  f"ttft {d.predicted_ttft_ms} ms vs budget "
                  f"{d.budget_ms} ms)")
        else:
            toks = streams.get(r.uid, [])
            print(f"{r.uid} [{r.tenant}] {len(toks)} tokens: "
                  f"{toks[:12]}{'...' if len(toks) > 12 else ''}")

    stats = cluster.stats()
    print(f"\nhosts: {stats['hosts']['prefill']} prefill + "
          f"{stats['hosts']['decode']} decode "
          f"(wire {ccfg.wire_mode}, kv {args.kv_quant})")
    r = stats["router"]
    print(f"router: {r['admitted']}/{r['submitted']} admitted, "
          f"{r['shed']} shed (rate {r['shed_rate']}), per tenant "
          f"{r['tenants']}")
    t = stats["transfer"]
    print(f"transfer: {t['transfers']} handoffs, "
          f"{t['wire_bytes_total']} wire bytes "
          f"({t['bytes_per_transfer']} per handoff), "
          f"p50 {stats.get('transfer_ms_p50')} ms")
    if "slo_report" in stats:
        s = stats["slo_report"]
        print(f"goodput: {s['goodput_rps']} req/s good "
              f"({s['good_fraction']} of {s['completed']}), "
              f"violations {s['violations']}")
    for dim in ("ttft_ms", "tpot_ms", "e2e_ms"):
        if f"{dim}_p50" in stats:
            print(f"  {dim}: p50 {stats[f'{dim}_p50']} "
                  f"p99 {stats[f'{dim}_p99']}")

    fleet = stats.get("fleet", {})
    print(f"fleet: {fleet.get('scrapes_total')} scrapes "
          f"(coverage {fleet.get('scrape_coverage')}, "
          f"p50 {fleet.get('scrape_ms_p50')} ms), "
          f"{fleet.get('alerts', {}).get('alerts_fired_total')} alerts, "
          f"{fleet.get('traces_minted')} traces")
    if args.flight_dir:
        paths = cluster.dump_flight(reason="shutdown")
        print(f"flight dumps -> {len(paths)} files in {args.flight_dir} "
              f"(read: python -m apex_tpu.monitor.postmortem "
              f"{args.flight_dir})")
    if args.expose:
        from apex_tpu.monitor import MetricsRegistry

        reg = MetricsRegistry()
        w = cluster.decode_workers[0]
        w.engine.collect_registry(reg, worker=w.name)
        print("\n# Prometheus exposition (decode0):")
        print(reg.expose_text())
    sink.close()
    if args.trace:
        write_chrome_trace(args.trace, read_jsonl(args.metrics))
        print(f"chrome trace -> {args.trace}")
    print(f"metrics -> {args.metrics}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""apex_tpu.serve.sharded — one ParallelismPlan from training to
pod-scale inference.

Gates, per residency strategy (``tp`` / ``pp`` / ``fsdp``):

* **stream parity** — plan-sharded decode/verify/chunked-prefill token
  streams equal the single-chip oracle's, greedy AND sampled, int8/int4
  quantized KV included. ``pp``/``fsdp`` are bitwise claims (stage
  splits reorder no op; uncompressed gather is slice-concat identity);
  ``tp`` logits differ by psum ring association only and the STREAMS
  still match exactly on these workloads;
* **compile-count gate** — the plan engines keep the plain engine's
  warmup contract (one compile per cold program) and run steady-state
  workloads under ``recompile_guard(budget=0)``;
* **overlap proof** — the TP q_len>1 programs' row exits are proven
  overlapped from their compiled HLO (``overlap_assertion``,
  hidden_fraction >= 0.5) while q_len=1 decode stays monolithic (zero
  collective-permutes — the PR-5 pin);
* **plan validation** (stock-safe) — ``serve_overrides()`` refuses
  optimizer-coupled knobs with the arithmetic, ``serve_strategy()``
  refuses composed sharding, ``describe()`` tells the serve story, and
  ``fsdp.accounting.hbm_serve_bytes`` prices each strategy under a chip
  budget.

All mesh rows run under the 0.4.37 shard_map shim (``sharded.shard_map``
dispatches graft ``jax.shard_map`` / stock ``jax.experimental``) on the
conftest's 8 virtual devices — the same validation idiom as the PR-9/12
mesh suites.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.analyze import recompile_guard
from apex_tpu.analyze.collectives import overlap_assertion
from apex_tpu.comm import CompressionConfig
from apex_tpu.fsdp.accounting import hbm_serve_bytes, param_gather_wire_bytes
from apex_tpu.fsdp.core import LeafMeta
from apex_tpu.parallel import ParallelismPlan
from apex_tpu.serve import (
    InferenceEngine,
    PPStagedEngine,
    Request,
    SamplingConfig,
    ServeConfig,
    build_engine,
)
from apex_tpu.serve.sharded import plan_world, program_hlo
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

MESH_OK = jax.device_count() >= 8
mesh_only = pytest.mark.skipif(
    not MESH_OK,
    reason="plan-sharded engines need >= 8 devices (conftest forces 8 "
           "virtual CPU devices; the shard_map shim covers stock 0.4.37)")

CFG = GPTConfig(vocab_size=64, max_seq=64, hidden=32, num_layers=4,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)

PLANS = {
    "tp": ParallelismPlan(tp=4, overlap_comm=True),
    "pp": ParallelismPlan(pp=2),
    "fsdp": ParallelismPlan("fsdp", dp=8),
}
SAMPLED = SamplingConfig(temperature=0.8, top_k=16)


def _reqs():
    return [Request("a", [1, 2, 3, 4, 5], max_new_tokens=6),
            Request("b", [7, 8, 9], max_new_tokens=4),
            Request("c", list(range(10, 22)), max_new_tokens=5),
            Request("d", [5, 4, 3], max_new_tokens=5)]


def _scfg(plan=None, **kw):
    return ServeConfig(num_slots=4, block_size=8, prefill_chunk=8,
                       plan=plan, **kw)


_ORACLE = {}


def _oracle(**kw):
    """Single-chip reference stream, cached per engine shape."""
    key = tuple(sorted(kw.items()))
    if key not in _ORACLE:
        _ORACLE[key] = InferenceEngine(PARAMS, CFG, _scfg(**kw)).run(_reqs())
    return _ORACLE[key]


# ---------------------------------------------------------------------------
# stream parity: sharded streams vs the single-chip oracle


@mesh_only
@pytest.mark.parametrize("sampling", ["greedy", "sampled"])
@pytest.mark.parametrize("strategy", sorted(PLANS))
def test_stream_parity(strategy, sampling):
    """Decode + chunked-prefill streams match the oracle exactly —
    bitwise claims for pp/fsdp, ring-reordered logits for tp (streams
    still equal; both greedy and same-key sampled draws)."""
    kw = {} if sampling == "greedy" else {"sampling": SAMPLED}
    eng = build_engine(PARAMS, CFG, _scfg(plan=PLANS[strategy], **kw))
    assert eng.run(_reqs()) == _oracle(**kw)
    assert eng.stats()["plan"] == strategy


@mesh_only
@pytest.mark.parametrize("kv_quant", ["int8", "int4"])
@pytest.mark.parametrize("strategy", sorted(PLANS))
def test_stream_parity_quantized_kv(strategy, kv_quant):
    """The quantized pools shard like the fp pools (heads at dim 1 on
    every leaf, scales included) — codec streams match the same-codec
    oracle."""
    eng = build_engine(PARAMS, CFG,
                       _scfg(plan=PLANS[strategy], kv_quant=kv_quant))
    assert eng.run(_reqs()) == _oracle(kv_quant=kv_quant)


@mesh_only
@pytest.mark.parametrize("strategy", sorted(PLANS))
def test_verify_stream_parity(strategy):
    """Speculative q_len=k+1 verify runs sharded too: spec_k=3 streams
    match the spec_k=3 oracle (which itself matches plain greedy — the
    spec contract)."""
    eng = build_engine(PARAMS, CFG, _scfg(plan=PLANS[strategy], spec_k=3))
    assert eng.run(_reqs()) == _oracle(spec_k=3)
    assert _oracle(spec_k=3) == _oracle()


# ---------------------------------------------------------------------------
# compile-count gate (the tightened PR-5 contract, now per strategy)


@mesh_only
@pytest.mark.parametrize("strategy", sorted(PLANS))
def test_compile_count_gate(strategy):
    """Warmup contract: one compile per cold program (the PP stage jits
    serve prefill/decode/verify shapes from ONE callable, so their
    budget is the shape count); steady state: a second workload
    compiles NOTHING."""
    eng = build_engine(PARAMS, CFG, _scfg(plan=PLANS[strategy], spec_k=3))
    budget = 3 if strategy == "pp" else None  # q in {chunk, 1, spec_k+1}
    with recompile_guard(eng.programs(), budget=budget):
        eng.run(_reqs())
    with recompile_guard(eng.programs(), budget=0):
        eng.run(_reqs())
    counts = eng.compile_counts()
    if any(v is None for v in counts.values()):
        pytest.skip("this jax cannot report jit cache sizes")
    if strategy != "pp":
        assert counts["chunk_prefill"] == 1
        assert counts["decode"] == 1
        assert counts["verify"] == 1


# ---------------------------------------------------------------------------
# overlap proof from compiled HLO (tp): q>1 rings hidden, q=1 monolithic


@mesh_only
@pytest.mark.parametrize("program", ["chunk_prefill", "verify"])
def test_tp_qgt1_exits_overlapped_in_hlo(program):
    """The q_len>1 TP programs route row exits through the comm.overlap
    rings — proven from the compiled HLO: >= 0.5 of the permute wire
    bytes ride behind partial GEMMs."""
    eng = build_engine(PARAMS, CFG, _scfg(plan=PLANS["tp"], spec_k=3))
    rep = overlap_assertion(program_hlo(eng, program), 0.5)
    assert rep.permutes > 0          # the rings are actually there
    assert rep.hidden_fraction >= 0.5


@mesh_only
def test_tp_decode_stays_monolithic():
    """q_len=1 decode keeps monolithic psum exits (the PR-5 pin: a
    single-row GEMM has nothing to hide a ring hop behind)."""
    hlo = program_hlo(build_engine(PARAMS, CFG, _scfg(plan=PLANS["tp"])),
                      "decode")
    assert "collective-permute" not in hlo
    assert "all-reduce" in hlo       # the exits still reduce


# ---------------------------------------------------------------------------
# pp: bubble accounting + stage validation


@mesh_only
def test_pp_bubble_and_stats():
    eng = build_engine(PARAMS, CFG, _scfg(plan=PLANS["pp"]))
    assert isinstance(eng, PPStagedEngine)
    eng.run(_reqs())
    st = eng.stats()
    assert st["plan"] == "pp" and st["plan_world"] == 2
    S, M = 2, st["pp_microbatches"]
    assert st["pp_bubble_fraction_modeled"] == (S - 1) / (M + S - 1)
    # measured bubble: some ticks MUST idle a stage (fill/drain), but a
    # microbatched steady loop keeps most cells busy
    assert 0.0 < st["pp_bubble_fraction"] < 1.0
    assert st["hbm_chip_bytes"] < st["hbm_model_bytes"] + st["hbm_chip_bytes"]


@mesh_only
def test_pp_engine_validation():
    with pytest.raises(ValueError, match="divisible by the stage count"):
        PPStagedEngine(PARAMS, dataclasses.replace(CFG, num_layers=3),
                       _scfg(plan=ParallelismPlan(pp=2)))
    with pytest.raises(ValueError, match="must divide num_slots"):
        PPStagedEngine(PARAMS, CFG, _scfg(plan=PLANS["pp"]),
                       microbatches=3)
    with pytest.raises(ValueError, match="stage_window"):
        PPStagedEngine(PARAMS, CFG, _scfg(plan=PLANS["pp"]),
                       stage_window=0)
    with pytest.raises(ValueError, match="needs ServeConfig.plan"):
        PPStagedEngine(PARAMS, CFG, _scfg(plan=PLANS["tp"]))


# ---------------------------------------------------------------------------
# fsdp: gather stats + codec wire accounting


@mesh_only
def test_fsdp_gather_stats_and_codec_stream():
    eng = build_engine(PARAMS, CFG, _scfg(plan=PLANS["fsdp"]))
    out = eng.run(_reqs())
    st = eng.stats()
    assert st["plan"] == "fsdp" and st["plan_world"] == 8
    assert st["weight_gather_ms"] > 0.0        # measured, not modeled
    assert st["weight_gather_wire_bytes"] > 0
    # the int8 weight_gather codec serves the same greedy stream here
    # (lossy within codec tolerance; greedy argmax is stable to it)
    plan8 = ParallelismPlan("fsdp", dp=8,
                            weight_gather=CompressionConfig(policy="int8"))
    assert build_engine(PARAMS, CFG, _scfg(plan=plan8)).run(_reqs()) == out


def test_param_gather_codec_halves_wire_at_size():
    """At real leaf sizes the int8 gather wire is <= ~1/2 the fp32 wire
    (codes + block scales); tiny leaves pad toward the codec block and
    the model reports that honestly — both directions pinned."""
    big = {"qkv": LeafMeta((1024, 3, 1024), "float32"),
           "fc1": LeafMeta((1024, 4096), "float32")}
    wg = CompressionConfig(policy="int8")
    full = param_gather_wire_bytes(big, 8, None, 1)
    coded = param_gather_wire_bytes(big, 8, wg, 128)
    assert coded < 0.5 * full
    tiny = {"ln": LeafMeta((32,), "float32")}
    assert (param_gather_wire_bytes(tiny, 8, wg, 128)
            > param_gather_wire_bytes(tiny, 8, None, 1))


# ---------------------------------------------------------------------------
# stock-safe: plan plumbing, validation, accounting


def test_build_engine_plan_none_is_plain_engine():
    eng = build_engine(PARAMS, CFG, _scfg())
    assert type(eng) is InferenceEngine
    assert "plan" not in eng.stats()


def test_plan_world():
    assert plan_world(PLANS["tp"]) == 4
    assert plan_world(PLANS["pp"]) == 2
    assert plan_world(PLANS["fsdp"]) == 8
    assert plan_world(ParallelismPlan("fsdp"), devices=list(range(6))) == 6


def test_serve_strategy_refuses_composition_and_nothing():
    with pytest.raises(NotImplementedError, match="ONE"):
        ParallelismPlan("fsdp", tp=2, overlap_comm=True).serve_strategy()
    with pytest.raises(ValueError, match="shards nothing"):
        ParallelismPlan().serve_strategy()


def test_serve_overrides_refuses_optimizer_coupled_knobs():
    with pytest.raises(ValueError, match="zero1"):
        ParallelismPlan("zero1").serve_overrides()
    with pytest.raises(ValueError, match="e5m2_allgather"):
        ParallelismPlan("zero1", tp=2, e5m2_allgather=True,
                        overlap_comm=True).serve_overrides()
    with pytest.raises(ValueError, match="error-feedback|error feedback"):
        ParallelismPlan(tp=2, overlap_comm=True,
                        compression=CompressionConfig(policy="int8_ef")
                        ).serve_overrides()


def test_serve_overrides_contents():
    ov = PLANS["tp"].serve_overrides()
    assert ov["strategy"] == "tp" and ov["tp"] == 4 and ov["overlap_comm"]
    ov = PLANS["pp"].serve_overrides()
    assert ov["strategy"] == "pp" and ov["pp"] == 2
    ov = PLANS["fsdp"].serve_overrides()
    assert ov["strategy"] == "fsdp" and ov["dp_axis"] == "dp"


def test_describe_tells_the_serve_story():
    assert "q_len=1 monolithic" in PLANS["tp"].describe()
    assert "staged layer shards" in PLANS["pp"].describe()
    assert "gathered on demand" in PLANS["fsdp"].describe()
    assert "single-chip engine" in ParallelismPlan().describe()


def test_serve_config_plan_validation():
    with pytest.raises(ValueError, match="must be a ParallelismPlan"):
        _scfg(plan=object()).validate()
    with pytest.raises(ValueError, match="zero1"):
        _scfg(plan=ParallelismPlan("zero1")).validate()
    with pytest.raises(NotImplementedError, match="LoRA|lora"):
        InferenceEngine(PARAMS, CFG,
                        _scfg(plan=PLANS["pp"], lora_rank=4, max_adapters=1))


def test_regress_polarity_covers_serve_plan_headliners():
    """The stage-24 bank's gate fields classify with the right sign:
    gather latency, PP bubble and the modeled residency footprint are
    lower-is-better; the goodput headline stays higher-is-better."""
    from apex_tpu.monitor.regress import classify_metric

    assert classify_metric("weight_gather_ms") == "lower"
    assert classify_metric("pp_bubble_fraction") == "lower"
    assert classify_metric("hbm_model_bytes") == "lower"
    assert classify_metric("hbm_chip_bytes") == "lower"
    assert classify_metric("goodput_rps") == "higher"
    # plan_world is topology, not a metric — never gated
    assert classify_metric("plan_world") is None


def test_hbm_serve_accounting_splits_strategies():
    """tp divides everything by world; pp divides layers only; fsdp
    shards layers and carries a one-layer gather workspace."""
    kv = 1000.0
    single = hbm_serve_bytes(PARAMS, strategy="single", world=1, kv_bytes=kv)
    tp = hbm_serve_bytes(PARAMS, strategy="tp", world=4, kv_bytes=kv / 4,
                         num_layers=CFG.num_layers)
    pp = hbm_serve_bytes(PARAMS, strategy="pp", world=2, kv_bytes=kv / 2,
                         num_layers=CFG.num_layers)
    fsdp = hbm_serve_bytes(PARAMS, strategy="fsdp", world=8, kv_bytes=kv,
                           num_layers=CFG.num_layers)
    assert single["total"] > max(tp["total"], pp["total"])
    assert tp["params_bytes"] == pytest.approx(single["params_bytes"] / 4)
    # pp keeps a full embed/head replica on the edge stages
    assert pp["params_bytes"] > single["params_bytes"] / 2 / 2
    assert fsdp["gather_workspace_bytes"] > 0
    assert single["gather_workspace_bytes"] == 0
    with pytest.raises(ValueError, match="strategy"):
        hbm_serve_bytes(PARAMS, strategy="zz", world=2, kv_bytes=0.0)

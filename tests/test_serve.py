"""apex_tpu.serve — paged KV cache, decode attention, sampling, engine.

All stock-jax-safe (single device, no shard_map): the serve programs run
with ``tp_axis=None``. Two acceptance gates live here:

* **request-order invariance** — continuous-batched multi-request streams
  are BITWISE identical (greedy; same-key sampled) to single-request
  decode of each prompt, in any admission order;
* **compile-count gate** — a mixed-length workload compiles EXACTLY one
  chunked-prefill program + one decode program (the bucket ladder and its
  per-bucket compiles are gone).

The prefix-cache / chunked-prefill / speculative-decoding oracles and the
allocator chaos gates live in ``tests/test_serve_prefix.py``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import attention_reference
from apex_tpu.serve import (
    BlockAllocator,
    InferenceEngine,
    KVCacheConfig,
    Request,
    SamplingConfig,
    ServeConfig,
    default_bucket_ladder,
    gather_kv,
    init_kv_cache,
    kv_cache_bytes,
    kv_read_bytes,
    kv_write_bytes_per_token,
    paged_attention,
    paged_attention_reference,
    paged_write,
    sample,
)
from apex_tpu.serve.decode import gpt_decode_step, gpt_prefill
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

CFG = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)
BUCKETS = (8, 16, 32, 64)


def _engine(sampling=None, **kw):
    # prefill_chunk=8 makes multi-chunk prompts common in these workloads
    scfg = ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                       sampling=sampling or SamplingConfig(), **kw)
    return InferenceEngine(PARAMS, CFG, scfg)


REQS = [
    Request("a", [1, 2, 3, 4, 5], max_new_tokens=6),
    Request("b", [7, 8, 9], max_new_tokens=4),
    Request("c", list(range(10, 22)), max_new_tokens=5),
]


# ---------------------------------------------------------------------------
# kv_cache: allocator, write/gather bookkeeping, byte models


def test_block_allocator_alloc_free_cycle():
    al = BlockAllocator(4)
    a = al.alloc(3)
    assert len(a) == 3 and al.free_count == 1
    assert al.alloc(2) is None          # insufficient: no partial grant
    assert al.free_count == 1
    al.free(a)
    assert al.free_count == 4
    b = al.alloc(4)
    assert sorted(b) == [0, 1, 2, 3]
    with pytest.raises(ValueError):
        al.free([99])
    al.free([0])
    with pytest.raises(ValueError):
        al.free([0])                     # double free


def test_paged_write_gather_roundtrip():
    """Tokens written through scattered block tables gather back exactly,
    partial last block and invalid (masked) writes included."""
    kv = KVCacheConfig(num_layers=1, num_heads=2, head_dim=4, num_blocks=6,
                       block_size=4, dtype=jnp.float32)
    cl = {k: v[0] for k, v in init_kv_cache(kv).items()}
    rng = jax.random.PRNGKey(1)
    t = 7  # 2 blocks minus one position
    k_new = jax.random.normal(rng, (2, t, 4))
    v_new = jax.random.normal(jax.random.fold_in(rng, 1), (2, t, 4))
    row = jnp.asarray([5, 2], jnp.int32)         # non-contiguous blocks
    positions = jnp.arange(t)
    cl = paged_write(cl, kv, k_new, v_new,
                     jnp.broadcast_to(row, (t, 2)), positions,
                     jnp.ones((t,), bool))
    k, v = gather_kv(cl, kv, row[None])          # (1, 2, 8, 4)
    np.testing.assert_array_equal(np.asarray(k[0, :, :t]),
                                  np.asarray(k_new))
    np.testing.assert_array_equal(np.asarray(v[0, :, :t]),
                                  np.asarray(v_new))
    # invalid writes are dropped: same positions, valid=False, new values
    cl2 = paged_write(cl, kv, k_new + 1.0, v_new + 1.0,
                      jnp.broadcast_to(row, (t, 2)), positions,
                      jnp.zeros((t,), bool))
    k2, _ = gather_kv(cl2, kv, row[None])
    np.testing.assert_array_equal(np.asarray(k2), np.asarray(k))


def test_kv_byte_models():
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8, num_blocks=10,
                       block_size=4, dtype=jnp.bfloat16)
    # pool: 2 (k+v) * L2 * H4 * B10 * bs4 * D8 * 2 bytes
    assert kv_cache_bytes(kv) == 2 * 2 * 4 * 10 * 4 * 8 * 2
    assert kv_write_bytes_per_token(kv) == 2 * 2 * 4 * 8 * 2
    # one slot at 5 tokens reads ceil(5/4)*4 = 8 block-granule tokens
    assert kv_read_bytes(kv, [5]) == 2 * 2 * 4 * 8 * 2 * 8
    kv8 = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                        num_blocks=10, block_size=4, quantized=True)
    # int8: 1 byte/elem + 4-byte scale per 8-elem vector = 1.5 bytes
    assert kv_cache_bytes(kv8) == int(2 * 2 * 4 * 10 * 4 * 8 * 1.5)
    # int4: packed nibble (0.5) + bf16 scale per 8-elem group = 0.75 —
    # exactly HALF the int8 pool (the >=1.9x acceptance gate, met at 2.0)
    kv4 = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                        num_blocks=10, block_size=4, quantized=True, bits=4)
    assert kv_cache_bytes(kv4) == int(2 * 2 * 4 * 10 * 4 * 8 * 0.75)
    assert kv_cache_bytes(kv8) / kv_cache_bytes(kv4) == 2.0
    # narrower groups trade bytes back for resolution
    kv4g = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                         num_blocks=10, block_size=4, quantized=True,
                         bits=4, group_size=4)
    assert kv_cache_bytes(kv4g) == int(2 * 2 * 4 * 10 * 4 * 8 * 1.0)
    with pytest.raises(ValueError):  # group must divide head_dim
        KVCacheConfig(num_layers=1, num_heads=1, head_dim=8, num_blocks=1,
                      quantized=True, bits=4, group_size=6).validate()
    with pytest.raises(ValueError):  # group_size is int4-only
        KVCacheConfig(num_layers=1, num_heads=1, head_dim=8, num_blocks=1,
                      quantized=True, group_size=4).validate()


# ---------------------------------------------------------------------------
# decode attention: the satellite coverage gates


def _filled_cache(kv, n, bt, lens, rng):
    """Write lens[s] random tokens per slot through its block-table row;
    returns (layer cache, contiguous K, contiguous V)."""
    cl = {k: v[0] for k, v in init_kv_cache(kv).items()}
    s_max = bt.shape[1] * kv.block_size
    K = jax.random.normal(rng, (n, kv.num_heads, s_max, kv.head_dim))
    V = jax.random.normal(jax.random.fold_in(rng, 1),
                          (n, kv.num_heads, s_max, kv.head_dim))
    for s in range(n):
        ln = int(lens[s])
        if ln == 0:
            continue
        positions = jnp.arange(ln)
        cl = paged_write(cl, kv, K[s, :, :ln], V[s, :, :ln],
                         jnp.broadcast_to(bt[s], (ln, bt.shape[1])),
                         positions, jnp.ones((ln,), bool))
    return cl, K, V


def test_paged_attention_q1_fp32_exact():
    """q_len=1 against the paged cache == attention_reference on the
    same-shape masked contiguous K/V — BITWISE (same ops, same shapes;
    unwritten pool positions are zeros, matching the zero padding)."""
    kv = KVCacheConfig(num_layers=1, num_heads=4, head_dim=8, num_blocks=12,
                       block_size=4, dtype=jnp.float32)
    bt = jnp.asarray([[0, 1, 2], [5, 6, 7], [9, 10, 11]], jnp.int32)
    lens = jnp.asarray([9, 5, 1], jnp.int32)
    cl, K, V = _filled_cache(kv, 3, bt, lens, jax.random.PRNGKey(2))
    q = jax.random.normal(jax.random.PRNGKey(3), (3, 4, 8))
    got = paged_attention_reference(q, cl, kv, bt, lens)
    s_tot = 12
    live = jnp.arange(s_tot) < lens[:, None]
    Kp = jnp.where(live[:, None, :, None], K[:, :, :s_tot], 0.0)
    Vp = jnp.where(live[:, None, :, None], V[:, :, :s_tot], 0.0)
    mask = jnp.arange(s_tot)[None, None, None, :] >= lens[:, None, None,
                                                          None]
    want = attention_reference(q[:, :, None], Kp, Vp, mask=mask)[:, :, 0]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # and against the TRIMMED per-slot reference (different reduction
    # shapes -> fp32 tolerance, not bitwise)
    for s in range(3):
        ln = int(lens[s])
        o = attention_reference(q[s][:, None], K[s, :, :ln], V[s, :, :ln])
        np.testing.assert_allclose(np.asarray(o[:, 0]),
                                   np.asarray(got[s]), atol=1e-6)


def test_paged_attention_int8_kv_within_codec_tolerance():
    kv = KVCacheConfig(num_layers=1, num_heads=4, head_dim=8, num_blocks=12,
                       block_size=4, dtype=jnp.float32)
    kv8 = KVCacheConfig(num_layers=1, num_heads=4, head_dim=8,
                        num_blocks=12, block_size=4, dtype=jnp.float32,
                        quantized=True)
    bt = jnp.asarray([[0, 1, 2], [5, 6, 7], [9, 10, 11]], jnp.int32)
    lens = jnp.asarray([12, 6, 3], jnp.int32)
    rng = jax.random.PRNGKey(4)
    cl, _, _ = _filled_cache(kv, 3, bt, lens, rng)
    cl8, _, _ = _filled_cache(kv8, 3, bt, lens, rng)
    q = jax.random.normal(jax.random.PRNGKey(5), (3, 4, 8))
    exact = paged_attention_reference(q, cl, kv, bt, lens)
    quant = paged_attention_reference(q, cl8, kv8, bt, lens)
    # int8 absmax/127 per 8-elem vector: attention outputs are convex
    # combinations of quantized V rows perturbed by quantized-K logits
    err = np.abs(np.asarray(quant) - np.asarray(exact)).max()
    assert 0 < err < 0.05, err


def test_paged_attention_int4_kv_within_codec_tolerance():
    """int4 KV (nibble-packed codes + bf16 group scales): attention stays
    within the coarser ±7-code half-step bound — lossy but bounded, and
    the halved pool is the point."""
    kv = KVCacheConfig(num_layers=1, num_heads=4, head_dim=8, num_blocks=12,
                       block_size=4, dtype=jnp.float32)
    kv4 = KVCacheConfig(num_layers=1, num_heads=4, head_dim=8,
                        num_blocks=12, block_size=4, dtype=jnp.float32,
                        quantized=True, bits=4)
    bt = jnp.asarray([[0, 1, 2], [5, 6, 7], [9, 10, 11]], jnp.int32)
    lens = jnp.asarray([12, 6, 3], jnp.int32)
    rng = jax.random.PRNGKey(4)
    cl, _, _ = _filled_cache(kv, 3, bt, lens, rng)
    cl4, _, _ = _filled_cache(kv4, 3, bt, lens, rng)
    q = jax.random.normal(jax.random.PRNGKey(5), (3, 4, 8))
    exact = paged_attention_reference(q, cl, kv, bt, lens)
    quant = paged_attention_reference(q, cl4, kv4, bt, lens)
    err = np.abs(np.asarray(quant) - np.asarray(exact)).max()
    assert 0 < err < 0.35, err
    # a narrower scale group recovers resolution
    kv4g = KVCacheConfig(num_layers=1, num_heads=4, head_dim=8,
                         num_blocks=12, block_size=4, dtype=jnp.float32,
                         quantized=True, bits=4, group_size=4)
    cl4g, _, _ = _filled_cache(kv4g, 3, bt, lens, rng)
    fine = paged_attention_reference(q, cl4g, kv4g, bt, lens)
    err_g = np.abs(np.asarray(fine) - np.asarray(exact)).max()
    assert err_g < err, (err_g, err)


@pytest.mark.parametrize("kv_mode", ["none", "int8", "int4"])
def test_paged_attention_pallas_interpret_parity(kv_mode):
    """The Pallas gather-attend kernel (scalar-prefetched block tables,
    online softmax, in-kernel int8/int4 dequant) matches the
    gather+reference path in interpret mode."""
    kv = KVCacheConfig(num_layers=1, num_heads=4, head_dim=8, num_blocks=12,
                       block_size=4, dtype=jnp.float32,
                       quantized=kv_mode != "none",
                       bits=4 if kv_mode == "int4" else 8)
    bt = jnp.asarray([[0, 1, 2], [5, 6, 7], [9, 10, 11]], jnp.int32)
    lens = jnp.asarray([9, 5, 0], jnp.int32)  # incl. an empty slot
    cl, _, _ = _filled_cache(kv, 3, bt, lens, jax.random.PRNGKey(6))
    q = jax.random.normal(jax.random.PRNGKey(7), (3, 4, 8))
    ref = paged_attention_reference(q, cl, kv, bt, lens)
    pal = paged_attention(q, cl, kv, bt, lens, use_pallas=True,
                          interpret=True)
    # live slots match; the empty slot is junk on both paths (uniform-
    # weights junk vs zeros) and the engine never reads it
    np.testing.assert_allclose(np.asarray(pal[:2]), np.asarray(ref[:2]),
                               atol=1e-5)
    assert np.isfinite(np.asarray(pal)).all()


def test_decode_step_matches_full_recompute():
    """Incremental prefill+decode logits == full prefill recompute of the
    growing sequence at every step (the KV bookkeeping proof), with fed
    tokens chosen to walk distinct inputs."""
    kv = KVCacheConfig(num_layers=CFG.num_layers, num_heads=CFG.num_heads,
                       head_dim=CFG.head_dim, num_blocks=8, block_size=4,
                       dtype=jnp.float32)
    prompt = [3, 14, 15, 92, 6]
    p = len(prompt)
    row = jnp.arange(8, dtype=jnp.int32)
    toks = jnp.zeros((16,), jnp.int32).at[:p].set(jnp.asarray(prompt))
    cache = init_kv_cache(kv)
    cache, logits = gpt_prefill(PARAMS, toks, jnp.int32(p), cache, row,
                                CFG, kv)
    feed = [10, 20, 30, 40]
    inc = [np.asarray(logits)]
    for i, t in enumerate(feed):
        cache, lg = gpt_decode_step(
            PARAMS, jnp.asarray([t]), jnp.asarray([p + i]),
            jnp.asarray([True]), cache, row[None], CFG, kv)
        inc.append(np.asarray(lg[0]))
    seq = list(prompt)
    for i in range(len(feed) + 1):
        tk = jnp.zeros((16,), jnp.int32).at[:len(seq)].set(
            jnp.asarray(seq))
        _, lg = gpt_prefill(PARAMS, tk, jnp.int32(len(seq)),
                            init_kv_cache(kv), row, CFG, kv)
        np.testing.assert_allclose(np.asarray(lg), inc[i], atol=2e-5)
        if i < len(feed):
            seq.append(feed[i])


# ---------------------------------------------------------------------------
# sampling


def test_sampling_greedy_is_argmax_and_key_free():
    logits = jax.random.normal(jax.random.PRNGKey(8), (3, 50))
    keys = np.zeros((3, 2), np.uint32)
    toks = sample(logits, jnp.asarray(keys), jnp.zeros((3,), jnp.int32),
                  SamplingConfig())
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))


def test_sampling_filters_and_determinism():
    rng = jax.random.PRNGKey(9)
    logits = jax.random.normal(rng, (4, 100)) * 3.0
    keys = jnp.asarray(
        np.stack([np.asarray(jax.random.fold_in(jax.random.PRNGKey(1), i),
                             np.uint32) for i in range(4)]))
    pos = jnp.asarray([5, 5, 7, 9], jnp.int32)
    cfg = SamplingConfig(temperature=0.7, top_k=10, top_p=0.9)
    a = np.asarray(sample(logits, keys, pos, cfg))
    b = np.asarray(sample(logits, keys, pos, cfg))
    np.testing.assert_array_equal(a, b)  # same (key, position) -> same draw
    c = np.asarray(sample(logits, keys, pos + 1, cfg))
    assert (a != c).any()                # position folds into the stream
    # top-k restricts support to the k largest logits per row
    topk = np.asarray(jax.lax.top_k(logits, 10)[1])
    for i in range(4):
        assert a[i] in topk[i]
    # top-p alone always keeps the argmax reachable
    tight = SamplingConfig(temperature=1.0, top_p=1e-9)
    t = np.asarray(sample(logits, keys, pos, tight))
    np.testing.assert_array_equal(t, np.asarray(jnp.argmax(logits, -1)))
    with pytest.raises(ValueError):
        SamplingConfig(top_p=0.0).validate()


# ---------------------------------------------------------------------------
# engine: the acceptance gates


def test_engine_request_order_invariance_greedy():
    """THE acceptance pin: continuous-batched streams are bitwise equal to
    single-request decode, in any admission order."""
    batched = _engine().run(REQS)
    shuffled = _engine().run([REQS[2], REQS[0], REQS[1]])
    singles = {}
    for r in REQS:
        singles.update(_engine().run([r]))
    assert batched == singles
    assert batched == shuffled
    assert set(batched) == {"a", "b", "c"}
    assert len(batched["a"]) == 6 and len(batched["b"]) == 4


def test_engine_request_order_invariance_sampled():
    samp = SamplingConfig(temperature=0.8, top_k=20, top_p=0.9)
    batched = _engine(sampling=samp).run(REQS)
    singles = {}
    for r in REQS:
        singles.update(_engine(sampling=samp).run([r]))
    assert batched == singles


def test_engine_compile_count_gate():
    """THE tightened gate: a mixed-length workload compiles EXACTLY one
    chunked-prefill program + one decode program — the PR-5 bucket ladder
    (one compile per bucket used) is gone. Speculation off -> no verify
    program; no full-prompt cache hit -> no CoW copy. The whole workload
    runs under the shared ``analyze.recompile_guard`` sentinel (warmup
    contract: one compile per cold program, then steady)."""
    from apex_tpu.analyze import recompile_guard

    eng = _engine()
    reqs = [
        Request("r1", [1, 2], max_new_tokens=3),
        Request("r2", list(range(10)), max_new_tokens=3),
        Request("r3", list(range(20)), max_new_tokens=3),
        Request("r4", [5, 6, 7], max_new_tokens=4),
        Request("r5", list(range(12)), max_new_tokens=2),
    ]
    with recompile_guard(eng.programs()):  # >1 compile per program raises
        out = eng.run(reqs)
    assert len(out) == 5
    counts = eng.compile_counts()
    if counts["decode"] is None:
        pytest.skip("this jax cannot report jit cache sizes")
    assert counts["decode"] == 1
    assert counts["chunk_prefill"] == 1    # one program, all lengths
    assert counts["verify"] == 0
    assert counts["cow_copy"] == 0


def test_engine_eos_and_max_len_retirement():
    greedy = _engine().run([REQS[0]])["a"]
    eos = int(greedy[1])
    out = _engine(eos_id=eos).run([REQS[0]])["a"]
    assert out[-1] == eos and len(out) < len(greedy)
    # max_new_tokens caps the stream exactly
    out2 = _engine().run([Request("x", [4, 5], max_new_tokens=2)])["x"]
    assert len(out2) == 2
    # context-window retirement: tiny max_context stops generation
    scfg = ServeConfig(num_slots=1, block_size=8, prefill_buckets=(8,),
                       max_context=8)
    eng = InferenceEngine(PARAMS, CFG, scfg)
    out3 = eng.run([Request("y", [1, 2, 3], max_new_tokens=50)])["y"]
    assert len(out3) == 8 - 3 + 1          # positions 3..8 exhausted


def test_engine_int8_kv_runs_and_matches_shapes():
    out = _engine(kv_quant="int8").run(REQS)
    base = _engine().run(REQS)
    assert {k: len(v) for k, v in out.items()} == \
        {k: len(v) for k, v in base.items()}


def test_engine_int4_kv_streams_pinned_and_accounted():
    """The int4 engine's streams are deterministic and admission-order-
    invariant BITWISE (the pinned-stream contract — greedy and sampled),
    and the stats record carries the sub-8-bit accounting: kv_bits=4 and
    a pool budget exactly half the int8 engine's."""
    for samp in (SamplingConfig(),
                 SamplingConfig(temperature=0.8, top_k=20)):
        batched = _engine(kv_quant="int4", sampling=samp).run(REQS)
        singles = {}
        for r in REQS:
            singles.update(
                _engine(kv_quant="int4", sampling=samp).run([r]))
        assert batched == singles
    eng4 = _engine(kv_quant="int4")
    eng8 = _engine(kv_quant="int8")
    eng4.run(REQS)
    st = eng4.stats()
    assert st["kv_bits"] == 4
    assert eng8.stats()["kv_bits"] == 8
    assert eng8.kv_budget_bytes() / eng4.kv_budget_bytes() == 2.0
    assert st["contexts_max"] == eng4.kv_cfg.tokens_capacity \
        // eng4.max_context


def test_int4_decode_matches_paged_recompute_and_bounds_flash():
    """The int4 KV bookkeeping oracle, two-sided. (a) chunk-by-chunk
    decode against the nibble-packed pools == one-shot
    ``gpt_paged_forward`` recompute of the whole sequence into a fresh
    int4 pool at fp32 round-off (per-row math independent of q — the
    PR-7 invariant — and both sides read/write the same quantized
    representation; the q=1 and q=n programs may reassociate). (b)
    TOLERANCE: both stay within the int4 codec's error of the bf16-free
    ``gpt_prefill`` cold path, which reads the RAW in-flight K/V and so
    bounds the quantization loss."""
    from apex_tpu.serve.decode import gpt_paged_forward

    kv = KVCacheConfig(num_layers=CFG.num_layers, num_heads=CFG.num_heads,
                       head_dim=CFG.head_dim, num_blocks=8, block_size=4,
                       dtype=jnp.float32, quantized=True, bits=4)
    prompt = [3, 14, 15, 92, 6]
    feed = [10, 20, 30]
    seq = prompt + feed
    p = len(prompt)
    row = jnp.arange(8, dtype=jnp.int32)

    def paged_all(tokens):
        """Whole sequence through ONE paged call on a fresh int4 pool."""
        n = len(tokens)
        cache = {k: v for k, v in init_kv_cache(kv).items()}
        _, lg = gpt_paged_forward(
            PARAMS, jnp.asarray(tokens)[None, :], jnp.zeros((1,), jnp.int32),
            jnp.asarray([n], jnp.int32), jnp.ones((1,), bool), cache,
            row[None], CFG, kv)
        return np.asarray(lg[0])

    # incremental: prompt in one paged call, then q=1 decode steps
    cache = init_kv_cache(kv)
    cache, lg = gpt_paged_forward(
        PARAMS, jnp.asarray(prompt)[None, :], jnp.zeros((1,), jnp.int32),
        jnp.asarray([p], jnp.int32), jnp.ones((1,), bool), cache,
        row[None], CFG, kv)
    inc = [np.asarray(lg[0, -1])]
    for i, t in enumerate(feed):
        cache, lg1 = gpt_decode_step(
            PARAMS, jnp.asarray([t]), jnp.asarray([p + i]),
            jnp.asarray([True]), cache, row[None], CFG, kv)
        inc.append(np.asarray(lg1[0]))
    full = paged_all(seq)
    for i in range(len(feed) + 1):
        np.testing.assert_allclose(full[p - 1 + i], inc[i], atol=1e-6)
    # (b) the codec loss vs the raw-K/V flash cold path is bounded
    kv_raw = KVCacheConfig(num_layers=CFG.num_layers,
                           num_heads=CFG.num_heads, head_dim=CFG.head_dim,
                           num_blocks=8, block_size=4, dtype=jnp.float32)
    toks = jnp.zeros((16,), jnp.int32).at[:len(seq)].set(jnp.asarray(seq))
    _, cold = gpt_prefill(PARAMS, toks, jnp.int32(len(seq)),
                          init_kv_cache(kv_raw), row, CFG, kv_raw)
    err = np.abs(np.asarray(cold) - inc[-1]).max()
    assert 0 < err < 0.05, err


def test_engine_admission_waits_for_blocks():
    """Pool sized for ~1.5 requests: the second admission defers until the
    first retires — the run still completes every request."""
    scfg = ServeConfig(num_slots=2, block_size=8, prefill_buckets=(8, 64),
                       num_blocks=3)  # 24 tokens of pool
    eng = InferenceEngine(PARAMS, CFG, scfg)
    reqs = [Request("p", [1, 2, 3], max_new_tokens=10),
            Request("q", [4, 5, 6], max_new_tokens=10)]
    out = eng.run(reqs)
    assert len(out["p"]) == 10 and len(out["q"]) == 10


def test_engine_unservable_requests_fail_loudly():
    eng = _engine()
    with pytest.raises(ValueError):
        eng.submit(Request("e", [], max_new_tokens=2))
    with pytest.raises(ValueError):
        eng.submit(Request("e", [1], max_new_tokens=0))
    with pytest.raises(ValueError):
        eng.submit(Request("e", list(range(64)), max_new_tokens=2))
    # a request the pool can NEVER hold stalls -> RuntimeError, not a hang
    scfg = ServeConfig(num_slots=1, block_size=8, prefill_buckets=(8, 64),
                       num_blocks=1)
    small = InferenceEngine(PARAMS, CFG, scfg)
    with pytest.raises(RuntimeError, match="pool is too small"):
        small.run([Request("big", list(range(20)), max_new_tokens=10)])


def test_engine_metrics_jsonl(tmp_path):
    from apex_tpu.monitor import JsonlSink, read_jsonl

    path = str(tmp_path / "serve.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        scfg = ServeConfig(num_slots=3, block_size=8,
                           prefill_buckets=BUCKETS)
        eng = InferenceEngine(PARAMS, CFG, scfg, sink=sink,
                              peak_flops_per_s=1e12)
        eng.run(REQS)
        st = eng.stats()
        assert st["completed"] == 3
        assert st["ttft_ms_p50"] > 0 and st["ttft_ms_p99"] > 0
        assert eng.hists["ttft_ms"].total == 3
        assert eng.throughput() > 0
    recs = list(read_jsonl(path))
    assert recs, "no step records written"
    decode_recs = [r for r in recs if r.get("phase") == "decode"]
    assert decode_recs, "no decode step records written"
    for r in decode_recs:
        assert r["schema"] == 1
        assert 0 < r["occupancy"] <= 1.0
        assert r["kv_read_bytes"] > 0 and r["kv_write_bytes"] > 0
        assert r["tokens_per_s"] > 0
        assert 0 <= r["decode_mfu"]
        assert r["active_slots"] >= 1     # in-graph Metrics made it out
        # the throughput-optimization telemetry rides every decode record
        assert r["prefill_backlog_tokens"] >= 0
        assert r["spec_proposed"] == 0    # speculation off in this engine
        assert r["prefix_blocks_needed_total"] >= 0
    # peak occupancy: all three requests were in flight at once
    assert max(r["occupancy"] for r in decode_recs) == 1.0


# ---------------------------------------------------------------------------
# monitor tier 2: lifecycle events, O(slots) state, stats/SLO, trace export


def test_engine_state_stays_o_slots():
    """THE leak gate: with retain_streams=False, per-request state after
    10x slot-count requests is zero — retirement folded every timeline
    into the histograms and dropped the per-uid entries."""
    n_slots = 3
    scfg = ServeConfig(num_slots=n_slots, block_size=8,
                       prefill_buckets=BUCKETS)
    got = {}
    eng = InferenceEngine(PARAMS, CFG, scfg, retain_streams=False,
                          on_retire=lambda uid, toks: got.__setitem__(
                              uid, toks))
    n = 10 * n_slots
    reqs = [Request(f"r{i:03d}", [1 + i % 7, 2, 3], max_new_tokens=3)
            for i in range(n)]
    out = eng.run(reqs)
    assert out == {}                       # streams not retained...
    assert len(got) == n                   # ...but delivered via callback
    assert eng.completed == n
    assert eng.per_request_state_count() == 0
    # the latencies all landed in the constant-size histograms
    assert eng.hists["ttft_ms"].total == n
    assert eng.hists["e2e_ms"].total == n
    assert eng.hists["tpot_ms"].total == n
    st = eng.stats()
    assert st["completed"] == n and st["ttft_ms_p99"] > 0
    # retained-mode comparison: identical streams
    base = InferenceEngine(PARAMS, CFG, scfg).run(reqs)
    assert got == base


def test_engine_event_timeline_and_chrome_trace(tmp_path):
    """Acceptance pin: the exported Chrome trace-event file is valid JSON
    whose span set matches the JSONL event log request-for-request."""
    import json

    from apex_tpu.monitor import (
        EventLog,
        JsonlSink,
        read_jsonl,
        write_chrome_trace,
    )
    from apex_tpu.monitor.events import request_spans

    path = str(tmp_path / "events.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        eng = InferenceEngine(PARAMS, CFG,
                              ServeConfig(num_slots=3, block_size=8,
                                          prefill_buckets=BUCKETS),
                              events=EventLog(sink=sink), chunk_tokens=2)
        out = eng.run(REQS)
    assert set(out) == {"a", "b", "c"}
    recs = list(read_jsonl(path))
    events = [r for r in recs if r.get("kind") == "event"]
    # every request ran the full lifecycle, in order, on one clock
    for uid in ("a", "b", "c"):
        evs = [r for r in events if r.get("uid") == uid]
        names = [r["event"] for r in evs]
        for must in ("submitted", "admitted", "prefill_start",
                     "prefill_end", "first_token", "retired"):
            assert must in names, (uid, names)
        ts = [r["t_ms"] for r in evs]
        assert ts == sorted(ts), f"{uid}: clock went backwards"
        ret = next(r for r in evs if r["event"] == "retired")
        assert ret["n_tokens"] == len(out[uid])
        assert ret["ttft_ms"] > 0 and ret["e2e_ms"] >= ret["ttft_ms"]
    # chrome trace: valid JSON round-trip...
    trace_path = str(tmp_path / "trace.json")
    write_chrome_trace(trace_path, recs)
    with open(trace_path) as f:
        trace = json.load(f)
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    # ...whose request-track span set matches the JSONL-derived spans
    # request-for-request (names AND timestamps)
    want = request_spans(events)
    req_spans = [e for e in spans if e["pid"] == 1]
    tid_uid = {e["tid"]: e["args"]["name"]
               for e in trace["traceEvents"]
               if e["ph"] == "M" and e["pid"] == 1
               and e["name"] == "thread_name"}
    got = {}
    for e in req_spans:
        got.setdefault(tid_uid[e["tid"]], []).append(
            (e["name"], e["ts"]))
    for uid in ("a", "b", "c"):
        want_set = sorted((s["name"], round(s["t0_ms"] * 1e3, 1))
                          for s in want[uid])
        assert sorted(got[uid]) == want_set, uid
    # slot tracks: one residency span per request, named by uid
    slot_spans = [e for e in spans if e["pid"] == 2]
    assert sorted(e["name"] for e in slot_spans) == ["a", "b", "c"]


def test_engine_slo_goodput_accounting():
    from apex_tpu.monitor import SloSpec

    # generous budgets: everything good
    scfg = ServeConfig(num_slots=3, block_size=8, prefill_buckets=BUCKETS)
    eng = InferenceEngine(PARAMS, CFG, scfg,
                          slo=SloSpec(ttft_ms=1e9, tpot_ms=1e9))
    eng.run(REQS)
    rep = eng.stats()["slo_report"]
    assert rep["completed"] == 3 and rep["good"] == 3
    assert rep["violations"] == {"ttft_ms": 0, "tpot_ms": 0}
    assert rep["goodput_rps"] > 0
    # tracker and engine SHARE histograms: one fold per retirement
    assert eng.hists["ttft_ms"].total == 3
    assert rep["ttft_ms_p50"] == eng.stats()["ttft_ms_p50"]
    # impossible budgets: everything violates, goodput 0
    eng2 = InferenceEngine(PARAMS, CFG, scfg,
                           slo=SloSpec(ttft_ms=1e-6))
    eng2.run(REQS)
    rep2 = eng2.stats()["slo_report"]
    assert rep2["good"] == 0 and rep2["violations"]["ttft_ms"] == 3
    assert rep2["goodput_rps"] == 0.0


def test_engine_stats_json_serializable():
    import json

    eng = _engine()
    eng.run(REQS)
    st = eng.stats()
    json.dumps(st)  # the whole snapshot must drop into a json_record
    assert st["generated_tokens"] == sum(
        len(v) for v in eng.finished.values())
    assert st["queue_depth"] == 0 and st["occupancy"] == 0.0
    assert st["decode_step_ms_p50"] > 0


def test_engine_from_checkpoint_latest_valid(tmp_path):
    """Weights load through resilience.CheckpointManager.latest_valid():
    a newer TORN checkpoint is skipped, the valid one serves."""
    from apex_tpu.resilience.chaos import corrupt_checkpoint
    from apex_tpu.resilience.checkpoint import CheckpointManager

    d = str(tmp_path / "ckpt")
    with CheckpointManager(d, keep_last_n=5) as mgr:
        mgr.save(PARAMS, step=3)
        mgr.save(jax.tree.map(lambda x: x * 0.5, PARAMS), step=7)
        corrupt_checkpoint(mgr.step_path(7), mode="flip")
    template = jax.tree.map(jnp.zeros_like, PARAMS)
    eng = InferenceEngine.from_checkpoint(
        d, template, CFG,
        ServeConfig(num_slots=3, block_size=8, prefill_buckets=BUCKETS))
    assert eng.checkpoint_step == 3
    assert eng.run([REQS[0]]) == _engine().run([REQS[0]])


def test_default_bucket_ladder_compat_shim():
    """The ladder survives as a COMPAT SHIM only: no prefill program is
    compiled per bucket anymore, and a short ladder no longer makes
    prompts unservable (chunked prefill handles any length)."""
    assert default_bucket_ladder(64) == (16, 32, 64)
    assert default_bucket_ladder(100) == (16, 32, 64, 100)
    eng = InferenceEngine(PARAMS, CFG, ServeConfig(
        num_slots=1, block_size=8, prefill_buckets=(8, 16),
        prefill_chunk=8, max_context=64))
    assert eng.buckets == (8, 16)          # surfaced for old callers
    assert eng.bucket_for(5) == 8
    # a prompt past the compat ladder still serves (the shim's whole point)
    out = eng.run([Request("long", list(range(30)), max_new_tokens=3)])
    assert len(out["long"]) == 3
    counts = eng.compile_counts()
    if counts["decode"] is not None:
        assert counts["chunk_prefill"] == 1


def test_engine_config_validation():
    with pytest.raises(ValueError, match="block_size"):
        InferenceEngine(PARAMS, CFG, ServeConfig(block_size=0))
    with pytest.raises(ValueError, match="exceeds the model"):
        InferenceEngine(PARAMS, CFG, ServeConfig(
            num_slots=1, block_size=8, max_context=CFG.max_seq * 2))
    with pytest.raises(ValueError, match="tp_axis"):
        InferenceEngine(PARAMS, CFG, ServeConfig(num_slots=1,
                                                 block_size=8), tp_size=2)
    with pytest.raises(ValueError, match="divisible"):
        InferenceEngine(PARAMS, CFG, ServeConfig(num_slots=1,
                                                 block_size=8),
                        tp_axis="tp", tp_size=3)

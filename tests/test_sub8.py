"""Sub-8-bit tier tests (stock-jax-safe): the fp8 amp tier — e4m3-fwd /
e5m2-grad ``fp8_dot`` with per-tensor delayed scaling, mid-run state_dict
round-trip, Metrics flattening — plus the ``analyze.dtype_leak`` fp8
policy-lattice fixture rows and the ``monitor.regress`` polarity coverage
for the new watcher-gated record fields (``kv_bits``/``wire_bytes_int4``/
``fp8_overflow_rate`` lower-better, ``contexts_max`` higher-better). The
mesh-level int4 collective tests live in ``test_comm_mesh.py`` /
``test_collective_counts.py``; the int4 KV tests in ``test_serve.py`` /
``test_megakernel.py`` / ``test_serve_cluster.py``."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.amp import fp8

REC = fp8.Fp8Recipe(history_len=4)


def _mlp_fixture():
    k = jax.random.PRNGKey(0)
    params = {
        "w1": jax.random.normal(k, (16, 32)) * 0.1,
        "w2": jax.random.normal(jax.random.fold_in(k, 1), (32, 8)) * 0.1,
    }
    x = jax.random.normal(jax.random.fold_in(k, 2), (4, 16))
    return params, x


def _loss_fn(params, st, x):
    h, st1 = fp8.fp8_dot(x, params["w1"], st["l1"], REC)
    h = jax.nn.relu(h)
    y, st2 = fp8.fp8_dot(h, params["w2"], st["l2"], REC)
    return jnp.mean(y ** 2), {"l1": st1, "l2": st2}


def _make_step(x):
    @jax.jit
    def step(params, st):
        (loss, fwd), grads = jax.value_and_grad(
            lambda p, s: _loss_fn(p, s, x), argnums=(0, 1),
            has_aux=True)(params, st)
        st = fp8.merge_state_grads(fwd, grads[1])
        params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                        params, grads[0])
        return params, st, loss

    return step


# ---------------------------------------------------------------------------
# fp8 dot + delayed scaling


def test_fp8_dot_matches_fp32_within_cast_tolerance():
    """With calibrated scales, e4m3 x e4m3 (f32 accumulate) tracks the
    fp32 dot within the e4m3 mantissa's relative error."""
    params, x = _mlp_fixture()
    st = fp8.init_fp8_state(["l1", "l2"], REC)
    step = _make_step(x)
    for _ in range(4):  # calibrate the delayed scales
        params, st, _ = step(params, st)
    y8, _ = fp8.fp8_dot(x, params["w1"], st["l1"], REC)
    yf = x @ params["w1"]
    rel = float(jnp.abs(y8 - yf).max() / jnp.abs(yf).max())
    assert 0 < rel < 0.06, rel  # lossy but bounded (e4m3: 3 mantissa bits)


def test_fp8_training_converges_and_scales_adapt():
    """The delayed scales move off their init to track the data's dynamic
    range (fwd e4m3 AND — via the state-cotangent channel — the e5m2 grad
    side), and the loss goes down."""
    params, x = _mlp_fixture()
    st = fp8.init_fp8_state(["l1", "l2"], REC)
    step = _make_step(x)
    losses = []
    for _ in range(6):
        params, st, l = step(params, st)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses
    for site in ("l1", "l2"):
        assert float(st[site].x.scale) != 1.0
        assert float(st[site].w.scale) != 1.0
        # the gradient half arrives through jax.grad's state slot
        assert float(st[site].g.scale) != 1.0
        assert float(jnp.max(st[site].g.amax_history)) > 0


def test_fp8_delayed_scale_reacts_within_history_window():
    """Feeding a 100x larger tensor drops the scale by ~100x within
    history_len steps — and the overflow_rate telemetry spikes on the
    step where the old scale saturates the cast."""
    st = fp8.init_tensor_state(REC)
    x = jnp.full((64,), 1.0)
    for _ in range(4):
        amax, over = fp8._observe(x, st.scale, fp8.E4M3)
        st = fp8.update_tensor_state(st, amax, over, fp8.E4M3, REC)
    s_small = float(st.scale)
    big = x * 100.0
    amax, over = fp8._observe(big, st.scale, fp8.E4M3)
    assert float(over) > 0.99  # the stale scale saturates every element
    st = fp8.update_tensor_state(st, amax, over, fp8.E4M3, REC)
    assert float(st.scale) == pytest.approx(s_small / 100.0, rel=1e-5)
    assert float(st.overflow_rate) > 0.99


def test_fp8_state_dict_roundtrip_midrun_exact():
    """The satellite gate: the delayed-scaling state survives a
    state_dict round-trip MID-RUN with the continued run bit-identical
    (the loss-scaler/EF-residual checkpoint contract)."""
    params, x = _mlp_fixture()
    st = fp8.init_fp8_state(["l1", "l2"], REC)
    step = _make_step(x)
    for _ in range(3):
        params, st, _ = step(params, st)
    d = fp8.state_dict(st)
    st2 = fp8.load_state_dict(
        jax.tree_util.tree_map(jnp.zeros_like, st), d)
    pa, sa, la = step(params, st)
    pb, sb, lb = step(params, st2)
    assert float(la) == float(lb)
    for a, b in zip(jax.tree_util.tree_leaves((pa, sa)),
                    jax.tree_util.tree_leaves((pb, sb))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fp8_state_dict_rejects_mismatch():
    st = fp8.init_fp8_state(["a"], REC)
    d = fp8.state_dict(st)
    with pytest.raises(ValueError):  # different structure
        fp8.load_state_dict(fp8.init_fp8_state(["b"], REC), d)
    with pytest.raises(ValueError):  # different history length
        fp8.load_state_dict(
            fp8.init_fp8_state(["a"], fp8.Fp8Recipe(history_len=8)), d)


def test_fp8_metrics_flatten_onto_metrics_pytree():
    from apex_tpu.monitor import Metrics

    st = fp8.init_fp8_state(["l1"], REC)
    m = fp8.fp8_metrics(st)
    assert "fp8_overflow_rate" in m
    assert "fp8_l1_x_scale" in m and "fp8_l1_g_amax" in m
    # every value is a Metrics-legal scalar
    metrics = Metrics().record(**{k: v for k, v in m.items()})
    assert float(metrics["fp8_overflow_rate"]) == 0.0


def test_fp8_recipe_and_policy_surface():
    with pytest.raises(ValueError):
        fp8.Fp8Recipe(history_len=0)
    assert fp8.fp8_max(fp8.E4M3) == 448.0
    assert fp8.fp8_max(fp8.E5M2) == 57344.0
    pol = amp.get_policy("FP8")
    assert pol.opt_level == "FP8" and pol.master_weights
    assert amp.policy_compute_dtype(pol) == jnp.dtype(jnp.float8_e4m3fn)
    assert fp8.fp8_policy() == pol


# ---------------------------------------------------------------------------
# dtype_leak: the fp8 policy lattice


def test_dtype_leak_clean_fp8_program_passes():
    from apex_tpu.analyze.dtype_leak import assert_no_dtype_leaks

    params, x = _mlp_fixture()
    st = fp8.init_fp8_state(["l1", "l2"], REC)
    rep = assert_no_dtype_leaks(
        lambda p, s: _loss_fn(p, s, x)[0], params, st,
        policy=amp.get_policy("FP8"))
    assert rep.total_dots == 2 and rep.fp32_dots == 0
    # the fp8 dots accumulate f32 (preferred_element_type): informational
    assert rep.fp32_accum_dots == 2


def test_dtype_leak_smuggled_fp32_dot_under_fp8_fails():
    from apex_tpu.analyze.dtype_leak import (
        DtypeLeakError,
        assert_no_dtype_leaks,
    )

    params, x = _mlp_fixture()
    st = fp8.init_fp8_state(["l1", "l2"], REC)

    def smuggled(p, s):
        l, _ = _loss_fn(p, s, x)
        return l + jnp.sum(x @ p["w1"])  # fp32 dot under the fp8 policy

    with pytest.raises(DtypeLeakError):
        assert_no_dtype_leaks(smuggled, params, st,
                              policy=amp.get_policy("FP8"))


def test_dtype_leak_lattice_counts_half_dots_under_fp8():
    """bf16 dots riding under an fp8 policy are one rung above: counted
    (off_policy_half_dots) but never raised — and under a bf16 policy the
    same program reports zero."""
    from apex_tpu.analyze.dtype_leak import dtype_leak_report

    x = jnp.ones((4, 8), jnp.bfloat16)
    w = jnp.ones((8, 8), jnp.bfloat16)

    def f(x, w):
        return jnp.sum(jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))

    rep8 = dtype_leak_report(f, x, w, policy=jnp.float8_e4m3fn)
    assert rep8.off_policy_half_dots == 1 and rep8.fp32_dots == 0
    assert rep8.ok  # informational, not a failure
    rep16 = dtype_leak_report(f, x, w, policy=jnp.bfloat16)
    assert rep16.off_policy_half_dots == 0


# ---------------------------------------------------------------------------
# regress polarity: the new watcher-gated fields


def test_regress_polarity_covers_sub8_fields():
    from apex_tpu.monitor.regress import classify_metric, compare_records

    assert classify_metric("kv_bits") == "lower"
    assert classify_metric("wire_bytes_int4") == "lower"
    assert classify_metric("fp8_overflow_rate") == "lower"
    assert classify_metric("contexts_max") == "higher"
    # and they actually gate a record diff in the right direction
    base = {"kv_bits": 4, "contexts_max": 8, "fp8_overflow_rate": 0.0,
            "wire_bytes_int4": 1000}
    worse = {"kv_bits": 8, "contexts_max": 4, "fp8_overflow_rate": 0.2,
             "wire_bytes_int4": 2000}
    rep = compare_records(base, worse, tol=0.1)
    assert not rep["ok"]
    assert {r["key"] for r in rep["regressions"]} == set(base)
    assert compare_records(base, dict(base), tol=0.1)["ok"]


# ---------------------------------------------------------------------------
# the concurrency headline: a fixed KV HBM budget serves 2x the contexts


def test_int4_doubles_contexts_at_fixed_hbm_budget():
    """The serving claim behind the int4 KV mode: at a fixed pool byte
    budget, halving bytes/token doubles the blocks — and so the
    concurrent max-length contexts — the pool holds."""
    from apex_tpu.serve.kv_cache import KVCacheConfig, kv_cache_bytes

    def blocks_for_budget(bits, budget):
        one = KVCacheConfig(num_layers=2, num_heads=4, head_dim=64,
                            num_blocks=1, block_size=16, quantized=True,
                            bits=bits)
        return budget // kv_cache_bytes(one)

    budget = 64 << 20
    b8 = blocks_for_budget(8, budget)
    b4 = blocks_for_budget(4, budget)
    assert b4 == 2 * b8

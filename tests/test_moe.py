"""MoE / expert-parallelism tests on the 8-device virtual mesh.

No reference counterpart (SURVEY §2.3: EP "not present" in the reference);
the gate here is internal consistency: the EP-sharded all-to-all program must
reproduce the single-rank dense computation exactly, TP must not change the
math, and the capacity logic must degrade to pass-through (zero expert
output) rather than corrupt neighbouring tokens.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.moe import (
    MoEConfig,
    init_moe_params,
    moe_mlp,
    moe_param_specs,
)

HID, FFN = 16, 32


def _cfg(**kw):
    base = dict(num_experts=8, hidden=HID, ffn_hidden=FFN, top_k=2,
                capacity_factor=8.0, dtype=jnp.float32)
    base.update(kw)
    return MoEConfig(**base)


def _run(mesh, cfg, params, x, ep_axis="dp"):
    def body(p, x):
        out, aux = moe_mlp(p, x, cfg, ep_axis=ep_axis)
        return out, aux["loss"][None]

    specs = moe_param_specs(ep_axis if mesh.shape.get("dp", 1) > 1 else None)
    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P("dp", None, None)),
        out_specs=(P("dp", None, None), P("dp"))))(params, x)


def _dense_reference(params, x, cfg):
    """Unbatched dense mixture: every token through every expert, combined
    by the renormalized top-k gates — the capacity-free ground truth."""
    xf = x.reshape(-1, cfg.hidden)
    logits = xf.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    gate, idx = jax.lax.top_k(probs, cfg.top_k)
    gate = gate / gate.sum(-1, keepdims=True)
    outs = []
    for e in range(cfg.num_experts):
        y = jax.nn.gelu(xf @ params["fc1_kernel"][e] + params["fc1_bias"][e],
                        approximate=True)
        outs.append(y @ params["fc2_kernel"][e] + params["fc2_bias"][e])
    outs = jnp.stack(outs, 1)  # (T, E, h)
    w = jnp.zeros_like(probs).at[
        jnp.arange(xf.shape[0])[:, None], idx].set(gate)
    return jnp.einsum("te,teh->th", w, outs).reshape(x.shape)


@pytest.fixture
def mesh_dp8():
    return build_mesh(tp=1, pp=1, sp=1, devices=jax.devices())


@pytest.fixture
def mesh_dp4_tp2():
    return build_mesh(tp=2, pp=1, sp=1, devices=jax.devices())


def test_moe_matches_dense_reference(mesh_dp8):
    """Ample capacity ⇒ the capacity-dispatch path must equal the dense
    top-k mixture bit-for-bit (fp32)."""
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, HID), jnp.float32)
    out, _ = _run(mesh_dp8, cfg, params, x)
    ref = _dense_reference(params, x, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ep8_matches_ep1(mesh_dp8):
    """The all-to-all EP program must reproduce the single-rank (ep=None)
    computation on the same global batch."""
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, HID), jnp.float32)
    out_ep, _ = _run(mesh_dp8, cfg, params, x)

    def body_local(p, xb):
        out, aux = moe_mlp(p, xb, cfg, ep_axis=None)
        return out

    mesh1 = build_mesh(tp=1, pp=1, sp=1, devices=jax.devices())
    # same per-rank token batches, experts replicated (no EP exchange)
    out_ref = jax.jit(shard_map(
        body_local, mesh=mesh1,
        in_specs=(moe_param_specs(None), P("dp", None, None)),
        out_specs=P("dp", None, None)))(params, x)
    np.testing.assert_allclose(np.asarray(out_ep), np.asarray(out_ref),
                               rtol=1e-5, atol=1e-5)


def test_tp2_matches_tp1(mesh_dp8, mesh_dp4_tp2):
    """TP-split expert FFN must not change the math."""
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg, tp=2)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, HID), jnp.float32)
    out1, _ = _run(mesh_dp8, cfg, params, x)
    out2, _ = _run(mesh_dp4_tp2, cfg, params,
                   x.reshape(4, 8, HID))
    np.testing.assert_allclose(np.asarray(out1).reshape(4, 8, HID),
                               np.asarray(out2), rtol=1e-4, atol=1e-4)


def test_capacity_drop_zeroes_not_corrupts(mesh_dp8):
    """With capacity 1 and a router forced to a single expert, all but one
    token per rank must come out zero (residual pass-through contract) and
    the survivor must match its dense value."""
    cfg = _cfg(top_k=1, capacity_factor=1e-9)  # capacity clamps to minimum
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    # router that always picks expert 0
    params["router"] = jnp.zeros_like(params["router"]).at[:, 0].set(0.0)
    params["router"] = params["router"].at[0, 0].add(100.0)
    x = jnp.ones((8, 6, HID), jnp.float32)
    out, _ = _run(mesh_dp8, cfg, params, x)
    out = np.asarray(out)
    cap = cfg.capacity(6)
    # per rank: first `cap` tokens kept, rest dropped to exactly zero
    for r in range(8):
        assert np.all(out[r, cap:] == 0.0), "dropped tokens must be zero"
        assert np.any(out[r, 0] != 0.0), "kept token must pass the expert"


def test_moe_grads_flow_and_aux_loss(mesh_dp8):
    """d(main+aux)/dparams is finite and nonzero for every leaf; the
    load-balance loss is minimized (=1 per Switch eq.4 scaling) under a
    uniform router."""
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, HID), jnp.float32)

    def loss_fn(p):
        def body(p, xb):
            out, aux = moe_mlp(p, xb, cfg)
            return ((jnp.sum(out * out)
                     + aux["loss"]) / jax.lax.axis_size("dp"))[None]

        specs = moe_param_specs("dp")
        per = shard_map(body, mesh=mesh_dp8,
                        in_specs=(specs, P("dp", None, None)),
                        out_specs=P("dp"))(p, x)
        return jnp.sum(per)

    grads = jax.jit(jax.grad(loss_fn))(params)
    for path, g in jax.tree_util.tree_flatten_with_path(grads)[0]:
        a = np.asarray(g)
        assert np.all(np.isfinite(a)), f"non-finite grad at {path}"
        assert np.any(a != 0.0), f"zero grad at {path}"

    # uniform router ⇒ lb_loss == E * E*(1/E)*(1/E) == 1
    cfgu = _cfg()
    pu = init_moe_params(jax.random.PRNGKey(0), cfgu)
    pu["router"] = jnp.zeros_like(pu["router"])

    def body(p, xb):
        _, aux = moe_mlp(p, xb, cfgu)
        return aux["lb_loss"][None]

    lb = jax.jit(shard_map(body, mesh=mesh_dp8,
                   in_specs=(moe_param_specs("dp"), P("dp", None, None)),
                   out_specs=P("dp")))(pu, x)
    np.testing.assert_allclose(np.asarray(lb), 1.0, rtol=1e-5)


# ---------------------------------------------------------------------------
# MoE inside the flagship GPT (GPTConfig.num_experts)


def _pipeline_sequential_reference(cfg, params, tok, tgt, ref_mesh,
                                   interleaved=False):
    """Sequential gpt_loss on the pipeline params flattened back to one
    layer stack (interleaved depth order is chunk-major v*pp + s, which a
    plain reshape restores) — the shared ground truth for the pipeline
    parity tests."""
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import gpt_loss, gpt_param_specs

    lead = 3 if interleaved else 2
    flat_layers = jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[lead:]), params["stages"])
    flat = {"embed": params["embed"], "layers": flat_layers,
            "head": params["head"]}

    def body(p, t, g):
        return replicate_loss(gpt_loss(p, t, g, cfg), ref_mesh,
                              masked_axis=None)

    return jax.jit(shard_map(body, mesh=ref_mesh,
                     in_specs=(gpt_param_specs(cfg), P("dp"), P("dp")),
                     out_specs=P()))(flat, tok, tgt)


def test_gpt_moe_single_expert_matches_dense(mesh_dp8):
    """A 1-expert MoE GPT with a zeroed router and ample capacity is the
    dense GPT plus a known constant aux loss (lb=1 exactly at E=1, z=0
    with zero router logits)."""
    import dataclasses

    from apex_tpu.transformer.moe import MoEConfig
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    dense_cfg = GPTConfig(vocab_size=96, max_seq=16, hidden=32, num_layers=2,
                          num_heads=4, dtype=jnp.float32)
    moe_cfg = dataclasses.replace(dense_cfg, num_experts=1, moe_top_k=1,
                                  moe_capacity_factor=64.0)
    dense = init_gpt_params(jax.random.PRNGKey(0), dense_cfg)
    moe = init_gpt_params(jax.random.PRNGKey(0), moe_cfg)
    # carry the dense FFN weights into the single expert; silence the router
    moe["layers"]["fc1_kernel"] = dense["layers"]["fc1_kernel"][:, None]
    moe["layers"]["fc1_bias"] = dense["layers"]["fc1_bias"][:, None]
    moe["layers"]["fc2_kernel"] = dense["layers"]["fc2_kernel"][:, None]
    moe["layers"]["fc2_bias"] = dense["layers"]["fc2_bias"][:, None]
    moe["layers"]["router"] = jnp.zeros_like(moe["layers"]["router"])
    for k in ("ln1_w", "ln1_b", "qkv_kernel", "qkv_bias", "out_kernel",
              "out_bias", "ln2_w", "ln2_b"):
        moe["layers"][k] = dense["layers"][k]
    moe["embed"], moe["head"] = dense["embed"], dense["head"]

    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 96)
    tgt = jnp.roll(tok, -1, 1)
    mesh1 = build_mesh(tp=1, pp=1, sp=1, devices=jax.devices()[:1])

    def run(cfg, params):
        from apex_tpu.transformer.pipeline_parallel.schedules.common import (
            replicate_loss,
        )

        def body(p, t, g):
            return replicate_loss(gpt_loss(p, t, g, cfg), mesh1,
                                  masked_axis=None)

        return float(jax.jit(shard_map(
            body, mesh=mesh1, in_specs=(gpt_param_specs(cfg), P(), P()),
            out_specs=P()))(params, tok, tgt))

    aux_expected = MoEConfig(num_experts=1, hidden=32, ffn_hidden=128,
                             top_k=1).lb_loss_weight * 1.0
    l_moe, l_dense = run(moe_cfg, moe), run(dense_cfg, dense)
    np.testing.assert_allclose(l_moe - aux_expected, l_dense,
                               rtol=1e-5, atol=1e-6)


def test_gpt_moe_ep8_trains(mesh_dp8):
    """Flagship GPT with 8 experts over the dp=8 mesh: expert weights are
    dp-SHARDED (each rank owns one expert), the full train step runs, the
    loss drops, and every grad leaf is finite."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    cfg = GPTConfig(vocab_size=96, max_seq=16, hidden=32, num_layers=2,
                    num_heads=4, dtype=jnp.float32, num_experts=8,
                    moe_capacity_factor=2.0)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    specs = gpt_param_specs(cfg)
    opt = FusedAdam(lr=1e-3)
    opt_state = opt.init(params)
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jnp.roll(tok, -1, 1)

    def loss_fn(p):
        def body(p, t, g):
            return replicate_loss(gpt_loss(p, t, g, cfg), mesh_dp8,
                                  masked_axis=None)

        return shard_map(body, mesh=mesh_dp8,
                         in_specs=(specs, P("dp"), P("dp")),
                         out_specs=P())(p, tok, tgt)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return jax.tree.map(lambda p, u: p + u, params, updates), \
            opt_state, loss, grads

    losses = []
    for _ in range(5):
        params, opt_state, loss, grads = step(params, opt_state)
        losses.append(float(loss))
    assert all(np.isfinite(x) for x in losses)
    assert losses[-1] < losses[0]
    for path, g in jax.tree_util.tree_flatten_with_path(grads)[0]:
        assert np.all(np.isfinite(np.asarray(g))), f"non-finite at {path}"


def test_gpt_moe_megatron_sp_matches_plain(mesh_dp4_tp2):
    """MoE under megatron_sp (gather -> MoE -> shard slice) == MoE on the
    plain TP path — loss AND grads, tp=2 x dp(=ep)=4."""
    import dataclasses

    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    base = GPTConfig(vocab_size=96, max_seq=16, hidden=32, num_layers=2,
                     num_heads=4, dtype=jnp.float32, num_experts=4,
                     moe_capacity_factor=4.0)
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jnp.roll(tok, -1, 1)

    def run(cfg):
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        specs = gpt_param_specs(cfg)

        def loss_fn(p):
            def body(p, t, g):
                return replicate_loss(gpt_loss(p, t, g, cfg), mesh_dp4_tp2,
                                      masked_axis=None)

            return shard_map(body, mesh=mesh_dp4_tp2,
                             in_specs=(specs, P("dp"), P("dp")),
                             out_specs=P())(p, tok, tgt)

        return jax.jit(jax.value_and_grad(loss_fn))(params)

    l0, g0 = run(base)
    l1, g1 = run(dataclasses.replace(base, megatron_sp=True))
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), g1, g0)


def test_bert_moe_trains(mesh_dp8):
    """BERT with MoE layers (shared _layer_stack): MLM loss carries the
    router aux term, trains finite; megatron_sp on BERT refuses loudly."""
    import dataclasses

    import pytest as _pytest

    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import bert_mlm_loss, gpt_param_specs
    from apex_tpu.transformer.testing.standalone_bert import (
        BertConfig,
        init_bert_params,
    )

    cfg = BertConfig(vocab_size=64, max_seq=16, hidden=32, num_layers=2,
                     num_heads=4, dtype=jnp.float32, remat=False,
                     num_experts=8, moe_capacity_factor=2.0)
    params = init_bert_params(jax.random.PRNGKey(6), cfg)
    b, s = 8, 16
    tok = jax.random.randint(jax.random.PRNGKey(7), (b, s), 0, 64)
    tgt = jax.random.randint(jax.random.PRNGKey(8), (b, s), 0, 64)
    lm = jnp.ones((b, s), jnp.float32)

    specs = gpt_param_specs(cfg)
    specs["embed"]["type"] = P()
    specs["embed"]["ln_w"] = P()
    specs["embed"]["ln_b"] = P()
    specs["head"] = {k: P() for k in ("dense_kernel", "dense_bias",
                                      "ln_w", "ln_b")}

    def loss_fn(p):
        def body(p, tok, tgt, lm):
            return replicate_loss(bert_mlm_loss(p, tok, tgt, lm, cfg),
                                  mesh_dp8, masked_axis=None)

        return shard_map(body, mesh=mesh_dp8,
                         in_specs=(specs, P("dp"), P("dp"), P("dp")),
                         out_specs=P())(p, tok, tgt, lm)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert np.isfinite(float(loss))
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree.leaves(grads))
    # router grads exist (aux loss is wired through bert_mlm_loss)
    assert np.any(np.asarray(grads["layers"]["router"]) != 0.0)

    # round 5: BERT rides Megatron-SP (the old NotImplementedError guard
    # is gone) — the MoE + megatron_sp composition must also run
    sp_cfg = dataclasses.replace(cfg, megatron_sp=True)

    def body2(p, tok, tgt, lm):
        return replicate_loss(
            bert_mlm_loss(p, tok, tgt, lm, sp_cfg),
            mesh_dp8, masked_axis=None)

    loss_sp = shard_map(body2, mesh=mesh_dp8,
                        in_specs=(specs, P("dp"), P("dp"), P("dp")),
                        out_specs=P())(params, tok, tgt, lm)
    # tp=1: megatron_sp is the identity sharding — same loss
    np.testing.assert_allclose(float(loss_sp), float(loss), rtol=1e-5)


@pytest.mark.slow
def test_gpt_moe_pipeline_matches_sequential():
    """MoE through the 1F1B pipeline: the schedules accumulate the router
    aux loss per stage (stage_aux) and the total equals the non-pipeline
    gpt_loss on the flattened params; router/expert grads are nonzero."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_without_interleaving,
    )
    from apex_tpu.transformer.testing import GPTConfig
    from apex_tpu.transformer.testing.standalone_gpt import (
        gpt_pipeline_params,
        gpt_pipeline_spec,
        gpt_pipeline_specs_tree,
    )

    # experts must divide BOTH meshes' dp: pipeline dp=4, sequential dp=8
    cfg = GPTConfig(vocab_size=96, max_seq=16, hidden=32, num_layers=2,
                    num_heads=4, dtype=jnp.float32, tie_embeddings=False,
                    num_experts=8, moe_capacity_factor=8.0)
    pp = 2
    params = gpt_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp)
    mesh = build_mesh(tp=1, pp=pp, sp=1)  # dp=4 (= ep for the schedule)
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jnp.roll(tok, -1, 1)

    loss, grads = forward_backward_pipelining_without_interleaving(
        gpt_pipeline_spec(cfg), params, (tok, tgt), num_microbatches=2,
        mesh=mesh, params_specs=gpt_pipeline_specs_tree(cfg),
        data_spec=P(None, "dp"), remat=False)

    want = _pipeline_sequential_reference(
        cfg, params, tok, tgt, build_mesh(tp=1, pp=1, sp=1))  # dp=8
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)
    assert np.any(np.asarray(grads["stages"]["router"]) != 0.0)
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree.leaves(grads))


@pytest.mark.slow
def test_gpt_moe_interleaved_pipeline_matches_sequential():
    """MoE aux through the interleaved schedule (vp=2): equals the
    sequential loss on the chunk-major-flattened params."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_with_interleaving,
    )
    from apex_tpu.transformer.testing import GPTConfig
    from apex_tpu.transformer.testing.standalone_gpt import (
        gpt_pipeline_params,
        gpt_pipeline_spec,
        gpt_pipeline_specs_tree,
    )

    cfg = GPTConfig(vocab_size=96, max_seq=16, hidden=32, num_layers=4,
                    num_heads=4, dtype=jnp.float32, tie_embeddings=False,
                    num_experts=8, moe_capacity_factor=8.0)
    pp, vp = 2, 2
    params = gpt_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp, vp=vp)
    mesh = build_mesh(tp=1, pp=pp, sp=1)  # dp=4
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jnp.roll(tok, -1, 1)

    loss, grads = forward_backward_pipelining_with_interleaving(
        gpt_pipeline_spec(cfg), params, (tok, tgt), num_microbatches=2,
        virtual_pipeline_size=vp, mesh=mesh,
        params_specs=gpt_pipeline_specs_tree(cfg, interleaved=True),
        data_spec=P(None, "dp"), remat=False)

    want = _pipeline_sequential_reference(
        cfg, params, tok, tgt, build_mesh(tp=1, pp=1, sp=1),
        interleaved=True)  # dp=8
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)
    assert np.any(np.asarray(grads["stages"]["router"]) != 0.0)


@pytest.mark.slow
def test_gpt_moe_pipeline_megatron_sp_triple_composition():
    """Everything at once: pp=2 x tp=2 x megatron_sp x MoE(ep=dp=2) through
    the 1F1B schedule equals the sequential gpt_loss — the full parallelism
    stack in one program (stage_aux + seq gather/scatter + tp-split
    experts + ppermute ring)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_without_interleaving,
    )
    from apex_tpu.transformer.testing import GPTConfig
    from apex_tpu.transformer.testing.standalone_gpt import (
        gpt_pipeline_params,
        gpt_pipeline_spec,
        gpt_pipeline_specs_tree,
    )

    cfg = GPTConfig(vocab_size=96, max_seq=16, hidden=32, num_layers=2,
                    num_heads=4, dtype=jnp.float32, tie_embeddings=False,
                    num_experts=2, moe_capacity_factor=8.0,
                    megatron_sp=True)
    pp, tp = 2, 2
    mesh = build_mesh(tp=tp, pp=pp, sp=1)  # dp=2 = ep
    params = gpt_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp)
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 96)
    tgt = jnp.roll(tok, -1, 1)

    loss, grads = forward_backward_pipelining_without_interleaving(
        gpt_pipeline_spec(cfg), params, (tok, tgt), num_microbatches=2,
        mesh=mesh, params_specs=gpt_pipeline_specs_tree(cfg),
        data_spec=P(None, "dp"), remat=False)

    want = _pipeline_sequential_reference(
        cfg, params, tok, tgt,
        build_mesh(tp=2, pp=1, sp=1, devices=jax.devices()[:4]))  # dp=2
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)
    assert np.any(np.asarray(grads["stages"]["router"]) != 0.0)
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree.leaves(grads))


def test_gpt_moe_seq_dispatch_matches_plain(mesh_dp4_tp2):
    """Sequence-sharded MoE dispatch (route local s/tp tokens, all-gather
    kept SLOTS, combine locally) == the plain path, loss AND grads, at
    ample capacity where the per-shard capacity semantics cannot drop
    differently. Removes the tp-fold router/dispatch duplication the
    gathered path pays (PERF.md "MoE under Megatron-SP")."""
    import dataclasses

    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    base = GPTConfig(vocab_size=96, max_seq=16, hidden=32, num_layers=2,
                     num_heads=4, dtype=jnp.float32, num_experts=4,
                     moe_capacity_factor=4.0)
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 96)
    tgt = jnp.roll(tok, -1, 1)

    def run(cfg):
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        specs = gpt_param_specs(cfg)

        def loss_fn(p):
            def body(p, t, g):
                return replicate_loss(gpt_loss(p, t, g, cfg), mesh_dp4_tp2,
                                      masked_axis=None)

            return shard_map(body, mesh=mesh_dp4_tp2,
                             in_specs=(specs, P("dp"), P("dp")),
                             out_specs=P())(p, tok, tgt)

        return jax.jit(jax.value_and_grad(loss_fn))(params)

    l0, g0 = run(base)
    l1, g1 = run(dataclasses.replace(base, megatron_sp=True,
                                     moe_seq_dispatch=True))
    # the aux (load-balance) loss becomes a per-sequence-shard estimate
    # under the sharded dispatch — the same approximation class dp-local
    # aux already makes — so loss/grads agree to aux-sized tolerance, not
    # bitwise; the dispatch/combine math itself is exact
    # (test_moe_seq_dispatch_exact_vs_gathered).
    np.testing.assert_allclose(float(l1), float(l0), rtol=5e-4)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-2, atol=5e-4), g1, g0)


def test_moe_seq_dispatch_exact_vs_gathered(mesh_dp4_tp2):
    """The sequence-sharded dispatch/combine math is EXACT vs the
    replicated-token path at ample capacity (aux weights zeroed: the aux
    loss legitimately becomes a per-shard estimate — same approximation
    class as dp-local aux — and is covered by the GPT-level test)."""
    cfg = _cfg(num_experts=4, lb_loss_weight=0.0, z_loss_weight=0.0)
    params = init_moe_params(jax.random.PRNGKey(0), cfg, ep=4, tp=2)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, HID), jnp.float32)

    def plain(p, xb):
        out, _ = moe_mlp(p, xb, cfg, ep_axis="dp")
        return out

    def seq_sharded(p, xb):
        out, _ = moe_mlp(p, xb, cfg, ep_axis="dp", seq_shard_axis="tp")
        return out

    specs = moe_param_specs("dp")
    out_plain = jax.jit(shard_map(
        plain, mesh=mesh_dp4_tp2, in_specs=(specs, P("dp", None, None)),
        out_specs=P("dp", None, None)))(params, x)
    out_seq = jax.jit(shard_map(
        seq_sharded, mesh=mesh_dp4_tp2, in_specs=(specs, P("dp", "tp", None)),
        out_specs=P("dp", "tp", None)))(params, x)
    np.testing.assert_allclose(np.asarray(out_seq), np.asarray(out_plain),
                               rtol=1e-6, atol=1e-6)

"""Compressed-collective tests on the 8-device virtual mesh: the quantized
allreduce against psum, DDP/ZeRO integration, and the int8+EF convergence
parity on the GPT fixture (the acceptance gate: compressed training must
track the uncompressed loss curve)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.comm import (
    CompressionConfig,
    compressed_allreduce,
    compressed_psum_scatter,
)
from apex_tpu.comm import error_feedback as ef
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.parallel.mesh import build_mesh

INT8 = CompressionConfig(policy="int8", block_size=128, min_elements=128)
INT8_EF = CompressionConfig(policy="int8_ef", block_size=128,
                            min_elements=128)
INT4 = CompressionConfig(policy="int4", block_size=128, min_elements=128)
INT4_EF = CompressionConfig(policy="int4_ef", block_size=128,
                            min_elements=128)


def test_compressed_allreduce_matches_psum(mesh8):
    """Two-pass quantized allreduce == psum within the codec's error bound
    (per-rank-distinct buffers, non-block-aligned length)."""
    n = 3000
    g = jax.random.normal(jax.random.PRNGKey(1), (8, n))

    def body(gstack):
        mine = gstack[lax.axis_index("dp")]
        out, _ = compressed_allreduce(mine, "dp", INT8)
        return out

    got = np.asarray(jax.jit(shard_map(
        body, mesh=mesh8, in_specs=P(), out_specs=P(), check_vma=False,
    ))(g))
    want = np.asarray(g).sum(0)
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel < 0.02, rel


def test_compressed_allreduce_small_buffers_ride_psum(mesh8):
    """Below min_elements the value is EXACT — the uncompressed path."""
    g = jnp.ones((64,))

    def body(x):
        out, _ = compressed_allreduce(x, "dp", INT8)
        return out

    got = np.asarray(shard_map(body, mesh=mesh8, in_specs=P(),
                               out_specs=P(), check_vma=False)(g))
    np.testing.assert_array_equal(got, 8.0)


def test_compressed_psum_scatter_matches(mesh8):
    n = 3000
    g = jax.random.normal(jax.random.PRNGKey(2), (8, n))

    def body(gstack):
        mine = gstack[lax.axis_index("dp")]
        shard, _ = compressed_psum_scatter(mine, "dp", INT8,
                                           shard_multiple=128)
        return shard

    shards = np.asarray(jax.jit(shard_map(
        body, mesh=mesh8, in_specs=P(), out_specs=P("dp"), check_vma=False,
    ))(g)).reshape(-1)
    k = shards.size // 8
    assert k % 128 == 0  # block-aligned shards
    want = np.zeros(8 * k, np.float32)
    want[:n] = np.asarray(g).sum(0)
    rel = np.abs(shards - want).max() / np.abs(want).max()
    assert rel < 0.02, rel


def test_int4_compressed_allreduce_matches_psum(mesh8):
    """The 4-bit two-pass allreduce == psum within the ±7-code error bound
    (coarser than int8 — the half-step is absmax/14 per group per pass)."""
    n = 3000
    g = jax.random.normal(jax.random.PRNGKey(11), (8, n))

    def body(gstack):
        mine = gstack[lax.axis_index("dp")]
        out, _ = compressed_allreduce(mine, "dp", INT4)
        return out

    got = np.asarray(jax.jit(shard_map(
        body, mesh=mesh8, in_specs=P(), out_specs=P(), check_vma=False,
    ))(g))
    want = np.asarray(g).sum(0)
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel < 0.25, rel  # ~16x the int8 bound; EF is what closes it


def test_int4_psum_scatter_matches(mesh8):
    n = 3000
    g = jax.random.normal(jax.random.PRNGKey(12), (8, n))

    def body(gstack):
        mine = gstack[lax.axis_index("dp")]
        shard, _ = compressed_psum_scatter(mine, "dp", INT4,
                                           shard_multiple=128)
        return shard

    shards = np.asarray(jax.jit(shard_map(
        body, mesh=mesh8, in_specs=P(), out_specs=P("dp"), check_vma=False,
    ))(g)).reshape(-1)
    k = shards.size // 8
    assert k % 128 == 0
    want = np.zeros(8 * k, np.float32)
    want[:n] = np.asarray(g).sum(0)
    rel = np.abs(shards - want).max() / np.abs(want).max()
    assert rel < 0.25, rel


def test_int4_error_feedback_telescopes(mesh8):
    """The int4_ef residual closes the (much larger) 4-bit one-shot error:
    the running mean of repeated EF-compressed allreduces converges toward
    the true sum the way the int8 telescoping test pins."""
    n = 2048
    g = jax.random.normal(jax.random.PRNGKey(13), (8, n))

    def body(gstack, r):
        mine = gstack[lax.axis_index("dp")]
        out, r2 = compressed_allreduce(mine, "dp", INT4_EF,
                                       residual=r.reshape(-1))
        return out, r2.reshape(r.shape)

    f = jax.jit(shard_map(body, mesh=mesh8, in_specs=(P(), P("dp")),
                          out_specs=(P(), P("dp")), check_vma=False))
    r = jnp.zeros((8, n))
    want = np.asarray(g).sum(0)
    acc = np.zeros(n)
    errs = []
    for i in range(16):
        out, r = f(g, r)
        acc += np.asarray(out)
        errs.append(np.abs(acc / (i + 1) - want).max())
    assert errs[-1] < errs[0] * 0.25, (errs[0], errs[-1])


def test_error_feedback_telescopes(mesh8):
    """Repeated EF-compressed allreduce of constant grads: the running
    mean converges to the true mean (the bias telescopes away); without EF
    it stays at the one-shot quantization error."""
    n = 2048
    g = jax.random.normal(jax.random.PRNGKey(3), (8, n))

    def body(gstack, r):
        mine = gstack[lax.axis_index("dp")]
        out, r2 = compressed_allreduce(mine, "dp", INT8_EF,
                                       residual=r.reshape(-1))
        return out, r2.reshape(r.shape)

    f = jax.jit(shard_map(body, mesh=mesh8, in_specs=(P(), P("dp")),
                          out_specs=(P(), P("dp")), check_vma=False))
    r = jnp.zeros((8, n))
    want = np.asarray(g).sum(0)
    acc = np.zeros(n)
    errs = []
    for i in range(16):
        out, r = f(g, r)
        acc += np.asarray(out)
        errs.append(np.abs(acc / (i + 1) - want).max())
    assert errs[-1] < errs[0] * 0.25, (errs[0], errs[-1])


def test_ddp_compression_options(mesh8):
    """test_ddp_options, compressed edition: every policy/bucketing combo
    must produce the dp mean within the codec tolerance."""
    grads = {"a": jax.random.normal(jax.random.PRNGKey(4), (100, 37)),
             "b": jax.random.normal(jax.random.PRNGKey(5), (51,))}
    stack = jax.tree_util.tree_map(
        lambda g: jnp.stack([g * (i + 1) for i in range(8)]), grads)
    want = jax.tree_util.tree_map(lambda g: np.asarray(g) * 4.5, grads)

    for cfg, kwargs in (
        (INT8, {}),
        (INT8, dict(flat_buckets=False)),
        (INT8, dict(message_size=512)),
        (CompressionConfig(policy="int8", block_size=128, min_elements=128,
                           stochastic_rounding=True), {}),
        (CompressionConfig(policy="none"), {}),
    ):
        ddp = DistributedDataParallel(compression=cfg, **kwargs)

        def body(gs):
            g = jax.tree_util.tree_map(
                lambda x: x[lax.axis_index("dp")], gs)
            seed = jnp.int32(7) if cfg.stochastic_rounding else None
            return ddp.average_gradients(g, seed=seed)

        out = jax.jit(shard_map(body, mesh=mesh8, in_specs=P(),
                                out_specs=P(), check_vma=False))(stack)
        tol = 1e-6 if not cfg.enabled else 0.05
        for k in grads:
            rel = (np.abs(np.asarray(out[k]) - want[k]).max()
                   / np.abs(want[k]).max())
            assert rel < tol, (cfg.policy, kwargs, k, rel)


def test_ddp_ef_requires_and_threads_state(mesh8):
    grads = {"w": jnp.ones((2048,))}
    ddp = DistributedDataParallel(compression=INT8_EF)
    with pytest.raises(ValueError):
        shard_map(lambda g: ddp.average_gradients(g), mesh=mesh8,
                  in_specs=P(), out_specs=P(), check_vma=False)(grads)

    def body(g, r):
        out, r2 = ddp.average_gradients(
            jax.tree_util.tree_map(lambda x: x[0], g),
            comm_state=jax.tree_util.tree_map(lambda x: x[0], r))
        return out, jax.tree_util.tree_map(lambda x: x[None], r2)

    r0 = jax.tree_util.tree_map(
        lambda g: jnp.zeros((8,) + g.shape, jnp.float32), grads)
    out, r1 = jax.jit(shard_map(
        body, mesh=mesh8, in_specs=(P(), P("dp")),
        out_specs=(P(), P("dp")), check_vma=False,
    ))(jax.tree_util.tree_map(lambda g: jnp.stack([g] * 8), grads), r0)
    assert r1["w"].shape == (8, 2048) and r1["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0, atol=0.05)


def test_error_feedback_survives_overflow_step(mesh8):
    """An AMP overflow step (inf grads) must not poison the carried
    residual: the scaler discards that step's gradients, and the next
    step's EF state has to be finite (reviewer find)."""
    n = 2048

    def body(g, r):
        out, r2 = compressed_allreduce(g, "dp", INT8_EF,
                                       residual=r.reshape(-1))
        return out, r2.reshape(r.shape)

    f = jax.jit(shard_map(body, mesh=mesh8, in_specs=(P(), P("dp")),
                          out_specs=(P(), P("dp")), check_vma=False))
    bad = jnp.ones((n,)).at[3].set(jnp.inf)
    out, r = f(bad, jnp.zeros((8, n)))
    assert np.all(np.isfinite(np.asarray(r))), "residual carried non-finite"
    # and a following clean step works off that residual
    out2, r2 = f(jnp.ones((n,)), r)
    assert np.all(np.isfinite(np.asarray(out2)))
    np.testing.assert_allclose(np.asarray(out2), 8.0, atol=0.3)


# ---------------------------------------------------------------------------
# ZeRO (sharded-optimizer) integration

def test_zero_compression_block_aligned_shards_and_threading(mesh8):
    params = {"w": jax.random.normal(jax.random.PRNGKey(6), (13, 7)),
              "b": jax.random.normal(jax.random.PRNGKey(7), (5,))}
    grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
    cfg = CompressionConfig(policy="int8_ef", block_size=64, min_elements=16)
    opt = DistributedFusedAdam(lr=1e-2, compression=cfg)

    def body(p, g):
        state = opt.init(p)
        # shards rounded up to the quantization block: ceil(91/8) -> 64
        assert state.mu["w"].shape == (64,)
        assert state.mu["b"].shape == (64,)
        comm = opt.init_comm_state(p)
        for _ in range(3):
            p, state, comm = opt.step(g, state, p, comm_state=comm)
        return p, jax.tree_util.tree_map(lambda x: x[None], comm)

    got, res = jax.jit(shard_map(
        body, mesh=mesh8,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),) * 2,
        out_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                   jax.tree_util.tree_map(lambda _: P("dp"), params)),
        check_vma=False,
    ))(params, grads)
    # residual rides per-rank, shaped like the grads
    assert res["w"].shape == (8, 13, 7)

    ref_opt = DistributedFusedAdam(lr=1e-2)

    def ref_body(p, g):
        state = ref_opt.init(p)
        for _ in range(3):
            p, state = ref_opt.step(g, state, p)
        return p

    want = jax.jit(shard_map(
        ref_body, mesh=mesh8,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),) * 2,
        out_specs=jax.tree_util.tree_map(lambda _: P(), params),
        check_vma=False,
    ))(params, grads)
    for k in params:
        # drift bounded by the 3 Adam steps' magnitude (per-element sign
        # flips from codes rounding to zero are the worst case)
        d = np.abs(np.asarray(got[k]) - np.asarray(want[k])).max()
        assert d <= 3 * 1e-2 + 1e-6, (k, d)


def test_zero_int4_compression_block_aligned_and_bounded(mesh8):
    """ZeRO reduce-scatter on the int4_ef wire: shards stay aligned to
    the (even) group size, the residual threads per-rank, and 3 Adam
    steps stay within the step-magnitude drift bound (wider than int8's
    — the codes are 16x coarser, EF compensates across steps)."""
    params = {"w": jax.random.normal(jax.random.PRNGKey(16), (13, 7)),
              "b": jax.random.normal(jax.random.PRNGKey(17), (5,))}
    grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
    cfg = CompressionConfig(policy="int4_ef", block_size=64,
                            min_elements=16)
    opt = DistributedFusedAdam(lr=1e-2, compression=cfg)

    def body(p, g):
        state = opt.init(p)
        assert state.mu["w"].shape == (64,)  # group-aligned shards
        comm = opt.init_comm_state(p)
        for _ in range(3):
            p, state, comm = opt.step(g, state, p, comm_state=comm)
        return p

    got = jax.jit(shard_map(
        body, mesh=mesh8,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),) * 2,
        out_specs=jax.tree_util.tree_map(lambda _: P(), params),
        check_vma=False,
    ))(params, grads)

    ref_opt = DistributedFusedAdam(lr=1e-2)

    def ref_body(p, g):
        state = ref_opt.init(p)
        for _ in range(3):
            p, state = ref_opt.step(g, state, p)
        return p

    want = jax.jit(shard_map(
        ref_body, mesh=mesh8,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),) * 2,
        out_specs=jax.tree_util.tree_map(lambda _: P(), params),
        check_vma=False,
    ))(params, grads)
    for k in params:
        d = np.abs(np.asarray(got[k]) - np.asarray(want[k])).max()
        assert d <= 3 * 1e-2 + 1e-6, (k, d)


def test_zero_compression_tuple_container_grads(mesh8):
    """Tuple CONTAINER nodes in the grads pytree must not be mistaken for
    internal (shard, residual) pairs (reviewer find on the tree plumbing)."""
    params = (jnp.ones((13, 7)), {"b": jnp.ones((5,))})
    grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
    cfg = CompressionConfig(policy="int8_ef", block_size=64, min_elements=16)
    opt = DistributedFusedAdam(lr=1e-2, compression=cfg)

    def body(p, g):
        state = opt.init(p)
        comm = opt.init_comm_state(p)
        p, state, comm = opt.step(g, state, p, comm_state=comm)
        return p

    specs = jax.tree_util.tree_map(lambda _: P(), params)
    got = jax.jit(shard_map(
        body, mesh=mesh8, in_specs=(specs,) * 2, out_specs=specs,
        check_vma=False))(params, grads)
    assert got[0].shape == (13, 7) and got[1]["b"].shape == (5,)
    assert np.all(np.isfinite(np.asarray(got[0])))


def test_zero_compression_policy_none_matches_uncompressed(mesh8):
    """policy='none' through the compression plumbing is bit-identical to
    the plain path (same collectives, same shard sizes)."""
    params = {"w": jax.random.normal(jax.random.PRNGKey(8), (13, 7))}
    grads = {"w": params["w"] * 0.1}

    def run(opt):
        def body(p, g):
            state = opt.init(p)
            p, state = opt.step(g, state, p)
            return p

        return jax.jit(shard_map(
            body, mesh=mesh8,
            in_specs=({"w": P()},) * 2, out_specs={"w": P()},
            check_vma=False))(params, grads)

    a = run(DistributedFusedAdam(lr=1e-2))
    b = run(DistributedFusedAdam(
        lr=1e-2, compression=CompressionConfig(policy="none")))
    np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))


# ---------------------------------------------------------------------------
# the acceptance gate: GPT DP training parity

def _gpt_losses(compression, steps=12, lr=2e-3):
    """Train the tiny GPT fixture data-parallel (FusedAdam) for ``steps``;
    return the per-step loss curve. The EF leg round-trips the residual
    through state_dict mid-run (exactness checked by the caller via the
    curve: a lossy round-trip would fork it)."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import (
        GPTConfig, gpt_loss, init_gpt_params,
    )

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    cfg = GPTConfig(vocab_size=128, max_seq=32, hidden=64, num_layers=2,
                    num_heads=2, dtype=jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0, 128)
    opt = FusedAdam(lr=lr)
    opt_state = opt.init(params)

    ddp = DistributedDataParallel(compression=compression)
    specs = jax.tree_util.tree_map(lambda _: P(), params)
    ospecs = jax.tree_util.tree_map(lambda _: P(), opt_state)
    ef_state = ddp.init_comm_state(params)

    def grad_and_loss(p, t):
        def loss(p):
            return gpt_loss(p, t, t, cfg)

        l, g = jax.value_and_grad(loss)(ddp.replicate(p))
        return lax.pmean(l, "dp"), g

    def apply(p, s, g):
        updates, s = opt.update(g, s, p)
        return jax.tree_util.tree_map(lambda p, u: p + u, p, updates), s

    if ef_state is None:
        def body(p, s, t):
            l, g = grad_and_loss(p, t)
            g = ddp.average_gradients(g)
            p, s = apply(p, s, g)
            return p, s, l

        step = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(specs, ospecs, P("dp")),
            out_specs=(specs, ospecs, P()), check_vma=False))
        losses = []
        for _ in range(steps):
            params, opt_state, l = step(params, opt_state, tok)
            losses.append(float(l))
        return losses

    def body(p, s, r, t):
        r = jax.tree_util.tree_map(lambda x: x[0], r)
        l, g = grad_and_loss(p, t)
        g, r = ddp.average_gradients(g, comm_state=r)
        p, s = apply(p, s, g)
        return p, s, jax.tree_util.tree_map(lambda x: x[None], r), l

    rspecs = jax.tree_util.tree_map(lambda _: P("dp"), params)
    step = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, ospecs, rspecs, P("dp")),
        out_specs=(specs, ospecs, rspecs, P()), check_vma=False))
    residual = jax.tree_util.tree_map(
        lambda p: jnp.zeros((8,) + jnp.shape(p), jnp.float32), params)
    losses = []
    for i in range(steps):
        params, opt_state, residual, l = step(params, opt_state, residual,
                                              tok)
        losses.append(float(l))
        if i == steps // 2:
            # the satellite contract: the residual survives a checkpoint
            # round-trip exactly — the continued curve cannot drift
            residual = ef.load_state_dict(
                jax.tree_util.tree_map(jnp.zeros_like, residual),
                ef.state_dict(residual))
    return losses


def test_int8_ef_training_tracks_fp32():
    base = _gpt_losses(None)
    efc = _gpt_losses(INT8_EF)
    raw = _gpt_losses(INT8)
    # training must actually progress (measured: ~1.56 over 12 steps)
    assert base[-1] < base[0] - 0.5, base
    # int8+EF: within tolerance of the uncompressed curve at every step
    # (measured max per-step divergence ~2e-4; 0.02 is 100x margin)
    np.testing.assert_allclose(efc, base, atol=0.02)
    # plain int8 also tracks at this horizon (EF matters over long runs)
    np.testing.assert_allclose(raw, base, atol=0.05)


def test_int4_ef_training_tracks_fp32():
    """The sub-8-bit acceptance gate (the PR-1 int8 gate one tier down):
    GPT trained on the 4-bit EF wire tracks the fp32 loss curve — the
    codes are 16x coarser, so the pinned tolerance is wider than int8's
    but the telescoping residual keeps the curve on track (the mid-run
    state_dict round-trip rides inside _gpt_losses exactly as for int8).
    Measured max per-step divergence at pin time: ~4e-3 with EF,
    ~1.5e-2 raw."""
    base = _gpt_losses(None)
    efc = _gpt_losses(INT4_EF)
    assert base[-1] < base[0] - 0.5, base
    np.testing.assert_allclose(efc, base, atol=0.05)
    # the no-EF 4-bit wire drifts visibly more — EF is load-bearing at
    # this tier (bounded, not matched: just sanity that training works)
    raw = _gpt_losses(INT4)
    assert raw[-1] < raw[0] - 0.4, raw

"""Encoder-decoder (T5-style) pipeline schedule tests on the virtual mesh.

Ref: ``ModelType.encoder_and_decoder`` plumbing —
``apex/transformer/pipeline_parallel/schedules/common.py:72-96`` (enc/dec
stage build at the split rank) and ``parallel_state.py:251-286`` (split-rank
predicates). The check here is the strongest available: the pipelined
enc-dec loss AND grads must equal the sequential single-device computation,
including the cross-attention gradient path from decoder stages back through
the encoder ring (the reference's "double grad" backward_step traffic).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.schedules import (
    EncDecPipelineSpec,
    build_model,
    forward_backward_pipelining_enc_dec,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
)

HID = 8
B = 16  # per-microbatch batch must stay divisible by dp (= 8/pp here)
SEQ_ENC = 6  # different enc/dec lengths exercise the two-stream plumbing
SEQ_DEC = 4


def _spec():
    def enc_embed_fn(ep, x):
        return x @ ep["we"]

    def enc_stage_fn(sp, h):
        return jnp.tanh(h @ sp["w"] + sp["b"])

    def dec_embed_fn(ep, x):
        return x @ ep["wd"]

    def dec_stage_fn(sp, h, mem):
        # self-mix + single-head cross-attention over the encoder memory:
        # grads must flow through BOTH operands (ref backward_step's
        # double-cotangent path).
        att = jax.nn.softmax(
            (h @ sp["wq"]) @ (mem @ sp["wk"]).transpose(0, 2, 1)
            / jnp.sqrt(jnp.float32(HID)),
            axis=-1,
        )
        return jnp.tanh(h @ sp["w"] + att @ (mem @ sp["wv"]) + sp["b"])

    def loss_fn(hp, h, tgt):
        pred = h @ hp["w"]
        return jnp.mean((pred - tgt) ** 2)

    return EncDecPipelineSpec(
        enc_embed_fn, enc_stage_fn, dec_embed_fn, dec_stage_fn, loss_fn
    )


def _params(rng, pp):
    k1, k2, k3, k4 = jax.random.split(rng, 4)

    def enc_init(key, c):
        kw, kb = jax.random.split(key)
        return {
            "w": jax.random.normal(kw, (HID, HID)) * 0.3,
            "b": jax.random.normal(kb, (HID,)) * 0.1,
        }

    def dec_init(key, c):
        ks = jax.random.split(key, 5)
        return {
            "w": jax.random.normal(ks[0], (HID, HID)) * 0.3,
            "b": jax.random.normal(ks[1], (HID,)) * 0.1,
            "wq": jax.random.normal(ks[2], (HID, HID)) * 0.3,
            "wk": jax.random.normal(ks[3], (HID, HID)) * 0.3,
            "wv": jax.random.normal(ks[4], (HID, HID)) * 0.3,
        }

    return {
        "embed": {
            "we": jax.random.normal(k1, (HID, HID)) * 0.3,
            "wd": jax.random.normal(k2, (HID, HID)) * 0.3,
        },
        "enc_stages": build_model(enc_init, k3, pp),
        "dec_stages": build_model(dec_init, k4, pp),
        "head": {"w": jax.random.normal(k3, (HID, HID)) * 0.3},
    }


def _batch(rng, b=B):
    ke, kd, kt = jax.random.split(rng, 3)
    return (
        jax.random.normal(ke, (b, SEQ_ENC, HID)),
        jax.random.normal(kd, (b, SEQ_DEC, HID)),
        jax.random.normal(kt, (b, SEQ_DEC, HID)),
    )


def _sequential_reference(spec, params, batch, num_microbatches, pp):
    enc_inputs, dec_inputs, targets = batch

    def loss_of(p):
        def one_mb(xe, xd, t):
            h = spec.enc_embed_fn(p["embed"], xe)
            for s in range(pp):
                h = spec.enc_stage_fn(jax.tree.map(lambda a: a[s], p["enc_stages"]), h)
            mem = h
            h = spec.dec_embed_fn(p["embed"], xd)
            for s in range(pp):
                h = spec.dec_stage_fn(
                    jax.tree.map(lambda a: a[s], p["dec_stages"]), h, mem
                )
            return spec.loss_fn(p["head"], h, t)

        M = num_microbatches
        nb = enc_inputs.shape[0]
        split = lambda x: x.reshape((M, nb // M) + x.shape[1:])  # noqa: E731
        return jnp.mean(
            jax.vmap(one_mb)(split(enc_inputs), split(dec_inputs), split(targets))
        )

    return jax.jit(jax.value_and_grad(loss_of))(params)


@pytest.mark.parametrize("pp,M", [
    pytest.param(2, 4, marks=pytest.mark.slow),
    (4, 4),
    pytest.param(4, 8, marks=pytest.mark.slow),
])
def test_enc_dec_pipeline_matches_sequential(pp, M):
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        pipeline_model_parallel_split_rank_=max(pp // 2, 1),
    )
    spec = _spec()
    params = _params(jax.random.PRNGKey(0), pp)
    batch = _batch(jax.random.PRNGKey(1))

    loss, grads = jax.jit(lambda p: forward_backward_pipelining_enc_dec(
        spec, p, batch, num_microbatches=M, mesh=mesh))(params)
    ref_loss, ref_grads = _sequential_reference(spec, params, batch, M, pp)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        grads,
        ref_grads,
    )


def test_enc_dec_dispatch_through_uniform_driver():
    """The reference serves enc-dec through the same driver name
    (``forward_backward_pipelining_without_interleaving`` +
    ``model_type=encoder_and_decoder``); the spec type routes here."""
    mesh = parallel_state.initialize_model_parallel(pipeline_model_parallel_size_=2)
    spec = _spec()
    params = _params(jax.random.PRNGKey(0), 2)
    batch = _batch(jax.random.PRNGKey(1))
    loss, _ = jax.jit(
        lambda p: forward_backward_pipelining_without_interleaving(
            spec, p, batch, num_microbatches=4, mesh=mesh))(params)
    ref_loss, _ = _sequential_reference(spec, params, batch, 4, 2)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


def test_interleaved_rejects_enc_dec():
    mesh = parallel_state.initialize_model_parallel(pipeline_model_parallel_size_=2)
    spec = _spec()
    params = _params(jax.random.PRNGKey(0), 2)
    batch = _batch(jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="encoder-decoder"):
        forward_backward_pipelining_with_interleaving(
            spec, params, batch, num_microbatches=4, virtual_pipeline_size=2,
            mesh=mesh,
        )


@pytest.mark.slow
def test_loss_scale_scales_grads_only():
    mesh = parallel_state.initialize_model_parallel(pipeline_model_parallel_size_=2)
    spec = _spec()
    params = _params(jax.random.PRNGKey(0), 2)
    batch = _batch(jax.random.PRNGKey(1))
    loss1, g1 = jax.jit(lambda p: forward_backward_pipelining_enc_dec(
        spec, p, batch, num_microbatches=4, mesh=mesh))(params)
    loss2, g2 = jax.jit(
        lambda p, s: forward_backward_pipelining_enc_dec(
            spec, p, batch, num_microbatches=4, mesh=mesh, loss_scale=s))(
        params, jnp.float32(64.0))
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a) * 64.0, np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        g1,
        g2,
    )


def test_split_rank_bookkeeping():
    """Host-level split-rank accessors (ref parallel_state.py:345-354) and
    validation."""
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4, pipeline_model_parallel_split_rank_=2
    )
    assert parallel_state.get_pipeline_model_parallel_split_rank() == 2
    parallel_state.set_pipeline_model_parallel_split_rank(3)
    assert parallel_state.get_pipeline_model_parallel_split_rank() == 3
    with pytest.raises(ValueError):
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=2, pipeline_model_parallel_split_rank_=5
        )


def test_split_predicates_inside_mesh_program():
    """Traced before/after/at-split predicates follow the reference's
    semantics (ref parallel_state.py:251-286) per pipeline rank."""
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4, pipeline_model_parallel_split_rank_=2
    )

    def body(x):
        before = parallel_state.is_pipeline_stage_before_split()
        after = parallel_state.is_pipeline_stage_after_split()
        at = parallel_state.is_pipeline_stage_at_split()
        code = (
            before.astype(jnp.int32)
            + 10 * after.astype(jnp.int32)
            + 100 * at.astype(jnp.int32)
        )
        return x + code

    f = shard_map(
        body, mesh=mesh, in_specs=P("pp", ("dp", "sp", "tp")),
        out_specs=P("pp", ("dp", "sp", "tp")),
    )
    out = np.asarray(f(jnp.zeros((4, 2), jnp.int32)))
    # ranks 0..3 with split 2: before={0,1}, after={2,3}, at={1}
    assert out[:, 0].tolist() == [1, 101, 10, 10]


def test_split_predicates_default_true():
    parallel_state.initialize_model_parallel(pipeline_model_parallel_size_=2)
    assert parallel_state.is_pipeline_stage_before_split() is True
    assert parallel_state.is_pipeline_stage_after_split() is True
    # no split rank -> no boundary stage; host-level False (usable outside
    # mesh programs, unlike a traced-rank read)
    assert parallel_state.is_pipeline_stage_at_split() is False


def test_split_rank_equal_to_pp_rejected():
    """split == pp would leave zero decoder stages (review finding)."""
    with pytest.raises(ValueError):
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=2, pipeline_model_parallel_split_rank_=2
        )

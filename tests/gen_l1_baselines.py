"""Regenerate tests/l1_baselines.json (ref tests/L1/common/run_test.sh's
`baselines/` files: per-config stored loss curves the sweep is compared to).

Run: ``PYTHONPATH=. python tests/gen_l1_baselines.py`` after an intentional
numerics change, and commit the diff. The environment is pinned to the SAME
8-device virtual CPU platform the test conftest forces — baselines depend on
the dp degree (DDP averaging, SyncBN statistics).
"""

import importlib.util
import json
import os
import pathlib
import sys


def _pin_platform():
    """Force the 8-device virtual CPU platform (same recipe as conftest.py).
    Called from ``main()`` only — importing this module for its constants
    (test_l1_determinism does) must not mutate the environment."""
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform(virtual_devices=8)


_ROOT = pathlib.Path(__file__).resolve().parent.parent

# {opt_level x sync_bn x loss_scale} cross-product on a small arch (compile
# cost), plus one flagship ResNet-50 config (ref runs ResNet-50 throughout).
CROSS_PRODUCT = [
    ("resnet18", "O0", False, None),
    ("resnet18", "O1", False, None),
    ("resnet18", "O1", False, "128.0"),
    ("resnet18", "O2", False, None),
    ("resnet18", "O2", True, None),
    ("resnet18", "O2", False, "128.0"),
    ("resnet18", "O3", False, None),
    ("resnet18", "O3", True, "128.0"),
    ("resnet50", "O2", True, "128.0"),
]

# batch 32 over the dp=8 mesh = per-device batch 4. Smaller per-device
# batches degrade the harness: at 1, BatchNorm over the (1, 1, 1, C)
# last-stage features degenerates to its bias and erases all conv numerics
# (O0 == O1 bit-exactly); at 2, the near-singular variance estimates amplify
# bf16 rounding into chaotic trajectories that no tolerance can pin.
BASE = ["--iters", "3", "--batch-size", "32", "--image-size", "32",
        "--num-classes", "10", "--deterministic", "--lr", "0.0001"]


def config_key(arch, opt_level, sync_bn, loss_scale):
    return f"{arch}_{opt_level}_{sync_bn}_{loss_scale}"


def config_argv(arch, opt_level, sync_bn, loss_scale):
    argv = ["--arch", arch, "--opt-level", opt_level] + BASE
    if sync_bn:
        argv.append("--sync_bn")
    if loss_scale is not None:
        argv += ["--loss-scale", loss_scale]
    return argv


_TRAINER_CACHE = None


def load_trainer():
    """Import the example trainer ONCE per process: every fresh
    exec_module would discard the module's jit caches, forcing each test
    that shares a config (e.g. the determinism double-run and the
    O0-vs-O2 comparison) to recompile the whole ResNet train step."""
    global _TRAINER_CACHE
    if _TRAINER_CACHE is None:
        spec = importlib.util.spec_from_file_location(
            "imagenet_main_amp",
            _ROOT / "examples" / "imagenet" / "main_amp.py")
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        _TRAINER_CACHE = m
    return _TRAINER_CACHE


def main():
    _pin_platform()
    m = load_trainer()
    out = {}
    for cfg in CROSS_PRODUCT:
        losses = m.train(m.parse_args(config_argv(*cfg)))
        out[config_key(*cfg)] = [float(x) for x in losses]
        print(config_key(*cfg), out[config_key(*cfg)], flush=True)
    path = _ROOT / "tests" / "l1_baselines.json"
    path.write_text(json.dumps(out, indent=1) + "\n")
    print("wrote", path)


if __name__ == "__main__":
    sys.exit(main())

"""apex_tpu.analyze — compiled-program contract checker + repo graph-lint.

Every program analyzer is pinned BOTH ways: a deliberately-broken fixture
(a copied "donated" buffer, a shape-recompiling step, an fp32 dot under a
bf16 policy, a synthetic exposed all-gather, a ``float(tracer)`` sync)
must be caught, and a clean program must pass. The flagship acceptance
rows run the donation checker and the recompile sentinel on the REAL
paths — the GPT train step and the serve chunk-prefill/decode programs —
all stock-jax-safe. Tier B: the repo lint must exit 0 against the
checked-in baseline and exit 1 the moment a new violation is introduced
(round-tripped through a tmp baseline).
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import analyze
from apex_tpu.analyze import hlo as hlo_mod
from apex_tpu.analyze import lint
from apex_tpu.analyze.collectives import overlap_assertion
from apex_tpu.comm import accounting

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# analyze.hlo — the shared normalization/parse entry point


def test_as_text_normalizes_str_and_compiled():
    assert hlo_mod.as_text("HloModule x") == "HloModule x"
    compiled = jax.jit(lambda x: x * 2).lower(jnp.ones(3)).compile()
    text = hlo_mod.as_text(compiled)
    assert "HloModule" in text
    with pytest.raises(TypeError):
        hlo_mod.as_text(42)


def test_parse_computations_walks_bare_snippets():
    snippet = (
        "  %a = f32[4] parameter(0)\n"
        "  %b = f32[4] multiply(f32[4] %a, f32[4] %a)\n")
    comps = hlo_mod.parse_computations(snippet)
    assert [op for _, op, _ in comps[""]] == ["parameter", "multiply"]


def test_accounting_imports_the_shared_parser():
    """Satellite: ONE HLO normalization/walker — accounting's parser IS
    analyze.hlo's (identity, not a copy), and collective_report accepts
    both text and compiled objects through the same as_text."""
    assert accounting._parse_computations is hlo_mod.parse_computations
    compiled = jax.jit(lambda x: x + 1).lower(jnp.ones(3)).compile()
    rep_obj = accounting.collective_report(compiled)
    rep_txt = accounting.collective_report(compiled.as_text())
    assert rep_obj.counts == rep_txt.counts


def test_input_output_alias_header_parse():
    header = ("HloModule jit_step, is_scheduled=true, input_output_alias="
              "{ {0}: (0, {}, may-alias), {1}: (2, {}, must-alias) }, "
              "entry_computation_layout={(f32[4])->f32[4]}\n")
    aliases = hlo_mod.input_output_aliases(header)
    assert [(p, k) for _, p, _, k in aliases] == \
        [(0, "may-alias"), (2, "must-alias")]
    assert hlo_mod.input_output_aliases("HloModule bare\n") == []


# ---------------------------------------------------------------------------
# donation checker


def test_donation_clean_step_aliased():
    def step(p, x):
        return p + x, (p * x).sum()

    rep = analyze.assert_donated(step, jnp.ones((4, 4)), jnp.ones((4, 4)),
                                 donate_argnums=(0,))
    assert rep.ok and rep.n_aliased == 1 and rep.expected_leaves == 1
    assert rep.as_record()["donation_ok"] is True


def test_donation_copied_buffer_flagged():
    """THE seeded defect: the donated buffer's only same-shaped output has
    a different dtype, so XLA silently copies instead of aliasing."""
    def bad(p, x):
        return (p + x).astype(jnp.bfloat16), (p * x).sum()

    rep = analyze.check_donation(bad, jnp.ones((4, 4)), jnp.ones((4, 4)),
                                 donate_argnums=(0,))
    assert not rep.ok and rep.n_aliased == 0
    with pytest.raises(analyze.DonationError):
        analyze.assert_donated(bad, jnp.ones((4, 4)), jnp.ones((4, 4)),
                               donate_argnums=(0,))


def test_donation_pytree_counts_all_leaves():
    def step(state, x):
        return {"w": state["w"] + x, "b": state["b"] * 2.0}, x.sum()

    state = {"w": jnp.ones((3, 3)), "b": jnp.ones((3,))}
    rep = analyze.assert_donated(step, state, jnp.ones((3, 3)),
                                 donate_argnums=(0,))
    assert rep.expected_leaves == 2 and rep.n_aliased >= 2


# ---------------------------------------------------------------------------
# recompile sentinel


def test_recompile_guard_steady_state():
    step = jax.jit(lambda x: x + 1)
    with analyze.recompile_guard(step) as g:   # warmup contract
        for _ in range(5):
            step(jnp.ones(4))
    if g.supported:
        assert g.growth() == {"<lambda>": 1}


def test_recompile_guard_catches_shape_recompiling_step():
    """THE seeded defect: a step re-jitted per input shape."""
    step = jax.jit(lambda x: x * 2)
    step(jnp.ones(4))  # warm
    guard = analyze.recompile_guard({"step": step}, budget=0)
    with pytest.raises(analyze.RecompileError, match="step: \\+2"):
        with guard:
            step(jnp.ones(5))
            step(jnp.ones(6))


def test_recompile_guard_budget_allows_declared_compiles():
    step = jax.jit(lambda x: x - 1)
    with analyze.recompile_guard({"step": step}, budget=2):
        step(jnp.ones(3))
        step(jnp.ones(8))   # 2 compiles, budget 2: fine


def test_recompile_guard_disambiguates_name_collisions():
    """Two bare callables sharing __name__ (every step is named 'step')
    must BOTH be guarded, not silently collapsed to one."""
    a, b = jax.jit(lambda x: x + 1), jax.jit(lambda x: x * 2)
    with analyze.recompile_guard(a, b) as g:
        a(jnp.ones(2))
        b(jnp.ones(2))
    assert len(g.programs) == 2
    if g.supported:
        assert sorted(g.growth().values()) == [1, 1]


def test_jit_cache_size_shapes():
    assert analyze.jit_cache_size(None) == 0
    assert analyze.jit_cache_size(lambda x: x) is None  # not jitted
    f = jax.jit(lambda x: x)
    f(jnp.ones(2))
    n = analyze.jit_cache_size(f)
    assert n is None or n == 1
    counts = analyze.compile_counts({"f": f, "g": None})
    assert counts["g"] == 0


# ---------------------------------------------------------------------------
# dtype-leak detector


_W_BF16 = jnp.ones((4, 4), jnp.bfloat16)
_X_BF16 = jnp.ones((2, 4), jnp.bfloat16)


def test_dtype_leak_fp32_dot_under_bf16_policy():
    """THE seeded defect: a dot promoted to f32 under a bf16 policy."""
    def leaky(x, w):
        return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))

    rep = analyze.dtype_leak_report(leaky, _X_BF16, _W_BF16,
                                    policy=jnp.bfloat16)
    assert rep.fp32_dots == 1 and not rep.ok
    with pytest.raises(analyze.DtypeLeakError, match="fp32 dot"):
        analyze.assert_no_dtype_leaks(leaky, _X_BF16, _W_BF16,
                                      policy=jnp.bfloat16)


def test_dtype_leak_clean_bf16_dot():
    rep = analyze.assert_no_dtype_leaks(jnp.dot, _X_BF16, _W_BF16,
                                        policy=jnp.bfloat16)
    assert rep.ok and rep.total_dots == 1 and rep.fp32_dots == 0


def test_dtype_leak_convert_churn_roundtrip():
    def churny(x, w):
        h = x.astype(jnp.float32).astype(jnp.bfloat16)  # f32 round trip
        return jnp.dot(h, w)

    rep = analyze.dtype_leak_report(churny, _X_BF16, _W_BF16,
                                    policy=jnp.bfloat16)
    assert rep.convert_churn_ops == 1 and rep.fp32_dots == 0
    with pytest.raises(analyze.DtypeLeakError, match="round-trip"):
        analyze.assert_no_dtype_leaks(churny, _X_BF16, _W_BF16,
                                      policy=jnp.bfloat16)
    # a single direction-changing cast is NOT churn
    def single(x, w):
        return jnp.dot(x.astype(jnp.float32).astype(jnp.bfloat16)
                       if False else x, w)
    assert analyze.dtype_leak_report(
        single, _X_BF16, _W_BF16, policy=jnp.bfloat16).convert_churn_ops == 0


def test_dtype_leak_f32_accumulate_is_not_a_leak():
    """bf16 operands accumulating into f32 (preferred_element_type — the
    TPU-native pattern) must NOT flag; only fp32 OPERANDS (the fp32 MXU
    path) are the leak. An explicit allowance admits deliberate fp32
    sites (attention-stability math)."""
    def accum(x, w):
        return jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    rep = analyze.assert_no_dtype_leaks(accum, _X_BF16, _W_BF16,
                                        policy=jnp.bfloat16)
    assert rep.fp32_dots == 0 and rep.fp32_accum_dots == 1

    def leaky(x, w):
        return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))
    rep2 = analyze.assert_no_dtype_leaks(leaky, _X_BF16, _W_BF16,
                                         policy=jnp.bfloat16,
                                         allow_fp32_dots=1)
    assert rep2.fp32_dots == 1  # admitted by the declared allowance


def test_dtype_leak_walks_scan_bodies():
    def scanned(x, w):
        def body(h, _):
            h = jnp.dot(h.astype(jnp.float32), w.astype(jnp.float32))
            return h.astype(jnp.bfloat16), ()
        h, _ = jax.lax.scan(body, x, None, length=3)
        return h

    rep = analyze.dtype_leak_report(scanned, _X_BF16, _W_BF16,
                                    policy=jnp.bfloat16)
    assert rep.fp32_dots == 1  # found inside the scan body


def test_policy_resolution_rules():
    from apex_tpu import amp
    from apex_tpu.transformer.testing import GPTConfig

    assert analyze.resolve_policy_dtype(jnp.bfloat16) == jnp.bfloat16
    assert analyze.resolve_policy_dtype(
        amp.get_policy("O2")) == jnp.bfloat16
    assert analyze.resolve_policy_dtype(amp.get_policy("O0")) is None
    cfg = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                    num_heads=4, dtype=jnp.bfloat16)
    assert analyze.resolve_policy_dtype(cfg) == jnp.bfloat16

    # O0 (no declared low precision): fp32 dots are NOT leaks
    def fp32_dot(x, w):
        return jnp.dot(x, w)
    rep = analyze.dtype_leak_report(
        fp32_dot, jnp.ones((2, 4)), jnp.ones((4, 4)),
        policy=amp.get_policy("O0"))
    assert rep.ok and rep.fp32_dots == 0


def test_fsdp_policy_dtype_declaration():
    """The fsdp wiring: FSDP.policy_dtype declares the widest
    low-precision FLOAT leaf dtype — int8 codebooks/bool masks never
    masquerade as the compute dtype (that would disarm the leak gate)."""
    from apex_tpu.fsdp.core import FSDP, LeafMeta

    f = FSDP()
    meta = {"w": LeafMeta((4, 4), "bfloat16"),
            "codes": LeafMeta((4,), "int8"),
            "b": LeafMeta((4,), "float32")}
    assert f.policy_dtype(meta) == jnp.dtype(jnp.bfloat16)
    assert f.policy_dtype({"w": LeafMeta((2,), "float32")}) == \
        jnp.dtype(jnp.float32)
    assert f.policy_dtype({"codes": LeafMeta((4,), "int8")}) is None
    assert analyze.resolve_policy_dtype(
        f.policy_dtype(meta)) == jnp.dtype(jnp.bfloat16)


# ---------------------------------------------------------------------------
# exposed-collective checker

_EXPOSED_AG = """\
HloModule synthetic, is_scheduled=true

ENTRY %main (p0: f32[1024]) -> f32[4096] {
  %p0 = f32[1024] parameter(0)
  %ag = f32[4096] all-gather(f32[1024] %p0), replica_groups={{0,1,2,3}}, dimensions={0}
  ROOT %r = f32[4096] add(f32[4096] %ag, f32[4096] %ag)
}
"""

_HIDDEN_AG = """\
HloModule synthetic, is_scheduled=true

ENTRY %main (p0: f32[1024], a: f32[8,8], b: f32[8,8]) -> f32[4096] {
  %p0 = f32[1024] parameter(0)
  %a = f32[8,8] parameter(1)
  %b = f32[8,8] parameter(2)
  %ag = f32[4096] all-gather(f32[1024] %p0), replica_groups={{0,1,2,3}}, dimensions={0}
  %d = f32[8,8] dot(f32[8,8] %a, f32[8,8] %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %r = f32[4096] add(f32[4096] %ag, f32[4096] %ag)
}
"""


def test_exposed_synthetic_all_gather_caught():
    """THE seeded defect: an all-gather with nothing to hide behind."""
    rep = analyze.exposed_report(_EXPOSED_AG)
    # f32[4096] result = 16384B, ring model: b*(W-1)/W over W=4
    assert rep.exposed_wire_bytes == pytest.approx(12288.0)
    assert rep.hidden_wire_bytes == 0.0 and rep.collectives == 1
    with pytest.raises(analyze.ExposedCollectiveError, match="all-gather"):
        analyze.assert_no_exposed(_EXPOSED_AG)
    # ... but an explicit budget admits it
    rep2 = analyze.assert_no_exposed(_EXPOSED_AG, budget_bytes=16384)
    assert rep2.as_record()["exposed_bytes"] == 12288


def test_exposed_hidden_behind_independent_dot():
    """Clean program: a def-use-independent dot in the same computation —
    a latency-hiding scheduler can overlap the gather."""
    rep = analyze.assert_no_exposed(_HIDDEN_AG)
    assert rep.hidden == 1 and rep.exposed_wire_bytes == 0.0
    assert rep.hidden_fraction == 1.0


def test_exposed_report_on_collective_free_program():
    compiled = jax.jit(lambda x: x * 2 + 1).lower(jnp.ones(8)).compile()
    rep = analyze.assert_no_exposed(compiled)
    assert rep.collectives == 0 and rep.hidden_fraction == 1.0


def test_overlap_assertion_floor():
    with pytest.raises(analyze.ExposedCollectiveError, match="under-hidden"):
        overlap_assertion(
            "  %cp = f32[64] collective-permute(f32[64] %x), "
            "source_target_pairs={{0,1}}\n", min_hidden_fraction=0.5)


# ---------------------------------------------------------------------------
# host-sync detector


def test_host_sync_float_tracer_caught():
    """THE seeded defect: float() on a traced value inside the step."""
    def step(x):
        return float(jnp.sum(x))

    rep = analyze.host_sync_report(step, jnp.ones(3))
    assert rep.implicit_syncs == 1 and rep.host_syncs == 1
    assert "float" in (rep.implicit_kind or "") \
        or "concretization" in (rep.implicit_kind or "")
    with pytest.raises(analyze.HostSyncError, match="implicit sync"):
        analyze.assert_no_host_sync(step, jnp.ones(3))


def test_host_sync_explicit_apis_counted():
    def step(x):
        jax.device_get(x)
        y = jax.block_until_ready(x * 2)
        return y + 1

    rep = analyze.host_sync_report(step, jnp.ones(3))
    assert rep.device_gets == 1 and rep.block_until_readys == 1
    assert rep.host_syncs == 2 and not rep.ok
    assert rep.as_record()["host_syncs"] == 2


def test_host_sync_clean_step():
    def step(p, x):
        g = jax.grad(lambda p: jnp.sum((x @ p) ** 2))(p)
        return p - 0.1 * g

    rep = analyze.assert_no_host_sync(step, jnp.ones((4, 2)),
                                      jnp.ones((3, 4)))
    assert rep.ok and rep.host_syncs == 0


def test_host_sync_method_form_block_until_ready_caught():
    """The METHOD form (`y.block_until_ready()`) syncs through an
    attribute tracers don't have — counted as a sync, not an analyzer
    crash; unrelated AttributeErrors still surface as bugs."""
    def step(x):
        return (x * 2).block_until_ready()

    rep = analyze.host_sync_report(step, jnp.ones(3))
    assert rep.implicit_syncs == 1
    assert rep.implicit_kind == "sync method on tracer"

    def buggy(x):
        return x.no_such_attribute_anywhere()
    with pytest.raises(AttributeError):
        analyze.host_sync_report(buggy, jnp.ones(3))


def test_host_sync_tracer_bool_branch_caught():
    def step(x):
        if jnp.sum(x) > 0:    # data-dependent Python branch
            return x
        return -x

    rep = analyze.host_sync_report(step, jnp.ones(3))
    assert rep.implicit_syncs == 1
    assert rep.implicit_kind == "bool(tracer)"


# ---------------------------------------------------------------------------
# Tier B: repo graph-lint

_BAD_SOURCE = '''\
import functools

import jax
import jax.numpy as jnp


@jax.jit
def branchy(x):
    if jnp.sum(x) > 0:
        return jnp.array(x)
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def train_step(p, n):
    return p


def helper(a, acc=[]):
    try:
        return a
    except Exception:
        return None
'''

_CLEAN_SOURCE = '''\
import functools

import jax
import jax.numpy as jnp


@jax.jit
def branchless(x):
    return jnp.where(jnp.sum(x) > 0, jnp.asarray(x), x)


@functools.partial(jax.jit, donate_argnums=(0,))
def train_step(p, g):
    return p - 0.1 * g


def helper(a, acc=None):
    try:
        return a
    except Exception:  # fixture: deliberately swallowed for the test
        return None
'''


def _lint_src(tmp_path, source, name="mod.py"):
    f = tmp_path / name
    f.write_text(source)
    return lint.lint_file(str(f), root=str(tmp_path))


def test_lint_catches_all_seeded_rules(tmp_path):
    found = {v.rule for v in _lint_src(tmp_path, _BAD_SOURCE)}
    assert found == {"tracer-branch", "jnp-array-on-tracer",
                     "missing-donate", "mutable-default-arg",
                     "bare-except"}


def test_lint_clean_file_passes(tmp_path):
    assert _lint_src(tmp_path, _CLEAN_SOURCE) == []


def test_lint_jit_call_form_missing_donate(tmp_path):
    src = ("import jax\n\n"
           "def decode_step(c, t):\n    return c\n\n"
           "prog = jax.jit(decode_step)\n"
           "good = jax.jit(decode_step, donate_argnums=(0,))\n")
    rules = [v.rule for v in _lint_src(tmp_path, src)]
    assert rules == ["missing-donate"]


def test_lint_comment_justifies_bare_except(tmp_path):
    src = ("def f():\n"
           "    try:\n        return 1\n"
           "    # best-effort: telemetry must never kill the step\n"
           "    except Exception:\n        return None\n")
    assert _lint_src(tmp_path, src) == []


def test_lint_baseline_roundtrip(tmp_path):
    """Acceptance: add violation -> exit 1; bless it -> exit 0; add a NEW
    one -> exit 1 again (multiset: a second copy of a blessed pattern
    still flags)."""
    mod = tmp_path / "mod.py"
    mod.write_text(_BAD_SOURCE)
    base = tmp_path / "baseline.json"
    argv = [str(mod), "--baseline", str(base), "--root", str(tmp_path)]
    assert lint.main(argv) == 1                       # no baseline yet
    assert lint.main(argv + ["--write-baseline"]) == 0
    assert lint.main(argv) == 0                       # blessed
    mod.write_text(_BAD_SOURCE +
                   "\n\ndef another(b, xs=[]):\n    return b\n")
    assert lint.main(argv) == 1                       # new violation fails
    data = json.loads(base.read_text())
    assert data["schema"] == 1 and len(data["violations"]) == 5


def test_lint_baseline_is_line_drift_proof(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(_BAD_SOURCE)
    base = tmp_path / "baseline.json"
    argv = [str(mod), "--baseline", str(base), "--root", str(tmp_path)]
    lint.main(argv + ["--write-baseline"])
    # unrelated edit shifts every line; the baseline still covers
    mod.write_text("# a new header comment\n\n" + _BAD_SOURCE)
    assert lint.main(argv) == 0


def test_repo_lint_gate_green():
    """THE tier-1 wiring: the repo lints clean against the checked-in
    baseline. A new anti-pattern anywhere under apex_tpu/ fails here."""
    rc = lint.main([os.path.join(ROOT, "apex_tpu"),
                    "--baseline",
                    os.path.join(ROOT, "tests", "lint_baseline.json"),
                    "--root", ROOT])
    assert rc == 0


# ---------------------------------------------------------------------------
# regress polarity (satellite: analyzer record fields classified)


def test_regress_polarity_for_analyzer_fields():
    from apex_tpu.monitor.regress import classify_metric

    for key in ("exposed_bytes", "convert_churn_ops", "host_syncs",
                "lint_violations", "fp32_dots", "donated_copied"):
        assert classify_metric(key) == "lower", key
    assert classify_metric("hidden_fraction") == "higher"
    assert classify_metric("hidden_bytes") == "higher"


def test_regress_gates_analyzer_record():
    from apex_tpu.monitor.regress import compare_records

    base = {"exposed_bytes": 0, "host_syncs": 0, "lint_violations": 0,
            "convert_churn_ops": 0}
    rep = compare_records(base, dict(base, host_syncs=2), tol=0.15)
    assert not rep["ok"]
    assert rep["regressions"][0]["key"] == "host_syncs"
    assert compare_records(base, dict(base), tol=0.15)["ok"]


# ---------------------------------------------------------------------------
# flagship acceptance: the REAL paths, tier-1


MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")
needs_mesh = pytest.mark.skipif(
    not MESH_OK,
    reason="mesh programs need jax.shard_map/lax.axis_size (graft jax)")


def test_flagship_gpt_train_step_donation_and_recompile():
    """Acceptance (stock-safe): a GPT train step over the flagship layer
    stack (the serve ``gpt_prefill`` forward, tp-optional — the same
    transformer the mesh ``gpt_loss`` runs) donates its params, the
    compiled executable ALIASES them, and N steps reuse ONE compilation."""
    from apex_tpu.serve.decode import gpt_prefill

    cfg, params, kv, cache = _serve_fixture()
    toks = jnp.zeros((16,), jnp.int32).at[:9].set(
        jnp.arange(1, 10, dtype=jnp.int32))
    block_row = jnp.arange(2, dtype=jnp.int32)

    def train_step(p, toks, target):
        def loss_fn(p):
            _, logits = gpt_prefill(p, toks, jnp.int32(9), cache,
                                    block_row, cfg, kv)
            return -jax.nn.log_softmax(logits)[target]

        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(
            lambda a, b: a - 0.01 * b, p, g), loss

    n_leaves = len(jax.tree_util.tree_leaves(params))
    rep = analyze.assert_donated(train_step, params, toks, jnp.int32(7),
                                 donate_argnums=(0,))
    assert rep.n_aliased >= n_leaves

    step = jax.jit(train_step, donate_argnums=(0,))
    p = jax.tree_util.tree_map(jnp.copy, params)
    with analyze.recompile_guard(step) as g:
        for _ in range(3):
            p, loss = step(p, toks, jnp.int32(7))
    assert np.isfinite(float(loss))
    if g.supported:
        assert g.growth() == {"train_step": 1}


@needs_mesh
def test_flagship_gpt_mesh_loss_step_donation_and_recompile():
    """Acceptance (graft jax): the REAL flagship step — ``gpt_loss``
    under ``shard_map`` — donated params aliased, one compilation."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.mesh import build_mesh
    from apex_tpu.transformer.testing import (
        GPTConfig, gpt_loss, gpt_param_specs, init_gpt_params,
    )

    cfg = GPTConfig(vocab_size=96, max_seq=32, hidden=32, num_layers=2,
                    num_heads=4, dtype=jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    mesh = build_mesh(tp=1, pp=1, sp=1)
    specs = gpt_param_specs(cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 96)

    def body(p, t, y):
        loss, g = jax.value_and_grad(gpt_loss)(p, t, y, cfg)
        return jax.tree_util.tree_map(
            lambda a, b: a - 0.01 * b, p, g), loss

    sharded = jax.shard_map(body, mesh=mesh,
                            in_specs=(specs, P(), P()),
                            out_specs=(specs, P()))
    n_leaves = len(jax.tree_util.tree_leaves(params))
    rep = analyze.check_donation(
        jax.jit(sharded, donate_argnums=(0,)), params, tok, tok,
        donate_argnums=(0,))
    assert rep.n_aliased >= n_leaves
    step = jax.jit(sharded, donate_argnums=(0,))
    p = jax.tree_util.tree_map(jnp.copy, params)
    with analyze.recompile_guard(step):
        for _ in range(3):
            p, loss = step(p, tok, tok)
    assert np.isfinite(float(loss))


def _serve_fixture():
    from apex_tpu.serve import KVCacheConfig, init_kv_cache
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    cfg = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                    num_heads=4, dtype=jnp.float32, fused_loss=False)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=8, block_size=8, dtype=jnp.float32)
    return cfg, params, kv, init_kv_cache(kv)


def test_flagship_serve_decode_step_donation():
    """Acceptance: the serve decode step's donated KV pools are aliased —
    a silently-copied pool would double serve HBM."""
    from apex_tpu.serve.decode import gpt_decode_step

    cfg, params, kv, cache = _serve_fixture()
    n = 3
    toks = jnp.zeros((n,), jnp.int32)
    lens = jnp.array([4, 2, 0], jnp.int32)
    active = jnp.array([True, True, False])
    bt = jnp.arange(n * 2, dtype=jnp.int32).reshape(n, 2)

    def decode(cache, toks, lens, active, bt):
        return gpt_decode_step(params, toks, lens, active, cache, bt,
                               cfg, kv, tp_axis=None, use_pallas=False)

    n_pool_leaves = len(jax.tree_util.tree_leaves(cache))
    rep = analyze.assert_donated(decode, cache, toks, lens, active, bt,
                                 donate_argnums=(0,))
    assert rep.n_aliased >= n_pool_leaves
    # ... and the step itself is host-sync-free
    sync = analyze.assert_no_host_sync(decode, cache, toks, lens, active,
                                       bt)
    assert sync.host_syncs == 0


def test_flagship_serve_chunk_prefill_donation():
    from apex_tpu.serve.decode import gpt_prefill_chunk

    cfg, params, kv, cache = _serve_fixture()
    toks = jnp.zeros((8,), jnp.int32)

    def chunk(cache, toks, start, n_valid, block_row):
        return gpt_prefill_chunk(params, toks, start, n_valid, cache,
                                 block_row, cfg, kv, tp_axis=None,
                                 use_pallas=False)

    n_pool_leaves = len(jax.tree_util.tree_leaves(cache))
    rep = analyze.assert_donated(
        chunk, cache, toks, jnp.int32(0), jnp.int32(5),
        jnp.arange(2, dtype=jnp.int32), donate_argnums=(0,))
    assert rep.n_aliased >= n_pool_leaves


def test_flagship_engine_steady_state_no_new_compiles():
    """Acceptance: a warmed engine serves a fresh mixed-length workload
    with ZERO new compilations — the recompile sentinel wraps the
    engine's own programs (the generalized compile-count gate)."""
    from apex_tpu.serve import (
        InferenceEngine, Request, SamplingConfig, ServeConfig,
    )

    cfg, params, _, _ = _serve_fixture()
    eng = InferenceEngine(params, cfg, ServeConfig(
        num_slots=3, block_size=8, prefill_chunk=8,
        sampling=SamplingConfig()))
    eng.run([Request("warm1", [1, 2, 3], max_new_tokens=2),
             Request("warm2", list(range(12)), max_new_tokens=2)])
    with analyze.recompile_guard(eng.programs(), budget=0):
        out = eng.run([Request("a", [5, 6], max_new_tokens=3),
                       Request("b", list(range(17)), max_new_tokens=2)])
    assert len(out["a"]) == 3 and len(out["b"]) == 2
    counts = eng.compile_counts()
    if counts["decode"] is not None:
        assert counts == {"chunk_prefill": 1, "decode": 1, "verify": 0,
                          "cow_copy": 0}


# ---------------------------------------------------------------------------
# analyze.adapters — the serve LoRA pool donation contract (PR-16)


def _lora_engine(spec_k=0):
    from apex_tpu.serve import (
        InferenceEngine, Request, SamplingConfig, ServeConfig,
        make_adapter_weights,
    )

    cfg, params, _, _ = _serve_fixture()
    eng = InferenceEngine(params, cfg, ServeConfig(
        num_slots=3, block_size=8, prefill_chunk=8, spec_k=spec_k,
        sampling=SamplingConfig(), lora_rank=4, max_adapters=2))
    eng.load_adapter("t0", make_adapter_weights(
        cfg, 4, jax.random.PRNGKey(11)), scale=0.5)
    eng.run([Request("warm-base", [1, 2, 3], max_new_tokens=2),
             Request("warm-t0", list(range(12)), max_new_tokens=2,
                     adapter="t0")])
    return cfg, eng


def test_flagship_adapter_pool_rides_every_jit_site_donated():
    """Acceptance: the AdapterPool is a donated, ALIASED input of every
    serve jit site — a copied pool would double adapter HBM per step."""
    cfg, eng = _lora_engine()
    reports = analyze.assert_adapter_donated(eng)
    assert set(reports) == {"chunk_prefill", "decode"}
    cache_leaves = len(jax.tree_util.tree_leaves(eng.cache))
    pool_leaves = len(jax.tree_util.tree_leaves(eng._lora_pool))
    for site, rep in reports.items():
        assert rep.expected_leaves == cache_leaves + pool_leaves, site
        assert rep.n_aliased >= rep.expected_leaves, site
        assert not rep.unusable, site
    rec = analyze.adapter_contract_record(eng)
    assert rec["adapter_donation_ok"] is True
    assert rec["adapter_donated_copied"] == 0
    assert rec["adapter_sites_checked"] == 2


def test_flagship_adapter_sites_include_verify_under_spec_k():
    cfg, eng = _lora_engine(spec_k=2)
    reports = analyze.adapter_donation_report(eng)
    assert set(reports) == {"chunk_prefill", "decode", "verify"}
    assert all(r.ok for r in reports.values())


def test_adapter_contract_refuses_lora_free_engine():
    from apex_tpu.serve import (
        InferenceEngine, SamplingConfig, ServeConfig,
    )

    cfg, params, _, _ = _serve_fixture()
    eng = InferenceEngine(params, cfg, ServeConfig(
        num_slots=3, block_size=8, prefill_chunk=8,
        sampling=SamplingConfig()))
    with pytest.raises(ValueError, match="lora_rank"):
        analyze.adapter_jit_sites(eng)


def test_flagship_adapter_swap_zero_new_compiles():
    """Acceptance: loading/unloading adapters on a warm engine and
    serving an adapter-bound workload compiles NOTHING new — residency
    is pool data, not a program constant (the aid=0 base path and the
    adapter path share one executable per site), and the AOT donation
    check itself leaves the jit caches untouched."""
    from apex_tpu.serve import Request, make_adapter_weights

    cfg, eng = _lora_engine()
    analyze.assert_adapter_donated(eng)  # AOT: must not pollute caches
    with analyze.recompile_guard(eng.programs(), budget=0):
        eng.unload_adapter("t0")
        eng.load_adapter("t1", make_adapter_weights(
            cfg, 4, jax.random.PRNGKey(12)), scale=0.5)
        out = eng.run([Request("a", [5, 6], max_new_tokens=3,
                               adapter="t1"),
                       Request("b", list(range(17)), max_new_tokens=2)])
    assert len(out["a"]) == 3 and len(out["b"]) == 2
    counts = eng.compile_counts()
    if counts["decode"] is not None:
        assert counts == {"chunk_prefill": 1, "decode": 1, "verify": 0,
                          "cow_copy": 0}

"""monitor tier 2 — hist/events/slo/regress/view + sink rotation.

All stock-jax/CPU-safe. The load-bearing gates:

* histogram quantile estimates stay within the bucket relative-error
  bound against EXACT nearest-rank quantiles on adversarial
  distributions (bimodal, heavy-tail), merges are associative and equal
  to one-shot ingestion, and the Metrics-pytree round-trip survives jit
  with donation at cache-size == 1 (the PR-2 convention);
* the loadgen + SLO path emits a goodput-under-SLO ``json_record`` with
  TTFT/TPOT quantiles from histograms and violation counts under a
  seeded Poisson+burst workload (the acceptance line);
* ``JsonlSink(rotate_bytes=)`` rolls to ``.1``/``.2``/… and
  ``read_jsonl`` iterates segments in order transparently.
"""

import functools
import json
import math
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor import (
    EventLog,
    HistSpec,
    Histogram,
    JsonlSink,
    Metrics,
    SloSpec,
    SloTracker,
    accumulate_hist,
    chrome_trace,
    compare_records,
    hist_from_metrics,
    hist_metric_names,
    json_record,
    load_record,
    read_jsonl,
    rotated_segments,
    write_chrome_trace,
)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))


def _cache_size(jitted):
    fn = getattr(jitted, "_cache_size", None)
    return fn() if callable(fn) else None


def _exact_nearest_rank(values, q):
    s = sorted(values)
    return s[max(1, math.ceil(q * len(s))) - 1]


# ---------------------------------------------------------------------------
# histograms: spec, bounded-error quantiles, merge, serialization


def test_hist_spec_buckets_and_edges():
    spec = HistSpec(lo=1.0, hi=1000.0, growth=2.0)
    assert spec.num_log_buckets == 10  # 2^10 = 1024 covers 1000
    assert spec.num_buckets == 12
    e = spec.edges()
    np.testing.assert_allclose(e, [2.0 ** i for i in range(11)])
    # bucket placement: underflow, ladder, overflow
    idx = spec.bucket_of(np.array([0.0, -3.0, 0.5, 1.0, 1.9, 2.0, 999.0,
                                   1024.0, 1e9]))
    assert idx.tolist() == [0, 0, 0, 1, 1, 2, 10, 11, 11]
    assert spec.rel_error == pytest.approx(math.sqrt(2.0) - 1.0)
    with pytest.raises(ValueError):
        HistSpec(lo=0.0, hi=1.0)
    with pytest.raises(ValueError):
        HistSpec(lo=1.0, hi=10.0, growth=1.0)


@pytest.mark.parametrize("dist", ["bimodal", "heavy_tail"])
def test_hist_quantiles_within_relative_error_bound(dist):
    """The correctness satellite: estimates within the bucket bound
    against exact nearest-rank quantiles on adversarial distributions."""
    rng = np.random.default_rng(7)
    if dist == "bimodal":
        v = np.concatenate([rng.lognormal(0.5, 0.25, 20000),
                            rng.lognormal(6.0, 0.4, 20000)])
    else:  # heavy tail (Pareto alpha=1.2: p99 >> p50)
        v = (rng.pareto(1.2, 40000) + 1.0) * 2.0
    spec = HistSpec(lo=0.1, hi=1e6, growth=1.1)
    h = Histogram(spec).add(v)
    assert h.total == v.size
    for q in (0.01, 0.1, 0.5, 0.9, 0.99, 0.999):
        exact = _exact_nearest_rank(v, q)
        est = h.quantile(q)
        err = abs(est - exact) / exact
        # tiny slack only for float-noise bucket placement at edges
        assert err <= spec.rel_error * 1.0001, (q, est, exact, err)
    # extremes are exact (clamped by tracked min/max)
    assert h.quantile(0.0) == pytest.approx(v.min())
    assert h.quantile(1.0) == pytest.approx(v.max())
    assert h.mean() == pytest.approx(v.mean())


def test_hist_merge_associative_and_matches_oneshot():
    rng = np.random.default_rng(3)
    v = rng.lognormal(2.0, 1.5, 9000)
    spec = HistSpec(lo=0.01, hi=1e5, growth=1.2)
    a = Histogram(spec).add(v[:3000])
    b = Histogram(spec).add(v[3000:6000])
    c = Histogram(spec).add(v[6000:])
    lhs, rhs = (a + b) + c, a + (b + c)
    one = Histogram(spec).add(v)
    for m in (lhs, rhs):
        np.testing.assert_array_equal(m.counts, one.counts)
        assert m.total == one.total
        assert m.min == one.min and m.max == one.max
        assert m.quantile(0.99) == one.quantile(0.99)
    # commutative too, and spec mismatch is loud
    np.testing.assert_array_equal((b + a).counts, (a + b).counts)
    with pytest.raises(ValueError):
        a.merge(Histogram(HistSpec(lo=0.01, hi=1e5, growth=1.3)))


def test_hist_json_roundtrip_and_empty():
    spec = HistSpec(lo=0.1, hi=100.0, growth=1.5)
    h = Histogram(spec).add([0.5, 3.0, 3.1, 250.0])
    h2 = Histogram.from_dict(json.loads(json.dumps(h.to_dict())))
    np.testing.assert_array_equal(h2.counts, h.counts)
    assert h2.total == h.total and h2.quantile(0.5) == h.quantile(0.5)
    assert h2.min == h.min and h2.max == h.max
    empty = Histogram(spec)
    assert empty.quantile(0.5) is None and empty.mean() is None
    e2 = Histogram.from_dict(json.loads(json.dumps(empty.to_dict())))
    assert e2.total == 0 and e2.quantile(0.9) is None
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_hist_metrics_pytree_roundtrip_under_jit_with_donation():
    """The PR-2 convention applied to histograms: per-bucket counters on
    a donated Metrics carry across steps with ONE compilation, and the
    reassembled host histogram equals the host-side reference."""
    spec = HistSpec(lo=0.1, hi=100.0, growth=1.5)
    rng = np.random.default_rng(0)
    batches = rng.lognormal(1.0, 1.0, (5, 16)).astype(np.float32)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(m, x):
        return accumulate_hist(m, "lat_ms", x, spec)

    m = Metrics({n: 0.0 for n in hist_metric_names("lat_ms", spec)})
    for i in range(5):
        m = step(m, jnp.asarray(batches[i]))
    n = _cache_size(step)
    if n is not None:
        assert n == 1, f"hist accumulation retraced: {n} compilations"
    got = hist_from_metrics(m.as_dict(), "lat_ms", spec)
    want = Histogram(spec).add(batches.ravel())
    np.testing.assert_array_equal(got.counts, want.counts)
    assert got.total == want.total == 80
    # bucket-estimate quantiles agree (counts are identical)
    assert got.quantile(0.9) == pytest.approx(
        spec.estimate_of(int(spec.bucket_of(
            np.array([_exact_nearest_rank(batches.ravel(), 0.9)]))[0])),
        rel=1e-6)


def test_hist_counts_masks_invalid_entries():
    from apex_tpu.monitor import hist_counts

    spec = HistSpec(lo=1.0, hi=100.0, growth=2.0)
    v = jnp.asarray([2.0, 5.0, 50.0, 7.0])
    valid = jnp.asarray([True, False, True, False])
    counts = np.asarray(hist_counts(v, spec, valid=valid))
    assert counts.sum() == 2
    h = Histogram(spec).add_counts(counts)
    assert h.total == 2


# ---------------------------------------------------------------------------
# events + chrome trace (module level; the engine integration test lives
# in test_serve.py)


def test_event_log_monotonic_clock_and_sink(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        log = EventLog(sink=sink, keep=True)
        t1 = log.emit("submitted", "r1", prompt_tokens=5)
        t2 = log.emit("admitted", "r1", slot=0)
        log.gauge("queue_depth", 3)
        assert t2 >= t1 >= 0.0
    recs = list(read_jsonl(path))
    assert [r.get("event", r.get("gauge")) for r in recs] == \
        ["submitted", "admitted", "queue_depth"]
    assert recs[0]["kind"] == "event" and recs[2]["kind"] == "gauge"
    assert recs[0]["prompt_tokens"] == 5 and recs[2]["value"] == 3.0
    assert log.records is not None and len(log.records) == 3
    # explicit timestamps pass through (replayed logs)
    log2 = EventLog()
    assert log2.emit("retired", "r1", t_ms=42.5) == 42.5
    assert log2.records is None  # keep=False holds nothing


def test_chrome_trace_structure_and_counter_tracks():
    log = EventLog(keep=True)
    for uid, slot in (("a", 0), ("b", 1)):
        log.emit("submitted", uid, t_ms=0.0)
        log.emit("admitted", uid, t_ms=1.0, slot=slot)
        log.emit("prefill_start", uid, t_ms=1.0, slot=slot)
        log.emit("prefill_end", uid, t_ms=2.0, slot=slot)
        log.emit("first_token", uid, t_ms=2.0, slot=slot)
        log.emit("decode_chunk", uid, t_ms=4.0, slot=slot, start_ms=2.0,
                 n_tokens=8)
        log.emit("retired", uid, t_ms=4.0, slot=slot, n_tokens=9)
    log.gauge("occupancy", 0.5, t_ms=1.0)
    trace = chrome_trace(log.records)
    json.dumps(trace)  # valid trace-event JSON
    evs = trace["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X"]
    per_req = sorted(e["name"] for e in spans if e["pid"] == 1)
    assert per_req == ["decode", "decode", "decode_chunk", "decode_chunk",
                      "prefill", "prefill", "queued", "queued"]
    # ts is µs, dur from the event pair: queued = 0..1 ms
    queued = next(e for e in spans if e["name"] == "queued")
    assert queued["ts"] == 0.0 and queued["dur"] == 1000.0
    # slot residency spans named by uid, one per slot tid
    slots = {e["tid"]: e["name"] for e in spans if e["pid"] == 2}
    assert slots == {0: "a", 1: "b"}
    counters = [e for e in evs if e["ph"] == "C"]
    assert counters and counters[0]["args"] == {"occupancy": 0.5}


# ---------------------------------------------------------------------------
# SLO accounting


def test_slo_spec_check_and_validate():
    spec = SloSpec(ttft_ms=100.0, tpot_ms=10.0)
    assert spec.budgets() == {"ttft_ms": 100.0, "tpot_ms": 10.0}
    assert spec.check(ttft_ms=50.0, tpot_ms=20.0) == \
        {"ttft_ms": False, "tpot_ms": True}
    # unmeasured dimension never violates (single-token request: no tpot)
    assert spec.check(ttft_ms=50.0, tpot_ms=None) == \
        {"ttft_ms": False, "tpot_ms": False}
    with pytest.raises(ValueError):
        SloSpec(ttft_ms=-1.0).validate()


def test_slo_tracker_counts_and_rolling_window():
    # manual clock: deterministic window arithmetic
    now = [0.0]
    t = SloTracker(SloSpec(ttft_ms=100.0, queue_ms=50.0), window_s=10.0,
                   clock=lambda: now[0])
    now[0] = 1.0
    assert t.observe(ttft_ms=20.0, queue_ms=5.0) is True
    now[0] = 2.0
    assert t.observe(ttft_ms=500.0, queue_ms=5.0) is False
    now[0] = 3.0
    assert t.observe(ttft_ms=20.0, queue_ms=80.0) is False
    rep = t.report()
    assert rep["completed"] == 3 and rep["good"] == 1
    assert rep["violations"] == {"ttft_ms": 1, "queue_ms": 2 - 1}
    assert rep["good_fraction"] == pytest.approx(1 / 3, abs=1e-4)
    # rates over min(window, elapsed) = 3 s
    assert rep["throughput_rps"] == pytest.approx(3 / 3.0)
    assert rep["goodput_rps"] == pytest.approx(1 / 3.0, abs=1e-4)
    # window prune: at t=12.5 the cutoff is 2.5 — the first two
    # observations age out, the t=3 one stays
    now[0] = 12.5
    rep2 = t.report()
    assert rep2["throughput_rps"] == pytest.approx(1 / 10.0)
    assert rep2["completed"] == 3  # lifetime counters survive the window
    # histograms feed quantiles
    assert rep2["ttft_ms_p99"] > rep2["ttft_ms_p50"] > 0
    with pytest.raises(ValueError):
        t.observe(bogus_ms=1.0)


# ---------------------------------------------------------------------------
# regression comparison


def test_regress_flags_both_polarities_with_tolerance():
    base = {"tokens_per_s": 100.0, "ttft_ms_p99": 20.0, "goodput_rps": 5.0,
            "violations": {"ttft_ms": 0}, "uncls": 7.0, "ok": True}
    # within tolerance: no flags
    near = {"tokens_per_s": 95.0, "ttft_ms_p99": 21.0, "goodput_rps": 5.2,
            "violations": {"ttft_ms": 0}, "uncls": 900.0, "ok": True}
    rep = compare_records(base, near, tol=0.1)
    assert rep["ok"] and not rep["regressions"]
    assert rep["compared"] == 4  # 'uncls'/'ok' skipped, never guessed
    # beyond tolerance, both polarities + zero-baseline violation jump
    bad = {"tokens_per_s": 80.0, "ttft_ms_p99": 30.0, "goodput_rps": 8.0,
           "violations": {"ttft_ms": 3}, "uncls": 7.0, "ok": True}
    rep2 = compare_records(base, bad, tol=0.1)
    assert not rep2["ok"]
    keys = {e["key"] for e in rep2["regressions"]}
    assert keys == {"tokens_per_s", "ttft_ms_p99", "violations.ttft_ms"}
    assert {e["key"] for e in rep2["improvements"]} == {"goodput_rps"}
    # explicit rules override name classification
    rep3 = compare_records({"weird": 1.0}, {"weird": 10.0},
                           rules={"weird": "lower"})
    assert [e["key"] for e in rep3["regressions"]] == ["weird"]


def test_regress_classifies_verify_ab_fields():
    """Polarity pins for the megakernel tier-2 verify A/B gate: the
    fused-vs-unfused step latencies are lower-is-better (explicitly
    listed next to the generic '_ms' rule), the speculative acceptance
    rate is higher-is-better — a slower verify step or a collapsing
    acceptance rate must flag, a faster/more-accepting record must not."""
    from apex_tpu.monitor.regress import classify_metric

    assert classify_metric("verify_step_ms_p50") == "lower"
    assert classify_metric("decode_step_ms_p50") == "lower"
    assert classify_metric("fused_on.verify_step_ms_p50") == "lower"
    assert classify_metric("spec_acceptance_rate") == "higher"
    assert classify_metric("decode_step_speedup_p50") == "higher"
    base = {"verify_step_ms_p50": 2.0, "spec_acceptance_rate": 0.9}
    bad = {"verify_step_ms_p50": 3.0, "spec_acceptance_rate": 0.5}
    rep = compare_records(base, bad, tol=0.15)
    assert not rep["ok"]
    assert {e["key"] for e in rep["regressions"]} == {
        "verify_step_ms_p50", "spec_acceptance_rate"}
    good = {"verify_step_ms_p50": 1.5, "spec_acceptance_rate": 1.0}
    rep2 = compare_records(base, good, tol=0.15)
    assert rep2["ok"] and not rep2["regressions"]


def test_regress_skips_embedded_histogram_dumps():
    """A fuller run's hist count/sum/min must never read as a latency
    regression: histogram dumps are excluded from the comparison."""
    def rec(n, p99):
        h = Histogram(HistSpec(lo=1.0, hi=100.0, growth=2.0))
        h.add(np.linspace(2.0, 50.0, n))
        return {"ttft_ms_p99": p99, "completed": n,
                "hists": {"ttft_ms": h.to_dict()},
                "embedded": h.to_dict()}  # a dump outside 'hists' too
    base, new = rec(50, 20.0), rec(64, 20.0)
    rep = compare_records(base, new, tol=0.1)
    assert rep["ok"], rep["regressions"]
    assert rep["compared"] == 1  # only the quantile summary compared


def test_regress_load_record_shapes(tmp_path):
    # whole-file JSON
    p1 = str(tmp_path / "a.json")
    with open(p1, "w") as f:
        json.dump({"tokens_per_s": 10.0}, f)
    assert load_record(p1)["tokens_per_s"] == 10.0
    # BENCH_r0* wrapper: payload under "parsed"
    p2 = str(tmp_path / "b.json")
    with open(p2, "w") as f:
        json.dump({"n": 5, "tail": "...", "parsed": {"value": 3.0}}, f)
    assert load_record(p2) == {"value": 3.0}
    # JSONL: last parseable line wins
    p3 = str(tmp_path / "c.jsonl")
    with open(p3, "w") as f:
        f.write(json_record(value=1.0) + "\n")
        f.write(json_record(value=2.0) + "\n")
        f.write('{"truncated": ')
    assert load_record(p3)["value"] == 2.0
    with pytest.raises(ValueError):
        p4 = str(tmp_path / "d.json")
        with open(p4, "w") as f:
            f.write("not json at all")
        load_record(p4)


def test_regress_cli_exit_codes(tmp_path, capsys):
    from apex_tpu.monitor.regress import main

    base = str(tmp_path / "base.json")
    new = str(tmp_path / "new.json")
    with open(base, "w") as f:
        json.dump({"tokens_per_s": 100.0}, f)
    with open(new, "w") as f:
        json.dump({"tokens_per_s": 50.0}, f)
    assert main([base, new, "--tol", "0.1"]) == 1
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rep = json.loads(out)
    assert rep["metric"] == "regress_report" and not rep["ok"]
    with open(new, "w") as f:
        json.dump({"tokens_per_s": 99.0}, f)
    assert main([base, new, "--tol", "0.1"]) == 0


# ---------------------------------------------------------------------------
# sink rotation


def test_jsonl_sink_rotation_and_transparent_read(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with JsonlSink(path, buffer_steps=3, rotate_bytes=120) as sink:
        for i in range(20):
            sink.write(step=i, metrics={"x": float(i)})
    segs = rotated_segments(path)
    assert len(segs) > 2, "rotation never triggered"
    assert segs[0].endswith(".1") and segs[-1] == path
    # every rotated segment respects the cap's flush granularity and ends
    # on a whole line
    for s in segs[:-1]:
        with open(s, "rb") as f:
            data = f.read()
        assert data.endswith(b"\n")
    # transparent ordered read across segments
    recs = list(read_jsonl(path))
    assert [r["step"] for r in recs] == list(range(20))
    # rotated=False reads only the live file
    live = list(read_jsonl(path, rotated=False))
    assert len(live) < 20
    with pytest.raises(ValueError):
        JsonlSink(str(tmp_path / "n.jsonl"), rotate_bytes=0)


def test_jsonl_sink_rotation_appends_after_reopen(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with JsonlSink(path, buffer_steps=1, rotate_bytes=100) as sink:
        for i in range(5):
            sink.write(step=i, metrics={"x": 1.0})
    n_segs = len(rotated_segments(path))
    # a restarted writer keeps numbering where the last one stopped
    with JsonlSink(path, buffer_steps=1, rotate_bytes=100) as sink:
        for i in range(5, 10):
            sink.write(step=i, metrics={"x": 1.0})
    assert len(rotated_segments(path)) >= n_segs
    assert [r["step"] for r in read_jsonl(path)] == list(range(10))


def test_jsonl_sink_rotation_survives_deleted_old_segments(tmp_path):
    """Disk-reclaim scenario: deleting old segments must NOT make the
    next roll reuse a freed low index — newest records would then read
    under the oldest name and scramble chronological iteration."""
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, buffer_steps=1, rotate_bytes=100)
    for i in range(6):
        sink.write(step=i, metrics={"x": 1.0})
    segs = rotated_segments(path)
    assert len(segs) >= 3
    os.remove(segs[0])  # operator reclaims the oldest segment
    top = max(int(s.rsplit(".", 1)[1]) for s in segs
              if s.rsplit(".", 1)[1].isdigit())
    for i in range(6, 10):
        sink.write(step=i, metrics={"x": 1.0})
    sink.close()
    # new segments numbered past the old maximum, never into the gap
    gap = int(segs[0].rsplit(".", 1)[1])
    new_idx = [int(s.rsplit(".", 1)[1]) for s in rotated_segments(path)
               if s.rsplit(".", 1)[1].isdigit()]
    assert gap not in new_idx
    assert max(new_idx) > top
    # and the surviving records still read in step order
    steps = [r["step"] for r in read_jsonl(path)]
    assert steps == sorted(steps) and steps[-1] == 9


# ---------------------------------------------------------------------------
# view CLI


def test_view_cli_summary_and_json_line(tmp_path, capsys):
    from apex_tpu.monitor.view import main

    path = str(tmp_path / "log.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        log = EventLog(sink=sink)
        for i, uid in enumerate(("a", "b")):
            log.emit("submitted", uid, t_ms=0.0)
            log.emit("admitted", uid, t_ms=5.0, slot=i)
            log.emit("first_token", uid, t_ms=10.0 + i, slot=i)
            log.emit("retired", uid, t_ms=30.0, slot=i, n_tokens=5)
        sink.write(step=0, metrics={"step_ms": 2.0, "occupancy": 0.5})
    rc = main([path, "--ttft-budget", "10.5"])
    assert rc == 0
    cap = capsys.readouterr()
    rec = json.loads(cap.out.strip())
    assert rec["metric"] == "monitor_view"
    assert rec["n_requests"] == 2 and rec["n_retired"] == 2
    assert rec["ttft_ms_p50"] == 10.0 and rec["ttft_ms_p99"] == 11.0
    assert rec["queue_ms_p50"] == 5.0
    # tpots: a=(30-10)/4=5.0, b=(30-11)/4=4.75; nearest-rank p50 of two
    assert rec["tpot_ms_p50"] == pytest.approx(4.75)
    assert rec["decode_step_ms_p50"] == 2.0
    assert rec["good"] == 1 and rec["violations"]["ttft_ms"] == 1
    assert "ttft_ms" in cap.err and "p99" in cap.err


def test_view_module_is_runnable(tmp_path):
    """``python -m apex_tpu.monitor.view`` — the CI/tooling entry point."""
    import subprocess

    path = str(tmp_path / "log.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        sink.write(step=0, metrics={"step_ms": 1.5, "occupancy": 1.0})
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.monitor.view", path],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout.strip())
    assert rec["n_steps"] == 1 and rec["decode_step_ms_p50"] == 1.5


# ---------------------------------------------------------------------------
# loadgen: deterministic workloads + the goodput-under-SLO record
# (drives the real engine on a tiny GPT — the acceptance line's test)


def test_loadgen_workload_deterministic_with_bursts():
    from loadgen import WorkloadConfig, build_workload

    cfg = WorkloadConfig(n_requests=32, rate_rps=20.0, burst_every_s=0.5,
                         burst_size=4, seed=5, prompt_len_max=48)
    w1 = build_workload(cfg, vocab_size=97, max_context=64)
    w2 = build_workload(cfg, vocab_size=97, max_context=64)
    assert [(t, r.uid, tuple(r.tokens), r.max_new_tokens)
            for t, r in w1] == \
        [(t, r.uid, tuple(r.tokens), r.max_new_tokens) for t, r in w2]
    arr = [t for t, _ in w1]
    assert arr == sorted(arr)
    # bursts: some arrival instants repeat burst_size times
    from collections import Counter

    assert max(Counter(arr).values()) >= cfg.burst_size
    # long-tail prompt lengths stay in bounds and leave room to generate
    plens = [len(r.tokens) for _, r in w1]
    assert max(plens) < 64 and min(plens) >= cfg.prompt_len_min
    # a different seed changes the stream
    w3 = build_workload(WorkloadConfig(n_requests=32, seed=6,
                                       prompt_len_max=48), 97, 64)
    assert [tuple(r.tokens) for _, r in w1] != \
        [tuple(r.tokens) for _, r in w3]
    with pytest.raises(ValueError):
        WorkloadConfig(mode="sideways").validate()


def test_loadgen_goodput_under_slo_record():
    """Acceptance: loadgen drives the engine under a seeded Poisson+burst
    workload and the resulting record carries goodput req/s, TTFT/TPOT
    p50/p99 from histograms, and violation counts."""
    from loadgen import WorkloadConfig, build_workload, run_workload

    from apex_tpu.serve import InferenceEngine, ServeConfig
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    cfg = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                    num_heads=4, dtype=jnp.float32, fused_loss=False)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    wcfg = WorkloadConfig(n_requests=12, rate_rps=200.0, burst_every_s=0.02,
                          burst_size=3, seed=0, prompt_len_median=6,
                          prompt_len_max=30, max_new_median=4,
                          max_new_max=8)
    workload = build_workload(wcfg, cfg.vocab_size, cfg.max_seq)
    eng = InferenceEngine(
        params, cfg,
        ServeConfig(num_slots=3, block_size=8,
                    prefill_buckets=(8, 16, 32, 64)),
        slo=SloSpec(ttft_ms=60000.0, tpot_ms=60000.0, queue_ms=60000.0),
        retain_streams=False)
    stats = run_workload(eng, workload, max_wall_s=120.0)
    assert stats["completed"] == len(workload)
    assert eng.per_request_state_count() == 0
    rep = stats["slo_report"]
    line = json_record(metric="goodput_slo_test", **{
        k: stats[k] for k in ("completed", "offered", "ttft_ms_p50",
                              "ttft_ms_p99", "tpot_ms_p50", "tpot_ms_p99")
    }, goodput_rps=rep["goodput_rps"], violations=rep["violations"])
    rec = json.loads(line)  # the one-JSON-line contract holds
    assert rec["ttft_ms_p99"] >= rec["ttft_ms_p50"] > 0
    assert rec["tpot_ms_p99"] >= rec["tpot_ms_p50"] > 0
    assert rec["goodput_rps"] > 0  # generous budgets: everything good
    assert set(rec["violations"]) == {"ttft_ms", "tpot_ms", "queue_ms"}
    assert sum(rec["violations"].values()) == 0

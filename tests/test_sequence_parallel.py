"""Ring / Ulysses attention tests on the 8-device virtual mesh — new
capability beyond the reference (SURVEY.md §2.3 SP row): the sharded result
must equal dense attention over the gathered sequence, fwd and bwd."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.attention import attention_reference
from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.sequence_parallel import (
    ring_attention,
    ulysses_attention,
)

B, H, S, D = 2, 8, 64, 16  # global seq S sharded 8 ways -> s_local 8


def _qkv(key):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D))
    k = jax.random.normal(kk, (B, H, S, D))
    v = jax.random.normal(kv, (B, H, S, D))
    return q, k, v


def _mesh():
    return build_mesh(tp=1, pp=1, sp=8, dp=1)


def _ring_auto(q, k, v, causal=False):
    return ring_attention(q, k, v, causal=causal, impl="auto")


def _ring_scan_impl(q, k, v, causal=False):
    return ring_attention(q, k, v, causal=causal, impl="scan")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("fn", [_ring_auto, _ring_scan_impl,
                                ulysses_attention],
                         ids=["ring-flash", "ring-scan", "ulysses"])
def test_sp_attention_matches_dense(causal, fn):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    mesh = _mesh()
    sharded = jax.shard_map(
        lambda q, k, v: fn(q, k, v, causal=causal),
        mesh=mesh,
        in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None),
    )(q, k, v)
    dense = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(dense), atol=2e-5)


@pytest.mark.parametrize("fn", [_ring_auto, _ring_scan_impl,
                                ulysses_attention],
                         ids=["ring-flash", "ring-scan", "ulysses"])
def test_sp_attention_grads_match_dense(fn):
    q, k, v = _qkv(jax.random.PRNGKey(1))
    mesh = _mesh()

    def sharded_loss(q, k, v):
        o = jax.shard_map(
            lambda q, k, v: fn(q, k, v, causal=True),
            mesh=mesh,
            in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None),
        )(q, k, v)
        return jnp.sum(jnp.sin(o))

    def dense_loss(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=True)))

    g1 = jax.jit(jax.grad(sharded_loss, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, e, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), atol=2e-4, err_msg=name)


def test_ring_attention_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(2))
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    mesh = _mesh()
    sharded = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True),
        mesh=mesh,
        in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None),
    )(q, k, v)
    dense = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(sharded, np.float32), np.asarray(dense, np.float32),
        atol=3e-2)


def test_ulysses_rejects_bad_head_count():
    mesh = _mesh()
    q = jnp.zeros((B, 4, S, D))  # 4 heads not divisible by sp=8

    with pytest.raises(ValueError, match="heads"):
        jax.shard_map(
            lambda q: ulysses_attention(q, q, q),
            mesh=mesh,
            in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None),
        )(q)


# ---------------------------------------------------------------------------
# attention dropout under ring-SP (round 5): the kernels' global-position
# counter hash makes sharding invisible to the dropout stream, so the ring
# result must EQUAL the dense flash kernel with the same seed.

@pytest.mark.parametrize("causal", [False, True])
def test_ring_dropout_matches_dense_kernel(causal):
    from apex_tpu.ops.attention import flash_attention

    q, k, v = _qkv(jax.random.PRNGKey(3))
    mesh = _mesh()
    rate, seed = 0.3, 1234
    sharded = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=causal,
                                       dropout_rate=rate,
                                       dropout_seed=seed),
        mesh=mesh,
        in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None),
    )(q, k, v)
    dense = flash_attention(q, k, v, causal=causal, dropout_rate=rate,
                            dropout_seed=seed, use_pallas=True,
                            interpret=True)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense),
                               atol=2e-5)


def test_ring_dropout_grads_match_dense_kernel():
    from apex_tpu.ops.attention import flash_attention

    q, k, v = _qkv(jax.random.PRNGKey(4))
    mesh = _mesh()
    rate, seed = 0.2, 77

    def sharded_loss(q, k, v):
        o = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=True,
                                           dropout_rate=rate,
                                           dropout_seed=seed),
            mesh=mesh,
            in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None),
        )(q, k, v)
        return jnp.sum(jnp.sin(o))

    def dense_loss(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=True, dropout_rate=rate, dropout_seed=seed,
            use_pallas=True, interpret=True)))

    g1 = jax.jit(jax.grad(sharded_loss, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, e, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), atol=2e-4, err_msg=name)


def test_ring_dropout_seed_sensitive_and_requires_seed():
    q, k, v = _qkv(jax.random.PRNGKey(5))
    mesh = _mesh()

    def run(seed):
        return np.asarray(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=True,
                                           dropout_rate=0.3,
                                           dropout_seed=seed),
            mesh=mesh,
            in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None),
        )(q, k, v))

    a, b_, c = run(1), run(1), run(2)
    np.testing.assert_array_equal(a, b_)  # same seed replays the mask
    assert np.abs(a - c).max() > 1e-3  # different seed, different mask
    with pytest.raises(ValueError, match="dropout_seed"):
        ring_attention(q, k, v, dropout_rate=0.3)


def test_ulysses_dropout_runs_deterministic_rank_decorrelated():
    """Ulysses dropout: per-rank-folded seeds — replays for a seed,
    changes across seeds, and the distinct head slices actually drop
    (output differs from no-dropout)."""
    q, k, v = _qkv(jax.random.PRNGKey(6))
    mesh = _mesh()

    # ONE jitted callable with the seed traced: three seed values share a
    # single compile (eager shard_map would recompile per call)
    @jax.jit
    def run_drop(q, k, v, seed):
        return jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, causal=True,
                                              dropout_rate=0.3,
                                              dropout_seed=seed),
            mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None),
        )(q, k, v)

    def run(seed):
        return np.asarray(run_drop(q, k, v, jnp.int32(seed)))

    def run_nodrop():
        return np.asarray(jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, causal=True),
            mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None),
        )(q, k, v))

    a, b_, c = run(5), run(5), run(6)
    np.testing.assert_array_equal(a, b_)
    assert np.abs(a - c).max() > 1e-3
    nodrop = run_nodrop()
    assert np.abs(a - nodrop).max() > 1e-3
    # every head must see live dropout (rank-folded seeds cover all slices)
    per_head = np.abs(a - nodrop).reshape(B, H, -1).max(-1)
    assert (per_head > 1e-4).all(), per_head


def test_ulysses_dropout_ranks_draw_independent_masks():
    """The rank fold itself (reviewer find: the basic test passes without
    it): with IDENTICAL data in every head, only the mask distinguishes
    head outputs. H=8 over sp=8 puts each head at local slot 0 of a
    different rank — without the fold all 8 would share one mask and be
    bitwise equal."""
    mesh = _mesh()
    base = jax.random.normal(jax.random.PRNGKey(7), (B, 1, S, D))
    q = jnp.broadcast_to(base, (B, H, S, D))

    out = np.asarray(jax.shard_map(
        lambda q: ulysses_attention(q, q, q, causal=True, dropout_rate=0.3,
                                    dropout_seed=9),
        mesh=mesh,
        in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None),
    )(q))
    for g1 in range(H):
        for g2 in range(g1 + 1, H):
            assert not np.array_equal(out[:, g1], out[:, g2]), \
                f"heads {g1} and {g2} shared a dropout mask"


def test_dots_attn_policy_skips_ring_fwd_replay():
    """The ring custom_vjp names its (o, lse) residuals like the dense
    flash kernels, so the dots_attn remat policy spares backward the
    ENTIRE forward-ring replay: grad-jaxpr ppermute count drops from 8
    (fwd k+v rotations, their replay, bwd's 4 rotations) to 6."""
    mesh = build_mesh(tp=1, pp=1, sp=4, dp=2)
    q = jnp.ones((1, 2, 64, 16), jnp.float32)

    def block(x):
        o = jax.shard_map(
            lambda x: ring_attention(x, x, x, causal=True),
            mesh=mesh, in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None))(x)
        return (o * x).sum()

    def n_ppermute(policy):
        f = jax.checkpoint(block, policy=policy)
        return str(jax.make_jaxpr(jax.grad(f))(q)).count("ppermute")

    from apex_tpu.transformer.testing.standalone_gpt import dots_attn_policy

    dots = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    assert n_ppermute(dots) == 8
    assert n_ppermute(dots_attn_policy()) == 6  # the REAL installed policy

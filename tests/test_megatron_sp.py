"""Megatron-style sequence parallelism in the flagship GPT.

Not in the reference (its only SP artifact is activation-shard
checkpointing); gate = the seq-sharded program must reproduce the plain TP
program exactly — values AND grads — across the fused/unfused loss paths,
the pipeline schedule, and composed with the ring-attention sp axis.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)

CFG = GPTConfig(vocab_size=96, max_seq=32, hidden=64, num_layers=2,
                num_heads=4, dtype=jnp.float32)


def _loss_and_grads(cfg, tp=1, sp=1, dropout_seed=None):
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    mesh = build_mesh(tp=tp, pp=1, sp=sp)
    specs = gpt_param_specs(cfg)
    key = jax.random.PRNGKey(1)
    tok = jax.random.randint(key, (4, cfg.max_seq), 0, cfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)
    dkey = (jax.random.PRNGKey(dropout_seed)
            if dropout_seed is not None else None)

    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )

    def loss_fn(p):
        def body(p, tok, tgt):
            loss = gpt_loss(p, tok, tgt, cfg, dropout_key=dkey)
            # pmean over every axis: averages the sp token shards, identity
            # on the tp/dp replicas — yields a mesh-invariant scalar
            return replicate_loss(loss, mesh, masked_axis=None)

        return jax.shard_map(body, mesh=mesh,
                             in_specs=(specs, P(None, "sp"), P(None, "sp")),
                             out_specs=P())(p, tok, tgt)

    return jax.jit(jax.value_and_grad(loss_fn))(params)


def _assert_tree_close(a, b, rtol, atol):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        x, y, rtol=rtol, atol=atol), a, b)


@pytest.mark.parametrize("fused", [True, False])
def test_megatron_sp_matches_plain_tp2(fused):
    cfg = dataclasses.replace(CFG, fused_loss=fused)
    l0, g0 = _loss_and_grads(cfg, tp=2)
    l1, g1 = _loss_and_grads(
        dataclasses.replace(cfg, megatron_sp=True), tp=2)
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    _assert_tree_close(g1, g0, rtol=1e-4, atol=1e-5)


def test_megatron_sp_tp4_untied():
    cfg = dataclasses.replace(CFG, tie_embeddings=False)
    l0, g0 = _loss_and_grads(cfg, tp=4)
    l1, g1 = _loss_and_grads(
        dataclasses.replace(cfg, megatron_sp=True), tp=4)
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    _assert_tree_close(g1, g0, rtol=1e-4, atol=1e-5)


def test_megatron_sp_composes_with_ring_sp():
    """tp=2 × sp=2: Megatron-SP shards each ring-sp shard further by tp."""
    l0, g0 = _loss_and_grads(CFG, tp=2, sp=2)
    l1, g1 = _loss_and_grads(
        dataclasses.replace(CFG, megatron_sp=True), tp=2, sp=2)
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    _assert_tree_close(g1, g0, rtol=1e-4, atol=1e-5)


def test_megatron_sp_dropout_trains_finite():
    """Dropout under megatron_sp: per-tp-rank masks (different tokens), the
    step runs and is deterministic for a fixed key."""
    cfg = dataclasses.replace(CFG, hidden_dropout=0.2, attention_dropout=0.0,
                              megatron_sp=True)
    l1, g1 = _loss_and_grads(cfg, tp=2, dropout_seed=7)
    l2, g2 = _loss_and_grads(cfg, tp=2, dropout_seed=7)
    assert np.isfinite(float(l1))
    np.testing.assert_allclose(l1, l2, rtol=0, atol=0)  # same key, same mask
    assert all(np.all(np.isfinite(np.asarray(g))) for g in
               jax.tree.leaves(g1))
    # a different key gives a different loss (masks actually active)
    l3, _ = _loss_and_grads(cfg, tp=2, dropout_seed=8)
    assert float(l3) != float(l1)


def test_megatron_sp_validates_divisibility():
    cfg = dataclasses.replace(CFG, max_seq=30, megatron_sp=True)
    with pytest.raises(ValueError, match="divisible by"):
        cfg.validate(tp=4)


@pytest.mark.parametrize("interleaved", [False, True])
def test_megatron_sp_pipeline_matches_plain(interleaved):
    """pp=2 × tp=2 with megatron_sp == the same schedule without it, for
    both the 1F1B and the interleaved virtual-stage schedule (inter-stage
    tensors are the seq shards — tp× smaller p2p)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_with_interleaving,
        forward_backward_pipelining_without_interleaving,
    )
    from apex_tpu.transformer.testing import (
        gpt_pipeline_params,
        gpt_pipeline_spec,
        gpt_pipeline_specs_tree,
    )

    pp, tp = 2, 2
    vp = 2 if interleaved else None

    def run(megatron_sp):
        cfg = dataclasses.replace(
            CFG, num_layers=pp * (vp or 1), megatron_sp=megatron_sp)
        mesh = build_mesh(tp=tp, pp=pp, sp=1)
        params = gpt_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp,
                                     vp=vp)
        spec = gpt_pipeline_spec(cfg)
        specs_tree = gpt_pipeline_specs_tree(cfg, interleaved=interleaved)
        nmb = 2
        tok = jax.random.randint(jax.random.PRNGKey(1), (2 * nmb,
                                                         cfg.max_seq),
                                 0, cfg.vocab_size)
        tgt = jnp.roll(tok, -1, axis=1)
        kw = dict(num_microbatches=nmb, mesh=mesh, params_specs=specs_tree,
                  data_spec=P(None, "dp", "sp"))

        def step(params):
            if interleaved:
                return forward_backward_pipelining_with_interleaving(
                    spec, params, (tok, tgt), virtual_pipeline_size=vp, **kw)
            return forward_backward_pipelining_without_interleaving(
                spec, params, (tok, tgt), **kw)

        return jax.jit(step)(params)

    l0, g0 = run(False)
    l1, g1 = run(True)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=1e-5, atol=1e-6)
    _assert_tree_close(g1, g0, rtol=1e-4, atol=1e-5)

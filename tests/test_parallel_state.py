"""Mesh / parallel_state tests — ref tests/L0/run_transformer/run_initialize_test.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer import parallel_state


def test_device_count_is_8():
    assert jax.device_count() == 8


@pytest.mark.parametrize("tp,pp,sp", [(1, 1, 1), (2, 1, 1), (2, 2, 1), (4, 2, 1), (2, 1, 2)])
def test_initialize_model_parallel_sizes(tp, pp, sp):
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tp,
        pipeline_model_parallel_size_=pp,
        sequence_parallel_size_=sp,
    )
    assert parallel_state.get_tensor_model_parallel_world_size() == tp
    assert parallel_state.get_pipeline_model_parallel_world_size() == pp
    assert parallel_state.get_sequence_parallel_world_size() == sp
    assert parallel_state.get_data_parallel_world_size() == 8 // (tp * pp * sp)
    assert parallel_state.get_model_parallel_world_size() == tp * pp * sp
    assert parallel_state.model_parallel_is_initialized()


def test_initialize_rejects_bad_shape():
    with pytest.raises(ValueError):
        build_mesh(tp=3)  # 3 does not divide 8


def test_destroy():
    parallel_state.initialize_model_parallel(2, 2)
    parallel_state.destroy_model_parallel()
    assert not parallel_state.model_parallel_is_initialized()
    with pytest.raises(RuntimeError):
        parallel_state.get_mesh()


def test_rank_accessors_inside_mesh_program():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )

    def body(x):
        tp_r = parallel_state.get_tensor_model_parallel_rank()
        pp_r = parallel_state.get_pipeline_model_parallel_rank()
        dp_r = parallel_state.get_data_parallel_rank()
        return x + tp_r + 10 * pp_r + 100 * dp_r

    f = shard_map(
        body,
        mesh=mesh,
        in_specs=P("dp", ("pp", "sp", "tp")),
        out_specs=P("dp", ("pp", "sp", "tp")),
    )
    x = jnp.zeros((2, 4), jnp.int32)
    out = np.asarray(f(x))
    # Every device contributes 100*dp + 10*pp + tp to its (1,1) shard.
    assert set(out.ravel().tolist()) == {0, 1, 10, 11, 100, 101, 110, 111}


def test_psum_over_each_axis():
    mesh = parallel_state.initialize_model_parallel(2, 2)

    def body(x):
        s_tp = jax.lax.psum(x, "tp")
        s_pp = jax.lax.psum(s_tp, "pp")
        s_dp = jax.lax.psum(s_pp, "dp")
        return s_dp

    f = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P())
    out = f(jnp.ones(()))
    assert float(out) == 8.0


def test_virtual_pipeline_bookkeeping():
    parallel_state.initialize_model_parallel(1, 2, virtual_pipeline_model_parallel_size_=2)
    assert parallel_state.get_virtual_pipeline_model_parallel_world_size() == 2
    assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 0
    parallel_state.set_virtual_pipeline_model_parallel_rank(1)
    assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 1


def test_params_l2_norm_tp_dedup():
    """With a specs tree, TP-replicated leaves (LN weights) are counted
    once, TP-sharded leaves psum across ranks — the reference's
    param_is_not_tensor_parallel_duplicate dedup
    (ref tensor_parallel/layers.py:55-58, pipeline_parallel/utils.py:213)."""
    from apex_tpu.transformer.pipeline_parallel.utils import (
        calc_params_l2_norm,
        clip_grad_norm,
    )

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2)
    params = {
        "w_col": jnp.arange(8.0).reshape(2, 4),  # sharded over tp cols
        "ln": jnp.arange(3.0),                   # replicated
    }
    specs = {"w_col": P(None, "tp"), "ln": P()}
    true_norm = float(jnp.sqrt(sum(jnp.sum(x * x)
                                   for x in jax.tree.leaves(params))))

    def body(p):
        return calc_params_l2_norm(p, model_parallel_axes=("tp",),
                                   specs=specs)

    norm = jax.jit(shard_map(body, mesh=mesh, in_specs=(specs,), out_specs=P()))(
        params)
    np.testing.assert_allclose(float(norm), true_norm, rtol=1e-6)

    # without specs the replicated leaf is double-counted (documented
    # all-sharded assumption) — the dedup is what specs adds
    def body_nospecs(p):
        return calc_params_l2_norm(p, model_parallel_axes=("tp",))

    norm2 = shard_map(body_nospecs, mesh=mesh, in_specs=(specs,),
                      out_specs=P())(params)
    ln_sq = float(jnp.sum(params["ln"] ** 2))
    np.testing.assert_allclose(float(norm2) ** 2,
                               true_norm ** 2 + ln_sq, rtol=1e-5)

    # clip: scaled grads have exactly max_norm when over the limit
    def body_clip(p):
        clipped, n = clip_grad_norm(p, max_norm=1.0,
                                    model_parallel_axes=("tp",),
                                    specs=specs)
        return calc_params_l2_norm(clipped, ("tp",), specs), n

    cn, n = shard_map(body_clip, mesh=mesh, in_specs=(specs,),
                      out_specs=(P(), P()))(params)
    np.testing.assert_allclose(float(n), true_norm, rtol=1e-6)
    np.testing.assert_allclose(float(cn), 1.0, rtol=1e-4)


def test_hybrid_mesh_single_slice_fallback():
    """build_hybrid_mesh on slice-index-less devices (CPU simulation, or a
    one-slice pod) degrades to the ICI-only mesh with identical axes."""
    from apex_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh(tp=2, pp=2)
    assert mesh.axis_names == ("dp", "pp", "sp", "tp")
    assert mesh.shape["tp"] == 2 and mesh.shape["pp"] == 2
    assert mesh.shape["dp"] == 2  # 8 devices / (2*2)

    # the hybrid layout is exercised for real only on multi-slice hardware;
    # argument validation still applies here
    with pytest.raises(ValueError):
        build_hybrid_mesh(tp=3)

"""The composed-program lowering preflight must stay green: every bench
sweep configuration of the flagship train step and the ring-attention SP
step AOT-lower for TPU with their Mosaic kernels present (not the
reference fallbacks). Complements tests/test_tpu_lowering.py (single
kernels) at the program level bench.py actually times.

Runs in a subprocess: the preflight pins the process to the CPU platform
at import time, which must not leak into the pytest process (reviewer
find — collection-order-dependent backend state)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.slow  # ~70 s subprocess; the 5 s per-kernel guard
# (test_tpu_lowering.py) stays in the default tier
def test_preflight_lowering_passes():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "preflight_lowering.py")],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"preflight failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    assert "PREFLIGHT PASS" in proc.stdout

"""Channel-permutation search for 2:4 sparsity (ref permutation_lib.py +
permutation_search_kernels: permuting input channels before m4n2 pruning must
preserve strictly more magnitude on structured inputs)."""

import numpy as np
import jax.numpy as jnp
import pytest

from apex_tpu.contrib.sparsity.asp import ASP
from apex_tpu.contrib.sparsity.permutation import (
    invert_permutation,
    magnitude_after_2_4,
    permute_and_mask,
    search_permutation,
)


def _adversarial_matrix(rows=16, groups=4, seed=0):
    """Matrix whose large-magnitude columns are packed into the same aligned
    groups — the worst case for aligned 2:4 pruning, where a permutation that
    spreads them across groups recovers magnitude."""
    rng = np.random.default_rng(seed)
    c = groups * 4
    m = rng.normal(size=(rows, c)).astype(np.float32) * 0.01
    # columns 0..groups*2-1 (first half of the first `groups//2` groups
    # worth) get large magnitude, packed contiguously
    m[:, : 2 * groups] += rng.choice([-1.0, 1.0], size=(rows, 2 * groups)) * 5
    return m


def test_magnitude_after_2_4_matches_bruteforce():
    rng = np.random.default_rng(1)
    m = rng.normal(size=(8, 12)).astype(np.float32)
    total = 0.0
    for r in range(8):
        for g in range(3):
            block = np.abs(m[r, 4 * g : 4 * g + 4])
            total += np.sort(block)[-2:].sum()
    assert np.isclose(magnitude_after_2_4(m), total, rtol=1e-5)


def test_permutation_beats_aligned_pruning_on_adversarial_case():
    m = _adversarial_matrix()
    perm, base, best = search_permutation(m, escape_attempts=4)
    assert best > base * 1.05, (base, best)
    # the permutation actually achieves the reported score
    assert np.isclose(magnitude_after_2_4(m[:, perm]), best, rtol=1e-5)
    # and is a real permutation
    assert sorted(perm.tolist()) == list(range(m.shape[1]))


def test_invert_permutation_roundtrip():
    rng = np.random.default_rng(2)
    perm = rng.permutation(12)
    m = rng.normal(size=(3, 12))
    np.testing.assert_array_equal(m[:, perm][:, invert_permutation(perm)], m)


def test_permute_and_mask_unpermuted_layout_and_2of4_density():
    m = _adversarial_matrix()
    mask, perm, base, best = permute_and_mask(m, escape_attempts=4)
    assert mask.shape == m.shape
    # exactly half the entries survive (2 of every 4)
    assert mask.sum() == m.size // 2
    # magnitude kept by the permuted mask beats the aligned mask
    from apex_tpu.contrib.sparsity.sparse_masklib import create_mask

    aligned = np.asarray(create_mask(jnp.asarray(m), "m4n2_1d"))
    kept_perm = np.abs(m)[mask.astype(bool)].sum()
    kept_aligned = np.abs(m)[aligned.astype(bool)].sum()
    assert kept_perm > kept_aligned * 1.05
    # in the permuted domain the mask is aligned-group 2:4 structured
    mp = mask[:, perm].reshape(mask.shape[0], -1, 4)
    assert (mp.sum(axis=2) == 2).all()


def test_asp_allow_permutation_end_to_end():
    params = {"dense": {"kernel": jnp.asarray(_adversarial_matrix())},
              "bias": jnp.zeros((4,))}
    asp = ASP(allow_permutation=True, permutation_escape_attempts=2)
    masks = asp.compute_sparse_masks(params)
    assert masks["bias"] is None  # not whitelisted (1-D)
    pruned = ASP.apply_masks(params, masks)
    k = np.asarray(pruned["dense"]["kernel"])
    assert (k == 0).sum() == k.size // 2
    # keeps more magnitude than aligned ASP
    aligned = ASP().compute_sparse_masks(params)
    k_aligned = np.asarray(ASP.apply_masks(params, aligned)["dense"]["kernel"])
    assert np.abs(k).sum() > np.abs(k_aligned).sum() * 1.02

"""Collective-count regression guards for the compiled SPMD programs.

An accidental extra all-gather in a TP block or a psum that stops fusing
is a silent perf bug — the program stays correct and slower. These tests
compile the tp=2 GPT grad program on the virtual mesh and bound the
collective counts (loose bounds: XLA may legally fuse/split a few), plus
assert the *semantic* shape of Megatron-SP: it must replace TP-block
boundary all-reduces with all-gather (entry ``g``) / reduce-scatter
(exit ``ḡ``) pairs — their presence is the feature.

Measured at pin time (2 layers, tp=2, dp=4): 35 all-reduces plain
(TP psums + per-param dp grad psums from the shard_map transpose +
loss replication); 33 AR + 8 AG + 7 RS under megatron_sp.
"""

import dataclasses
import re

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    replicate_loss,
)
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)

BASE = GPTConfig(vocab_size=256, max_seq=64, hidden=128, num_layers=2,
                 num_heads=2, dtype=jnp.bfloat16)

MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")


def _compiled_text(megatron_sp: bool, overlap_comm: bool = False) -> str:
    """Compiled flagship tp=2 grad-program HLO on the virtual mesh."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=2, pp=1, sp=1, dp=4)
    cfg = dataclasses.replace(BASE, megatron_sp=megatron_sp,
                              overlap_comm=overlap_comm)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jnp.zeros((4, 64), jnp.int32)

    def loss(p, t, y):
        def body(p, a, b):
            return replicate_loss(gpt_loss(p, a, b, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(gpt_param_specs(cfg), P("dp"), P("dp")),
            out_specs=P())(p, t, y)

    return jax.jit(jax.grad(loss)).lower(params, tok, tok).compile().as_text()


def _counts(megatron_sp: bool):
    txt = _compiled_text(megatron_sp)
    return {k: len(re.findall(k, txt)) for k in
            ("all-reduce", "all-gather", "reduce-scatter")}


def test_tp_program_collective_budget():
    c = _counts(megatron_sp=False)
    assert c["all-reduce"] <= 42, c
    # plain TP has no sequence resharding: gathers/scatters would mean a
    # sharding annotation leaked
    assert c["all-gather"] == 0 and c["reduce-scatter"] == 0, c


def test_moe_dispatch_rides_all_to_all():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=2, pp=1, sp=1, dp=4)
    cfg = dataclasses.replace(BASE, num_experts=4, moe_top_k=2)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jnp.zeros((4, 64), jnp.int32)

    def loss(p, t, y):
        def body(p, a, b):
            return replicate_loss(gpt_loss(p, a, b, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(gpt_param_specs(cfg), P("dp"), P("dp")),
            out_specs=P())(p, t, y)

    txt = jax.jit(jax.grad(loss)).lower(params, tok, tok).compile().as_text()
    c = {k: len(re.findall(k, txt)) for k in
         ("all-gather", "all-to-all")}
    # expert dispatch/combine must be all_to_all over the ep(=dp) axis —
    # a fallback to gather-everything would be a silent traffic blow-up
    assert c["all-to-all"] >= 4, c
    assert c["all-to-all"] <= 44, c
    assert c["all-gather"] == 0, c


def test_megatron_sp_uses_gather_scatter_pairs():
    c = _counts(megatron_sp=True)
    # the feature itself: TP-block entry all-gathers + exit reduce-scatters
    assert c["all-gather"] >= 4, c
    assert c["reduce-scatter"] >= 4, c
    assert c["all-gather"] <= 12 and c["reduce-scatter"] <= 11, c
    assert c["all-reduce"] <= 40, c


# ---------------------------------------------------------------------------
# bytes-on-wire: counts guard the program SHAPE; the comm subsystem's claim
# is about BYTES, so it is asserted from the same compiled-HLO source of
# truth via apex_tpu.comm.accounting's ring-model pricer.


def _ddp_grad_program(compression, allreduce_always_fp32):
    """Compiled dp=8 GPT grad+allreduce step (the GPT-2 DP fixture)."""
    from apex_tpu.comm import collective_report
    from apex_tpu.parallel import DistributedDataParallel

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    cfg = dataclasses.replace(BASE, dtype=jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jnp.zeros((8, 64), jnp.int32)
    ddp = DistributedDataParallel(
        compression=compression,
        allreduce_always_fp32=allreduce_always_fp32)

    def step(p, t, y):
        g = jax.grad(lambda p: gpt_loss(p, t, y, cfg))(ddp.replicate(p))
        return ddp.average_gradients(g)

    specs = jax.tree_util.tree_map(lambda _: P(), params)
    compiled = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(specs, P("dp"), P("dp")),
        out_specs=specs, check_vma=False,
    )).lower(params, tok, tok).compile()
    return collective_report(compiled)


def assert_overlapped(hlo, min_hidden: int = 1):
    """The comm/compute-overlap acceptance gate, from the compiled HLO (the
    repo's prove-it-from-the-program methodology — the chip tunnel is too
    unreliable to prove overlap with a profile).

    On a SCHEDULED module (TPU: async ``collective-permute-start``/``-done``
    pairs) this demands ≥1 pair with a ``dot`` scheduled inside the
    start→done window — execution-order proof that the hop travels behind a
    GEMM. On pre-schedule/CPU modules (synchronous ``collective-permute``)
    it demands hops with data-INDEPENDENT dots — the eligibility a
    latency-hiding scheduler needs; a monolithic collective→matmul chain
    has no permutes at all and fails immediately. Returns the
    :class:`~apex_tpu.comm.OverlapReport` for further assertions."""
    from apex_tpu.comm import overlap_report

    rep = overlap_report(hlo)
    assert rep.permutes > 0, f"no collective-permute rings in program: {rep}"
    assert rep.hidden >= min_hidden, rep
    if rep.async_pairs:  # scheduled module: the window proof must hold
        assert rep.async_hidden >= 1, rep
    return rep


@pytest.mark.skipif(not MESH_OK, reason="needs jax.shard_map (graft jax)")
@pytest.mark.parametrize("megatron_sp", [False, True])
def test_flagship_overlap_comm_decomposed_and_proven(megatron_sp):
    """overlap_comm=True on the flagship tp=2 program (plain TP and
    Megatron-SP): the TP-boundary collectives must actually decompose into
    ppermute rings (the monolithic op counts DROP, permutes appear) and
    the rings must be overlap-eligible/proven per assert_overlapped."""
    from apex_tpu.comm import collective_report

    txt_off = _compiled_text(megatron_sp)
    txt_on = _compiled_text(megatron_sp, overlap_comm=True)
    off = collective_report(txt_off)
    on = collective_report(txt_on)
    # the decomposition happened: permute rings replace monolithic ops
    assert off.counts["collective-permute"] == 0, off
    assert on.counts["collective-permute"] >= 4, on
    if megatron_sp:
        # the SP entry/exit all-gather+reduce-scatter pairs became rings
        # (the embedding exit / LM-head entry keep their monolithic ops)
        assert on.counts["all-gather"] < off.counts["all-gather"], (on, off)
        assert on.counts["reduce-scatter"] < off.counts["reduce-scatter"], \
            (on, off)
    else:
        # the row-parallel exit psums became rings
        assert on.counts["all-reduce"] < off.counts["all-reduce"], (on, off)
    rep = assert_overlapped(txt_on, min_hidden=2)
    # the overwhelming share of ring traffic must be hideable
    assert rep.hidden_fraction >= 0.5, rep


@pytest.mark.skipif(not MESH_OK, reason="needs jax.shard_map (graft jax)")
def test_int4_allreduce_wire_byte_reduction_and_model_agreement():
    """The sub-8-bit acceptance gate: the 4-bit EF allreduce must move
    >= 6.5x fewer bytes than fp32 on the same model (theory:
    8 / (1 + 8/group) ~ 7.5x at group 128 — nibble-packed codes at
    0.5 B/elem plus the fp32 scale sidecar), asserted from the compiled
    HLO. The packed-payload wire MODEL must agree with the HLO pricer to
    the byte on a single flat-buffer program."""
    from apex_tpu.comm import (
        CompressionConfig,
        allreduce_wire_bytes,
        collective_report,
        compressed_allreduce,
    )

    cfg = CompressionConfig(policy="int4_ef", block_size=128,
                            min_elements=128)
    fp32 = _ddp_grad_program(None, allreduce_always_fp32=True)
    # the DDP fixture threads no EF state; the wire is policy-identical
    # (EF only adds local element-wise math), so the program ratio is
    # measured on plain int4 and the EF program is priced below
    int4 = _ddp_grad_program(
        CompressionConfig(policy="int4", block_size=128, min_elements=128),
        allreduce_always_fp32=False)
    assert fp32.wire_bytes > 0 and int4.wire_bytes > 0, (fp32, int4)
    # the compressed program really rides the two-pass decomposition
    assert int4.counts["all-to-all"] >= 2, int4
    assert int4.counts["all-gather"] >= 2, int4
    ratio = fp32.wire_bytes / int4.wire_bytes
    assert ratio >= 6.5, (ratio, fp32, int4)

    # model<->HLO agreement on one flat buffer: the pricer reads u8
    # packed codes + f32 scales off the program XLA emitted; the model
    # predicts the same bytes from (n, config) alone
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    n = 8192

    def body(flat, r):
        out, r2 = compressed_allreduce(flat, "dp", cfg,
                                       residual=r.reshape(-1))
        return out, r2.reshape(r.shape)

    from jax.sharding import PartitionSpec as P2
    compiled = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P2(), P2("dp")),
        out_specs=(P2(), P2("dp")), check_vma=False,
    )).lower(jnp.zeros((n,)), jnp.zeros((8, n))).compile()
    priced = collective_report(compiled).wire_bytes
    modeled = allreduce_wire_bytes(n, 4, 8, cfg)
    assert priced == pytest.approx(modeled), (priced, modeled)
    # and the EF program itself clears the gate vs a same-shape fp32 psum
    psum = jax.jit(jax.shard_map(
        lambda flat: jax.lax.psum(flat, "dp"), mesh=mesh, in_specs=P2(),
        out_specs=P2(), check_vma=False,
    )).lower(jnp.zeros((n,))).compile()
    fp32_flat = collective_report(psum).wire_bytes
    assert fp32_flat / priced >= 6.5, (fp32_flat, priced)


def test_int8_allreduce_wire_byte_reduction():
    """The comm subsystem's acceptance gate: int8 gradient allreduce must
    move >= 3.5x fewer bytes than the fp32 allreduce on the same model
    (theory: 4 / (1 + 4/block) ~ 3.94x at block 256; the scales' fp32
    sidecar is the only overhead)."""
    from apex_tpu.comm import CompressionConfig

    fp32 = _ddp_grad_program(None, allreduce_always_fp32=True)
    int8 = _ddp_grad_program(
        CompressionConfig(policy="int8", block_size=256, min_elements=256),
        allreduce_always_fp32=False)
    assert fp32.wire_bytes > 0 and int8.wire_bytes > 0, (fp32, int8)
    # the compressed program really rides the two-pass decomposition
    assert int8.counts["all-to-all"] >= 2, int8
    assert int8.counts["all-gather"] >= 2, int8
    ratio = fp32.wire_bytes / int8.wire_bytes
    assert ratio >= 3.5, (ratio, fp32, int8)

"""apex_tpu.serve.adapters — per-tenant paged LoRA serving.

The acceptance oracles from the PR-16 issue, all stock-jax-safe:

* **aid=0 transparency** — an adapter-ENABLED engine serving base-only
  traffic streams BITWISE what the pre-adapter engine streams (greedy,
  same-key sampled, speculative and int8-KV included): slot 0 of the
  pool is all-zeros, so the gathered BGMV delta is exact zero, not
  epsilon;
* **merged-weight oracle** — a nonzero adapter's output matches the
  offline dense model ``W + B@A * scale`` (logit tolerance through the
  cold flash-prefill, stream equality through the engine);
* **compile-count gate** — adapters ride the SAME compiled program per
  jit site: one chunked prefill + one decode, loads/swaps retrace
  nothing (``analyze.adapters`` pins the donation side);
* **registry discipline** — BlockAllocator semantics for weights:
  refcounts pin residents against eviction, LRU evicts idle under
  pressure, a wholly-pinned pool refuses loudly, and a randomized chaos
  loop reconciles refcounts exactly (zero leaks);
* **fleet mix** — workers advertise resident adapters + quant mode in
  membership heartbeats, the router lands adapter-bound handoffs on
  warm hosts (cold fallback emits ``adapter_load``), and unknown
  adapters shed at admission — never a crash.

The mid-decode migration row (adapter binding survives a worker death
bitwise) lives with its chaos siblings in ``tests/test_serve_chaos.py``.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor.events import EventLog
from apex_tpu.monitor.regress import classify_metric
from apex_tpu.serve import (
    ADAPTER_TARGETS,
    AdapterRegistry,
    ClusterConfig,
    InferenceEngine,
    KVCacheConfig,
    Request,
    SamplingConfig,
    ServeCluster,
    ServeConfig,
    adapter_pool_bytes,
    init_adapter_pool,
    init_kv_cache,
    lora_delta,
    make_adapter_weights,
    merge_adapter_params,
    write_adapter,
)
from apex_tpu.serve.decode import ensure_dense_ffn, gpt_prefill_chunk
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

CFG = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)
KV = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                   num_blocks=8, block_size=8, dtype=jnp.float32)

REQS = [
    Request("a", [1, 2, 3, 4, 5], max_new_tokens=6),
    Request("b", [7, 8, 9], max_new_tokens=4),
    Request("c", list(range(10, 22)), max_new_tokens=5),
]

W1 = make_adapter_weights(CFG, 4, jax.random.PRNGKey(42), std=0.05)
W2 = make_adapter_weights(CFG, 4, jax.random.PRNGKey(43), std=0.05)

SAMPLED = SamplingConfig(temperature=0.8, top_k=20, top_p=0.9)


def _engine(sampling=None, **kw):
    scfg = ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                       sampling=sampling or SamplingConfig(), **kw)
    return InferenceEngine(PARAMS, CFG, scfg)


def _lora_engine(sampling=None, rank=4, max_adapters=3, **kw):
    return _engine(sampling=sampling, lora_rank=rank,
                   max_adapters=max_adapters, **kw)


# ---------------------------------------------------------------------------
# AdapterPool: shapes, the zero base slot, scale folding


def test_pool_shapes_and_reserved_base_slot():
    pool = init_adapter_pool(CFG, 4, 3)
    assert set(pool) == {f"{t}_{ab}" for t in ADAPTER_TARGETS
                         for ab in ("a", "b")}
    h, f = CFG.hidden, CFG.ffn_hidden
    assert pool["qkv_a"].shape == (CFG.num_layers, 4, h, 4)
    assert pool["qkv_b"].shape == (CFG.num_layers, 4, 4, 3 * h)
    assert pool["fc1_b"].shape == (CFG.num_layers, 4, 4, f)
    assert pool["fc2_a"].shape == (CFG.num_layers, 4, f, 4)
    # slot axis = max_adapters + 1: slot 0 is the base model, all-zero
    for leaf in pool.values():
        assert not np.asarray(leaf[:, 0]).any()
    assert adapter_pool_bytes(CFG, 4, 3) == sum(
        np.asarray(v).nbytes for v in pool.values())


def test_write_adapter_folds_scale_and_guards_slot0():
    pool = init_adapter_pool(CFG, 4, 2)
    pool = write_adapter(pool, 1, W1, scale=2.0)
    np.testing.assert_array_equal(pool["qkv_a"][:, 1], W1["qkv_a"])
    np.testing.assert_array_equal(pool["qkv_b"][:, 1],
                                  np.asarray(W1["qkv_b"]) * 2.0)
    # slot 0 (base) untouched and refused
    assert not np.asarray(pool["qkv_b"][:, 0]).any()
    with pytest.raises(ValueError, match="slot 0"):
        write_adapter(pool, 0, W1)
    with pytest.raises(ValueError):
        write_adapter(pool, 3, W1)  # beyond max_adapters


def test_lora_delta_slot0_is_exact_zero():
    pool = init_adapter_pool(CFG, 4, 2)
    pool = write_adapter(pool, 1, W1, scale=1.5)
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 3, CFG.hidden))
    layer = jax.tree_util.tree_map(lambda v: v[0], pool)
    zero = lora_delta(x, layer["qkv_a"], layer["qkv_b"],
                      jnp.zeros((2,), jnp.int32))
    # EXACT zero — the aid=0 bitwise gate rests on this, not on allclose
    assert not np.asarray(zero).any()
    got = lora_delta(x, layer["qkv_a"], layer["qkv_b"],
                     jnp.array([1, 0], jnp.int32))
    want = (x[0] @ W1["qkv_a"][0]) @ (np.asarray(W1["qkv_b"][0]) * 1.5)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    assert not np.asarray(got[1]).any()


def test_merged_weight_oracle_logits_through_cold_prefill():
    """The paged forward with a nonzero adapter == the dense merged
    model ``W + B@A*scale`` through the SAME prefill — logit level."""
    merged = merge_adapter_params(PARAMS, W1, scale=2.0)
    pool = write_adapter(init_adapter_pool(CFG, 4, 2), 1, W1, scale=2.0)
    toks = jnp.zeros((8,), jnp.int32).at[:6].set(
        jnp.arange(1, 7, dtype=jnp.int32))
    row = jnp.arange(2, dtype=jnp.int32)
    _, logits_adapter = gpt_prefill_chunk(
        PARAMS, toks, jnp.int32(0), jnp.int32(6), init_kv_cache(KV),
        row, CFG, KV, adapters=pool, adapter_id=1)
    _, logits_merged = gpt_prefill_chunk(
        merged, toks, jnp.int32(0), jnp.int32(6), init_kv_cache(KV),
        row, CFG, KV)
    np.testing.assert_allclose(np.asarray(logits_adapter),
                               np.asarray(logits_merged),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# AdapterRegistry: BlockAllocator discipline for weights


def test_registry_load_acquire_release_cycle():
    reg = AdapterRegistry(2)
    assert reg.load("t1") == 1          # deterministic LIFO: slot 1 first
    assert reg.load("t2") == 2
    assert reg.load("t1") == 1          # idempotent refresh
    assert reg.free_count == 0 and reg.resident_count == 2
    assert reg.acquire("t1") == 1
    assert reg.refcount("t1") == 1
    assert reg.acquire("nope") is None  # miss, counted
    reg.release("t1")
    assert reg.refcount("t1") == 0
    c = reg.counters()
    assert c["hits_total"] == 1 and c["misses_total"] == 1
    assert c["loads_total"] == 3


def test_registry_lru_evicts_idle_under_pressure():
    reg = AdapterRegistry(2)
    reg.load("t1")
    reg.load("t2")
    reg.acquire("t2")                   # pin t2: only t1 is evictable
    slot = reg.load("t3")               # pressure: evicts idle t1
    assert slot == 1 and reg.lookup("t1") is None
    assert reg.evictions_total == 1
    reg.acquire("t3")
    with pytest.raises(RuntimeError, match="pinned"):
        reg.load("t4")                  # everything pinned: loud refusal
    reg.release("t2")
    assert reg.load("t4") == 2          # t2 idle now — LRU victim
    assert reg.lookup("t2") is None


def test_registry_unload_guards():
    reg = AdapterRegistry(2)
    reg.load("t1")
    reg.acquire("t1")
    with pytest.raises(RuntimeError, match="reference"):
        reg.unload("t1")                # pinned: refuse
    reg.release("t1")
    reg.unload("t1")
    assert reg.free_count == 2
    with pytest.raises(KeyError):
        reg.unload("t1")                # not resident anymore
    with pytest.raises(RuntimeError):
        reg.release("t1")               # release of non-resident


def test_registry_chaos_refcounts_reconcile_exactly():
    """Satellite: randomized load/unload/acquire/release/evict against a
    shadow model, ``assert_consistent`` EVERY step — the chaos-allocator
    pattern from test_serve_prefix applied to adapter slots. Final
    teardown drains every ref and unloads every resident: zero leaks."""
    rng = random.Random(7)
    reg = AdapterRegistry(4)
    names = [f"t{i}" for i in range(8)]
    pins = {}                           # name -> outstanding refs (shadow)
    for _ in range(400):
        op = rng.choice(("load", "unload", "acquire", "release"))
        name = rng.choice(names)
        if op == "load":
            try:
                slot = reg.load(name)
                assert 1 <= slot <= 4
            except RuntimeError:
                # only legal when all 4 residents are pinned
                assert len([n for n, r in pins.items() if r > 0]) >= 4
        elif op == "unload":
            if reg.lookup(name) is not None and pins.get(name, 0) == 0:
                reg.unload(name)
            else:
                with pytest.raises((KeyError, RuntimeError)):
                    reg.unload(name)
        elif op == "acquire":
            slot = reg.acquire(name)
            if slot is not None:
                pins[name] = pins.get(name, 0) + 1
        else:
            if pins.get(name, 0) > 0:
                reg.release(name)
                pins[name] -= 1
            elif reg.lookup(name) is not None:
                with pytest.raises(RuntimeError):
                    reg.release(name)
        # evicted names cannot carry refs — their pins must be zero
        for n, r in pins.items():
            if r > 0:
                assert reg.lookup(n) is not None, n
                assert reg.refcount(n) == r, n
        reg.assert_consistent()
    for n, r in list(pins.items()):
        for _ in range(r):
            reg.release(n)
        pins[n] = 0
    for n in list(reg.resident()):
        reg.unload(n)
    reg.assert_consistent()
    assert reg.resident_count == 0 and reg.free_count == 4


# ---------------------------------------------------------------------------
# ACCEPTANCE: aid=0 transparency — bitwise vs the pre-adapter engine


@pytest.mark.parametrize("sampling,extra", [
    (SamplingConfig(), {}),
    (SAMPLED, {}),
    (SamplingConfig(), {"spec_k": 4}),
    (SamplingConfig(), {"kv_quant": "int8"}),
    (SAMPLED, {"kv_quant": "int8"}),
], ids=["greedy", "sampled", "spec_k", "int8_kv", "sampled_int8"])
def test_aid0_streams_bitwise_equal_pre_adapter_engine(sampling, extra):
    """An adapter-ENABLED engine serving base traffic is bitwise the
    pre-adapter engine — slot 0's zero delta is exact, and the lora
    program set draws from the same position-keyed streams."""
    reqs = REQS + [Request("rep", ([5, 6, 7, 8] * 4)[:14],
                           max_new_tokens=8)]
    base = _engine(sampling=sampling, **extra).run(reqs)
    lora = _lora_engine(sampling=sampling, **extra).run(reqs)
    assert base == lora


def test_compile_counts_unchanged_with_adapters_enabled():
    """One chunked prefill + one decode, with adapters enabled AND in
    use; loading/swapping adapters compiles nothing new."""
    eng = _lora_engine()
    eng.load_adapter("t1", W1, scale=2.0)
    eng.run([Request("a", [1, 2, 3, 4, 5], max_new_tokens=6,
                     adapter="t1")] + REQS[1:])
    eng.load_adapter("t2", W2)          # swap after warmup
    eng.run([Request("d", [4, 4, 2], max_new_tokens=3, adapter="t2")])
    counts = eng.compile_counts()
    if counts["decode"] is not None:
        assert counts == {"chunk_prefill": 1, "decode": 1, "verify": 0,
                          "cow_copy": 0}


# ---------------------------------------------------------------------------
# ACCEPTANCE: nonzero adapters — the merged-weight engine oracle


@pytest.mark.parametrize("sampling", [SamplingConfig(), SAMPLED],
                         ids=["greedy", "sampled"])
def test_adapter_stream_matches_merged_weight_engine(sampling):
    eng = _lora_engine(sampling=sampling)
    eng.load_adapter("t1", W1, scale=2.0)
    reqs = [Request("a", [1, 2, 3, 4, 5], max_new_tokens=6,
                    adapter="t1")]
    got = eng.run(reqs)["a"]
    merged_eng = InferenceEngine(
        merge_adapter_params(PARAMS, W1, scale=2.0), CFG,
        ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                    sampling=sampling))
    want = merged_eng.run([Request("a", [1, 2, 3, 4, 5],
                                   max_new_tokens=6)])["a"]
    assert got == want


def test_multi_tenant_batch_no_cross_contamination():
    """t1 + t2 + base interleaved in ONE continuous batch: every stream
    equals its own single-tenant oracle — the per-slot adapter-id table
    keeps deltas tenant-local."""
    eng = _lora_engine()
    eng.load_adapter("t1", W1, scale=2.0)
    eng.load_adapter("t2", W2)
    mixed = [Request("a", [1, 2, 3, 4, 5], max_new_tokens=6,
                     adapter="t1"),
             Request("b", [7, 8, 9], max_new_tokens=4, adapter="t2"),
             Request("c", list(range(10, 22)), max_new_tokens=5)]
    got = eng.run(mixed)
    for uid, w, s in (("a", W1, 2.0), ("b", W2, 1.0)):
        oracle = InferenceEngine(
            merge_adapter_params(PARAMS, w, scale=s), CFG,
            ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                        sampling=SamplingConfig()))
        req = next(r for r in mixed if r.uid == uid)
        want = oracle.run([Request(uid, list(req.tokens),
                                   max_new_tokens=req.max_new_tokens)])
        assert got[uid] == want[uid], uid
    assert got["c"] == _engine().run([mixed[2]])["c"]


# ---------------------------------------------------------------------------
# admission: unknown adapters shed (or raise loudly), never corrupt


def test_unknown_adapter_sheds_via_on_reject():
    shed = []
    eng = InferenceEngine(
        PARAMS, CFG,
        ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                    sampling=SamplingConfig(), lora_rank=4,
                    max_adapters=2),
        on_reject=lambda req, info: shed.append((req.uid,
                                                 info["reason"])))
    out = eng.run([Request("x", [1, 2], max_new_tokens=2,
                           adapter="nope"),
                   Request("y", [3, 4], max_new_tokens=2)])
    assert shed == [("x", "unknown_adapter")]
    assert "y" in out and "x" not in out
    assert eng.stats()["rejected"] == 1


def test_all_requests_shed_drains_cleanly():
    # the ONLY pending request sheds at admission: the queue moving is
    # progress, so run() drains to {} instead of misreading the step as
    # a pool stall (regression: IndexError on the emptied deque)
    shed = []
    eng = InferenceEngine(
        PARAMS, CFG,
        ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                    lora_rank=4, max_adapters=2),
        on_reject=lambda req, info: shed.append((req.uid,
                                                 info["reason"])))
    out = eng.run([Request("x", [1, 2, 3], max_new_tokens=4,
                           adapter="nope")])
    assert out == {}
    assert shed == [("x", "unknown_adapter")]
    assert eng.stats()["rejected"] == 1


def test_unknown_adapter_without_hook_raises():
    eng = _lora_engine()
    with pytest.raises(KeyError, match="unknown adapter"):
        eng.run([Request("x", [1, 2], max_new_tokens=2, adapter="nope")])


def test_adapter_request_on_lora_free_engine_refused_at_submit():
    eng = _engine()
    with pytest.raises(ValueError, match="lora_rank"):
        eng.submit(Request("x", [1, 2], max_new_tokens=2, adapter="t1"))


def test_serve_config_lora_validation():
    with pytest.raises(ValueError, match="max_adapters"):
        ServeConfig(lora_rank=4).validate()
    with pytest.raises(ValueError, match="lora_rank"):
        ServeConfig(max_adapters=2).validate()


# ---------------------------------------------------------------------------
# engine lifecycle: load/unload events, stats, eviction under pressure


def test_engine_adapter_lifecycle_events_and_stats():
    events = EventLog(keep=True)
    eng = InferenceEngine(
        PARAMS, CFG,
        ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                    sampling=SamplingConfig(), lora_rank=4,
                    max_adapters=2),
        events=events)
    eng.load_adapter("t1", W1, scale=2.0)
    eng.run([Request("a", [1, 2, 3], max_new_tokens=3, adapter="t1")])
    eng.load_adapter("t2", W2)
    eng.load_adapter("t3", W1)          # pool pressure: evicts idle LRU
    eng.unload_adapter("t3")
    st = eng.stats()
    assert st["adapters"]["rank"] == 4
    assert st["adapters"]["max_adapters"] == 2
    assert st["adapters"]["resident"] == 1
    assert st["adapters"]["pool_bytes"] == adapter_pool_bytes(CFG, 4, 2)
    assert st["adapter_evictions"] == 1
    assert st["adapter_hit_rate"] == 1.0
    assert st["adapter_load_ms"] >= 0.0
    evs = [r for r in events.records if r.get("kind") == "event"]
    names = [r["event"] for r in evs]
    assert names.count("adapter_load") == 3
    assert names.count("adapter_unload") == 1
    from apex_tpu.monitor.registry import MetricsRegistry

    reg = MetricsRegistry()
    eng.collect_registry(reg)
    by = {s["name"]: s["value"] for s in reg.snapshot()["series"]}
    assert by["adapters_resident"] == 1.0
    assert by["adapter_evictions_total"] == 1.0


def test_engine_decoding_adapter_pinned_against_eviction():
    """While a stream decodes on an adapter, loading new adapters under
    pool pressure must not evict it — load refuses instead."""
    eng = _lora_engine(max_adapters=1)
    eng.load_adapter("t1", W1)
    eng.submit(Request("a", [1, 2, 3], max_new_tokens=4, adapter="t1"))
    eng.step()                          # prefill begins: t1 is pinned
    with pytest.raises(RuntimeError, match="pinned"):
        eng.load_adapter("t2", W2)
    while eng.active:
        eng.step()
    eng.load_adapter("t2", W2)          # retired: t1 idle, evictable
    assert eng.adapters.lookup("t1") is None


# ---------------------------------------------------------------------------
# satellite: the ONE MoE serving refusal, pinned on both entry paths


def test_moe_refusal_is_single_sourced():
    moe_cfg = GPTConfig(vocab_size=97, max_seq=64, hidden=32,
                        num_layers=2, num_heads=4, dtype=jnp.float32,
                        fused_loss=False, num_experts=2, moe_top_k=1)
    with pytest.raises(NotImplementedError, match="ROADMAP item 5a"):
        ensure_dense_ffn(moe_cfg.num_experts)
    # path 1: the paged forward's config check
    with pytest.raises(NotImplementedError, match="ROADMAP item 5a"):
        gpt_prefill_chunk(PARAMS, jnp.zeros((8,), jnp.int32),
                          jnp.int32(0), jnp.int32(4), init_kv_cache(KV),
                          jnp.arange(2, dtype=jnp.int32), moe_cfg, KV)
    # path 2: the engine constructor
    with pytest.raises(NotImplementedError, match="ROADMAP item 5a"):
        InferenceEngine(PARAMS, moe_cfg, ServeConfig(
            num_slots=3, block_size=8, prefill_chunk=8,
            sampling=SamplingConfig()))


# ---------------------------------------------------------------------------
# satellite: regress polarity of the new headline fields


def test_regress_polarity_covers_adapter_fields():
    assert classify_metric("adapter_hit_rate") == "higher"
    assert classify_metric("adapters.adapter_hit_rate") == "higher"
    assert classify_metric("adapter_warm_dispatch_rate") == "higher"
    assert classify_metric("adapter_load_ms") == "lower"
    assert classify_metric("adapter_load_ms_total") == "lower"
    assert classify_metric("adapter_evictions") == "lower"
    assert classify_metric("adapters.adapter_evictions") == "lower"
    # the generic hit_rate fragment must not have flipped
    assert classify_metric("prefix_hit_rate") == "higher"


# ---------------------------------------------------------------------------
# cluster: fleet-mix routing, advertisement, catalog, cold loads


def _cluster(serve, **kw):
    return ServeCluster(PARAMS, CFG, ClusterConfig(
        n_prefill=1, n_decode=2, serve=serve, **kw))


def _scfg(**kw):
    return ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                       sampling=SamplingConfig(), **kw)


def test_cluster_aid0_bitwise_vs_pre_adapter_cluster():
    base = _cluster(_scfg()).run(REQS, max_steps=20000)
    lora = _cluster(_scfg(lora_rank=4, max_adapters=3)).run(
        REQS, max_steps=20000)
    assert base == lora


def test_cluster_adapter_streams_match_single_engine():
    areqs = [Request("a", [1, 2, 3, 4, 5], max_new_tokens=6,
                     adapter="t1"),
             Request("b", [7, 8, 9], max_new_tokens=4, adapter="t2"),
             Request("c", list(range(10, 22)), max_new_tokens=5)]
    cl = _cluster(_scfg(lora_rank=4, max_adapters=3))
    cl.load_adapter("t1", W1, scale=2.0)
    cl.load_adapter("t2", W2)
    got = cl.run(areqs, max_steps=20000)
    eng = _lora_engine()
    eng.load_adapter("t1", W1, scale=2.0)
    eng.load_adapter("t2", W2)
    assert got == eng.run(areqs)
    assert cl.adapter_catalog() == ["t1", "t2"]


def test_cluster_membership_advertises_adapters_and_quant():
    cl = _cluster(_scfg(lora_rank=4, max_adapters=3, kv_quant="int8"))
    cl.load_adapter("t1", W1)
    cl.run([Request("a", [1, 2, 3], max_new_tokens=3, adapter="t1")],
           max_steps=20000)
    workers = cl.membership.stats()["workers"]
    # prefill hosts eager-load the catalog; the decode host that served
    # "a" cold-loaded t1 and re-advertised in its next heartbeat
    assert all(w["quant"] == "int8" for w in workers.values())
    assert any("t1" in w["adapters"] for n, w in workers.items()
               if n.startswith("prefill"))
    assert any("t1" in w["adapters"] for n, w in workers.items()
               if n.startswith("decode"))


def test_cluster_unknown_adapter_sheds_at_submit():
    cl = _cluster(_scfg(lora_rank=4, max_adapters=3))
    cl.load_adapter("t1", W1)
    out = cl.run([Request("x", [1, 2], max_new_tokens=2,
                          adapter="nope"),
                  Request("y", [3, 4], max_new_tokens=2, adapter="t1")],
                 max_steps=20000)
    assert "x" not in out and "y" in out
    assert cl.shed["x"].reason == "unknown_adapter"


def test_cluster_steady_state_dispatch_is_adapter_warm():
    """ACCEPTANCE: with one hot adapter and two decode hosts, ≥90% of
    steady-state adapter-bound handoffs land adapter-warm (the first
    placement per host is the unavoidable cold load)."""
    cl = _cluster(_scfg(lora_rank=4, max_adapters=3))
    cl.load_adapter("t1", W1)
    many = [Request(f"r{i}", [1 + i % 9, 2, 3], max_new_tokens=3,
                    adapter="t1") for i in range(12)]
    out = cl.run(many, max_steps=40000)
    assert len(out) == 12
    st = cl.stats()
    assert st["adapter_warm_dispatch_rate"] >= 0.9
    assert st["adapters"]["warm_dispatches"] >= 10
    # cold loads happened through the explicit lifecycle (catalog pulls)
    assert st["adapters"]["catalog_loads"] >= 1
    assert st["adapter_hit_rate"] is not None


def test_cluster_adapter_lifecycle_event_on_cold_load():
    events = EventLog(keep=True)
    cl = ServeCluster(PARAMS, CFG, ClusterConfig(
        n_prefill=1, n_decode=2,
        serve=_scfg(lora_rank=4, max_adapters=3)), events=events)
    cl.load_adapter("t1", W1)
    cl.run([Request("a", [1, 2, 3], max_new_tokens=3, adapter="t1")],
           max_steps=20000)
    evs = [r for r in events.records if r.get("kind") == "event"]
    # at least the prefill-eager load and the decode cold load
    assert sum(1 for r in evs if r["event"] == "adapter_load") >= 2


def test_cluster_load_adapter_refused_when_lora_disabled():
    cl = _cluster(_scfg())
    with pytest.raises(RuntimeError, match="lora_rank"):
        cl.load_adapter("t1", W1)

"""Test configuration: force an 8-device virtual CPU platform BEFORE jax init.

This mirrors the reference's multi-node-without-a-cluster strategy (SURVEY.md
§4): the same mesh code that runs on a v5e-8 slice runs here on 8 virtual CPU
devices, so every distributed test (DDP, SyncBN, TP, PP, ring attention)
executes real collectives in-process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pathlib  # noqa: E402

import jax  # noqa: E402

# The image's sitecustomize pins JAX_PLATFORMS to the one-chip 'axon' TPU
# tunnel at interpreter startup; the config flag takes precedence over it.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is compile-bound on this box
# (hundreds of small shard_map programs), and the cache is keyed by HLO
# hash, so re-runs of unchanged tests skip XLA entirely. min_entry_size
# -1 is required for entries to be written on the CPU backend.
_CACHE_DIR = pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"
jax.config.update("jax_compilation_cache_dir", str(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (the full-coverage suite)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy compile-bound test excluded from the default fast "
        "suite; enable with --runslow or RUN_SLOW=1")


def pytest_collection_modifyitems(config, items):
    """Default run = fast subset (the ref's L0 sanity tier); --runslow or
    RUN_SLOW=1 = full cross-product (the ref's L1 nightly tier)."""
    if config.getoption("--runslow") or os.environ.get("RUN_SLOW") == "1":
        return
    skip = pytest.mark.skip(reason="slow: use --runslow or RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def mesh8():
    """A dp=8 mesh over the 8 virtual devices."""
    from apex_tpu.parallel.mesh import build_mesh

    return build_mesh(tp=1, pp=1, sp=1)


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    yield
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()

"""apex_tpu.comm unit tests — the int8 codec, the EF state round-trip and
the bytes-on-wire accounting, all mesh-free (the collective-level tests
live in tests/test_comm_mesh.py; the wire-byte regression gate in
tests/test_collective_counts.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.comm import (
    CompressionConfig,
    collective_report,
    dequantize_blockwise,
    init_error_feedback,
    quantization_error,
    quantize_blockwise,
)
from apex_tpu.comm import error_feedback as ef


# ---------------------------------------------------------------------------
# codec

def test_quantize_roundtrip_half_step_bound():
    """|x - dq(q(x))| <= scale/2 per element, scale = block absmax/127."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4096,))
    q, s = quantize_blockwise(x, 256)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert q.shape == (4096,) and s.shape == (16,)
    y = dequantize_blockwise(q, s, 256)
    err = np.abs(np.asarray(y) - np.asarray(x)).reshape(16, 256)
    step = np.abs(np.asarray(x)).reshape(16, 256).max(1) / 127.0
    assert (err <= step[:, None] * 0.5 + 1e-6).all()


def test_quantize_zero_block():
    """All-zero blocks must quantize to zero codes with a finite scale."""
    x = jnp.zeros((512,))
    q, s = quantize_blockwise(x, 256)
    assert np.all(np.asarray(q) == 0)
    assert np.all(np.isfinite(np.asarray(s)))
    np.testing.assert_array_equal(
        np.asarray(dequantize_blockwise(q, s, 256)), 0.0)


def test_quantize_per_block_scales_isolate_outliers():
    """A huge element in one block must not destroy resolution elsewhere —
    the point of BLOCKWISE scales vs one per-tensor scale."""
    x = np.random.RandomState(0).normal(size=1024).astype(np.float32)
    x[0] = 1e4
    y = np.asarray(dequantize_blockwise(
        *quantize_blockwise(jnp.asarray(x), 256), 256))
    # the outlier's own block is coarse; the other blocks stay fine-grained
    assert np.abs(y[256:] - x[256:]).max() < np.abs(x[256:]).max() / 100.0


def test_quantize_validates():
    with pytest.raises(ValueError):
        quantize_blockwise(jnp.zeros((100,)), 256)  # not a block multiple
    with pytest.raises(ValueError):
        quantize_blockwise(jnp.zeros((4, 64)), 64)  # not flat
    with pytest.raises(ValueError):
        quantize_blockwise(jnp.zeros((256,)), 256, stochastic=True)  # no seed
    with pytest.raises(ValueError):
        # pallas path needs lane-aligned blocks
        quantize_blockwise(jnp.zeros((256,)), 64, use_pallas=True)


def test_stochastic_rounding_unbiased_and_seeded():
    x = jnp.full((256,), 0.3)
    outs = []
    for seed in range(64):
        q, s = quantize_blockwise(x, 256, stochastic=True, seed=seed)
        outs.append(np.asarray(dequantize_blockwise(q, s, 256)))
    m = float(np.mean(outs))
    assert abs(m - 0.3) < 0.005, m  # unbiased across seeds
    q1, _ = quantize_blockwise(x, 256, stochastic=True, seed=11)
    q2, _ = quantize_blockwise(x, 256, stochastic=True, seed=11)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))


def test_pallas_interpret_matches_reference():
    """The kernel and the XLA path are the same codec (codes equal up to
    the 1-ulp scale difference of reassociated maxes)."""
    x = jax.random.normal(jax.random.PRNGKey(1), (32 * 128,))
    q_ref, s_ref = quantize_blockwise(x, 128)
    q_pl, s_pl = quantize_blockwise(x, 128, use_pallas=True)
    np.testing.assert_allclose(np.asarray(s_ref), np.asarray(s_pl),
                               rtol=1e-6)
    assert np.abs(np.asarray(q_ref, np.int32)
                  - np.asarray(q_pl, np.int32)).max() <= 1
    y_ref = dequantize_blockwise(q_pl, s_pl, 128)
    y_pl = dequantize_blockwise(q_pl, s_pl, 128, use_pallas=True)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_pl),
                               rtol=1e-6)


def test_quantization_error_is_the_ef_residual():
    x = jax.random.normal(jax.random.PRNGKey(2), (512,))
    e = quantization_error(x, 256)
    q, s = quantize_blockwise(x, 256)
    want = np.asarray(x) - np.asarray(dequantize_blockwise(q, s, 256))
    np.testing.assert_allclose(np.asarray(e), want, atol=1e-7)


# ---------------------------------------------------------------------------
# the 4-bit codec


def test_int4_pack_unpack_exact_inverse():
    from apex_tpu.comm import pack_int4, unpack_int4

    q = jax.random.randint(jax.random.PRNGKey(0), (256,), -8, 8
                           ).astype(jnp.int8)
    packed = pack_int4(q)
    assert packed.dtype == jnp.uint8 and packed.shape == (128,)
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed)),
                                  np.asarray(q))
    with pytest.raises(ValueError):
        pack_int4(jnp.zeros((3,), jnp.int8))  # odd axis


def test_int4_roundtrip_half_step_bound():
    """|x - dq(q(x))| <= scale/2 per element, scale = group absmax/7 —
    the 4-bit analogue of the int8 bound (16x coarser steps: why EF
    matters at this tier)."""
    from apex_tpu.comm import (
        dequantize_blockwise_int4,
        quantize_blockwise_int4,
    )

    x = jax.random.normal(jax.random.PRNGKey(3), (4096,))
    q, s = quantize_blockwise_int4(x, 128)
    assert q.dtype == jnp.uint8 and q.shape == (2048,)  # two codes/byte
    assert s.dtype == jnp.float32 and s.shape == (32,)
    y = dequantize_blockwise_int4(q, s, 128)
    err = np.abs(np.asarray(y) - np.asarray(x)).reshape(32, 128)
    step = np.abs(np.asarray(x)).reshape(32, 128).max(1) / 7.0
    assert (err <= step[:, None] * 0.5 + 1e-6).all()
    # all-zero groups: zero codes, finite scales
    q0, s0 = quantize_blockwise_int4(jnp.zeros((256,)), 128)
    assert np.all(np.asarray(q0) == 0)
    np.testing.assert_array_equal(
        np.asarray(dequantize_blockwise_int4(q0, s0, 128)), 0.0)


def test_int4_stochastic_unbiased_and_seeded():
    from apex_tpu.comm import (
        dequantize_blockwise_int4,
        quantize_blockwise_int4,
    )

    x = jnp.full((256,), 0.3)
    outs = []
    for seed in range(64):
        q, s = quantize_blockwise_int4(x, 128, stochastic=True, seed=seed)
        outs.append(np.asarray(dequantize_blockwise_int4(q, s, 128)))
    m = float(np.mean(outs))
    assert abs(m - 0.3) < 0.01, m  # unbiased across seeds
    q1, _ = quantize_blockwise_int4(x, 128, stochastic=True, seed=11)
    q2, _ = quantize_blockwise_int4(x, 128, stochastic=True, seed=11)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))


def test_int4_pallas_interpret_matches_reference():
    """The shared Pallas rounding kernels at the ±7 code range: same codec
    as the XLA path up to 1-ulp scale reassociation."""
    from apex_tpu.comm import quantize_blockwise_int4, unpack_int4

    x = jax.random.normal(jax.random.PRNGKey(4), (32 * 128,))
    q_ref, s_ref = quantize_blockwise_int4(x, 128)
    q_pl, s_pl = quantize_blockwise_int4(x, 128, use_pallas=True)
    np.testing.assert_allclose(np.asarray(s_ref), np.asarray(s_pl),
                               rtol=1e-6)
    assert np.abs(np.asarray(unpack_int4(q_ref), np.int32)
                  - np.asarray(unpack_int4(q_pl), np.int32)).max() <= 1


def test_int4_validates():
    from apex_tpu.comm import quantize_blockwise_int4

    with pytest.raises(ValueError):
        quantize_blockwise_int4(jnp.zeros((100,)), 128)  # not a multiple
    with pytest.raises(ValueError):
        quantize_blockwise_int4(jnp.zeros((4, 64)), 64)  # not flat
    with pytest.raises(ValueError):
        quantize_blockwise_int4(jnp.zeros((254,)), 127)  # odd group
    with pytest.raises(ValueError):
        quantize_blockwise_int4(jnp.zeros((256,)), 128,
                                stochastic=True)  # no seed


def test_int4_wire_models():
    """The packed-payload wire math: codes at 0.5 B/elem + fp32 scales,
    and the modeled fp32/int4 allreduce ratio clears the acceptance gate
    (>=6.5x; 7.53x at group 128)."""
    from apex_tpu.comm import allreduce_wire_bytes, psum_scatter_wire_bytes

    cfg = CompressionConfig(policy="int4_ef", block_size=128,
                            min_elements=128)
    n, w = 4096, 8
    fp32 = allreduce_wire_bytes(n, 4, w, None)
    i4 = allreduce_wire_bytes(n, 4, w, cfg)
    # two passes of (n/2 codes + 4n/128 scales), ring-scaled
    assert i4 == pytest.approx(2.0 * (n / 2 + 4.0 * n / 128) * (w - 1) / w)
    assert fp32 / i4 >= 6.5, fp32 / i4
    rs4 = psum_scatter_wire_bytes(n, 4, w, cfg, shard_multiple=128)
    assert rs4 == pytest.approx((n / 2 + 4.0 * n / 128) * (w - 1) / w)
    # sub-min_elements buffers fall back to the fp32 path
    assert allreduce_wire_bytes(64, 4, w, cfg) == \
        allreduce_wire_bytes(64, 4, w, None)


# ---------------------------------------------------------------------------
# config

def test_compression_config_validates():
    with pytest.raises(ValueError):
        CompressionConfig(policy="int2")  # not a codec tier
    with pytest.raises(ValueError):
        CompressionConfig(block_size=0)
    with pytest.raises(ValueError):
        CompressionConfig(policy="int4", block_size=129)  # odd group
    cfg = CompressionConfig(policy="int8_ef", min_elements=100)
    assert cfg.enabled and cfg.error_feedback and cfg.bits == 8
    assert cfg.compresses(100) and not cfg.compresses(99)
    assert not CompressionConfig(policy="none").enabled
    cfg4 = CompressionConfig(policy="int4_ef", block_size=128)
    assert cfg4.enabled and cfg4.error_feedback and cfg4.bits == 4
    # packed codes at 0.5 B/elem + fp32 scale per group
    assert cfg4.payload_bytes(4096) == 4096 * 0.5 + 4 * 4096 / 128


# ---------------------------------------------------------------------------
# error-feedback state

def test_error_feedback_state_dict_roundtrip():
    grads = {"layer": {"w": jnp.ones((3, 4), jnp.bfloat16),
                       "b": jnp.zeros((7,))},
             "head": jnp.full((2,), 0.5)}
    r = init_error_feedback(grads)
    # residuals are fp32 regardless of grad dtype
    assert all(x.dtype == jnp.float32 for x in jax.tree_util.tree_leaves(r))
    r = jax.tree_util.tree_map(
        lambda x: x + np.random.RandomState(0).normal(size=x.shape), r)
    d = ef.state_dict(r)
    r2 = ef.load_state_dict(init_error_feedback(grads), d)
    for a, b in zip(jax.tree_util.tree_leaves(r),
                    jax.tree_util.tree_leaves(r2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_error_feedback_load_rejects_mismatch():
    r = init_error_feedback({"a": jnp.zeros((4,)), "b": jnp.zeros((2,))})
    d = ef.state_dict(r)
    with pytest.raises(ValueError):  # different structure, same leaf count
        ef.load_state_dict(
            init_error_feedback({"a": jnp.zeros((4,)), "c": jnp.zeros((2,))}),
            d)
    with pytest.raises(ValueError):  # same structure, different shapes
        bad = dict(d, treedef=None)
        ef.load_state_dict(
            init_error_feedback({"a": jnp.zeros((4,)), "b": jnp.zeros((3,))}),
            bad)


# ---------------------------------------------------------------------------
# accounting — the HLO pricer itself (compiled-program integration is in
# test_collective_counts.py, which needs the 8-device mesh)

_HLO = """
HloModule test
  %all-reduce.1 = f32[1024]{0} all-reduce(f32[1024]{0} %p), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
  %ag = s8[4096]{0} all-gather(s8[512]{0} %q), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %a2a = (s8[128]{0}, s8[128]{0}, /*index=2*/s8[128]{0}, s8[128]{0}) all-to-all(s8[128]{0} %a, s8[128]{0} %b, s8[128]{0} %c, s8[128]{0} %d), replica_groups={{0,1,2,3}}
  %rs = f32[64]{0} reduce-scatter(f32[512]{0} %x), replica_groups=[1,8]<=[8], dimensions={0}
  %start = bf16[256]{0} all-reduce-start(bf16[256]{0} %y), replica_groups={{0,1}}
  %done = bf16[256]{0} all-reduce-done(bf16[256]{0} %start)
  %gte = s8[128]{0} get-tuple-element((s8[128]{0}, s8[128]{0}) %all-to-all.9), index=0
  %cp = f32[32]{0} collective-permute(f32[32]{0} %z), source_target_pairs={{0,1}}
"""


def test_accounting_counts_and_prices():
    rep = collective_report(_HLO)
    assert rep.counts == {"all-reduce": 2, "all-gather": 1,
                          "reduce-scatter": 1, "all-to-all": 1,
                          "collective-permute": 1}
    # all-reduce: 2*4096*(7/8) + 2*512*(1/2); gather: 4096*(7/8);
    # a2a: 512*(3/4); rs: 256*7; permute: 128
    assert rep.wire_bytes_by_kind["all-reduce"] == pytest.approx(
        2 * 4096 * 7 / 8 + 2 * 512 * 1 / 2)
    assert rep.wire_bytes_by_kind["all-gather"] == pytest.approx(
        4096 * 7 / 8)
    assert rep.wire_bytes_by_kind["all-to-all"] == pytest.approx(512 * 3 / 4)
    assert rep.wire_bytes_by_kind["reduce-scatter"] == pytest.approx(256 * 7)
    assert rep.wire_bytes_by_kind["collective-permute"] == pytest.approx(128)
    assert rep.wire_bytes == pytest.approx(sum(
        rep.wire_bytes_by_kind.values()))


def test_accounting_single_device_groups_are_free():
    rep = collective_report(
        "%ar = f32[64]{0} all-reduce(f32[64]{0} %p), replica_groups={{0}}")
    assert rep.counts["all-reduce"] == 1
    assert rep.wire_bytes == 0.0


# ---------------------------------------------------------------------------
# async-emitted HLO (what the TPU latency-hiding scheduler produces, and
# what comm.overlap's decomposed rings make common): the '-start' result is
# a TUPLE aliasing the operand next to the output plus u32[] context
# scalars, so pricing it like a sync result double-charges — the pricer
# must price '-start' ops from their operands, once.

_ASYNC_HLO = """
HloModule async_test, is_scheduled=true

ENTRY %main (p0: f32[16,32], p1: f32[32,8]) -> f32[16,8] {
  %p0 = f32[16,32]{1,0} parameter(0)
  %p1 = f32[32,8]{1,0} parameter(1)
  %collective-permute-start.1 = (f32[16,32]{1,0}, f32[16,32]{1,0}, u32[], u32[]) collective-permute-start(f32[16,32]{1,0} %p0), channel_id=1, source_target_pairs={{0,1},{1,0}}
  %dot.1 = f32[16,8]{1,0} dot(f32[16,32]{1,0} %p0, f32[32,8]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %collective-permute-done.1 = f32[16,32]{1,0} collective-permute-done((f32[16,32]{1,0}, f32[16,32]{1,0}, u32[], u32[]) %collective-permute-start.1)
  %dot.2 = f32[16,8]{1,0} dot(f32[16,32]{1,0} %collective-permute-done.1, f32[32,8]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %all-gather-start.1 = (f32[16,8]{1,0}, f32[64,8]{1,0}) all-gather-start(f32[16,8]{1,0} %dot.2), channel_id=2, replica_groups={{0,1,2,3}}, dimensions={0}
  %all-gather-done.1 = f32[64,8]{1,0} all-gather-done((f32[16,8]{1,0}, f32[64,8]{1,0}) %all-gather-start.1)
  ROOT %add.1 = f32[16,8]{1,0} add(f32[16,8]{1,0} %dot.1, f32[16,8]{1,0} %dot.2)
}
"""


def test_accounting_async_start_priced_once_from_operands():
    rep = collective_report(_ASYNC_HLO)
    # one pair each, counted once at the '-start'
    assert rep.counts["collective-permute"] == 1, rep
    assert rep.counts["all-gather"] == 1, rep
    # cp: ONE hop of the f32[16,32] operand = 2048 bytes — NOT the start
    # tuple's 2*2048 + 8 (operand alias + u32 contexts double-charge)
    assert rep.wire_bytes_by_kind["collective-permute"] == pytest.approx(
        2048)
    # ag: sync result reconstructed as operand*W -> 64*8*4 * (3/4)
    assert rep.wire_bytes_by_kind["all-gather"] == pytest.approx(
        64 * 8 * 4 * 3 / 4)


def test_overlap_report_async_windows():
    from apex_tpu.comm import overlap_report

    rep = overlap_report(_ASYNC_HLO)
    # dot.1 is scheduled inside the start.1/done.1 window -> hidden
    assert rep.async_pairs == 1 and rep.async_hidden == 1, rep
    assert rep.hidden_wire_bytes == pytest.approx(2048)
    assert rep.exposed_wire_bytes == 0.0, rep
    # removing the in-window dot exposes the permute
    exposed = overlap_report(_ASYNC_HLO.replace(
        "  %dot.1 = f32[16,8]{1,0} dot(f32[16,32]{1,0} %p0, "
        "f32[32,8]{1,0} %p1), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}\n", ""))
    assert exposed.async_hidden == 0, exposed
    assert exposed.exposed_wire_bytes == pytest.approx(2048)


_SYNC_RING_HLO = """
ENTRY %main (p0: f32[16,32], p1: f32[32,8]) -> f32[16,8] {
  %p0 = f32[16,32]{1,0} parameter(0)
  %p1 = f32[32,8]{1,0} parameter(1)
  %collective-permute.1 = f32[16,32]{1,0} collective-permute(f32[16,32]{1,0} %p0), channel_id=1, source_target_pairs={{0,1},{1,0}}
  %dot.1 = f32[16,8]{1,0} dot(f32[16,32]{1,0} %p0, f32[32,8]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %dot.2 = f32[16,8]{1,0} dot(f32[16,32]{1,0} %collective-permute.1, f32[32,8]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %add.1 = f32[16,8]{1,0} add(f32[16,8]{1,0} %dot.1, f32[16,8]{1,0} %dot.2)
}
"""


def test_overlap_report_sync_independence():
    """Pre-schedule/CPU modules emit synchronous collective-permute; a hop
    counts as hideable iff some dot neither feeds it nor consumes it."""
    from apex_tpu.comm import overlap_report

    rep = overlap_report(_SYNC_RING_HLO)
    # dot.1 is independent of the permute (dot.2 consumes it)
    assert rep.sync_permutes == 1 and rep.sync_hidden == 1, rep
    # drop the independent dot: the only remaining dot DEPENDS on the
    # permute -> nothing a scheduler could overlap
    dep_only = overlap_report(_SYNC_RING_HLO.replace(
        "  %dot.1 = f32[16,8]{1,0} dot(f32[16,32]{1,0} %p0, "
        "f32[32,8]{1,0} %p1), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}\n", "").replace(
        "f32[16,8]{1,0} %dot.1", "f32[16,8]{1,0} %dot.2"))
    assert dep_only.sync_permutes == 1 and dep_only.sync_hidden == 0, \
        dep_only


def test_overlap_report_fusion_wrapped_dot_counts():
    """On TPU the partial GEMMs ride inside fusions — a fusion calling a
    dot-bearing computation must count as a dot for the window check."""
    from apex_tpu.comm import overlap_report

    hlo = """
%fused_dot (pa: f32[16,32], pb: f32[32,8]) -> f32[16,8] {
  %pa = f32[16,32]{1,0} parameter(0)
  %pb = f32[32,8]{1,0} parameter(1)
  ROOT %dot.9 = f32[16,8]{1,0} dot(f32[16,32]{1,0} %pa, f32[32,8]{1,0} %pb), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}

ENTRY %main (p0: f32[16,32], p1: f32[32,8]) -> f32[16,8] {
  %p0 = f32[16,32]{1,0} parameter(0)
  %p1 = f32[32,8]{1,0} parameter(1)
  %collective-permute-start.1 = (f32[16,32]{1,0}, f32[16,32]{1,0}, u32[], u32[]) collective-permute-start(f32[16,32]{1,0} %p0), channel_id=1, source_target_pairs={{0,1},{1,0}}
  %fusion.1 = f32[16,8]{1,0} fusion(f32[16,32]{1,0} %p0, f32[32,8]{1,0} %p1), kind=kOutput, calls=%fused_dot
  %collective-permute-done.1 = f32[16,32]{1,0} collective-permute-done((f32[16,32]{1,0}, f32[16,32]{1,0}, u32[], u32[]) %collective-permute-start.1)
  ROOT %tail = f32[16,8]{1,0} add(f32[16,8]{1,0} %fusion.1, f32[16,8]{1,0} %fusion.1)
}
"""
    rep = overlap_report(hlo)
    assert rep.async_pairs == 1 and rep.async_hidden == 1, rep


def test_overlap_wire_models_match_ring_shape():
    """The comm.overlap byte models must equal the monolithic collective
    models — the decomposition is wire-neutral by design: (W-1) hops of
    one shard vs the ring cost of the fused collective."""
    from apex_tpu.comm import (
        all_gather_matmul_wire_bytes,
        all_gather_wire_bytes,
        allreduce_wire_bytes,
        matmul_all_reduce_wire_bytes,
        matmul_reduce_scatter_wire_bytes,
    )

    w, shard, item = 8, 16 * 128, 4
    full = shard * w
    assert all_gather_matmul_wire_bytes(shard, item, w) == pytest.approx(
        all_gather_wire_bytes(full, item, w))
    # monolithic reduce-scatter: result shard bytes * (W-1)
    assert matmul_reduce_scatter_wire_bytes(shard, item, w) == \
        pytest.approx(float(shard) * item * (w - 1))
    assert matmul_all_reduce_wire_bytes(shard, item, w) == pytest.approx(
        allreduce_wire_bytes(full, item, w, None))
    for fn in (all_gather_matmul_wire_bytes,
               matmul_reduce_scatter_wire_bytes,
               matmul_all_reduce_wire_bytes):
        assert fn(shard, item, 1) == 0.0

"""fp16_utils + ASP + transducer + batch sampler tests — ref
tests/L0/run_fp16util, contrib/test/sparsity, contrib/test/transducer
(vs transducer_ref.py), run_transformer/test_batch_sampler.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)
from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    clip_grad_norm,
    convert_network,
    master_params_to_model_params,
    network_to_half,
    prep_param_lists,
)
from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)

# ---------------------------------------------------------------------------
# fp16_utils (ref tests/L0/run_fp16util/test_fp16util.py)


def _net():
    return {
        "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros((4,))},
        "LayerNorm_0": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
    }


def test_network_to_half_keeps_norms_fp32():
    half = network_to_half(_net())
    assert half["dense"]["kernel"].dtype == jnp.bfloat16
    assert half["LayerNorm_0"]["scale"].dtype == jnp.float32


def test_convert_network_fp16():
    half = convert_network(_net(), jnp.float16)
    assert half["dense"]["kernel"].dtype == jnp.float16
    assert half["LayerNorm_0"]["bias"].dtype == jnp.float32


def test_prep_and_copy_param_lists():
    model = network_to_half(_net())
    model_params, masters = prep_param_lists(model)
    assert masters["dense"]["kernel"].dtype == jnp.float32
    masters = jax.tree.map(lambda m: m + 0.25 if m.dtype == jnp.float32 else m,
                           masters)
    back = master_params_to_model_params(masters, model_params)
    assert back["dense"]["kernel"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(back["dense"]["kernel"],
                                          np.float32), 1.25)


def test_clip_grad_norm():
    g = {"a": jnp.full((4,), 3.0), "b": jnp.full((4,), 4.0)}
    clipped, total = clip_grad_norm(g, max_norm=5.0)
    np.testing.assert_allclose(float(total), 10.0)
    norm2 = float(jnp.sqrt(sum(jnp.sum(x * x)
                               for x in jax.tree.leaves(clipped))))
    np.testing.assert_allclose(norm2, 5.0, rtol=1e-5)


def test_fp16_optimizer_skips_on_overflow():
    opt = FP16_Optimizer(optax.sgd(0.1), static_loss_scale=128.0)
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    state = opt.init(params)
    # finite grads: step applies
    g = {"w": jnp.full((4,), 128.0, jnp.bfloat16)}  # scaled grad of 1.0
    p2, state2, skipped = opt.step(g, state)
    assert not bool(skipped)
    np.testing.assert_allclose(np.asarray(p2["w"]), 0.9, rtol=1e-2)
    # inf grads: step skipped, masters unchanged
    g_bad = {"w": jnp.asarray([jnp.inf, 1, 1, 1], jnp.bfloat16)}
    p3, state3, skipped = opt.step(g_bad, state2)
    assert bool(skipped)
    np.testing.assert_array_equal(np.asarray(p3["w"]),
                                  np.asarray(state2.master_params["w"]))


def test_fp16_optimizer_dynamic_scaler_backoff():
    opt = FP16_Optimizer(optax.sgd(0.1), dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2.0 ** 8})
    params = {"w": jnp.ones((2,))}
    state = opt.init(params)
    g_bad = {"w": jnp.asarray([jnp.nan, 1.0])}
    _, state2, skipped = opt.step(g_bad, state)
    assert bool(skipped)
    assert float(state2.scaler.loss_scale) == 2.0 ** 7


# ---------------------------------------------------------------------------
# ASP (ref contrib/test/sparsity/test_sparsity.py)


def test_create_mask_m4n2():
    w = jnp.asarray([[0.1, -5.0, 2.0, 0.05, 3.0, -0.2, 0.1, 4.0]])
    mask = create_mask(w)
    np.testing.assert_array_equal(
        np.asarray(mask[0]),
        [False, True, True, False, True, False, False, True])


def test_asp_masks_and_optimizer_wrap():
    params = {"dense": {"kernel": jnp.asarray(
        np.random.RandomState(0).randn(8, 8), jnp.float32)},
        "bias": jnp.ones((3,))}
    asp = ASP()
    masks = asp.compute_sparse_masks(params)
    assert masks["bias"] is None  # 1-D not whitelisted
    sparse = ASP.apply_masks(params, masks)
    # exactly 50% zeros in every 4-group
    k = np.asarray(sparse["dense"]["kernel"]).reshape(-1, 4)
    assert ((k != 0).sum(axis=1) == 2).all()

    opt = asp.init_optimizer_for_pruning(optax.sgd(0.1), masks)
    state = opt.init(sparse)
    g = jax.tree.map(jnp.ones_like, sparse)
    updates, _ = opt.update(g, state, sparse)
    stepped = jax.tree.map(lambda p, u: p + u, sparse, updates)
    k2 = np.asarray(stepped["dense"]["kernel"]).reshape(-1, 4)
    assert ((k2 != 0).sum(axis=1) == 2).all()  # still 2:4 after the step


# ---------------------------------------------------------------------------
# transducer (ref contrib/test/transducer/transducer_ref.py)


def _transducer_ref_nll(logp, label, T, U):
    """O(T·U) numpy alpha recursion — independent reference implementation."""
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U + 1):
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + logp[t - 1, u, 0])
            if u > 0:
                cands.append(alpha[t, u - 1] + logp[t, u - 1, label[u - 1]])
            if cands:
                alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[T - 1, U] + logp[T - 1, U, 0])


def test_transducer_loss_matches_numpy_reference():
    rng = np.random.RandomState(1)
    B, T, U, V = 3, 5, 4, 7
    x = rng.randn(B, T, U + 1, V).astype(np.float32)
    logp = x - np.log(np.exp(x).sum(-1, keepdims=True))
    label = rng.randint(1, V, (B, U))
    f_len = np.asarray([5, 4, 3])
    y_len = np.asarray([4, 2, 3])
    got = transducer_loss(jnp.asarray(logp), jnp.asarray(label),
                          jnp.asarray(f_len), jnp.asarray(y_len))
    for b in range(B):
        want = _transducer_ref_nll(logp[b], label[b], f_len[b], y_len[b])
        np.testing.assert_allclose(float(got[b]), want, rtol=1e-5,
                                   err_msg=f"batch {b}")


def test_transducer_loss_gradients_flow():
    B, T, U, V = 2, 4, 3, 5
    x = jnp.asarray(np.random.RandomState(2).randn(B, T, U + 1, V),
                    jnp.float32)
    label = jnp.asarray(np.random.RandomState(3).randint(1, V, (B, U)))
    loss_mod = TransducerLoss()
    f_len = jnp.asarray([4, 4])
    y_len = jnp.asarray([3, 3])
    g = jax.grad(lambda x: jnp.sum(loss_mod(x, label, f_len, y_len)))(x)
    assert np.all(np.isfinite(np.asarray(g)))
    assert np.abs(np.asarray(g)).max() > 0


def test_transducer_joint():
    f = jnp.ones((2, 3, 4))
    g = jnp.full((2, 5, 4), -2.0)
    out = transducer_joint(f, g)
    assert out.shape == (2, 3, 5, 4)
    np.testing.assert_allclose(np.asarray(out), -1.0)
    relu_out = TransducerJoint(relu=True)(f, g)
    np.testing.assert_allclose(np.asarray(relu_out), 0.0)


def test_transducer_joint_packed_matches_dense():
    """pack_output parity (ref TransducerJoint packing contract:
    batch_offset = cumsum(f_len * g_len), batch b's cell (t, u) at row
    offset[b-1] + t * g_len[b] + u)."""
    rng = np.random.RandomState(5)
    B, T, U, H = 3, 5, 4, 8
    f = rng.randn(B, T, H).astype(np.float32)
    g = rng.randn(B, U, H).astype(np.float32)
    f_len = np.asarray([5, 3, 4])
    g_len = np.asarray([4, 2, 3])
    offset = np.cumsum(f_len * g_len)
    packed_batch = int(offset[-1]) + 3  # surplus rows must zero-fill
    packed = jax.jit(lambda *a: transducer_joint(
        *a, relu=True, pack_output=True,
        batch_offset=jnp.asarray(offset), packed_batch=packed_batch))(
        jnp.asarray(f), jnp.asarray(g), jnp.asarray(f_len),
        jnp.asarray(g_len))
    assert packed.shape == (packed_batch, H)
    dense = np.maximum(f[:, :, None, :] + g[:, None, :, :], 0.0)
    want = np.concatenate([
        dense[b, :f_len[b], :g_len[b]].reshape(-1, H) for b in range(B)])
    np.testing.assert_allclose(np.asarray(packed[:offset[-1]]), want,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(packed[offset[-1]:]), 0.0)


def test_transducer_loss_packed_matches_dense():
    """packed_input parity incl. gradients (ref TransducerLoss packing
    contract: batch_offset = cumsum(f_len * (y_len + 1)))."""
    rng = np.random.RandomState(6)
    B, T, U, V = 3, 5, 4, 7
    x = rng.randn(B, T, U + 1, V).astype(np.float32)
    label = rng.randint(1, V, (B, U))
    f_len = np.asarray([5, 4, 3])
    y_len = np.asarray([4, 2, 3])
    offset = np.cumsum(f_len * (y_len + 1))
    x_packed = np.concatenate([
        x[b, :f_len[b], :y_len[b] + 1].reshape(-1, V) for b in range(B)])

    dense_loss = TransducerLoss()
    packed_loss = TransducerLoss(packed_input=True)
    args = (jnp.asarray(label), jnp.asarray(f_len), jnp.asarray(y_len))
    want, g_dense = jax.value_and_grad(
        lambda x: jnp.sum(dense_loss(x, *args)))(jnp.asarray(x))
    got, g_packed = jax.jit(jax.value_and_grad(
        lambda x: jnp.sum(packed_loss(
            x, *args, batch_offset=jnp.asarray(offset), max_f_len=T))))(
        jnp.asarray(x_packed))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    # the packed cotangent must equal the dense cotangent's valid cells
    g_dense_packed = np.concatenate([
        np.asarray(g_dense)[b, :f_len[b], :y_len[b] + 1].reshape(-1, V)
        for b in range(B)])
    np.testing.assert_allclose(np.asarray(g_packed), g_dense_packed,
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# batch samplers (ref run_transformer/test_batch_sampler.py)


def test_pretraining_sampler_shards_by_rank():
    got = {r: list(MegatronPretrainingSampler(
        total_samples=16, consumed_samples=0, local_minibatch_size=2,
        data_parallel_rank=r, data_parallel_size=2))
        for r in range(2)}
    assert got[0][0] == [0, 1] and got[1][0] == [2, 3]
    assert got[0][1] == [4, 5] and got[1][1] == [6, 7]
    # resume from consumed_samples
    resumed = list(MegatronPretrainingSampler(
        total_samples=16, consumed_samples=8, local_minibatch_size=2,
        data_parallel_rank=0, data_parallel_size=2))
    assert resumed[0] == [8, 9]


def test_random_sampler_is_deterministic_and_disjoint():
    a0 = list(MegatronPretrainingRandomSampler(64, 0, 4, 0, 2))
    a0b = list(MegatronPretrainingRandomSampler(64, 0, 4, 0, 2))
    a1 = list(MegatronPretrainingRandomSampler(64, 0, 4, 1, 2))
    assert a0 == a0b  # same epoch -> same permutation
    flat0 = {i for b in a0 for i in b}
    flat1 = {i for b in a1 for i in b}
    assert not (flat0 & flat1)  # ranks read disjoint shards
    assert all(len(b) == 4 for b in a0)

"""Chaos-driven recovery tests for ``apex_tpu.resilience``.

Every claim the subsystem makes is proven against an injected failure:
a NaN at step k must be survived (the loss curve rejoins the clean run),
a deliberately corrupted checkpoint must be skipped by ``latest_valid()``,
and resume-after-simulated-preemption must be bit-identical to an
uninterrupted run on CPU. All tests are stock-jax-safe (no shard_map) —
the guard/checkpoint/preemption machinery is mesh-agnostic pytree code.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.monitor import Metrics
from apex_tpu.resilience import (
    AnomalyGuard,
    AnomalyHalted,
    CheckpointError,
    CheckpointManager,
    GuardPolicy,
    PreemptionAtStep,
    PreemptionHandler,
    StallWatchdog,
    chaos,
    fingerprint,
)

# ---------------------------------------------------------------------------
# shared fixture: a tiny deterministic quadratic trainer (data built
# eagerly at import — creating it lazily inside a traced step would cache
# tracers)

_X = jnp.asarray(np.random.RandomState(0).randn(32, 4).astype(np.float32))
_Y = _X @ jnp.arange(1.0, 5.0)  # realizable: the clean loss goes to ~0


def _data():
    return _X, _Y


def _loss_fn(w):
    X, Y = _data()
    return jnp.mean((X @ w - Y) ** 2)


def _make_guarded_step(guard, chaos_step=-1, mode="nan", lr=0.1):
    """One jitted SGD step with optional in-graph NaN/Inf injection."""

    @jax.jit
    def step(params, gstate, metrics, it):
        loss, grads = jax.value_and_grad(_loss_fn)(params)
        grads = chaos.inject_nonfinite(grads, it, chaos_step, mode=mode)
        proposed = params - lr * grads
        bad, metrics = guard.check(loss=loss, grads=grads, metrics=metrics)
        params, gstate, metrics = guard.apply(
            gstate, bad, proposed, params, metrics=metrics)
        return params, gstate, metrics, loss

    return step


def _seed_metrics():
    return Metrics({"anomalies_total": 0.0, "nonfinite_loss_total": 0.0,
                    "nonfinite_grads_total": 0.0, "guard_skips_total": 0.0,
                    "rollbacks_total": 0.0, "guard_halted": 0.0})


def _run(guard, n, chaos_step=-1, mode="nan"):
    params = jnp.zeros(4)
    gstate = guard.init(params)
    metrics = _seed_metrics()
    step = _make_guarded_step(guard, chaos_step, mode)
    losses = []
    for it in range(n):
        params, gstate, metrics, loss = step(
            params, gstate, metrics, jnp.asarray(it))
        losses.append(float(loss))
    return params, gstate, metrics.as_dict(), losses


# ---------------------------------------------------------------------------
# anomaly guard

def test_nan_at_step_k_is_survived_and_curve_rejoins():
    """The acceptance gate: a NaN gradient injected at step k is absorbed
    by a skip and the loss curve rejoins the clean baseline."""
    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip", skip_budget=3))
    _, _, clean_m, clean = _run(guard, 60)
    params, _, m, chaotic = _run(guard, 60, chaos_step=5)

    assert np.isfinite(np.asarray(params)).all()
    assert m["nonfinite_grads_total"] == 1.0
    assert m["guard_skips_total"] == 1.0
    assert m["rollbacks_total"] == 0.0
    assert m["guard_halted"] == 0.0
    assert clean_m["anomalies_total"] == 0.0
    # rejoins the clean run: both converged, final losses agree
    assert clean[-1] < 1e-2 and chaotic[-1] < 1e-2
    assert abs(clean[-1] - chaotic[-1]) < 1e-2
    # the chaotic loss at the injected step was the already-poisoned one's
    # objective value — still finite (loss is computed pre-injection here),
    # and every recorded loss is finite because the poison never landed
    assert np.isfinite(chaotic).all()


def test_inf_injection_also_caught():
    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip"))
    params, _, m, _ = _run(guard, 10, chaos_step=2, mode="inf")
    assert np.isfinite(np.asarray(params)).all()
    assert m["nonfinite_grads_total"] == 1.0


def test_rollback_restores_lagged_snapshot_exactly():
    """on_anomaly='rollback': the bad step restores the carried snapshot
    bit-exactly. The snapshot lags the live state by one ACCEPTED step —
    it is the newest state whose health a step's own finite loss/grads
    vouched for."""
    guard = AnomalyGuard(GuardPolicy(on_anomaly="rollback",
                                     rollback_budget=5))
    step = _make_guarded_step(guard, chaos_step=4)
    params = jnp.zeros(4)
    gstate = guard.init(params)
    metrics = _seed_metrics()
    history = []
    for it in range(4):  # clean steps
        history.append(np.asarray(params))
        params, gstate, metrics, _ = step(params, gstate, metrics,
                                          jnp.asarray(it))
    # entering the bad step the live state is history[3]'s successor; the
    # snapshot is the state step 3's checks validated: history[3]
    params, gstate, metrics, _ = step(params, gstate, metrics,
                                      jnp.asarray(4))
    np.testing.assert_array_equal(np.asarray(params), history[3])
    m = metrics.as_dict()
    assert m["rollbacks_total"] == 1.0 and m["guard_skips_total"] == 0.0


def test_rollback_recovers_from_state_poisoning_missed_by_one_step():
    """Poison that reaches the STATE while the step's own detectors stay
    clean (finite grads) must not enter the snapshot: the next step's
    checks expose it and rollback restores a pre-poison state."""
    guard = AnomalyGuard(GuardPolicy(on_anomaly="rollback",
                                     rollback_budget=5))

    @jax.jit
    def step(params, gstate, metrics, poison):
        loss, grads = jax.value_and_grad(_loss_fn)(params)
        proposed = params - 0.1 * grads
        # state-poisoning path the detectors don't see at this step
        proposed = jnp.where(poison, proposed * jnp.nan, proposed)
        bad, metrics = guard.check(loss=loss, grads=grads, metrics=metrics)
        params, gstate, metrics = guard.apply(
            gstate, bad, proposed, params, metrics=metrics)
        return params, gstate, metrics

    params = jnp.zeros(4)
    gstate = guard.init(params)
    metrics = _seed_metrics()
    for _ in range(3):
        params, gstate, metrics = step(params, gstate, metrics,
                                       jnp.asarray(False))
    pre_poison = np.asarray(params)
    # poisoned step: grads/loss are finite (computed from healthy params),
    # so the guard accepts the NaN'd proposed state...
    params, gstate, metrics = step(params, gstate, metrics,
                                   jnp.asarray(True))
    assert not np.isfinite(np.asarray(params)).all()
    # ...but the NEXT step's checks fire and rollback restores a finite
    # pre-poison state (the lagged snapshot), not the poisoned one
    params, gstate, metrics = step(params, gstate, metrics,
                                   jnp.asarray(False))
    assert np.isfinite(np.asarray(params)).all()
    np.testing.assert_array_equal(np.asarray(params), pre_poison)


def test_persistent_nan_escalates_skip_rollback_halt():
    """The ladder: skip_budget skips, then rollbacks, then halt — and the
    params stay finite (the last-good snapshot) throughout."""
    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip", skip_budget=2,
                                     rollback_budget=1))
    params = jnp.ones(4)
    gstate = guard.init(params)
    metrics = _seed_metrics()

    @jax.jit
    def bad_step(params, gstate, metrics):
        grads = params * jnp.nan
        proposed = params - 0.1 * grads
        bad, metrics = guard.check(grads=grads, metrics=metrics)
        return *guard.apply(gstate, bad, proposed, params, metrics=metrics),

    halted_at = None
    for it in range(10):
        params, gstate, metrics = bad_step(params, gstate, metrics)
        try:
            guard.raise_if_halted(gstate)
        except AnomalyHalted:
            halted_at = it
            break
    m = metrics.as_dict()
    # 2 skips (budget), then rollbacks; the 2nd rollback breaches
    # rollback_budget=1 and halts → 4 bad steps total
    assert halted_at == 3
    assert m["guard_skips_total"] == 2.0
    assert m["rollbacks_total"] == 2.0
    assert m["guard_halted"] == 1.0
    assert np.isfinite(np.asarray(params)).all()


def test_clean_step_resets_escalation():
    """A clean step between anomalies resets the consecutive counters —
    isolated blips never walk the ladder."""
    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip", skip_budget=1,
                                     rollback_budget=0))
    step = _make_guarded_step(guard, chaos_step=-1)
    poisoned = _make_guarded_step(guard, chaos_step=0)  # fires when it==0
    params = jnp.zeros(4)
    gstate = guard.init(params)
    metrics = _seed_metrics()
    for _ in range(4):  # bad, good, bad, good ... never two bad in a row
        params, gstate, metrics, _ = poisoned(params, gstate, metrics,
                                              jnp.asarray(0))
        params, gstate, metrics, _ = step(params, gstate, metrics,
                                          jnp.asarray(1))
    m = metrics.as_dict()
    assert m["guard_skips_total"] == 4.0
    assert m["rollbacks_total"] == 0.0 and m["guard_halted"] == 0.0


def test_guard_consumes_scaler_found_inf():
    """AMP wiring: the guard spends budget on the scaler's found_inf — an
    fp16 overflow is an anomaly like any other."""
    scaler = LossScaler("dynamic")
    sstate = scaler.init_state()
    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip"))
    grads = {"w": jnp.asarray([1.0, jnp.inf])}
    _, found_inf = scaler.unscale(grads, sstate)
    bad, m = guard.check(found_inf=found_inf, metrics=_seed_metrics())
    assert float(bad) == 1.0
    assert m.as_dict()["anomalies_total"] == 1.0
    # clean grads → no anomaly
    _, ok = scaler.unscale({"w": jnp.ones(2)}, sstate)
    assert float(guard.check(found_inf=ok)) == 0.0


def test_guard_init_requires_state_for_rollback():
    with pytest.raises(ValueError):
        AnomalyGuard(GuardPolicy(on_anomaly="rollback")).init()
    # halt-only guards carry no snapshot and need no state
    g = AnomalyGuard(GuardPolicy(on_anomaly="halt")).init()
    assert g.snapshot == ()


# ---------------------------------------------------------------------------
# checkpoint manager

def _rich_state():
    """A train-state pytree with the real members: params, AMP scaler
    state, synthetic ZeRO shards (count + master/mu/nu), EF residuals."""
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistAdamState,
    )

    scaler = LossScaler("dynamic")
    zero = DistAdamState(
        count=jnp.asarray(7, jnp.int32),
        master={"w": jnp.arange(8.0), "b": jnp.arange(2.0)},
        mu={"w": jnp.ones(8) * 0.5, "b": jnp.zeros(2)},
        nu={"w": jnp.ones(8) * 0.25, "b": jnp.zeros(2)})
    return {
        "params": {"w": jnp.arange(8.0) * 1.5, "b": jnp.asarray(0.5)},
        "scaler": scaler.init_state(),
        "zero": zero,
        "ef_residual": {"w": jnp.linspace(0, 1, 8), "b": jnp.zeros(2)},
    }


def test_checkpoint_roundtrip_bit_identical(tmp_path):
    state = _rich_state()
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 3)
    restored, step = mgr.restore(target=jax.tree.map(jnp.zeros_like, state))
    assert step == 3
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert got.dtype == want.dtype


def test_checkpoint_refuses_fingerprint_mismatch(tmp_path):
    state = _rich_state()
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 1)
    wrong = dict(state, params={"w": jnp.zeros(9), "b": jnp.asarray(0.0)})
    with pytest.raises(CheckpointError, match="different"):
        mgr.restore(target=wrong)


@pytest.mark.parametrize("mode", ["truncate", "flip", "delete"])
def test_latest_valid_skips_corrupt_payload(tmp_path, mode):
    """The acceptance gate: a deliberately corrupted checkpoint is skipped
    by latest_valid() and resume lands on the older good one."""
    state = _rich_state()
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 1)
    mgr.save(state, 2)
    chaos.corrupt_checkpoint(mgr.step_path(2), part="payload", mode=mode)
    assert not mgr.verify(mgr.step_path(2))
    assert mgr.latest_valid() == mgr.step_path(1)
    restored, step = mgr.restore(target=jax.tree.map(jnp.zeros_like, state))
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]),
        np.asarray(state["params"]["w"]))


def test_latest_valid_skips_corrupt_manifest(tmp_path):
    state = _rich_state()
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 1)
    mgr.save(state, 2)
    chaos.corrupt_checkpoint(mgr.step_path(2), part="manifest", mode="flip")
    assert mgr.latest_valid() == mgr.step_path(1)


def test_verify_catches_silent_crc_mismatch(tmp_path):
    """Payload loads fine but one leaf's bytes don't match the manifest
    crc — the silent-corruption case checksums exist for."""
    state = _rich_state()
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 1)
    assert mgr.verify(mgr.step_path(1))
    chaos.make_manifest_lie(mgr.step_path(1))
    assert not mgr.verify(mgr.step_path(1))
    assert mgr.latest_valid() is None
    with pytest.raises(CheckpointError):
        mgr.restore(target=state)


def test_restore_wraps_unreadable_paths_in_checkpoint_error(tmp_path):
    """A typo'd --resume path or a pre-manager-format file raises
    CheckpointError (catchable by drivers), not a raw FileNotFoundError."""
    state = {"w": jnp.ones(3)}
    mgr = CheckpointManager(str(tmp_path))
    with pytest.raises(CheckpointError, match="no valid checkpoint"):
        mgr.restore(target=state)
    with pytest.raises(CheckpointError, match="not a readable checkpoint"):
        mgr.restore(target=state, path=str(tmp_path / "nope"))
    legacy = tmp_path / "old_ckpt.npz.pkl"
    legacy.write_bytes(b"not a manager checkpoint")
    with pytest.raises(CheckpointError, match="not a readable checkpoint"):
        mgr.restore(target=state, path=str(legacy))


def test_gc_sweeps_stale_staging_and_recovers_orphan_trash(tmp_path):
    """Crash-orphaned staging from a dead pid: .tmp-* (never complete) is
    deleted; .trash-* (a previously-published copy parked by a same-step
    re-save that crashed between its two renames) is RESTORED when it is
    the only copy of that step, deleted when the step was re-published."""
    state = {"w": jnp.ones(3)}
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 2)
    # a dead writer's leftovers: junk staging + a parked copy of step 1
    # (the only copy) + a parked superseded copy of step 2
    stale = tmp_path / ".tmp-ckpt_00000009-99999999"
    stale.mkdir()
    (stale / "junk").write_bytes(b"x" * 128)
    os.rename(mgr.step_path(2),
              tmp_path / ".trash-ckpt_00000001-99999999")
    mgr.save(state, 2)  # publish + post-publish sweep
    assert [n for n in os.listdir(tmp_path)
            if n.startswith((".tmp-", ".trash-"))] == []
    # step 1 came back from the trash (it was the only copy) — recovery
    # is by directory move, content untouched
    assert os.path.isdir(mgr.step_path(1))


def test_torn_tmp_dir_is_invisible(tmp_path):
    """A staging dir left by a crashed save is not a checkpoint."""
    state = {"w": jnp.ones(3)}
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 1)
    torn = tmp_path / ".tmp-ckpt_00000002-999"
    torn.mkdir()
    (torn / "manifest.json").write_text("{")
    assert mgr.all_steps() == [1]
    assert mgr.latest_valid() == mgr.step_path(1)


def test_same_step_resave_replaces_cleanly(tmp_path):
    """Re-saving an existing step parks the old copy and publishes the new
    one — no torn mixture, no staging/trash litter left behind."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save({"w": jnp.zeros(4)}, 1)
    mgr.save({"w": jnp.ones(4)}, 1)
    restored, _ = mgr.restore(target={"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(4))
    assert [n for n in os.listdir(tmp_path)
            if n.startswith((".tmp-", ".trash-"))] == []


def test_retention_keep_last_n_and_every_k(tmp_path):
    state = {"w": jnp.ones(3)}
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2, keep_every_k=4)
    for s in range(1, 10):
        mgr.save(state, s)
    # last 2 = {8, 9}; milestones {4, 8} survive the GC
    assert mgr.all_steps() == [4, 8, 9]


def test_async_save_off_critical_path(tmp_path):
    state = _rich_state()
    mgr = CheckpointManager(str(tmp_path), async_save=True, keep_last_n=10)
    for s in range(5):
        mgr.save(state, s)
    mgr.close()  # drains the worker; re-raises its errors
    assert mgr.all_steps() == [0, 1, 2, 3, 4]
    for s in range(5):
        assert mgr.verify(mgr.step_path(s))
    assert mgr.last_save_ms is not None and mgr.last_save_bytes > 0


def test_save_records_ckpt_telemetry(tmp_path):
    """ckpt_save_ms / ckpt_bytes ride the monitor JSONL sink."""
    from apex_tpu.monitor import JsonlSink, read_jsonl

    path = str(tmp_path / "metrics.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        mgr = CheckpointManager(str(tmp_path / "ck"), sink=sink)
        mgr.save({"w": jnp.ones(16)}, 5)
    recs = list(read_jsonl(path))
    assert len(recs) == 1
    assert recs[0]["step"] == 5
    assert recs[0]["ckpt_save_ms"] > 0
    assert recs[0]["ckpt_bytes"] == 64


# ---------------------------------------------------------------------------
# preemption + bit-identical resume

def _amp_loop(ckpt_dir, n_steps, preempt_at=None):
    """Deterministic AMP train loop with auto-resume; data keyed by the
    absolute step so an interrupted+resumed run sees the same batches."""
    scaler = LossScaler("dynamic")

    @jax.jit
    def step(params, sstate, it):
        X, Y = _data()
        xb = X + 0.01 * it  # step-keyed data, deterministic
        def loss_fn(w):
            loss = jnp.mean((xb @ w - Y) ** 2)
            return scaler.scale_loss(loss, sstate), loss
        grads, loss = jax.grad(loss_fn, has_aux=True)(params)
        grads, found_inf = scaler.unscale(grads, sstate)
        new_sstate, skip = scaler.update_scale(sstate, found_inf)
        new_params = jnp.where(skip, params, params - 0.05 * grads)
        return new_params, new_sstate, loss

    params = jnp.zeros(4)
    sstate = scaler.init_state()
    state = (params, sstate)
    mgr = CheckpointManager(ckpt_dir)
    start = 0
    if mgr.latest_valid() is not None:
        state, start = mgr.restore(target=state)
    params, sstate = state
    pre = PreemptionHandler(install=False)
    trigger = PreemptionAtStep(pre, preempt_at) if preempt_at is not None \
        else None
    losses = []
    for it in range(start, n_steps):
        params, sstate, loss = step(params, sstate, jnp.asarray(it))
        losses.append(float(loss))
        if trigger is not None:
            trigger.maybe_fire(it)
            save_at = pre.sync_save_step(it)
            if save_at is not None:
                mgr.save((params, sstate), save_at + 1, block=True)
                return losses, (params, sstate), True
    return losses, (params, sstate), False


def test_preemption_resume_bit_identical(tmp_path):
    """The acceptance gate: simulated preemption at step k leaves a valid
    checkpoint, and the resumed run continues bit-identically to an
    uninterrupted run on CPU (scaler state included)."""
    clean_losses, (clean_p, clean_s), _ = _amp_loop(
        str(tmp_path / "clean"), 12)

    d = str(tmp_path / "pre")
    first, _, preempted = _amp_loop(d, 12, preempt_at=4)
    assert preempted and len(first) == 5  # steps 0..4 ran, saved at 5
    mgr = CheckpointManager(d)
    assert mgr.latest_valid() is not None and mgr.verify(mgr.latest_valid())

    rest, (res_p, res_s), _ = _amp_loop(d, 12)  # auto-resume
    assert first + rest == clean_losses
    np.testing.assert_array_equal(np.asarray(res_p), np.asarray(clean_p))
    for got, want in zip(jax.tree.leaves(res_s), jax.tree.leaves(clean_s)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_preemption_sync_every_and_local_flag():
    pre = PreemptionHandler(install=False, sync_every=4)
    assert pre.sync_save_step(0) is None  # not preempted
    pre.trigger()
    assert pre.preempted()
    assert pre.sync_save_step(5) is None  # off-cadence step: no barrier
    assert pre.sync_save_step(8) == 8


def test_sigterm_sets_flag_and_chains_previous():
    import signal

    seen = []
    old = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        pre = PreemptionHandler()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 2
        while not pre.preempted() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pre.preempted()
        assert seen == [signal.SIGTERM]  # previous handler still ran
        pre.uninstall()
    finally:
        signal.signal(signal.SIGTERM, old)


# ---------------------------------------------------------------------------
# stall watchdog

def test_watchdog_dumps_diagnostics_and_rearms(tmp_path):
    from apex_tpu.monitor import JsonlSink, read_jsonl

    path = str(tmp_path / "stall.jsonl")
    hits = []
    with JsonlSink(path, buffer_steps=1) as sink:
        wd = StallWatchdog(0.25, sink=sink, on_stall=hits.append,
                           poll_s=0.05)
        with wd:
            wd.tick(step=3)
            time.sleep(0.5)  # stall fires once (one-shot until re-armed)
            first = wd.stalls
            time.sleep(0.3)
            assert wd.stalls == first  # no re-fire without a tick
            wd.tick(step=4)
            time.sleep(0.5)
    assert wd.stalls == 2 and len(hits) == 2
    recs = list(read_jsonl(path))
    assert len(recs) == 2
    assert recs[0]["step"] == 3 and recs[0]["stall_s"] >= 0.25
    assert "test_resilience" in recs[0]["stacks"]  # this frame is in there


# ---------------------------------------------------------------------------
# ZeRO / DDP state through the manifest path

def test_zero_optimizer_state_dict_roundtrip():
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistAdamState,
    )

    opt = DistributedFusedAdam()
    state = DistAdamState(
        count=jnp.asarray(11, jnp.int32),
        master={"w": jnp.arange(16.0)},
        mu={"w": jnp.linspace(0, 1, 16)},
        nu={"w": jnp.linspace(1, 2, 16)})
    d = opt.state_dict(state)
    template = jax.tree.map(jnp.zeros_like, state)
    restored = opt.load_state_dict(template, d)
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # a different dp degree halves the shard: refused, not mis-bound
    wrong = DistAdamState(
        count=jnp.asarray(0, jnp.int32),
        master={"w": jnp.zeros(8)}, mu={"w": jnp.zeros(8)},
        nu={"w": jnp.zeros(8)})
    with pytest.raises(CheckpointError):
        opt.load_state_dict(wrong, d)


def test_ddp_comm_state_dict_roundtrip():
    from apex_tpu.comm import CompressionConfig
    from apex_tpu.parallel import DistributedDataParallel

    ddp = DistributedDataParallel(
        compression=CompressionConfig(policy="int8_ef"))
    grads = {"w": jnp.ones((4, 4)), "b": jnp.ones(4)}
    cs = ddp.init_comm_state(grads)
    cs = jax.tree.map(lambda r: r + 0.5, cs)  # non-trivial residuals
    d = ddp.comm_state_dict(cs)
    cs2 = ddp.load_comm_state_dict(ddp.init_comm_state(grads), d)
    for got, want in zip(jax.tree.leaves(cs2), jax.tree.leaves(cs)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # no-compression DDP: None stays None through both directions
    plain = DistributedDataParallel()
    assert plain.comm_state_dict(plain.init_comm_state(grads)) is None
    assert plain.load_comm_state_dict(None, None) is None


# ---------------------------------------------------------------------------
# satellites

def test_scaler_load_state_dict_rejects_corrupt_scale():
    sc = LossScaler("dynamic")
    good = sc.state_dict(sc.init_state())
    for bad in (float("nan"), float("inf"), 0.0, -128.0):
        with pytest.raises(ValueError, match="loss_scale"):
            sc.load_state_dict(dict(good, loss_scale=bad))


def test_scaler_load_state_dict_clamps_into_bounds():
    sc = LossScaler("dynamic", min_loss_scale=1.0, max_loss_scale=2.0 ** 24)
    good = sc.state_dict(sc.init_state())
    assert float(sc.load_state_dict(
        dict(good, loss_scale=2.0 ** 40)).loss_scale) == 2.0 ** 24
    assert float(sc.load_state_dict(
        dict(good, loss_scale=2.0 ** -40)).loss_scale) == 1.0
    # static scalers keep their stored value (min/max govern the dynamic
    # policy only)
    st = LossScaler(0.5)
    assert float(st.load_state_dict(
        dict(good, loss_scale=0.5)).loss_scale) == 0.5


def test_pickle_fallback_is_atomic_and_loud(tmp_path, monkeypatch):
    from apex_tpu.utils import checkpoint as uc

    monkeypatch.setattr(uc, "_orbax", lambda: None)
    state = {"w": jnp.arange(6.0)}
    p = uc.save_checkpoint(str(tmp_path / "ck"), state, step=1)
    assert p.endswith(".npz.pkl")
    # no staging litter after a successful publish
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []
    np.testing.assert_array_equal(
        np.asarray(uc.load_checkpoint(p)["w"]), np.arange(6.0))

    # overwrite=False refuses BEFORE writing anything
    with pytest.raises(FileExistsError):
        uc.save_checkpoint(str(tmp_path / "ck"), state, step=1,
                           overwrite=False)

    # a truncated pickle is a clear error naming the path, not a raw
    # UnpicklingError/EOFError
    chaos.corrupt_file(p, mode="truncate", nbytes=16)
    with pytest.raises(ValueError, match="truncated or corrupt"):
        uc.load_checkpoint(p)
    with pytest.raises(ValueError, match=os.path.basename(p)):
        uc.load_checkpoint(p)


def test_orbax_save_honors_overwrite_false(tmp_path):
    from apex_tpu.utils import checkpoint as uc

    if uc._orbax() is None:
        pytest.skip("orbax unavailable")
    state = {"w": jnp.arange(4.0)}
    uc.save_checkpoint(str(tmp_path / "ck"), state, step=1)
    with pytest.raises(FileExistsError):
        uc.save_checkpoint(str(tmp_path / "ck"), state, step=1,
                           overwrite=False)


def test_sink_flushes_on_interpreter_exit(tmp_path):
    """The atexit fallback: a run that never calls close() still lands its
    buffered tail on disk at normal interpreter exit."""
    path = str(tmp_path / "tail.jsonl")
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu'\n"
        "from apex_tpu.monitor import JsonlSink\n"
        f"s = JsonlSink({path!r}, buffer_steps=1000)\n"
        "s.write(step=1, loss=2.5)\n"
        "s.write(step=2, loss=1.5)\n"
        "# no close(), no with-block: atexit must flush\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   cwd=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))), timeout=240)
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert [r["step"] for r in recs] == [1, 2]


def test_sink_close_unregisters_atexit(tmp_path):
    from apex_tpu.monitor import JsonlSink

    s = JsonlSink(str(tmp_path / "x.jsonl"), buffer_steps=10)
    assert s._atexit_registered
    s.write(step=0, a=1.0)
    s.close()
    assert not s._atexit_registered
    s.close()  # idempotent
    with open(tmp_path / "x.jsonl") as f:
        assert len(f.readlines()) == 1


def test_fingerprint_detects_shape_dtype_and_structure_changes():
    base = {"a": jnp.zeros((2, 3)), "b": jnp.zeros(4, jnp.int32)}
    assert fingerprint(base) == fingerprint(
        {"a": jnp.ones((2, 3)), "b": jnp.ones(4, jnp.int32)})
    assert fingerprint(base) != fingerprint(
        {"a": jnp.zeros((3, 2)), "b": jnp.zeros(4, jnp.int32)})
    assert fingerprint(base) != fingerprint(
        {"a": jnp.zeros((2, 3)), "b": jnp.zeros(4, jnp.float32)})
    assert fingerprint(base) != fingerprint({"a": jnp.zeros((2, 3))})

"""Regression bounds for the ring-attention memory study
(``benchmarks/ring_memory.py``): the long-context claim — ring SP divides
the O(S²) attention temp footprint by ~sp — is measured from XLA buffer
assignment, and this test keeps it true.

Caveat pinned here: on the CPU study mesh the ring's per-step chunk
compute falls back to dense (S/sp, S/sp) scores, so total temps scale
O(S²/sp). On the real chip the chunk runs the flash kernel and never
materializes chunk scores — the study UNDER-sells the TPU ring.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "benchmarks"))

from ring_memory import measure  # noqa: E402


@pytest.mark.slow
def test_ring_divides_attention_temps():
    dense = measure(4096, 1)
    ring = measure(4096, 8)
    # sp=8 should cut total attention temps by at least half sp (exact
    # factor depends on XLA's buffer reuse; measured 6.9x at this shape)
    assert dense["temp_mb"] / ring["temp_mb"] > 4.0
    # and the per-device footprint must stay well under one v5e HBM
    assert ring["temp_mb_per_dev"] < 1024

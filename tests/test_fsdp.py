"""FSDP (ZeRO-3) + ParallelismPlan acceptance suite.

Gates: (1) the ParallelismPlan refuses bad axis names / indivisible
shapes / nonsense compositions at CONSTRUCTION; (2) the modeled
``hbm_params_bytes`` accounting shows the acceptance drop (≥1.8× vs the
DDP leg of the DDP+ZeRO-1 baseline at dp=2 on the GPT example — exactly
2.0× — and ≥1.8× vs the ZeRO-1 leg from dp=4 up; the replicated-params
term is what FSDP deletes, so the ZeRO-1 ratio grows with dp); (3)
mesh-gated (graft-only, shard_map-shim-validated like PR 8's rows):
FSDP == DDP+FusedAdam loss-curve parity over ≥5 GPT steps at dp=2
(measured BITWISE on the sim; asserted to 1e-5), the int8 weight-gather
codec within codec tolerance, a mid-run checkpoint save/restore
round-trip rejoining the curve exactly, and the compiled tp/fsdp
program's forward gather ring proven ≥0.5 hidden from its HLO
(``accounting.overlap_report`` — the PR-4 flagship contract in FSDP
position); (4) the sharded-checkpoint manifest path saves local shards
and refuses dp-degree / shard-shape skew.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.comm import CompressionConfig
from apex_tpu.fsdp import (
    FSDP,
    FSDPAdam,
    FSDPAdamState,
    LeafMeta,
    fsdp_step_wire_bytes,
    hbm_params_bytes,
    hbm_reduction,
    param_gather_wire_bytes,
)
from apex_tpu.parallel import ParallelismPlan
from apex_tpu.parallel.mesh import build_mesh

MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")
mesh_only = pytest.mark.skipif(
    not MESH_OK,
    reason="mesh programs need jax.shard_map/lax.axis_size (graft jax)")


# ---------------------------------------------------------------------------
# ParallelismPlan validation (stock-safe): bad plans die at construction


def test_plan_presets_construct():
    for name in ("ddp", "zero1", "fsdp", "fsdp+tp"):
        plan = ParallelismPlan.preset(name)
        desc = plan.describe()
        assert plan.data in desc and "mesh:" in desc
    assert ParallelismPlan.preset("fsdp+tp").tp == 2
    assert ParallelismPlan.preset("fsdp+tp").overlap_comm


@pytest.mark.parametrize("bad", [
    dict(data="zzz"),
    dict(optimizer="sgd"),
    dict(dp_axis="rows"),  # not in the mesh vocabulary
    dict(tp=0),
    dict(pp=-2),
    dict(dp=0),
    dict(data="ddp", weight_gather=CompressionConfig("int8")),
    dict(data="fsdp", e5m2_allgather=True),
    dict(data="fsdp", optimizer="lamb"),
    dict(data="fsdp", compression=CompressionConfig("int8_ef")),
    dict(data="fsdp",
         weight_gather=CompressionConfig("int8", stochastic_rounding=True)),
    dict(fused_update="sometimes"),
])
def test_plan_refuses_bad_construction(bad):
    with pytest.raises(ValueError):
        ParallelismPlan(**bad)


def test_plan_refuses_unknown_preset():
    with pytest.raises(ValueError, match="preset"):
        ParallelismPlan.preset("fsdp+pp")


def test_plan_mesh_indivisible_fails_loudly():
    n = len(jax.devices())
    with pytest.raises(ValueError, match="divisible"):
        ParallelismPlan.preset("fsdp", tp=n + 1).mesh()


def test_plan_component_cross_checks():
    with pytest.raises(ValueError, match="reduce-scatter"):
        ParallelismPlan.preset("fsdp").ddp()
    with pytest.raises(ValueError, match="not fsdp"):
        ParallelismPlan.preset("ddp").fsdp()


def test_plan_builds_the_right_optimizer():
    from apex_tpu.contrib.optimizers import (
        DistributedFusedAdam,
        DistributedFusedLAMB,
    )

    assert isinstance(ParallelismPlan.preset("zero1").build_optimizer(),
                      DistributedFusedAdam)
    assert isinstance(
        ParallelismPlan.preset("zero1", optimizer="lamb").build_optimizer(),
        DistributedFusedLAMB)
    assert isinstance(ParallelismPlan.preset("fsdp").build_optimizer(),
                      FSDPAdam)


def test_fsdp_engine_refuses_stateful_codecs():
    with pytest.raises(ValueError, match="error feedback"):
        FSDP(compression=CompressionConfig("int8_ef"))
    with pytest.raises(ValueError, match="stochastic"):
        FSDP(weight_gather=CompressionConfig(
            "int8", stochastic_rounding=True))


def test_fsdp_shard_multiple_is_lcm_of_codecs():
    f = FSDP(compression=CompressionConfig("int8", block_size=192),
             weight_gather=CompressionConfig("int8", block_size=256))
    assert f.shard_multiple == 768  # lcm(192, 256)
    assert FSDP().shard_multiple == 1


# ---------------------------------------------------------------------------
# the HBM acceptance accounting (stock-safe: pure shape arithmetic)


def _gpt_meta(dtype="float32"):
    """LeafMeta of the GPT example fixture (shapes only — no init)."""
    from apex_tpu.transformer.testing import GPTConfig

    cfg = GPTConfig(vocab_size=128, max_seq=32, hidden=64, num_layers=2,
                    num_heads=2, dtype=jnp.float32)
    h, f, L, v = cfg.hidden, cfg.ffn_hidden, cfg.num_layers, cfg.vocab_size
    leaf = lambda *s: LeafMeta(tuple(s), dtype)  # noqa: E731
    return {
        "embed": {"tok": leaf(v, h), "pos": leaf(cfg.max_seq, h)},
        "layers": {
            "ln1_w": leaf(L, h), "ln1_b": leaf(L, h),
            "qkv_kernel": leaf(L, h, 3 * h), "qkv_bias": leaf(L, 3 * h),
            "out_kernel": leaf(L, h, h), "out_bias": leaf(L, h),
            "ln2_w": leaf(L, h), "ln2_b": leaf(L, h),
            "fc1_kernel": leaf(L, h, f), "fc1_bias": leaf(L, f),
            "fc2_kernel": leaf(L, f, h), "fc2_bias": leaf(L, h),
        },
        "head": {"ln_w": leaf(h), "ln_b": leaf(h)},
    }


def test_hbm_drop_acceptance_gate():
    """THE acceptance assertion: per-chip param+grad+optimizer-state HBM
    for the GPT example at dp=2 drops ≥1.8× vs the DDP leg of the
    baseline pair (measured exactly 2.0×: fp32 params+grads+m+v replicated
    vs everything fp32 sharded), with the ZeRO-1 leg at 1.75× (its
    replicated params+grads are half the total at dp=2) crossing 1.8×
    from dp=4 (2.75×) and reaching 16.75× at dp=32."""
    meta = _gpt_meta()
    assert hbm_reduction(meta, world=2, baseline="ddp") >= 1.8
    assert abs(hbm_reduction(meta, world=2, baseline="ddp") - 2.0) < 1e-6
    z2 = hbm_reduction(meta, world=2, baseline="zero1")
    assert 1.7 <= z2 < 1.8  # honest: the zero1 win at dp=2 is 1.75x
    assert hbm_reduction(meta, world=4, baseline="zero1") >= 1.8
    assert hbm_reduction(meta, world=8, baseline="zero1") >= 2.7
    assert hbm_reduction(meta, world=32, baseline="zero1") >= 5.0


def test_hbm_breakdown_terms():
    meta = _gpt_meta()
    n = sum(m.size for m in jax.tree_util.tree_leaves(
        meta, is_leaf=lambda x: isinstance(x, LeafMeta)))
    ddp = hbm_params_bytes(meta, strategy="ddp", world=2)
    z = hbm_params_bytes(meta, strategy="zero1", world=2)
    f = hbm_params_bytes(meta, strategy="fsdp", world=2)
    # ddp fp32: params 4n + grads 4n + m+v 8n (no master at fp32)
    assert ddp["total"] == 16 * n
    # zero1 keeps replicated params+grads, shards the 12n fp32 state
    assert z["params_bytes"] == 4 * n and z["grads_bytes"] == 4 * n
    assert z["opt_state_bytes"] == pytest.approx(6 * n, rel=0.01)
    # fsdp: NO replicated params; state+grads all sharded
    assert f["params_bytes"] == 0
    assert f["total"] == pytest.approx(8 * n, rel=0.01)
    # the gather working set stays leaf-sized, not model-sized
    assert 0 < f["gather_workspace_bytes"] < 0.2 * ddp["total"]
    with pytest.raises(ValueError, match="strategy"):
        hbm_params_bytes(meta, strategy="zero3", world=2)


def test_plan_hbm_accounting_matches_module():
    meta = _gpt_meta()
    plan = ParallelismPlan.preset("fsdp")
    assert plan.hbm_params_bytes(meta, world=2) == hbm_params_bytes(
        meta, strategy="fsdp", world=2)


# ---------------------------------------------------------------------------
# wire-byte models (stock-safe)


def test_param_gather_ring_wire_byte_neutrality():
    """The fused ring moves EXACTLY the monolithic tiled all-gather's
    bytes: shard*(W-1) == full*(W-1)/W; backward adds the fp32 dW ring."""
    from apex_tpu.comm import (
        all_gather_wire_bytes,
        matmul_param_gather_wire_bytes,
    )

    shard, itemsize, w = 4096, 2, 8
    ring = matmul_param_gather_wire_bytes(shard, itemsize, w)
    mono = all_gather_wire_bytes(shard * w, itemsize, w)
    assert ring == mono == shard * itemsize * (w - 1)
    bwd = matmul_param_gather_wire_bytes(shard, itemsize, w, backward=True)
    assert bwd == ring + shard * 4 * (w - 1)
    assert matmul_param_gather_wire_bytes(shard, itemsize, 1) == 0.0


def test_fsdp_step_wire_model():
    meta = _gpt_meta()
    fp32 = fsdp_step_wire_bytes(meta, 8)
    int8 = fsdp_step_wire_bytes(
        meta, 8,
        compression=CompressionConfig("int8", min_elements=256),
        weight_gather=CompressionConfig("int8", min_elements=256),
        shard_multiple=256)
    assert 0 < int8 < fp32  # the codec must actually shrink the wire
    # remat replays the forward gather: one extra gather leg
    remat = fsdp_step_wire_bytes(meta, 8, remat_gathers=2)
    assert remat == fp32 + param_gather_wire_bytes(meta, 8)
    f = FSDP(weight_gather=CompressionConfig("int8", min_elements=256))
    assert f.gather_wire_bytes(meta, 8) < FSDP().gather_wire_bytes(meta, 8)


def test_regress_polarity_covers_fsdp_headliners():
    """The watch-stage gate actually covers the FSDP record: memory and
    wire growth regress, hidden_fraction/reduction shrink regress."""
    from apex_tpu.monitor.regress import classify_metric

    assert classify_metric("hbm_params_bytes_fsdp") == "lower"
    assert classify_metric("peak_hbm_bytes_zero1") == "lower"
    assert classify_metric("ring.exposed_bytes") == "lower"
    assert classify_metric("wire_bytes_fsdp") == "lower"
    assert classify_metric("step_ms_fsdp") == "lower"
    assert classify_metric("ring.hidden_fraction") == "higher"
    assert classify_metric("ring.hidden_bytes") == "higher"
    assert classify_metric("hbm_reduction_vs_zero1") == "higher"


# ---------------------------------------------------------------------------
# sharded-checkpoint manifest path (stock-safe: forced predicate on the
# single-process mesh, plus duck-typed fakes for the refusal ladder)


@pytest.fixture
def sharded_ckpt(monkeypatch, tmp_path):
    """Force the cross-process predicate for dp-sharded (64,) leaves so
    the per-shard path runs on this single-process mesh."""
    from apex_tpu.resilience import checkpoint as ck

    monkeypatch.setattr(
        ck, "_is_cross_process",
        lambda a: hasattr(a, "addressable_shards") and getattr(
            a, "shape", ()) == (64,))
    from jax.sharding import NamedSharding

    mesh = build_mesh(tp=1, pp=1, sp=1)
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32),
                       NamedSharding(mesh, P("dp")))
    state = {"w": x, "b": jnp.ones((3,))}
    return ck, str(tmp_path), state, x


def test_sharded_checkpoint_round_trip(sharded_ckpt):
    ck, d, state, x = sharded_ckpt
    mgr = ck.CheckpointManager(d)
    mgr.save(state, 7, block=True)
    path = mgr.step_path(7)
    # local shards landed under the per-process shard dir, fingerprinted
    assert os.path.isdir(os.path.join(path, "shard-p0"))
    sm = json.load(open(os.path.join(path, "shard-p0", "manifest.json")))
    assert sm["process_count"] == 1 and len(sm["shards"]) == 8
    assert mgr.latest_valid() == path
    got, step = mgr.restore(target=state)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(x))
    assert got["w"].sharding == x.sharding  # rebound onto the LIVE layout


def test_sharded_checkpoint_refuses_dp_degree_skew(sharded_ckpt):
    ck, d, state, x = sharded_ckpt
    mgr = ck.CheckpointManager(d)
    mgr.save(state, 1, block=True)
    mp = os.path.join(mgr.step_path(1), "manifest.json")
    m = json.load(open(mp))
    (key,) = list(m["sharded"])
    m["sharded"][key]["dp_degree"] = 4
    json.dump(m, open(mp, "w"))
    # an explicit-path restore refuses loudly (dp degree 4 recorded, shard
    # dirs for processes 1-3 absent) ...
    with pytest.raises(ck.CheckpointError, match="dp degree"):
        mgr.restore(target=state, path=mgr.step_path(1))
    # ... and discovery skips it: every process reaches the same verdict,
    # so no rank restores state its peers do not have
    assert mgr.latest_valid() is None
    with pytest.raises(ck.CheckpointError, match="no valid checkpoint"):
        mgr.restore(target=state)


def test_sharded_checkpoint_refuses_shard_shape_skew(sharded_ckpt):
    """A template sliced differently (different dp degree -> different
    shard placement) is refused before any rebinding."""
    ck, d, state, x = sharded_ckpt
    mgr = ck.CheckpointManager(d)
    mgr.save(state, 1, block=True)
    from jax.sharding import NamedSharding

    mesh2 = build_mesh(tp=4, pp=1, sp=1)  # dp=2: 2 shards of 32, not 8x8
    y = jax.device_put(jnp.arange(64, dtype=jnp.float32),
                       NamedSharding(mesh2, P("dp")))
    with pytest.raises(ck.CheckpointError, match="skew"):
        mgr.restore(target={"w": y, "b": state["b"]})


def test_sharded_checkpoint_torn_shard_dir_is_invalid(sharded_ckpt):
    """A crash between process 0's publish and a peer's shard rename
    leaves the shard dir missing — verify() must call that torn, and
    latest_valid() must fall back to the previous good checkpoint."""
    import shutil

    ck, d, state, x = sharded_ckpt
    mgr = ck.CheckpointManager(d)
    mgr.save(state, 1, block=True)
    mgr.save(state, 2, block=True)
    shutil.rmtree(os.path.join(mgr.step_path(2), "shard-p0"))
    assert not mgr.verify(mgr.step_path(2))
    assert mgr.latest_valid() == mgr.step_path(1)


def test_sharded_multiwriter_save_refused(sharded_ckpt, monkeypatch):
    """process0_only=False on a multi-process sharded save is refused:
    every process would publish its own step dir holding only its own
    shard-p{K}, the last os.replace wins, and every save verifies torn."""
    ck, d, state, x = sharded_ckpt
    monkeypatch.setattr(ck, "_process_info", lambda: (0, 2))
    mgr = ck.CheckpointManager(d, process0_only=False)
    with pytest.raises(ck.CheckpointError, match="process0_only"):
        mgr.save(state, 1, block=True)
    assert mgr.latest_valid() is None  # nothing was written


def test_genuinely_non_addressable_still_refused():
    """The loud CheckpointError survives for leaves with no addressable
    replica-0 shard."""
    from apex_tpu.resilience import checkpoint as ck

    class _Shard:
        replica_id = 1  # only replicas of other processes' data

        def __init__(self):
            self.index = (slice(0, 4),)
            self.data = np.zeros(4)

    class _Fake:
        shape = (8,)
        dtype = np.float32
        is_fully_addressable = False
        is_fully_replicated = False
        addressable_shards = [_Shard()]

    with pytest.raises(ck.CheckpointError, match="non-addressable"):
        ck.state_dict({"x": _Fake()})


def test_state_dict_sharded_leaf_round_trip(monkeypatch):
    from apex_tpu.resilience import checkpoint as ck

    monkeypatch.setattr(
        ck, "_is_cross_process",
        lambda a: hasattr(a, "addressable_shards") and getattr(
            a, "shape", ()) == (64,))
    from jax.sharding import NamedSharding

    mesh = build_mesh(tp=1, pp=1, sp=1)
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32),
                       NamedSharding(mesh, P("dp")))
    sd = ck.state_dict({"w": x})
    assert sd["leaves"]["0"]["__sharded__"]
    assert len(sd["leaves"]["0"]["shards"]) == 8
    back = ck.load_state_dict({"w": x}, sd)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(x))


# ---------------------------------------------------------------------------
# mesh-gated: the ring op, training parity, checkpoint rejoin, HLO gate


B, S = 8, 32


def _gpt_fixture():
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    cfg = GPTConfig(vocab_size=128, max_seq=S, hidden=64, num_layers=2,
                    num_heads=2, dtype=jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, 128)
    return cfg, params, tok


def _mesh_dp(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} of the 8 virtual devices")
    return build_mesh(tp=1, pp=1, sp=1, devices=jax.devices()[:n])


def _state_specs(params):
    shard = jax.tree_util.tree_map(lambda _: P("dp"), params)
    return FSDPAdamState(count=P(), master=shard, mu=shard, nu=shard)


@mesh_only
@pytest.mark.parametrize("bidirectional", [False, True])
def test_matmul_param_gather_matches_monolithic(bidirectional):
    """Forward BITWISE vs x @ all_gather(w) (the gathered dim is
    non-contracting); dX/dW to fp-reorder tolerance (ring association)."""
    from apex_tpu.comm import matmul_param_gather

    mesh = _mesh_dp(8)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(ks[0], (8, 4, 16), jnp.float32)
    w = jax.random.normal(ks[1], (16, 32), jnp.float32)
    cot = jax.random.normal(ks[2], (8, 4, 32), jnp.float32)

    def run(body):
        def loss(x, w, cot):
            def inner(x, w, cot):
                return lax.psum(jnp.sum(body(x[0], w) * cot[0]), "dp")

            return jax.shard_map(
                inner, mesh=mesh,
                in_specs=(P("dp"), P(None, "dp"), P("dp")),
                out_specs=P())(x, w, cot)

        return jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(x, w, cot)

    fused = lambda x, w: matmul_param_gather(  # noqa: E731
        x, w, axis_name="dp", bidirectional=bidirectional)
    mono = lambda x, w: jnp.dot(  # noqa: E731
        x, lax.all_gather(w, "dp", axis=1, tiled=True))
    vf, (gxf, gwf) = run(fused)
    vm, (gxm, gwm) = run(mono)
    np.testing.assert_array_equal(np.asarray(vf), np.asarray(vm))
    np.testing.assert_allclose(np.asarray(gxf), np.asarray(gxm),
                               rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gwf), np.asarray(gwm),
                               rtol=2e-5, atol=1e-5)


def _fsdp_gpt_losses(steps=6, weight_gather=None, compression=None,
                     ckpt_dir=None, lr=2e-3):
    """FSDP-trained loss curve on the GPT fixture at dp=2; optionally
    round-trips the FULL optimizer state through a CheckpointManager
    mid-run (the rejoin contract)."""
    from apex_tpu.transformer.testing import gpt_loss

    cfg, params, tok = _gpt_fixture()
    mesh = _mesh_dp(2)
    fsdp = FSDP(weight_gather=weight_gather, compression=compression)
    opt = FSDPAdam(fsdp=fsdp, lr=lr)
    meta = fsdp.meta(params)
    pspecs = jax.tree_util.tree_map(lambda _: P(), params)
    sspec = _state_specs(params)
    init = jax.jit(jax.shard_map(
        opt.init, mesh=mesh, in_specs=(pspecs,), out_specs=sspec,
        check_vma=False))
    state = init(params)

    def body(st, t):
        def loss_fn(master):
            return gpt_loss(fsdp.gather(master, meta), t, t, cfg)

        l, g = jax.value_and_grad(loss_fn)(st.master)
        st = opt.step(g, st)
        return st, lax.pmean(l, "dp")

    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(sspec, P("dp")),
        out_specs=(sspec, P()), check_vma=False))
    losses = []
    for i in range(steps):
        state, l = step(state, tok)
        losses.append(float(l))
        if ckpt_dir is not None and i == steps // 2:
            # the satellite contract: shard state survives the manifest
            # path exactly — the continued curve cannot drift
            from apex_tpu.resilience import CheckpointManager

            mgr = CheckpointManager(ckpt_dir)
            mgr.save(state, i + 1, block=True)
            fresh = jax.tree_util.tree_map(jnp.zeros_like, state)
            state, got_step = mgr.restore(target=fresh)
            assert got_step == i + 1
    return losses


def _ddp_gpt_losses(steps=6, lr=2e-3):
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.transformer.testing import gpt_loss

    cfg, params, tok = _gpt_fixture()
    mesh = _mesh_dp(2)
    opt = FusedAdam(lr=lr)
    opt_state = opt.init(params)
    ddp = DistributedDataParallel()

    def body(p, s, t):
        l, g = jax.value_and_grad(lambda p: gpt_loss(p, t, t, cfg))(p)
        g = ddp.average_gradients(g)
        updates, s = opt.update(g, s, p)
        return (jax.tree_util.tree_map(lambda p, u: p + u, p, updates), s,
                lax.pmean(l, "dp"))

    pspecs = jax.tree_util.tree_map(lambda _: P(), params)
    ospecs = jax.tree_util.tree_map(lambda _: P(), opt_state)
    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(pspecs, ospecs, P("dp")),
        out_specs=(pspecs, ospecs, P()), check_vma=False))
    losses = []
    p, s = params, opt_state
    for _ in range(steps):
        p, s, l = step(p, s, tok)
        losses.append(float(l))
    return losses


@mesh_only
def test_fsdp_matches_ddp_loss_curve():
    """ACCEPTANCE: FSDP == DDP+FusedAdam over ≥5 GPT steps at dp=2.
    The shared Adam tail + exact gather/reduce-scatter make the curves
    bitwise on the sim; asserted to 1e-5 (fp-reorder headroom), plus
    training must actually progress."""
    base = _ddp_gpt_losses()
    fsdp = _fsdp_gpt_losses()
    assert len(fsdp) >= 5
    assert base[-1] < base[0] - 0.5, base
    np.testing.assert_allclose(fsdp, base, atol=1e-5)


@mesh_only
def test_fsdp_int8_weight_gather_within_codec_tolerance():
    """int8 param-gather wire: the curve tracks the exact one within
    codec tolerance (measured ~1e-3 max divergence; 0.02 is margin) —
    the fp32 master stays exact, only the gathered copy is rounded."""
    base = _ddp_gpt_losses()
    int8 = _fsdp_gpt_losses(
        weight_gather=CompressionConfig("int8", min_elements=256))
    np.testing.assert_allclose(int8, base, atol=0.02)
    assert any(a != b for a, b in zip(int8, base)), \
        "the codec should actually round something"


@mesh_only
def test_fsdp_int8_grad_reduce_within_tolerance():
    base = _ddp_gpt_losses()
    int8 = _fsdp_gpt_losses(
        compression=CompressionConfig("int8", min_elements=256))
    np.testing.assert_allclose(int8, base, atol=0.05)


@mesh_only
def test_fsdp_int4_weight_gather_within_codec_tolerance():
    """The sub-8-bit FSDP wire: nibble-packed int4 param gathers (half
    the int8 gather bytes again) keep the curve within the ±7-code
    tolerance of the exact run — the fp32 master stays exact, only the
    gathered model-dtype copy is rounded, so the loss never drifts, it
    just wobbles inside the codec band."""
    base = _ddp_gpt_losses()
    int4 = _fsdp_gpt_losses(
        weight_gather=CompressionConfig("int4", block_size=128,
                                        min_elements=256))
    np.testing.assert_allclose(int4, base, atol=0.1)
    assert any(a != b for a, b in zip(int4, base)), \
        "the codec should actually round something"
    assert int4[-1] < int4[0] - 0.4, int4  # training still progresses


@mesh_only
def test_fsdp_int4_grad_reduce_within_tolerance():
    base = _ddp_gpt_losses()
    int4 = _fsdp_gpt_losses(
        compression=CompressionConfig("int4", block_size=128,
                                      min_elements=256))
    np.testing.assert_allclose(int4, base, atol=0.15)
    assert int4[-1] < int4[0] - 0.4, int4


@mesh_only
def test_fsdp_checkpoint_midrun_rejoins_exactly(tmp_path):
    """Mid-run save → zeroed state → restore: the continued curve is
    IDENTICAL to the uninterrupted run (shard-exact manifest path)."""
    plain = _fsdp_gpt_losses()
    rejoined = _fsdp_gpt_losses(ckpt_dir=str(tmp_path))
    np.testing.assert_array_equal(plain, rejoined)


@mesh_only
def test_fsdp_adam_matches_fused_adam_singleleaf():
    """The shard optimizer is FusedAdam given the same grads (the ZeRO-1
    parity contract, now for the stage-3 optimizer)."""
    from apex_tpu.optimizers import FusedAdam

    key = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(key, (13, 7)),
              "b": jax.random.normal(jax.random.fold_in(key, 1), (5,))}
    grads = jax.tree_util.tree_map(
        lambda x: jax.random.normal(jax.random.fold_in(key, 2), x.shape)
        * 0.1, params)
    mesh = _mesh_dp(8)
    fsdp = FSDP()
    opt = FSDPAdam(fsdp=fsdp, lr=1e-2, weight_decay=0.01)
    meta = fsdp.meta(params)

    def run(p, g):
        st = opt.init(p)
        world = lax.axis_size("dp")
        for _ in range(3):
            def loss_fn(master):
                full = fsdp.gather(master, meta)
                # sum(g*p): grad of this IS g (dp-summed by the VJP)
                return lax.psum(
                    sum(jnp.vdot(a, b) for a, b in zip(
                        jax.tree_util.tree_leaves(full),
                        jax.tree_util.tree_leaves(g))), "dp") / world
            gs = jax.grad(loss_fn)(st.master)
            st = opt.step(gs, st)
        return fsdp.gather(st.master, meta)

    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    got = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(pspec, pspec), out_specs=pspec,
        check_vma=False))(params, grads)

    ref = FusedAdam(lr=1e-2, weight_decay=0.01)
    rs = ref.init(params)
    want = params
    for _ in range(3):
        upd, rs = ref.update(grads, rs, want)
        want = jax.tree_util.tree_map(lambda p, u: p + u, want, upd)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   atol=1e-6, err_msg=k)


@mesh_only
def test_fsdp_step_records_metrics():
    from apex_tpu.monitor import Metrics

    params = {"w": jnp.ones((64, 8))}
    mesh = _mesh_dp(8)
    fsdp = FSDP()
    opt = FSDPAdam(fsdp=fsdp, lr=1e-2)
    meta = fsdp.meta(params)
    metrics = Metrics({"grad_norm": 0.0, "param_norm": 0.0,
                       "update_norm": 0.0, "param_gather_bytes": 0.0,
                       "comm_wire_bytes": 0.0, "hbm_params_bytes": 0.0})

    def run(p, m):
        st = opt.init(p)
        g = jax.grad(lambda s: lax.psum(
            jnp.sum(fsdp.gather(s, meta)["w"] ** 2), "dp"))(st.master)
        st, m = opt.step(g, st, metrics=m, meta=meta)
        return m

    got = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(jax.tree_util.tree_map(lambda _: P(),
                                                         params), P()),
        out_specs=P(), check_vma=False))(params, metrics)
    d = got.as_dict()
    assert d["grad_norm"] > 0 and d["param_norm"] > 0
    assert d["param_gather_bytes"] == param_gather_wire_bytes(meta, 8)
    assert d["hbm_params_bytes"] == hbm_params_bytes(
        meta, strategy="fsdp", world=8)["total"]
    assert d["comm_wire_bytes"] > d["param_gather_bytes"]


@mesh_only
def test_flagship_tp_fsdp_gather_ring_proven_hidden():
    """ACCEPTANCE: the compiled tp/fsdp program's forward weight-gather
    rings are ≥0.5 hidden, proven from the HLO (the PR-4 flagship
    contract in FSDP position): a two-layer MLP whose weights are
    tp-column-split AND fsdp-sharded over dp on a dp=2 x tp=4 mesh."""
    from apex_tpu.comm import overlap_report

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=4, pp=1, sp=1)  # dp=2
    fsdp = FSDP()
    d_in, d_h = 128, 256
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, d_in), jnp.float32)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (d_in, d_h), jnp.float32)
    w2 = jax.random.normal(jax.random.PRNGKey(2), (d_h, d_in), jnp.float32)

    def loss(x, w1, w2):
        def body(x, w1s, w2s):
            # column-parallel entry over tp; its tp-local weight fsdp-
            # sharded over dp and gathered through the overlapped ring
            h = jax.nn.gelu(fsdp.linear(x[0], w1s))
            # row-parallel exit: the weight's gather dim is CONTRACTING,
            # so this leaf rides the plain dp all-gather (the non-ring
            # FSDP position), then the tp psum
            w2f = lax.all_gather(w2s, "dp", axis=0, tiled=True)
            y = lax.psum(jnp.dot(h, w2f), "tp")
            return lax.psum(jnp.sum(y * y), "dp")

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("dp"), P(None, ("tp", "dp")), P(("tp", "dp"))),
            out_specs=P())(x, w1, w2)

    compiled = jax.jit(jax.value_and_grad(loss, argnums=(1, 2))).lower(
        x, w1, w2).compile()
    rep = overlap_report(compiled.as_text())
    assert rep.permutes > 0, f"no gather rings in the program: {rep}"
    assert rep.hidden >= 2, rep
    assert rep.hidden_fraction >= 0.5, rep


@mesh_only
def test_plan_drives_fsdp_end_to_end():
    """The ParallelismPlan IS the wiring: preset('fsdp') -> mesh,
    engine, optimizer; one train step runs and shrinks the loss."""
    from apex_tpu.transformer.testing import gpt_loss

    cfg, params, tok = _gpt_fixture()
    plan = ParallelismPlan.preset("fsdp")
    mesh = plan.mesh(devices=jax.devices()[:2])
    fsdp = plan.fsdp()
    opt = plan.build_optimizer(lr=2e-3)
    meta = fsdp.meta(params)
    pspecs = jax.tree_util.tree_map(lambda _: P(), params)
    sspec = _state_specs(params)
    init = jax.jit(jax.shard_map(
        opt.init, mesh=mesh, in_specs=(pspecs,), out_specs=sspec,
        check_vma=False))

    def body(st, t):
        def loss_fn(master):
            return gpt_loss(fsdp.gather(master, meta), t, t, cfg)

        l, g = jax.value_and_grad(loss_fn)(st.master)
        return opt.step(g, st), lax.pmean(l, "dp")

    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(sspec, P("dp")),
        out_specs=(sspec, P()), check_vma=False))
    state = init(params)
    first = None
    for _ in range(3):
        state, l = step(state, tok)
        first = first if first is not None else float(l)
    assert float(l) < first

"""ZeRO-style optimizer tests — ref tests/L0/run_optimizers/test_dist_adam.py:
the dp-sharded optimizer must produce the SAME parameters as the non-sharded
fused optimizer given the same gradients, while holding only 1/dp state."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.parallel.mesh import build_mesh


def _params_grads(key):
    p = {
        "w": jax.random.normal(key, (13, 7)),  # deliberately non-multiple of 8
        "b": jax.random.normal(jax.random.fold_in(key, 1), (5,)),
    }
    g = jax.tree.map(
        lambda x: jax.random.normal(jax.random.fold_in(key, 2), x.shape) * 0.1,
        p)
    return p, g


def test_dist_adam_matches_fused_adam():
    params, grads = _params_grads(jax.random.PRNGKey(0))
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01)

    def run(p, g):
        state = opt.init(p)
        for _ in range(3):
            p, state = opt.step(g, state, p)
        # state shards are 1/8 (padded) of each param
        assert state.mu["w"].shape == (12,)  # ceil(91/8)
        return p

    got = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(), params),) * 2,
        out_specs=jax.tree.map(lambda _: P(), params),
        check_vma=False,  # replicated-by-construction all-gather output
    ))(params, grads)

    ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
    ref_state = ref_opt.init(params)
    want = params
    for _ in range(3):
        updates, ref_state = ref_opt.update(grads, ref_state, want)
        want = jax.tree.map(lambda p, u: p + u, want, updates)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), atol=1e-6, err_msg=k)


def test_dist_adam_sums_grads_over_dp():
    # different grads per dp rank: the reduce-scatter must average them
    params = {"w": jnp.zeros((8, 4))}
    mesh = build_mesh(tp=1, pp=1, sp=1)
    opt = DistributedFusedAdam(lr=1.0, betas=(0.0, 0.999), eps=1e-8,
                               weight_decay=0.0)

    per_rank_g = jnp.stack(
        [jnp.full((8, 4), float(i)) for i in range(8)])  # mean = 3.5

    def run(p, g):
        g = jax.tree.map(lambda x: x[0], g)  # my rank's grad
        state = opt.init(p)
        p, state = opt.step(g, state, p)
        return p

    got = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=({"w": P()}, {"w": P("dp")}),
        out_specs={"w": P()},
        check_vma=False,
    ))(params, {"w": per_rank_g})
    # beta1=0: update direction = sign-ish mhat/sqrt(vhat); with identical
    # entries everywhere the update must be identical too — and nonzero
    v = np.asarray(got["w"])
    assert np.allclose(v, v.flat[0])
    assert abs(v.flat[0]) > 0.1


def test_dist_lamb_matches_fused_lamb():
    params, grads = _params_grads(jax.random.PRNGKey(1))
    mesh = build_mesh(tp=1, pp=1, sp=1)
    opt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                               max_grad_norm=None, grad_averaging=True)

    def run(p, g):
        state = opt.init(p)
        for _ in range(3):
            p, state = opt.step(g, state, p)
        return p

    got = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(), params),) * 2,
        out_specs=jax.tree.map(lambda _: P(), params),
        check_vma=False,
    ))(params, grads)

    ref_opt = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=0.0)
    ref_state = ref_opt.init(params)
    want = params
    for _ in range(3):
        updates, ref_state = ref_opt.update(grads, ref_state, want)
        want = jax.tree.map(lambda p, u: p + u, want, updates)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), atol=2e-6, err_msg=k)


def test_dist_adam_grad_clipping_and_scale():
    params = {"w": jnp.ones((4, 4))}
    big = {"w": jnp.full((4, 4), 100.0)}
    mesh = build_mesh(tp=1, pp=1, sp=1)
    opt = DistributedFusedAdam(lr=1e-2, max_grad_norm=1.0)

    def run(p, g):
        state = opt.init(p)
        p2, _ = opt.step(g, state, p, scale=jnp.asarray(2.0))
        return p2

    got = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=({"w": P()}, {"w": P()}),
        out_specs={"w": P()}, check_vma=False,
    ))(params, big)
    # huge grads clipped to norm 1 -> bounded first step
    delta = np.abs(np.asarray(got["w"]) - 1.0).max()
    assert 0 < delta < 0.05


def test_dist_adam_e5m2_allgather():
    """Ref e5m2_allgather: fp8-transport param all-gather. Masters stay
    fp32-exact (bit-compared against the uncompressed run — compression
    only touches the wire); the replicated params carry only the e5m2
    rounding of the model dtype (|rel| <= 2^-2 on normals)."""
    params, grads = _params_grads(jax.random.PRNGKey(3))
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8

    def run(e5m2):
        opt = DistributedFusedAdam(lr=1e-2, e5m2_allgather=e5m2)

        def body(p, g):
            state = opt.init(p)
            for _ in range(3):
                p, state = opt.step(g, state, p)
            return p, state.master

        return jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(), params),) * 2,
            out_specs=(jax.tree.map(lambda _: P(), params),
                       jax.tree.map(lambda _: P("dp"), params)),
            check_vma=False,
        ))(params, grads)

    p_c, m_c = run(True)
    p_u, m_u = run(False)
    for k in ("w", "b"):
        # the sharded fp32 masters are bit-identical: compression only
        # touches the wire format of the gather
        np.testing.assert_array_equal(np.asarray(m_c[k]), np.asarray(m_u[k]),
                                      err_msg=f"master {k}")
        a, b = np.asarray(p_c[k], np.float32), np.asarray(p_u[k], np.float32)
        # e5m2 keeps 2 mantissa bits: worst-case relative step 25%
        np.testing.assert_allclose(a, b, rtol=0.25, atol=1e-6,
                                   err_msg=f"params {k}")
        assert np.any(a != b), "compression should actually round something"


MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")


@pytest.mark.skipif(not MESH_OK,
                    reason="needs graft jax (jax.shard_map + lax.axis_size)")
@pytest.mark.parametrize("cls_name", ["adam", "lamb"])
def test_zero_fused_update_matches_unfused(cls_name):
    """fused_update='on' (the ops/fused_update.py Pallas tail) produces
    the same parameters as the per-op chain — the megakernel-PR gate for
    the ZeRO update tail. Tolerance is fp reassociation noise only."""
    params, grads = _params_grads(jax.random.PRNGKey(3))
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8

    def run(mode):
        cls = (DistributedFusedAdam if cls_name == "adam"
               else DistributedFusedLAMB)
        opt = cls(lr=1e-2, weight_decay=0.01, fused_update=mode)

        def body(p, g):
            state = opt.init(p)
            for _ in range(3):
                p, state = opt.step(g, state, p)
            return p

        return jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(), params),) * 2,
            out_specs=jax.tree.map(lambda _: P(), params),
            check_vma=False,
        ))(params, grads)

    got, want = run("on"), run("off")
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=5e-6, atol=5e-7, err_msg=k)

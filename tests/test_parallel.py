"""DDP + SyncBatchNorm tests on the 8-device virtual mesh — ref
tests/distributed/ (DDP race/overlap test checks grad values vs analytic
expectation; synced_batchnorm compares vs single-process BN over the full
batch)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import DistributedDataParallel, Reducer, SyncBatchNorm
from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.parallel.sync_batchnorm import create_syncbn_process_group, sync_batch_stats


def test_ddp_average_matches_full_batch_grad(mesh8):
    """The DDP correctness invariant: per-shard grads averaged over dp ==
    grad of the mean loss over the full batch."""
    k = jax.random.PRNGKey(0)
    W = jax.random.normal(k, (8, 4))
    X = jax.random.normal(jax.random.fold_in(k, 1), (16, 8))
    Y = jax.random.normal(jax.random.fold_in(k, 2), (16, 4))

    def loss(W, x, y):
        return jnp.mean((x @ W - y) ** 2)

    ddp = DistributedDataParallel()

    def step(W, x, y):
        # canonical pattern: differentiate w.r.t. per-replica params so the
        # gradients come back unreduced, then DDP does the single allreduce
        g = jax.jit(jax.grad(loss))(ddp.replicate(W), x, y)
        return ddp.average_gradients(g)

    f = shard_map(
        step, mesh=mesh8,
        in_specs=(P(), P("dp", None), P("dp", None)),
        out_specs=P(),
    )
    got = f(W, X, Y)
    want = jax.grad(loss)(W, X, Y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_ddp_options(mesh8):
    grads = {"a": jnp.ones((4, 4)), "b": jnp.ones((3,), jnp.bfloat16)}
    for kwargs in (
        dict(),
        dict(allreduce_always_fp32=True),
        dict(gradient_predivide_factor=4.0),
        dict(gradient_average=False),
        dict(flat_buckets=False),
        dict(message_size=4),  # force multiple buckets
    ):
        ddp = DistributedDataParallel(**kwargs)
        f = shard_map(
            lambda g: ddp.average_gradients(g), mesh=mesh8, in_specs=P(), out_specs=P()
        )
        out = f(grads)
        expect = 1.0 if kwargs.get("gradient_average", True) else 8.0
        np.testing.assert_allclose(np.asarray(out["a"]), expect, atol=1e-6)
        assert out["b"].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out["b"], np.float32), expect, atol=1e-2
        )


def test_ddp_sync_disabled(mesh8):
    # enabled=False is the functional no_sync: grads pass through untouched,
    # and there is no stateful flag that jit could freeze at trace time
    ddp = DistributedDataParallel()
    g = {"w": jnp.ones((2,))}
    f = shard_map(lambda g: ddp.average_gradients(g, enabled=False),
                  mesh=mesh8, in_specs=P(), out_specs=P())
    out = f(g)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)  # untouched
    assert not hasattr(ddp, "no_sync")


def test_reducer_raw_sum(mesh8):
    r = Reducer()
    f = shard_map(lambda g: r.reduce(g), mesh=mesh8, in_specs=P(), out_specs=P())
    out = f({"w": jnp.ones((2,))})
    np.testing.assert_allclose(np.asarray(out["w"]), 8.0)


def test_broadcast_params_agree(mesh8):
    ddp = DistributedDataParallel()

    def body(x):
        # make per-rank divergent params, then broadcast rank 0's
        r = jax.lax.axis_index("dp").astype(jnp.float32)
        p = {"w": x + r}
        return ddp.broadcast_params(p)

    f = shard_map(body, mesh=mesh8, in_specs=P(), out_specs=P("dp"))
    out = f(jnp.zeros((1,)))
    np.testing.assert_allclose(np.asarray(out["w"]), np.zeros((8,)))  # all = rank0


# ---------------------------------------------------------------------------
# SyncBatchNorm — ref tests/distributed/synced_batchnorm: SyncBN over shards
# must equal plain BN over the full batch.


def _full_batch_bn(x, eps=1e-5):
    mean = x.mean(axis=(0, 1, 2))
    var = x.var(axis=(0, 1, 2))
    return (x - mean) / np.sqrt(var + eps)


def test_syncbn_matches_full_batch(mesh8):
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (16, 4, 4, 8)) * 3 + 2  # N H W C
    bn = SyncBatchNorm(features=8, axis_name="dp")
    params = bn.init(jax.random.PRNGKey(1), x[:2], use_running_average=False)

    def body(params, x):
        y, updates = bn.apply(
            params, x, use_running_average=False, mutable=["batch_stats"]
        )
        return y, updates["batch_stats"]

    f = shard_map(
        body, mesh=mesh8,
        in_specs=(P(), P("dp", None, None, None)),
        out_specs=(P("dp", None, None, None), P()),
    )
    y, stats = f(params, x)
    want = _full_batch_bn(np.asarray(x))
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4)
    # running stats updated with global batch stats
    np.testing.assert_allclose(
        np.asarray(stats["mean"]), 0.1 * np.asarray(x).mean((0, 1, 2)), atol=1e-4
    )


def test_syncbn_backward_matches_full_batch(mesh8):
    """The custom-backward parity check (ref two_gpu unit test): grad of a
    loss through SyncBN over shards == grad through full-batch BN."""
    k = jax.random.PRNGKey(2)
    x = jax.random.normal(k, (16, 2, 2, 4)) * 2
    bn = SyncBatchNorm(features=4, axis_name="dp", track_running_stats=False)
    params = bn.init(jax.random.PRNGKey(1), x[:2], use_running_average=False)

    def sharded_loss(params, x):
        def body(params, x):
            y = bn.apply(params, x, use_running_average=False)
            local = jnp.sum(jnp.sin(y))
            return jax.lax.psum(local, "dp")

        f = shard_map(
            body, mesh=mesh8,
            in_specs=(P(), P("dp", None, None, None)),
            out_specs=P(),
        )
        return f(params, x)

    def full_loss(params, x):
        bn1 = SyncBatchNorm(features=4, axis_name=None, track_running_stats=False)
        y = bn1.apply(params, x, use_running_average=False)
        return jnp.sum(jnp.sin(y))

    g1 = jax.jit(jax.grad(sharded_loss))(params, x)
    g2 = jax.jit(jax.grad(full_loss))(params, x)
    np.testing.assert_allclose(
        np.asarray(g1["params"]["scale"]), np.asarray(g2["params"]["scale"]), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(g1["params"]["bias"]), np.asarray(g2["params"]["bias"]), atol=1e-4
    )


def test_syncbn_eval_uses_running_stats():
    bn = SyncBatchNorm(features=4, axis_name=None)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    params = bn.init(jax.random.PRNGKey(1), x, use_running_average=False)
    y = bn.apply(params, x * 100, use_running_average=True)
    # running stats are fresh (mean 0, var 1): eval output == affine(x*100)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 100, atol=2e-3)


def test_syncbn_groups(mesh8):
    """Group BN (ref test_groups.py): stats shared only within each group."""
    groups = create_syncbn_process_group(4, 8)
    assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def body(x):
        mean, var, cnt = sync_batch_stats(
            x, (0,), "dp", axis_index_groups=groups
        )
        return mean[None, :]  # (1, C) so the dp axis can be stacked

    f = shard_map(body, mesh=mesh8, in_specs=P("dp", None), out_specs=P("dp", None))
    # ranks 0-3 see value 1, ranks 4-7 see value 5
    x = jnp.concatenate([jnp.ones((16, 3)), jnp.full((16, 3), 5.0)])
    out = np.asarray(f(x))
    np.testing.assert_allclose(out[:4], 1.0, atol=1e-6)
    np.testing.assert_allclose(out[4:], 5.0, atol=1e-6)

    with pytest.raises(ValueError):
        create_syncbn_process_group(3, 8)


def test_syncbn_fuse_relu():
    bn = SyncBatchNorm(features=4, axis_name=None, fuse_relu=True,
                       track_running_stats=False)
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
    params = bn.init(jax.random.PRNGKey(1), x, use_running_average=False)
    y = bn.apply(params, x, use_running_average=False)
    assert float(np.asarray(y).min()) >= 0.0

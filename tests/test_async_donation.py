"""Async-dispatch + buffer-donation stress tests.

Ref analogue: ``tests/distributed/DDP/ddp_race_condition_test.py:28-50``
backs the reference's overlap engine with a dedicated race test (mutate a
param mid-flight, assert the all-reduced grads still come out right). The
XLA design dissolves stream races, but this repo's own hazard class —
donated buffers reused across asynchronously-dispatched steps, host reads
interleaved with in-flight work, and the early-returning
``block_until_ready`` observed on the tunnel transport — had no dedicated
test until this one.

Strategy: run the donated flagship-style train step (the same
donate_argnums=(0,1) shape bench.py and the EP dryrun use) many steps with
host reads interleaved at different cadences; every cadence must produce
the bitwise-identical loss trajectory. If XLA ever handed a donated buffer
to a new step while a prior consumer was still in flight — or a host read
raced the write — the trajectories would diverge.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)

STEPS = 6


def _make_step(mesh, cfg, donate):
    specs = gpt_param_specs(cfg)
    opt = FusedAdam(lr=1e-2)

    def loss_fn(p, tok, tgt):
        def body(p, tok, tgt):
            from apex_tpu.transformer.pipeline_parallel.schedules.common import (
                replicate_loss,
            )

            return replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(body, mesh=mesh,
                             in_specs=(specs, P(), P()), out_specs=P())(
                                 p, tok, tgt)

    def train_step(params, opt_state, tok, tgt):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    step = (jax.jit(train_step, donate_argnums=(0, 1)) if donate
            else jax.jit(train_step))

    def init():
        p = init_gpt_params(jax.random.PRNGKey(0), cfg)
        s = opt.init(p)
        k = jax.random.PRNGKey(1)
        tok = jax.random.randint(k, (4, cfg.max_seq), 0, cfg.vocab_size)
        return p, s, tok, jnp.roll(tok, -1, axis=1)

    return step, init


@pytest.fixture(scope="module")
def small_cfg():
    return GPTConfig(vocab_size=64, max_seq=32, hidden=32, num_layers=2,
                     num_heads=4, dtype=jnp.float32, tie_embeddings=False)


def _run_trajectory(step, init, read_every):
    """Drive STEPS donated steps, host-reading the loss every
    ``read_every`` steps (1 = fence each step; STEPS = let the whole
    donated chain queue up async before the single final read)."""
    params, opt_state, tok, tgt = init()
    losses = []
    for i in range(STEPS):
        params, opt_state, loss = step(params, opt_state, tok, tgt)
        losses.append(loss)
        if (i + 1) % read_every == 0:
            losses[-1] = float(losses[-1])
    return [float(x) for x in losses]


def test_donated_chain_value_stability(small_cfg):
    """The same donated-step chain must be bitwise identical whether the
    host fences every step or lets the async queue run ahead."""
    mesh = parallel_state.initialize_model_parallel()  # dp=8 mesh
    step, init = _make_step(mesh, small_cfg, donate=True)
    fenced = _run_trajectory(step, init, read_every=1)
    queued = _run_trajectory(step, init, read_every=STEPS)
    assert fenced == queued, (fenced, queued)
    assert fenced[-1] < fenced[0]  # and it actually trains


def test_donation_matches_undonated(small_cfg):
    """Donation is an aliasing optimization — it must not change values
    vs the undonated step (the reference's race test asserts the overlap
    engine is value-neutral the same way)."""
    mesh = parallel_state.initialize_model_parallel()
    donated_step, init = _make_step(mesh, small_cfg, donate=True)
    plain_step, _ = _make_step(mesh, small_cfg, donate=False)
    donated = _run_trajectory(donated_step, init, read_every=2)
    plain = _run_trajectory(plain_step, init, read_every=1)
    assert donated == plain, (donated, plain)


def test_interleaved_param_reads_see_consistent_state(small_cfg):
    """Host-reading a param leaf between queued donated steps must see
    that step's committed value (never a torn/reused buffer): the read-back
    norms must match the fenced trajectory's norms exactly."""
    mesh = parallel_state.initialize_model_parallel()
    step, init = _make_step(mesh, small_cfg, donate=True)

    def norms(read_back):
        params, opt_state, tok, tgt = init()
        out = []
        for i in range(STEPS):
            params, opt_state, loss = step(params, opt_state, tok, tgt)
            if read_back:
                # immediate host read of a mid-pytree leaf, racing the
                # async dispatch of the NEXT iteration's donation
                leaf = jax.tree.leaves(params)[3]
                out.append(float(jnp.vdot(leaf, leaf)))
        if not read_back:
            leaf = jax.tree.leaves(params)[3]
            out.append(float(jnp.vdot(leaf, leaf)))
        return out

    interleaved = norms(read_back=True)
    final_only = norms(read_back=False)
    np.testing.assert_array_equal(interleaved[-1], final_only[-1])


def test_donated_input_is_consumed(small_cfg):
    """Reading a donated argument AFTER the step must raise — the buffer
    belongs to the new state. Pins the deletion semantics the donated
    entry points (bench.py, the EP dryrun) rely on."""
    mesh = parallel_state.initialize_model_parallel()
    step, init = _make_step(mesh, small_cfg, donate=True)
    params, opt_state, tok, tgt = init()
    new_params, new_opt_state, loss = step(params, opt_state, tok, tgt)
    float(loss)
    with pytest.raises(RuntimeError):
        np.asarray(jax.tree.leaves(params)[0])
    # the NEW state is alive and readable
    assert np.isfinite(np.asarray(jax.tree.leaves(new_params)[0])).all()

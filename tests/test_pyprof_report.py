"""Per-op attribution report (ref apex/pyprof/parse + prof: kernels mapped to
layers with FLOP/byte estimates, rendered as a table)."""

import jax
import jax.numpy as jnp

from apex_tpu.pyprof import annotate, format_table, op_table


def _f(x, w1, w2):
    with annotate("layer1"):
        h = jnp.tanh(x @ w1)
    with annotate("layer2"):
        return jnp.sum(h @ w2)


def test_op_table_attributes_dots_to_scopes_with_exact_flops():
    x = jnp.ones((256, 512), jnp.bfloat16)
    w1 = jnp.ones((512, 512), jnp.bfloat16)
    w2 = jnp.ones((512, 128), jnp.bfloat16)
    rows = op_table(_f, x, w1, w2)
    scopes = {r["scope"] for r in rows}
    assert any(s.startswith("layer1") for s in scopes)
    assert any(s.startswith("layer2") for s in scopes)
    total_flops = sum(r["flops"] for r in rows)
    expected = 2 * 256 * 512 * 512 + 2 * 256 * 512 * 128
    assert abs(total_flops - expected) / expected < 0.05
    assert all(r["bytes"] > 0 for r in rows if r["op"] != "custom-call")
    # sorted by estimated time, roofline fields present
    times = [r["est_time_s"] for r in rows]
    assert times == sorted(times, reverse=True)
    assert all(r["bound"] in ("compute", "memory") for r in rows)


def test_format_table_renders():
    x = jnp.ones((64, 128), jnp.float32)
    w = jnp.ones((128, 128), jnp.float32)
    rows = op_table(lambda x, w: jnp.sum(x @ w), x, w)
    text = format_table(rows, top=5)
    assert "GFLOP" in text and "TOTAL est" in text


def test_op_table_on_train_step_with_grad():
    # fwd+bwd+sgd: the report must handle fusions, transposes, reductions
    def loss(w, x):
        with annotate("mlp"):
            return jnp.mean((jnp.tanh(x @ w["a"]) @ w["b"]) ** 2)

    def step(w, x):
        g = jax.grad(loss)(w, x)
        return jax.tree.map(lambda p, gg: p - 0.1 * gg, w, g)

    w = {"a": jnp.ones((128, 256), jnp.float32),
         "b": jnp.ones((256, 64), jnp.float32)}
    x = jnp.ones((32, 128), jnp.float32)
    rows = op_table(step, w, x)
    assert sum(r["flops"] for r in rows) > 0
    # backward dots exist: total flops ~3x forward dot flops
    fwd = 2 * 32 * 128 * 256 + 2 * 32 * 256 * 64
    assert sum(r["flops"] for r in rows) > 2.0 * fwd


def test_measured_op_table_joins_trace_and_hlo():
    """Ref parse/kernel.py + prof/output.py: MEASURED kernel time joined
    with per-op flops/bytes. On the CPU backend the thunk spans carry the
    HLO instruction names, same as TPU device rows."""
    from apex_tpu.pyprof import format_measured_table, measured_op_table

    def step(x, w1, w2):
        with annotate("mlp"):
            return (jnp.tanh(x @ w1) @ w2).sum()

    x = jnp.ones((256, 256), jnp.float32)
    w1 = jnp.ones((256, 512), jnp.float32)
    w2 = jnp.ones((512, 256), jnp.float32)
    res = measured_op_table(step, x, w1, w2, steps=3)
    rows = res["rows"]
    assert rows, "no measured rows joined"
    dot = [r for r in rows if r["op"] == "dot"]
    assert dot and all(r["time_ms"] > 0 and r["flops"] > 0 for r in dot)
    # measured time yields a finite achieved-MFU and bandwidth per op
    assert all(r["mfu_pct"] >= 0 and r["gbps"] >= 0 for r in rows)
    assert 0 < res["coverage_pct"] <= 100.0
    # rows sorted by measured time, percentages sum to ~100
    times = [r["time_ms"] for r in rows]
    assert times == sorted(times, reverse=True)
    assert abs(sum(r["pct"] for r in rows) - 100.0) < 1e-6
    text = format_measured_table(res, top=5)
    assert "ms/step" in text and "coverage" in text

"""pyprof / RNN / weight-norm / multiproc tests — ref tests/L0/run_pyprof_*,
apex/RNN usage, reparameterization tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.pyprof import annotate, annotate_function, cost_analysis, summary
from apex_tpu.reparameterization import apply_weight_norm, remove_weight_norm
from apex_tpu.RNN import GRU, LSTM, RNNReLU, RNNTanh, mLSTM


# ---------------------------------------------------------------------------
# pyprof analogue


def test_cost_analysis_reports_matmul_flops():
    a = jnp.ones((128, 128))
    ca = cost_analysis(lambda a: a @ a, a)
    # 2*n^3 = 4.19e6 MACs; XLA reports >= the matmul flops
    assert ca.get("flops", 0) >= 2 * 128 ** 3 * 0.9


def test_summary_and_annotations():
    a = jnp.ones((64, 64))

    @annotate_function(name="my_matmul")
    def f(a):
        with annotate("inner"):
            return a @ a

    s = summary(f, a, peak_flops=1e12)
    assert s["flops"] > 0 and s["min_time_s_compute_bound"] > 0
    np.testing.assert_allclose(np.asarray(f(a)), np.asarray(a @ a))


# ---------------------------------------------------------------------------
# RNN (ref apex/RNN/models.py surface)


@pytest.mark.parametrize("factory,carry", [(LSTM, 2), (GRU, 1),
                                           (RNNTanh, 1), (RNNReLU, 1)])
def test_rnn_shapes_and_grads(factory, carry):
    m = factory(input_size=8, hidden_size=16, num_layers=2)
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 8))
    params = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(params, x)
    assert y.shape == (3, 5, 16)
    g = jax.grad(lambda p: jnp.sum(m.apply(p, x) ** 2))(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


def test_rnn_bidirectional_doubles_features():
    m = LSTM(input_size=8, hidden_size=16, num_layers=1, bidirectional=True)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 7, 8))
    params = m.init(jax.random.PRNGKey(3), x)
    assert m.apply(params, x).shape == (2, 7, 32)


def test_mlstm_runs():
    m = mLSTM(input_size=8, hidden_size=16)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 5, 8))
    params = m.init(jax.random.PRNGKey(5), x)
    y, (h, c) = m.apply(params, x)
    assert y.shape == (2, 5, 16) and h.shape == (2, 16)


def test_lstm_state_is_causal():
    """Output at time t must not depend on inputs after t."""
    m = LSTM(input_size=4, hidden_size=8)
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 6, 4))
    params = m.init(jax.random.PRNGKey(7), x)
    y1 = m.apply(params, x)
    x2 = x.at[:, 4:].set(0.0)
    y2 = m.apply(params, x2)
    np.testing.assert_allclose(np.asarray(y1[:, :4]), np.asarray(y2[:, :4]),
                               atol=1e-6)
    assert not np.allclose(np.asarray(y1[:, 5]), np.asarray(y2[:, 5]))


# ---------------------------------------------------------------------------
# weight norm (ref apex/reparameterization)


def test_weight_norm_round_trip_and_direction():
    params = {"dense": {"kernel": jax.random.normal(jax.random.PRNGKey(8),
                                                    (6, 4)),
                        "bias": jnp.zeros((4,))}}
    wn = apply_weight_norm(params, dim=0)
    assert set(wn["dense"]["kernel"].keys()) == {"wn_g", "wn_v"}
    back = remove_weight_norm(wn, dim=0)
    np.testing.assert_allclose(np.asarray(back["dense"]["kernel"]),
                               np.asarray(params["dense"]["kernel"]),
                               rtol=1e-5)
    # scaling v must not change the recomposed weight (direction-only)
    wn2 = jax.tree_util.tree_map(lambda x: x, wn)
    wn2["dense"]["kernel"] = {"wn_g": wn["dense"]["kernel"]["wn_g"],
                              "wn_v": wn["dense"]["kernel"]["wn_v"] * 3.0}
    back2 = remove_weight_norm(wn2, dim=0)
    np.testing.assert_allclose(np.asarray(back2["dense"]["kernel"]),
                               np.asarray(params["dense"]["kernel"]),
                               rtol=1e-5)


def test_multiproc_initialize_noop_single_process(monkeypatch):
    from apex_tpu.parallel import multiproc

    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("WORLD_SIZE", raising=False)
    multiproc.initialize_distributed()  # must not raise or call jax.distributed


def test_multi_tensor_applier_shim():
    from apex_tpu.multi_tensor_apply import multi_tensor_applier

    a = [jnp.ones((4,)), jnp.full((2, 2), 2.0)]
    b = [jnp.full((4,), 3.0), jnp.ones((2, 2))]
    (out, found) = multi_tensor_applier(lambda x, y, s: x * y * s, None,
                                        (a, b), 2.0)
    np.testing.assert_allclose(np.asarray(out[0]), 6.0)
    np.testing.assert_allclose(np.asarray(out[1]), 4.0)
    assert float(found) == 0.0
    bad = [jnp.asarray([jnp.inf, 1.0, 1.0, 1.0]), b[1]]
    _, found2 = multi_tensor_applier(lambda x, y: x + y, None, (bad, a))
    assert float(found2) == 1.0


def test_checkpoint_round_trip(tmp_path):
    from apex_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    state = {"w": jnp.arange(6.0).reshape(2, 3),
             "opt": {"count": jnp.asarray(3)}}
    p = save_checkpoint(str(tmp_path / "ckpt"), state, step=7)
    restored = load_checkpoint(p)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(state["w"]))
    assert int(np.asarray(restored["opt"]["count"])) == 3


def test_checkpointer_prefers_orbax_dir_over_pickle(tmp_path):
    """When both an orbax dir step_N and a pickle step_N.npz.pkl exist for
    one step, the restore must deterministically pick the orbax dir
    regardless of listdir order (round-2 advisor finding)."""
    from apex_tpu.transformer.testing.arguments import Checkpointer
    from apex_tpu.utils.checkpoint import save_checkpoint

    state = {"w": jnp.arange(4.0)}
    # orbax save produces the step_3 dir (or .npz.pkl fallback if orbax is
    # absent — then this test degenerates to single-format and still holds)
    p = save_checkpoint(str(tmp_path / "step"), state, step=3)
    if p.endswith(".npz.pkl"):
        pytest.skip("orbax unavailable; only one format exists")
    # plant a DIFFERENT pickle for the same step
    import pickle

    with open(tmp_path / "step_3.npz.pkl", "wb") as f:
        pickle.dump({"w": np.zeros(4)}, f)
    ck = Checkpointer(None, str(tmp_path), None)
    restored = ck.load()
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(4.0))


def test_arguments_to_config():
    from apex_tpu.transformer.testing.arguments import (
        args_to_config, parallel_sizes, parse_args)

    ns = parse_args(["--num-layers", "4", "--hidden-size", "64",
                     "--num-attention-heads", "4", "--seq-length", "32",
                     "--vocab-size", "128", "--bf16",
                     "--tensor-model-parallel-size", "2",
                     "--pipeline-model-parallel-size", "2"])
    cfg = args_to_config(ns)
    assert cfg.num_layers == 4 and cfg.hidden == 64
    assert cfg.dtype == jnp.bfloat16
    assert parallel_sizes(ns) == (2, 2, 1)


def test_global_vars_registry():
    from apex_tpu.transformer.testing import global_vars as gv

    gv.destroy_global_vars()
    with pytest.raises(RuntimeError):
        gv.get_args()
    gv.set_args({"x": 1})
    assert gv.get_args() == {"x": 1}
    gv.destroy_global_vars()


def test_autocast_utils():
    from apex_tpu._autocast_utils import (
        _cast_if_autocast_enabled, _get_autocast_dtypes)

    assert _get_autocast_dtypes()[0] == jnp.bfloat16
    out = _cast_if_autocast_enabled(jnp.ones((2,), jnp.float32),
                                    jnp.asarray([1], jnp.int32))
    assert out[0].dtype == jnp.bfloat16 and out[1].dtype == jnp.int32


def test_arguments_reference_shaped_invocation():
    """A realistic Megatron-style command line (ref arguments.py surface):
    mapped flags are used, inert flags warn but parse, unknown flags warn
    but do not abort."""
    import warnings as _w

    from apex_tpu.transformer.testing.arguments import (
        args_to_config, make_optimizer, parse_args)

    argv = [
        "--num-layers", "24", "--hidden-size", "1024",
        "--num-attention-heads", "16", "--seq-length", "512",
        "--max-position-embeddings", "512", "--vocab-size", "32000",
        "--attention-dropout", "0.1", "--hidden-dropout", "0.1",
        "--weight-decay", "0.01", "--adam-beta2", "0.95",
        "--micro-batch-size", "4", "--global-batch-size", "256",
        "--rampup-batch-size", "32", "32", "1000",
        "--train-iters", "1000", "--lr", "3e-4", "--min-lr", "3e-5",
        "--lr-decay-style", "cosine", "--lr-warmup-fraction", "0.01",
        "--bf16", "--loss-scale", "4096",
        "--recompute-granularity", "selective",
        "--untie-embeddings-and-output-weights",
        "--tensor-model-parallel-size", "2",
        "--distributed-backend", "nccl",          # inert on TPU
        "--some-flag-we-never-heard-of", "7",     # unknown
    ]
    with _w.catch_warnings(record=True) as caught:
        _w.simplefilter("always")
        ns = parse_args(argv)
    msgs = "".join(str(c.message) for c in caught)
    assert "unknown" in msgs and "inert" in msgs
    assert ns.unknown_flags == ["--some-flag-we-never-heard-of", "7"]
    assert "--distributed-backend" in ns.inert_flags

    cfg = args_to_config(ns)
    assert cfg.hidden == 1024 and cfg.num_layers == 24
    assert cfg.attention_dropout == 0.1 and cfg.hidden_dropout == 0.1
    assert cfg.remat_policy == "dots"
    assert not cfg.tie_embeddings

    opt, schedule = make_optimizer(ns)
    # warmup then cosine decay toward min-lr
    assert float(schedule(0)) < 1e-6
    assert abs(float(schedule(10)) - 3e-4) < 1e-5  # end of 10-iter warmup
    assert float(schedule(1000)) < 3.2e-5 + 1e-6
    state = opt.init({"w": jnp.ones((4, 4))})
    u, _ = opt.update({"w": jnp.ones((4, 4))}, state, {"w": jnp.ones((4, 4))})
    assert jnp.all(jnp.isfinite(u["w"]))


def test_arguments_flag_wiring(tmp_path):
    """The first-tier flags the docstring claims are *used* must actually
    construct the subsystem they name: loss scaler, microbatch ramp-up,
    DDP fp32 comm, checkpointer (previously parsed-but-unconsumed)."""
    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.transformer.pipeline_parallel.microbatches import (
        RampupBatchsizeNumMicroBatches,
    )
    from apex_tpu.transformer.testing.arguments import (
        ddp_options,
        make_checkpointer,
        make_loss_scaler,
        make_microbatch_calculator,
        make_optimizer,
        parse_args,
    )

    ns = parse_args([
        "--num-layers", "2", "--hidden-size", "64",
        "--num-attention-heads", "4", "--seq-length", "32",
        "--vocab-size", "1024", "--fp16",
        "--initial-loss-scale", "1024", "--loss-scale-window", "500",
        "--hysteresis", "2", "--min-loss-scale", "2",
        "--rampup-batch-size", "16", "16", "640",
        "--global-batch-size", "64", "--micro-batch-size", "4",
        "--train-samples", "128000",
        "--accumulate-allreduce-grads-in-fp32",
        "--save", str(tmp_path / "ckpt"), "--save-interval", "2",
    ])

    scaler = make_loss_scaler(ns)
    assert isinstance(scaler, LossScaler) and scaler.dynamic
    assert scaler.hysteresis == 2 and scaler.scale_window == 500
    assert float(scaler.init_state().loss_scale) == 1024.0

    # static scale takes precedence; bf16/fp32 needs none
    ns_static = parse_args(["--loss-scale", "128"])
    assert make_loss_scaler(ns_static).dynamic is False
    assert make_loss_scaler(parse_args(["--bf16"])) is None

    calc = make_microbatch_calculator(ns, data_parallel_size=2)
    assert isinstance(calc, RampupBatchsizeNumMicroBatches)
    calc.update(0, consistency_check=False)
    assert calc.get_current_global_batch_size() == 16

    assert ddp_options(ns) == {"allreduce_always_fp32": True}

    # --train-samples drives the schedule length, walking the batch ramp
    # (ramp iterations consume fewer samples each, so total > samples/global)
    from apex_tpu.transformer.testing.arguments import _iters_from_samples

    total = _iters_from_samples(ns)
    assert total > 128000 // 64
    _, schedule = make_optimizer(ns)
    assert abs(float(schedule(total)) - ns.min_lr) < 1e-7
    assert float(schedule(total // 2)) > ns.min_lr + 1e-6

    ck = make_checkpointer(ns)
    state = {"w": jnp.arange(4.0), "step": jnp.asarray(3)}
    assert ck.maybe_save(state, 2) and not ck.maybe_save(state, 3)
    restored = ck.load(target=state)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.asarray(state["w"]))


def test_loss_scaler_hysteresis():
    """Megatron-LM DynamicGradScaler hysteresis semantics: with hysteresis=2
    the first overflow only spends a credit (scale unchanged, step still
    skipped); the second overflow — consecutive or not — backs off; credits
    refill only when the scale grows after scale_window clean steps."""
    from apex_tpu.amp.scaler import LossScaler

    sc = LossScaler("dynamic", init_scale=1024.0, hysteresis=2,
                    scale_window=2)
    s = sc.init_state()

    s, skip = sc.update_scale(s, jnp.asarray(1.0))
    assert bool(skip) and float(s.loss_scale) == 1024.0  # credit spent
    s, skip = sc.update_scale(s, jnp.asarray(1.0))
    assert bool(skip) and float(s.loss_scale) == 512.0  # backoff

    # one clean step does NOT refill: the next overflow backs off again
    s, skip = sc.update_scale(s, jnp.asarray(0.0))
    assert not bool(skip)
    s, _ = sc.update_scale(s, jnp.asarray(1.0))
    assert float(s.loss_scale) == 256.0

    # scale_window clean steps -> growth AND credit refill; the following
    # overflow is tolerated again
    for _ in range(2):
        s, skip = sc.update_scale(s, jnp.asarray(0.0))
        assert not bool(skip)
    assert float(s.loss_scale) == 512.0  # grew
    s, skip = sc.update_scale(s, jnp.asarray(1.0))
    assert bool(skip) and float(s.loss_scale) == 512.0  # tolerated

    # state_dict round-trip carries the credits; old dicts default to full
    d = sc.state_dict(s)
    assert d["hysteresis_left"] == 1
    assert int(sc.load_state_dict(d).hysteresis_left) == 1
    del d["hysteresis_left"]
    assert int(sc.load_state_dict(d).hysteresis_left) == 2


@pytest.mark.slow
def test_imagenet_trainer_exact_resume(tmp_path):
    """The reference's --resume contract on the flagship example trainer:
    2 iters + checkpoint, then resume to 4, must reproduce the
    uninterrupted 4-iter run EXACTLY (deterministic synthetic data is
    keyed by absolute iteration, state round-trips through orbax)."""
    from tests.gen_l1_baselines import load_trainer

    m = load_trainer()
    # the L1 fast tier's exact config (resnet18_O2_False_128.0 at BASE
    # shapes): when that test ran first in this process, the jitted step
    # is already cached and this test costs only the 8 tiny iterations
    base = ["--arch", "resnet18", "--opt-level", "O2", "--loss-scale",
            "128.0", "--iters", "4", "--batch-size", "32", "--image-size",
            "32", "--num-classes", "10", "--deterministic", "--lr",
            "0.0001", "--print-freq", "100"]
    full = m.train(m.parse_args(base))

    ck = str(tmp_path / "ck")
    half = [("2" if a == "4" else a) for a in base]
    first = m.train(m.parse_args(half + ["--checkpoint-dir", ck]))
    import glob as _glob

    ckpt = sorted(_glob.glob(ck + "/ckpt_*"))[-1]
    rest = m.train(m.parse_args(base + ["--resume", ckpt]))
    assert first + rest == full, (first, rest, full)


# ---------------------------------------------------------------------------
# no-pipelining schedule arity guard (stock-jax-safe home for it: the
# pipeline test files need a mesh toolchain to even collect)


def test_no_pipelining_arity_guard_catches_wrapped_step_func():
    """ADVICE round-5: the inspect guard binds (*args, **kwargs) wrappers
    fine, so a wrapped 2-arg step func used to die with the opaque in-scan
    TypeError — the trace-time catch must re-raise the same hint."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_no_pipelining,
    )

    def two_arg(params, mb):
        return jnp.sum(params * mb["x"])

    def wrapper(*args, **kwargs):  # defeats the signature.bind check
        return two_arg(*args, **kwargs)

    batch = {"x": jnp.ones((4, 2))}
    # plain 2-arg works (no dropout key)
    loss, grads = forward_backward_no_pipelining(
        wrapper, batch, jnp.ones((2,)), num_microbatches=2)
    assert np.isfinite(float(loss))
    for fn in (two_arg, wrapper):
        with pytest.raises(ValueError,
                           match="third per-microbatch key"):
            forward_backward_no_pipelining(
                fn, batch, jnp.ones((2,)), num_microbatches=2,
                dropout_key=jax.random.PRNGKey(0))
    # a TypeError raised by the step computation itself (not arity) must
    # propagate untranslated — with AND without a key: a correct 3-arg
    # step func whose body raises TypeError must not be misdiagnosed as
    # a signature problem
    def broken(params, mb):
        raise TypeError("not an arity problem")

    def broken3(params, mb, key):
        raise TypeError("not an arity problem")

    with pytest.raises(TypeError, match="not an arity problem"):
        forward_backward_no_pipelining(
            broken, batch, jnp.ones((2,)), num_microbatches=2)
    with pytest.raises(TypeError, match="not an arity problem"):
        forward_backward_no_pipelining(
            broken3, batch, jnp.ones((2,)), num_microbatches=2,
            dropout_key=jax.random.PRNGKey(0))

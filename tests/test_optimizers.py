"""Fused optimizer golden tests — ref tests/L0/run_optimizers/test_fused_optimizer.py
pattern: same init, same grads, compare params within max_abs_diff against a
reference implementation (torch.optim where one exists, hand-computed math
otherwise)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torch

from apex_tpu import optimizers as opt
from apex_tpu.optimizers import apply_updates


def _rand_tree(seed=0, shapes=((7, 3), (11,), (2, 5, 3))):
    rng = np.random.RandomState(seed)
    params = {f"p{i}": rng.randn(*s).astype(np.float32) for i, s in enumerate(shapes)}
    grads = {f"p{i}": rng.randn(*s).astype(np.float32) for i, s in enumerate(shapes)}
    return params, grads


def _run_jax(tx, params_np, grads_seq):
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    state = tx.init(params)

    @jax.jit
    def step(params, state, grads):
        updates, state = tx.update(grads, state, params)
        return apply_updates(params, updates), state

    for g in grads_seq:
        params, state = step(params, state, jax.tree_util.tree_map(jnp.asarray, g))
    return jax.tree_util.tree_map(np.asarray, params)


def _run_torch(opt_ctor, params_np, grads_seq):
    tparams = {k: torch.nn.Parameter(torch.tensor(v)) for k, v in params_np.items()}
    optimizer = opt_ctor(list(tparams.values()))
    for g in grads_seq:
        for k, p in tparams.items():
            p.grad = torch.tensor(g[k])
        optimizer.step()
    return {k: p.detach().numpy() for k, p in tparams.items()}


def _grad_seq(n=5, seed=1):
    rng = np.random.RandomState(seed)
    params, _ = _rand_tree()
    return [
        {k: rng.randn(*v.shape).astype(np.float32) for k, v in params.items()}
        for _ in range(n)
    ]


@pytest.mark.parametrize("adam_w,wd", [(True, 0.0), (True, 0.1), (False, 0.0), (False, 0.1)])
def test_fused_adam_matches_torch(adam_w, wd):
    params, _ = _rand_tree()
    grads_seq = _grad_seq()
    got = _run_jax(
        opt.FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=adam_w), params, grads_seq
    )
    ctor = (
        (lambda ps: torch.optim.AdamW(ps, lr=1e-2, weight_decay=wd))
        if adam_w
        else (lambda ps: torch.optim.Adam(ps, lr=1e-2, weight_decay=wd))
    )
    want = _run_torch(ctor, params, grads_seq)
    for k in params:
        np.testing.assert_allclose(got[k], want[k], atol=2e-5, err_msg=k)


@pytest.mark.parametrize(
    "momentum,nesterov,wd", [(0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 0.05)]
)
def test_fused_sgd_matches_torch(momentum, nesterov, wd):
    params, _ = _rand_tree()
    grads_seq = _grad_seq()
    got = _run_jax(
        opt.FusedSGD(lr=1e-2, momentum=momentum, nesterov=nesterov, weight_decay=wd),
        params,
        grads_seq,
    )
    want = _run_torch(
        lambda ps: torch.optim.SGD(
            ps, lr=1e-2, momentum=momentum, nesterov=nesterov, weight_decay=wd
        ),
        params,
        grads_seq,
    )
    for k in params:
        np.testing.assert_allclose(got[k], want[k], atol=2e-5, err_msg=k)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_fused_adagrad_matches_torch(wd):
    params, _ = _rand_tree()
    grads_seq = _grad_seq()
    got = _run_jax(opt.FusedAdagrad(lr=1e-2, weight_decay=wd), params, grads_seq)
    want = _run_torch(
        lambda ps: torch.optim.Adagrad(ps, lr=1e-2, weight_decay=wd, eps=1e-10),
        params,
        grads_seq,
    )
    for k in params:
        np.testing.assert_allclose(got[k], want[k], atol=2e-5, err_msg=k)


def _lamb_reference(params, grads_seq, lr, b1, b2, eps, wd, max_grad_norm):
    """Hand implementation of the reference two-stage LAMB
    (csrc/multi_tensor_lamb.cu:41 semantics)."""
    m = {k: np.zeros_like(v) for k, v in params.items()}
    v = {k: np.zeros_like(vv) for k, vv in params.items()}
    p = {k: vv.copy() for k, vv in params.items()}
    t = 0
    for grads in grads_seq:
        t += 1
        gnorm = np.sqrt(sum(np.sum(g ** 2) for g in grads.values()))
        clip = gnorm / max_grad_norm if (max_grad_norm > 0 and gnorm > max_grad_norm) else 1.0
        c1 = 1 - b1 ** t
        c2 = 1 - b2 ** t
        for k in p:
            g = grads[k] / clip
            m[k] = b1 * m[k] + (1 - b1) * g
            v[k] = b2 * v[k] + (1 - b2) * g * g
            upd = (m[k] / c1) / (np.sqrt(v[k] / c2) + eps) + wd * p[k]
            w_norm = np.sqrt(np.sum(p[k] ** 2))
            u_norm = np.sqrt(np.sum(upd ** 2))
            ratio = w_norm / u_norm if (w_norm > 0 and u_norm > 0) else 1.0
            if wd == 0.0:
                ratio = 1.0
            p[k] = p[k] - lr * ratio * upd
    return p


@pytest.mark.parametrize("wd,mgn", [(0.01, 1.0), (0.0, 1.0), (0.1, 0.0)])
def test_fused_lamb_matches_reference_math(wd, mgn):
    params, _ = _rand_tree()
    grads_seq = _grad_seq()
    got = _run_jax(
        opt.FusedLAMB(lr=1e-2, weight_decay=wd, max_grad_norm=mgn, eps=1e-6),
        params,
        grads_seq,
    )
    want = _lamb_reference(params, grads_seq, 1e-2, 0.9, 0.999, 1e-6, wd, mgn)
    for k in params:
        np.testing.assert_allclose(got[k], want[k], atol=3e-5, err_msg=k)


def _novograd_reference(params, grads_seq, lr, b1, b2, eps, wd, grad_averaging):
    m = {k: np.zeros_like(v) for k, v in params.items()}
    v = {k: 0.0 for k in params}
    p = {k: vv.copy() for k, vv in params.items()}
    beta3 = (1 - b1) if grad_averaging else 1.0
    first = True
    for grads in grads_seq:
        for k in p:
            g = grads[k]
            norm = np.sum(g * g)
            v[k] = norm if first else b2 * v[k] + (1 - b2) * norm
            d = g / (np.sqrt(v[k]) + eps)
            m[k] = b1 * m[k] + beta3 * d
            step = m[k] + wd * p[k]
            p[k] = p[k] - lr * step
        first = False
    return p


@pytest.mark.parametrize("wd", [0.0, 0.01])
def test_fused_novograd_matches_reference_math(wd):
    params, _ = _rand_tree()
    grads_seq = _grad_seq()
    got = _run_jax(
        opt.FusedNovoGrad(lr=1e-2, betas=(0.95, 0.98), weight_decay=wd), params, grads_seq
    )
    want = _novograd_reference(params, grads_seq, 1e-2, 0.95, 0.98, 1e-8, wd, True)
    for k in params:
        np.testing.assert_allclose(got[k], want[k], atol=3e-5, err_msg=k)


def test_larc_rescales_gradients():
    # ref apex/parallel/LARC.py:78-107 semantics
    params = {"w": np.full((4,), 2.0, np.float32)}   # |p| = 4
    grads = {"w": np.full((4,), 0.001, np.float32)}  # tiny grads -> adaptive lr big -> clipped to 1
    tx = opt.LARC(opt.FusedSGD(lr=0.1), trust_coefficient=0.02, clip=True, lr=0.1)
    got = _run_jax(tx, params, [grads])
    # clipped: min(0.02*|p|/(|g|)/lr, 1) = min(0.02*4/0.002/0.1, 1) = 1 -> plain SGD
    np.testing.assert_allclose(got["w"], 2.0 - 0.1 * 0.001, rtol=1e-6)

    # huge grads -> adaptive < 1 -> grad scaled down
    big = {"w": np.full((4,), 100.0, np.float32)}  # |g| = 200
    got2 = _run_jax(tx, params, [big])
    adaptive = 0.02 * 4.0 / 200.0 / 0.1  # = 0.004
    np.testing.assert_allclose(got2["w"], 2.0 - 0.1 * 100.0 * adaptive, rtol=1e-5)


def test_zero_norm_params_passthrough_larc():
    params = {"w": np.zeros((4,), np.float32)}
    grads = {"w": np.ones((4,), np.float32)}
    tx = opt.LARC(opt.FusedSGD(lr=0.1), clip=True, lr=0.1)
    got = _run_jax(tx, params, [grads])
    np.testing.assert_allclose(got["w"], -0.1, rtol=1e-6)  # adaptive forced to 1


def test_bf16_params_fp32_state():
    # mixed-precision capability: bf16 params, fp32 moments (ref
    # fused_adam dtype grouping + FusedMixedPrecisionLamb fp32 state)
    params = {"w": jnp.ones((8,), jnp.bfloat16)}
    tx = opt.FusedAdam(lr=1e-2)
    state = tx.init(params)
    assert state.mu["w"].dtype == jnp.float32
    updates, state = tx.update({"w": jnp.ones((8,), jnp.bfloat16)}, state, params)
    assert updates["w"].dtype == jnp.bfloat16
    new = apply_updates(params, updates)
    assert new["w"].dtype == jnp.bfloat16


def test_global_norm():
    tree = {"a": jnp.ones((3,)), "b": jnp.full((4,), 2.0)}
    np.testing.assert_allclose(float(opt.global_norm(tree)), np.sqrt(3 + 16), rtol=1e-6)

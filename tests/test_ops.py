"""Kernel-layer numerics tests — ref tests/L0/run_fused_layer_norm, run_mlp,
run_transformer/test_fused_softmax.py, contrib xentropy tests: compare each
fused op (fwd + bwd) against a pure reference at fp32 and bf16."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import ops
from apex_tpu.ops.layer_norm import layer_norm_reference, rms_norm_reference


# ---------------------------------------------------------------------------
# LayerNorm / RMSNorm (Pallas interpret mode on CPU)


@pytest.mark.parametrize("rows,hidden", [(32, 128), (64, 256), (8, 384)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_layer_norm_forward_matches_reference(rows, hidden, dtype):
    k = jax.random.PRNGKey(0)
    x = (jax.random.normal(k, (rows, hidden)) * 3 + 1).astype(dtype)
    w = jax.random.normal(jax.random.fold_in(k, 1), (hidden,)) * 0.5 + 1
    b = jax.random.normal(jax.random.fold_in(k, 2), (hidden,)) * 0.1
    got = ops.layer_norm(x, w, b, use_pallas=True)
    want = layer_norm_reference(x, w, b)
    atol = 1e-5 if dtype == jnp.float32 else 3e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=atol
    )


def test_layer_norm_backward_matches_reference():
    k = jax.random.PRNGKey(3)
    x = jax.random.normal(k, (32, 128)) * 2
    w = jax.random.normal(jax.random.fold_in(k, 1), (128,)) + 1
    b = jnp.zeros((128,))

    def loss_pallas(x, w, b):
        return jnp.sum(jnp.sin(ops.layer_norm(x, w, b, use_pallas=True)))

    def loss_ref(x, w, b):
        return jnp.sum(jnp.sin(layer_norm_reference(x, w, b)))

    g1 = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, e, name in zip(g1, g2, "xwb"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), atol=2e-4, err_msg=name
        )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rms_norm_fwd_bwd(dtype):
    k = jax.random.PRNGKey(5)
    x = (jax.random.normal(k, (16, 256)) * 2).astype(dtype)
    w = jax.random.normal(jax.random.fold_in(k, 1), (256,)) + 1

    got = ops.rms_norm(x, w, use_pallas=True)
    want = rms_norm_reference(x, w)
    atol = 1e-5 if dtype == jnp.float32 else 3e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=atol
    )
    if dtype == jnp.float32:
        g1 = jax.grad(lambda x, w: ops.rms_norm(x, w, use_pallas=True).sum(), (0, 1))(x, w)
        g2 = jax.grad(lambda x, w: rms_norm_reference(x, w).sum(), (0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(g1[0]), np.asarray(g2[0]), atol=2e-4)
        np.testing.assert_allclose(np.asarray(g1[1]), np.asarray(g2[1]), atol=2e-4)


def test_layer_norm_unaligned_falls_back():
    # hidden not %128: XLA path, still correct
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 100))
    w = jnp.ones((100,)); b = jnp.zeros((100,))
    got = ops.layer_norm(x, w, b)
    want = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    with pytest.raises(ValueError):
        ops.layer_norm(x, w, b, use_pallas=True)


def test_layer_norm_no_affine():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 128))
    got = ops.layer_norm(x)  # non-affine variant
    assert abs(float(got.mean())) < 1e-5
    np.testing.assert_allclose(float(got.std()), 1.0, atol=1e-3)


def test_fused_layer_norm_module():
    from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 128), jnp.bfloat16)
    ln = FusedLayerNorm(normalized_shape=128)
    params = ln.init(jax.random.PRNGKey(1), x)
    y = ln.apply(params, x)
    assert y.shape == x.shape and y.dtype == jnp.bfloat16
    assert params["params"]["scale"].dtype == jnp.float32

    rn = FusedRMSNorm(normalized_shape=128, elementwise_affine=False)
    y2 = rn.apply(rn.init(jax.random.PRNGKey(2), x), x)
    assert y2.shape == x.shape


# ---------------------------------------------------------------------------
# Fused softmax — ref test_fused_softmax.py (kernel vs unfused reference)


def test_scaled_masked_softmax_matches_unfused():
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (2, 4, 8, 16), jnp.bfloat16)
    mask = jax.random.bernoulli(jax.random.fold_in(k, 1), 0.3, (2, 1, 8, 16))
    got = ops.scaled_masked_softmax(x, mask, scale=2.0)
    ref = jax.nn.softmax(
        jnp.where(mask, -10000.0, x.astype(jnp.float32) * 2.0), axis=-1
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref), atol=2e-2
    )
    assert got.dtype == jnp.bfloat16


def test_scaled_masked_softmax_grad():
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 4, 8))
    mask = jnp.zeros((2, 1, 4, 8), bool).at[:, :, :, 6:].set(True)
    g1 = jax.grad(lambda x: ops.scaled_masked_softmax(x, mask, 1.5).sum() ** 2)(x)
    g2 = jax.grad(
        lambda x: jax.nn.softmax(jnp.where(mask, -10000.0, x * 1.5), -1).sum() ** 2
    )(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_causal_softmax():
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 2, 8, 8))
    y = ops.scaled_upper_triang_masked_softmax(x, 1.0)
    yn = np.asarray(y)
    # strictly upper triangle ~ 0; rows sum to 1
    for q in range(7):
        assert yn[..., q, q + 1 :].max() < 1e-3
    np.testing.assert_allclose(yn.sum(-1), 1.0, atol=1e-5)
    # grad matches the unfused composition
    g1 = jax.grad(lambda x: (ops.scaled_upper_triang_masked_softmax(x, 1.0) ** 2).sum())(x)
    causal = np.triu(np.ones((8, 8), bool), 1)
    g2 = jax.grad(
        lambda x: (jax.nn.softmax(jnp.where(jnp.asarray(causal), -10000.0, x), -1) ** 2).sum()
    )(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_softmax_long_sequence_no_limit():
    # the reference kernels cap sk at 2048; ours must not
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 4, 4096))
    y = ops.scaled_softmax(x, 1.0)
    np.testing.assert_allclose(np.asarray(y).sum(-1), 1.0, atol=1e-5)


# ---------------------------------------------------------------------------
# xentropy — ref apex/contrib/test/xentropy


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_matches_reference(smoothing):
    k = jax.random.PRNGKey(0)
    logits = jax.random.normal(k, (16, 50)) * 3
    labels = jax.random.randint(jax.random.fold_in(k, 1), (16,), 0, 50)

    got = ops.softmax_cross_entropy_loss(logits, labels, smoothing)

    logp = jax.nn.log_softmax(logits)
    n = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, n)
    target = (1 - smoothing) * onehot + smoothing / n
    want = -jnp.sum(target * logp, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    g1 = jax.grad(lambda l: ops.softmax_cross_entropy_loss(l, labels, smoothing).sum())(logits)
    g2 = jax.grad(lambda l: (-jnp.sum(target * jax.nn.log_softmax(l), -1)).sum())(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-5)


def test_xentropy_half_to_float():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 10), jnp.bfloat16)
    labels = jnp.array([1, 2, 3, 4])
    out = ops.softmax_cross_entropy_loss(logits, labels, 0.0, True)
    assert out.dtype == jnp.float32


# ---------------------------------------------------------------------------
# MLP / fused dense — ref tests/L0/run_mlp numerical comparison


def test_mlp_matches_sequential():
    from apex_tpu.mlp import MLP

    mlp = MLP(mlp_sizes=(16, 32, 8), activation="relu")
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    params = mlp.init(jax.random.PRNGKey(1), x)
    got = mlp.apply(params, x)
    p = params["params"]
    want = jax.nn.relu(x @ p["kernel_0"] + p["bias_0"]) @ p["kernel_1"] + p["bias_1"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_mlp_no_bias_sigmoid():
    from apex_tpu.mlp import mlp_forward

    x = jnp.ones((2, 4))
    ks = [jnp.ones((4, 4)), jnp.ones((4, 2))]
    got = mlp_forward(x, ks, None, "sigmoid")
    want = jax.nn.sigmoid(x @ ks[0]) @ ks[1]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    with pytest.raises(ValueError):
        mlp_forward(x, ks, None, "tanh")


def test_fused_dense_gelu_dense():
    from apex_tpu.fused_dense import FusedDenseGeluDense

    m = FusedDenseGeluDense(intermediate_features=32, out_features=8)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    params = m.init(jax.random.PRNGKey(1), x)
    got = m.apply(params, x)
    p = params["params"]
    h = x @ p["kernel1"] + p["bias1"]
    h = 0.5 * h * (1 + jax.lax.erf(h / jnp.sqrt(2.0)))
    want = h @ p["kernel2"] + p["bias2"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_layer_norm_large_hidden_gate():
    """Ref fast_layer_norm exists for hidden up to 65k: past the VMEM budget
    the pallas path must decline (fallback to XLA) instead of faulting."""
    from apex_tpu.ops.layer_norm import _pick_block_rows, layer_norm

    # bench-scale hidden keeps a healthy block; 65k hidden exceeds budget
    assert _pick_block_rows(1024, 768) == 256
    assert _pick_block_rows(1024, 16384) in (8, 16)
    assert _pick_block_rows(1024, 65536) is None

    x = jax.random.normal(jax.random.PRNGKey(0), (8, 65536), jnp.bfloat16)
    w = jnp.ones((65536,), jnp.bfloat16)
    b = jnp.zeros((65536,), jnp.bfloat16)
    y = layer_norm(x, w, b)  # auto: XLA path
    from apex_tpu.ops.layer_norm import layer_norm_reference
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(layer_norm_reference(x, w, b), np.float32), atol=1e-2)
    with pytest.raises(ValueError, match="VMEM"):
        layer_norm(x, w, b, use_pallas=True)

"""Regression guard for the round-4 fp32-backward-matmul find (PERF.md).

The projection layers once computed ``dot(..., preferred_element_type=
f32).astype(bf16)``; the forward was equivalent to a bf16 dot (the MXU
accumulates in fp32 either way) but the f32 intermediate made every
backward cotangent f32, so all dX/dW matmuls ran as f32(-mixed) dots —
the slow MXU path, ~2/3 of step flops. The signature of that bug class is
a *mixed-dtype* dot: a bf16 parameter (or activation) meeting an f32
cotangent. This test walks the flagship train-step jaxpr and asserts no
mixed dot exists — the CPU-fallback attention/LM-head reference dots are
legitimately pure-f32 and allowed.
"""

import collections

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P


def _dot_dtypes(jaxpr):
    found = collections.Counter()

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "dot_general":
                found[tuple(sorted(str(v.aval.dtype)
                                   for v in eqn.invars))] += 1
            for p in eqn.params.values():
                if hasattr(p, "jaxpr"):
                    sub = p.jaxpr
                    walk(sub if hasattr(sub, "eqns") else sub.jaxpr)
                elif hasattr(p, "eqns"):
                    walk(p)
                elif isinstance(p, (list, tuple)):
                    for q in p:
                        if hasattr(q, "jaxpr"):
                            walk(q.jaxpr)
                        elif hasattr(q, "eqns"):
                            walk(q)

    walk(jaxpr.jaxpr)
    return found


@pytest.mark.parametrize("remat", [False, True])
def test_no_mixed_dtype_dots_in_train_step(remat):
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel.mesh import build_mesh
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    cfg = GPTConfig(vocab_size=256, max_seq=128, hidden=128, num_layers=2,
                    num_heads=2, dtype=jnp.bfloat16, remat=remat)
    mesh = build_mesh(tp=1, pp=1, sp=1, devices=jax.devices()[:1])
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    tok = jnp.zeros((2, 128), jnp.int32)

    def loss_fn(p, tok, tgt):
        return jax.shard_map(
            lambda p, t, y: gpt_loss(p, t, y, cfg), mesh=mesh,
            in_specs=(gpt_param_specs(cfg), P(), P()),
            out_specs=P())(p, tok, tgt)

    def train_step(params, opt_state, tok, tgt):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    jaxpr = jax.make_jaxpr(train_step)(params, opt_state, tok, tok)
    dots = _dot_dtypes(jaxpr)

    mixed = {k: c for k, c in dots.items() if len(set(k)) > 1}
    assert not mixed, (
        f"mixed-dtype dots reintroduce the fp32-backward-matmul bug: {mixed}"
    )
    # the projection matmuls (4/layer fwd + their backwards) must be bf16
    assert dots.get(("bfloat16", "bfloat16"), 0) >= 12, dots

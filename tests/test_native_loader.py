"""Native data-loader core tests: the C++ gather/normalize must agree with
numpy exactly, survive pipelined iteration, and reject bad indices."""

import numpy as np
import pytest

from apex_tpu._native import build_lib
from apex_tpu.data import BatchLoader, normalize_u8


def test_native_lib_builds():
    # the image ships g++; the native path must actually be exercised here
    assert build_lib() is not None


def test_gather_matches_numpy():
    src = np.random.RandomState(0).randn(100, 3, 5).astype(np.float32)
    bl = BatchLoader(src, n_workers=3)
    idx = np.asarray([5, 17, 99, 0, 42])
    np.testing.assert_array_equal(bl.gather(idx), src[idx])
    bl.close()


def test_pipelined_iterate():
    src = np.arange(64 * 4, dtype=np.int32).reshape(64, 4)
    bl = BatchLoader(src, n_workers=2)
    batches = [np.arange(i, i + 8) for i in range(0, 64, 8)]
    got = list(bl.iterate(batches))
    assert len(got) == 8
    for b, idx in zip(got, batches):
        np.testing.assert_array_equal(b, src[idx])
    bl.close()


def test_gather_rejects_out_of_range():
    bl = BatchLoader(np.zeros((4, 2), np.float32))
    if build_lib() is None:
        pytest.skip("no toolchain")
    with pytest.raises(IndexError):
        bl.gather(np.asarray([0, 7]))
    bl.close()


def test_normalize_u8_matches_numpy():
    rng = np.random.RandomState(1)
    img = rng.randint(0, 256, (16, 8, 8, 3), np.uint8)
    mean, std = (0.485, 0.456, 0.406), (0.229, 0.224, 0.225)
    got = normalize_u8(img, mean, std, n_threads=4)
    want = ((img.astype(np.float32) / 255.0 - np.asarray(mean, np.float32))
            / np.asarray(std, np.float32))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_loader_numpy_fallback(monkeypatch):
    import apex_tpu.data.loader as mod

    monkeypatch.setattr(mod, "build_lib", lambda: None)
    src = np.random.RandomState(2).randn(10, 3).astype(np.float32)
    bl = mod.BatchLoader(src)
    idx = np.asarray([1, 3])
    np.testing.assert_array_equal(bl.gather(idx), src[idx])
    out = list(bl.iterate([idx, np.asarray([0, 9])]))
    np.testing.assert_array_equal(out[1], src[[0, 9]])


def test_prefetch_to_device_order_and_sharding():
    """prefetch_to_device preserves order/values, lands leaves on device
    with the requested sharding, and drains fully (ref data_prefetcher
    semantics: same batches, just in flight early)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.data import prefetch_to_device
    from apex_tpu.parallel.mesh import build_mesh

    batches = [{"x": np.full((8, 4), i, np.float32), "i": np.int32(i)}
               for i in range(5)]
    mesh = build_mesh(tp=1)
    shard = NamedSharding(mesh, P("dp"))

    seen = list(prefetch_to_device(
        iter(batches), size=3,
        sharding=None))
    assert [int(b["i"]) for b in seen] == list(range(5))

    sharded = list(prefetch_to_device(
        (b["x"] for b in batches), size=2, sharding=shard))
    assert len(sharded) == 5
    for i, x in enumerate(sharded):
        assert x.sharding == shard
        np.testing.assert_array_equal(np.asarray(x), batches[i]["x"])

    with pytest.raises(ValueError):
        next(prefetch_to_device(iter(batches), size=0))


def test_prefetch_to_device_early_break_drains():
    """A consumer that breaks early must not strand the ``size`` in-flight
    device batches: close() drains the deque, stops pulling from the
    source, and the generator is finished."""
    import jax

    from apex_tpu.data import prefetch_to_device

    pulls = 0

    def src():
        nonlocal pulls
        for i in range(100):
            pulls += 1
            yield np.full((4,), i, np.float32)

    gen = prefetch_to_device(src(), size=3)
    first = next(gen)
    np.testing.assert_array_equal(np.asarray(first), 0.0)
    gen.close()
    # size (initial) + 1 (refill after the first yield) — and no more
    assert pulls == 4
    with pytest.raises(StopIteration):
        next(gen)
    # the for-loop break path rides the same close() via GC/refcount
    gen2 = prefetch_to_device(src(), size=2)
    for batch in gen2:
        break
    gen2.close()
    with pytest.raises(StopIteration):
        next(gen2)

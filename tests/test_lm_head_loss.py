"""Fused LM-head CE vs dense logits + vocab-parallel CE (ground truth)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.lm_head_loss import (
    _lm_head_loss,
    lm_head_loss,
    lm_head_loss_reference,
)
from apex_tpu.parallel.mesh import TP_AXIS, build_mesh


def _dense_loss(x2, w, t):
    logits = jnp.einsum("nh,vh->nv", x2.astype(jnp.float32),
                        w.astype(jnp.float32))
    lse = jax.nn.logsumexp(logits, axis=-1)
    return lse - jnp.take_along_axis(logits, t[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("n,v,h,bn,bv", [
    (16, 64, 128, 8, 16),     # aligned vocab
    (16, 37, 128, 8, 16),     # ragged final vocab block
    (32, 100, 256, 16, 32),   # ragged, larger
])
def test_fused_matches_dense_and_grads(n, v, h, bn, bv):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    x2 = jax.random.normal(ks[0], (n, h), jnp.float32) * 0.5
    w = jax.random.normal(ks[1], (v, h), jnp.float32) * 0.1
    t = jax.random.randint(ks[2], (n,), 0, v)

    def fused(x2, w):
        return jnp.mean(_lm_head_loss(x2, w, t, None, bn, bv,
                                      "pallas_interpret"))

    def dense(x2, w):
        return jnp.mean(_dense_loss(x2, w, t))

    lf, (dxf, dwf) = jax.jit(jax.value_and_grad(fused, argnums=(0, 1)))(x2, w)
    ld, (dxd, dwd) = jax.jit(jax.value_and_grad(dense, argnums=(0, 1)))(x2, w)
    np.testing.assert_allclose(lf, ld, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(dxf, dxd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(dwf, dwd, rtol=1e-4, atol=1e-5)


def test_reference_unsharded_matches_dense():
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    x2 = jax.random.normal(ks[0], (8, 32))
    w = jax.random.normal(ks[1], (20, 32)) * 0.2
    t = jax.random.randint(ks[2], (8,), 0, 20)
    np.testing.assert_allclose(lm_head_loss_reference(x2, w, t),
                               _dense_loss(x2, w, t), rtol=1e-5, atol=1e-6)


def test_vocab_parallel_fused_matches_dense():
    """tp=8 sharded vocab: loss and grads match the unsharded dense CE."""
    tp = 8
    n, v, h = 16, 8 * 16, 128
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    x = jax.random.normal(ks[0], (n, h), jnp.float32) * 0.5
    w = jax.random.normal(ks[1], (v, h), jnp.float32) * 0.1
    t = jax.random.randint(ks[2], (n,), 0, v)
    mesh = build_mesh(tp=tp, pp=1, sp=1)

    from apex_tpu.transformer.tensor_parallel.mappings import (
        copy_to_tensor_model_parallel_region,
    )

    def sharded(x, w):
        def body(x, w):
            xr = copy_to_tensor_model_parallel_region(x)
            # dense local impl: pallas interpret cannot run inside shard_map
            # (VMA strictness); the custom_vjp + collectives are shared, the
            # kernel math is covered by the unsharded tests above.
            loss = jnp.mean(
                _lm_head_loss(xr, w, t, TP_AXIS, 8, 8, "dense"))
            return jax.lax.psum(loss, TP_AXIS) / tp

        return jax.shard_map(body, mesh=mesh,
                             in_specs=(P(), P(TP_AXIS, None)),
                             out_specs=P())(x, w)

    def dense(x, w):
        return jnp.mean(_dense_loss(x, w, t))

    lf, (dxf, dwf) = jax.jit(jax.value_and_grad(sharded, argnums=(0, 1)))(x, w)
    ld, (dxd, dwd) = jax.jit(jax.value_and_grad(dense, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(lf, ld, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(dxf, dxd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(dwf, dwd, rtol=1e-4, atol=1e-5)


def test_dense_impl_matches_pallas_interpret_unsharded():
    """The dense local impl and the kernel impl are interchangeable."""
    n, v, h, bn, bv = 16, 37, 128, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    x2 = jax.random.normal(ks[0], (n, h), jnp.float32) * 0.5
    w = jax.random.normal(ks[1], (v, h), jnp.float32) * 0.1
    t = jax.random.randint(ks[2], (n,), 0, v)

    def f(impl):
        def loss(x2, w):
            return jnp.mean(_lm_head_loss(x2, w, t, None, bn, bv, impl))
        l, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(x2, w)
        return l, grads

    lp, (dxp, dwp) = f("pallas_interpret")
    ld, (dxd, dwd) = f("dense")
    np.testing.assert_allclose(lp, ld, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dxp, dxd, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(dwp, dwd, rtol=1e-4, atol=1e-6)


def test_public_wrapper_fallback_shapes():
    """(b, s, h) wrapper reshapes and falls back off-TPU."""
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    x = jax.random.normal(ks[0], (2, 8, 32))
    w = jax.random.normal(ks[1], (20, 32)) * 0.2
    t = jax.random.randint(ks[2], (2, 8), 0, 20)
    loss = lm_head_loss(x, w, t)
    assert loss.shape == (2, 8)
    np.testing.assert_allclose(
        loss.reshape(-1), _dense_loss(x.reshape(-1, 32), w, t.reshape(-1)),
        rtol=1e-5, atol=1e-6)

"""Elastic fault-tolerant serving — the ISSUE-13 acceptance gates.

All stock-jax-safe (single device; the multi-"host" cluster runs on the
in-process SimTransport, chaos is step-keyed and deterministic, failure
detection runs on a MANUAL clock — no sleeps, no wall time):

* **chaos acceptance** — a decode worker killed mid-decode under a
  burst at ~2× capacity: zero stream corruption (surviving AND migrated
  request streams BITWISE equal the fault-free run, greedy and sampled,
  fp32 and int8/int4 KV pools), bounded goodput loss, and the cluster
  drains (no deadlock);
* **transfer reliability** — corrupted / dropped / stalled transfers
  are detected (CRC / timeout), retried with exponential backoff, and
  the stream still lands bitwise; a retry ladder that runs dry becomes
  an explicit ``transfer_failed`` terminal state;
* **preemptible workers** — SIGTERM (via PreemptionHandler.trigger, the
  exact signal code path) drains: prefill re-enqueues staged prompts at
  the router, decode proactively migrates before exit;
* **membership** — heartbeat-miss and StallWatchdog detection mark a
  stalled worker dead so its requests migrate; autoscale joins/drains
  workers off the backlog/occupancy gauges;
* **compile gate** — a kill-and-migrate run on warmed workers mints
  ZERO new compilations (migration reuses the existing
  extract/insert/decode programs);
* satellites: ``InferenceEngine.evict_slot``/``restore_slot`` local
  no-op pin, the router tenant-table GC bound, the chaos-field
  ``monitor.regress`` polarity.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.analyze.recompile import recompile_guard
from apex_tpu.monitor.alerts import AlertRule, Condition
from apex_tpu.monitor.events import (
    EventLog,
    chrome_trace,
    request_spans,
    stitch_traces,
)
from apex_tpu.monitor.flight import load_dumps
from apex_tpu.monitor.regress import classify_metric, compare_records
from apex_tpu.monitor.slo import SloSpec
from apex_tpu.resilience.preemption import StallWatchdog
from apex_tpu.serve import (
    AutoscalePolicy,
    ClusterChaos,
    ClusterConfig,
    InferenceEngine,
    Request,
    Router,
    RouterConfig,
    SamplingConfig,
    ServeCluster,
    ServeConfig,
)
from apex_tpu.serve.cluster.chaos import (
    CorruptTransfer,
    DropTransfer,
    KillWorker,
    PreemptWorker,
    StallLink,
    StallWorker,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

CFG = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)

REQS = [
    Request("a", [1, 2, 3, 4, 5], max_new_tokens=6),
    Request("b", [7, 8, 9], max_new_tokens=8),
    Request("c", list(range(20, 42)), max_new_tokens=8),
    Request("d", [11, 3, 11, 3, 11, 3, 7], max_new_tokens=9),
    Request("e", list(range(60, 73)), max_new_tokens=7),
]


def _serve_cfg(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return ServeConfig(**kw)


class _ManualClock:
    """Deterministic cluster time: one .advance per tick."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, **fields):
        self.records.append(fields)

    def flush(self):
        pass


def _drive(cl, clock=None, tick_ms=5.0, max_steps=20000):
    steps = 0
    while cl.active and steps < max_steps:
        cl.step()
        if clock is not None:
            clock.advance(tick_ms / 1e3)
        steps += 1
    assert steps < max_steps, "cluster failed to drain"
    return steps


# ---------------------------------------------------------------------------
# ACCEPTANCE: worker killed mid-decode → bitwise streams, bounded goodput


@pytest.mark.parametrize("kv_quant,greedy", [
    ("none", True),
    ("none", False),
    ("int8", True),
    ("int8", False),
    ("int4", True),
    ("int4", False),
])
def test_kill_mid_decode_streams_bitwise(kv_quant, greedy):
    """The chaos acceptance gate: a decode worker dies mid-run under a
    burst of ~2× slot capacity; every request still completes, and
    every stream — the migrated ones included — is BITWISE equal to the
    fault-free run. Manual clock: the run is exactly reproducible."""
    sampling = (SamplingConfig() if greedy
                else SamplingConfig(temperature=0.7, top_k=13))
    scfg = _serve_cfg(kv_quant=kv_quant, sampling=sampling)
    slo = SloSpec(ttft_ms=600000.0)

    def run(chaos):
        clock = _ManualClock()
        events = EventLog(keep=True, clock=clock)
        ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=scfg,
                             router=RouterConfig(slo=slo))
        cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
        for r in REQS:  # one burst: ~2.5x the 2 slots a decode host has
            cl.submit(r)
        _drive(cl, clock)
        return cl, events

    cl_ff, _ = run(None)
    chaos = ClusterChaos([KillWorker(at_step=12, worker="decode0")])
    cl_ch, events = run(chaos)
    st = cl_ch.stats()
    # the fault happened and was survived: a real death, real migrations
    assert st["worker_deaths"] == 1
    assert st["migrations_total"] >= 1
    assert st["replayed_tokens"] >= 1
    assert st["completed"] + len(cl_ch.shed) == len(REQS)  # drained
    # zero stream corruption: bitwise vs the fault-free run
    ff = cl_ff.finished
    ch = cl_ch.finished
    assert set(ch) == set(ff) == {r.uid for r in REQS}
    for uid in ff:
        assert ch[uid] == ff[uid], uid
    # bounded goodput loss (here: generous budgets -> no loss at all)
    gf_ff = cl_ff.stats()["slo_report"]["good_fraction"]
    gf_ch = st["slo_report"]["good_fraction"]
    assert gf_ch is not None and gf_ch >= gf_ff - 0.3
    # the elastic lifecycle is in the ONE shared event stream
    evs = [r for r in events.records if r.get("kind") == "event"]
    names = {r["event"] for r in evs}
    assert {"worker_join", "worker_leave", "migrate_start",
            "migrate_end", "replay"} <= names
    leave = [r for r in evs if r["event"] == "worker_leave"]
    assert [r["reason"] for r in leave] == ["killed"]


@pytest.mark.parametrize("greedy", [True, False],
                         ids=["greedy", "sampled"])
def test_kill_mid_decode_adapter_binding_survives_bitwise(greedy):
    """PR-16 satellite: a decode worker dies while adapter-bound streams
    decode on it; migration carries the adapter binding (by NAME — the
    destination re-resolves its own pool slot, cold-loading from the
    catalog if needed) and every stream, adapter-bound or base, is
    BITWISE the fault-free run."""
    from apex_tpu.serve import make_adapter_weights

    w1 = make_adapter_weights(CFG, 4, jax.random.PRNGKey(42), std=0.05)
    sampling = (SamplingConfig() if greedy
                else SamplingConfig(temperature=0.7, top_k=13))
    scfg = _serve_cfg(sampling=sampling, lora_rank=4, max_adapters=3)
    areqs = [
        Request("a", [1, 2, 3, 4, 5], max_new_tokens=6, adapter="t1"),
        Request("b", [7, 8, 9], max_new_tokens=8),
        Request("c", list(range(20, 42)), max_new_tokens=8, adapter="t1"),
        Request("d", [11, 3, 11, 3, 11, 3, 7], max_new_tokens=9,
                adapter="t1"),
        Request("e", list(range(60, 73)), max_new_tokens=7),
    ]

    def run(chaos):
        clock = _ManualClock()
        events = EventLog(keep=True, clock=clock)
        ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=scfg,
                             router=RouterConfig(
                                 slo=SloSpec(ttft_ms=600000.0)))
        cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
        cl.load_adapter("t1", w1, scale=1.5)
        for r in areqs:
            cl.submit(r)
        _drive(cl, clock)
        return cl

    cl_ff = run(None)
    cl_ch = run(ClusterChaos([KillWorker(at_step=12, worker="decode0")]))
    st = cl_ch.stats()
    assert st["worker_deaths"] == 1
    assert st["migrations_total"] >= 1
    ff, ch = cl_ff.finished, cl_ch.finished
    assert set(ch) == set(ff) == {r.uid for r in areqs}
    for uid in ff:
        assert ch[uid] == ff[uid], uid
    # the survivor actually serves the adapter traffic adapter-warm
    assert st["adapters"]["warm_dispatches"] + \
        st["adapters"]["cold_dispatches"] >= 1


def test_migrate_span_in_trace_on_one_clock():
    """The migrate span renders in the Chrome trace next to the other
    lifecycle spans, all on the one shared clock."""
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    chaos = ClusterChaos([KillWorker(at_step=12, worker="decode0")])
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    spans = request_spans(events.records)
    migrated = {r["uid"] for r in events.records
                if r.get("kind") == "event" and r["event"] == "migrate_start"}
    assert migrated
    for uid in migrated:
        names = {s["name"] for s in spans[uid]}
        assert "migrate" in names
        mig = [s for s in spans[uid] if s["name"] == "migrate"][0]
        assert mig["t1_ms"] >= mig["t0_ms"]
        # ordering on the shared clock: the hop happens mid-lifecycle
        by_ev = {}
        for r in events.records:
            if r.get("kind") == "event" and r.get("uid") == uid:
                by_ev.setdefault(r["event"], r["t_ms"])
        assert (by_ev["first_token"] <= by_ev["migrate_start"]
                <= by_ev["migrate_end"] <= by_ev["retired"])


# ---------------------------------------------------------------------------
# Transfer reliability: CRC, timeout, backoff, terminal failure


def test_corrupt_transfer_detected_retried_bitwise():
    """A corrupted transfer is caught by the CRC at delivery, retried
    with backoff, and the stream lands bitwise — never a silent
    divergence. Retry counters surface in stats()."""
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    chaos = ClusterChaos([CorruptTransfer(at_step=0, count=2)])
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         retry_backoff_ms=2.0)
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    assert st["transfer"]["faults"]["corrupts"] == 2
    assert st["elastic"]["transfer_crc_failures"] == 2
    assert st["transfer_retries"] == 2
    assert not cl.shed
    out = cl.finished
    assert out == ref  # bitwise, corruption and all
    retry_evs = [r for r in events.records if r.get("kind") == "event"
                 and r["event"] == "transfer_retry"]
    assert len(retry_evs) == 2
    assert all(r["reason"] == "crc" for r in retry_evs)


def test_dropped_transfer_times_out_and_retries():
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    chaos = ClusterChaos([DropTransfer(at_step=0, count=1)])
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         transfer_timeout_ms=40.0, retry_backoff_ms=2.0)
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    assert st["transfer"]["faults"]["drops"] == 1
    assert st["elastic"]["transfer_timeouts"] >= 1
    assert st["transfer_retries"] >= 1
    assert cl.finished == ref


def test_stalled_transfer_times_out_and_late_copy_is_ignored():
    """A transfer stalled past the timeout is retried; when the stalled
    original finally limps in, the receiver drops it as a duplicate
    instead of double-installing."""
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    chaos = ClusterChaos([StallLink(at_step=0, stall_ms=60.0, count=1)])
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         transfer_timeout_ms=25.0, retry_backoff_ms=2.0)
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    assert st["transfer"]["faults"]["stalls"] == 1
    assert st["elastic"]["transfer_timeouts"] >= 1
    assert st["elastic"]["duplicates_ignored"] >= 1
    assert cl.finished == ref


def test_transfer_failed_is_terminal_not_a_hang():
    """Every attempt corrupted: the retry ladder runs dry and the
    request becomes an explicit transfer_failed terminal state — the
    cluster still drains and keeps serving everything else."""
    scfg = _serve_cfg()
    clock = _ManualClock()
    # enough corrupt faults to rot EVERY attempt (initial + 2 retries);
    # one victim request first, then clean traffic behind it
    chaos = ClusterChaos([CorruptTransfer(at_step=0, count=3)])
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         transfer_max_retries=2, retry_backoff_ms=2.0)
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    victim = Request("victim", list(range(1, 9)), max_new_tokens=4)
    cl.submit(victim)
    # drive the victim's retry ladder dry before offering more traffic
    steps = 0
    while cl.active and steps < 20000:
        cl.step()
        clock.advance(0.005)
        steps += 1
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    assert st["elastic"]["transfer_crc_failures"] == 3
    assert st["elastic"]["transfer_failed"] == 1
    failed = [d for d in cl.shed.values() if d.reason == "transfer_failed"]
    assert len(failed) == 1 and failed[0].request.uid == "victim"
    assert st["completed"] == len(REQS)   # everything else still served
    assert st["completed"] + len(cl.shed) == len(REQS) + 1  # drained
    # the router ledger moved the victim admitted -> shed: the invariant
    # submitted == admitted + shed + queued holds and shed_rate shows it
    r = st["router"]
    assert r["submitted"] == r["admitted"] + r["shed"] + r["queue_depth"]
    assert r["shed"] == 1 and r["shed_rate"] > 0


def test_drop_without_timeout_is_a_loud_config_error():
    """A dropped send is only detectable by timeout; injecting one into
    a cluster that cannot notice must fail the configuration loudly
    instead of hanging the stream forever."""
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    chaos = ClusterChaos([DropTransfer(at_step=0)])
    cl = ServeCluster(PARAMS, CFG, ccfg, chaos=chaos)
    cl.submit(Request("x", [1, 2, 3], max_new_tokens=2))
    with pytest.raises(ValueError, match="transfer_timeout_ms"):
        cl.step()


def test_forever_stall_without_detection_is_a_loud_config_error():
    """A wedged worker is only detectable by heartbeat or watchdog;
    injecting an unbounded stall with neither armed must fail loudly
    instead of hanging its requests forever."""
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    cl = ServeCluster(PARAMS, CFG, ccfg, chaos=ClusterChaos(
        [StallWorker(at_step=0, worker="decode0")]))
    with pytest.raises(ValueError, match="heartbeat_timeout_ms"):
        cl.step()


def test_headless_fleet_with_autoscale_respawns_and_serves():
    """Losing EVERY decode worker with autoscale armed replaces the
    capacity instead of shedding: the fleet respawns and every request
    still completes bitwise."""
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    chaos = ClusterChaos([KillWorker(at_step=10, worker="decode0")])
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         autoscale=AutoscalePolicy(max_decode=2,
                                                   cooldown_ms=0.0))
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    assert not cl.shed
    assert cl.finished == ref
    assert cl.membership.autoscale_ups >= 1
    assert len(cl.alive_decode_workers()) >= 1


def test_all_decode_workers_dead_sheds_instead_of_hanging():
    """Losing EVERY decode worker with no autoscale to replace them is
    fatal-by-config: in-flight handoffs and queued work become explicit
    no_decode_workers terminal sheds and the cluster drains."""
    clock = _ManualClock()
    chaos = ClusterChaos([KillWorker(at_step=10, worker="decode0")])
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)   # asserts drain inside
    assert not cl.active
    assert cl.stats()["completed"] + len(cl.shed) == len(REQS)
    assert {d.reason for d in cl.shed.values()} == {"no_decode_workers"}


# ---------------------------------------------------------------------------
# Preemptible workers: SIGTERM → drain protocol


def test_preempted_decode_worker_migrates_then_leaves():
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    chaos = ClusterChaos([PreemptWorker(at_step=12, worker="decode0")])
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    assert cl.membership.state("decode0") == "dead"
    assert cl.membership.record("decode0").reason == "preempted"
    # a drained exit is voluntary: not a death
    assert st["worker_deaths"] == 0
    assert st["migrations_total"] >= 1
    assert cl.finished == ref
    leave = [r for r in events.records if r.get("kind") == "event"
             and r["event"] == "worker_leave"]
    assert [r["reason"] for r in leave] == ["preempted"]


def test_preempted_prefill_worker_requeues_staged_prompts():
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    chaos = ClusterChaos([PreemptWorker(at_step=2, worker="prefill0")])
    ccfg = ClusterConfig(n_prefill=2, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    assert cl.membership.state("prefill0") == "dead"
    assert cl.membership.record("prefill0").reason == "preempted"
    assert cl.finished == ref  # everything still served, bitwise
    # the drain finished the in-flight prompt instead of re-prefilling it
    assert cl.stats()["worker_deaths"] == 0


def test_killed_prefill_worker_requeues_even_midflight():
    """A KILLED prefill host loses its staging pool; its mid-flight
    prompt restarts from scratch elsewhere — prefill is deterministic,
    so streams are unchanged."""
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    chaos = ClusterChaos([KillWorker(at_step=3, worker="prefill0")])
    ccfg = ClusterConfig(n_prefill=2, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    assert cl.finished == ref
    assert cl.router.requeued >= 1
    assert cl.stats()["worker_deaths"] == 1


# ---------------------------------------------------------------------------
# Membership: heartbeat-miss death, stall watchdog, autoscale


def test_stalled_worker_heartbeat_death_migrates():
    """A wedged decode worker stops beating; the heartbeat detector
    declares it dead at the configured timeout on the MANUAL clock and
    its requests migrate — streams bitwise."""
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    chaos = ClusterChaos([StallWorker(at_step=12, worker="decode0")])
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         heartbeat_timeout_ms=50.0)
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    assert cl.membership.state("decode0") == "dead"
    assert cl.membership.record("decode0").reason == "heartbeat"
    assert st["heartbeat_misses"] == 1
    assert st["worker_deaths"] == 1
    assert cl.finished == ref
    # the death stamp sits one timeout after the last beat, exactly
    rec = cl.membership.record("decode0")
    assert rec.left_ms - rec.last_beat_ms >= 50.0


def test_short_stall_recovers_without_death():
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    chaos = ClusterChaos([StallWorker(at_step=12, worker="decode0",
                                      for_steps=4)])  # 20 "ms" < 100
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         heartbeat_timeout_ms=100.0)
    cl = ServeCluster(PARAMS, CFG, ccfg,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    assert st["worker_deaths"] == 0 and st["heartbeat_misses"] == 0
    assert st["migrations_total"] == 0
    assert cl.finished == ref


def test_stall_watchdog_dumps_diagnostics_and_migrates():
    """resilience.StallWatchdog + cluster: the stalled decode worker
    trips its per-worker watchdog on the shared manual clock (no
    sleeps, no daemon thread), per-worker diagnostics land in the sink,
    the worker is marked dead and its requests migrate."""
    scfg = _serve_cfg()
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS)
    clock = _ManualClock()
    sink = _ListSink()
    chaos = ClusterChaos([StallWorker(at_step=12, worker="decode0")])
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         watchdog_timeout_ms=50.0)
    cl = ServeCluster(PARAMS, CFG, ccfg, sink=sink,
                      events=EventLog(keep=True, clock=clock), chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    assert cl.membership.state("decode0") == "dead"
    assert cl.membership.record("decode0").reason == "stall"
    assert cl.finished == ref
    # the watchdog's own diagnostic record (thread stacks) AND the
    # cluster's per-worker snapshot both reached the sink
    stall_recs = [r for r in sink.records if "stalls_total" in r]
    assert len(stall_recs) == 1 and "stacks" in stall_recs[0]
    wd_recs = [r for r in sink.records
               if r.get("phase") == "watchdog" and r.get("worker") == "decode0"]
    assert len(wd_recs) == 1
    assert wd_recs[0]["occupied_slots"] >= 1  # it held live requests


def test_stall_watchdog_manual_clock_unit():
    """The new StallWatchdog clock/check surface: drivable without the
    daemon thread, fires once per stall, re-arms on tick."""
    t = {"v": 0.0}
    fired = []
    wd = StallWatchdog(timeout_s=1.0, clock=lambda: t["v"],
                       on_stall=fired.append)
    wd.tick(0)
    assert not wd.check()
    t["v"] = 0.9
    assert not wd.check()
    t["v"] = 1.1
    assert wd.check() and len(fired) == 1
    assert not wd.check()  # one shot per stall
    wd.tick(1)             # re-armed
    t["v"] = 2.0
    assert not wd.check()
    t["v"] = 2.2
    assert wd.check() and wd.stalls == 2


def test_autoscale_up_and_down_on_gauges():
    """Backlog at saturated occupancy joins a worker; an idle fleet
    drains one back down — both decisions off the live gauges, both
    evented, neither counted as a death."""
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    pol = AutoscalePolicy(scale_up_queue_depth=3, scale_up_occupancy=0.5,
                          scale_down_occupancy=0.1, min_decode=1,
                          max_decode=2, cooldown_ms=0.0)
    scfg = _serve_cfg(num_slots=1)
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         autoscale=pol)
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events)
    rng = np.random.default_rng(0)
    reqs = [Request(f"r{i}", rng.integers(0, 97, size=12).tolist(),
                    max_new_tokens=6) for i in range(10)]
    for r in reqs:
        cl.submit(r)
    _drive(cl, clock)
    assert len(cl.decode_workers) == 2           # scaled up mid-run
    assert cl.membership.autoscale_ups == 1
    assert cl.stats()["completed"] == len(reqs)
    # drained and idle now: keep ticking -> scale back down
    for _ in range(5):
        cl.step()
        clock.advance(0.005)
    assert cl.membership.autoscale_downs == 1
    assert len(cl.alive_decode_workers()) == 1
    assert cl.stats()["worker_deaths"] == 0
    names = [r["event"] for r in events.records if r.get("kind") == "event"]
    assert names.count("worker_join") == 3       # 1 prefill + 2 decode
    leave = [r for r in events.records if r.get("kind") == "event"
             and r["event"] == "worker_leave"]
    assert [r["reason"] for r in leave] == ["scale_down"]


# ---------------------------------------------------------------------------
# Compile gate: migration mints no new programs on warmed workers


def test_kill_and_migrate_zero_new_compiles_when_warm():
    scfg = _serve_cfg()
    ccfg = ClusterConfig(n_prefill=1, n_decode=3, serve=scfg,
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    cl = ServeCluster(PARAMS, CFG, ccfg)
    # warm round: every worker prefills/inserts/decodes, and one kill
    # compiles the ONE shared migrate-extract program
    for r in REQS:
        cl.submit(r)
    steps = 0
    while cl.active and steps < 20000:
        if steps == 12:
            cl.kill_worker("decode0")
        cl.step()
        steps += 1
    assert cl.stats()["migrations_total"] >= 1
    # guarded round: a fresh workload + a SECOND kill recompiles nothing
    reqs2 = [Request(f"g{i}", [3 + i, 5, 7, 11], max_new_tokens=5)
             for i in range(4)]
    ref = InferenceEngine(PARAMS, CFG, scfg).run(
        [Request(r.uid, r.tokens, max_new_tokens=r.max_new_tokens)
         for r in reqs2])
    with recompile_guard(cl.programs(), budget=0):
        for r in reqs2:
            cl.submit(r)
        steps = 0
        killed = False
        while cl.active and steps < 20000:
            if not killed and any(
                    cl._workers["decode1"].live_uids()):
                cl.kill_worker("decode1")
                killed = True
            cl.step()
            steps += 1
    assert killed and cl.stats()["worker_deaths"] == 2
    out = cl.finished
    for r in reqs2:
        assert out[r.uid] == ref[r.uid], r.uid


# ---------------------------------------------------------------------------
# Engine satellite: evict_slot / restore_slot local no-op


@pytest.mark.parametrize("greedy", [True, False])
def test_evict_restore_local_noop_bitwise(greedy):
    sampling = (SamplingConfig() if greedy
                else SamplingConfig(temperature=0.7, top_k=13))
    scfg = _serve_cfg(num_slots=2, sampling=sampling)
    ref = InferenceEngine(PARAMS, CFG, scfg).run(REQS[:2])
    eng = InferenceEngine(PARAMS, CFG, scfg)
    for r in REQS[:2]:
        eng.submit(r)
    # step until both are mid-decode
    while not (eng._active.all() and all(
            s is not None and len(s.generated) >= 2 for s in eng._slots)):
        eng.step()
    st = eng.stats()
    rec = eng.evict_slot("a")
    assert rec["seq_len"] > rec["prompt_len"] - 1
    assert eng.occupancy() == 0.5
    eng.restore_slot(rec)   # same blocks, same pool: a pure no-op
    while eng.active:
        eng.step()
    assert eng.finished == ref  # bitwise
    assert eng.stats()["completed"] == 2  # eviction is not a retirement
    assert st["completed"] == 0


def test_evict_slot_validation():
    scfg = _serve_cfg(num_slots=2)
    eng = InferenceEngine(PARAMS, CFG, scfg)
    with pytest.raises(KeyError, match="no occupied slot"):
        eng.evict_slot("ghost")
    long_req = Request("mid", list(range(30)), max_new_tokens=4)
    eng.submit(long_req)
    eng.step()  # first chunk only: mid-prefill
    with pytest.raises(RuntimeError, match="mid-prefill"):
        eng.evict_slot("mid")


# ---------------------------------------------------------------------------
# Router satellite: the tenant-state tables are bounded


def test_router_tenant_table_bounded_under_churn():
    """A tenant whose every request was shed used to leave vtime +
    counter state behind forever; the table is now bounded and the
    aggregate counters stay exact."""
    r = Router(RouterConfig(max_tenant_states=64))
    n = 2000
    for i in range(n):
        d = r.submit(Request(f"u{i}", [1] * 10, max_new_tokens=10,
                             tenant=f"t{i}"),
                     t_ms=float(i), total_tokens=999999,
                     max_servable_tokens=16)
        assert d is not None and d.reason == "unservable"
    assert r.submitted == n and r.shed == n
    assert len(r.tenants) <= 64
    assert len(r._vtime) <= 64
    assert len(r._last_seen) <= 64
    assert len(r.sheds) <= 64          # the debug window is bounded too
    st = r.stats()
    assert st["tenants_evicted"] == n - len(r.tenants)
    # no request lost to eviction: aggregate + retained == totals
    kept = sum(v["submitted"] for v in st["tenants"].values())
    assert st["evicted_totals"]["submitted"] + kept == n
    # tenants with QUEUED work are never evicted
    r2 = Router(RouterConfig(max_tenant_states=8))
    for i in range(20):
        r2.submit(Request(f"q{i}", [1] * 4, tenant=f"live{i}"), t_ms=0.0)
    assert r2.queue_depth == 20        # all still dispatchable
    served = 0
    while r2.next_request(0, 0.0)[0] is not None:
        served += 1
    assert served == 20


# ---------------------------------------------------------------------------
# Fleet observability (monitor tier 3, ISSUE-14): cross-host traces,
# alert-driven decisions, flight-recorder postmortem


def test_chaos_cross_host_trace_acceptance():
    """ISSUE-14 acceptance: a chaos run (worker killed at step k)
    produces ONE Perfetto trace where the migrated request's spans sit
    on BOTH hosts under one trace id, causally ordered on the single
    shared clock, with zero stitch failures."""
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    chaos = ClusterChaos([KillWorker(at_step=12, worker="decode0")])
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    assert cl.stats()["migrations_total"] >= 1
    # every request minted exactly one trace id, threaded everywhere
    uid_traces = {}
    for r in events.records:
        if r.get("kind") == "event" and "uid" in r and "trace" in r:
            uid_traces.setdefault(r["uid"], set()).add(r["trace"])
    assert set(uid_traces) == {r.uid for r in REQS}
    assert all(len(ts) == 1 for ts in uid_traces.values())
    st = stitch_traces(events.records)
    assert st["stitch_failures"] == 0          # zero, fleet-wide
    migrated = {r["uid"] for r in events.records
                if r.get("kind") == "event"
                and r["event"] == "migrate_start"}
    assert migrated
    trace = chrome_trace(events.records)
    host_pids = {e["args"]["name"][len("host "):]: e["pid"]
                 for e in trace["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"
                 and e["args"]["name"].startswith("host ")}
    assert {"prefill0", "decode0", "decode1"} <= set(host_pids)
    assert trace["stitch"]["stitch_failures"] == 0
    for uid in migrated:
        tid = next(iter(uid_traces[uid]))
        tr = st["traces"][tid]
        # the migrated request touched BOTH decode hosts
        assert {"decode0", "decode1"} <= set(tr["hosts"])
        assert tr["ordered"] and tr["terminal"] == "retired"
        # ...and renders on >= 2 decode-host TRACKS under one trace id
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"
                 and e["name"] == tid
                 and e["pid"] in (host_pids["decode0"],
                                  host_pids["decode1"])]
        assert len({e["pid"] for e in spans}) >= 2
        spans.sort(key=lambda e: e["ts"])
        for a, b in zip(spans, spans[1:]):     # causal on the one clock
            assert a["ts"] + a["dur"] <= b["ts"] + 1e-6


def test_autoscale_is_alert_driven_pinned_via_events():
    """The autoscaler no longer peeks gauges: the scale_up/scale_down
    thresholds are alert rules over the scraped fleet view, and the
    alert_fire events PRECEDE the join/drain they cause in the one
    event stream."""
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    pol = AutoscalePolicy(scale_up_queue_depth=3, scale_up_occupancy=0.5,
                          scale_down_occupancy=0.1, min_decode=1,
                          max_decode=2, cooldown_ms=0.0)
    ccfg = ClusterConfig(n_prefill=1, n_decode=1,
                         serve=_serve_cfg(num_slots=1),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         autoscale=pol)
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events)
    rng = np.random.default_rng(0)
    reqs = [Request(f"r{i}", rng.integers(0, 97, size=12).tolist(),
                    max_new_tokens=6) for i in range(10)]
    for r in reqs:
        cl.submit(r)
    _drive(cl, clock)
    for _ in range(5):
        cl.step()
        clock.advance(0.005)
    evs = [r for r in events.records if r.get("kind") == "event"]
    t_up = next(r["t_ms"] for r in evs if r["event"] == "alert_fire"
                and r["rule"] == "scale_up")
    t_join2 = [r["t_ms"] for r in evs if r["event"] == "worker_join"
               and r["worker"] == "decode1"][0]
    assert t_up <= t_join2                     # the alert caused the join
    t_down = next(r["t_ms"] for r in evs if r["event"] == "alert_fire"
                  and r["rule"] == "scale_down")
    t_leave = next(r["t_ms"] for r in evs if r["event"] == "worker_leave"
                   and r["reason"] == "scale_down")
    assert t_down <= t_leave                   # and the drain
    st = cl.stats()
    assert st["alerts_fired_total"] >= 2
    assert st["fleet"]["alerts"]["alerts_fired_total"] >= 2
    assert cl.membership.autoscale_ups == 1    # actuation gate unchanged
    assert cl.membership.autoscale_downs == 1


def test_heartbeat_death_is_alert_evidenced():
    """A heartbeat-missed death routes through the alert plane: the
    heartbeat_absent firing is a first-class event that precedes the
    migration it triggers."""
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    chaos = ClusterChaos([StallWorker(at_step=12, worker="decode0")])
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         heartbeat_timeout_ms=50.0)
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    evs = [r for r in events.records if r.get("kind") == "event"]
    fire = next(r for r in evs if r["event"] == "alert_fire"
                and r["rule"] == "heartbeat_absent")
    assert fire["ctx_worker"] == "decode0"
    t_mig = min(r["t_ms"] for r in evs if r["event"] == "migrate_start")
    assert fire["t_ms"] <= t_mig
    # a stalled worker is also a scrape miss while it is wedged
    assert cl.scraper.scrape_misses_total >= 1


def test_postmortem_rebuilds_prekill_timeline_from_dumps(tmp_path,
                                                         capsys):
    """ISSUE-14 acceptance: the kill dumps the dying worker's flight
    ring (plus the cluster ring) atomically; with the survivors dumped
    too, ``python -m apex_tpu.monitor.postmortem`` rebuilds the merged
    pre-kill timeline — every trace, both hosts, zero stitch failures —
    from the dump files ALONE."""
    from apex_tpu.monitor.postmortem import main as postmortem_main

    d = str(tmp_path / "flight")
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         flight_dir=d)
    chaos = ClusterChaos([KillWorker(at_step=12, worker="decode0")])
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    # the kill itself dumped the dying worker + the cluster ring
    auto = load_dumps(d)
    assert {x["worker"] for x in auto} == {"decode0", "cluster"}
    assert all(x["reason"] == "killed" for x in auto)
    # flight_dump events recorded the escalation in the stream
    assert sum(1 for r in events.records if r.get("kind") == "event"
               and r["event"] == "flight_dump") == 2
    # survivors dump at end-of-incident (reason manual)
    cl.dump_flight(reason="manual")
    # the CLI (main == python -m) rebuilds from the dumps alone
    rc = postmortem_main([d, "--timeline", "0"])
    assert rc == 0
    import json as _json

    rec = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "postmortem"
    assert rec["n_traces"] == len(REQS)
    assert rec["trace_stitch_failures"] == 0
    assert rec["n_retired"] == len(REQS)
    assert {"worker": "decode0", "reason": "killed",
            "t_ms": rec["worker_leaves"][0]["t_ms"]} \
        in rec["worker_leaves"]
    # the pre-kill half is genuinely there: decode0-hosted decode
    # activity from BEFORE the kill, and the migration out of it
    n_mig = rec.get("n_migrations", 0)
    assert n_mig >= 1
    st = cl.stats()
    assert st["fleet"]["flight"]["decode0"]["dumps"] == 2  # kill + manual


def test_autoscale_without_scraping_is_a_loud_config_error():
    """Autoscale (and user alert rules) act on the alert engine, which
    evaluates over scraped views — a non-scraping cluster could never
    fire them, so the combination fails at construction."""
    with pytest.raises(ValueError, match="scrape_every"):
        ClusterConfig(n_prefill=1, n_decode=1, serve=_serve_cfg(),
                      scrape_every=0,
                      autoscale=AutoscalePolicy()).validate()
    with pytest.raises(ValueError, match="scrape_every"):
        ClusterConfig(n_prefill=1, n_decode=1, serve=_serve_cfg(),
                      scrape_every=0,
                      alert_rules=(AlertRule("x", conditions=(
                          Condition("s", ">", 0.0),)),)).validate()
    # scraping off WITHOUT rules is a legal floor (the bench's off arm)
    ClusterConfig(n_prefill=1, n_decode=1, serve=_serve_cfg(),
                  scrape_every=0, flight_capacity=0).validate()


def test_death_dump_streams_to_sink_without_flight_dir(tmp_path):
    """No flight_dir but a durable JsonlSink: the kill's black box
    streams into the shared log as header-fenced write_many batches
    instead of being dropped."""
    from apex_tpu.monitor import JsonlSink, read_jsonl

    path = str(tmp_path / "fleet.jsonl")
    clock = _ManualClock()
    chaos = ClusterChaos([KillWorker(at_step=12, worker="decode0")])
    ccfg = ClusterConfig(n_prefill=1, n_decode=2, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)))
    sink = JsonlSink(path, buffer_steps=4)
    cl = ServeCluster(PARAMS, CFG, ccfg, sink=sink,
                      events=EventLog(keep=True, clock=clock),
                      chaos=chaos)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    sink.close()
    recs = list(read_jsonl(path))
    headers = [r for r in recs if r.get("kind") == "flight_dump_header"]
    assert {h["worker"] for h in headers} == {"decode0", "cluster"}
    assert all(h["reason"] == "killed" for h in headers)
    # each header is immediately followed by its n_records batch
    for h in headers:
        i = recs.index(h)
        batch = recs[i + 1:i + 1 + h["n_records"]]
        assert len(batch) == h["n_records"]


def test_custom_alert_rules_and_scrape_plane_in_stats():
    """User-declared rules evaluate over the scraped series; the scrape
    plane accounts for itself in stats() (scrapes_total, coverage,
    scrape_ms) and the worker scrape snapshots carry the engine
    series."""
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    ccfg = ClusterConfig(
        n_prefill=1, n_decode=1, serve=_serve_cfg(),
        router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
        alert_rules=(AlertRule("backlog_high", conditions=(
            Condition("queued_tokens", ">", 0.0),)),))
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events)
    for r in REQS:
        cl.submit(r)
    _drive(cl, clock)
    st = cl.stats()
    fleet = st["fleet"]
    assert fleet["scrapes_total"] > 0
    assert fleet["scrape_coverage"] == 1.0
    assert fleet["scrape_ms_p50"] is not None
    assert st["scrape_coverage"] == 1.0
    rules = [f.rule for f in cl._alerts.firings]
    assert "backlog_high" in rules             # it fired while loaded
    assert not cl._alerts.active("backlog_high")  # and resolved, drained
    # worker scrape snapshot: engine + worker series, Prometheus-ready
    snap = cl.decode_workers[0].scrape()
    names = {s["name"] for s in snap["series"]}
    assert {"worker_up", "requests_completed_total", "occupancy",
            "tokens_generated_total", "handoffs_admitted_total"} <= names
    assert all(s["labels"].get("worker") == "decode0"
               for s in snap["series"])
    import json as _json

    _json.dumps(snap)
    # fleet_goodput_rps rides the stats record for the stage-19 gate
    assert st["fleet_goodput_rps"] == st["goodput_rps"]


# ---------------------------------------------------------------------------
# regress satellite: chaos-field polarity + record gating


def test_regress_polarity_covers_chaos_fields():
    for k in ("migrations_total", "replayed_tokens", "worker_deaths",
              "heartbeat_misses", "transfer_retries",
              "elastic.transfer_retries", "overload.worker_deaths"):
        assert classify_metric(k) == "lower", k
    for k in ("goodput_under_chaos_rps", "survivor_good_fraction",
              "chaos.goodput_under_chaos_rps"):
        assert classify_metric(k) == "higher", k


def test_regress_gates_chaos_records():
    base = {"goodput_under_chaos_rps": 10.0, "survivor_good_fraction": 1.0,
            "worker_deaths": 1, "migrations_total": 4,
            "transfer_retries": 0, "replayed_tokens": 4}
    worse = dict(base, survivor_good_fraction=0.5, transfer_retries=3)
    rep = compare_records(base, worse, tol=0.15)
    assert not rep["ok"]
    keys = {e["key"] for e in rep["regressions"]}
    assert {"survivor_good_fraction", "transfer_retries"} <= keys
    # same chaos plan, same outcome: clean
    assert compare_records(base, dict(base), tol=0.15)["ok"]
    # a retry storm from zero must flag even at infinite relative delta
    assert not compare_records({"transfer_retries": 0},
                               {"transfer_retries": 2}, tol=0.15)["ok"]

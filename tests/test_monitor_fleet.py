"""monitor tier 3 — fleet observability plane (ISSUE-14).

All stock-jax-safe and host-side (no model, no device work): the
registry/exposition/aggregation plane, the alert-rules engine, the
flight recorder + postmortem CLI, the distributed-tracing
reconstruction fixes, the ``JsonlSink.write_many`` rotation contract
and the new regress polarity rows. The cluster-integrated acceptance
(one trace id across host tracks under chaos, alert-driven autoscale,
postmortem-from-dumps) lives in ``tests/test_serve_chaos.py``.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from apex_tpu.monitor.alerts import (
    AbsenceRule,
    AlertEngine,
    AlertRule,
    Condition,
    RateRule,
)
from apex_tpu.monitor.events import (
    EventLog,
    chrome_trace,
    request_spans,
    stitch_traces,
)
from apex_tpu.monitor.flight import FlightRecorder, load_dump, load_dumps
from apex_tpu.monitor.hist import Histogram
from apex_tpu.monitor.postmortem import merge_dumps, rebuild
from apex_tpu.monitor.regress import classify_metric, compare_records
from apex_tpu.monitor.registry import (
    FleetScraper,
    MetricsRegistry,
    merge_snapshots,
)
from apex_tpu.monitor.sink import JsonlSink, read_jsonl
from apex_tpu.monitor.view import summarize


# ---------------------------------------------------------------------------
# MetricsRegistry: instruments, labels, cardinality bound, exposition


def test_registry_instruments_and_labels():
    r = MetricsRegistry()
    r.counter("reqs_total", 2, worker="d0")
    r.counter("reqs_total", 3, worker="d0")
    r.counter("reqs_total", 1, worker="d1")
    r.gauge("occupancy", 0.25, t_ms=10.0, worker="d0")
    r.gauge("occupancy", 0.75, t_ms=20.0, worker="d0")  # overwrites
    r.observe("lat_ms", [1.0, 2.0, 4.0], worker="d0")
    snap = r.snapshot(t_ms=30.0)
    json.dumps(snap)  # JSON-serializable by contract
    by = {(s["name"], s["labels"].get("worker")): s
          for s in snap["series"]}
    assert by[("reqs_total", "d0")]["value"] == 5.0
    assert by[("reqs_total", "d1")]["value"] == 1.0
    assert by[("occupancy", "d0")]["value"] == 0.75
    assert by[("lat_ms", "d0")]["hist"]["count"] == 3
    # type confusion is loud, counters are monotonic
    with pytest.raises(ValueError, match="registered as counter"):
        r.gauge("reqs_total", 1.0, worker="d0")
    with pytest.raises(ValueError, match="only go up"):
        r.counter("reqs_total", -1, worker="d0")


def test_registry_cardinality_bound_folds_to_overflow():
    r = MetricsRegistry(max_series=4)
    for i in range(10):
        r.counter("per_tenant_total", 1, tenant=f"t{i}")
    # the table is bounded (the fold target may sit one past the bound)
    assert len(r) <= 5
    assert r.series_dropped_total == 6
    snap = r.snapshot()
    overflow = [s for s in snap["series"]
                if s["labels"].get("overflow") == "true"]
    assert len(overflow) == 1 and overflow[0]["value"] == 6.0
    assert snap["series_dropped_total"] == 6
    # no request lost: retained + overflow == all increments
    total = sum(s["value"] for s in snap["series"]
                if s["name"] == "per_tenant_total")
    assert total == 10.0


def test_prometheus_text_exposition():
    r = MetricsRegistry()
    r.counter("apex_reqs_total", 7, worker="d0", kind="decode")
    r.gauge("apex_occupancy", 0.5, worker="d0")
    r.observe("apex_lat_ms", [0.5, 50.0], worker="d0")
    text = r.expose_text()
    lines = text.splitlines()
    assert "# TYPE apex_reqs_total counter" in lines
    assert "# TYPE apex_occupancy gauge" in lines
    assert "# TYPE apex_lat_ms histogram" in lines
    assert 'apex_reqs_total{kind="decode",worker="d0"} 7' in lines
    assert 'apex_occupancy{worker="d0"} 0.5' in lines
    # histogram: cumulative buckets + the terminal +Inf + sum/count
    buckets = [ln for ln in lines if ln.startswith("apex_lat_ms_bucket")]
    assert buckets, text
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts)          # cumulative by construction
    assert 'le="+Inf"' in buckets[-1] and counts[-1] == 2
    assert 'apex_lat_ms_count{worker="d0"} 2' in lines
    sum_line = [ln for ln in lines
                if ln.startswith("apex_lat_ms_sum")][0]
    assert float(sum_line.rsplit(" ", 1)[1]) == pytest.approx(50.5)


# ---------------------------------------------------------------------------
# Aggregation: merge semantics + FleetView selectors


def test_merge_snapshots_counter_sum_gauge_freshest_hist_merge():
    def worker_snap(name, n, occ, t, lats):
        r = MetricsRegistry()
        r.counter("reqs_total", n, worker=name)
        r.counter("fleet_reqs_total", n)          # shared key: sums
        r.gauge("newest", occ, t_ms=t)            # shared key: freshest
        r.observe("lat_ms", lats)                 # shared key: merges
        return r.snapshot(t_ms=t)

    a = worker_snap("d0", 3, 0.1, 10.0, [1.0, 2.0])
    b = worker_snap("d1", 5, 0.9, 20.0, [4.0, 8.0])
    view = merge_snapshots([("d0", a), ("d1", b)], t_ms=21.0)
    assert view.sources == ["d0", "d1"]
    assert view.value("reqs_total", worker="d0") == 3.0
    assert view.total("reqs_total") == 8.0
    assert view.total("fleet_reqs_total") == 8.0      # summed
    assert view.value("newest") == 0.9                # freshest stamp won
    merged = view.hist("lat_ms")
    one_shot = Histogram().add([1.0, 2.0, 4.0, 8.0])
    assert merged.total == 4
    assert (merged.counts == one_shot.counts).all()   # merge == one-shot
    # order independence (associative+commutative)
    view2 = merge_snapshots([("d1", b), ("d0", a)])
    assert view2.total("reqs_total") == 8.0
    assert view2.value("newest") == 0.9
    d = view.as_dict()
    assert d["reqs_total"] == 8.0 and "lat_ms_p50" in d
    json.dumps(d)


def test_fleet_scraper_coverage_and_timing():
    reg = MetricsRegistry()
    reg.gauge("up", 1.0, worker="d0")

    def targets():
        return [("d0", lambda: reg.snapshot()),
                ("d1", lambda: None),                     # a scrape miss
                ("d2", lambda: (_ for _ in ()).throw(RuntimeError()))]

    sc = FleetScraper(targets, clock=lambda: 123.0)
    view = sc.scrape()
    assert view.t_ms == 123.0
    assert view.sources == ["d0"] and set(view.missed) == {"d1", "d2"}
    st = sc.stats()
    assert st["scrapes_total"] == 1
    assert st["scrape_misses_total"] == 2
    assert st["scrape_coverage"] == pytest.approx(1 / 3)
    assert st["scrape_ms_p50"] is not None  # the scrape measured itself


# ---------------------------------------------------------------------------
# Alert engine: thresholds, for_ticks, absence, rate, external fires


def _view(**scalars):
    r = MetricsRegistry()
    for k, v in scalars.items():
        if isinstance(v, dict):
            for labels, val in v.items():
                r.gauge(k, val, worker=labels)
        else:
            r.gauge(k, v)
    return merge_snapshots([("t", r.snapshot())])


def test_threshold_rule_for_ticks_and_resolve():
    log = EventLog(keep=True)
    eng = AlertEngine([AlertRule(
        "backlog_high",
        conditions=(Condition("backlog_tokens", ">", 100.0),),
        for_ticks=3)], events=log)
    assert eng.evaluate(_view(backlog_tokens=500.0), 1.0) == []
    assert eng.evaluate(_view(backlog_tokens=500.0), 2.0) == []
    fired = eng.evaluate(_view(backlog_tokens=500.0), 3.0)
    assert [f.rule for f in fired] == ["backlog_high"]
    assert eng.active("backlog_high")
    # stays active without re-firing
    assert eng.evaluate(_view(backlog_tokens=500.0), 4.0) == []
    assert eng.alerts_fired_total == 1
    # a dip resets BOTH the firing and the consecutive counter
    assert eng.evaluate(_view(backlog_tokens=0.0), 5.0) == []
    assert not eng.active("backlog_high")
    assert eng.evaluate(_view(backlog_tokens=500.0), 6.0) == []
    names = [(r["event"], r.get("rule")) for r in log.records]
    assert ("alert_fire", "backlog_high") in names
    assert ("alert_resolve", "backlog_high") in names
    assert eng.alerts_resolved_total == 1


def test_condition_aggregates_and_label_filters():
    view = _view(occupancy={"d0": 0.2, "d1": 1.0})
    assert Condition("occupancy", ">=", 0.5, agg="avg").holds(view) is True
    assert Condition("occupancy", ">=", 0.7, agg="avg").holds(view) is False
    assert Condition("occupancy", ">=", 1.0, agg="max").holds(view)
    assert Condition("occupancy", "<=", 0.2, agg="min").holds(view)
    assert Condition("occupancy", ">=", 0.9,
                     labels={"worker": "d1"}).holds(view)
    # a missing series never satisfies a threshold
    assert not Condition("ghost", ">", -1e9).holds(view)


def test_absence_rule_heartbeat_shape():
    eng = AlertEngine([AbsenceRule("hb_d1", series="worker_up",
                                   labels={"worker": "d1"},
                                   for_ticks=2)])
    both = _view(worker_up={"d0": 1.0, "d1": 1.0})
    only0 = _view(worker_up={"d0": 1.0})
    assert eng.evaluate(both, 1.0) == []
    assert eng.evaluate(only0, 2.0) == []          # 1 consecutive miss
    fired = eng.evaluate(only0, 3.0)               # 2: fires
    assert [f.rule for f in fired] == ["hb_d1"]
    assert eng.evaluate(both, 4.0) == [] and not eng.active("hb_d1")


def test_rate_rule_rising_trend():
    eng = AlertEngine([RateRule("shed_rising", series="shed_rate",
                                min_increase=0.1, window_ticks=2)])
    for t, v in ((1, 0.0), (2, 0.05), (3, 0.1)):   # +0.1 not > 0.1
        assert eng.evaluate(_view(shed_rate=float(v)), float(t)) == []
    fired = eng.evaluate(_view(shed_rate=0.5), 4.0)  # 0.5-0.05 > 0.1
    assert [f.rule for f in fired] == ["shed_rising"]
    # flat series resolves
    for t in (5, 6, 7):
        eng.evaluate(_view(shed_rate=0.5), float(t))
    assert not eng.active("shed_rising")


def test_external_fire_shares_ledger_and_events():
    log = EventLog(keep=True)
    hits = []
    eng = AlertEngine([], events=log, on_fire=hits.append)
    f = eng.fire("heartbeat_absent", 42.0, worker="d0", severity="page")
    assert f.rule == "heartbeat_absent" and f.severity == "page"
    assert eng.alerts_fired_total == 1 and len(hits) == 1
    rec = [r for r in log.records if r["event"] == "alert_fire"][0]
    assert rec["rule"] == "heartbeat_absent"
    assert rec["severity"] == "page" and rec["ctx_worker"] == "d0"
    assert eng.summary()[0]["rule"] == "heartbeat_absent"


def test_alert_engine_validation():
    with pytest.raises(ValueError, match="at least one condition"):
        AlertEngine([AlertRule("empty")])
    with pytest.raises(ValueError, match="duplicate rule name"):
        AlertEngine([AbsenceRule("x", series="a"),
                     AbsenceRule("x", series="b")])
    with pytest.raises(ValueError, match="op must be"):
        AlertEngine([AlertRule("bad", conditions=(
            Condition("s", "!!", 1.0),))])
    with pytest.raises(TypeError, match="not an alert rule"):
        AlertEngine(["nope"])


# ---------------------------------------------------------------------------
# Flight recorder: bounded ring, sink protocol, atomic dump


def test_flight_ring_bounds_and_sink_protocol(tmp_path):
    inner = JsonlSink(str(tmp_path / "log.jsonl"), buffer_steps=1)
    fr = FlightRecorder(capacity=4, worker="d0", inner=inner,
                        clock=lambda: 99.0)
    for i in range(10):
        fr.write(step=i, phase="decode", tokens_per_s=float(i))
    inner.close()
    assert len(fr) == 4 and fr.dropped_records == 6
    assert [r["step"] for r in fr.records()] == [6, 7, 8, 9]
    # the ring observed, never swallowed: the inner sink got all 10
    assert len(list(read_jsonl(str(tmp_path / "log.jsonl")))) == 10


def test_flight_step_records_ride_the_shared_clock():
    """Step records written through the sink protocol get the ring's
    clock stamped — postmortem's merged timeline sorts by t_ms, and an
    unstamped step record would sort to the head of a timeline it
    belongs at the tail of."""
    t = {"v": 100.0}
    fr = FlightRecorder(capacity=8, worker="d0", clock=lambda: t["v"])
    fr.record({"kind": "event", "event": "submitted", "uid": "a",
               "t_ms": 1.0})
    t["v"] = 200.0
    fr.write(step=7, phase="decode")
    dump = _mk_dump("d0", "manual", 300.0, fr.records())
    merged = merge_dumps([dump])
    assert [r.get("t_ms") for r in merged] == [1.0, 200.0]
    assert merged[-1]["step"] == 7          # the step record sorts LAST


def test_flight_dump_to_sink_uses_write_many(tmp_path):
    """The no-filesystem dump path: the ring streams into the shared
    JSONL as ONE contiguous header-fenced batch via write_many."""
    path = str(tmp_path / "log.jsonl")
    sink = JsonlSink(path, buffer_steps=1)
    fr = FlightRecorder(capacity=4, worker="d0", clock=lambda: 55.0)
    for i in range(3):
        fr.record({"kind": "event", "event": "decode_chunk",
                   "uid": f"r{i}", "t_ms": float(i)})
    n = fr.dump_to_sink(sink, reason="heartbeat")
    sink.close()
    assert n == 3 and fr.dumps_total == 1
    recs = list(read_jsonl(path))
    hdr = recs[0]
    assert hdr["kind"] == "flight_dump_header"
    assert hdr["worker"] == "d0" and hdr["reason"] == "heartbeat"
    assert hdr["t_dump_ms"] == 55.0 and hdr["n_records"] == 3
    assert [r["uid"] for r in recs[1:]] == ["r0", "r1", "r2"]


def test_exposition_escapes_client_labels():
    """Tenant ids are client-supplied: a quote/backslash/newline in a
    label value must escape, or one tenant invalidates the whole
    Prometheus scrape."""
    r = MetricsRegistry()
    r.counter("t_total", 1, tenant='a"b\\c\nd')
    line = [ln for ln in r.expose_text().splitlines()
            if ln.startswith("t_total{")][0]
    assert line == 't_total{tenant="a\\"b\\\\c\\nd"} 1'


def test_inlog_dump_copies_never_double_count(tmp_path):
    """An in-log flight dump re-writes records already present live in
    the same JSONL; the copies are marked and every reader skips them —
    view counts and chrome-trace tracks are identical before and after
    the dump."""
    path = str(tmp_path / "log.jsonl")
    sink = JsonlSink(path, buffer_steps=1)
    fr = FlightRecorder(capacity=16, worker="decode0", inner=sink,
                        clock=lambda: 50.0)
    log = EventLog(sink=fr, keep=False)
    log.emit("submitted", "a", t_ms=1.0, trace="tr1")
    log.emit("retired", "a", t_ms=9.0, n_tokens=3, host="decode0",
             trace="tr1")
    log.gauge("occupancy", 0.5, t_ms=2.0)
    fr.write(step=1, phase="decode", t_ms=5.0)
    before = summarize(list(read_jsonl(path)))
    fr.dump_to_sink(sink, reason="killed")
    sink.close()
    after_recs = list(read_jsonl(path))
    after = summarize(after_recs)
    for k in ("n_events", "n_gauges", "n_steps", "n_retired",
              "n_requests"):
        assert after[k] == before[k], k
    # chrome trace: no phantom 'host cluster' track from dump/alert
    # worker= fields, and the real host track is there exactly once
    trace = chrome_trace(after_recs)
    host_meta = [e["args"]["name"] for e in trace["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"
                 and e["args"]["name"].startswith("host ")]
    assert host_meta == ["host decode0"]


def test_postmortem_window_cuts_epoch_stamps():
    """t_ms == 0.0 is a real stamp (the log epoch) — --last-s must
    window it out like any other old record."""
    d = _mk_dump("d0", "manual", 100.0, [
        {"kind": "event", "event": "submitted", "uid": "a", "t_ms": 0.0},
        {"kind": "event", "event": "retired", "uid": "a",
         "t_ms": 5000.0, "n_tokens": 1},
    ])
    win = merge_dumps([d], last_s=1.0)
    assert [r["t_ms"] for r in win] == [5000.0]


def test_registry_overflow_keeps_kind_contract():
    r = MetricsRegistry(max_series=2)
    r.counter("c", 1, tenant="t0")
    r.counter("c", 1, tenant="t1")
    r.counter("c", 1, tenant="t2")        # folds into overflow (counter)
    with pytest.raises(ValueError, match="registered as counter"):
        r.gauge("c", 1.0, tenant="t3")    # folded write, same contract


def test_flight_dump_atomic_and_loadable(tmp_path):
    d = str(tmp_path / "dumps")
    fr = FlightRecorder(capacity=8, worker="decode0",
                        clock=lambda: 1234.5)
    for i in range(12):
        fr.record({"kind": "event", "event": "decode_chunk",
                   "uid": f"r{i}", "t_ms": float(i)})
    p1 = fr.dump(d, reason="killed")
    fr.record({"kind": "event", "event": "retired", "uid": "r99",
               "t_ms": 99.0})
    p2 = fr.dump(d, reason="manual")
    assert os.path.basename(p1) == "flight-decode0-1.json"
    assert os.path.basename(p2) == "flight-decode0-2.json"
    one = load_dump(p1)
    assert one["worker"] == "decode0" and one["reason"] == "killed"
    assert one["t_dump_ms"] == 1234.5
    assert len(one["records"]) == 8 and one["dropped_records"] == 4
    # a torn .tmp leftover (a dumper that died mid-write) is never read
    with open(os.path.join(d, "flight-ghost-1.json.tmp.123"), "w") as f:
        f.write('{"torn":')
    dumps = load_dumps(d)
    assert [x["reason"] for x in dumps] == ["killed", "manual"]
    # schema gate
    with open(os.path.join(d, "flight-bad-1.json"), "w") as f:
        json.dump({"schema": 99, "records": []}, f)
    with pytest.raises(ValueError, match="schema"):
        load_dumps(d)


# ---------------------------------------------------------------------------
# Postmortem: merge/dedupe/window + CLI


def _mk_dump(worker, reason, t_dump, records):
    return {"schema": 1, "worker": worker, "reason": reason,
            "t_dump_ms": t_dump, "capacity": 100, "records_total":
            len(records), "dropped_records": 0, "records": records}


def test_postmortem_merge_dedupes_and_windows():
    shared = {"kind": "event", "event": "submitted", "uid": "a",
              "t_ms": 1.0, "trace": "tr1"}
    da = _mk_dump("decode0", "killed", 50.0, [
        shared,
        {"kind": "event", "event": "admitted", "uid": "a", "t_ms": 2.0,
         "host": "decode0", "trace": "tr1"},
        {"kind": "event", "event": "decode_chunk", "uid": "a",
         "t_ms": 10.0, "start_ms": 2.0, "n_tokens": 8,
         "host": "decode0", "trace": "tr1"},
        {"kind": "event", "event": "migrate_start", "uid": "a",
         "t_ms": 11.0, "host": "decode0", "trace": "tr1"},
    ])
    db = _mk_dump("decode1", "manual", 60.0, [
        shared,                                   # duplicated record
        {"kind": "event", "event": "migrate_end", "uid": "a",
         "t_ms": 12.0, "host": "decode1", "trace": "tr1"},
        {"kind": "event", "event": "retired", "uid": "a", "t_ms": 20.0,
         "n_tokens": 9, "host": "decode1", "trace": "tr1"},
        {"step": 3, "phase": "decode", "t_ms": 19.0, "host": "decode1"},
    ])
    merged = merge_dumps([da, db])
    subs = [r for r in merged if r.get("event") == "submitted"]
    assert len(subs) == 1                         # deduplicated
    ts = [r.get("t_ms") for r in merged]
    assert ts == sorted(ts)                       # one ordered timeline
    # window: last 10 "seconds" (ms-scaled clock in this synthetic log)
    win = merge_dumps([da, db], last_s=0.0105)
    assert all(r["t_ms"] >= 20.0 - 10.5 for r in win)
    rec = rebuild([da, db])
    assert rec["n_dumps"] == 2
    assert rec["workers"] == ["decode0", "decode1"]
    assert rec["n_traces"] == 1
    assert rec["trace_stitch_failures"] == 0
    assert rec["n_retired"] == 1
    json.dumps(rec)


def test_postmortem_cli_runnable(tmp_path):
    d = str(tmp_path / "dumps")
    fr0 = FlightRecorder(capacity=16, worker="cluster",
                         clock=lambda: 30.0)
    fr0.record({"kind": "event", "event": "submitted", "uid": "a",
                "t_ms": 1.0, "trace": "tr1"})
    fr0.record({"kind": "event", "event": "alert_fire", "t_ms": 5.0,
                "rule": "scale_up", "severity": "warn"})
    fr1 = FlightRecorder(capacity=16, worker="decode0",
                         clock=lambda: 30.0)
    fr1.record({"kind": "event", "event": "retired", "uid": "a",
                "t_ms": 9.0, "n_tokens": 3, "host": "decode0",
                "trace": "tr1"})
    fr0.dump(d, reason="killed")
    fr1.dump(d, reason="killed")
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.monitor.postmortem", d,
         "--trace", str(tmp_path / "pm_trace.json")],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "postmortem"
    assert rec["n_dumps"] == 2 and rec["n_traces"] == 1
    assert rec["alerts_fired"][0]["rule"] == "scale_up"
    with open(tmp_path / "pm_trace.json") as f:
        json.load(f)                              # valid trace JSON
    # empty dir exits 1
    out2 = subprocess.run(
        [sys.executable, "-m", "apex_tpu.monitor.postmortem",
         str(tmp_path / "empty")],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out2.returncode == 1


# ---------------------------------------------------------------------------
# Distributed tracing: per-trace reconstruction fixes (satellite 1)


def _migrated_two_log_records():
    """A request whose lifecycle spans two workers' logs: log A holds
    the pre-kill half, log B the post-migration half; both captured the
    cluster-global submitted/transfer records (the merge duplicates)."""
    shared = [
        {"kind": "event", "event": "submitted", "uid": "a", "t_ms": 0.0,
         "trace": "tr1"},
        {"kind": "event", "event": "transfer_start", "uid": "a",
         "t_ms": 3.0, "host": "prefill0", "trace": "tr1"},
        {"kind": "event", "event": "transfer_end", "uid": "a",
         "t_ms": 4.0, "host": "prefill0", "trace": "tr1"},
    ]
    log_a = shared + [
        {"kind": "event", "event": "prefill_start", "uid": "a",
         "t_ms": 1.0, "host": "prefill0", "trace": "tr1"},
        {"kind": "event", "event": "prefill_end", "uid": "a",
         "t_ms": 2.5, "host": "prefill0", "trace": "tr1"},
        {"kind": "event", "event": "first_token", "uid": "a",
         "t_ms": 2.5, "host": "prefill0", "trace": "tr1"},
        {"kind": "event", "event": "admitted", "uid": "a", "t_ms": 5.0,
         "slot": 0, "host": "decode0", "trace": "tr1"},
        {"kind": "event", "event": "decode_chunk", "uid": "a",
         "t_ms": 8.0, "start_ms": 5.0, "n_tokens": 4, "host": "decode0",
         "trace": "tr1"},
        {"kind": "event", "event": "migrate_start", "uid": "a",
         "t_ms": 9.0, "host": "decode0", "trace": "tr1"},
    ]
    log_b = shared + [
        {"kind": "event", "event": "migrate_end", "uid": "a",
         "t_ms": 10.0, "host": "decode1", "trace": "tr1"},
        {"kind": "event", "event": "replay", "uid": "a", "t_ms": 10.0,
         "n_tokens": 1, "host": "decode1", "trace": "tr1"},
        {"kind": "event", "event": "admitted", "uid": "a", "t_ms": 10.0,
         "slot": 1, "migrated": True, "host": "decode1", "trace": "tr1"},
        {"kind": "event", "event": "decode_chunk", "uid": "a",
         "t_ms": 14.0, "start_ms": 10.0, "n_tokens": 5,
         "host": "decode1", "trace": "tr1"},
        {"kind": "event", "event": "retired", "uid": "a", "t_ms": 14.0,
         "n_tokens": 9, "host": "decode1", "trace": "tr1"},
    ]
    return log_a, log_b


def test_view_reconstructs_migrated_request_per_trace():
    """THE satellite fix: merged two-log events of a migrated request
    must anchor queue/TTFT on the FIRST admitted/first_token (the
    client-observed ones), e2e on the LAST retired, and count the
    request once."""
    log_a, log_b = _migrated_two_log_records()
    rec = summarize(log_a + log_b)
    assert rec["n_requests"] == 1
    assert rec["n_retired"] == 1                 # not double-counted
    assert rec["queue_ms_p50"] == 5.0            # FIRST admitted (5.0)
    assert rec["ttft_ms_p50"] == 2.5             # first_token - submitted
    assert rec["e2e_ms_p50"] == 14.0             # last retired
    # tpot over the true stream: (14 - 2.5) / (9 - 1)
    assert rec["tpot_ms_p50"] == pytest.approx(11.5 / 8, abs=1e-3)
    assert rec["n_migrations"] == 1 and rec["n_replays"] == 1
    # order independence: B-then-A reads identically
    rec2 = summarize(log_b + log_a)
    for k in ("queue_ms_p50", "ttft_ms_p50", "e2e_ms_p50"):
        assert rec2[k] == rec[k]


def test_request_spans_dedupe_across_merged_logs():
    log_a, log_b = _migrated_two_log_records()
    spans = request_spans(log_a + log_b)["a"]
    chunks = [s for s in spans if s["name"] == "decode_chunk"]
    assert len(chunks) == 2                      # one per REAL chunk
    names = {s["name"] for s in spans}
    assert {"queued", "prefill", "transfer", "migrate", "decode"} <= names
    queued = [s for s in spans if s["name"] == "queued"][0]
    assert queued["t1_ms"] == 5.0                # first admitted
    assert all(s.get("trace") == "tr1" for s in spans
               if s["name"] != "decode_chunk" or "trace" in s)


def test_stitch_traces_cross_host_structure():
    log_a, log_b = _migrated_two_log_records()
    st = stitch_traces(log_a + log_b)
    assert st["stitch_failures"] == 0
    tr = st["traces"]["tr1"]
    assert tr["hosts"] == ["prefill0", "decode0", "decode1"]
    assert tr["ordered"] and tr["terminal"] == "retired"
    # losing the migrate_end half (an unstitched log) is a failure
    broken = [r for r in log_a + log_b if r["event"] != "migrate_end"]
    st2 = stitch_traces(broken)
    assert st2["stitch_failures"] == 1
    assert st2["traces"]["tr1"]["unmatched_pairs"] == {"migrate": 1}
    # a transfer RETRY (attempt 2 start, one end) is NOT a failure
    retry = log_a + log_b + [
        {"kind": "event", "event": "transfer_start", "uid": "a",
         "t_ms": 3.5, "attempt": 2, "host": "prefill0", "trace": "tr1"}]
    assert stitch_traces(retry)["stitch_failures"] == 0


def test_chrome_trace_host_tracks_one_trace_id():
    log_a, log_b = _migrated_two_log_records()
    trace = chrome_trace(log_a + log_b)
    json.dumps(trace)
    assert trace["stitch"]["stitch_failures"] == 0
    # one process per host, each holding a span named by THE trace id
    host_names = {e["args"]["name"]: e["pid"]
                  for e in trace["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "process_name"
                  and e["args"]["name"].startswith("host ")}
    assert set(host_names) == {"host prefill0", "host decode0",
                               "host decode1"}
    host_spans = [e for e in trace["traceEvents"] if e["ph"] == "X"
                  and e["pid"] in host_names.values()]
    assert {e["name"] for e in host_spans} == {"tr1"}
    assert len(host_spans) == 3                  # one segment per host
    # causally ordered on the one clock
    host_spans.sort(key=lambda e: e["ts"])
    for a, b in zip(host_spans, host_spans[1:]):
        assert a["ts"] + a["dur"] <= b["ts"] + 1e-6
    # request/slot tracks unchanged by the host tier
    req_names = {e["name"] for e in trace["traceEvents"]
                 if e["ph"] == "X" and e["pid"] == 1}
    assert {"queued", "prefill", "transfer", "migrate", "decode"} \
        <= req_names


def test_eventlog_bind_defaults_and_unbind():
    log = EventLog(keep=True)
    log.bind("a", trace="tr9", tenant="t0")
    log.bind("a", host="d0")                     # binds accumulate
    log.emit("decode_chunk", "a", t_ms=1.0, start_ms=0.0, n_tokens=2)
    log.emit("retired", "a", t_ms=2.0, host="d1")  # explicit wins
    assert log.records[0]["trace"] == "tr9"
    assert log.records[0]["host"] == "d0"
    assert log.records[1]["host"] == "d1"
    assert log.records[1]["tenant"] == "t0"
    log.unbind("a")
    log.emit("shed", "a", t_ms=3.0)
    assert "trace" not in log.records[2]
    # taps observe every record in order
    seen = []
    log.tap(seen.append)
    log.emit("submitted", "b", t_ms=4.0)
    log.gauge("queue_depth", 2, t_ms=4.0)
    assert [r.get("event", r.get("gauge")) for r in seen] == \
        ["submitted", "queue_depth"]


# ---------------------------------------------------------------------------
# JsonlSink.write_many: lock-scoped batches under concurrent rotation


def test_write_many_batches_stay_whole_under_rotation(tmp_path):
    """The satellite gate: a flight-ring dump written concurrently with
    a rotating step-record writer must land every record whole and
    every batch contiguous — no record ever splits across a segment
    boundary, no batch interleaves with the other writer."""
    path = str(tmp_path / "rot.jsonl")
    sink = JsonlSink(path, buffer_steps=1, rotate_bytes=600)
    n_batches, batch_sz, n_steps = 40, 8, 300
    errs = []

    def dumper():
        try:
            for b in range(n_batches):
                sink.write_many([
                    {"kind": "flight", "batch": b, "i": i,
                     "pad": "x" * 40} for i in range(batch_sz)])
        except Exception as e:  # pragma: no cover
            errs.append(e)

    th = threading.Thread(target=dumper)
    th.start()
    for s in range(n_steps):
        sink.write(step=s, phase="decode", pad="y" * 30)
    th.join()
    sink.close()
    assert not errs
    # every line in every segment parses (no torn/interleaved records)
    recs = list(read_jsonl(path, strict=True))
    steps = [r for r in recs if "step" in r]
    flights = [r for r in recs if r.get("kind") == "flight"]
    assert len(steps) == n_steps
    assert len(flights) == n_batches * batch_sz
    # batches are contiguous in the stream: once a batch starts, its
    # batch_sz records follow back-to-back
    i = 0
    while i < len(flights):
        b = flights[i]["batch"]
        chunk = flights[i:i + batch_sz]
        assert [r["batch"] for r in chunk] == [b] * batch_sz
        assert [r["i"] for r in chunk] == list(range(batch_sz))
        i += batch_sz
    # and contiguous means adjacent in the FULL stream too
    stream = [(r.get("batch"), r.get("i")) for r in recs
              if r.get("kind") == "flight" or "step" in r]
    flight_pos = [j for j, r in enumerate(recs)
                  if r.get("kind") == "flight"]
    for a, b in zip(flight_pos, flight_pos[1:]):
        if recs[a]["batch"] == recs[b]["batch"]:
            assert b == a + 1, "batch interleaved with other writers"
    assert stream  # rotation actually happened and everything is whole
    assert os.path.exists(path + ".1")


# ---------------------------------------------------------------------------
# regress polarity: the fleet fields (satellite 3)


def test_regress_polarity_covers_fleet_fields():
    for k in ("alerts_fired_total", "scrape_ms_p50", "scrape_ms_p99",
              "trace_stitch_failures", "fleet.alerts_fired_total",
              "series_dropped_total", "scrape_misses_total",
              "dropped_records"):
        assert classify_metric(k) == "lower", k
    for k in ("scrape_coverage", "fleet_goodput_rps",
              "fleet.scrape_coverage"):
        assert classify_metric(k) == "higher", k


def test_regress_gates_fleet_records():
    base = {"fleet_goodput_rps": 10.0, "scrape_coverage": 1.0,
            "alerts_fired_total": 2, "scrape_ms_p50": 0.5,
            "trace_stitch_failures": 0}
    worse = dict(base, scrape_coverage=0.5, trace_stitch_failures=3)
    rep = compare_records(base, worse, tol=0.15)
    assert not rep["ok"]
    keys = {e["key"] for e in rep["regressions"]}
    assert {"scrape_coverage", "trace_stitch_failures"} <= keys
    assert compare_records(base, dict(base), tol=0.15)["ok"]
    # a stitch failure appearing from zero must flag at any tolerance
    assert not compare_records({"trace_stitch_failures": 0},
                               {"trace_stitch_failures": 1},
                               tol=0.5)["ok"]

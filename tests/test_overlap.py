"""Decomposed collective matmuls (comm.overlap) + overlap-scheduled DDP.

Gates: (1) numeric parity — each ring op must match its monolithic
collective exactly (all-gather side) or to fp-reorder tolerance (reduce
side), values AND grads, and the flagship GPT must be invariant to
``overlap_comm`` under plain TP and Megatron-SP; (2) wire-byte neutrality —
``comm.accounting`` must price the compiled decomposed program to exactly
the bytes the ``comm.overlap`` models predict, which equal the monolithic
program's; (3) the DDP ``accumulate_and_average`` restructure must be
loss-curve-identical to the barriered scan+reduce path, int8+EF included.
The HLO overlap *proof* (async pairs / independence) lives in
``test_collective_counts.py::assert_overlapped``.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")
pytestmark = pytest.mark.skipif(
    not MESH_OK,
    reason="mesh programs need jax.shard_map/lax.axis_size (graft jax)")

if MESH_OK:
    from apex_tpu.comm import (
        CompressionConfig,
        all_gather_matmul,
        collective_report,
        matmul_all_reduce,
        matmul_reduce_scatter,
    )
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.parallel.mesh import build_mesh

B, S, H, N = 2, 64, 32, 48


def _mesh_tp8():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return build_mesh(tp=8, pp=1, sp=1)


def _data(key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    x = jax.random.normal(ks[0], (B, S, H), jnp.float32)
    w = jax.random.normal(ks[1], (H, N), jnp.float32)
    cot = jax.random.normal(ks[2], (B, S, N), jnp.float32)
    return x, w, cot


# ---------------------------------------------------------------------------
# op-level parity (values and grads) vs the monolithic collectives


@pytest.mark.parametrize("bidirectional", [False, True])
def test_all_gather_matmul_matches_monolithic(bidirectional):
    mesh = _mesh_tp8()
    x, w, cot = _data()

    def decomposed(x, w):
        return all_gather_matmul(x, w, gather_axis=1,
                                 bidirectional=bidirectional)

    def monolithic(x, w):
        return jnp.dot(lax.all_gather(x, "tp", axis=1, tiled=True), w)

    def run_loss(body):
        def loss(x, w):
            y = jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, "tp", None), P(None, "tp")),
                out_specs=P(None, None, "tp"))(x, w)
            return jnp.sum(y * cot), y

        return jax.jit(jax.value_and_grad(loss, argnums=(0, 1),
                                          has_aux=True))(x, w)

    ((_, y0), (dx0, dw0)) = run_loss(monolithic)
    ((_, y1), (dx1, dw1)) = run_loss(decomposed)
    # the gathered dim is non-contracting: the decomposition reorders no
    # reduction — forward is EXACT
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y0))
    # dX rides a ring reduce-scatter (fp reorder), dW an fp32-accumulated
    # ring — both within reorder tolerance of the monolithic transposes
    np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw0),
                               rtol=1e-4, atol=1e-5)


def test_matmul_reduce_scatter_matches_monolithic():
    mesh = _mesh_tp8()
    x, w, cot = _data(1)

    def decomposed(x, w):
        return matmul_reduce_scatter(x, w, scatter_axis=1)

    def monolithic(x, w):
        return lax.psum_scatter(jnp.dot(x, w), "tp", scatter_dimension=1,
                                tiled=True)

    def run_loss(body):
        def loss(x, w):
            y = jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "tp"), P("tp", None)),
                out_specs=P(None, "tp", None))(x, w)
            return jnp.sum(y * cot), y

        return jax.jit(jax.value_and_grad(loss, argnums=(0, 1),
                                          has_aux=True))(x, w)

    ((_, y0), (dx0, dw0)) = run_loss(monolithic)
    ((_, y1), (dx1, dw1)) = run_loss(decomposed)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw0),
                               rtol=1e-4, atol=1e-5)


def test_matmul_all_reduce_matches_monolithic():
    """Plain row-parallel exit: per-rank losses computed redundantly (the
    Megatron pattern) and pmean'd — the decomposed op's psum-of-partials
    backward must reproduce the monolithic psum program exactly."""
    mesh = _mesh_tp8()
    x, w, cot = _data(2)

    def run_loss(overlap):
        def body(x, w, c):
            if overlap:
                y = matmul_all_reduce(x, w, scatter_axis=1)
            else:
                y = lax.psum(jnp.dot(x, w), "tp")
            return lax.pmean(jnp.sum(y * c), "tp")

        def loss(x, w):
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "tp"), P("tp", None), P()),
                out_specs=P())(x, w, cot)

        return jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(x, w)

    l0, (dx0, dw0) = run_loss(False)
    l1, (dx1, dw1) = run_loss(True)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw0),
                               rtol=1e-4, atol=1e-5)


def test_matmul_reduce_scatter_validates_divisibility():
    mesh = _mesh_tp8()
    x = jnp.zeros((B, 60, H))  # 60 % 8 != 0
    w = jnp.zeros((H, N))
    with pytest.raises(ValueError, match="divisible"):
        jax.shard_map(
            lambda a, b: matmul_reduce_scatter(a, b, scatter_axis=1),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False)(x, w)


# ---------------------------------------------------------------------------
# wire-byte neutrality: accounting on the compiled decomposed program must
# equal the overlap byte models AND the monolithic program's bytes


def test_decomposed_wire_bytes_agree_with_accounting():
    from apex_tpu.comm import (
        all_gather_matmul_wire_bytes,
        matmul_all_reduce_wire_bytes,
        matmul_reduce_scatter_wire_bytes,
    )

    mesh = _mesh_tp8()
    w_axis = 8
    x, w, _ = _data(3)

    def compile_(body, in_specs, out_specs, *args):
        return jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)).lower(*args).compile()

    # all_gather_matmul: (W-1) hops of the INPUT shard
    ag = compile_(lambda a, b: all_gather_matmul(a, b, gather_axis=1),
                  (P(None, "tp", None), P(None, "tp")),
                  P(None, None, "tp"), x, w)
    model = all_gather_matmul_wire_bytes(B * (S // w_axis) * H, 4, w_axis)
    got = collective_report(ag)
    assert got.wire_bytes == pytest.approx(model), (got, model)
    # ... which equals the monolithic program's bytes on the same mesh
    mono = compile_(
        lambda a, b: jnp.dot(lax.all_gather(a, "tp", axis=1, tiled=True), b),
        (P(None, "tp", None), P(None, "tp")), P(None, None, "tp"), x, w)
    assert got.wire_bytes == pytest.approx(
        collective_report(mono).wire_bytes)

    # matmul_reduce_scatter: (W-1) hops of the OUTPUT shard
    rs = compile_(lambda a, b: matmul_reduce_scatter(a, b, scatter_axis=1),
                  (P(None, None, "tp"), P("tp", None)),
                  P(None, "tp", None), x, w)
    model = matmul_reduce_scatter_wire_bytes(B * (S // w_axis) * N, 4,
                                             w_axis)
    got = collective_report(rs)
    assert got.wire_bytes == pytest.approx(model), (got, model)
    mono = compile_(
        lambda a, b: lax.psum_scatter(jnp.dot(a, b), "tp",
                                      scatter_dimension=1, tiled=True),
        (P(None, None, "tp"), P("tp", None)), P(None, "tp", None), x, w)
    assert got.wire_bytes == pytest.approx(
        collective_report(mono).wire_bytes)

    # matmul_all_reduce: reduce ring + broadcast ring = the allreduce cost
    ar = compile_(lambda a, b: matmul_all_reduce(a, b, scatter_axis=1),
                  (P(None, None, "tp"), P("tp", None)), P(None, None, None),
                  x, w)
    model = matmul_all_reduce_wire_bytes(B * (S // w_axis) * N, 4, w_axis)
    got = collective_report(ar)
    assert got.wire_bytes == pytest.approx(model), (got, model)
    mono = compile_(
        lambda a, b: lax.psum(jnp.dot(a, b), "tp"),
        (P(None, None, "tp"), P("tp", None)), P(None, None, None), x, w)
    assert got.wire_bytes == pytest.approx(
        collective_report(mono).wire_bytes)


# ---------------------------------------------------------------------------
# flagship GPT: overlap_comm must be numerics-invariant (plain TP + SP)


def _gpt_loss_and_grads(cfg, tp):
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    mesh = build_mesh(tp=tp, pp=1, sp=1)
    specs = gpt_param_specs(cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, cfg.max_seq), 0,
                             cfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)

    def loss_fn(p):
        def body(p, tok, tgt):
            return replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(body, mesh=mesh,
                             in_specs=(specs, P(None, "sp"), P(None, "sp")),
                             out_specs=P())(p, tok, tgt)

    return jax.jit(jax.value_and_grad(loss_fn))(params)


@pytest.mark.parametrize("megatron_sp", [False, True])
def test_gpt_overlap_comm_parity(megatron_sp):
    from apex_tpu.transformer.testing import GPTConfig

    cfg = GPTConfig(vocab_size=96, max_seq=32, hidden=64, num_layers=2,
                    num_heads=4, dtype=jnp.float32,
                    megatron_sp=megatron_sp)
    l0, g0 = _gpt_loss_and_grads(cfg, tp=2)
    l1, g1 = _gpt_loss_and_grads(
        dataclasses.replace(cfg, overlap_comm=True), tp=2)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-5), g1, g0)


def test_gpt_overlap_comm_validates_divisibility():
    from apex_tpu.transformer.testing import GPTConfig

    cfg = GPTConfig(vocab_size=96, max_seq=30, hidden=64, num_layers=2,
                    num_heads=4, overlap_comm=True)
    with pytest.raises(ValueError, match="divisible"):
        cfg.validate(tp=4)
    # the rings shard the SP-LOCAL sequence: tp=8 alone divides 16, but
    # composed with ring-sp=4 the local shard is 4 rows — config-time
    # error, not a trace-time failure deep inside the ring
    cfg16 = dataclasses.replace(cfg, max_seq=16, num_heads=8, hidden=64)
    cfg16.validate(tp=8)
    with pytest.raises(ValueError, match="sp-local"):
        cfg16.validate(tp=8, sp=4)


# ---------------------------------------------------------------------------
# DDP: the interleaved accumulate-and-reduce restructure must be
# loss-curve-identical to the barriered scan + average_gradients path


def _ddp_gpt_curve(overlapped: bool, compression, steps=8, microbatches=2):
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        init_gpt_params,
    )

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    cfg = GPTConfig(vocab_size=128, max_seq=32, hidden=64, num_layers=2,
                    num_heads=2, dtype=jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    m = microbatches
    # (M, global_batch, seq): scan dim leads, dp shards the batch dim
    tok = jax.random.randint(jax.random.PRNGKey(1), (m, 16, 32), 0, 128)
    opt = FusedAdam(lr=2e-3)
    opt_state = opt.init(params)
    ddp = DistributedDataParallel(compression=compression)
    specs = jax.tree.map(lambda _: P(), params)
    ospecs = jax.tree.map(lambda _: P(), opt_state)
    ef_state = ddp.init_comm_state(params)

    def vg(p, mb):
        return jax.value_and_grad(
            lambda p: gpt_loss(p, mb, mb, cfg))(ddp.replicate(p))

    def finish(p, s, l, g):
        updates, s = opt.update(g, s, p)
        return (jax.tree.map(lambda p, u: p + u, p, updates), s,
                lax.pmean(l, "dp"))

    def barriered_body(p, s, t, r=None):
        zeros = jax.tree.map(jnp.zeros_like, p)

        def sbody(acc, mb):
            ls, ga = acc
            l, g = vg(p, mb)
            return (ls + l, jax.tree.map(jnp.add, ga, g)), None

        (ls, ga), _ = lax.scan(sbody, (jnp.zeros(()), zeros), t)
        if r is None:
            g = ddp.average_gradients(ga)
            return finish(p, s, ls / m, g)
        g, r = ddp.average_gradients(ga, comm_state=r)
        return (*finish(p, s, ls / m, g), r)

    def overlapped_body(p, s, t, r=None):
        if r is None:
            l, g = ddp.accumulate_and_average(vg, p, t)
            return finish(p, s, l, g)
        l, g, r = ddp.accumulate_and_average(vg, p, t, comm_state=r)
        return (*finish(p, s, l, g), r)

    body = overlapped_body if overlapped else barriered_body
    if ef_state is None:
        step = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(specs, ospecs, P(None, "dp")),
            out_specs=(specs, ospecs, P()), check_vma=False))
        losses = []
        for _ in range(steps):
            params, opt_state, l = step(params, opt_state, tok)
            losses.append(float(l))
        return losses

    def body_ef(p, s, r, t):
        r = jax.tree.map(lambda x: x[0], r)
        out = body(p, s, t, r)
        p, s, l, r = out
        return p, s, jax.tree.map(lambda x: x[None], r), l

    rspecs = jax.tree.map(lambda _: P("dp"), params)
    step = jax.jit(jax.shard_map(
        body_ef, mesh=mesh,
        in_specs=(specs, ospecs, rspecs, P(None, "dp")),
        out_specs=(specs, ospecs, rspecs, P()), check_vma=False))
    residual = jax.tree.map(
        lambda p: jnp.zeros((8,) + jnp.shape(p), jnp.float32), params)
    losses = []
    for _ in range(steps):
        params, opt_state, residual, l = step(params, opt_state, residual,
                                              tok)
        losses.append(float(l))
    return losses


def test_ddp_overlapped_reduction_loss_curve_identical():
    base = _ddp_gpt_curve(False, None)
    over = _ddp_gpt_curve(True, None)
    # training progresses and the restructure changes only the schedule:
    # scan(M-1)+peeled-last associates the grad sum exactly like the full
    # scan, so the curves are identical (same math, different emission)
    assert base[-1] < base[0] - 0.3, base
    np.testing.assert_allclose(over, base, rtol=0, atol=1e-6)


def test_ddp_overlapped_reduction_int8_ef_identical():
    cfg = CompressionConfig(policy="int8_ef", block_size=128,
                            min_elements=128)
    base = _ddp_gpt_curve(False, cfg)
    over = _ddp_gpt_curve(True, cfg)
    np.testing.assert_allclose(over, base, rtol=0, atol=1e-6)


def test_ddp_metrics_bucket_labels_stable():
    """Reverse-order emission must not renumber the per-bucket metric
    labels: comm_bucket{i}_bytes stays keyed by tree-order bucket index."""
    from apex_tpu.comm.collectives import allreduce_wire_bytes
    from apex_tpu.monitor import Metrics

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)
    grads = {"a": jnp.ones((3000,)), "b": jnp.ones((5000,)),
             "c": jnp.ones((100,))}
    ddp = DistributedDataParallel(message_size=4000)

    out, metrics = jax.jit(jax.shard_map(
        lambda g: ddp.average_gradients(g, metrics=Metrics()),
        mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))(grads)
    got = metrics.as_dict()
    # buckets in tree order: [a+b (8000, crosses message_size)], [c (100)]
    assert got["comm_bucket0_bytes"] == pytest.approx(
        allreduce_wire_bytes(8000, 4, 8))
    assert got["comm_bucket1_bytes"] == pytest.approx(
        allreduce_wire_bytes(100, 4, 8))
    jax.tree.map(lambda o, g: np.testing.assert_allclose(o, g, rtol=1e-6),
                 out, grads)
"""Segment-aware flash attention: kernel (interpret) vs dense reference,
packed fmha routing, pad-row zeroing, block-skip equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.fmha import cu_seqlens_to_segment_ids, fmha_packed
from apex_tpu.ops.attention_varlen import (
    _varlen,
    attention_varlen_reference,
    flash_attention_varlen,
)


def _packed_segs(key, b, s, max_len):
    """Random contiguous segments with a pad tail per batch row."""
    segs = []
    for i in range(b):
        kk = jax.random.fold_in(key, i)
        lens = []
        used = 0
        j = 0
        while used < s - 4:
            n = int(jax.random.randint(jax.random.fold_in(kk, j), (), 2,
                                       max_len))
            n = min(n, s - 4 - used)
            lens.append(n)
            used += n
            j += 1
        row = sum(([i] * n for i, n in enumerate(lens)), []) + [-1] * (s - used)
        segs.append(row)
    return jnp.asarray(segs, jnp.int32)


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_kernel_matches_reference(causal):
    b, h, s, d = 2, 3, 64, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    seg = _packed_segs(ks[3], b, s, 20)

    def fused(q, k, v):
        o = _varlen(q, k, v, seg, seg, d ** -0.5, causal, 16, 16, True)
        return jnp.sum(jnp.sin(o)), o

    def dense(q, k, v):
        o = attention_varlen_reference(q, k, v, seg, causal=causal)
        return jnp.sum(jnp.sin(o)), o

    (lf, of), gf = jax.value_and_grad(fused, argnums=(0, 1, 2),
                                      has_aux=True)(q, k, v)
    (ld, od), gd = jax.value_and_grad(dense, argnums=(0, 1, 2),
                                      has_aux=True)(q, k, v)
    np.testing.assert_allclose(of, od, atol=2e-5)
    for a, e, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), atol=2e-4,
                                   err_msg=name)


def test_pad_rows_zero_and_isolated():
    """Pad queries output exactly 0; pad keys receive zero gradient."""
    b, h, s, d = 1, 2, 32, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    seg = jnp.asarray([[0] * 10 + [1] * 12 + [-1] * 10], jnp.int32)

    o = _varlen(q, k, v, seg, seg, d ** -0.5, False, 8, 8, True)
    np.testing.assert_array_equal(np.asarray(o[:, :, 22:]), 0.0)

    def loss(k, v):
        # loss reads only real rows; pad k/v must get zero grad
        return jnp.sum(_varlen(q, k, v, seg, seg, d ** -0.5, False,
                               8, 8, True)[:, :, :22] ** 2)

    dk, dv = jax.grad(loss, argnums=(0, 1))(k, v)
    np.testing.assert_array_equal(np.asarray(dk[:, :, 22:]), 0.0)
    np.testing.assert_array_equal(np.asarray(dv[:, :, 22:]), 0.0)


def test_fmha_packed_matches_reference_and_zero_pads():
    total, h, d = 48, 2, 16
    key = jax.random.PRNGKey(2)
    qkv = jax.random.normal(key, (total, 3, h, d))
    cu = jnp.asarray([0, 12, 30, 40], jnp.int32)  # 8 pad tokens
    out = fmha_packed(qkv, cu)
    # reference: dense per-sequence softmax
    seg = cu_seqlens_to_segment_ids(cu, total)
    q, k, v = (qkv[:, i].transpose(1, 0, 2)[None] for i in range(3))
    ref = attention_varlen_reference(q, k, v, seg[None])[0].transpose(1, 0, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    np.testing.assert_array_equal(np.asarray(out[40:]), 0.0)


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_misaligned_seq_pads_into_kernel(causal):
    """Seqs with no Mosaic-legal block (s=130: not even 8-aligned) used to
    drop silently to the dense reference; the dispatcher now pads to the
    next 128-multiple with seg=-1 and slices back — use_pallas=True must
    take the kernel, and numerics must match the unpadded reference."""
    b, h, s, d = 1, 2, 130, 8
    ks = jax.random.split(jax.random.PRNGKey(4), 4)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    seg = jnp.concatenate([jnp.zeros((1, 70), jnp.int32),
                           jnp.ones((1, 50), jnp.int32),
                           jnp.full((1, 10), -1, jnp.int32)], axis=1)

    def fused(q, k, v):
        o = flash_attention_varlen(q, k, v, seg, causal=causal,
                                   use_pallas=True, interpret=True)
        return jnp.sum(jnp.sin(o)), o

    def dense(q, k, v):
        o = attention_varlen_reference(q, k, v, seg, causal=causal)
        return jnp.sum(jnp.sin(o)), o

    (_, of), gf = jax.value_and_grad(fused, argnums=(0, 1, 2),
                                     has_aux=True)(q, k, v)
    (_, od), gd = jax.value_and_grad(dense, argnums=(0, 1, 2),
                                     has_aux=True)(q, k, v)
    assert of.shape == (b, h, s, d)
    np.testing.assert_allclose(of, od, atol=2e-5)
    for a, e, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), atol=2e-4,
                                   err_msg=name)


def test_varlen_long_sequence_beyond_reference_limit():
    """The reference kernels cap at seqlen 512; ours must not."""
    b, h, s, d = 1, 1, 1024, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    seg = jnp.concatenate([jnp.zeros((1, 600), jnp.int32),
                           jnp.ones((1, 400), jnp.int32),
                           jnp.full((1, 24), -1, jnp.int32)], axis=1)
    o = _varlen(q, k, v, seg, seg, d ** -0.5, False, 128, 128, True)
    ref = attention_varlen_reference(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)

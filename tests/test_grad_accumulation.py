"""fp32 main-grad accumulation (ref fused_weight_gradient_dense +
LinearWithGradAccumulationAndAsyncAllreduce's gradient_accumulation_fusion):
microbatched bf16 training must accumulate weight grads in fp32."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.optimizers import (
    FusedAdam,
    accumulate_gradients,
    accumulate_into_main_grads,
    init_main_grads,
)


def _loss(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - y).astype(jnp.float32) ** 2)


def _data(key, n=64, din=16, dh=32):
    kx, ky, k1, k2 = jax.random.split(key, 4)
    params = {
        "w1": (jax.random.normal(k1, (din, dh)) * 0.3).astype(jnp.bfloat16),
        "w2": (jax.random.normal(k2, (dh, 1)) * 0.3).astype(jnp.bfloat16),
    }
    x = jax.random.normal(kx, (n, din)).astype(jnp.bfloat16)
    y = jax.random.normal(ky, (n, 1)).astype(jnp.bfloat16)
    return params, x, y


def test_main_grads_are_fp32_and_match_full_batch():
    params, x, y = _data(jax.random.PRNGKey(0))
    n_micro = 8
    mb = (x.reshape(n_micro, -1, x.shape[-1]), y.reshape(n_micro, -1, 1))

    loss, main = jax.jit(
        lambda p, mb: accumulate_gradients(_loss, p, mb))(params, mb)

    assert all(g.dtype == jnp.float32 for g in jax.tree.leaves(main))

    # reference: fp32 grad of the mean-over-microbatches loss
    def full(p):
        losses = jax.vmap(lambda xx, yy: _loss(p, (xx, yy)))(*mb)
        return jnp.mean(losses)

    ref_loss, ref_grads = jax.value_and_grad(full)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-3)
    for a, b in zip(jax.tree.leaves(main), jax.tree.leaves(ref_grads)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b, np.float32), rtol=2e-2, atol=1e-3)


def test_fp32_accumulation_beats_bf16_accumulation():
    # accumulate many tiny identical grads: fp32 keeps them, bf16 loses bits
    g = {"w": jnp.full((4, 4), 1e-3, jnp.bfloat16)}
    main = init_main_grads(g)
    half = jnp.zeros((4, 4), jnp.bfloat16)
    for _ in range(1000):
        main = accumulate_into_main_grads(main, g)
        half = half + g["w"]
    exact = 1e-3 * 1000 * np.float32(jnp.full((), 1e-3, jnp.bfloat16) / 1e-3)
    fp32_err = abs(float(main["w"][0, 0]) - exact) / exact
    bf16_err = abs(float(half[0, 0]) - exact) / exact
    assert fp32_err < 1e-3
    assert bf16_err > 10 * fp32_err


def test_accumulated_grads_drive_optimizer_step():
    params, x, y = _data(jax.random.PRNGKey(1))
    mb = (x.reshape(4, -1, x.shape[-1]), y.reshape(4, -1, 1))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)

    @jax.jit
    def step(p, s, mb):
        loss, grads = accumulate_gradients(_loss, p, mb)
        updates, s = opt.update(grads, s, p)
        p = jax.tree.map(lambda a, u: (a + u).astype(a.dtype), p, updates)
        return p, s, loss

    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, mb)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

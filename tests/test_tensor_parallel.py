"""Tensor-parallel tests on the 8-device virtual mesh.

Ref test strategy: ``tests/L0/run_transformer/run_mappings_test.py``,
``run_layers_test.py``, ``run_cross_entropy_test.py``, ``run_random_test.py``
— each TP construct is checked against the unsharded single-device reference
computation (fwd AND grad).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel as tp


@pytest.fixture
def mesh_tp2():
    return parallel_state.initialize_model_parallel(tensor_model_parallel_size_=2)


@pytest.fixture
def mesh_tp8():
    return parallel_state.initialize_model_parallel(tensor_model_parallel_size_=8)


# ---------------------------------------------------------------------------
# mappings


def test_scatter_gather_roundtrip(mesh_tp2):
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))

    def body(x):
        return tp.gather_from_tensor_model_parallel_region(
            tp.scatter_to_tensor_model_parallel_region(x)
        )

    f = shard_map(body, mesh=mesh_tp2, in_specs=P(), out_specs=P(),
                  check_vma=False)
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x), atol=1e-6)


def test_reduce_sums_shards(mesh_tp2):
    def body(x):
        return tp.reduce_from_tensor_model_parallel_region(x)

    f = shard_map(body, mesh=mesh_tp2, in_specs=P(None, "tp"), out_specs=P(None, "tp"))
    x = jnp.ones((2, 4))
    # each tp shard (2,2) is summed over tp=2 → all entries 2 after gather
    np.testing.assert_allclose(np.asarray(f(x)), 2.0)


def test_copy_backward_is_psum(mesh_tp2):
    """copy fwd = identity; bwd = allreduce over tp (ref mappings.py:77-92).
    grad of sum(copy(x)) per rank contributions sum across tp ranks."""

    def loss(x):
        y = tp.copy_to_tensor_model_parallel_region(x)
        # per-rank different weighting so the psum is observable
        r = jax.lax.axis_index("tp").astype(jnp.float32)
        return jnp.sum(y * (r + 1.0)), None

    def body(x):
        g = jax.grad(lambda x: loss(x)[0])(x)
        return g

    f = shard_map(body, mesh=mesh_tp2, in_specs=P(), out_specs=P("tp"))
    g = np.asarray(f(jnp.ones((4,)))).reshape(2, 4)
    # each rank's grad = psum over ranks of (r+1) = 1+2 = 3
    np.testing.assert_allclose(g, 3.0)


# ---------------------------------------------------------------------------
# layers


def test_column_row_composition_matches_dense(mesh_tp2):
    """ColumnParallel(gather_output=False) → RowParallel(input_is_parallel)
    == the unsharded two-layer matmul, fwd and grads."""
    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (4, 6))
    w1 = jax.random.normal(jax.random.fold_in(k, 1), (6, 8))
    w2 = jax.random.normal(jax.random.fold_in(k, 2), (8, 6))
    b2 = jax.random.normal(jax.random.fold_in(k, 3), (6,))

    def ref_loss(x, w1, w2, b2):
        return jnp.sum((x @ w1) @ w2 + b2)

    def body(x, w1_shard, w2_shard, b2):
        def loss(w1_shard, w2_shard, b2):
            h = tp.column_parallel_linear(x, w1_shard, gather_output=False)
            y = tp.row_parallel_linear(h, w2_shard, b2, input_is_parallel=True)
            return jnp.sum(y)

        val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
            w1_shard, w2_shard, b2
        )
        return val, grads

    f = shard_map(
        body,
        mesh=mesh_tp2,
        in_specs=(P(), P(None, "tp"), P("tp", None), P()),
        out_specs=(P(), (P(None, "tp"), P("tp", None), P())),
    )
    val, (g1, g2, gb) = f(x, w1, w2, b2)
    want_val = ref_loss(x, w1, w2, b2)
    want_g1, want_g2, want_gb = jax.grad(ref_loss, argnums=(1, 2, 3))(x, w1, w2, b2)
    np.testing.assert_allclose(np.asarray(val), np.asarray(want_val), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(want_g1), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(want_g2), rtol=1e-4)
    # row-parallel bias is replicated; its grad must NOT be double-counted
    np.testing.assert_allclose(np.asarray(gb), np.asarray(want_gb), rtol=1e-4)


def test_vocab_parallel_embedding_matches_dense(mesh_tp2):
    V, H = 16, 4
    k = jax.random.PRNGKey(2)
    table = jax.random.normal(k, (V, H))
    ids = jnp.array([[0, 3, 7, 15], [8, 9, 1, 2]])

    def body(ids, shard):
        return tp.vocab_parallel_embedding(ids, shard)

    f = shard_map(body, mesh=mesh_tp2, in_specs=(P(), P("tp", None)), out_specs=P())
    got = f(ids, table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(table[ids]), atol=1e-6)


def test_vocab_parallel_embedding_grad(mesh_tp2):
    V, H = 8, 4
    k = jax.random.PRNGKey(3)
    table = jax.random.normal(k, (V, H))
    ids = jnp.array([1, 5, 5, 7])

    def body(ids, shard):
        def loss(shard):
            return jnp.sum(tp.vocab_parallel_embedding(ids, shard) ** 2)

        return jax.grad(loss)(shard)

    f = shard_map(body, mesh=mesh_tp2, in_specs=(P(), P("tp", None)),
                  out_specs=P("tp", None))
    got = f(ids, table)
    want = jax.grad(lambda t: jnp.sum(t[ids] ** 2))(table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_column_parallel_module_init_is_tp_invariant():
    """sharded_init: the kernel gathered across tp=2 equals the kernel a tp=1
    run initializes — checkpoints don't depend on the TP degree (ref
    _initialize_affine_weight_cpu master-weight semantics, layers.py:89-120).
    """
    layer = tp.ColumnParallelLinear(input_size=4, output_size=8, use_bias=False)
    x = jnp.ones((2, 4))

    def body(key, x):
        params = layer.init(key, x)
        y, _ = layer.apply(params, x)
        return params["params"]["kernel"], y

    mesh2 = parallel_state.initialize_model_parallel(tensor_model_parallel_size_=2)
    f2 = shard_map(body, mesh=mesh2, in_specs=(P(), P()),
                   out_specs=(P(None, "tp"), P()), check_vma=False)
    kernel2, y2 = f2(jax.random.PRNGKey(4), x)

    mesh1 = parallel_state.initialize_model_parallel(tensor_model_parallel_size_=1)
    f1 = shard_map(body, mesh=mesh1, in_specs=(P(), P()),
                   out_specs=(P(), P()), check_vma=False)
    kernel1, y1 = f1(jax.random.PRNGKey(4), x)

    np.testing.assert_allclose(np.asarray(kernel2), np.asarray(kernel1),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y1), rtol=1e-5)


# ---------------------------------------------------------------------------
# cross entropy


def test_vocab_parallel_cross_entropy_matches_dense(mesh_tp8):
    B, S, V = 2, 4, 32
    k = jax.random.PRNGKey(5)
    logits = jax.random.normal(k, (B, S, V)) * 3.0
    target = jax.random.randint(jax.random.fold_in(k, 1), (B, S), 0, V)

    def body(shard, target):
        return tp.vocab_parallel_cross_entropy(shard, target)

    f = shard_map(body, mesh=mesh_tp8,
                  in_specs=(P(None, None, "tp"), P()), out_specs=P())
    got = f(logits, target)

    lse = jax.nn.logsumexp(logits, axis=-1)
    want = lse - jnp.take_along_axis(logits, target[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_vocab_parallel_cross_entropy_grad(mesh_tp8):
    B, V = 4, 16
    k = jax.random.PRNGKey(6)
    logits = jax.random.normal(k, (B, V))
    target = jax.random.randint(jax.random.fold_in(k, 1), (B,), 0, V)

    def body(shard, target):
        def loss(shard):
            return jnp.mean(tp.vocab_parallel_cross_entropy(shard, target))

        return jax.grad(loss)(shard)

    f = shard_map(body, mesh=mesh_tp8, in_specs=(P(None, "tp"), P()),
                  out_specs=P(None, "tp"))
    got = f(logits, target)

    def ref_loss(logits):
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, target[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - picked)

    want = jax.grad(ref_loss)(logits)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


# ---------------------------------------------------------------------------
# random / checkpointing


def test_model_parallel_key_differs_per_rank(mesh_tp2):
    def body(key):
        k = tp.model_parallel_key(key)
        return jax.random.uniform(k, (1,))

    f = shard_map(body, mesh=mesh_tp2, in_specs=P(), out_specs=P("tp"))
    vals = np.asarray(f(jax.random.PRNGKey(7)))
    assert vals[0] != vals[1]  # different dropout draw per TP rank


def test_rng_tracker_named_streams():
    tr = tp.RngStatesTracker()
    tr.add("default", 123)
    with pytest.raises(RuntimeError):
        tr.add("default", 5)
    with pytest.raises(RuntimeError):
        tr.add("other", 123)  # duplicate seed
    k1 = tr.key("default")
    k2 = tr.key("default")
    assert not np.array_equal(
        jax.random.key_data(k1), jax.random.key_data(k2)
    )
    with pytest.raises(RuntimeError):
        tr.key("missing")


def test_rng_tracker_state_roundtrip_replays_keys():
    """get_states/set_states must snapshot stream counters so a restore
    replays the same subkeys (the CheckpointFunction recompute pattern,
    ref random.py:247-283)."""
    tr = tp.RngStatesTracker()
    tr.add("s", 1)
    tr.key("s")  # advance
    snap = tr.get_states()
    k1 = tr.key("s")
    tr.set_states(snap)
    k2 = tr.key("s")
    assert np.array_equal(jax.random.key_data(k1), jax.random.key_data(k2))


def test_checkpoint_matches_uncheckpointed():
    x = jax.random.normal(jax.random.PRNGKey(8), (4, 4))

    def f(x):
        return jnp.sum(jnp.tanh(x @ x.T) ** 2)

    for policy in ("nothing", "dots", "everything"):
        g_ckpt = jax.grad(lambda x: tp.checkpoint(f, x, policy=policy))(x)
        g_ref = jax.grad(f)(x)
        np.testing.assert_allclose(np.asarray(g_ckpt), np.asarray(g_ref),
                                   rtol=1e-5)


def test_checkpoint_dropout_replay_consistent():
    """Recompute must replay identical dropout — keys are explicit inputs."""
    key = jax.random.PRNGKey(9)
    x = jax.random.normal(key, (8, 8))

    def f(x, key):
        mask = jax.random.bernoulli(key, 0.5, x.shape)
        return jnp.sum(jnp.where(mask, x, 0.0) ** 2)

    g1 = jax.grad(lambda x: tp.checkpoint(f, x, key))(x)
    g2 = jax.grad(lambda x: f(x, key))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-6)


# ---------------------------------------------------------------------------
# utils / data / memory


def test_vocab_utility():
    assert tp.VocabUtility.vocab_range_from_global_vocab_size(16, 1, 4) == (4, 8)
    with pytest.raises(ValueError):
        tp.divide(10, 3)


def test_split_tensor_along_last_dim():
    x = jnp.arange(12).reshape(2, 6)
    parts = tp.split_tensor_along_last_dim(x, 3)
    assert len(parts) == 3 and parts[0].shape == (2, 2)


def test_broadcast_data_single_process():
    out = tp.broadcast_data(
        ["a"], {"a": jnp.array([[1, 2]], jnp.int32)}, jnp.int32
    )
    np.testing.assert_array_equal(np.asarray(out["a"]), [[1, 2]])
    with pytest.raises(TypeError):
        tp.broadcast_data(["a"], {"a": jnp.array([1.0])}, jnp.int32)


def test_memory_buffer():
    buf = tp.MemoryBuffer("act", 16, jnp.float32, track_usage=True)
    v = buf.get((2, 4))
    assert v.shape == (2, 4)
    with pytest.raises(RuntimeError):
        buf.get((3, 4))
    buf.reset()
    assert not buf.is_in_use()
    ring = tp.RingMemBuffer("r", 2, 8, jnp.float32)
    b1 = ring.get_next_buffer()
    b1.get((8,))
    b2 = ring.get_next_buffer()
    assert b2 is not b1


# ---------------------------------------------------------------------------
# Megatron-style sequence parallelism (Korthikanti SP; north-star addition —
# the reference snapshot has no LN/dropout sequence sharding)


def test_sequence_parallel_block_matches_tp(mesh_tp2):
    """An LN -> column-parallel(gelu) -> row-parallel block computed on
    sequence-sharded activations (all_gather in, reduce-scatter out) must
    equal the plain TP block on replicated activations — values AND grads."""
    from apex_tpu.ops.layer_norm import layer_norm

    b, s, h, f = 2, 8, 16, 32
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (b, s, h), jnp.float32)
    w = {
        "ln_w": jnp.ones((h,)), "ln_b": jnp.zeros((h,)),
        "fc1": jax.random.normal(jax.random.fold_in(k, 1), (h, f)) * 0.1,
        "fc2": jax.random.normal(jax.random.fold_in(k, 2), (f, h)) * 0.1,
    }
    wspecs = {"ln_w": P(), "ln_b": P(), "fc1": P(None, "tp"),
              "fc2": P("tp", None)}

    def block(p, xl, sequence_parallel):
        # LN runs on the (b, s/tp, h) shard under SP — the memory win
        y = layer_norm(xl, p["ln_w"], p["ln_b"])
        y = tp.column_parallel_linear(y, p["fc1"], gather_output=False,
                                      sequence_parallel=sequence_parallel)
        y = jax.nn.gelu(y, approximate=True)
        return tp.row_parallel_linear(y, p["fc2"], input_is_parallel=True,
                                      sequence_parallel=sequence_parallel)

    def run(sequence_parallel):
        in_spec = P(None, "tp", None) if sequence_parallel else P()
        out_spec = in_spec

        def loss_body(p, xl):
            out = block(p, xl, sequence_parallel)
            return out

        f = shard_map(loss_body, mesh=mesh_tp2, in_specs=(wspecs, in_spec),
                      out_specs=out_spec)

        def loss(p, x):
            return jnp.sum(jnp.sin(f(p, x)))

        # jit: eager shard_map grad dispatches op-by-op through the
        # 8-device SPMD interpreter (was the slowest test in the suite)
        val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(w, x)
        out = jax.jit(f)(w, x)
        return out, val, grads

    out_sp, val_sp, g_sp = run(True)
    out_tp, val_tp, g_tp = run(False)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_tp),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(val_sp), float(val_tp), rtol=1e-6)
    for a, b_ in zip(jax.tree.leaves(g_sp), jax.tree.leaves(g_tp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-6)


def test_sequence_parallel_region_roundtrip(mesh_tp2):
    """gather ∘ reduce_scatter over a seq-sharded tensor is psum-consistent:
    scattering a replicated partial then gathering reproduces the psum."""
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 4), jnp.float32)

    def body(xl):
        scat = tp.reduce_scatter_to_sequence_parallel_region(xl)
        return tp.gather_from_sequence_parallel_region(scat)

    f = shard_map(body, mesh=mesh_tp2, in_specs=P(), out_specs=P(),
                  check_vma=False)
    # every rank contributes the same replicated x -> psum = 2x
    np.testing.assert_allclose(np.asarray(f(x)), 2 * np.asarray(x),
                               rtol=1e-6)

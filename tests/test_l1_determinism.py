"""L1 determinism harness — ref tests/L1/common/compare.py:34-66: run the
imagenet trainer twice per config with --deterministic and require EXACT
per-iteration loss equality; sweep a mini {opt_level × sync_bn}
cross-product (ref tests/L1/cross_product/run.sh)."""

import importlib.util
import pathlib

import numpy as np
import pytest

_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load_trainer():
    spec = importlib.util.spec_from_file_location(
        "imagenet_main_amp", _ROOT / "examples" / "imagenet" / "main_amp.py")
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


_BASE = ["--arch", "resnet18", "--iters", "3", "--batch-size", "16",
         "--image-size", "32", "--num-classes", "10", "--deterministic",
         "--lr", "0.001"]


@pytest.mark.parametrize("opt_level,sync_bn", [
    ("O0", False), ("O2", False), ("O2", True), ("O1", False),
])
def test_l1_loss_curves_are_deterministic(opt_level, sync_bn):
    m = _load_trainer()
    argv = _BASE + ["--opt-level", opt_level] + (
        ["--sync_bn"] if sync_bn else [])
    a = m.train(m.parse_args(argv))
    b = m.train(m.parse_args(argv))
    # bitwise per-iteration equality (ref compare.py exact equality gate)
    assert a == b, f"nondeterministic losses: {a} vs {b}"
    assert np.isfinite(a).all()


def test_l1_opt_levels_start_close():
    """O0 (fp32) and O2 (bf16+masters) must agree at init within bf16
    tolerance (ref cross_product expectation: same first-iter loss)."""
    m = _load_trainer()
    a = m.train(m.parse_args(_BASE + ["--opt-level", "O0"]))
    b = m.train(m.parse_args(_BASE + ["--opt-level", "O2"]))
    np.testing.assert_allclose(a[0], b[0], rtol=5e-2)

"""L1 determinism + stored-baseline harness.

Ref ``tests/L1/common/run_test.sh`` + ``compare.py:34-66``: every config in
the {opt_level × keep_batchnorm × loss_scale} cross-product is run twice
with ``--deterministic`` and gated on EXACT per-iteration loss equality,
then compared against checked-in baseline loss curves (``baselines/`` files)
to catch silent numerics regressions across code versions.

Here: the cross-product {O0–O3 × sync_bn × loss-scale} runs on a small arch
(CPU compile cost), with one flagship ResNet-50 config; the determinism gate
is bitwise like the reference, the stored-baseline gate uses a small
tolerance because XLA CPU codegen may legally reorder float math between
versions (regenerate via ``tests/gen_l1_baselines.py``).
"""

import json
import pathlib

import numpy as np
import pytest

from gen_l1_baselines import (  # noqa: E402 — sibling module, pytest rootdir
    CROSS_PRODUCT,
    config_argv,
    config_key,
    load_trainer,
)

_BASELINES = json.loads(
    (pathlib.Path(__file__).parent / "l1_baselines.json").read_text())


# Fast-tier subset: one end-to-end exercise of the determinism +
# stored-baseline gate (O2 + static scale, the richest masters-path
# composition). The rest of the cross-product (O0/O1/O3, SyncBN variants,
# the ResNet-50 flagship) is the --runslow tier — the reference draws the
# same L0-sanity / L1-nightly line (SURVEY §4).
_FAST = {"resnet18_O2_False_128.0"}


@pytest.mark.parametrize(
    "cfg",
    [pytest.param(
        c, id=config_key(*c),
        marks=[] if config_key(*c) in _FAST else [pytest.mark.slow])
     for c in CROSS_PRODUCT])
def test_l1_cross_product_deterministic_and_matches_baseline(cfg):
    m = load_trainer()
    args = m.parse_args(config_argv(*cfg))
    a = m.train(args)
    assert np.isfinite(a).all()

    # exact-equality determinism gate (second run hits the jit cache, so the
    # pair costs one compile) — ref compare.py's loss_e == loss_p assert
    b = m.train(m.parse_args(config_argv(*cfg)))
    assert a == b, f"nondeterministic losses: {a} vs {b}"

    # stored-baseline gate — ref compare.py --use_baseline
    base = _BASELINES[config_key(*cfg)]
    rtol = 1e-4 if cfg[1] == "O0" else 5e-3
    np.testing.assert_allclose(a, base, rtol=rtol, err_msg=(
        f"{config_key(*cfg)} drifted from stored baseline; if the numerics "
        f"change is intentional, regenerate via tests/gen_l1_baselines.py"))


@pytest.mark.slow
def test_l1_opt_levels_start_close():
    """O0 (fp32) and O2 (bf16+masters) agree at init within bf16 tolerance
    (ref cross_product expectation: same first-iter loss). Runs the trainer
    live — comparing two stored baselines to each other could never catch a
    regression in the current code."""
    m = load_trainer()
    a = m.train(m.parse_args(config_argv("resnet18", "O0", False, None)))
    b = m.train(m.parse_args(config_argv("resnet18", "O2", False, None)))
    np.testing.assert_allclose(a[0], b[0], rtol=5e-2)

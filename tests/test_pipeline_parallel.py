"""Pipeline-parallel schedule tests on the 8-device virtual mesh.

Ref test strategy: ``tests/L0/run_transformer/run_pipeline_parallel_test.py``
runs all three schedules (× dtypes × grad scaler) and checks losses; here the
stronger check is available: the pipelined loss/grads must EQUAL the
sequential single-device computation of the same stage stack.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    get_ltor_masks_and_position_ids,
    microbatches as mb_mod,
)
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    PipelineSpec,
    build_model,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
)

HID = 8
B = 8
SEQ = 4


def _spec():
    def embed_fn(ep, x):
        return x @ ep["w"]

    def stage_fn(sp, h):
        return jnp.tanh(h @ sp["w"] + sp["b"])

    def loss_fn(hp, h, tgt):
        pred = h @ hp["w"]
        return jnp.mean((pred - tgt) ** 2)

    return PipelineSpec(embed_fn, stage_fn, loss_fn)


def _params(rng, num_chunks, vp=None):
    k1, k2, k3 = jax.random.split(rng, 3)

    def stage_init(key, c):
        kw, kb = jax.random.split(key)
        return {
            "w": jax.random.normal(kw, (HID, HID)) * 0.3,
            "b": jax.random.normal(kb, (HID,)) * 0.1,
        }

    stages = build_model(stage_init, k1, num_chunks if vp is None else num_chunks,
                         virtual_pipeline_size=vp)
    return {
        "embed": {"w": jax.random.normal(k2, (HID, HID)) * 0.3},
        "stages": stages,
        "head": {"w": jax.random.normal(k3, (HID, HID)) * 0.3},
    }


def _batch(rng, b=B):
    ki, kt = jax.random.split(rng)
    return (
        jax.random.normal(ki, (b, SEQ, HID)),
        jax.random.normal(kt, (b, SEQ, HID)),
    )


def _chunk_order_reference(spec, params, batch, num_microbatches, pp, vp):
    """Ground truth for interleaved layout [vp, pp, ...]: execution order is
    chunk v*pp+s i.e. iterate v outer, s inner."""
    inputs, targets = batch

    def loss_of(p):
        def one_mb(x, t):
            h = spec.embed_fn(p["embed"], x)
            for v in range(vp):
                for s in range(pp):
                    sp = jax.tree.map(lambda a: a[v, s], p["stages"])
                    h = spec.stage_fn(sp, h)
            return spec.loss_fn(p["head"], h, t)

        M = num_microbatches
        nb = inputs.shape[0]
        xs = inputs.reshape((M, nb // M) + inputs.shape[1:])
        ts = targets.reshape((M, nb // M) + targets.shape[1:])
        return jnp.mean(jax.vmap(one_mb)(xs, ts))

    return jax.jit(jax.value_and_grad(loss_of))(params)


def _flat_reference(spec, params, batch, num_microbatches, pp):
    inputs, targets = batch

    def loss_of(p):
        def one_mb(x, t):
            h = spec.embed_fn(p["embed"], x)
            for s in range(pp):
                sp = jax.tree.map(lambda a: a[s], p["stages"])
                h = spec.stage_fn(sp, h)
            return spec.loss_fn(p["head"], h, t)

        M = num_microbatches
        nb = inputs.shape[0]
        xs = inputs.reshape((M, nb // M) + inputs.shape[1:])
        ts = targets.reshape((M, nb // M) + targets.shape[1:])
        return jnp.mean(jax.vmap(one_mb)(xs, ts))

    return jax.jit(jax.value_and_grad(loss_of))(params)


def _assert_tree_close(a, b, atol=1e-5):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=atol, rtol=1e-4
        ),
        a,
        b,
    )


# ---------------------------------------------------------------------------


def test_no_pipelining_matches_plain_grad():
    parallel_state.initialize_model_parallel()  # trivial mesh ok
    rng = jax.random.PRNGKey(0)
    spec = _spec()
    params = _params(rng, 2)
    batch = _batch(jax.random.PRNGKey(1))

    def fwd(p, mb):
        x, t = mb
        h = spec.embed_fn(p["embed"], x)
        for s in range(2):
            h = spec.stage_fn(jax.tree.map(lambda a: a[s], p["stages"]), h)
        return spec.loss_fn(p["head"], h, t)

    loss, grads = forward_backward_no_pipelining(
        fwd, batch, params, num_microbatches=4
    )
    ref_loss, ref_g = _flat_reference(spec, params, batch, 4, 2)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    # grads of mean-over-microbatch loss: no_pipelining returns grads of
    # sum(loss/M) = grads of mean
    _assert_tree_close(grads, ref_g)


@pytest.mark.parametrize("num_microbatches", [4, pytest.param(8, marks=pytest.mark.slow)])
def test_1f1b_matches_sequential(num_microbatches):
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )
    rng = jax.random.PRNGKey(2)
    spec = _spec()
    params = _params(rng, 4)
    batch = _batch(jax.random.PRNGKey(3), b=16)

    loss, grads = jax.jit(
        lambda p: forward_backward_pipelining_without_interleaving(
            spec, p, batch, num_microbatches=num_microbatches, mesh=mesh))(
        params)
    ref_loss, ref_g = _flat_reference(spec, params, batch, num_microbatches, 4)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    _assert_tree_close(grads, ref_g)


def test_1f1b_with_dp():
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )  # dp = 2 remaining
    assert mesh.shape["dp"] == 2
    rng = jax.random.PRNGKey(4)
    spec = _spec()
    params = _params(rng, 4)
    batch = _batch(jax.random.PRNGKey(5))

    loss, grads = jax.jit(
        lambda p: forward_backward_pipelining_without_interleaving(
            spec, p, batch, num_microbatches=2, mesh=mesh))(params)
    ref_loss, ref_g = _flat_reference(spec, params, batch, 2, 4)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    _assert_tree_close(grads, ref_g)


@pytest.mark.parametrize("vp", [pytest.param(2, marks=pytest.mark.slow), 3])
def test_interleaved_matches_sequential(vp):
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2,
        virtual_pipeline_model_parallel_size_=vp,
    )
    rng = jax.random.PRNGKey(6)
    spec = _spec()
    params = _params(rng, 2, vp=vp)
    batch = _batch(jax.random.PRNGKey(7), b=16)

    loss, grads = jax.jit(
        lambda p: forward_backward_pipelining_with_interleaving(
            spec, p, batch, num_microbatches=4, virtual_pipeline_size=vp,
            mesh=mesh))(params)
    ref_loss, ref_g = _chunk_order_reference(spec, params, batch, 4, 2, vp)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    _assert_tree_close(grads, ref_g)


def test_loss_scale_scales_grads():
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )
    spec = _spec()
    params = _params(jax.random.PRNGKey(8), 4)
    batch = _batch(jax.random.PRNGKey(9))
    loss1, g1 = jax.jit(
        lambda p: forward_backward_pipelining_without_interleaving(
            spec, p, batch, num_microbatches=4, mesh=mesh))(params)
    loss2, g2 = jax.jit(
        lambda p, s: forward_backward_pipelining_without_interleaving(
            spec, p, batch, num_microbatches=4, mesh=mesh, loss_scale=s))(
        params, jnp.asarray(8.0))
    np.testing.assert_allclose(float(loss1), float(loss2), atol=1e-6)
    _assert_tree_close(g2, jax.tree.map(lambda x: 8.0 * x, g1))


def test_get_forward_backward_func_dispatch():
    parallel_state.initialize_model_parallel(pipeline_model_parallel_size_=4)
    assert (
        get_forward_backward_func()
        is forward_backward_pipelining_without_interleaving
    )
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        virtual_pipeline_model_parallel_size_=2,
    )
    assert (
        get_forward_backward_func()
        is forward_backward_pipelining_with_interleaving
    )
    parallel_state.initialize_model_parallel()
    assert get_forward_backward_func() is forward_backward_no_pipelining


# ---------------------------------------------------------------------------
# microbatch calculator (ref microbatches.py tests via run_pipeline tests)


def test_constant_microbatches():
    c = ConstantNumMicroBatches(64, 4, 2)
    assert c.get() == 8
    with pytest.raises(ValueError):
        ConstantNumMicroBatches(63, 4, 2)


def test_rampup_microbatches():
    r = RampupBatchsizeNumMicroBatches(
        start_batch_size=8, batch_size_increment=8, ramup_samples=400,
        global_batch_size=32, micro_batch_size=4, data_parallel_size=2,
    )
    assert r.get_current_global_batch_size() == 8
    r.update(100, True)
    assert r.get_current_global_batch_size() == 8
    r.update(200, True)
    assert r.get_current_global_batch_size() == 16
    r.update(1000, True)
    assert r.get_current_global_batch_size() == 32
    assert r.get() == 32 // (4 * 2)


def test_ltor_masks_and_position_ids():
    data = jnp.asarray([[5, 1, 7, 2, 9, 9]])  # eod = 9
    am, lm, pid = get_ltor_masks_and_position_ids(
        data, eod_token=9, reset_position_ids=True,
        reset_attention_mask=True, eod_mask_loss=True,
    )
    assert am.shape == (1, 1, 6, 6)
    # causal: last row all visible within doc; first row only position 0
    assert not bool(am[0, 0, 0, 0])  # self not masked
    assert bool(am[0, 0, 0, 1])  # future masked
    np.testing.assert_array_equal(np.asarray(lm[0]), [1, 1, 1, 1, 0, 0])
    # after the first eod at index 4, positions restart
    np.testing.assert_array_equal(np.asarray(pid[0]), [0, 1, 2, 3, 4, 0])
    # cross-document attention masked: token 5 (doc 1) cannot see token 0
    assert bool(am[0, 0, 5, 0])


def test_explicit_pp_still_picks_up_installed_vp():
    # regression: an explicit pp argument must not drop the installed vp
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        virtual_pipeline_model_parallel_size_=2,
    )
    assert (
        get_forward_backward_func(pipeline_model_parallel_size=4)
        is forward_backward_pipelining_with_interleaving
    )


def test_rampup_no_ramp_when_start_equals_global():
    r = RampupBatchsizeNumMicroBatches(
        start_batch_size=32, batch_size_increment=8, ramup_samples=400,
        global_batch_size=32, micro_batch_size=4, data_parallel_size=2,
    )
    assert r.get_current_global_batch_size() == 32
    r.update(100, True)
    assert r.get() == 32 // (4 * 2)


# ---------------------------------------------------------------------------
# p2p_communication ring ops (ref p2p_communication.py public API :187-408)


def test_p2p_ring_shifts(mesh8):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4, tensor_model_parallel_size_=2
    )

    def body(x):
        fwd = p2p.send_forward_recv_forward(x)
        bwd = p2p.send_backward_recv_backward(x)
        fr, br = p2p.send_forward_recv_backward(x, x)
        return fwd, bwd, fr, br

    x = jnp.arange(8.0).reshape(4, 2)  # [pp, tp] distinct per device
    out = jax.shard_map(
        body, mesh=mesh,
        in_specs=P("pp", "tp"),
        out_specs=(P("pp", "tp"),) * 4,
    )(x)
    fwd, bwd, fr, br = (np.asarray(o) for o in out)
    want_fwd = np.roll(np.arange(8.0).reshape(4, 2), 1, axis=0)
    want_bwd = np.roll(np.arange(8.0).reshape(4, 2), -1, axis=0)
    np.testing.assert_array_equal(fwd, want_fwd)
    np.testing.assert_array_equal(bwd, want_bwd)
    np.testing.assert_array_equal(fr, want_fwd)
    np.testing.assert_array_equal(br, want_bwd)


def test_p2p_scatter_gather_matches_plain_shift(mesh8):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4, tensor_model_parallel_size_=2
    )

    def body(x):
        plain = p2p.send_forward_recv_forward(x)
        sg = p2p.send_forward_recv_forward(x, scatter_gather=True)
        return plain, sg

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))  # last dim % tp == 0
    # the scatter→shift→gather value is tp-replicated by construction but the
    # VMA system can't prove it — hence check_vma=False
    out = jax.shard_map(
        body, mesh=mesh, in_specs=P("pp"), out_specs=(P("pp"), P("pp")),
        check_vma=False,
    )(x)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out[1]),
                               atol=1e-6)


# ---------------------------------------------------------------------------
# MP-aware GradScaler (ref transformer/amp/grad_scaler.py:8-106)


def test_grad_scaler_syncs_found_inf_across_mp(mesh8):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.amp import GradScaler

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    scaler = GradScaler(init_scale=2.0**10, growth_interval=2)
    state = scaler.init_state()

    def body(flag):
        return scaler.sync_found_inf(flag)

    # only tp rank 3 overflows; every rank must agree after sync
    flag = jnp.asarray([0.0, 0.0, 0.0, 1.0] * 2)
    out = jax.shard_map(
        body, mesh=mesh, in_specs=P(("dp", "tp")), out_specs=P(("dp", "tp"))
    )(flag)
    np.testing.assert_array_equal(np.asarray(out), np.ones(8))

    # backoff on overflow, growth after growth_interval clean steps
    state2, skip = scaler.update_scale(state, jnp.asarray(1.0))
    assert bool(skip)
    assert float(state2.loss_scale) == 2.0**10 * 0.5
    s = scaler.init_state()
    for _ in range(2):
        s, skip = scaler.update_scale(s, jnp.asarray(0.0))
        assert not bool(skip)
    assert float(s.loss_scale) == 2.0**10 * 2.0


def test_grad_scaler_custom_backoff():
    from apex_tpu.transformer.amp import GradScaler

    scaler = GradScaler(init_scale=1024.0, growth_factor=2.0,
                        backoff_factor=0.25)
    state = scaler.init_state()
    state, _ = scaler.update_scale(state, jnp.asarray(1.0))
    assert float(state.loss_scale) == 256.0

"""Monitor tier 4 — performance forensics acceptance gates (ISSUE-17).

All stock-jax-safe (single device, manual clock, SimTransport):

* **attribution identity** — every retired request's queue/prefill/
  transfer/decode/stall components sum to the event-derived e2e exactly
  (stall is the residual and stays >= -tol), INCLUDING chaos-migrated
  requests, and the decomposition is independent of event-log
  concatenation order (merged worker logs replay shared records);
* **explain_regression** — an injected slow component is named in the
  diagnosis, and the component deltas account for the whole e2e move;
* **metering** — one charge per retirement means Σ per-tenant rollups
  == fleet totals to the unit; deterministic across identical runs;
  cardinality overflow folds into ``_overflow`` LOUDLY; unknown
  resources raise; worker cost rates accrue and ride heartbeats;
* **trend gating** — the ``python -m apex_tpu.monitor.trend`` CLI exits
  1 on a step change in the bad direction, 0 on a stationary series and
  0 on an improvement (good-direction moves never flag);
* satellites: the tier-4 ``monitor.regress`` polarity rows, provenance
  byte-compatibility on ``json_record``, the ``monitor.view``
  attribution table / tenant rollup / ``--baseline`` diagnosis, and the
  ON/OFF cluster config parity (tier-4 off: no keys, same streams).
"""

import json

import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor import sink as sink_mod
from apex_tpu.monitor import trend, view
from apex_tpu.monitor.attrib import (
    COMPONENTS,
    DEFAULT_TOL_MS,
    AttributionAccumulator,
    attribute_requests,
    attribution_summary,
    explain_regression,
)
from apex_tpu.monitor.events import EventLog
from apex_tpu.monitor.meter import (
    OVERFLOW_TENANT,
    CostModel,
    Meter,
    modeled_request_flops,
)
from apex_tpu.monitor.regress import classify_metric
from apex_tpu.monitor.slo import SloSpec
from apex_tpu.serve import (
    ClusterChaos,
    ClusterConfig,
    InferenceEngine,
    Request,
    RouterConfig,
    ServeCluster,
    ServeConfig,
)
from apex_tpu.serve.cluster.chaos import KillWorker
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

CFG = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)

TREQS = [
    Request("a", [1, 2, 3, 4, 5], max_new_tokens=6, tenant="t0"),
    Request("b", [7, 8, 9], max_new_tokens=8, tenant="t1"),
    Request("c", list(range(20, 42)), max_new_tokens=8, tenant="t0"),
    Request("d", [11, 3, 11, 3, 11, 3, 7], max_new_tokens=9, tenant="t2"),
    Request("e", list(range(60, 73)), max_new_tokens=7, tenant="t1"),
]


def _serve_cfg(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return ServeConfig(**kw)


class _ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _drive(cl, clock=None, tick_ms=5.0, max_steps=20000):
    steps = 0
    while cl.active and steps < max_steps:
        cl.step()
        if clock is not None:
            clock.advance(tick_ms / 1e3)
        steps += 1
    assert steps < max_steps, "cluster failed to drain"


def _run_cluster(chaos=None, n_decode=2, reqs=TREQS, **cfg_kw):
    clock = _ManualClock()
    events = EventLog(keep=True, clock=clock)
    ccfg = ClusterConfig(n_prefill=1, n_decode=n_decode,
                         serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         **cfg_kw)
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events, chaos=chaos)
    for r in reqs:
        cl.submit(r)
    _drive(cl, clock)
    return cl, events


def _ev(uid, event, t_ms, **kw):
    return {"kind": "event", "uid": uid, "event": event,
            "t_ms": float(t_ms), **kw}


def _check_identity(att, tol=DEFAULT_TOL_MS):
    assert att, "no requests attributed"
    for uid, comp in att.items():
        total = sum(comp[c] for c in COMPONENTS)
        # each of 5 components + e2e round to 3dp independently
        assert total == pytest.approx(comp["e2e_ms"], abs=0.01), uid
        assert comp["stall"] >= -tol, (uid, comp)


# -- attribution: identity, order independence, chaos -----------------------


def test_attribution_synthetic_decomposition():
    """A hand-built lifecycle decomposes into the exact documented
    components, and stall picks up the unexplained residual."""
    recs = [
        _ev("r", "submitted", 0.0, tenant="t0"),
        _ev("r", "admitted", 2.0),
        _ev("r", "prefill_start", 10.0),
        _ev("r", "prefill_end", 30.0),
        _ev("r", "transfer_start", 30.0),
        _ev("r", "transfer_end", 40.0),
        _ev("r", "first_token", 45.0),
        _ev("r", "retired", 100.0),
    ]
    att = attribute_requests(recs)
    comp = att["r"]
    assert comp["queue"] == 10.0       # submitted -> first prefill_start
    assert comp["prefill"] == 20.0
    assert comp["transfer"] == 10.0
    assert comp["decode"] == 55.0      # first_token -> retired, no overlap
    assert comp["stall"] == 5.0        # 40 -> 45 gap
    assert comp["e2e_ms"] == 100.0
    assert comp["tenant"] == "t0"
    assert comp["migrated"] is False
    _check_identity(att)


def test_attribution_transfer_retry_opens_no_second_interval():
    """A retried transfer re-emits ``transfer_start`` with attempt > 1;
    only the first attempt opens an interval (stitch_traces rule)."""
    recs = [
        _ev("r", "submitted", 0.0),
        _ev("r", "prefill_start", 0.0),
        _ev("r", "prefill_end", 10.0),
        _ev("r", "transfer_start", 10.0),
        _ev("r", "transfer_start", 15.0, attempt=2),
        _ev("r", "transfer_end", 20.0),
        _ev("r", "first_token", 20.0),
        _ev("r", "retired", 50.0),
    ]
    comp = attribute_requests(recs)["r"]
    assert comp["transfer"] == 10.0
    assert comp["decode"] == 30.0
    _check_identity({"r": comp})


def test_attribution_order_independent_synthetic():
    base = [
        _ev("x", "submitted", 0.0), _ev("x", "prefill_start", 3.0),
        _ev("x", "prefill_end", 9.0), _ev("x", "first_token", 11.0),
        _ev("x", "retired", 40.0),
        _ev("y", "submitted", 1.0), _ev("y", "prefill_start", 9.0),
        _ev("y", "prefill_end", 14.0), _ev("y", "first_token", 15.0),
        _ev("y", "retired", 33.0),
    ]
    fwd = attribute_requests(base)
    rev = attribute_requests(list(reversed(base)))
    assert fwd == rev


def test_attribution_identity_under_chaos_both_orders():
    """The acceptance pin: a kill-and-migrate run attributes with full
    coverage, the migrated request included, the identity holds for
    every request, and BOTH concatenation orders of the merged log
    yield the identical decomposition."""
    chaos = ClusterChaos([KillWorker(at_step=12, worker="decode0")])
    cl, events = _run_cluster(chaos=chaos)
    st = cl.stats()
    assert st["worker_deaths"] == 1
    assert st["migrations_total"] >= 1

    recs = [r for r in events.records if r.get("kind") == "event"]
    att = attribute_requests(recs)
    _check_identity(att)
    assert set(att) == {r.uid for r in TREQS}
    migrated = [c for c in att.values() if c["migrated"]]
    assert migrated, "no migrated request attributed"
    assert any(c["replayed_tokens"] > 0 for c in migrated)

    # order independence: swap the halves AND fully reverse — a merged
    # worker log has no canonical order, attribution must not care
    half = len(recs) // 2
    swapped = recs[half:] + recs[:half]
    assert attribute_requests(swapped) == att
    assert attribute_requests(list(reversed(recs))) == att

    summ = attribution_summary(recs)
    assert summ["attrib_coverage"] == 1.0
    assert summ["n_retired"] == len(TREQS)

    # the streaming accumulator (what cluster.stats() reports) agrees
    acc = AttributionAccumulator()
    for r in recs:
        acc.tap(r)
    assert acc.summary() == summ
    assert acc.in_flight == 0


def test_cluster_stats_carry_attribution_and_meter():
    cl, _ = _run_cluster()
    st = cl.stats()
    assert st["attrib_coverage"] == 1.0
    assert st["meter_coverage"] == 1.0
    for c in COMPONENTS:
        assert f"{c}_component_ms_p50" in st["attribution"]
    assert st["decode_component_ms_p50"] > 0.0
    assert st["cost_per_token"] > 0.0
    assert st["meter"]["totals"]["requests"] == len(TREQS)
    # heartbeat-advertised worker cost rates (ROADMAP 5c): every decode
    # worker that retired work advertises a positive rate
    rates = st["meter"]["worker_cost_rates"]
    assert any(v > 0.0 for v in rates.values())


def test_tier4_off_no_keys_and_streams_bitwise():
    """``metering=False, attribution=False`` removes the tier-4 surface
    entirely AND the forensics plane never perturbs the work: streams
    bitwise vs the ON run."""
    cl_on, _ = _run_cluster()
    cl_off, _ = _run_cluster(metering=False, attribution=False)
    st = cl_off.stats()
    for k in ("attribution", "attrib_coverage", "meter", "cost_per_token",
              "cost_per_request", "meter_coverage"):
        assert k not in st, k
    assert cl_off.meter is None and cl_off.attrib is None
    assert cl_on.finished == cl_off.finished  # bitwise


# -- explain_regression ------------------------------------------------------


def _lifecycle(uid, *, decode_ms=18.0, transfer=None):
    recs = [
        _ev(uid, "submitted", 0.0),
        _ev(uid, "prefill_start", 5.0),
        _ev(uid, "prefill_end", 10.0),
        _ev(uid, "first_token", 12.0),
    ]
    end = 12.0 + decode_ms
    if transfer is not None:
        a, b = transfer
        recs += [_ev(uid, "transfer_start", a),
                 _ev(uid, "transfer_end", b)]
        end = max(end, b) + decode_ms - min(decode_ms, 0.0)
        end = b + decode_ms  # decode resumes after the hop
    recs.append(_ev(uid, "retired", end))
    return recs


def test_explain_regression_names_injected_decode():
    base = [r for i in range(8) for r in _lifecycle(f"b{i}")]
    slow = [r for i in range(8)
            for r in _lifecycle(f"n{i}", decode_ms=68.0)]
    ex = explain_regression(base, slow)
    assert ex["diagnosis"] == "decode"
    assert ex["top_regressed"][0] == "decode"
    assert ex["delta_ms"] == pytest.approx(50.0, abs=0.01)
    # the component deltas account for ALL of the e2e move
    assert sum(c["delta_ms"] for c in ex["components"]) == pytest.approx(
        ex["delta_ms"], abs=0.01)


def test_explain_regression_names_injected_transfer():
    base = [r for i in range(8) for r in _lifecycle(f"b{i}")]
    slow = [r for i in range(8)
            for r in _lifecycle(f"n{i}", transfer=(12.0, 42.0))]
    ex = explain_regression(base, slow)
    assert ex["diagnosis"] == "transfer"
    dec = [c for c in ex["components"] if c["component"] == "decode"][0]
    assert dec["delta_ms"] == pytest.approx(0.0, abs=0.01)


def test_explain_regression_no_regression_no_diagnosis():
    base = [r for i in range(8) for r in _lifecycle(f"b{i}")]
    ex = explain_regression(base, base)
    assert ex["diagnosis"] is None
    assert ex["delta_ms"] == 0.0


# -- metering ----------------------------------------------------------------


def test_meter_rollup_equals_totals_to_the_unit():
    cl, _ = _run_cluster()
    m = cl.meter
    # RAW ledger identity: totals are literally the field-wise sum
    for key in ("flops", "kv_block_s", "tokens", "requests"):
        raw = sum(led[key] for led in m._tenants.values())
        tot = sum(m._tenants[t][key] for t in m._tenants)
        assert raw == tot
    st = m.stats(completed=cl.completed)
    roll = sum(t["cost_units"] for t in st["tenants"].values())
    # displayed values round per-tenant to 1e-6
    assert roll == pytest.approx(st["totals"]["cost_units"],
                                 abs=len(st["tenants"]) * 1e-6)
    assert sum(t["tokens"] for t in st["tenants"].values()) \
        == st["totals"]["tokens"]
    assert sum(t["requests"] for t in st["tenants"].values()) \
        == st["totals"]["requests"] == cl.completed
    assert st["meter_coverage"] == 1.0
    assert set(st["tenants"]) >= {"t0", "t1", "t2"}


def test_meter_charge_once_under_migration():
    """A migrated request retires exactly once (on the destination), so
    chaos never double-bills: metered requests == completed."""
    chaos = ClusterChaos([KillWorker(at_step=12, worker="decode0")])
    cl, _ = _run_cluster(chaos=chaos)
    assert cl.stats()["migrations_total"] >= 1
    st = cl.meter.stats(completed=cl.completed)
    assert st["totals"]["requests"] == cl.completed == len(TREQS)
    assert st["meter_coverage"] == 1.0


def test_meter_deterministic_across_identical_runs():
    st1 = _run_cluster()[0].meter.stats(completed=len(TREQS))
    st2 = _run_cluster()[0].meter.stats(completed=len(TREQS))
    assert st1 == st2


def test_meter_overflow_is_loud_and_bounded():
    m = Meter(max_tenants=2)
    m.charge("t0", flops=1e9, tokens=1, requests=1)
    m.charge("t1", flops=1e9, tokens=1, requests=1)
    m.charge("t2", flops=1e9, tokens=1, requests=1)  # over the bound
    m.charge("t3", flops=1e9, tokens=1, requests=1)
    st = m.stats()
    assert st["overflow_charges_total"] == 2
    assert OVERFLOW_TENANT in st["tenants"]
    assert st["tenants"][OVERFLOW_TENANT]["requests"] == 2
    # the fold loses per-tenant resolution, never revenue
    assert st["totals"]["requests"] == 4


def test_meter_unknown_resource_raises():
    with pytest.raises(ValueError, match="unknown resource"):
        Meter().charge("t0", watts=9000.0)
    with pytest.raises(ValueError, match="max_tenants"):
        Meter(max_tenants=0)
    with pytest.raises(ValueError, match="meter_max_tenants"):
        ClusterConfig(n_prefill=1, n_decode=1, serve=_serve_cfg(),
                      meter_max_tenants=0).validate()


def test_modeled_flops_shape():
    base = modeled_request_flops(1000000, 2, 32, prompt_len=16,
                                 n_generated=8)
    more = modeled_request_flops(1000000, 2, 32, prompt_len=16,
                                 n_generated=16)
    cached = modeled_request_flops(1000000, 2, 32, prompt_len=16,
                                   n_generated=8, cached_tokens=8)
    assert more > base > cached > 0.0


def test_worker_cost_rate_accrues():
    m = Meter(model=CostModel())
    assert m.worker_cost_rate("w0") == 0.0
    m.charge("t0", worker="w0", t_ms=0.0, flops=1e12, tokens=10,
             requests=1)
    m.charge("t0", worker="w0", t_ms=2000.0, flops=1e12, tokens=10,
             requests=1)
    # 2 cost units over 2 s
    assert m.worker_cost_rate("w0", 2000.0) == pytest.approx(1.0)
    assert m.worker_rates(2000.0) == {"w0": 1.0}


def test_standalone_engine_attribution_and_meter():
    """The single-engine form: attribution histograms + metering without
    a cluster (ServeCluster passes its shared Meter the same way)."""
    m = Meter()
    eng = InferenceEngine(PARAMS, CFG, _serve_cfg(num_slots=4),
                          meter=m, meter_worker="solo")
    reqs = [Request(r.uid, list(r.tokens), max_new_tokens=r.max_new_tokens,
                    tenant=r.tenant) for r in TREQS]
    out = eng.run(reqs)
    assert len(out) == len(TREQS)
    st = eng.stats()
    assert st["attrib_coverage"] == 1.0
    assert st["queue_component_ms_p50"] is not None
    assert st["decode_component_ms_p50"] > 0.0
    assert st["meter_coverage"] == 1.0
    assert st["cost_per_token"] > 0.0
    assert m.stats()["totals"]["requests"] == len(TREQS)
    assert m.worker_cost_rate("solo") > 0.0


# -- trend gating ------------------------------------------------------------


def _bank(tmp_path, values, start=0):
    hist = str(tmp_path / "hist.jsonl")
    for i, v in enumerate(values):
        trend.append_history(hist, {"metric": "serve", "ok": True,
                                    "tokens_per_s": v}, stage="s10")
    return hist


def test_trend_cli_stationary_exit_0(tmp_path, capsys):
    hist = _bank(tmp_path, [100.0, 101.0, 102.0] * 4)
    assert trend.main(["check", hist, "--stage", "s10"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["ok"] is True and rep["checked"] >= 1


def test_trend_cli_step_change_exit_1(tmp_path, capsys):
    hist = _bank(tmp_path, [100.0, 101.0, 102.0] * 4 + [70.0] * 5)
    assert trend.main(["check", hist, "--stage", "s10"]) == 1
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["ok"] is False
    assert any(d["key"] == "tokens_per_s" and d["kind"] == "step"
               for d in rep["drifts"])
    assert rep["drift_score"] > 1.0


def test_trend_good_direction_never_flags(tmp_path):
    hist = _bank(tmp_path, [100.0, 101.0, 102.0] * 4 + [150.0] * 5)
    assert trend.main(["check", hist, "--stage", "s10"]) == 0


def test_trend_slow_drift_caught(tmp_path, capsys):
    """Every pairwise hop stays inside a 15% regress gate (-3% each);
    the series still walks 24% down off a stable baseline — the gap
    trend gating exists to close."""
    vals = [100.0, 101.0, 102.0] * 4 + [100.0 - 3.0 * i
                                        for i in range(1, 9)]
    hist = _bank(tmp_path, vals)
    assert trend.main(["check", hist, "--stage", "s10"]) == 1
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert any(d["key"] == "tokens_per_s" for d in rep["drifts"])


def test_trend_thin_history_passes(tmp_path):
    hist = _bank(tmp_path, [100.0, 50.0, 100.0])
    assert trend.main(["check", hist, "--stage", "s10"]) == 0


def test_trend_append_cli_stamps_and_filters(tmp_path, capsys):
    hist = str(tmp_path / "h.jsonl")
    rec = tmp_path / "rec.json"
    rec.write_text(json.dumps({"metric": "m", "tokens_per_s": 9.0}) + "\n")
    assert trend.main(["append", hist, str(rec), "--stage", "a"]) == 0
    assert trend.main(["append", hist, str(rec), "--stage", "b"]) == 0
    capsys.readouterr()
    assert len(trend.load_history(hist, stage="a")) == 1
    assert len(trend.load_history(hist)) == 2
    pts = [json.loads(ln) for ln in open(hist)]
    assert all(p["kind"] == "trend_point" for p in pts)
    # the CLI stamps provenance so a drift can be tied to what changed
    assert "provenance" in pts[0]


# -- satellites: polarity, provenance, view ---------------------------------


def test_regress_polarity_tier4_rows():
    for k in ("decode_component_ms_p50", "stall_component_ms_p99",
              "cost_per_token", "cost_per_request", "drift_score"):
        assert classify_metric(k) == "lower", k
    for k in ("attrib_coverage", "meter_coverage"):
        assert classify_metric(k) == "higher", k


def test_json_record_provenance_byte_compat():
    old = sink_mod._PROVENANCE
    try:
        sink_mod.set_provenance(None)
        line = sink_mod.json_record(metric="m", v=1)
        # byte-for-byte the pre-provenance format when no stamp is set
        assert line == json.dumps(
            {"schema": sink_mod.SCHEMA_VERSION, "metric": "m", "v": 1})
        sink_mod.set_provenance({"git_sha": "abc"})
        rec = json.loads(sink_mod.json_record(metric="m"))
        assert rec["provenance"] == {"git_sha": "abc"}
        # explicit fields win over the process stamp
        rec = json.loads(sink_mod.json_record(metric="m",
                                              provenance={"x": 1}))
        assert rec["provenance"] == {"x": 1}
    finally:
        sink_mod.set_provenance(old)


def test_collect_provenance_keys():
    prov = sink_mod.collect_provenance(extra={"stage": "test"})
    assert "hostname" in prov and "jax_version" in prov
    assert prov["git_sha"]  # tests run inside the repo
    # jax is imported in this process, so the backend is stamped
    assert prov["backend"] == jax.default_backend()
    assert prov["stage"] == "test"


def test_view_attribution_table_tenants_and_baseline(tmp_path, capsys):
    cl, events = _run_cluster()
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        for r in events.records:
            f.write(json.dumps(r) + "\n")
    assert view.main([str(path)]) == 0
    out = capsys.readouterr()
    assert "attribution (coverage 1.0)" in out.err
    for c in COMPONENTS:
        assert c in out.err
    assert "t0" in out.err and "t2" in out.err  # tenant rollup rows
    rec = json.loads(out.out.strip())
    assert rec["attrib_coverage"] == 1.0
    assert rec["tenants"]["t0"]["requests"] == 2
    assert rec["decode_component_ms_p50"] > 0.0
    # --baseline against itself: zero delta, explicit null diagnosis
    assert view.main([str(path), "--baseline", str(path)]) == 0
    out = capsys.readouterr()
    assert "vs baseline: e2e" in out.err
    rec = json.loads(out.out.strip())
    assert rec["explain"]["delta_ms"] == 0.0
    assert rec["explain"]["diagnosis"] is None

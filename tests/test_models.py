"""GPT/BERT fixture-model tests — ref tests/L0/run_transformer/
run_gpt_minimal_test.py and run_bert_minimal_test.py: the model must run
under TP (+PP), match its single-device computation exactly, and train."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.testing import (
    BertConfig,
    GPTConfig,
    bert_mlm_loss,
    gpt_loss,
    gpt_param_specs,
    gpt_pipeline_params,
    gpt_pipeline_spec,
    gpt_pipeline_specs_tree,
    init_gpt_params,
)
from apex_tpu.transformer.testing.standalone_bert import init_bert_params

CFG = GPTConfig(vocab_size=64, max_seq=16, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, remat=False)
B, S = 8, 16


def _batch(key, cfg=CFG, b=B, s=S):
    tokens = jax.random.randint(key, (b, s), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.fold_in(key, 1), (b, s), 0,
                                 cfg.vocab_size)
    return tokens, targets


def _loss_on_mesh(mesh, params, tokens, targets, cfg=CFG):
    def body(p, tok, tgt):
        from apex_tpu.transformer.pipeline_parallel.schedules.common import (
            replicate_loss,
        )

        return replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                              masked_axis=None)

    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(gpt_param_specs(cfg), P(DP := "dp"), P(DP)),
        out_specs=P(),
    ))(params, tokens, targets)


def test_gpt_tp_matches_single_device():
    params = init_gpt_params(jax.random.PRNGKey(0), CFG)
    tokens, targets = _batch(jax.random.PRNGKey(1))
    mesh_tp = build_mesh(tp=4, dp=2)
    mesh_1 = build_mesh(tp=1, dp=8)
    l_tp = _loss_on_mesh(mesh_tp, params, tokens, targets)
    l_1 = _loss_on_mesh(mesh_1, params, tokens, targets)
    # per-head interleaved qkv packing makes the computed function exactly
    # TP-degree invariant; only reduction-order noise remains
    np.testing.assert_allclose(float(l_tp), float(l_1), rtol=1e-5)


def test_gpt_trains_tp_dp():
    cfg = CFG
    params = init_gpt_params(jax.random.PRNGKey(2), cfg)
    mesh = build_mesh(tp=2, dp=4)
    tokens, targets = _batch(jax.random.PRNGKey(3))
    # target = shifted tokens would be realistic; fixed random targets are
    # memorizable by a 2-layer net — loss must drop
    specs = gpt_param_specs(cfg)

    def body(p, tok, tgt):
        from apex_tpu.transformer.pipeline_parallel.schedules.common import (
            replicate_loss,
        )

        loss = replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                              masked_axis=None)
        return loss

    def step(p, tok, tgt):
        loss, g = jax.value_and_grad(
            lambda p: jax.shard_map(
                body, mesh=mesh, in_specs=(specs, P("dp"), P("dp")),
                out_specs=P())(p, tok, tgt))(p)
        p = jax.tree.map(lambda w, gw: w - 0.1 * gw.astype(w.dtype), p, g)
        return p, loss

    step = jax.jit(step)
    first = None
    for _ in range(20):
        params, loss = step(params, tokens, targets)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.9, (first, float(loss))


@pytest.mark.slow
def test_gpt_pipeline_1f1b_matches_tp_only():
    cfg = dataclasses.replace(CFG, tie_embeddings=False)
    pp = 2
    params = gpt_pipeline_params(jax.random.PRNGKey(4), cfg, pp=pp)
    tokens, targets = _batch(jax.random.PRNGKey(5))
    mesh = build_mesh(tp=2, pp=pp, dp=2)
    spec = gpt_pipeline_spec(cfg)
    loss, grads = forward_backward_pipelining_without_interleaving(
        spec, params, (tokens, targets), num_microbatches=2, mesh=mesh,
        params_specs=gpt_pipeline_specs_tree(cfg),
        data_spec=P(None, "dp"), remat=False,
    )
    # sequential single-mesh computation of the same stacked params
    flat_layers = jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[2:]), params["stages"])
    flat = {"embed": params["embed"], "layers": flat_layers,
            "head": params["head"]}
    mesh1 = build_mesh(tp=1, dp=8)
    want = _loss_on_mesh(mesh1, flat, tokens, targets, cfg)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)
    # grads exist and are finite everywhere
    for leaf in jax.tree.leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_bert_runs_and_trains():
    cfg = BertConfig(vocab_size=64, max_seq=16, hidden=32, num_layers=2,
                     num_heads=4, dtype=jnp.float32, remat=False)
    params = init_bert_params(jax.random.PRNGKey(6), cfg)
    mesh = build_mesh(tp=2, dp=4)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (B, S), 0,
                                cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(8), (B, S), 0,
                                 cfg.vocab_size)
    loss_mask = (jax.random.uniform(jax.random.PRNGKey(9), (B, S)) < 0.3
                 ).astype(jnp.float32)
    pad = jnp.broadcast_to(jnp.arange(S)[None, :] >= 14, (B, S))  # pad tail

    def body(p, tok, tgt, lm, pm):
        from apex_tpu.transformer.pipeline_parallel.schedules.common import (
            replicate_loss,
        )

        return replicate_loss(
            bert_mlm_loss(p, tok, tgt, lm, cfg, padding_mask=pm), mesh,
            masked_axis=None)

    specs = gpt_param_specs(cfg)
    specs["embed"]["type"] = P()
    specs["embed"]["ln_w"] = P()
    specs["embed"]["ln_b"] = P()
    specs["head"] = jax.tree.map(lambda _: P(), {
        "dense_kernel": 0, "dense_bias": 0, "ln_w": 0, "ln_b": 0})

    def loss_fn(p):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(specs, P("dp"), P("dp"), P("dp"), P("dp")),
            out_specs=P())(p, tokens, targets, loss_mask, pad)

    step = jax.jit(lambda p: (jax.value_and_grad(loss_fn)(p)))
    first = None
    for _ in range(20):
        loss, g = step(params)
        params = jax.tree.map(lambda w, gw: w - 0.1 * gw.astype(w.dtype),
                              params, g)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.9, (first, float(loss))


def test_gpt_sequence_parallel_matches():
    params = init_gpt_params(jax.random.PRNGKey(0), CFG)
    tokens, targets = _batch(jax.random.PRNGKey(1))
    mesh_sp = build_mesh(tp=2, sp=2, dp=2)

    def body(p, tok, tgt):
        from apex_tpu.transformer.pipeline_parallel.schedules.common import (
            replicate_loss,
        )

        return replicate_loss(gpt_loss(p, tok, tgt, CFG), mesh_sp,
                              masked_axis=None)

    l_sp = jax.jit(jax.shard_map(
        body, mesh=mesh_sp,
        in_specs=(gpt_param_specs(CFG), P("dp", "sp"), P("dp", "sp")),
        out_specs=P(),
    ))(params, tokens, targets)
    l_1 = _loss_on_mesh(build_mesh(tp=1, dp=8), params, tokens, targets)
    np.testing.assert_allclose(float(l_sp), float(l_1), rtol=1e-3)


@pytest.mark.slow
def test_gpt_pipeline_interleaved_matches_sequential():
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_with_interleaving,
    )

    cfg = dataclasses.replace(CFG, num_layers=4, tie_embeddings=False)
    pp, vp = 2, 2
    params = gpt_pipeline_params(jax.random.PRNGKey(10), cfg, pp=pp, vp=vp)
    tokens, targets = _batch(jax.random.PRNGKey(11))
    mesh = build_mesh(tp=2, pp=pp, dp=2)
    spec = gpt_pipeline_spec(cfg)
    loss, grads = forward_backward_pipelining_with_interleaving(
        spec, params, (tokens, targets), num_microbatches=2,
        virtual_pipeline_size=vp, mesh=mesh,
        params_specs=gpt_pipeline_specs_tree(cfg, interleaved=True),
        data_spec=P(None, "dp"), remat=False,
    )
    # sequential: depth order is chunk-major (v*pp + s), i.e. reshape back
    flat_layers = jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[3:]), params["stages"])
    flat = {"embed": params["embed"], "layers": flat_layers,
            "head": params["head"]}
    want = _loss_on_mesh(build_mesh(tp=1, dp=8), flat, tokens, targets, cfg)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)
    for leaf in jax.tree.leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_bert_megatron_sp_matches_plain():
    """BERT under Megatron-SP (round 5: the embedding now reduce-scatters
    the sequence, LN/head boundaries gather — the GPT entry/exit wired to
    BERT's pos/type embeddings): loss and grads EQUAL the plain tp=2 run."""
    import dataclasses

    cfg = BertConfig(vocab_size=64, max_seq=16, hidden=32, num_layers=2,
                     num_heads=4, dtype=jnp.float32, remat=False)
    params = init_bert_params(jax.random.PRNGKey(6), cfg)
    mesh = build_mesh(tp=2, dp=4)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (B, S), 0,
                                cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(8), (B, S), 0,
                                 cfg.vocab_size)
    loss_mask = (jax.random.uniform(jax.random.PRNGKey(9), (B, S)) < 0.3
                 ).astype(jnp.float32)
    types = jax.random.randint(jax.random.PRNGKey(10), (B, S), 0, 2)
    pad = jnp.broadcast_to(jnp.arange(S)[None, :] >= 14, (B, S))

    def run(c):
        def body(p, tok, tgt, lm, tt, pm):
            from apex_tpu.transformer.pipeline_parallel.schedules.common import (
                replicate_loss,
            )

            return replicate_loss(
                bert_mlm_loss(p, tok, tgt, lm, c, token_types=tt,
                              padding_mask=pm), mesh, masked_axis=None)

        specs = gpt_param_specs(c)
        specs["embed"]["type"] = P()
        specs["embed"]["ln_w"] = P()
        specs["embed"]["ln_b"] = P()
        specs["head"] = jax.tree.map(lambda _: P(), {
            "dense_kernel": 0, "dense_bias": 0, "ln_w": 0, "ln_b": 0})

        def loss_fn(p):
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(specs, P("dp"), P("dp"), P("dp"), P("dp"),
                          P("dp")),
                out_specs=P())(p, tokens, targets, loss_mask, types, pad)

        return jax.jit(jax.value_and_grad(loss_fn))(params)

    l0, g0 = run(cfg)
    l1, g1 = run(dataclasses.replace(cfg, megatron_sp=True))
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5), g1, g0)

"""apex_tpu.monitor tests — metric pytrees under jit+donation, AMP/DDP/ZeRO
wiring, JSONL schema round-trip + append-after-crash, span visibility in
HLO/trace layer paths, and the compile-accounting gate (monitoring must add
ZERO recompilations; DDP-reported bytes must agree with comm.accounting).

Mesh-free tests are stock-jax/CPU-safe; mesh programs (shard_map + the GPT
fixture) run on the graft jax toolchain and skip cleanly elsewhere; the
profiler-trace tests are marked slow.

Treedef note exercised throughout: a Metrics carried THROUGH a step must be
pre-seeded with every name the step records (names are treedef aux data, so
a growing name set would retrace). ``jax.eval_shape`` on the step discovers
the full name set without compiling anything.
"""

import functools
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor import (
    JsonlSink,
    Metrics,
    SCHEMA_VERSION,
    global_norm,
    gpt_analytic_flops_per_token,
    json_record,
    phase_breakdown,
    pipeline_bubble_fraction,
    read_jsonl,
    span,
    span_function,
    train_metrics,
)

MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")
needs_mesh = pytest.mark.skipif(
    not MESH_OK,
    reason="mesh programs need jax.shard_map/lax.axis_size (graft jax)")


# compilation count of a jitted callable (None if this jax can't say) —
# ONE implementation, shared with engine.compile_counts and the
# recompile_guard sentinel (tests/test_analyze.py pins its semantics)
from apex_tpu.analyze.recompile import jit_cache_size as _cache_size  # noqa: E402,E501


# ---------------------------------------------------------------------------
# Metrics pytree


def test_metrics_record_accumulate_merge():
    m = Metrics({"loss": 2.0})
    m = m.record(grad_norm=3.0)
    m = m.accumulate(overflow_total=1.0).accumulate(overflow_total=1.0)
    m = m.merge(Metrics({"loss": 1.0}))
    d = m.as_dict()
    assert d == {"grad_norm": 3.0, "loss": 1.0, "overflow_total": 2.0}
    # names sorted -> treedef stable regardless of insertion order
    assert m.names() == ("grad_norm", "loss", "overflow_total")
    a = Metrics({"x": 1.0, "y": 2.0})
    b = Metrics({"y": 2.0}).record(x=1.0)
    assert (jax.tree_util.tree_structure(a)
            == jax.tree_util.tree_structure(b))


def test_metrics_rejects_nonscalar():
    with pytest.raises(ValueError):
        Metrics({"v": jnp.ones((3,))})


def test_metrics_is_a_pytree():
    m = Metrics({"a": 1.0, "b": 2.0})
    doubled = jax.tree_util.tree_map(lambda x: 2 * x, m)
    assert doubled.as_dict() == {"a": 2.0, "b": 4.0}


def test_global_norm_matches_reference():
    tree = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": -jnp.ones((4,), jnp.bfloat16)}
    want = np.sqrt(sum((np.asarray(x, np.float32) ** 2).sum()
                       for x in jax.tree_util.tree_leaves(tree)))
    np.testing.assert_allclose(float(global_norm(tree)), want, rtol=1e-6)
    assert float(global_norm({})) == 0.0


def test_metric_pytree_under_jit_and_donation():
    """The tentpole contract: metrics threaded like the scaler state —
    grad norm matches a reference computation, carried counters survive
    donation, the instrumented step computes the same params as the
    uninstrumented one, and 5 steps reuse ONE compilation."""

    def loss_fn(p, x):
        return jnp.mean((x @ p["w"] + p["b"]) ** 2)

    def update(p, x):
        loss, grads = jax.value_and_grad(loss_fn)(p, x)
        new_p = jax.tree_util.tree_map(lambda a, g: a - 0.1 * g, p, grads)
        return new_p, loss, grads

    @jax.jit
    def plain_step(p, x):
        new_p, _, _ = update(p, x)
        return new_p

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, m, x):
        new_p, loss, grads = update(p, x)
        m = train_metrics(m, loss=loss, grads=grads, params=p)
        return new_p, m.accumulate(steps=1.0)

    def init():
        return {"w": jnp.ones((4, 2)), "b": jnp.zeros((2,))}

    x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    ref_norm = float(global_norm(jax.grad(loss_fn)(init(), x)))

    # pre-seed every recorded name so the carry treedef never changes
    p, m = init(), Metrics({"steps": 0.0, "loss": 0.0, "grad_norm": 0.0,
                            "param_norm": 0.0})
    p_plain = init()
    for i in range(5):
        p, m = step(p, m, x)
        p_plain = plain_step(p_plain, x)
        if i == 0:
            np.testing.assert_allclose(m.as_dict()["grad_norm"], ref_norm,
                                       rtol=1e-5)
    d = m.as_dict()
    assert d["steps"] == 5.0
    assert d["loss"] >= 0.0 and d["param_norm"] > 0.0
    # monitoring does not change the training math
    np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(p_plain["w"]),
                               rtol=1e-6)
    # ... and adds ZERO extra compilations: one cache entry after 5 steps
    n = _cache_size(step)
    if n is not None:
        assert n == 1, f"metrics threading retraced: {n} compilations"


# ---------------------------------------------------------------------------
# AMP scaler wiring


def test_scaler_metrics_overflow_steps_recorded():
    from apex_tpu.amp import LossScaler

    scaler = LossScaler("dynamic", init_scale=2.0 ** 8, hysteresis=1)

    @jax.jit
    def step(state, m, g):
        grads, found_inf = scaler.unscale({"g": g}, state)
        state, _skip = scaler.update_scale(state, found_inf)
        return state, LossScaler.metrics(state, found_inf, m)

    state = scaler.init_state()
    m = Metrics({"loss_scale": 0.0, "overflow": 0.0,
                 "overflow_total": 0.0, "skipped_total": 0.0})
    good = jnp.ones((4,)) * 2.0 ** 8
    bad = jnp.array([jnp.inf, 1.0, 1.0, 1.0]) * 2.0 ** 8
    state, m = step(state, m, good)
    assert m.as_dict()["overflow"] == 0.0
    state, m = step(state, m, bad)
    d = m.as_dict()
    assert d["overflow"] == 1.0
    assert d["overflow_total"] == 1.0 and d["skipped_total"] == 1.0
    assert d["loss_scale"] == 2.0 ** 7  # backed off after the overflow
    state, m = step(state, m, good)
    d = m.as_dict()
    assert d["overflow"] == 0.0 and d["overflow_total"] == 1.0
    n = _cache_size(step)
    if n is not None:
        assert n == 1


# ---------------------------------------------------------------------------
# JSONL sink


def test_jsonl_schema_roundtrip(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    m = Metrics({"loss": 1.25, "grad_norm": 3.5})
    with JsonlSink(path, buffer_steps=2, log_every=3) as sink:
        for i in range(5):
            sink.write(step=i, metrics=m, lr=0.1)
    recs = list(read_jsonl(path))
    assert len(recs) == 5
    for i, r in enumerate(recs):
        assert r["schema"] == SCHEMA_VERSION
        assert r["step"] == i and r["loss"] == 1.25
        assert r["grad_norm"] == 3.5 and r["lr"] == 0.1
        assert "ts" in r
    # json_record shares the same schema stamp
    assert json.loads(json_record(metric="x"))["schema"] == SCHEMA_VERSION


def test_jsonl_append_after_crash(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        sink.write(step=0, metrics={"loss": 1.0})
        sink.write(step=1, metrics={"loss": 2.0})
    # crash mid-write: a partial record with no terminating newline
    with open(path, "a") as f:
        f.write('{"schema": 1, "step": 2, "loss":')
    # the partial tail is skipped, earlier records survive
    recs = list(read_jsonl(path))
    assert [r["step"] for r in recs] == [0, 1]
    # a restarted job appends to the same file; the fragment is terminated
    with JsonlSink(path, buffer_steps=1) as sink:
        sink.write(step=2, metrics={"loss": 3.0})
    recs = list(read_jsonl(path))
    assert [r["step"] for r in recs] == [0, 1, 2]
    with pytest.raises(json.JSONDecodeError):
        list(read_jsonl(path, strict=True))  # the fragment is now interior


def test_jsonl_sink_buffers_until_flush(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, buffer_steps=100)
    sink.write(step=0, metrics={"x": 1.0})
    assert not os.path.exists(path)  # buffered, nothing written yet
    sink.flush()
    assert len(list(read_jsonl(path))) == 1
    sink.close()


# ---------------------------------------------------------------------------
# logging satellites


def test_metrics_logger_child_exists():
    from apex_tpu import get_logger

    logger = get_logger("apex_tpu.monitor")
    assert logger.metrics.name == "apex_tpu.monitor.metrics"


def test_get_logger_no_duplicate_handlers():
    import logging

    from apex_tpu import _logging

    root = logging.getLogger("apex_tpu")
    _logging.get_logger("apex_tpu.a")
    before = len(root.handlers)
    # simulate a re-import: the module-level guard set is reset, but the
    # handler scan must still find the installed handler
    _logging._configured_roots.clear()
    _logging.get_logger("apex_tpu.b")
    assert len(root.handlers) == before
    rank_handlers = [h for h in root.handlers
                     if type(h.formatter).__name__ == "RankInfoFormatter"]
    assert len(rank_handlers) == 1


def test_log_level_env_var(monkeypatch):
    import logging

    from apex_tpu import _logging

    monkeypatch.setenv("APEX_TPU_LOG_LEVEL", "debug")
    _logging._configured_roots.discard("apex_tpu_lvltest")
    logger = _logging.get_logger("apex_tpu_lvltest")
    assert logging.getLogger("apex_tpu_lvltest").level == logging.DEBUG
    assert logger.metrics.name == "apex_tpu_lvltest.metrics"
    # garbage level is ignored, not fatal
    monkeypatch.setenv("APEX_TPU_LOG_LEVEL", "NOT_A_LEVEL")
    _logging._configured_roots.discard("apex_tpu_lvltest2")
    _logging.get_logger("apex_tpu_lvltest2")


# ---------------------------------------------------------------------------
# spans


def test_span_names_visible_in_hlo_op_table():
    """Static check (no profiler): ops traced under monitor.span carry the
    span name as their pyprof layer path — the same join key the measured
    table and the trace viewer use."""
    from apex_tpu.pyprof import op_table

    def f(x, w):
        with span("fwd"):
            h = jnp.tanh(x @ w)
        with span("opt"):
            return jnp.sum(h * h)

    rows = op_table(f, jnp.ones((64, 32)), jnp.ones((32, 16)))
    # jax version differences add jit(...) wrapper components; the span
    # names must appear as path components either way
    comps = {c for r in rows for c in r["scope"].split("/")}
    assert "fwd" in comps, comps
    assert "opt" in comps, comps


def test_span_function_decorator():
    from apex_tpu.pyprof import op_table

    @span_function(name="layer0")
    def layer(x, w):
        return x @ w

    rows = op_table(lambda x, w: jnp.sum(layer(x, w)),
                    jnp.ones((16, 8)), jnp.ones((8, 8)))
    assert any("layer0" in r["scope"].split("/") for r in rows)


@pytest.mark.slow
def test_span_phases_in_measured_table():
    """Profiler-trace check: spans become measured phases (the trace-join
    half of the capability). Slow: runs jax.profiler."""
    from apex_tpu.monitor import step_report

    def loss(w, x):
        with span("fwd"):
            return jnp.mean((jnp.tanh(x @ w["a"]) @ w["b"]) ** 2)

    def stepf(w, x):
        with span("bwd"):
            g = jax.grad(loss)(w, x)
        with span("opt"):
            return jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, w, g)

    w = {"a": jnp.ones((128, 256)), "b": jnp.ones((256, 64))}
    x = jnp.ones((32, 128))
    rep = step_report(stepf, w, x, steps=3, peak_flops=1e12)
    assert rep["step_time_ms"] > 0 and 0 < rep["coverage_pct"] <= 100
    phases = rep["phase_ms"]
    assert any(k.startswith("bwd") for k in phases), phases
    assert any(k.startswith("opt") for k in phases), phases
    # the span inside the differentiated loss rolls up to its own name
    # (jvp/transpose wrappers peeled), under whichever outer span it nests
    assert any("fwd" in k or k.startswith("bwd") for k in phases), phases


# ---------------------------------------------------------------------------
# report helpers


def test_phase_breakdown_unwraps_ad_wrappers():
    """Spans traced under jax.grad surface as jvp(name)/transpose(jvp(name))
    scope components; the phase rollup must peel the AD wrappers so one
    logical phase stays one bucket."""
    measured = {"rows": [
        {"scope": "jit(main)/fwd", "time_ms": 1.0},
        {"scope": "jit(main)/jvp(fwd)", "time_ms": 2.0},
        {"scope": "jit(main)/transpose(jvp(fwd))", "time_ms": 3.0},
        {"scope": "opt", "time_ms": 4.0},
        {"scope": "jit(main)", "time_ms": 0.5},
    ]}
    assert phase_breakdown(measured) == {
        "fwd": 6.0, "opt": 4.0, "<no-scope>": 0.5}


def test_sink_log_every_enables_metrics_logger(tmp_path):
    """log_every is an explicit opt-in: the sink must raise the metrics
    child logger to INFO when the hierarchy default would swallow it."""
    import logging

    child = logging.getLogger("apex_tpu.monitor.metrics")
    old = child.level
    try:
        child.setLevel(logging.NOTSET)
        with JsonlSink(str(tmp_path / "m.jsonl"), buffer_steps=1,
                       log_every=1) as sink:
            sink.write(step=0, metrics={"loss": 1.0})
        assert child.isEnabledFor(logging.INFO)
    finally:
        child.setLevel(old)


def test_pipeline_bubble_fraction():
    assert pipeline_bubble_fraction(8, 4) == pytest.approx(3 / 11)
    assert pipeline_bubble_fraction(8, 1) == 0.0
    with pytest.raises(ValueError):
        pipeline_bubble_fraction(0, 4)


def test_gpt_analytic_flops_per_token():
    # 6N + causal-attention term, the constant bench.py divides by
    assert gpt_analytic_flops_per_token(100, 2, 8, 16) == \
        6 * 100 + 6 * 2 * 8 * 16


def test_mfu_check_compile_only():
    from apex_tpu.monitor import mfu_check

    def f(x, w):
        return jnp.sum(x @ w)

    analytic = 2 * 64 * 32 * 16
    res = mfu_check(f, jnp.ones((64, 32)), jnp.ones((32, 16)),
                    analytic_flops=analytic)
    assert res["hlo_flops"] > 0
    assert 0.9 < res["hlo_over_analytic"] < 1.1
    assert res["wire_bytes"] == 0.0  # single-device program


# ---------------------------------------------------------------------------
# wire-byte model ↔ accounting pricer agreement (mesh-free: the pricer reads
# HLO text, so synthetic programs pin the exact formulas the DDP metrics use)


def _hlo_line(op, shape, groups=8):
    g = "{{" + ",".join(str(i) for i in range(groups)) + "}}"
    return (f"  %r = {shape} {op}({shape} %x), replica_groups={g}, "
            f"to_apply=%add")


def test_allreduce_wire_model_matches_pricer_uncompressed():
    from apex_tpu.comm import allreduce_wire_bytes, collective_report

    n, world = 4096, 8
    rep = collective_report(_hlo_line("all-reduce", f"f32[{n}]"))
    assert rep.counts["all-reduce"] == 1
    assert rep.wire_bytes == pytest.approx(
        allreduce_wire_bytes(n, 4, world, None))
    rep16 = collective_report(_hlo_line("all-reduce", f"bf16[{n}]"))
    assert rep16.wire_bytes == pytest.approx(
        allreduce_wire_bytes(n, 2, world, None))
    assert allreduce_wire_bytes(n, 4, 1, None) == 0.0


def test_allreduce_wire_model_matches_pricer_compressed():
    """The compressed model must price exactly the op sequence
    compressed_allreduce emits: all_to_all(codes) + all_to_all(scales) +
    all_gather(codes) + all_gather(scales), padded to block·world."""
    from apex_tpu.comm import (
        CompressionConfig,
        allreduce_wire_bytes,
        collective_report,
    )
    from apex_tpu.comm.quantize import padded_size

    n, world = 5000, 8
    cfg = CompressionConfig(policy="int8", block_size=256, min_elements=256)
    size = padded_size(n, cfg.block_size * world)
    nb = size // cfg.block_size
    hlo = "\n".join([
        _hlo_line("all-to-all", f"s8[{size}]"),
        _hlo_line("all-to-all", f"f32[{nb}]"),
        _hlo_line("all-gather", f"s8[{size}]"),
        _hlo_line("all-gather", f"f32[{nb}]"),
    ])
    rep = collective_report(hlo)
    assert rep.counts["all-to-all"] == 2 and rep.counts["all-gather"] == 2
    assert rep.wire_bytes == pytest.approx(
        allreduce_wire_bytes(n, 4, world, cfg))
    # small buffers ride the fp32 psum path
    small = cfg.min_elements - 1
    assert allreduce_wire_bytes(small, 4, world, cfg) == pytest.approx(
        collective_report(
            _hlo_line("all-reduce", f"f32[{small}]")).wire_bytes)


def test_psum_scatter_wire_model_matches_pricer():
    from apex_tpu.comm import (
        CompressionConfig,
        collective_report,
        psum_scatter_wire_bytes,
    )
    from apex_tpu.comm.quantize import padded_size

    n, world = 4100, 8
    # uncompressed: reduce-scatter result is the k-element shard
    k = -(-n // world)
    rep = collective_report(_hlo_line("reduce-scatter", f"f32[{k}]"))
    assert rep.wire_bytes == pytest.approx(
        psum_scatter_wire_bytes(n, 4, world, None))
    # compressed: one all_to_all pass of codes + scales
    cfg = CompressionConfig(policy="int8", block_size=256, min_elements=256)
    kb = -(-(-(-n // world)) // cfg.block_size) * cfg.block_size
    size = max(kb * world, padded_size(n, cfg.block_size * world))
    hlo = "\n".join([
        _hlo_line("all-to-all", f"s8[{size}]"),
        _hlo_line("all-to-all", f"f32[{size // cfg.block_size}]"),
    ])
    assert collective_report(hlo).wire_bytes == pytest.approx(
        psum_scatter_wire_bytes(n, 4, world, cfg,
                                shard_multiple=cfg.block_size))


def test_all_gather_wire_model_matches_pricer():
    from apex_tpu.comm import all_gather_wire_bytes, collective_report

    n, world = 4096, 8
    rep = collective_report(_hlo_line("all-gather", f"f32[{n}]"))
    assert rep.wire_bytes == pytest.approx(
        all_gather_wire_bytes(n, 4, world))


# ---------------------------------------------------------------------------
# mesh integration: DDP-reported bytes vs the compiled HLO; the compile gate
# on the instrumented GPT fixture (the CI/tooling acceptance criterion)


def _gpt_bits():
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        init_gpt_params,
    )

    cfg = GPTConfig(vocab_size=256, max_seq=64, hidden=128, num_layers=2,
                    num_heads=2, dtype=jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jnp.zeros((8, 64), jnp.int32)
    return cfg, gpt_loss, params, tok


@needs_mesh
@pytest.mark.parametrize("policy", ["none", "int8"])
def test_ddp_reported_bytes_match_accounting(policy):
    """DDP's in-metrics per-bucket bytes must agree with what
    comm.accounting prices off the SAME compiled HLO — the model is honest
    because both sides see the identical program."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.comm import CompressionConfig, collective_report
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.parallel.mesh import build_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    cfg, gpt_loss, params, tok = _gpt_bits()
    comp = None if policy == "none" else CompressionConfig(
        policy="int8", block_size=256, min_elements=256)
    ddp = DistributedDataParallel(compression=comp,
                                  allreduce_always_fp32=True)

    def step(p, t, y):
        g = jax.grad(lambda p: gpt_loss(p, t, y, cfg))(ddp.replicate(p))
        return ddp.average_gradients(g, metrics=Metrics())

    specs = jax.tree_util.tree_map(lambda _: P(), params)
    compiled = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(specs, P("dp"), P("dp")),
        out_specs=(specs, P()),
        check_vma=False,
    )).lower(params, tok, tok).compile()
    _, metrics = compiled(params, tok, tok)
    d = metrics.as_dict()
    reported = d["comm_wire_bytes"]
    buckets = sum(v for k, v in d.items()
                  if k.startswith("comm_bucket") and k.endswith("_bytes"))
    assert buckets == pytest.approx(reported)
    priced = collective_report(compiled).wire_bytes
    assert reported == pytest.approx(priced, rel=1e-3), (reported, priced)
    if policy == "int8":
        assert d["comm_compression_ratio"] > 3.5
    else:
        assert d["comm_compression_ratio"] == pytest.approx(1.0)


@needs_mesh
def test_instrumented_gpt_step_compiles_once_and_sinks_jsonl(tmp_path):
    """The acceptance criterion: 5 monitored GPT steps produce a JSONL
    where every record carries step/loss/grad-norm/loss-scale/overflow/
    comm-bytes, the comm bytes match accounting on the compiled HLO, and
    the compile count is 1 with monitoring on AND off."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.amp import LossScaler
    from apex_tpu.comm import collective_report
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.parallel.mesh import build_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)
    cfg, gpt_loss, params, tok = _gpt_bits()
    ddp = DistributedDataParallel()
    scaler = LossScaler("dynamic", init_scale=2.0 ** 4)
    opt = FusedAdam(lr=1e-3)
    specs = jax.tree_util.tree_map(lambda _: P(), params)

    def build(monitored):
        def body(p, s, scaler_state, m, t, y):
            loss, g = jax.value_and_grad(
                lambda p: scaler.scale_loss(
                    gpt_loss(p, t, y, cfg), scaler_state))(ddp.replicate(p))
            if monitored:
                g, m = ddp.average_gradients(g, metrics=m)
            else:
                g = ddp.average_gradients(g)
            g, found_inf = scaler.unscale(g, scaler_state)
            new_scaler, skip = scaler.update_scale(scaler_state, found_inf)
            updates, new_s = opt.update(g, s, p)
            new_p = jax.tree_util.tree_map(lambda a, u: a + u, p, updates)
            keep = lambda new, old: jax.tree_util.tree_map(  # noqa: E731
                lambda a, b: jnp.where(skip, b, a), new, old)
            p, s = keep(new_p, p), keep(new_s, s)
            unscaled = loss / scaler_state.loss_scale
            if monitored:
                m = train_metrics(m, loss=unscaled, grads=g)
                m = LossScaler.metrics(new_scaler, found_inf, m)
            return p, s, new_scaler, m, unscaled

        sharded = jax.shard_map(
            body, mesh=mesh,
            in_specs=(specs, P(), P(), P(), P("dp"), P("dp")),
            out_specs=(specs, P(), P(), P(), P()),
            check_vma=False)
        return jax.jit(sharded, donate_argnums=(0, 1, 2, 3))

    for monitored in (True, False):
        step = build(monitored)
        p = jax.tree_util.tree_map(jnp.copy, params)
        s = opt.init(p)
        scaler_state = scaler.init_state()
        if monitored:
            # discover the step's full metric-name set WITHOUT compiling,
            # then pre-seed so the carried treedef is stable from step 0
            out_shape = jax.eval_shape(step, p, s, scaler_state, Metrics(),
                                       tok, tok)
            m = Metrics({k: 0.0 for k in out_shape[3].names()})
        else:
            m = Metrics()
        compiled = step.lower(p, s, scaler_state, m, tok, tok).compile()
        path = str(tmp_path / f"gpt_{monitored}.jsonl")
        with JsonlSink(path, buffer_steps=2) as sink:
            for i in range(5):
                p, s, scaler_state, m, loss = step(p, s, scaler_state, m,
                                                   tok, tok)
                if monitored:
                    sink.write(step=i, metrics=m)
        n = _cache_size(step)
        if n is not None:
            assert n == 1, f"monitored={monitored}: {n} compilations"
        if not monitored:
            continue
        recs = list(read_jsonl(path))
        assert len(recs) == 5
        priced = collective_report(compiled).wire_bytes
        for r in recs:
            for field in ("step", "loss", "grad_norm", "loss_scale",
                          "overflow", "comm_wire_bytes"):
                assert field in r, (field, sorted(r))
            assert np.isfinite(r["loss"]) and r["grad_norm"] > 0
            assert r["loss_scale"] == 2.0 ** 4 and r["overflow"] == 0.0
            # DDP-reported bytes == accounting on the same HLO (the grad
            # allreduce dominates; scalar psums ride inside the tolerance)
            assert r["comm_wire_bytes"] == pytest.approx(priced, rel=1e-3)


@needs_mesh
def test_zero_adam_metrics_shard_norms():
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel.mesh import build_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=1, pp=1, sp=1)
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (13, 7)),
              "b": jax.random.normal(jax.random.PRNGKey(1), (5,))}
    grads = jax.tree_util.tree_map(lambda x: 0.1 * jnp.ones_like(x), params)
    opt = DistributedFusedAdam(lr=1e-2)

    def run(p, g):
        state = opt.init(p)
        p2, state, m = opt.step(g, state, p, metrics=Metrics())
        return p2, m

    p_specs = jax.tree_util.tree_map(lambda _: P(), params)
    got, m = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(p_specs, p_specs),
        out_specs=(p_specs, P()),
        check_vma=False,
    ))(params, grads)
    d = m.as_dict()
    # every rank contributed the same grads; reduce-scatter averages them
    want = float(global_norm(grads))
    np.testing.assert_allclose(d["grad_norm"], want, rtol=1e-5)
    assert d["param_norm"] > 0 and d["update_norm"] > 0
    assert d["comm_wire_bytes"] > 0

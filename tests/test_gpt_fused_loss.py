"""Flagship GPT: fused-loss path == unfused logits+CE path; remat policies."""

import dataclasses

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)

CFG = GPTConfig(vocab_size=96, max_seq=32, hidden=64, num_layers=2,
                num_heads=4, dtype=jnp.float32)


def _loss_and_grads(cfg, tp=1):
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    mesh = build_mesh(tp=tp, pp=1, sp=1)
    specs = gpt_param_specs(cfg)
    key = jax.random.PRNGKey(1)
    tok = jax.random.randint(key, (4, cfg.max_seq), 0, cfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)

    def loss_fn(p):
        def body(p, tok, tgt):
            loss = gpt_loss(p, tok, tgt, cfg)
            return jax.lax.psum(loss, ("tp",)) / tp

        return jax.shard_map(body, mesh=mesh, in_specs=(specs, P(), P()),
                             out_specs=P())(p, tok, tgt)

    # jit the whole grad program: eager shard_map dispatches op-by-op
    # through the 8-device SPMD interpreter (~30x slower on this box) and
    # never hits the persistent compile cache
    return jax.jit(jax.value_and_grad(loss_fn))(params)


def _assert_tree_close(a, b, rtol, atol):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        x, y, rtol=rtol, atol=atol), a, b)


def test_fused_loss_matches_unfused_tied():
    lf, gf = _loss_and_grads(dataclasses.replace(CFG, fused_loss=True))
    lu, gu = _loss_and_grads(dataclasses.replace(CFG, fused_loss=False))
    np.testing.assert_allclose(lf, lu, rtol=1e-5, atol=1e-6)
    _assert_tree_close(gf, gu, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_fused_loss_matches_unfused_untied_tp2():
    cfg = dataclasses.replace(CFG, tie_embeddings=False)
    lf, gf = _loss_and_grads(dataclasses.replace(cfg, fused_loss=True), tp=2)
    lu, gu = _loss_and_grads(dataclasses.replace(cfg, fused_loss=False), tp=2)
    np.testing.assert_allclose(lf, lu, rtol=1e-5, atol=1e-6)
    _assert_tree_close(gf, gu, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_remat_dots_policy_matches_full():
    lf, gf = _loss_and_grads(dataclasses.replace(CFG, remat_policy="dots"))
    lu, gu = _loss_and_grads(dataclasses.replace(CFG, remat_policy="full"))
    np.testing.assert_allclose(lf, lu, rtol=1e-5, atol=1e-6)
    _assert_tree_close(gf, gu, rtol=1e-4, atol=1e-5)


def test_remat_dots_attn_policy_matches_full():
    """dots_attn (save dots + the named flash-attention outputs — spares
    backward the O(s^2) attention recompute) is numerically identical to
    full remat."""
    lf, gf = _loss_and_grads(dataclasses.replace(CFG,
                                                 remat_policy="dots_attn"))
    lu, gu = _loss_and_grads(dataclasses.replace(CFG, remat_policy="full"))
    np.testing.assert_allclose(lf, lu, rtol=1e-5, atol=1e-6)
    _assert_tree_close(gf, gu, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_remat_off_matches_on():
    lf, gf = _loss_and_grads(dataclasses.replace(CFG, remat=False))
    lu, gu = _loss_and_grads(dataclasses.replace(CFG, remat=True))
    np.testing.assert_allclose(lf, lu, rtol=1e-5, atol=1e-6)
    _assert_tree_close(gf, gu, rtol=1e-4, atol=1e-5)


def test_dots_attn_policy_skips_flash_fwd_replay():
    """The property dots_attn exists for: with o AND lse saved (the flash
    custom_vjp's computed residuals), the backward no longer replays the
    forward kernel. Counted on the grad jaxpr: dots = fwd + replay + 2 bwd
    kernels = 4 pallas calls; dots_attn = 3 (reviewer-verified that naming
    the output alone does NOT achieve this — lse must be saved too)."""
    from apex_tpu.ops.attention import flash_attention

    q = jnp.ones((1, 2, 256, 32), jnp.float32)

    def block(x):
        o = flash_attention(x, x, x, causal=True, use_pallas=True,
                            interpret=True)
        return (o * x).sum()

    def n_pallas(policy):
        f = jax.checkpoint(block, policy=policy)
        return str(jax.make_jaxpr(jax.grad(f))(q)).count("pallas_call")

    from apex_tpu.transformer.testing.standalone_gpt import dots_attn_policy

    dots = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    assert n_pallas(dots) == 4
    assert n_pallas(dots_attn_policy()) == 3  # the REAL installed policy

"""Megakernel decode + fused optimizer update tail (ROADMAP item 4).

Two fused hot paths, each pinned against the per-op program it replaces:

* ``serve.megakernel`` — the per-layer fused Pallas decode block must
  agree with ``decode.gpt_decode_step`` (the pure-JAX/paged-kernel
  oracle): fp32 logits + written pools within fp tolerance, int8 pools
  with IDENTICAL codes, and — the acceptance gate — the engine's streams
  equal between ``megakernel="on"`` and ``"off"`` (greedy AND same-key
  sampled, speculative included) with the compile-count gate intact.
* ``ops.fused_update`` — the Adam/LAMB tail kernels must match the
  ``upd`` closure math the ZeRO optimizers ran before fusion, including
  the padding edges (leaves far from tile multiples) and the LAMB
  trust-ratio composition; ``FusedAdam(fused_tail=...)`` steps must agree
  end-to-end.

All stock-jax-safe (interpret-mode Pallas, no mesh); the AOT Mosaic
lowering rows live in ``tests/test_tpu_lowering.py``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops.fused_update import (
    adam_tail_reference,
    fused_adam_tail,
    fused_lamb_tail,
    lamb_tail_reference,
    resolve_fused,
)
from apex_tpu.serve import (
    InferenceEngine,
    KVCacheConfig,
    Request,
    SamplingConfig,
    ServeConfig,
    init_kv_cache,
    megakernel_ok,
)
from apex_tpu.serve.decode import (
    gpt_decode_step,
    gpt_prefill,
    gpt_verify_step,
)
from apex_tpu.serve.megakernel import (
    default_tiles,
    fused_layer_decode,
    fused_live_bytes,
    gpt_decode_step_fused,
    gpt_verify_step_fused,
    layer_weight_bytes,
    megakernel_refusal,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

CFG = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)

REQS = [
    Request("a", [1, 2, 3, 4, 5], max_new_tokens=6),
    Request("b", [7, 8, 9], max_new_tokens=4),
    Request("c", list(range(10, 22)), max_new_tokens=5),
]


def _engine(megakernel, sampling=None, **kw):
    scfg = ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                       megakernel=megakernel,
                       sampling=sampling or SamplingConfig(), **kw)
    return InferenceEngine(PARAMS, CFG, scfg)


def _prefilled(kv, prompts):
    """Prefill ``prompts`` into a fresh cache, one slot per prompt, block
    rows carved consecutively; returns (cache, block_tables)."""
    bpslot = kv.num_blocks // len(prompts)
    rows = np.arange(len(prompts) * bpslot,
                     dtype=np.int32).reshape(len(prompts), bpslot)
    bt = jnp.asarray(rows)
    cache = init_kv_cache(kv)
    for s, pr in enumerate(prompts):
        toks = jnp.zeros((16,), jnp.int32).at[:len(pr)].set(jnp.asarray(pr))
        cache, _ = gpt_prefill(PARAMS, toks, jnp.int32(len(pr)), cache,
                               bt[s], CFG, kv)
    return cache, bt


# ---------------------------------------------------------------------------
# fused decode step vs the per-op oracle


@pytest.mark.parametrize("kv_mode", ["none", "int8", "int4"])
def test_fused_decode_matches_unfused(kv_mode):
    """Multi-step decode: the fused per-layer block produces the same
    logits AND the same written pools as gpt_decode_step — fp32 within fp
    tolerance, int8/int4 codes bitwise (both paths quantize identical
    values through the same codec; the int4 path dequantizes nibble-packed
    codes + bf16 group scales IN kernel). Includes an inactive slot
    (ctx 0): junk but finite logits, no pool writes."""
    quantized = kv_mode != "none"
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=24, block_size=4, dtype=jnp.float32,
                       quantized=quantized,
                       bits=4 if kv_mode == "int4" else 8)
    cache, bt = _prefilled(kv, [[3, 14, 15, 92, 6], [7, 8, 9],
                                [1]])  # slot 2 then marked inactive
    cache_f = jax.tree.map(lambda a: a, cache)
    lens = np.array([5, 3, 0], np.int32)
    last = np.array([10, 20, 0], np.int32)
    active = jnp.asarray([True, True, False])
    for _ in range(4):
        cache, lg_u = gpt_decode_step(
            PARAMS, jnp.asarray(last), jnp.asarray(lens), active, cache,
            bt, CFG, kv)
        cache_f, lg_f = gpt_decode_step_fused(
            PARAMS, jnp.asarray(last), jnp.asarray(lens), active, cache_f,
            bt, CFG, kv)
        np.testing.assert_allclose(np.asarray(lg_f[:2]),
                                   np.asarray(lg_u[:2]), atol=5e-5)
        assert np.isfinite(np.asarray(lg_f)).all()
        for key, pool in cache.items():
            if quantized and key in ("k", "v"):
                np.testing.assert_array_equal(np.asarray(pool),
                                              np.asarray(cache_f[key]))
            else:
                np.testing.assert_allclose(np.asarray(cache_f[key]),
                                           np.asarray(pool), atol=1e-5)
        last = np.asarray(jnp.argmax(lg_u, -1))
        lens = lens + np.array([1, 1, 0], np.int32)


@pytest.mark.parametrize("kv_mode", ["none", "int8", "int4"])
def test_fused_verify_matches_unfused(kv_mode):
    """Multi-round VERIFY parity: gpt_verify_step_fused (q=k+1 rows per
    slot, causal-within-window fold in-kernel) produces the same
    valid-row logits AND the same written pools as the unfused
    gpt_verify_step — fp32 within fp tolerance, int8/int4 codes bitwise.
    Rounds 2-3 accept FEWER tokens than were fed (rejected drafts), so
    the stale K/V those rows wrote must be masked by the next window and
    overwritten identically on both paths — the no-rollback contract."""
    quantized = kv_mode != "none"
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=24, block_size=4, dtype=jnp.float32,
                       quantized=quantized,
                       bits=4 if kv_mode == "int4" else 8)
    cache, bt = _prefilled(kv, [[3, 14, 15, 92, 6], [7, 8, 9], [1]])
    cache_f = jax.tree.map(lambda a: a, cache)
    lens = np.array([5, 3, 0], np.int32)
    active = jnp.asarray([True, True, False])
    rng = np.random.default_rng(7)
    fed = rng.integers(1, 96, (3, 3)).astype(np.int32)
    for n_fed, accept in [(np.array([3, 2, 0], np.int32), (1, 2)),
                          (np.array([2, 3, 0], np.int32), (2, 1)),
                          (np.array([3, 1, 0], np.int32), (3, 1))]:
        cache, lg_u = gpt_verify_step(
            PARAMS, jnp.asarray(fed), jnp.asarray(lens),
            jnp.asarray(n_fed), active, cache, bt, CFG, kv)
        cache_f, lg_f = gpt_verify_step_fused(
            PARAMS, jnp.asarray(fed), jnp.asarray(lens),
            jnp.asarray(n_fed), active, cache_f, bt, CFG, kv)
        valid = np.asarray(active)[:, None] & (
            np.arange(3)[None, :] < n_fed[:, None])
        np.testing.assert_allclose(np.asarray(lg_f)[valid],
                                   np.asarray(lg_u)[valid], atol=5e-5)
        assert np.isfinite(np.asarray(lg_f)).all()
        for key, pool in cache.items():
            if quantized and key in ("k", "v"):
                np.testing.assert_array_equal(np.asarray(pool),
                                              np.asarray(cache_f[key]))
            else:
                np.testing.assert_allclose(np.asarray(cache_f[key]),
                                           np.asarray(pool), atol=1e-5)
        # accept a PREFIX of what was fed (possibly rejecting drafts):
        # only the accepted count advances the context
        lens = lens + np.array([accept[0], accept[1], 0], np.int32)
        fed = rng.integers(1, 96, (3, 3)).astype(np.int32)


def test_fused_verify_single_row_matches_decode():
    """q=1 verify (no drafts proposed) degenerates to the decode step:
    same logits, same pools — the fused block's q generalization is a
    strict superset of the PR-8 q=1 kernel."""
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=24, block_size=4, dtype=jnp.float32)
    cache, bt = _prefilled(kv, [[3, 14, 15], [7, 8, 9, 10]])
    cache_v = jax.tree.map(lambda a: a, cache)
    lens = jnp.asarray([3, 4], jnp.int32)
    active = jnp.asarray([True, True])
    last = jnp.asarray([10, 20], jnp.int32)
    cache, lg_d = gpt_decode_step_fused(
        PARAMS, last, lens, active, cache, bt, CFG, kv)
    cache_v, lg_v = gpt_verify_step_fused(
        PARAMS, last[:, None], lens, jnp.asarray([1, 1], jnp.int32),
        active, cache_v, bt, CFG, kv)
    np.testing.assert_array_equal(np.asarray(lg_v[:, 0]), np.asarray(lg_d))
    for key, pool in cache.items():
        np.testing.assert_array_equal(np.asarray(pool),
                                      np.asarray(cache_v[key]))


def test_tile_validation_and_multi_tile_parity():
    """Tile-boundary edges: a count that does not divide its dim refuses
    loudly with the valid counts listed; compiled Mosaic additionally
    refuses lane-misaligned tiles; explicit ``(1, 1, 1)`` is the SAME
    program as ``tiles=None`` here (default_tiles resolves to full
    residency — the PR-8 path — bitwise); multi-tile streaming agrees
    with full residency (column tiles keep contractions whole, only the
    fc2 row tiles reassociate the fp32 ffn accumulation)."""
    from apex_tpu.serve.megakernel import _check_tiles

    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=8, block_size=8, dtype=jnp.float32)
    cache, bt = _prefilled(kv, [[5, 6, 7], [11, 12]])
    x = jax.random.normal(jax.random.PRNGKey(3), (2, CFG.hidden))
    lp = jax.tree.map(lambda a: a[0], PARAMS["layers"])
    cl = {k: v[0] for k, v in cache.items()}
    lens = jnp.asarray([3, 2], jnp.int32)
    with pytest.raises(ValueError, match="does not divide"):
        fused_layer_decode(x, lp, cl, CFG, kv, bt, lens, tiles=(5, 1, 1))
    with pytest.raises(ValueError, match="lane-aligned"):
        _check_tiles(CFG, (2, 1, 1), True)  # 96 / 2 = 48: not 128-aligned
    assert default_tiles(CFG, kv, compiled=False) == (1, 1, 1)
    base = fused_layer_decode(x, lp, cl, CFG, kv, bt, lens,
                              tiles=(1, 1, 1))
    auto = fused_layer_decode(x, lp, cl, CFG, kv, bt, lens)
    for a, b in zip(base, auto):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for tiles in [(2, 2, 2), (3, 1, 4)]:
        got = fused_layer_decode(x, lp, cl, CFG, kv, bt, lens,
                                 tiles=tiles)
        for a, b in zip(base, got):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-5)


def test_fused_layer_single_block_table():
    """nb == 1 edge: the j==0 grid step is also the last — init, QKV,
    block attend and the current-token fold all land in one step."""
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=4, block_size=8, dtype=jnp.float32)
    cache, bt = _prefilled(kv, [[5, 6, 7], [11]])
    assert bt.shape[1] == 2
    bt1 = bt[:, :1]  # single-block tables (max_context <= block_size)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, CFG.hidden))
    lp = jax.tree.map(lambda a: a[0], PARAMS["layers"])
    cl = {k: v[0] for k, v in cache.items()}
    x2, k_new, v_new = fused_layer_decode(
        x, lp, cl, CFG, kv, bt1, jnp.asarray([3, 1], jnp.int32))
    assert x2.shape == x.shape and k_new.shape == (2, 4, 8)
    assert np.isfinite(np.asarray(x2)).all()


# ---------------------------------------------------------------------------
# engine acceptance: stream equality on/off, compile gate, gating


@pytest.mark.parametrize("sampling", [
    SamplingConfig(),
    SamplingConfig(temperature=0.8, top_k=20),
])
def test_engine_streams_equal_megakernel_on_off(sampling):
    """ACCEPTANCE: the fused decode program changes no stream — greedy
    and same-key sampled outputs are equal request-for-request."""
    outs = {}
    for mode in ("on", "off"):
        eng = _engine(mode, sampling=sampling)
        outs[mode] = eng.run([Request(r.uid, r.tokens, r.max_new_tokens)
                              for r in REQS])
        assert eng.megakernel_enabled == (mode == "on")
    assert outs["on"] == outs["off"]


@pytest.mark.parametrize("kv_quant", ["int8", "int4"])
def test_engine_streams_equal_with_speculation_and_quant_kv(kv_quant):
    """The fused decode program composes with the FUSED speculative
    verify program (megakernel='on' now drives both jit sites) and the
    quantized caches: streams stay equal to the fully-unfused engine for
    int8 AND the nibble-packed int4 pools."""
    outs = {}
    for mode in ("on", "off"):
        eng = _engine(mode, spec_k=2, kv_quant=kv_quant)
        outs[mode] = eng.run([Request(r.uid, r.tokens, r.max_new_tokens)
                              for r in REQS])
    assert outs["on"] == outs["off"]


def test_engine_compile_gate_holds_with_megakernel():
    """The tightened PR-7 compile gate survives fusion: exactly 1 chunked
    prefill + 1 decode program (pinned through the shared
    ``analyze.recompile_guard`` sentinel)."""
    from apex_tpu.analyze import recompile_guard

    eng = _engine("on")
    with recompile_guard(eng.programs()):  # warmup contract
        eng.run([Request(r.uid, r.tokens, r.max_new_tokens) for r in REQS])
    counts = eng.compile_counts()
    assert counts["chunk_prefill"] == 1
    assert counts["decode"] == 1
    assert eng.stats()["megakernel"] is True


def test_megakernel_gating_and_validation():
    """auto falls back off-TPU; unsupported shapes refuse 'on' loudly
    WITH the reason; the VMEM gate is now a tile-budget computation —
    GPT-2-124M-class layers (whose full weight set is over budget) gate
    ON because their weight TILES fit, and only never-fits shapes
    refuse, reporting the measured bytes."""
    from apex_tpu.ops._pallas_util import force_compiled

    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=8, block_size=8, dtype=jnp.float32)
    assert megakernel_ok(CFG, kv)
    # auto on a CPU backend -> the unfused program
    eng = _engine("auto")
    assert eng.megakernel_enabled is False
    with pytest.raises(ValueError, match="megakernel"):
        ServeConfig(megakernel="bogus").validate()
    # MoE unsupported
    moe = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                    num_heads=4, num_experts=2, dtype=jnp.float32)
    assert not megakernel_ok(moe, kv)
    assert "dense FFN" in megakernel_refusal(moe, kv)
    # head_dim % 8 gate — and 'on' surfaces the reason in the raise
    odd = GPTConfig(vocab_size=97, max_seq=64, hidden=36, num_layers=2,
                    num_heads=4, dtype=jnp.float32)
    kv9 = KVCacheConfig(num_layers=2, num_heads=4, head_dim=9,
                        num_blocks=8, block_size=8, dtype=jnp.float32)
    assert not megakernel_ok(odd, kv9)
    with pytest.raises(ValueError, match="megakernel='on'.*head_dim"):
        InferenceEngine(init_gpt_params(jax.random.PRNGKey(0), odd), odd,
                        ServeConfig(num_slots=1, block_size=8,
                                    megakernel="on"))
    # THE LIFTED GATE: a 124M-shaped layer (768 hidden, 3072 ffn) in
    # fp32 is ~28 MB of weights — over the old full-residency budget —
    # but its streamed tile set fits, so it now gates ON
    big = GPTConfig(vocab_size=128, max_seq=64, hidden=768, num_layers=2,
                    num_heads=12, dtype=jnp.float32)
    kv_big = KVCacheConfig(num_layers=2, num_heads=12, head_dim=64,
                           num_blocks=8, block_size=8, dtype=jnp.float32)
    assert layer_weight_bytes(big) > 10 * 1024 * 1024
    assert megakernel_ok(big, kv_big)
    tiles = default_tiles(big, kv_big, compiled=False)
    assert tiles is not None and tiles != (1, 1, 1)
    assert fused_live_bytes(big, kv_big, tiles) <= 10 * 1024 * 1024
    # the GPT-2-124M flagship serve shape (bf16, lane-aligned tiles on
    # a compiled backend) gates ON too — the acceptance criterion
    flag = GPTConfig(vocab_size=50304, max_seq=1024, hidden=768,
                     num_layers=12, num_heads=12, dtype=jnp.bfloat16)
    kv_flag = KVCacheConfig(num_layers=12, num_heads=12, head_dim=64,
                            num_blocks=64, block_size=16,
                            dtype=jnp.bfloat16)
    assert layer_weight_bytes(flag) > 10 * 1024 * 1024
    with force_compiled():
        assert megakernel_ok(flag, kv_flag)
        assert megakernel_ok(flag, kv_flag, q=5)  # spec_k=4 verify fits
        # never-fits: even the finest lane-aligned tiling of an 8192-
        # hidden fp32 layer keeps >10 MB live; the refusal reports the
        # MEASURED bytes, not a bare no
        huge = GPTConfig(vocab_size=128, max_seq=64, hidden=8192,
                         num_layers=1, num_heads=64, dtype=jnp.float32)
        kv_huge = KVCacheConfig(num_layers=1, num_heads=64, head_dim=128,
                                num_blocks=8, block_size=8,
                                dtype=jnp.float32)
        refusal = megakernel_refusal(huge, kv_huge)
        assert refusal is not None and "VMEM" in refusal
        assert str(layer_weight_bytes(huge)) in refusal
        assert "finest weight tiling" in refusal


def test_engine_streams_equal_at_124m_shaped_config():
    """ACCEPTANCE: a GPT-2-124M-shaped config (768 hidden, fp32 — the
    shape the old full-residency gate refused) now serves with
    megakernel='on' + spec_k, and its streams equal both the unfused
    speculative engine AND the no-speculation reference. An oracle
    drafter (replays the reference continuation) guarantees the FUSED
    verify program actually runs."""
    big = GPTConfig(vocab_size=256, max_seq=64, hidden=768, num_layers=1,
                    num_heads=12, dtype=jnp.float32, fused_loss=False)
    assert layer_weight_bytes(big) > 10 * 1024 * 1024  # previously OFF
    params = init_gpt_params(jax.random.PRNGKey(1), big)
    reqs = [Request("a", [5, 6, 7, 8], max_new_tokens=4),
            Request("b", [9, 10, 11], max_new_tokens=3)]
    base = InferenceEngine(params, big, ServeConfig(
        num_slots=2, block_size=8, prefill_chunk=8, megakernel="off"))
    ref = base.run([Request(r.uid, r.tokens, r.max_new_tokens)
                    for r in reqs])
    conts = [list(r.tokens) + ref[r.uid] for r in reqs]
    outs, stats = {}, {}
    for mode in ("on", "off"):
        scfg = ServeConfig(num_slots=2, block_size=8, prefill_chunk=8,
                           megakernel=mode, spec_k=2)
        eng = InferenceEngine(params, big, scfg,
                              drafter=_OracleDrafter(conts))
        assert eng.megakernel_enabled == (mode == "on")
        outs[mode] = eng.run([Request(r.uid, r.tokens, r.max_new_tokens)
                              for r in reqs])
        stats[mode] = eng.stats()
    assert outs["on"] == outs["off"] == ref
    assert stats["on"]["decode_kernel"] == "fused"
    assert stats["on"]["verify_kernel"] == "fused"
    assert stats["on"]["speculative"]["verify_steps"] > 0
    assert stats["on"]["spec_acceptance_rate"] == 1.0


class _OracleDrafter:
    """Proposes exactly the continuation a reference run produced —
    every draft matches, so acceptance must be 1.0 and every speculative
    step emits k+1 tokens."""

    def __init__(self, continuations):
        self._conts = continuations  # full prompt+generated token lists

    def propose(self, tokens, k):
        t = list(tokens)
        for full in self._conts:
            if len(full) >= len(t) and full[:len(t)] == t:
                return full[len(t):len(t) + k]
        return []


@pytest.mark.parametrize("sampling", [
    SamplingConfig(),
    SamplingConfig(temperature=0.8, top_k=20),
])
def test_oracle_drafter_full_acceptance_on_fused_verify(sampling):
    """ACCEPTANCE: with an oracle drafter (proposes the recorded
    baseline continuation) the fused verify path accepts EVERY draft —
    acceptance_rate == 1.0 greedy AND sampled — and the streams stay
    equal to the unfused no-speculation baseline. Sampling draws are
    position-keyed, so the verify step's parallel draws equal the
    sequential ones."""
    base = _engine("off", sampling=sampling)
    ref = base.run([Request(r.uid, r.tokens, r.max_new_tokens)
                    for r in REQS])
    conts = [list(r.tokens) + ref[r.uid] for r in REQS]
    scfg = ServeConfig(num_slots=3, block_size=8, prefill_chunk=8,
                       megakernel="on", spec_k=2, sampling=sampling)
    eng = InferenceEngine(PARAMS, CFG, scfg,
                          drafter=_OracleDrafter(conts))
    outs = eng.run([Request(r.uid, r.tokens, r.max_new_tokens)
                    for r in REQS])
    assert outs == ref
    st = eng.stats()
    assert st["speculative"]["proposed"] > 0
    assert st["spec_acceptance_rate"] == 1.0
    assert st["verify_kernel"] == "fused"


def test_verify_kernel_field_reports_actual_path():
    """stats()/record field ``verify_kernel``: None without a verify
    program (spec_k == 0), 'fused' when the megakernel drives the verify
    jit site, 'reference'/'pallas' mirroring decode_kernel otherwise —
    the verify A/B gate's fallback-vs-regression discriminator."""
    from apex_tpu.ops._pallas_util import force_compiled

    assert _engine("on").verify_kernel is None  # no verify program
    eng_on = _engine("on", spec_k=2)
    assert eng_on.verify_kernel == "fused"
    assert eng_on.stats()["verify_kernel"] == "fused"
    eng_off = _engine("off", spec_k=2)
    assert eng_off.verify_kernel == "reference"  # CPU: no compiled Mosaic
    with force_compiled():
        assert eng_off.verify_kernel == "pallas"


def test_megakernel_auto_fallback_warns_once_with_reason():
    """megakernel='auto' falling back on a COMPILED backend logs ONE
    warning per reason, carrying the reason text (here: LoRA adapters
    ride the per-op path) — a slower serve run must be diagnosable from
    the log. The normal CPU auto fallback (no compiled Mosaic — nothing
    to miss) stays silent."""
    import logging

    from apex_tpu.ops._pallas_util import force_compiled
    from apex_tpu.serve.megakernel import _FALLBACK_WARNED

    records = []

    class Grab(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = logging.getLogger("apex_tpu.serve")
    handler = Grab(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        _FALLBACK_WARNED.clear()
        with force_compiled():
            for _ in range(2):  # second construction: no duplicate warn
                eng = InferenceEngine(PARAMS, CFG, ServeConfig(
                    num_slots=2, block_size=8, prefill_chunk=8,
                    megakernel="auto", lora_rank=4, max_adapters=2))
                assert eng.megakernel_enabled is False
        warns = [r for r in records if "falling back" in r.getMessage()]
        assert len(warns) == 1
        assert "LoRA" in warns[0].getMessage()
        # off-TPU auto-resolution (the normal CPU path) does not warn
        records.clear()
        _FALLBACK_WARNED.clear()
        assert _engine("auto").megakernel_enabled is False
        assert not [r for r in records if "falling back" in r.getMessage()]
    finally:
        logger.removeHandler(handler)


# ---------------------------------------------------------------------------
# fused optimizer update tail


@pytest.mark.parametrize("shape", [(7, 13), (300, 700), (1,), (1024,)])
@pytest.mark.parametrize("wd,adam_w", [(0.0, True), (0.01, True),
                                       (0.01, False)])
def test_adam_tail_kernel_matches_reference(shape, wd, adam_w):
    """The fused kernel equals the per-op Adam tail on every leaf shape,
    including leaves far from the (8, 128) tile (padding lanes sliced
    back off). Tolerance is fp reassociation noise, not algorithmic."""
    k = jax.random.PRNGKey(0)
    g, m, v, p = (jax.random.normal(jax.random.fold_in(k, i), shape)
                  for i in range(4))
    v = jnp.abs(v)
    c1, c2 = jnp.float32(1 - 0.9 ** 3), jnp.float32(1 - 0.999 ** 3)
    kw = dict(betas=(0.9, 0.999), eps=1e-8, weight_decay=wd,
              adam_w_mode=adam_w)
    ref = adam_tail_reference(g, m, v, p, c1, c2, **kw)
    fus = fused_adam_tail(g, m, v, p, c1, c2, use_pallas=True, **kw)
    for a, b in zip(ref, fus):
        assert b.shape == shape
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-6, atol=5e-7)


def test_lamb_tail_kernel_matches_reference_and_trust_composition():
    """LAMB kernel: tail + in-kernel Σp²/Σu² accumulated across grid
    steps match the reference, and the composed p' (trust ratio applied
    outside, world=1 so psum == identity) matches the DistributedFusedLAMB
    ``upd`` math."""
    k = jax.random.PRNGKey(1)
    shape = (300, 700)  # multi-block grid: accumulation across steps
    g, m, v, p = (jax.random.normal(jax.random.fold_in(k, i), shape)
                  for i in range(4))
    v = jnp.abs(v)
    c1, c2 = jnp.float32(1 - 0.9 ** 5), jnp.float32(1 - 0.999 ** 5)
    kw = dict(betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01)
    ref = lamb_tail_reference(g, m, v, p, c1, c2, **kw)
    fus = fused_lamb_tail(g, m, v, p, c1, c2, use_pallas=True, **kw)
    for a, b in zip(ref[:3], fus[:3]):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-6, atol=5e-7)
    np.testing.assert_allclose(np.asarray(fus[3]), np.asarray(ref[3]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(fus[4]), np.asarray(ref[4]),
                               rtol=1e-5)
    # trust-ratio composition == the unfused upd closure
    lr = 1e-2
    u, _, _, wsq, usq = fus
    w_norm, u_norm = jnp.sqrt(wsq), jnp.sqrt(usq)
    trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
    got = p - lr * trust * u
    b1, b2 = 0.9, 0.999
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    u_ref = (m_new / c1) / (jnp.sqrt(v_new / c2) + 1e-6) + 0.01 * p
    wn = jnp.sqrt(jnp.sum(p * p))
    un = jnp.sqrt(jnp.sum(u_ref * u_ref))
    want = p - lr * jnp.where((wn > 0) & (un > 0), wn / un, 1.0) * u_ref
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_fused_adam_optimizer_steps_match():
    """FusedAdam(fused_tail='on') == FusedAdam(fused_tail='off') over
    multiple steps — params and moments."""
    from apex_tpu.optimizers.fused_adam import FusedAdam

    k = jax.random.PRNGKey(2)
    params = {"w": jax.random.normal(k, (13, 7)),
              "b": jnp.zeros((5,))}
    grads = {"w": jax.random.normal(jax.random.fold_in(k, 1), (13, 7)),
             "b": jax.random.normal(jax.random.fold_in(k, 2), (5,))}
    outs = {}
    for mode in ("on", "off"):
        opt = FusedAdam(lr=1e-2, weight_decay=0.01, fused_tail=mode)
        st = opt.init(params)
        p = params
        for _ in range(3):
            upd, st = opt.update(grads, st, p)
            p = jax.tree.map(lambda a, u: a + u, p, upd)
        outs[mode] = (p, st.mu, st.nu)
    for a, b in zip(jax.tree.leaves(outs["on"]),
                    jax.tree.leaves(outs["off"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-6, atol=5e-7)


def test_resolve_fused_modes():
    assert resolve_fused("off") is False
    assert resolve_fused("on") is True  # pallas importable on this box
    # auto off-TPU: interpret mode saves no dispatch -> stays off
    assert resolve_fused("auto") is False
    with pytest.raises(ValueError, match="fused_tail"):
        resolve_fused("bogus", what="fused_tail")
    from apex_tpu.optimizers.fused_adam import FusedAdam

    with pytest.raises(ValueError, match="fused_tail"):
        FusedAdam(fused_tail="sometimes")


def test_decode_kernel_field_reports_actual_path():
    """stats()/record field ``decode_kernel``: 'fused' when the
    megakernel serves, 'reference' when auto-resolution fell back
    off-TPU, 'pallas' when the per-op body would pick the gather-attend
    kernel on a compiled backend — the stage-12 gate's fallback-vs-
    regression discriminator."""
    from apex_tpu.ops._pallas_util import force_compiled

    eng_on = _engine("on")
    assert eng_on.decode_kernel == "fused"
    assert eng_on.stats()["decode_kernel"] == "fused"
    eng_off = _engine("off")
    assert eng_off.decode_kernel == "reference"  # CPU: no compiled Mosaic
    with force_compiled():
        assert eng_off.decode_kernel == "pallas"  # head_dim 8: kernel-ok


def test_paged_attention_reference_fallback_warns_once():
    """The silent kernel->reference fallback (head_dim % 8 != 0 on a
    compiled backend) logs ONE warning — a 10x slower serve run must be
    diagnosable from the log, not only from the bench line. (Handler
    attached directly: the apex_tpu root logger does not propagate.)"""
    import logging

    from apex_tpu.ops._pallas_util import force_compiled
    from apex_tpu.serve import paged_attention
    from apex_tpu.serve.decode import _FALLBACK_WARNED

    kv = KVCacheConfig(num_layers=1, num_heads=2, head_dim=9,
                       num_blocks=4, block_size=4, dtype=jnp.float32)
    cache = init_kv_cache(kv)
    cl = {k: v[0] for k, v in cache.items()}
    q = jnp.zeros((2, 2, 9))
    bt = jnp.zeros((2, 2), jnp.int32)
    lens = jnp.zeros((2,), jnp.int32)

    records = []

    class Grab(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = logging.getLogger("apex_tpu.serve")
    handler = Grab(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        _FALLBACK_WARNED.discard(9)
        with force_compiled():
            paged_attention(q, cl, kv, bt, lens)
            paged_attention(q, cl, kv, bt, lens)  # second call: no dup
        warns = [r for r in records if "falling back" in r.getMessage()]
        assert len(warns) == 1
        assert "head_dim 9" in warns[0].getMessage()
        # off-TPU auto-resolution (the normal CPU path) does not warn
        _FALLBACK_WARNED.discard(9)
        records.clear()
        paged_attention(q, cl, kv, bt, lens)
        assert not [r for r in records if "falling back" in r.getMessage()]
    finally:
        logger.removeHandler(handler)

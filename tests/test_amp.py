"""AMP tests — mirror the reference L0/run_amp strategy (SURVEY.md §4):
behavioral dtype checks for the cast policy, scaler semantics with injected
inf/nan, O2 master-weight flow, checkpoint round-trips."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


# ---------------------------------------------------------------------------
# O1 autocast — ref tests/L0/run_amp/test_basic_casts.py


def _dot_out_dtype(fn, *args):
    out = amp.autocast(fn)(*args)
    return out.dtype


def test_whitelist_matmul_runs_bf16():
    x = jnp.ones((4, 8));  w = jnp.ones((8, 16))
    out = amp.autocast(lambda x, w: x @ w)(x, w)
    assert out.dtype == jnp.bfloat16


def test_whitelist_conv_runs_bf16():
    x = jnp.ones((1, 8, 8, 3))
    k = jnp.ones((3, 3, 3, 4))
    fn = lambda x, k: jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    out = amp.autocast(fn)(x, k)
    assert out.dtype == jnp.bfloat16


def test_blacklist_exp_stays_fp32():
    x = jnp.ones((4, 8)); w = jnp.ones((8, 8)) * 0.1
    out = amp.autocast(lambda x, w: jnp.exp(x @ w))(x, w)
    # matmul produced bf16, exp must cast back up
    assert out.dtype == jnp.float32


def test_blacklist_softmax_numerics():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 128)) * 10
    w = jnp.eye(128)
    ref = jax.nn.softmax(x)
    got = amp.autocast(lambda x, w: jax.nn.softmax(x @ w))(x, w)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got, np.float32), atol=2e-2)


def test_promote_mixed_dtypes():
    a = jnp.ones((4,), jnp.bfloat16)
    b = jnp.ones((4,), jnp.float32)
    out = amp.autocast(lambda a, b: a + b)(a, b)
    assert out.dtype == jnp.float32


def test_fp16_compute_dtype():
    x = jnp.ones((4, 8)); w = jnp.ones((8, 16))
    out = amp.autocast(lambda x, w: x @ w, compute_dtype=jnp.float16)(x, w)
    assert out.dtype == jnp.float16


def test_autocast_disabled_is_identity():
    f = lambda x: x * 2
    assert amp.autocast(f, enabled=False) is f


def test_autocast_under_jit_and_grad():
    x = jnp.ones((4, 8)); w = jnp.full((8, 8), 0.05)
    fn = amp.autocast(lambda x, w: jnp.exp(x @ w).sum())
    g = jax.jit(jax.grad(fn, argnums=1))(x, w)
    assert g.shape == (8, 8) and g.dtype == jnp.float32
    ref = jax.grad(lambda x, w: jnp.exp(x @ w).sum(), argnums=1)(x, w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref), rtol=2e-2)


def test_autocast_scan_cond_while():
    x = jnp.ones((4, 8)); w = jnp.eye(8) * 1.01

    def f_scan(x):
        out, _ = jax.lax.scan(lambda c, _: (c @ w, None), x, None, length=3)
        return out.sum()

    def f_cond(x):
        return jax.lax.cond(x.sum() > 0, lambda v: (v @ w).sum(), lambda v: v.sum(), x)

    def f_while(x):
        def body(c):
            return (c[0] @ w, c[1] + 1)
        out, _ = jax.lax.while_loop(lambda c: c[1] < 3, body, (x, 0))
        return out.sum()

    for f in (f_scan, f_cond, f_while):
        ref = float(f(x))
        got = float(amp.autocast(f)(x))
        assert abs(ref - got) / abs(ref) < 2e-2, f


def test_half_and_float_function_registration():
    # ref apex/amp/amp.py:30-64 decorator API
    captured = {}

    @amp.half_function
    def my_gemm(x):
        captured["dtype"] = x.dtype
        return x

    @amp.float_function
    def my_loss(x):
        captured["loss_dtype"] = x.dtype
        return x

    x = jnp.ones((4,), jnp.float32)
    # outside autocast: no casting
    my_gemm(x)
    assert captured["dtype"] == jnp.float32

    def model(x):
        y = my_gemm(x)
        return my_loss(y.astype(jnp.bfloat16)).sum()

    amp.autocast(model)(x)
    assert captured["dtype"] == jnp.bfloat16
    assert captured["loss_dtype"] == jnp.float32


# ---------------------------------------------------------------------------
# Loss scaler — ref tests/L0/run_amp test of scale update + overflow handling


def test_dynamic_scaler_growth_and_backoff():
    scaler = amp.LossScaler("dynamic", init_scale=2.0 ** 8, scale_window=4)
    state = scaler.init_state()
    ok = jnp.asarray(0.0)
    bad = jnp.asarray(1.0)
    # 4 clean steps -> double
    for _ in range(4):
        state, skipped = scaler.update_scale(state, ok)
    assert float(state.loss_scale) == 2.0 ** 9
    assert int(state.unskipped) == 0
    # overflow -> halve + reset
    state, skipped = scaler.update_scale(state, bad)
    assert bool(skipped)
    assert float(state.loss_scale) == 2.0 ** 8
    assert int(state.unskipped) == 0


def test_dynamic_scaler_bounds():
    scaler = amp.LossScaler("dynamic", init_scale=2.0, min_loss_scale=1.0,
                            max_loss_scale=4.0, scale_window=1)
    state = scaler.init_state()
    state, _ = scaler.update_scale(state, jnp.asarray(1.0))
    assert float(state.loss_scale) == 1.0
    state, _ = scaler.update_scale(state, jnp.asarray(1.0))
    assert float(state.loss_scale) == 1.0  # clamped below
    for _ in range(5):
        state, _ = scaler.update_scale(state, jnp.asarray(0.0))
    assert float(state.loss_scale) == 4.0  # clamped above


def test_static_scaler_never_updates():
    scaler = amp.LossScaler(128.0)
    state = scaler.init_state()
    state, skipped = scaler.update_scale(state, jnp.asarray(1.0))
    assert float(state.loss_scale) == 128.0
    assert bool(skipped)  # still skips the step on overflow


def test_unscale_detects_inf_and_nan():
    scaler = amp.LossScaler("dynamic")
    state = scaler.init_state()
    good = {"a": jnp.ones((4,)), "b": jnp.ones((2, 2))}
    for poison in (jnp.inf, jnp.nan):
        bad = {"a": jnp.ones((4,)).at[1].set(poison), "b": jnp.ones((2, 2))}
        _, found = scaler.unscale(bad, state)
        assert float(found) == 1.0
    _, found = scaler.unscale(good, state)
    assert float(found) == 0.0


def test_unscale_divides_by_scale():
    scaler = amp.LossScaler(16.0)
    state = scaler.init_state()
    grads = {"w": jnp.full((3,), 32.0, jnp.bfloat16)}
    out, _ = scaler.unscale(grads, state)
    assert out["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0)


# ---------------------------------------------------------------------------
# O0-O3 presets + O2 end-to-end — ref frontend.py policies + _process_optimizer


def test_opt_level_presets():
    o0 = amp.get_policy("O0")
    assert o0.cast_model_type is None and o0.master_weights is False
    o1 = amp.get_policy("O1")
    assert o1.compute_dtype == jnp.bfloat16 and o1.loss_scale == "dynamic"
    o2 = amp.get_policy("O2")
    assert o2.cast_model_type == jnp.bfloat16
    assert o2.keep_batchnorm_fp32 is True and o2.master_weights is True
    o3 = amp.get_policy("O3")
    assert o3.keep_batchnorm_fp32 is False and o3.loss_scale == 1.0
    with pytest.raises(ValueError):
        amp.get_policy("O4")


def test_policy_overrides():
    p = amp.get_policy("O2", loss_scale=512.0, keep_batchnorm_fp32=False)
    assert p.loss_scale == 512.0 and p.keep_batchnorm_fp32 is False


def test_o2_keeps_norm_params_fp32():
    params = {
        "Dense_0": {"kernel": jnp.ones((8, 4))},
        "BatchNorm_0": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
        "layer_norm": {"scale": jnp.ones((4,))},
    }
    state, policy = amp.initialize(params, "O2")
    mp = amp.model_params(state)
    assert mp["Dense_0"]["kernel"].dtype == jnp.bfloat16
    assert mp["BatchNorm_0"]["scale"].dtype == jnp.float32
    assert mp["layer_norm"]["scale"].dtype == jnp.float32
    # masters stay fp32
    assert state.master_params["Dense_0"]["kernel"].dtype == jnp.float32


def test_o3_casts_everything():
    params = {"BatchNorm_0": {"scale": jnp.ones((4,))}}
    state, _ = amp.initialize(params, "O3")
    assert amp.model_params(state)["BatchNorm_0"]["scale"].dtype == jnp.bfloat16


def test_o2_step_and_overflow_skip():
    params = {"w": jnp.ones((8, 4))}
    state, _ = amp.initialize(params, "O2")
    x = jnp.ones((2, 8))

    def sgd(g, p):
        return jax.tree_util.tree_map(lambda pi, gi: pi - 0.1 * gi, p, g)

    @jax.jit
    def step(state):
        mp = amp.model_params(state)

        def loss_fn(p):
            return amp.scale_loss(((x @ p["w"].astype(jnp.float32)) ** 2).mean(), state)

        grads = jax.grad(loss_fn)(mp)
        return amp.apply_grads(state, grads, sgd)

    state2, skipped = step(state)
    assert not bool(skipped)
    assert float(state2.master_params["w"][0, 0]) < 1.0  # actually stepped
    assert state2.master_params["w"].dtype == jnp.float32

    @jax.jit
    def step_inf(state):
        grads = {"w": jnp.full((8, 4), jnp.inf)}
        return amp.apply_grads(state, grads, sgd)

    state3, skipped3 = step_inf(state)
    assert bool(skipped3)
    np.testing.assert_array_equal(
        np.asarray(state3.master_params["w"]), np.asarray(state.master_params["w"])
    )
    assert float(state3.scaler.loss_scale) == float(state.scaler.loss_scale) / 2


def test_checkpoint_roundtrip():
    # ref tests/L0/run_amp/test_checkpointing.py + frontend.py:361-401
    params = {"w": jnp.ones((2,))}
    state, _ = amp.initialize(params, "O2")
    scaler = amp.LossScaler("dynamic")
    # advance the scaler a bit
    s = state.scaler
    for _ in range(3):
        s, _ = scaler.update_scale(s, jnp.asarray(0.0))
    state = state._replace(scaler=s)
    d = amp.state_dict(state)
    assert d["loss_scaler0"]["unskipped"] == 3
    restored = amp.load_state_dict(state, d)
    assert int(restored.scaler.unskipped) == 3
    assert float(restored.scaler.loss_scale) == float(s.loss_scale)


def test_two_models_independent_scalers():
    # ref test_multiple_models_optimizers_losses.py: per-loss scaler state
    pa, _ = amp.initialize({"w": jnp.ones((2,))}, "O2")
    pb, _ = amp.initialize({"w": jnp.ones((2,))}, "O2")
    sgd = lambda g, p: p
    pa2, _ = amp.apply_grads(pa, {"w": jnp.full((2,), jnp.inf)}, sgd)
    pb2, _ = amp.apply_grads(pb, {"w": jnp.ones((2,))}, sgd)
    assert float(pa2.scaler.loss_scale) == 2.0 ** 15
    assert float(pb2.scaler.loss_scale) == 2.0 ** 16


def test_found_inf_allreduce_across_mesh(mesh8):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def body(flag):
        return amp.LossScaler.all_reduce_found_inf(flag, "dp")

    f = shard_map(body, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
    flags = jnp.zeros((8,)).at[3].set(1.0)
    out = f(flags)
    np.testing.assert_array_equal(np.asarray(out), np.ones((8,)))


def test_apply_grads_with_optimizer_guards_opt_state():
    from apex_tpu import optimizers as opt

    params = {"w": jnp.ones((4,))}
    state, _ = amp.initialize(params, "O2")
    tx = opt.FusedAdam(lr=1e-2)
    opt_state = tx.init(state.master_params)

    state2, opt2, sk = jax.jit(
        lambda s, o: amp.apply_grads_with_optimizer(s, {"w": jnp.ones((4,))}, tx, o)
    )(state, opt_state)
    assert not bool(sk)
    assert int(opt2.count) == 1
    assert float(state2.master_params["w"][0]) < 1.0

    # overflow: params AND optimizer state roll back together
    state3, opt3, sk3 = jax.jit(
        lambda s, o: amp.apply_grads_with_optimizer(s, {"w": jnp.full((4,), jnp.nan)}, tx, o)
    )(state2, opt2)
    assert bool(sk3)
    assert int(opt3.count) == int(opt2.count)
    np.testing.assert_array_equal(
        np.asarray(opt3.mu["w"]), np.asarray(opt2.mu["w"])
    )
    np.testing.assert_array_equal(
        np.asarray(state3.master_params["w"]), np.asarray(state2.master_params["w"])
    )

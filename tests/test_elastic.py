"""Elastic fault-tolerant training acceptance suite (reshard + supervisor
+ sentinels + training chaos).

Gates: (1) the reshard arithmetic round-trips a dp=N block-aligned flat
layout through every dp degree in {1,2,4,8} BITWISE (the concatenated
global layout is dp-independent except trailing zero padding) and refuses
manifest lies, non-zero tails, indivisible shard multiples, and
gap/overlap placement sets loudly; (2) a dp=4 checkpoint saved with an
``elastic=`` spec (masters, Adam moments, EF residuals) restores at
dp∈{1,2,8} with ``allow_reshard=True`` — bitwise leaf parity for flat
leaves, rank-sum conservation for stacked EF residuals — and the SAME
restore without the flag still raises the fingerprint ``CheckpointError``;
(3) the TrainSupervisor's retry/skip→rollback→halt ladder, preemption
exit, and chaos kill→elastic-resume-at-a-different-dp all run on a manual
clock, and the resumed loss curve rejoins the fault-free run bitwise (the
sim optimizer is elementwise, so the padded-flat math is dp-invariant);
(4) the straggler/SDC sentinels flag injected faults with zero false
positives on a clean run — mesh rows under the shard_map shim.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers._sharding import shard_size
from apex_tpu.parallel.mesh import DP_AXIS, build_mesh
from apex_tpu.resilience import (
    AnomalyHalted,
    CheckpointError,
    CheckpointManager,
    CorruptShardFile,
    GuardPolicy,
    KillRankAtStep,
    PreemptionHandler,
    ReshardError,
    SDCSentinel,
    SlowRank,
    StragglerSentinel,
    TrainChaosPlan,
    TrainSupervisor,
    dp_flat_spec,
    dp_stacked_spec,
    grad_checksum,
    legal_resume_degrees,
    load_state_dict,
    replicated_spec,
    state_dict,
)
from apex_tpu.resilience import chaos
from apex_tpu.resilience.reshard import (
    LeafSpec,
    assemble_leaf,
    elastic_manifest,
    reshard_flat,
    reshard_stacked,
    retarget_leaf,
)
from apex_tpu.resilience.supervisor import RESTART_NAME

MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")
mesh_only = pytest.mark.skipif(
    not MESH_OK,
    reason="mesh programs need jax.shard_map/lax.axis_size (graft jax)")

DEGREES = (1, 2, 4, 8)
_N, _MULT = 13, 2  # odd logical size + alignment: padding differs per dp


# ---------------------------------------------------------------------------
# reshard arithmetic (stock-safe, pure numpy)


def test_reshard_flat_round_trips_all_degrees():
    base = np.arange(1, _N + 1, dtype=np.float32)
    for dp_a in DEGREES:
        flat_a = np.zeros(shard_size(_N, dp_a, _MULT) * dp_a, np.float32)
        flat_a[:_N] = base
        for dp_b in DEGREES:
            flat_b = reshard_flat(flat_a, _N, dp_b, multiple=_MULT)
            assert flat_b.size == shard_size(_N, dp_b, _MULT) * dp_b
            np.testing.assert_array_equal(flat_b[:_N], base)
            assert not flat_b[_N:].any()  # padding stays zero
            back = reshard_flat(flat_b, _N, dp_a, multiple=_MULT)
            np.testing.assert_array_equal(back, flat_a)  # bitwise


def test_reshard_flat_refuses_bad_inputs():
    # non-zero tail past n means the manifest's n is a lie
    with pytest.raises(ReshardError):
        reshard_flat(np.ones(8, np.float32), 5, 2)
    # stored buffer shorter than the logical size
    with pytest.raises(ReshardError):
        reshard_flat(np.zeros(4, np.float32), 5, 2)


def test_reshard_stacked_grow_shrink_conserves_rank_sum():
    stacked = np.arange(1, 9, dtype=np.float32).reshape(4, 2)
    np.testing.assert_array_equal(reshard_stacked(stacked, 4), stacked)
    grown = reshard_stacked(stacked, 8)
    assert grown.shape == (8, 2)
    np.testing.assert_array_equal(grown[:4], stacked)
    assert not grown[4:].any()  # new ranks start with zero residual
    # grow-then-shrink folds the zero rows away: bitwise original
    np.testing.assert_array_equal(reshard_stacked(grown, 4), stacked)
    shrunk = reshard_stacked(stacked, 2)
    assert shrunk.shape == (2, 2)
    # the EF convergence quantity is the rank-SUM of residuals
    np.testing.assert_array_equal(shrunk.sum(0), stacked.sum(0))


def test_retarget_leaf_refusals():
    spec = dp_flat_spec(_N, 4, _MULT)
    stored = np.zeros(shard_size(_N, 4, _MULT) * 4, np.float32)
    # replicated leaves must not change shape under reshard
    with pytest.raises(ReshardError):
        retarget_leaf(np.zeros((3,)), replicated_spec(), (4,))
    # dp_flat lives are 1-D by construction
    with pytest.raises(ReshardError):
        retarget_leaf(stored, spec, (4, 4))
    # manifest arithmetic lie: stored size != shard_size(n,dp,mult)*dp
    with pytest.raises(ReshardError):
        retarget_leaf(stored[:-2], spec, (16,))
    # live layout not a multiple of the shard alignment
    with pytest.raises(ReshardError, match="shard_multiple arithmetic"):
        retarget_leaf(stored, spec, (15,))


def test_assemble_leaf_round_trip_and_refusals():
    full = np.arange(8, dtype=np.float32)
    got = assemble_leaf((8,), np.float32, {"0:4": full[:4], "4:8": full[4:]})
    np.testing.assert_array_equal(got, full)
    # 2-D placements (the per-shard manifest's index keys are per-dim)
    sq = np.arange(16, dtype=np.float32).reshape(4, 4)
    got2 = assemble_leaf((4, 4), np.float32,
                         {"0:2,0:4": sq[:2], "2:4,0:4": sq[2:]})
    np.testing.assert_array_equal(got2, sq)
    with pytest.raises(ReshardError, match="overlap"):
        assemble_leaf((8,), np.float32,
                      {"0:4": full[:4], "2:6": full[2:6]})
    with pytest.raises(ReshardError, match="missing"):
        assemble_leaf((8,), np.float32, {"0:4": full[:4]})
    with pytest.raises(ReshardError, match="dims"):
        assemble_leaf((8,), np.float32, {"0:4,0:1": full[:4].reshape(4, 1)})


def test_legal_resume_degrees():
    # n=13, mult=2: at dp=8 every rank owns 2 slots but rank 7 starts at
    # 14 > 13 — all padding, so 8 is illegal
    specs = {"0": dataclasses.asdict(dp_flat_spec(_N, 4, _MULT))}
    assert legal_resume_degrees(specs, candidates=DEGREES) == [1, 2, 4]
    # a big leaf keeps every candidate legal
    big = {"0": dataclasses.asdict(dp_flat_spec(1 << 20, 4, 256))}
    assert legal_resume_degrees(big, candidates=DEGREES) == list(DEGREES)
    # no dp_flat leaves -> nothing constrains the topology
    free = {"0": dataclasses.asdict(replicated_spec()),
            "1": dataclasses.asdict(dp_stacked_spec(4))}
    assert legal_resume_degrees(free, candidates=DEGREES) == list(DEGREES)


def test_elastic_manifest_forms():
    state = {"a": jnp.zeros((3,)), "b": jnp.zeros(())}
    spec = {"a": dp_flat_spec(3, 1), "b": replicated_spec()}
    m = elastic_manifest(state, spec)
    assert set(m) == {"0", "1"} and m["0"]["kind"] == "dp_flat"
    # an already-flat digit-keyed mapping passes through validated
    assert elastic_manifest(state, m) == m
    # leaf-count mismatch is refused (spec tree from a different state)
    with pytest.raises((ReshardError, ValueError)):
        elastic_manifest(state, {"a": dp_flat_spec(3, 1)})
    with pytest.raises(ValueError):
        LeafSpec(kind="diagonal")


# ---------------------------------------------------------------------------
# elementwise-Adam sim: the padded-flat math is dp-invariant, so every
# cross-degree restore must continue the loss curve BITWISE


def _flat_layout(dp):
    return shard_size(_N, dp, _MULT) * dp


def _sim_init(dp):
    """dp-flat padded Adam state over one logical 13-element param, plus
    a stacked per-rank EF-residual-style leaf."""
    size = _flat_layout(dp)
    master = np.zeros(size, np.float32)
    master[:_N] = np.linspace(-1.0, 1.0, _N, dtype=np.float32)
    state = {
        "count": jnp.zeros((), jnp.int32),
        "master": jnp.asarray(master),
        "mu": jnp.zeros(size, jnp.float32),
        "nu": jnp.zeros(size, jnp.float32),
        "ef": jnp.zeros((dp, 3), jnp.float32),
    }
    spec = {
        "count": replicated_spec(),
        "master": dp_flat_spec(_N, dp, _MULT),
        "mu": dp_flat_spec(_N, dp, _MULT),
        "nu": dp_flat_spec(_N, dp, _MULT),
        "ef": dp_stacked_spec(dp),
    }
    return state, spec


_TARGET = np.linspace(1.0, 2.0, _N, dtype=np.float32)


def _sim_step(state, losses=None):
    """One elementwise Adam step on the padded flat layout. Padded slots
    see zero grads and stay zero, so the [0:n) math is identical at every
    dp degree — elementwise float32 ops make it bitwise-identical."""
    master = np.asarray(state["master"])
    mu, nu = np.asarray(state["mu"]), np.asarray(state["nu"])
    w = master[:_N]
    g_log = w - _TARGET
    if losses is not None:
        losses.append(0.5 * float(np.dot(g_log, g_log)))
    g = np.zeros_like(master)
    g[:_N] = g_log
    t = int(state["count"]) + 1
    mu = np.float32(0.9) * mu + np.float32(0.1) * g
    nu = np.float32(0.999) * nu + np.float32(0.001) * (g * g)
    mhat = mu / np.float32(1.0 - 0.9 ** t)
    vhat = nu / np.float32(1.0 - 0.999 ** t)
    master = master - np.float32(0.1) * mhat / (np.sqrt(vhat)
                                                + np.float32(1e-8))
    return {"count": jnp.int32(t), "master": jnp.asarray(master),
            "mu": jnp.asarray(mu), "nu": jnp.asarray(nu),
            "ef": state["ef"]}


def test_elastic_restore_across_degrees_bitwise(tmp_path):
    state, spec = _sim_init(4)
    for _ in range(3):  # non-trivial moments before the save
        state = _sim_step(state)
    state["ef"] = jnp.arange(12, dtype=jnp.float32).reshape(4, 3)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 3, block=True, elastic=spec)
    manifest = json.load(open(os.path.join(mgr.step_path(3),
                                           "manifest.json")))
    # flat leaf order is the sorted-key treedef: count, ef, master, mu, nu
    assert manifest["elastic"]["2"]["kind"] == "dp_flat"
    assert manifest["elastic"]["1"]["kind"] == "dp_stacked"
    for dp_new in (1, 2, 8):
        template, _ = _sim_init(dp_new)
        got, step = mgr.restore(target=template, allow_reshard=True)
        assert step == 3
        assert mgr.last_reshard_ms > 0.0
        for k in ("master", "mu", "nu"):
            flat = np.asarray(got[k])
            assert flat.size == _flat_layout(dp_new)
            np.testing.assert_array_equal(
                flat[:_N], np.asarray(state[k])[:_N])  # bitwise
            assert not flat[_N:].any()
        # stacked EF residuals conserve the rank-sum at every degree
        np.testing.assert_array_equal(
            np.asarray(got["ef"]).sum(0), np.asarray(state["ef"]).sum(0))
        assert got["ef"].shape == (dp_new, 3)
        assert int(got["count"]) == int(state["count"])


def test_elastic_restore_without_flag_still_refused(tmp_path):
    state, spec = _sim_init(4)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 1, block=True, elastic=spec)
    template, _ = _sim_init(2)
    with pytest.raises(CheckpointError):
        mgr.restore(target=template)  # fingerprint refusal survives
    # same-topology restores never pay the reshard path
    same, _ = _sim_init(4)
    got, _ = mgr.restore(target=same)
    np.testing.assert_array_equal(np.asarray(got["master"]),
                                  np.asarray(state["master"]))


def test_resave_at_new_degree_restores_at_old_bitwise(tmp_path):
    state, spec4 = _sim_init(4)
    for _ in range(2):
        state = _sim_step(state)
    mgr = CheckpointManager(str(tmp_path), allow_reshard=True)
    mgr.save(state, 2, block=True, elastic=spec4)
    template2, spec2 = _sim_init(2)
    at2, _ = mgr.restore(target=template2)  # ctor-level opt-in
    mgr.save(at2, 4, block=True, elastic=spec2)
    template4, _ = _sim_init(4)
    back, step = mgr.restore(target=template4)
    assert step == 4
    for k in ("master", "mu", "nu"):  # leaf-for-leaf identical
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(state[k]))


def test_state_dict_elastic_round_trip():
    state, spec = _sim_init(4)
    state = _sim_step(state)
    d = state_dict(state, elastic=spec)
    assert set(d["elastic"]) == {str(i) for i in range(5)}
    template, _ = _sim_init(2)
    got = load_state_dict(template, d, allow_reshard=True)
    np.testing.assert_array_equal(np.asarray(got["master"])[:_N],
                                  np.asarray(state["master"])[:_N])
    with pytest.raises(CheckpointError):
        load_state_dict(template, d)  # no flag -> fingerprint refusal


def test_optimizer_elastic_specs():
    from apex_tpu.comm import CompressionConfig
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.fsdp import FSDP, FSDPAdam

    params = {"w": jnp.zeros((5, 3)), "b": jnp.zeros((7,))}
    opt = DistributedFusedAdam(lr=1e-3)
    spec = opt.elastic_spec(params, 4)
    assert spec.count.kind == "replicated"
    assert spec.master["w"] == dp_flat_spec(15, 4, spec.master["w"].multiple)
    assert spec.mu["b"].n == 7 and spec.nu["b"].dp == 4
    assert opt.elastic_comm_spec(params, 4) is None  # no EF residuals
    ef = DistributedFusedAdam(
        lr=1e-3, compression=CompressionConfig("int8_ef", min_elements=1))
    comm = ef.elastic_comm_spec(params, 4)
    assert comm["w"] == dp_stacked_spec(4)
    fopt = FSDPAdam(fsdp=FSDP())
    fspec = fopt.elastic_spec(params, 2)
    assert fspec.master["w"].multiple == FSDP().shard_multiple
    assert fspec.count.kind == "replicated"


# ---------------------------------------------------------------------------
# sharded checkpoint dirs: on-disk per-shard reshard + chaos corruption
# (stock-safe: forced predicate on the single-process mesh, test_fsdp's
# fixture idiom)


@pytest.fixture
def sharded_ckpt(monkeypatch, tmp_path):
    """Force the cross-process predicate for dp-sharded (64,) leaves so
    the per-shard path runs on this single-process mesh."""
    from apex_tpu.resilience import checkpoint as ck

    monkeypatch.setattr(
        ck, "_is_cross_process",
        lambda a: hasattr(a, "addressable_shards") and getattr(
            a, "shape", ()) == (64,))
    from jax.sharding import NamedSharding

    mesh = build_mesh(tp=1, pp=1, sp=1)
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32),
                       NamedSharding(mesh, P("dp")))
    state = {"w": x, "b": jnp.ones((3,))}
    return ck, str(tmp_path), state, x


def test_sharded_elastic_restore_onto_new_dp_degree(sharded_ckpt):
    """A dp=8 per-shard checkpoint (8 placements of 8) reassembles and
    rebinds onto a dp=2 mesh's layout (2 shards of 32) under
    allow_reshard=True; without the flag the PR-9 skew refusal stands."""
    ck, d, state, x = sharded_ckpt
    spec = {"w": dp_flat_spec(64, 8), "b": replicated_spec()}
    mgr = ck.CheckpointManager(d)
    mgr.save(state, 7, block=True, elastic=spec)
    from jax.sharding import NamedSharding

    mesh2 = build_mesh(tp=4, pp=1, sp=1)  # dp=2
    y = jax.device_put(jnp.zeros(64, dtype=jnp.float32),
                       NamedSharding(mesh2, P("dp")))
    template = {"w": y, "b": jnp.zeros((3,))}
    with pytest.raises(ck.CheckpointError, match="skew"):
        mgr.restore(target=template)
    got, step = mgr.restore(target=template, allow_reshard=True)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(x))
    assert got["w"].sharding == y.sharding  # rebound onto the LIVE layout
    assert mgr.last_reshard_ms > 0.0


def test_corrupt_shard_dir_detected_and_skipped(sharded_ckpt):
    """chaos.corrupt_checkpoint(shard=K) reaches inside a sharded
    checkpoint's per-process dir; the damage is detectable (verify False)
    and latest_valid() falls back to the older good step."""
    ck, d, state, x = sharded_ckpt
    mgr = ck.CheckpointManager(d)
    mgr.save(state, 1, block=True)
    mgr.save(state, 2, block=True)
    chaos.corrupt_checkpoint(mgr.step_path(2), part="payload", mode="flip",
                             shard=0)
    assert not mgr.verify(mgr.step_path(2))
    assert mgr.latest_valid() == mgr.step_path(1)
    # a shard dir that does not exist would be an undetectable fault
    with pytest.raises(FileNotFoundError, match="undetectable"):
        chaos.corrupt_checkpoint(mgr.step_path(1), shard=3)


# ---------------------------------------------------------------------------
# TrainSupervisor: chaos kill -> elastic resume rejoins bitwise; manual
# clock for retry/escalation/preemption (no real sleeps)


def test_chaos_kill_then_elastic_resume_rejoins_bitwise(tmp_path):
    # fault-free reference at dp=4
    ref_losses = []
    state, _ = _sim_init(4)
    for _ in range(8):
        state = _sim_step(state, ref_losses)

    # run A: dp=4 under the supervisor, killed by chaos at step 5
    losses_a = []
    state_a, spec4 = _sim_init(4)
    mgr = CheckpointManager(str(tmp_path))
    plan = TrainChaosPlan([KillRankAtStep(at_step=5)])
    sup_a = TrainSupervisor(
        lambda st, i: _sim_step(st, losses_a), mgr, elastic=spec4,
        dp_degree=4, save_freq=2, chaos=plan,
        clock=iter(np.arange(1e6)).__next__, sleep=lambda s: None)
    _, stopped = sup_a.run(state_a, 0, 8)
    assert sup_a.exited == "killed" and stopped == 5
    assert plan.summary() == [{"step": 5, "fault": "KillRankAtStep",
                               "at_step": 5, "rank": 0}]
    info = TrainSupervisor.read_restart(str(tmp_path))
    assert info["reason"] == "killed" and info["allow_reshard"]
    assert info["checkpoint"] == mgr.step_path(4)
    assert info["legal_resume_dp"] == [1, 2, 4]  # dp=8 would be all-padding

    # run B: resume at dp=2 from the restart manifest, finish the run
    losses_b = []
    template, spec2 = _sim_init(2)
    mgr2 = CheckpointManager(str(tmp_path), allow_reshard=True)
    sup_b = TrainSupervisor(
        lambda st, i: _sim_step(st, losses_b), mgr2, elastic=spec2,
        dp_degree=2, clock=iter(np.arange(1e6)).__next__,
        sleep=lambda s: None)
    state_b, start = sup_b.resume(template)
    assert start == 4 and sup_b.counters["elastic_resumes_total"] == 1
    _, done = sup_b.run(state_b, start, 8 - start)
    assert sup_b.exited == "completed" and done == 8
    # the stitched curve rejoins the fault-free one BITWISE
    assert losses_a[:4] + losses_b == ref_losses


def test_resume_at_illegal_degree_refused(tmp_path):
    state, spec4 = _sim_init(4)
    mgr = CheckpointManager(str(tmp_path))
    sup = TrainSupervisor(lambda st, i: _sim_step(st), mgr, elastic=spec4,
                          dp_degree=4, save_freq=1)
    sup.run(state, 0, 2)
    template, spec8 = _sim_init(8)
    sup8 = TrainSupervisor(lambda st, i: _sim_step(st),
                           CheckpointManager(str(tmp_path),
                                             allow_reshard=True),
                           elastic=spec8, dp_degree=8)
    with pytest.raises(ValueError, match="legal resume degree"):
        sup8.resume(template)


def test_supervisor_retries_transients_with_backoff():
    sleeps, fails = [], {"left": 2}

    def flaky(state, step):
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("transient collective timeout")
        return _sim_step(state)

    sup = TrainSupervisor(flaky, None, dp_degree=1, max_retries=2,
                          backoff_s=0.5, clock=iter(np.arange(1e6)).__next__,
                          sleep=sleeps.append)
    state, _ = _sim_init(1)
    _, nxt = sup.run(state, 0, 1)
    assert nxt == 1 and sup.exited == "completed"
    assert sup.counters["retries_total"] == 2
    assert sleeps == [0.5, 1.0]  # exponential backoff


def test_supervisor_escalation_ladder_skip_rollback_halt(tmp_path):
    state, spec = _sim_init(1)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, 0, block=True, elastic=spec)

    def poisoned(st, step):
        raise RuntimeError("persistent desync")

    sup = TrainSupervisor(
        poisoned, mgr, elastic=spec, dp_degree=1, max_retries=0,
        policy=GuardPolicy(on_anomaly="skip", skip_budget=1,
                           rollback_budget=1),
        clock=iter(np.arange(1e6)).__next__, sleep=lambda s: None)
    with pytest.raises(AnomalyHalted):
        sup.run(state, 0, 10)
    assert sup.counters["skips_total"] == 1
    assert sup.counters["rollbacks_total"] == 1
    assert TrainSupervisor.read_restart(str(tmp_path))["reason"] == "halted"


def test_supervisor_preemption_synchronized_save_and_exit(tmp_path):
    handler = PreemptionHandler(install=False)
    state, spec = _sim_init(1)
    mgr = CheckpointManager(str(tmp_path))

    def step_fn(st, step):
        if step == 2:
            handler.trigger()  # the SIGTERM body, minus the kernel
        return _sim_step(st)

    sup = TrainSupervisor(step_fn, mgr, elastic=spec, dp_degree=1,
                          preemption=handler,
                          clock=iter(np.arange(1e6)).__next__,
                          sleep=lambda s: None)
    _, nxt = sup.run(state, 0, 10)
    assert sup.exited == "preempted" and nxt == 4
    assert mgr.latest_valid() is not None
    info = TrainSupervisor.read_restart(str(tmp_path))
    assert info["reason"] == "preempted" and info["step"] == 4
    # the saved state resumes exactly where the grace-window save left it
    got, step = mgr.restore(target=_sim_init(1)[0])
    assert step == 4 and int(got["count"]) == 3  # steps 0,1,2 ran


def test_chaos_plan_validation_and_slow_rank_flags():
    with pytest.raises(TypeError):
        TrainChaosPlan([object()])
    with pytest.raises(ValueError, match="at_step"):
        TrainChaosPlan([KillRankAtStep(at_step=-1)])
    # CorruptShardFile before any durable save is undetectable -> loud
    sup = TrainSupervisor(lambda st, i: _sim_step(st), None, dp_degree=1,
                          chaos=TrainChaosPlan([CorruptShardFile(at_step=0)]),
                          clock=iter(np.arange(1e6)).__next__,
                          sleep=lambda s: None)
    with pytest.raises(ValueError, match="no valid checkpoint"):
        sup.run(_sim_init(1)[0], 0, 1)
    # SlowRank rides the per-rank gauge into the straggler sentinel
    sent = StragglerSentinel(threshold=4.0)
    sup2 = TrainSupervisor(
        lambda st, i: _sim_step(st), None, dp_degree=4, straggler=sent,
        chaos=TrainChaosPlan([SlowRank(at_step=1, rank=2, factor=8.0,
                                       for_steps=1)]),
        clock=iter(np.arange(1e6)).__next__, sleep=lambda s: None)
    sup2.run(_sim_init(4)[0], 0, 3)
    assert sent.flags_total == 1 and sent.flagged[0][1] == 2
    assert sup2.summary()["straggler_flags_total"] == 1
    assert sup2.summary()["chaos"][0]["fault"] == "SlowRank"


# ---------------------------------------------------------------------------
# sentinels (stock-safe cores + one mesh row)


def test_straggler_sentinel_flags_slow_rank_only():
    class _Alerts:
        def __init__(self):
            self.fired = []

        def fire(self, name, t_ms, severity="warn", **ctx):
            self.fired.append((name, severity, ctx))

    alerts = _Alerts()
    s = StragglerSentinel(threshold=4.0, alerts=alerts)
    assert s.observe(0, [1.0, 1.0, 1.0, 1.0]) == []  # zero false positives
    assert s.observe(1, [1.0, 1.0]) == []  # below min_ranks: stay quiet
    assert s.observe(2, [1.0, 1.0, 1.0, 9.0]) == [3]  # MAD=0 fallback path
    assert s.observe(3, [1.0, 1.01, 0.99, 1.02, 1.0]) == []  # jitter
    assert s.flags_total == 1
    (name, severity, ctx), = alerts.fired
    assert name == "straggler" and ctx["rank"] == 3
    with pytest.raises(ValueError):
        StragglerSentinel(slack=0.5)


def test_sdc_disagreement_host_math():
    agree = jnp.full((4,), 7.5)
    assert float(SDCSentinel.disagreement(agree)) == 0.0
    flipped = agree.at[2].add(1e-3)  # one corrupted rank
    assert float(SDCSentinel.disagreement(flipped)) == 1.0
    assert float(SDCSentinel.disagreement(flipped, tol=1e-2)) == 0.0
    assert float(SDCSentinel.disagreement(agree.at[1].set(jnp.nan))) == 1.0
    with pytest.raises(ValueError):
        SDCSentinel(every=0)


def test_grad_checksum_sums_inexact_leaves_only():
    grads = {"w": jnp.ones((2, 3)), "b": jnp.full((4,), 0.5),
             "step": jnp.int32(9)}
    assert float(grad_checksum(grads)) == 8.0
    assert float(grad_checksum({"i": jnp.int32(3)})) == 0.0


@mesh_only
def test_sdc_check_is_rank_uniform_under_shard_map():
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    sent = SDCSentinel()

    def prog(x, poison):
        r = lax.axis_index(DP_AXIS)
        g = {"w": x + jnp.where((r == 3) & (poison > 0), 1e-2, 0.0)}
        return sent.check(g)[None]

    run = jax.jit(jax.shard_map(
        prog, mesh=mesh, in_specs=(P("dp"), P()), out_specs=P("dp"),
        check_vma=False))
    clean = np.asarray(run(jnp.ones(8), jnp.int32(0)))
    np.testing.assert_array_equal(clean, np.zeros(8))  # no false positives
    # a one-rank grad flip trips the SAME flag on EVERY rank
    hit = np.asarray(run(jnp.ones(8), jnp.int32(1)))
    np.testing.assert_array_equal(hit, np.ones(8))


# ---------------------------------------------------------------------------
# watch-stage gate coverage


def test_regress_polarity_covers_elastic_headliners():
    from apex_tpu.monitor.regress import classify_metric

    assert classify_metric("reshard_ms") == "lower"
    assert classify_metric("reshard_ms_per_gb") == "lower"
    assert classify_metric("sdc_disagreements_total") == "lower"
    assert classify_metric("straggler_flags_total") == "lower"
    assert classify_metric("retries_total") == "lower"
    # a resume at a new degree is a FEATURE firing, not a regression
    assert classify_metric("elastic_resumes_total") is None

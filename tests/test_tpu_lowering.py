"""AOT TPU-lowering guard for every compiled Pallas kernel path.

``jit(f).trace(args).lower(lowering_platforms=("tpu",))`` runs the full
Pallas→Mosaic lowering — block-shape tiling rules, layout checks, scalar
prefetch plumbing — on a CPU-only box, with no TPU attached. Interpret
mode (what the rest of the CPU suite exercises) skips exactly those
checks, which is how the varlen kernels' seg-id block shape
(``(1, block)`` slice of a ``(b, s)`` array — sublane dim neither
8-divisible nor full) passed 300+ tests while being unlowerable on
hardware (round-4 find; fixed by the jax-flash-style widened id layout,
``attention_varlen._seg_wide``).

Every kernel the TPU smoke (``benchmarks/smoke_tpu.py``) executes on the
chip must lower here first. Reference parity note: the reference compiles
its CUDA kernels at build time (``setup.py:119-630``) so an unbuildable
kernel fails CI without a GPU; this is the TPU analogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.ops._pallas_util import force_compiled

MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")


def _lower_tpu(f, *args):
    return jax.jit(f).trace(*args).lower(lowering_platforms=("tpu",))


B, H, S, D = 2, 4, 1024, 64


@pytest.fixture()
def qkv():
    k = jax.random.PRNGKey(0)
    q = jax.random.normal(k, (B, H, S, D), jnp.bfloat16)
    kk = jax.random.normal(jax.random.fold_in(k, 1), (B, H, S, D),
                           jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(k, 2), (B, H, S, D),
                          jnp.bfloat16)
    return q, kk, v


def test_flash_attention_fwd_bwd_causal(qkv):
    from apex_tpu.ops.attention import flash_attention

    q, k, v = qkv

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, use_pallas=True,
                            interpret=False)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_flash_attention_dropout(qkv):
    from apex_tpu.ops.attention import flash_attention

    q, k, v = qkv

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, use_pallas=True,
                            interpret=False, dropout_rate=0.1,
                            dropout_seed=jnp.int32(7))
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_flash_attention_bias(qkv):
    from apex_tpu.ops.attention import flash_attention

    q, k, v = qkv
    bias = jnp.zeros((H, S, S), jnp.float32)

    def loss(q, k, v, bias):
        o = flash_attention(q, k, v, causal=True, bias=bias,
                            use_pallas=True, interpret=False)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss, argnums=(0, 1, 2, 3)), q, k, v, bias)


def test_flash_attention_unequal_blocks(qkv):
    from apex_tpu.ops.attention import flash_attention

    q, k, v = qkv

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, use_pallas=True,
                            interpret=False, block_q=256, block_k=512)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_varlen_fwd_bwd(qkv):
    from apex_tpu.ops.attention_varlen import flash_attention_varlen

    q, k, v = qkv
    # two packed sequences + trailing pad per row
    seg = jnp.where(jnp.arange(S) < 600, 0,
                    jnp.where(jnp.arange(S) < 1000, 1, -1))
    seg = jnp.broadcast_to(seg, (B, S)).astype(jnp.int32)

    def loss(q, k, v):
        o = flash_attention_varlen(q, k, v, seg, causal=True,
                                   use_pallas=True, interpret=False)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_varlen_sub128_seq_lowers_or_falls_back():
    """seqs divisible by 8 but not 128 (reviewer repro: s=192): the widened
    seg-id lane layout forbids sub-128 kv blocks, so the picker must choose
    one full-seq block (legal: block == array dim) — and the forced Pallas
    path must lower."""
    from apex_tpu.ops.attention_varlen import flash_attention_varlen

    s = 192
    q = jnp.zeros((B, H, s, D), jnp.bfloat16)
    seg = jnp.zeros((B, s), jnp.int32)

    def loss(q):
        o = flash_attention_varlen(q, q, q, seg, causal=True,
                                   use_pallas=True, interpret=False)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss), q)


@pytest.mark.parametrize("s", [100, 2056])
def test_varlen_misaligned_seq_pads_and_lowers(s):
    """Seqs with no legal block pad to the next 128-multiple with seg=-1
    instead of raising (s=100) or silently falling back to the dense
    O(s^2) reference (s=2056: 8-aligned, not 128-divisible, past the
    one-block VMEM cap — the advisor's repro). The padded dispatch must
    lower for TPU end to end, fwd + bwd."""
    from apex_tpu.ops.attention_varlen import flash_attention_varlen

    q = jnp.zeros((B, H, s, D), jnp.bfloat16)
    seg = jnp.zeros((B, s), jnp.int32)

    def loss(q):
        o = flash_attention_varlen(q, q, q, seg, causal=True,
                                   use_pallas=True, interpret=False)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss), q)


def test_varlen_bad_head_dim_raises_when_forced():
    from apex_tpu.ops.attention_varlen import flash_attention_varlen

    q = jnp.zeros((B, H, 256, 12), jnp.bfloat16)  # head_dim % 8 != 0
    seg = jnp.zeros((B, 256), jnp.int32)
    with pytest.raises(ValueError, match="head_dim"):
        flash_attention_varlen(q, q, q, seg, use_pallas=True)


def test_varlen_unfixable_block_hint_raises_not_recurses():
    """Padding cannot fix a block hint < 8 on an already-aligned seq; the
    dispatcher must raise (reviewer find: it used to recurse forever)."""
    from apex_tpu.ops.attention_varlen import flash_attention_varlen

    q = jnp.zeros((B, H, 256, D), jnp.bfloat16)
    seg = jnp.zeros((B, 256), jnp.int32)
    with pytest.raises(ValueError, match="block"):
        flash_attention_varlen(q, q, q, seg, use_pallas=True, block_q=7)


def test_interpret_arg_rejected_on_reference_path():
    """interpret= silently ignored on the fallback path was the round-4
    silent-fallback trap; both entry points must reject it loudly."""
    from apex_tpu.ops.attention import flash_attention
    from apex_tpu.ops.attention_varlen import flash_attention_varlen

    q = jnp.zeros((B, H, 256, D), jnp.bfloat16)
    seg = jnp.zeros((B, 256), jnp.int32)
    with pytest.raises(ValueError, match="interpret= only applies"):
        flash_attention(q, q, q, mask=jnp.zeros((256, 256), bool),
                        interpret=False)
    with pytest.raises(ValueError, match="interpret= only applies"):
        flash_attention_varlen(q, q, q, seg, use_pallas=False,
                               interpret=False)


def _ring_loss(op_body, in_specs, x, w):
    """Scalar loss through a shard_map'd decomposed ring — the form whose
    grad program we must be able to AOT-lower for TPU."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.mesh import build_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(tp=8, pp=1, sp=1)

    def body(x, w):
        y = op_body(x, w)
        return jax.lax.psum(jnp.sum(y.astype(jnp.float32) ** 2), "tp")

    def loss(x, w):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=P())(x, w)

    return loss


@pytest.mark.skipif(not MESH_OK,
                    reason="mesh programs need jax.shard_map (graft jax)")
def test_all_gather_matmul_ring_lowers_for_tpu():
    """AOT TPU lowering of the decomposed all-gather-matmul ring, fwd+bwd
    (the varlen lesson: what only ever EXECUTES on the CPU sim skips every
    platform lowering rule — here the SPMD collective-permute lowering and
    the partitioner's handling of the custom-VJP ring bodies)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.comm import all_gather_matmul

    x = jnp.zeros((2, 64, 32), jnp.bfloat16)
    w = jnp.zeros((32, 48), jnp.bfloat16)
    for bidir in (False, True):
        loss = _ring_loss(
            lambda a, b, bd=bidir: all_gather_matmul(
                a, b, gather_axis=1, bidirectional=bd),
            (P(None, "tp", None), P(None, "tp")), x, w)
        _lower_tpu(jax.grad(loss, argnums=(0, 1)), x, w)


@pytest.mark.skipif(not MESH_OK,
                    reason="mesh programs need jax.shard_map (graft jax)")
def test_matmul_reduce_scatter_ring_lowers_for_tpu():
    """AOT TPU lowering of the shifting-accumulator reduce-scatter ring
    (and its fused dx/dw backward ring), fwd+bwd."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.comm import matmul_reduce_scatter

    x = jnp.zeros((2, 64, 32), jnp.bfloat16)
    w = jnp.zeros((32, 48), jnp.bfloat16)
    loss = _ring_loss(
        lambda a, b: matmul_reduce_scatter(a, b, scatter_axis=1),
        (P(None, None, "tp"), P("tp", None)), x, w)
    _lower_tpu(jax.grad(loss, argnums=(0, 1)), x, w)


@pytest.mark.parametrize("hidden", [1024, 16384])
def test_layer_norm(hidden):
    from apex_tpu.ops.layer_norm import layer_norm

    x = jnp.ones((256, hidden), jnp.bfloat16)
    w = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)

    def loss(x, w, b):
        return jnp.sum(layer_norm(x, w, b, use_pallas=True)
                       .astype(jnp.float32) ** 2)

    with force_compiled():
        _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), x, w, b)


def test_lm_head_loss():
    from apex_tpu.ops.lm_head_loss import lm_head_loss

    n, h, vocab = 512, 768, 50304
    x = jnp.ones((n, h), jnp.bfloat16)
    w = jnp.ones((vocab, h), jnp.bfloat16)
    t = jnp.zeros((n,), jnp.int32)

    def loss(x, w):
        return jnp.sum(lm_head_loss(x, w, t, use_pallas=True))

    with force_compiled():
        _lower_tpu(jax.grad(loss, argnums=(0, 1)), x, w)


_PALLAS_PARAMS_OK = False
try:  # the kernel entry points need the graft-era Pallas compiler params
    from jax.experimental.pallas import tpu as _pltpu

    _PALLAS_PARAMS_OK = hasattr(_pltpu, "CompilerParams")
except Exception:
    pass


@pytest.mark.skipif(not _PALLAS_PARAMS_OK,
                    reason="pltpu.CompilerParams needs graft-era pallas")
@pytest.mark.parametrize("quantized", [False, True])
def test_paged_attention_kernel_lowers_for_tpu(quantized):
    """AOT TPU lowering of the serve gather-attend kernel: scalar-prefetch
    block-table plumbing, the (H, 1, bs, D) pool block shape, and the int8
    code + fp32 scale dequant path all pass Mosaic's layout rules."""
    from apex_tpu.serve import KVCacheConfig, init_kv_cache
    from apex_tpu.serve.decode import paged_attention

    kv = KVCacheConfig(num_layers=1, num_heads=8, head_dim=64,
                       num_blocks=16, block_size=128, dtype=jnp.bfloat16,
                       quantized=quantized)
    cl = {k: v[0] for k, v in init_kv_cache(kv).items()}
    q = jnp.zeros((4, 8, 64), jnp.bfloat16)
    bt = jnp.zeros((4, 4), jnp.int32)
    lens = jnp.zeros((4,), jnp.int32)

    def f(q, cl, bt, lens):
        return paged_attention(q, cl, kv, bt, lens, use_pallas=True,
                               interpret=False)

    with force_compiled():
        _lower_tpu(f, q, cl, bt, lens)


@pytest.mark.skipif(not _PALLAS_PARAMS_OK,
                    reason="pltpu.CompilerParams needs graft-era pallas")
@pytest.mark.parametrize("quantized", [False, True])
def test_fused_layer_decode_kernel_lowers_for_tpu(quantized):
    """AOT TPU lowering of the megakernel fused layer block: resident
    weight BlockSpecs (constant index maps), the clamped pool-walk DMA,
    the in-register current-token fold and the in-kernel int8 dequant all
    pass Mosaic's tiling/layout rules at a serving-sized shape."""
    from apex_tpu.serve import KVCacheConfig, init_kv_cache
    from apex_tpu.serve.megakernel import fused_layer_decode, megakernel_ok
    from apex_tpu.transformer.testing import GPTConfig

    cfg = GPTConfig(vocab_size=512, max_seq=1024, hidden=512, num_layers=1,
                    num_heads=8, dtype=jnp.bfloat16, fused_loss=False)
    kv = KVCacheConfig(num_layers=1, num_heads=8, head_dim=64,
                       num_blocks=16, block_size=128, dtype=jnp.bfloat16,
                       quantized=quantized)
    assert megakernel_ok(cfg, kv)
    h, f3, hd = cfg.hidden, 3 * cfg.hidden, cfg.num_heads * cfg.head_dim
    f = cfg.ffn_hidden
    dt = jnp.bfloat16
    lp = {
        "ln1_w": jnp.ones((h,), dt), "ln1_b": jnp.zeros((h,), dt),
        "qkv_kernel": jnp.zeros((h, f3), dt),
        "qkv_bias": jnp.zeros((f3,), dt),
        "out_kernel": jnp.zeros((hd, h), dt),
        "out_bias": jnp.zeros((h,), dt),
        "ln2_w": jnp.ones((h,), dt), "ln2_b": jnp.zeros((h,), dt),
        "fc1_kernel": jnp.zeros((h, f), dt),
        "fc1_bias": jnp.zeros((f,), dt),
        "fc2_kernel": jnp.zeros((f, h), dt),
        "fc2_bias": jnp.zeros((h,), dt),
    }
    cl = {k: v[0] for k, v in init_kv_cache(kv).items()}
    x = jnp.zeros((4, h), dt)
    bt = jnp.zeros((4, 4), jnp.int32)
    lens = jnp.zeros((4,), jnp.int32)

    def fn(x, lp, cl, bt, lens):
        return fused_layer_decode(x, lp, cl, cfg, kv, bt, lens,
                                  interpret=False)

    with force_compiled():
        _lower_tpu(fn, x, lp, cl, bt, lens)


def _mega_layer_fixture(quantized):
    """Shared serving-sized layer for the tier-2 megakernel lowering
    rows: 512 hidden bf16, lane-aligned weight tiles available."""
    from apex_tpu.serve import KVCacheConfig, init_kv_cache
    from apex_tpu.transformer.testing import GPTConfig

    cfg = GPTConfig(vocab_size=512, max_seq=1024, hidden=512, num_layers=1,
                    num_heads=8, dtype=jnp.bfloat16, fused_loss=False)
    kv = KVCacheConfig(num_layers=1, num_heads=8, head_dim=64,
                       num_blocks=16, block_size=128, dtype=jnp.bfloat16,
                       quantized=quantized)
    h, f3, hd = cfg.hidden, 3 * cfg.hidden, cfg.num_heads * cfg.head_dim
    f = cfg.ffn_hidden
    dt = jnp.bfloat16
    lp = {
        "ln1_w": jnp.ones((h,), dt), "ln1_b": jnp.zeros((h,), dt),
        "qkv_kernel": jnp.zeros((h, f3), dt),
        "qkv_bias": jnp.zeros((f3,), dt),
        "out_kernel": jnp.zeros((hd, h), dt),
        "out_bias": jnp.zeros((h,), dt),
        "ln2_w": jnp.ones((h,), dt), "ln2_b": jnp.zeros((h,), dt),
        "fc1_kernel": jnp.zeros((h, f), dt),
        "fc1_bias": jnp.zeros((f,), dt),
        "fc2_kernel": jnp.zeros((f, h), dt),
        "fc2_bias": jnp.zeros((h,), dt),
    }
    cl = {k: v[0] for k, v in init_kv_cache(kv).items()}
    return cfg, kv, lp, cl


@pytest.mark.skipif(not _PALLAS_PARAMS_OK,
                    reason="pltpu.CompilerParams needs graft-era pallas")
@pytest.mark.parametrize("quantized", [False, True])
def test_fused_layer_decode_tiled_kernel_lowers_for_tpu(quantized):
    """AOT TPU lowering of the WEIGHT-STREAMING fused layer: multi-tile
    BlockSpecs with phase-clamped index maps over the flattened grid
    axis (qkv 3-way, out-proj 2-way, ffn 4-way column/row tiles), fp32
    partial accumulation across fc2 row tiles — the tier-2 path that
    lifts the VMEM residency gate past Mosaic's tiling rules."""
    from apex_tpu.serve.megakernel import _check_tiles, fused_layer_decode

    cfg, kv, lp, cl = _mega_layer_fixture(quantized)
    tiles = (3, 2, 4)  # 1536/3, 512/2, 2048/4 — all lane-aligned
    _check_tiles(cfg, tiles, True)
    x = jnp.zeros((4, cfg.hidden), jnp.bfloat16)
    bt = jnp.zeros((4, 4), jnp.int32)
    lens = jnp.zeros((4,), jnp.int32)

    def fn(x, lp, cl, bt, lens):
        return fused_layer_decode(x, lp, cl, cfg, kv, bt, lens,
                                  interpret=False, tiles=tiles)

    with force_compiled():
        _lower_tpu(fn, x, lp, cl, bt, lens)


@pytest.mark.skipif(not _PALLAS_PARAMS_OK,
                    reason="pltpu.CompilerParams needs graft-era pallas")
@pytest.mark.parametrize("quantized", [False, True])
def test_fused_layer_verify_kernel_lowers_for_tpu(quantized):
    """AOT TPU lowering of the fused VERIFY layer (q_len = k+1 = 3 rows
    per slot): the per-row-unrolled online softmax, the causal
    within-window fold across fed rows and the per-row codec round-trip
    emission all pass Mosaic's layout rules."""
    from apex_tpu.serve.megakernel import fused_layer_verify

    cfg, kv, lp, cl = _mega_layer_fixture(quantized)
    x = jnp.zeros((4, 3, cfg.hidden), jnp.bfloat16)
    bt = jnp.zeros((4, 4), jnp.int32)
    start_ctx = jnp.zeros((4,), jnp.int32)

    def fn(x, lp, cl, bt, start_ctx):
        return fused_layer_verify(x, lp, cl, cfg, kv, bt, start_ctx,
                                  interpret=False)

    with force_compiled():
        _lower_tpu(fn, x, lp, cl, bt, start_ctx)


@pytest.mark.skipif(not _PALLAS_PARAMS_OK,
                    reason="pltpu.CompilerParams needs graft-era pallas")
@pytest.mark.parametrize("with_norms", [False, True])
def test_fused_update_tail_lowers_for_tpu(with_norms):
    """AOT TPU lowering of the fused Adam/LAMB update-tail kernel: the
    SMEM scalar block, the padded (rows, 128) row blocking and the LAMB
    variant's sequential (1, 1) norm accumulators."""
    from apex_tpu.ops.fused_update import fused_adam_tail, fused_lamb_tail

    n = 70_001  # deliberately unaligned: exercises the padding path
    g = jnp.zeros((n,), jnp.float32)
    c = jnp.float32(0.5)

    def fn(g, c):
        tail = fused_lamb_tail if with_norms else fused_adam_tail
        return tail(g, g, g, g, c, c, betas=(0.9, 0.999), eps=1e-8,
                    weight_decay=0.01, use_pallas=True, interpret=False)

    with force_compiled():
        _lower_tpu(fn, g, c)

"""Serve throughput tier 2 — prefix caching, chunked prefill, speculation.

The correctness oracles and chaos gates for the three stacked
optimizations on the serving hot path (all stock-jax-safe, single
device):

* **allocator invariants under chaos** — random admit/retire/evict
  interleavings never leak a block, double-free, or break the
  refcount-0 ⇔ evictable equivalence;
* **copy-on-write never mutates a shared block** — bitwise gather parity
  for the sharing request across another request's CoW admission;
* **cold-path oracle** — chunked-prefill, prefix-cached and speculative
  engine streams are BITWISE equal (greedy AND same-key sampled) to a
  reference loop built on the full-prompt flash prefill
  (``gpt_prefill``) + sequential ``gpt_decode_step``;
* **tightened compile gate** — 1 chunked prefill + 1 decode + exactly 1
  verify shape per spec-k (+ <= 1 CoW copy), for ANY prompt mix;
* **device-mirror transfers** — steady-state decode re-uploads only the
  arrays that changed (transfer counts + identity pins);
* **shared-prefix loadgen** — the workload knob is deterministic per
  seed and actually exercises the cache (hit rate > 0 end to end).
"""

import random
import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.serve import (
    BlockAllocator,
    InferenceEngine,
    KVCacheConfig,
    NGramDrafter,
    Request,
    SamplingConfig,
    ServeConfig,
    gpt_decode_step,
    gpt_prefill,
    init_kv_cache,
    prefix_block_hashes,
    request_key,
    sample,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

CFG = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)
BS = 8  # block size used throughout


def _engine(sampling=None, **kw):
    scfg = ServeConfig(num_slots=3, block_size=BS, prefill_chunk=8,
                       sampling=sampling or SamplingConfig(), **kw)
    return InferenceEngine(PARAMS, CFG, scfg)


# two full blocks exactly (the CoW-triggering shape) and a sharing tail
PROMPT16 = list(range(30, 46))
PROMPT_TAIL = PROMPT16[:8] + [60, 61, 62, 63]


# ---------------------------------------------------------------------------
# prefix hashing


def test_prefix_block_hashes_chain():
    h = prefix_block_hashes(PROMPT16, BS)
    assert len(h) == 2                      # two FULL blocks, no tail hash
    assert len(prefix_block_hashes(PROMPT16[:15], BS)) == 1
    assert len(prefix_block_hashes(PROMPT16[:7], BS)) == 0
    # chained: same second block after a different first block -> both differ
    other = [9] + PROMPT16[1:]
    h2 = prefix_block_hashes(other, BS)
    assert h2[0] != h[0] and h2[1] != h[1]
    # prefix property: shared first block -> shared first hash
    assert prefix_block_hashes(PROMPT_TAIL, BS)[0] == h[0]


# ---------------------------------------------------------------------------
# allocator: caching lifecycle + chaos invariants


def test_allocator_lookup_commit_park_evict():
    al = BlockAllocator(4, prefix_cache=True)
    h = prefix_block_hashes(PROMPT16, BS)
    a = al.alloc(2)
    al.commit(a[0], h[0])
    al.commit(a[1], h[1])
    assert al.cached_count == 2
    # another holder: refcount 2, lookup acquires
    got = al.lookup(h)
    assert got == a and al.refcount(a[0]) == 2
    al.free(got)
    al.free(a)                      # rc 0: parks in LRU, stays addressable
    assert al.free_count == 4 and al.cached_count == 2
    # partial chain: a missing first hash stops the match immediately
    assert al.lookup([12345] + h) == []
    got = al.lookup(h[:1])
    assert got == [a[0]]
    al.free(got)
    # pressure: alloc past the truly-free blocks evicts parked LRU blocks
    big = al.alloc(4)
    assert len(big) == 4 and al.cached_count == 0
    assert al.blocks_evicted_total == 2
    assert al.lookup(h) == []       # addresses died with the eviction
    al.assert_consistent()


def test_allocator_double_free_and_commit_rules():
    al = BlockAllocator(3, prefix_cache=True)
    a = al.alloc(1)
    al.free(a)
    with pytest.raises(ValueError, match="double free"):
        al.free(a)
    with pytest.raises(ValueError, match="out of range"):
        al.free([99])
    with pytest.raises(ValueError, match="unallocated"):
        al.commit(a[0], 42)         # freed block can't take an address
    b = al.alloc(2)
    assert al.commit(b[0], 7)
    assert not al.commit(b[1], 7)   # hash race: first writer wins
    assert not al.commit(b[0], 8)   # a block carries ONE address
    al.free(b)
    al.assert_consistent()


def test_allocator_chaos_refcount_invariants():
    """THE chaos gate: random admit (alloc+lookup+commit) / retire (free)
    / pressure (alloc forcing eviction) interleavings keep every
    invariant: no leaked blocks, no double free, refcount-0 ⇔ evictable,
    and the allocator's view always reconciles with the model's."""
    rng = random.Random(7)
    al = BlockAllocator(24, prefix_cache=True)
    live = []          # (blocks, hashes) of "admitted requests"
    next_prompt = [0]

    def admit():
        n_blocks = rng.randint(1, 4)
        if rng.random() < 0.5 and next_prompt[0] > 0:
            pid = rng.randrange(next_prompt[0])     # maybe-shared prompt
        else:
            pid = next_prompt[0]
            next_prompt[0] += 1
        toks = [(pid * 131 + i) % 997 for i in range(n_blocks * BS)]
        hashes = prefix_block_hashes(toks, BS)
        hit = al.lookup(hashes)
        fresh = al.alloc(n_blocks - len(hit))
        if fresh is None:
            if hit:
                al.free(hit)
            return
        blocks = hit + fresh
        for j in range(len(hit), n_blocks):
            al.commit(blocks[j], hashes[j])
        live.append(blocks)

    def retire():
        if live:
            al.free(live.pop(rng.randrange(len(live))))

    def pressure():
        grab = al.alloc(rng.randint(1, 6))
        if grab is not None:
            al.free(grab)  # parked blocks were evicted, grabbed are plain

    for _ in range(400):
        rng.choice((admit, admit, retire, pressure))()
        al.assert_consistent()
        # the model's refcounts reconcile exactly with the allocator's
        refs = {}
        for blocks in live:
            for b in blocks:
                refs[b] = refs.get(b, 0) + 1
        for b in range(al.num_blocks):
            assert al.refcount(b) == refs.get(b, 0), b
    for blocks in live:
        al.free(blocks)
    al.assert_consistent()
    assert al.free_count == al.num_blocks   # zero leaked blocks


# ---------------------------------------------------------------------------
# cold-path oracle: reference loop on the FULL flash prefill


def _reference_stream(prompt, max_new, sampling=SamplingConfig(),
                      uid="ref", eos_id=None, max_context=None):
    """Sequential single-request decode on the cold path: one full
    flash-attention prefill (gpt_prefill) + one gpt_decode_step per
    token, with the engine's request-intrinsic sampling keys."""
    max_context = max_context or CFG.max_seq
    mb = -(-max_context // BS)
    kv = KVCacheConfig(num_layers=CFG.num_layers, num_heads=CFG.num_heads,
                       head_dim=CFG.head_dim, num_blocks=mb, block_size=BS,
                       dtype=jnp.float32)
    row = jnp.arange(mb, dtype=jnp.int32)
    p = len(prompt)
    toks = jnp.zeros((max_context,), jnp.int32).at[:p].set(
        jnp.asarray(prompt))
    cache, logits = gpt_prefill(PARAMS, toks, jnp.int32(p),
                                init_kv_cache(kv), row, CFG, kv)
    key = request_key(jax.random.PRNGKey(0), zlib.crc32(uid.encode()))
    tok = int(sample(logits[None], key[None],
                     jnp.asarray([p], jnp.int32), sampling)[0])
    stream = [tok]
    while True:
        if eos_id is not None and tok == eos_id:
            break
        if len(stream) >= max_new or p + len(stream) > max_context:
            break
        s = p + len(stream) - 1
        cache, lg = gpt_decode_step(
            PARAMS, jnp.asarray([tok]), jnp.asarray([s], jnp.int32),
            jnp.asarray([True]), cache, row[None], CFG, kv)
        tok = int(sample(lg, key[None],
                         jnp.asarray([s + 1], jnp.int32), sampling)[0])
        stream.append(tok)
    return stream


@pytest.mark.parametrize("sampling", [
    SamplingConfig(),
    SamplingConfig(temperature=0.8, top_k=20, top_p=0.9),
], ids=["greedy", "sampled"])
def test_chunked_and_cached_streams_match_cold_full_prefill(sampling):
    """ACCEPTANCE oracle: chunk-prefilled streams — cold AND prefix-cache
    warm (partial hit, and full hit through CoW) — are bitwise equal to
    the reference full-flash-prefill sequential decode."""
    want16 = _reference_stream(PROMPT16, 6, sampling, uid="a")
    want_tail = _reference_stream(PROMPT_TAIL, 5, sampling, uid="t")
    eng = _engine(sampling=sampling)
    cold = eng.run([Request("a", PROMPT16, max_new_tokens=6)])
    assert cold["a"] == want16
    # warm, full-prompt hit -> CoW path
    warm = eng.run([Request("a", PROMPT16, max_new_tokens=6, seed=None)])
    assert warm["a"] == want16
    assert eng.stats()["prefix_cache"]["cow_copies"] == 1
    # warm, partial hit (shared first block, fresh tail)
    tail = eng.run([Request("t", PROMPT_TAIL, max_new_tokens=5)])
    assert tail["t"] == want_tail
    pc = eng.stats()["prefix_cache"]
    assert pc["blocks_hit"] == 3 and pc["hit_rate"] > 0
    assert pc["tokens_saved"] > 0 and pc["prefill_flops_saved"] > 0


def test_prefix_cache_off_matches_on():
    """The cache is a pure optimization: identical streams with it
    disabled (and zero hit accounting)."""
    on = _engine().run([Request("a", PROMPT16, max_new_tokens=4),
                        Request("b", PROMPT16, max_new_tokens=4)])
    off_eng = _engine(prefix_cache=False)
    off = off_eng.run([Request("a", PROMPT16, max_new_tokens=4),
                       Request("b", PROMPT16, max_new_tokens=4)])
    assert on == off
    assert off_eng.stats()["prefix_cache"]["blocks_needed"] == 0
    assert off_eng.allocator.cached_count == 0


def test_cow_never_mutates_shared_block():
    """THE CoW gate: while request A still holds (and decodes against)
    its cached prompt blocks, request B's full-hit admission CoWs the
    last block — A's pool blocks stay BITWISE identical and A's stream
    is unperturbed."""
    eng = _engine()
    # A: long generation so it stays active while B admits
    eng.submit(Request("A", PROMPT16, max_new_tokens=20))
    for _ in range(6):   # prefill A fully (2 chunks + CoW-free decode)
        eng.step()
    a_state = next(s for s in eng._slots if s is not None)
    # the SHARED prompt blocks (A's later blocks legitimately keep
    # filling with A's own generation)
    a_blocks = list(a_state.blocks[:2])
    snap = {k: np.asarray(v[:, :, a_blocks]) for k, v in eng.cache.items()}
    eng.submit(Request("B", PROMPT16, max_new_tokens=3))
    eng.step()           # admits B -> full hit -> CoW of A's 2nd block
    assert eng.stats()["prefix_cache"]["cow_copies"] == 1
    b_state = next(s for s in eng._slots
                   if s is not None and s.request.uid == "B")
    assert b_state.blocks[0] == a_blocks[0]      # first block SHARED
    assert b_state.blocks[1] != a_blocks[1]      # second block CoW'd
    # drive B to completion; A's shared blocks must never change
    while eng.active:
        eng.step()
        for k, v in eng.cache.items():
            np.testing.assert_array_equal(
                np.asarray(v[:, :, a_blocks]), snap[k],
                err_msg=f"shared block mutated in pool {k}")
    out = eng.finished
    assert out["A"] == _reference_stream(PROMPT16, 20, uid="A")
    assert out["B"] == _reference_stream(PROMPT16, 3, uid="B")
    eng.allocator.assert_consistent()


def test_cache_survives_eviction_pressure():
    """A pool smaller than the working set: parked cached blocks are
    evicted under pressure, streams stay correct, nothing leaks."""
    scfg = ServeConfig(num_slots=2, block_size=BS, prefill_chunk=8,
                       num_blocks=10)  # < 2 slots * 8 blocks/slot
    eng = InferenceEngine(PARAMS, CFG, scfg)
    reqs = [Request(f"r{i}", [(7 * i + j) % 97 for j in range(18)],
                    max_new_tokens=4) for i in range(6)]
    out = eng.run(reqs)
    assert len(out) == 6
    for r in reqs:
        single = InferenceEngine(PARAMS, CFG, scfg).run([r])
        assert single[r.uid] == out[r.uid]
    assert eng.allocator.blocks_evicted_total > 0
    eng.allocator.assert_consistent()
    assert eng.allocator.free_count == eng.allocator.num_blocks


# ---------------------------------------------------------------------------
# speculative decoding


class _OracleDrafter:
    """Test-only drafter that proposes the KNOWN base streams — forces
    maximal acceptance so the verify path is exercised even under
    temperature sampling (where generated text has no n-gram repeats for
    the prompt-lookup drafter to find)."""

    def __init__(self, reqs, streams):
        self._by_prompt = {tuple(r.tokens): streams[r.uid] for r in reqs}

    def propose(self, tokens, k):
        for prompt, stream in self._by_prompt.items():
            n = len(prompt)
            if tuple(tokens[:n]) == prompt and tokens[n:] == stream[
                    :len(tokens) - n]:
                done = len(tokens) - n
                return stream[done:done + k]
        return []


@pytest.mark.parametrize("sampling", [
    SamplingConfig(),
    SamplingConfig(temperature=0.8, top_k=20, top_p=0.9),
], ids=["greedy", "sampled"])
def test_speculative_streams_bitwise_equal_non_speculative(sampling):
    """ACCEPTANCE oracle: the speculative path emits BITWISE the
    non-speculative streams (greedy and same-key sampled) — acceptance
    is decided against the engine's own position-keyed draws, so the
    drafter can only add tokens per step, never change them. The greedy
    case runs the real prompt-lookup drafter; the sampled case forces
    full verify coverage with an oracle drafter (random draws have no
    n-grams to look up)."""
    # a periodic prompt the n-gram drafter reads well + a mixed batch
    reqs = [Request("rep", ([5, 6, 7, 8] * 4)[:14], max_new_tokens=10),
            Request("mix", list(range(40, 51)), max_new_tokens=7),
            Request("sh", [3, 1, 4], max_new_tokens=5)]
    base = _engine(sampling=sampling).run(reqs)
    greedy = sampling.temperature == 0.0
    spec = _engine(sampling=sampling, spec_k=4)
    if not greedy:
        spec.drafter = _OracleDrafter(reqs, base)
    out = spec.run(reqs)
    assert out == base
    st = spec.stats()["speculative"]
    assert st["proposed"] > 0 and st["verify_steps"] > 0
    assert st["accepted"] <= st["proposed"]
    if not greedy:
        # the oracle drafter is always right: every draft accepted, and
        # the sampled draws STILL match the sequential ones bitwise
        assert st["accepted"] == st["proposed"]
    counts = spec.compile_counts()
    if counts["decode"] is not None:
        assert counts["chunk_prefill"] == 1
        assert counts["verify"] == 1          # ONE spec-k shape
        assert counts["decode"] <= 1


def test_speculative_acceptance_on_repetitive_stream():
    """On a strongly periodic stream the prompt-lookup drafter should
    actually land drafts (acceptance > 0) and cover the generation in
    fewer engine steps than one-token decode would need."""
    prompt = ([11, 12, 13] * 5)[:14]
    eng = _engine(spec_k=4)
    out = eng.run([Request("p", prompt, max_new_tokens=12)])
    assert len(out["p"]) == 12
    st = eng.stats()
    sp = st["speculative"]
    assert sp["accepted"] > 0
    assert sp["acceptance_rate"] > 0
    assert st["spec_acceptance_rate"] == sp["acceptance_rate"]
    # steps to generate: first token rides the last chunk; every further
    # token would cost one step without speculation
    decode_like_steps = sp["verify_steps"] + sp["decode_steps"]
    assert decode_like_steps < 11, (decode_like_steps, sp)


def test_speculative_eos_and_budget_respected():
    """EOS inside an accepted run stops the stream exactly there, and a
    1-token budget never drafts (nothing to amortize)."""
    greedy = _engine().run([Request("rep", ([5, 6, 7, 8] * 4)[:14],
                                    max_new_tokens=10)])["rep"]
    eos = int(greedy[3])
    base = _engine(eos_id=eos).run(
        [Request("rep", ([5, 6, 7, 8] * 4)[:14], max_new_tokens=10)])
    spec = _engine(eos_id=eos, spec_k=4).run(
        [Request("rep", ([5, 6, 7, 8] * 4)[:14], max_new_tokens=10)])
    assert spec == base
    assert spec["rep"][-1] == eos
    one = _engine(spec_k=4)
    out = one.run([Request("one", ([5, 6, 7, 8] * 4)[:14],
                           max_new_tokens=1)])
    assert len(out["one"]) == 1
    assert one.stats()["speculative"]["proposed"] == 0


def test_drafter_interface_and_ngram():
    d = NGramDrafter(ngram=2, min_context=4)
    #            0  1  2  3  4  5
    hist = [1, 2, 9, 1, 2, 7, 1, 2]
    # last bigram (1,2) most recently seen at index 3 -> proposes [7, 1, 2]
    assert d.propose(hist, 3) == [7, 1, 2]
    assert d.propose(hist, 1) == [7]
    assert d.propose([1, 2], 3) == []        # below min_context
    assert d.propose([1, 2, 3, 4, 5, 6], 3) == []  # no repeat
    with pytest.raises(ValueError):
        NGramDrafter(ngram=0)

    class ConstantDrafter:
        def propose(self, tokens, k):
            return [0] * k                    # deliberately terrible

    # a pluggable drafter that is always wrong: streams unchanged,
    # acceptance 0
    reqs = [Request("a", PROMPT16, max_new_tokens=5)]
    base = _engine().run(reqs)
    eng = InferenceEngine(
        PARAMS, CFG,
        ServeConfig(num_slots=3, block_size=BS, prefill_chunk=8,
                    spec_k=3),
        drafter=ConstantDrafter())
    assert eng.run(reqs) == base
    sp = eng.stats()["speculative"]
    assert sp["proposed"] > 0 and sp["accepted"] == 0
    with pytest.raises(ValueError, match="spec_k"):
        InferenceEngine(PARAMS, CFG,
                        ServeConfig(num_slots=1, block_size=BS),
                        drafter=NGramDrafter())  # drafter without spec_k


def test_speculative_with_prefix_cache_and_int8():
    """All three optimizations stacked, fp32 bitwise vs the plain engine;
    int8 KV within codec tolerance (same stream LENGTHS, engine runs)."""
    reqs = [Request("x", ([5, 6, 7, 8] * 4)[:14], max_new_tokens=8),
            Request("y", ([5, 6, 7, 8] * 4)[:14], max_new_tokens=8)]
    base = _engine().run(reqs)
    allopt = _engine(spec_k=4)
    assert allopt.run(reqs) == base
    int8 = _engine(spec_k=4, kv_quant="int8")
    out8 = int8.run(reqs)
    assert {k: len(v) for k, v in out8.items()} == \
        {k: len(v) for k, v in base.items()}
    # int8 warm-vs-cold is still bitwise: cached codes ARE the recompute
    int8b = _engine(kv_quant="int8")
    c1 = int8b.run([Request("x", PROMPT16, max_new_tokens=5)])
    c2 = int8b.run([Request("x2", PROMPT16, max_new_tokens=5,
                            seed=zlib.crc32(b"x"))])
    assert c2["x2"] == c1["x"]
    assert int8b.stats()["prefix_cache"]["blocks_hit"] > 0


def test_plain_allocator_mode_really_plain():
    """prefix_cache=False is a real mode on the ALLOCATOR, not just an
    engine-side guard: lookup always misses, commit never registers,
    freed blocks go straight back to the free list."""
    al = BlockAllocator(4, prefix_cache=False)
    h = prefix_block_hashes(PROMPT16, BS)
    a = al.alloc(2)
    assert not al.commit(a[0], h[0])
    assert al.cached_count == 0
    al.free(a)
    assert al.lookup(h) == []
    assert al.free_count == 4 and len(al._lru) == 0
    al.assert_consistent()


def test_verify_step_records_fed_and_emitted_tokens(tmp_path):
    """Telemetry honesty: a verify step feeds 1+len(drafts) tokens per
    slot and emits 1+accepted — the step record's kv_write_bytes and
    tokens_per_s must reflect that, not 1/slot."""
    from apex_tpu.monitor import JsonlSink, read_jsonl
    from apex_tpu.serve import kv_write_bytes_per_token

    path = str(tmp_path / "steps.jsonl")
    with JsonlSink(path, buffer_steps=1) as sink:
        scfg = ServeConfig(num_slots=3, block_size=BS, prefill_chunk=8,
                           spec_k=4)
        eng = InferenceEngine(PARAMS, CFG, scfg, sink=sink)
        eng.run([Request("rep", ([5, 6, 7, 8] * 4)[:14],
                         max_new_tokens=12)])
        assert eng.stats()["speculative"]["accepted"] > 0
        per_tok = kv_write_bytes_per_token(eng.kv_cfg)
    recs = [r for r in read_jsonl(path) if r.get("phase") == "decode"]
    spec_recs = [r for r in recs if r["spec_proposed"] > 0]
    assert spec_recs
    for r in recs:
        n_active = round(r["occupancy"] * 3)
        fed = n_active + r["spec_proposed"]
        assert r["kv_write_bytes"] == fed * per_tok
    # at least one accepted-draft step reported > 1 token of throughput
    # relative to a plain step (emitted = 1 + accepted per slot)
    accepted = [r for r in spec_recs if r["spec_accepted"] > 0]
    assert accepted
    for r in accepted:
        assert r["tokens_per_s"] > 0


def test_regress_gates_hit_and_acceptance_rates():
    """The stage-11 regression gate actually covers the two headline
    rates: both classify higher-is-better, so a collapse fails regress."""
    from apex_tpu.monitor.regress import classify_metric, compare_records

    assert classify_metric("prefix_hit_rate") == "higher"
    assert classify_metric("spec_acceptance_rate") == "higher"
    base = {"prefix_hit_rate": 0.7, "spec_acceptance_rate": 0.9}
    bad = {"prefix_hit_rate": 0.1, "spec_acceptance_rate": 0.9}
    rep = compare_records(base, bad, tol=0.15)
    assert not rep["ok"]
    assert any(r["key"] == "prefix_hit_rate" for r in rep["regressions"])
    assert compare_records(base, dict(base), tol=0.15)["ok"]


# ---------------------------------------------------------------------------
# device-mirror satellite: upload only on change


def test_device_mirrors_upload_only_on_change():
    """engine.step() must not re-upload unchanged host arrays: across a
    pure-decode stretch the block tables / keys / active mask keep ONE
    upload (identity-stable device arrays); per-token arrays re-upload
    each step."""
    eng = _engine()
    eng.submit(Request("long", list(range(6)), max_new_tokens=25))
    while eng._prefill_queue or eng._pending:
        eng.step()
    base = dict(eng.transfer_counts)
    bt0 = eng._dev("block_tables")
    for _ in range(10):
        assert eng.step()
    assert eng._dev("block_tables") is bt0          # identity-stable
    assert eng.transfer_counts["block_tables"] == base["block_tables"]
    assert eng.transfer_counts["keys"] == base["keys"]
    assert eng.transfer_counts["active"] == base["active"]
    # the per-token arrays DID change (and therefore re-uploaded)
    assert eng.transfer_counts["seq_lens"] >= base["seq_lens"] + 10
    # a retirement dirties the slot-shaped arrays again
    while eng.active:
        eng.step()
    assert eng.transfer_counts["block_tables"] == base["block_tables"]
    eng.submit(Request("next", [1, 2, 3], max_new_tokens=2))
    while eng.active:
        eng.step()
    assert eng.transfer_counts["block_tables"] > base["block_tables"]


# ---------------------------------------------------------------------------
# loadgen shared-prefix workload


def test_loadgen_shared_prefix_deterministic_and_mixed():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    from loadgen import WorkloadConfig, build_workload

    cfg = WorkloadConfig(n_requests=40, rate_rps=50.0, seed=3,
                         prefix_pool=2, prefix_len=16, prefix_ratio=0.7,
                         prompt_len_max=24)
    w1 = build_workload(cfg, vocab_size=97, max_context=64)
    w2 = build_workload(cfg, vocab_size=97, max_context=64)
    assert [(t, r.uid, list(r.tokens), r.max_new_tokens)
            for t, r in w1] == \
        [(t, r.uid, list(r.tokens), r.max_new_tokens) for t, r in w2]
    # the prefix pool really is a pool: exactly 2 distinct 16-token heads
    # among shared requests, and some requests stay fully random
    heads = {tuple(r.tokens[:16]) for _, r in w1 if len(r.tokens) > 16}
    shared = [h for h in heads
              if sum(tuple(r.tokens[:16]) == h for _, r in w1) > 1]
    assert len(shared) == 2
    n_shared = sum(1 for _, r in w1
                   if len(r.tokens) >= 16 and tuple(r.tokens[:16]) in shared)
    assert 0 < n_shared < 40
    # every prompt still leaves room to generate
    assert all(1 <= len(r.tokens) < 64 for _, r in w1)
    # a different seed reshuffles the pool
    w3 = build_workload(dataclasses_replace(cfg, seed=4), 97, 64)
    assert [list(r.tokens) for _, r in w1] != \
        [list(r.tokens) for _, r in w3]
    with pytest.raises(ValueError, match="prefix_ratio"):
        WorkloadConfig(prefix_pool=1, prefix_ratio=0.0).validate()


def dataclasses_replace(cfg, **kw):
    import dataclasses
    return dataclasses.replace(cfg, **kw)


def test_loadgen_shared_prefix_exercises_cache_end_to_end():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    from loadgen import WorkloadConfig, build_workload, run_workload

    wcfg = WorkloadConfig(n_requests=10, mode="closed", seed=1,
                          prefix_pool=1, prefix_len=16, prefix_ratio=1.0,
                          prompt_len_min=2, prompt_len_max=8,
                          max_new_min=2, max_new_max=4)
    workload = build_workload(wcfg, CFG.vocab_size, CFG.max_seq)
    eng = _engine()
    stats = run_workload(eng, workload, max_wall_s=120.0)
    assert stats["completed"] == 10
    # every request shares the 2-block system prompt; the first wave of
    # admissions (up to num_slots concurrent) misses because the blocks
    # are not committed until their prefill lands — later ones hit
    assert stats["prefix_hit_rate"] > 0.3
    assert stats["prefix_cache"]["tokens_saved"] > 0
    eng.allocator.assert_consistent()

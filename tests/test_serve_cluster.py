"""apex_tpu.serve.cluster — disaggregated prefill/decode serving.

All stock-jax-safe (single device; the multi-"host" cluster runs on the
in-process SimTransport). The acceptance gates from ISSUE 10 live here:

* **disaggregated parity** — under a fixed seeded workload, per-request
  token streams from a multi-host simulated cluster are BITWISE equal to
  the single-engine path, greedy AND sampled (position-keyed sampling
  makes this checkable), across raw/int8 wire and fp32/int8 pools;
* **int8 transfer round-trip** — codes+scales shipped over the simulated
  transport land bitwise-identical in the decode worker's int8 pool vs
  local prefill (and within codec tolerance for fp32 pools on an int8
  wire);
* **overload** — at offered load ≥ 2× capacity the cluster SHEDS (shed
  counters + events recorded) and never deadlocks or raises; the kept
  traffic's goodput-under-SLO stays comparable to the at-capacity run;
* **wire accounting** — the packed payload's measured bytes equal the
  ``transfer_wire_bytes`` model, and the int8 wire cuts fp32 transfer
  bytes ≥ 3.5×.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor.events import EventLog, chrome_trace, request_spans
from apex_tpu.monitor.regress import classify_metric
from apex_tpu.monitor.slo import SloSpec
from apex_tpu.serve import (
    ClusterConfig,
    InferenceEngine,
    PrefillWorker,
    Request,
    Router,
    RouterConfig,
    SamplingConfig,
    ServeCluster,
    ServeConfig,
    SimTransport,
    transfer_wire_bytes,
)
from apex_tpu.serve.cluster.transfer import (
    pack_blocks,
    payload_nbytes,
)
from apex_tpu.serve.cluster.workers import DecodeWorker
from apex_tpu.serve.kv_cache import KVCacheConfig, init_kv_cache
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

CFG = GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                num_heads=4, dtype=jnp.float32, fused_loss=False)
PARAMS = init_gpt_params(jax.random.PRNGKey(0), CFG)

REQS = [
    Request("a", [1, 2, 3, 4, 5], max_new_tokens=6),
    Request("b", [7, 8, 9], max_new_tokens=4),
    Request("c", list(range(20, 42)), max_new_tokens=8),
    Request("d", [11, 3, 11, 3, 11, 3, 7], max_new_tokens=5),
    Request("e", list(range(60, 73)), max_new_tokens=7),
]


def _serve_cfg(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return ServeConfig(**kw)


def _cluster(scfg, n_prefill=1, n_decode=2, slo=None, **kw):
    ccfg = ClusterConfig(
        n_prefill=n_prefill, n_decode=n_decode, serve=scfg,
        router=RouterConfig(slo=slo or SloSpec(ttft_ms=600000.0)), **kw)
    return ServeCluster(PARAMS, CFG, ccfg)


# ---------------------------------------------------------------------------
# Transfer: pack/unpack round-trips + wire accounting


def _prefill_one(request, kv_quant="none", wire_mode="raw"):
    """Run one prompt through a PrefillWorker; returns (worker, handoff)."""
    w = PrefillWorker(PARAMS, CFG, _serve_cfg(kv_quant=kv_quant),
                      wire_mode=wire_mode)
    w.accept(request, 0.0)
    h = None
    while h is None:
        h = w.step()
    return w, h


def _install_on_decode(h, kv_quant="none", wire_mode="raw"):
    d = DecodeWorker(PARAMS, CFG, _serve_cfg(kv_quant=kv_quant),
                     wire_mode=wire_mode)
    d.admit(h)
    assert d.try_admit() == 1
    return d


def _local_engine_cache(request, kv_quant="none"):
    """Single-engine oracle: prefill the prompt locally, return (engine,
    slot block ids in order)."""
    eng = InferenceEngine(PARAMS, CFG, _serve_cfg(kv_quant=kv_quant))
    eng.submit(request)
    # drive prefill chunks only (no decode: max_new never reached)
    while eng._prefill_queue or eng._pending:
        eng.step()
    return eng


def _slot_blocks(engine_like, prompt_len, bs=8):
    nb = -(-prompt_len // bs)
    row = engine_like._block_tables[0]
    return [int(b) for b in row[:nb]]


@pytest.mark.parametrize("kv_quant", ["none", "int8", "int4"])
def test_transfer_lands_bitwise_vs_local_prefill(kv_quant):
    """The satellite gate: blocks shipped over the simulated transport
    land in the decode pool BITWISE identical to what local prefill
    writes (int8/int4 pools: codes AND scales ship verbatim — the
    nibble-packed int4 representation never dequantizes on the wire;
    fp32 pools: raw wire)."""
    req = Request("x", list(range(1, 20)), max_new_tokens=4)
    _, h = _prefill_one(req, kv_quant=kv_quant, wire_mode="raw")
    d = _install_on_decode(h, kv_quant=kv_quant, wire_mode="raw")
    oracle = _local_engine_cache(req, kv_quant=kv_quant)
    nb = h.n_blocks
    dst = _slot_blocks(d.engine, h.prompt_len)
    src = _slot_blocks(oracle, h.prompt_len)
    bs = d.engine.kv_cfg.block_size
    p = h.prompt_len
    for name in d.engine.cache:
        got = np.asarray(d.engine.cache[name])[:, :, dst]
        want = np.asarray(oracle.cache[name])[:, :, src]
        # compare exactly the PROMPT positions — the oracle engine's
        # first decode step already wrote position p into its pool, and
        # trailing offsets of the last block are junk on both sides
        for j in range(nb):
            v = min(bs, p - j * bs)
            np.testing.assert_array_equal(
                got[:, :, j, :v], want[:, :, j, :v],
                err_msg=f"{name} block {j}")
    assert nb == len(dst)


def test_int8_wire_on_fp32_pool_within_codec_tolerance():
    """int8 wire over an fp32 pool: the landed K/V match local prefill
    within the blockwise codec's round-trip tolerance."""
    req = Request("x", list(range(1, 20)), max_new_tokens=4)
    _, h = _prefill_one(req, wire_mode="int8")
    d = _install_on_decode(h, wire_mode="int8")
    oracle = _local_engine_cache(req)
    dst = _slot_blocks(d.engine, h.prompt_len)
    src = _slot_blocks(oracle, h.prompt_len)
    bs = d.engine.kv_cfg.block_size
    p = h.prompt_len
    worst = 0.0
    for name in ("k", "v"):
        got = np.asarray(d.engine.cache[name])[:, :, dst]
        want = np.asarray(oracle.cache[name])[:, :, src]
        for j in range(h.n_blocks):
            v = min(bs, p - j * bs)
            g = got[:, :, j, :v].astype(np.float64)   # (L, H, v, D)
            w = want[:, :, j, :v].astype(np.float64)
            # codec bound: half a code step per element, scale =
            # absmax/127 per (L, H, token) head_dim vector
            tol = (np.abs(w).max(axis=-1, keepdims=True) / 127.0 * 0.51
                   + 1e-7)
            err = np.abs(g - w)
            assert (err <= tol).all(), name
            worst = max(worst, float(err.max()))
    assert worst > 0  # genuinely lossy, not a no-op


def test_wire_bytes_model_agrees_and_int8_reduces():
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=8, block_size=8, dtype=jnp.float32)
    cache = init_kv_cache(kv)
    ids = jnp.asarray([0, 3, 5], jnp.int32)
    for mode in ("raw", "int8"):
        payload = jax.jit(
            lambda c, i, m=mode: pack_blocks(c, kv, i, wire_mode=m)
        )(cache, ids)
        host = {k: np.asarray(v) for k, v in payload.items()}
        assert payload_nbytes(host, 3) == transfer_wire_bytes(kv, 3, mode)
    raw = transfer_wire_bytes(kv, 3, "raw")
    q = transfer_wire_bytes(kv, 3, "int8")
    assert raw / q >= 2.0  # head_dim=8: 4 / 1.5; >=3.5x at head_dim>=32
    kv64 = KVCacheConfig(num_layers=2, num_heads=4, head_dim=64,
                         num_blocks=8, block_size=8, dtype=jnp.float32)
    assert (transfer_wire_bytes(kv64, 3, "raw")
            / transfer_wire_bytes(kv64, 3, "int8")) >= 3.5
    # int8 POOL: both wire modes are the codes+scales representation
    kvq = KVCacheConfig(num_layers=2, num_heads=4, head_dim=64,
                        num_blocks=8, block_size=8, quantized=True)
    assert (transfer_wire_bytes(kvq, 3, "raw")
            == transfer_wire_bytes(kvq, 3, "int8"))
    # int4 POOL: packed codes + bf16 group scales ship verbatim — the
    # model equals the measured payload and halves the int8 wire
    kv4 = KVCacheConfig(num_layers=2, num_heads=4, head_dim=64,
                        num_blocks=8, block_size=8, quantized=True, bits=4)
    payload = jax.jit(
        lambda c, i: pack_blocks(c, kv4, i, wire_mode="raw")
    )(init_kv_cache(kv4), ids)
    host = {k: np.asarray(v) for k, v in payload.items()}
    assert payload_nbytes(host, 3) == transfer_wire_bytes(kv4, 3, "raw")
    assert (transfer_wire_bytes(kvq, 3, "raw")
            / transfer_wire_bytes(kv4, 3, "raw")) == pytest.approx(2.0)


def test_sim_transport_latency_and_totals():
    tr = SimTransport(fixed_ms=2.0, gib_per_s=1.0)
    mib = 1 << 20
    d = tr.send("item", 512 * mib, t_ms=100.0)
    assert d.transfer_ms == pytest.approx(2.0 + 500.0)
    assert tr.poll(101.0) == []
    assert tr.in_flight == 1
    got = tr.poll(700.0)
    assert [g.item for g in got] == ["item"] and tr.in_flight == 0
    assert tr.wire_bytes_total == 512 * mib
    assert tr.transfers_total == 1


# ---------------------------------------------------------------------------
# ACCEPTANCE: disaggregated parity vs the single engine


def _single_engine_streams(scfg, reqs):
    return InferenceEngine(PARAMS, CFG, scfg).run(reqs)


@pytest.mark.parametrize("kv_quant,wire_mode,greedy", [
    ("none", "raw", True),
    ("none", "raw", False),
    ("int8", "raw", True),
    ("int8", "int8", False),
    ("int4", "raw", True),
    ("int4", "raw", False),
])
def test_cluster_streams_bitwise_equal_single_engine(kv_quant, wire_mode,
                                                     greedy):
    """The parity gate: multi-host cluster streams == single-engine
    streams, bitwise, greedy AND sampled (int8/int4 pools ship
    codes+scales verbatim, so even the quantized stacks are exact)."""
    sampling = (SamplingConfig() if greedy
                else SamplingConfig(temperature=0.7, top_k=13))
    scfg = _serve_cfg(kv_quant=kv_quant, sampling=sampling)
    ref = _single_engine_streams(scfg, REQS)
    cl = _cluster(scfg, n_prefill=2, n_decode=2, wire_mode=wire_mode)
    out = cl.run(REQS, max_steps=20000)
    assert not cl.shed
    assert set(out) == set(ref)
    for uid in ref:
        assert out[uid] == ref[uid], uid


def test_cluster_parity_with_speculation_and_link_latency():
    """Speculative decode on the decode hosts + a laggy link change
    nothing about the streams (acceptance is the engine's own verify)."""
    scfg = _serve_cfg(spec_k=3)
    ref = _single_engine_streams(_serve_cfg(), REQS)
    cl = _cluster(scfg, n_prefill=1, n_decode=2, link_fixed_ms=5.0)
    out = cl.run(REQS, max_steps=20000)
    assert out == ref


# ---------------------------------------------------------------------------
# Router: WFQ fairness, feasibility shedding, terminal states


def test_router_wfq_respects_weights_under_saturation():
    r = Router(RouterConfig(tenant_weights={"a": 3.0, "b": 1.0}))
    for i in range(80):
        r.submit(Request(f"a{i}", [1] * 10, tenant="a"), t_ms=0.0)
        r.submit(Request(f"b{i}", [1] * 10, tenant="b"), t_ms=0.0)
    order = []
    for _ in range(40):
        item, sheds = r.next_request(backlog_tokens=0, t_ms=0.0)
        assert item is not None and not sheds
        order.append(item[0].tenant)
    na, nb = order.count("a"), order.count("b")
    assert na / nb == pytest.approx(3.0, abs=0.5)
    # deterministic: same construction, same order
    r2 = Router(RouterConfig(tenant_weights={"a": 3.0, "b": 1.0}))
    for i in range(80):
        r2.submit(Request(f"a{i}", [1] * 10, tenant="a"), t_ms=0.0)
        r2.submit(Request(f"b{i}", [1] * 10, tenant="b"), t_ms=0.0)
    order2 = [r2.next_request(0, 0.0)[0][0].tenant for _ in range(40)]
    assert order2 == order


def test_router_feasibility_sheds_terminal():
    r = Router(RouterConfig(slo=SloSpec(ttft_ms=100.0)))
    # calibrate: 1 ms per token measured
    r.observe_chunk(tokens=8, ms=8.0)
    r.submit(Request("fits", [1] * 10), t_ms=0.0)
    r.submit(Request("too_big", [1] * 10), t_ms=0.0)
    item, sheds = r.next_request(backlog_tokens=50, t_ms=0.0)
    assert item is not None and item[0].uid == "fits" and not sheds
    # 500-token backlog: predicted ttft ~510 ms >> 100 ms budget
    item, sheds = r.next_request(backlog_tokens=500, t_ms=0.0)
    assert item is None
    assert [d.request.uid for d in sheds] == ["too_big"]
    assert sheds[0].reason == "infeasible"
    assert sheds[0].predicted_ttft_ms > 100.0
    st = r.stats()
    assert st["shed"] == 1 and st["admitted"] == 1
    assert st["shed_rate"] == 0.5


def test_router_late_tenant_cannot_replay_idle_service():
    """A tenant arriving after another has accrued service starts at the
    global virtual clock — it cannot monopolize dispatch to 'catch up'
    on service it never queued for."""
    r = Router(RouterConfig(tenant_weights={"a": 1.0, "b": 1.0}))
    # tenant a alone accrues lots of service (queue drains in between)
    for i in range(50):
        r.submit(Request(f"a{i}", [1] * 10, tenant="a"), t_ms=0.0)
        assert r.next_request(0, 0.0)[0][0].tenant == "a"
    # b arrives late; with both now contending, service must alternate
    for i in range(20):
        r.submit(Request(f"A{i}", [1] * 10, tenant="a"), t_ms=0.0)
        r.submit(Request(f"B{i}", [1] * 10, tenant="b"), t_ms=0.0)
    order = [r.next_request(0, 0.0)[0][0].tenant for _ in range(20)]
    assert order.count("a") == pytest.approx(10, abs=2)
    assert order.count("b") == pytest.approx(10, abs=2)


def test_cluster_step_reports_progress_while_transfer_in_flight():
    """A handoff on a laggy wire counts as pending progress — a driver
    polling step() (loadgen.run_workload's contract) must not declare
    the cluster drained while transfers are in flight."""
    scfg = _serve_cfg()
    cl = _cluster(scfg, n_prefill=1, n_decode=1, link_fixed_ms=50.0)
    cl.submit(Request("x", [1, 2, 3], max_new_tokens=2))
    progressed = True
    deadline = 20000
    saw_inflight_progress = False
    while cl.active and deadline:
        progressed = cl.step()
        if cl.transport.in_flight:
            assert progressed  # the wire is work, not idleness
            saw_inflight_progress = True
        deadline -= 1
    assert saw_inflight_progress
    assert cl.completed == 1


def test_router_cold_start_admits():
    r = Router(RouterConfig(slo=SloSpec(ttft_ms=1.0)))
    r.submit(Request("x", [1] * 500), t_ms=0.0)
    item, sheds = r.next_request(backlog_tokens=10**6, t_ms=0.0)
    assert item is not None and not sheds  # no calibration -> admit


def test_router_unservable_shed_at_submit():
    r = Router(RouterConfig())
    d = r.submit(Request("huge", [1] * 100, max_new_tokens=100), t_ms=0.0,
                 total_tokens=200, max_servable_tokens=64)
    assert d is not None and d.reason == "unservable"
    assert r.queue_depth == 0 and r.shed == 1


# ---------------------------------------------------------------------------
# ACCEPTANCE: overload sheds, never deadlocks, goodput holds


def test_overload_sheds_and_never_deadlocks():
    """Offered load far beyond capacity: the cluster sheds (counters +
    events recorded) and completes without raising; kept traffic stays
    within its SLO at a good_fraction comparable to the at-capacity run.
    Driven on a MANUAL clock (EventLog(clock=...)) — every cluster tick
    advances 200 "ms" — so queue-wait, TTFT and the feasibility
    predictor are deterministic, not wall-time."""
    slo = SloSpec(ttft_ms=20000.0)
    scfg = _serve_cfg(num_slots=2)

    def run(n_requests):
        clock = {"t": 0.0}
        events = EventLog(keep=True, clock=lambda: clock["t"])
        ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=scfg,
                             router=RouterConfig(slo=slo))
        cl = ServeCluster(PARAMS, CFG, ccfg, events=events)
        rng = np.random.default_rng(3)
        reqs = [Request(f"r{i}", rng.integers(0, 97, size=24).tolist(),
                        max_new_tokens=8) for i in range(n_requests)]
        for r in reqs:
            cl.submit(r)  # all arrive at once — a pure burst
        steps = 0
        while cl.active and steps < 200000:
            cl.step()
            clock["t"] += 0.2  # 200 ms of model time per tick
            steps += 1
        st = cl.stats()
        assert st["completed"] + len(cl.shed) == n_requests  # drained
        return cl, st, events

    cl_cap, st_cap, _ = run(3)            # at capacity: nothing sheds
    cl_ov, st_ov, ev = run(64)            # >20x: queue wait forces sheds
    assert st_cap["router"]["shed"] == 0
    assert st_cap["slo_report"]["good_fraction"] == 1.0
    assert st_ov["router"]["shed"] > 0
    assert st_ov["shed_rate"] > 0
    assert st_ov["completed"] > 0        # degraded, not collapsed
    # every shed is a terminal state with an event record
    shed_events = [r for r in ev.records
                   if r.get("kind") == "event" and r["event"] == "shed"]
    assert {r["uid"] for r in shed_events} == set(cl_ov.shed)
    assert all(r["reason"] == "infeasible" for r in shed_events)
    # the kept traffic still meets its budgets about as well as the
    # uncongested run (goodput-under-SLO degrades gracefully)
    gf_cap = st_cap["slo_report"]["good_fraction"]
    gf_ov = st_ov["slo_report"]["good_fraction"]
    assert gf_ov is not None and gf_ov >= gf_cap - 0.5


def test_unservable_request_sheds_instead_of_deadlock():
    scfg = _serve_cfg(num_slots=1, num_blocks=4)  # 32-token pool
    cl = _cluster(scfg, n_decode=1)
    cl.run([Request("huge", list(range(40)), max_new_tokens=20)],
           max_steps=1000)
    assert "huge" in cl.shed
    assert cl.shed["huge"].reason == "unservable"
    assert cl.completed == 0


# ---------------------------------------------------------------------------
# Engine satellite: on_reject structured rejection


def test_engine_on_reject_hook():
    scfg = ServeConfig(num_slots=1, block_size=8, num_blocks=4,
                       prefill_chunk=8)
    big = Request("big", list(range(30)), max_new_tokens=20)
    # default: deadlock-loud
    eng = InferenceEngine(PARAMS, CFG, scfg)
    with pytest.raises(RuntimeError, match="pool is"):
        eng.run([big])
    # with the hook: structured rejection, run() returns, serving goes on
    rejections = []
    eng2 = InferenceEngine(PARAMS, CFG, scfg,
                           on_reject=lambda r, info: rejections.append(
                               (r.uid, info)))
    small = Request("small", [1, 2, 3], max_new_tokens=3)
    out = eng2.run([big, small])
    assert [u for u, _ in rejections] == ["big"]
    info = rejections[0][1]
    assert info["reason"] == "pool_exhausted"
    assert info["needed_blocks"] > info["pool_blocks"] - 0
    assert info["needed_blocks"] > info["free_blocks"]
    assert "small" in out and len(out["small"]) == 3
    assert eng2.stats()["rejected"] == 1


# ---------------------------------------------------------------------------
# loadgen satellite: tenant tagging


def test_loadgen_tenants_deterministic_and_weighted():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks"))
    import loadgen

    cfg = loadgen.WorkloadConfig(n_requests=200, n_tenants=2,
                                 tenant_weights=(3.0, 1.0), seed=5)
    w1 = loadgen.build_workload(cfg, vocab_size=97, max_context=64)
    w2 = loadgen.build_workload(cfg, vocab_size=97, max_context=64)
    assert [(t, r.uid, r.tenant, list(r.tokens)) for t, r in w1] == \
           [(t, r.uid, r.tenant, list(r.tokens)) for t, r in w2]
    counts = {}
    for _, r in w1:
        counts[r.tenant] = counts.get(r.tenant, 0) + 1
    assert set(counts) == {"t0", "t1"}
    assert counts["t0"] / counts["t1"] == pytest.approx(3.0, rel=0.4)
    # default stays tenant-free AND bit-identical to the pre-tenant draw
    base = loadgen.WorkloadConfig(n_requests=20, seed=5)
    w0 = loadgen.build_workload(base, vocab_size=97, max_context=64)
    assert all(r.tenant == "default" for _, r in w0)
    with pytest.raises(ValueError, match="tenant_weights"):
        loadgen.WorkloadConfig(n_requests=4, n_tenants=2,
                               tenant_weights=(1.0,)).validate()


# ---------------------------------------------------------------------------
# regress satellite: polarity of the new headline fields


def test_regress_polarity_covers_cluster_fields():
    assert classify_metric("shed_rate") == "lower"
    assert classify_metric("overload.shed_rate") == "lower"
    assert classify_metric("transfer_ms_p50") == "lower"
    assert classify_metric("transfer.transfer_ms_total") == "lower"
    assert classify_metric("admitted_rps") == "higher"
    assert classify_metric("goodput_rps") == "higher"
    # prefix coverage intact (ordering: _HIGHER first)
    assert classify_metric("prefix_hit_rate") == "higher"


# ---------------------------------------------------------------------------
# Events: transfer span + shed terminal in the trace


def test_transfer_span_and_shed_event_in_trace():
    events = EventLog(keep=True)
    ccfg = ClusterConfig(n_prefill=1, n_decode=1, serve=_serve_cfg(),
                         router=RouterConfig(slo=SloSpec(ttft_ms=600000.0)),
                         link_fixed_ms=1.0)
    cl = ServeCluster(PARAMS, CFG, ccfg, events=events)
    cl.run(REQS[:3], max_steps=20000)
    spans = request_spans(events.records)
    for uid in ("a", "b", "c"):
        names = {s["name"] for s in spans[uid]}
        assert {"queued", "prefill", "transfer", "decode"} <= names
        tr = [s for s in spans[uid] if s["name"] == "transfer"][0]
        assert tr["t1_ms"] >= tr["t0_ms"]
    trace = chrome_trace(events.records)
    x_names = {e["name"] for e in trace["traceEvents"]
               if e.get("ph") == "X"}
    assert "transfer" in x_names
    # lifecycle ordering on the one shared clock
    by_uid = {}
    for r in events.records:
        if r.get("kind") == "event" and r.get("uid") == "a":
            by_uid.setdefault(r["event"], r["t_ms"])
    order = ["submitted", "prefill_start", "prefill_end", "first_token",
             "transfer_start", "transfer_end", "admitted", "retired"]
    ts = [by_uid[e] for e in order]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# Compile-count gate: the cluster mints no extra programs


def test_cluster_compile_counts():
    scfg = _serve_cfg()
    cl = _cluster(scfg, n_prefill=1, n_decode=2)
    cl.run(REQS, max_steps=20000)
    counts = cl.compile_counts()
    for w in counts["prefill"]:
        assert w["chunk_prefill"] in (1, None)
        assert w["extract"] in (1, None)
    for w in counts["decode"]:
        assert w["decode"] in (1, None)
        assert w["insert"] in (1, None)
        assert w["chunk_prefill"] in (0, None)  # decode hosts never prefill


# ---------------------------------------------------------------------------
# Stats: JSON round-trip + headline fields present


def test_cluster_stats_json_and_headlines():
    import json

    scfg = _serve_cfg()
    cl = _cluster(scfg, n_prefill=1, n_decode=2,
                  slo=SloSpec(ttft_ms=600000.0, tpot_ms=600000.0))
    cl.run(REQS, max_steps=20000)
    st = cl.stats()
    json.dumps(st)  # JSON-serializable end to end
    assert st["completed"] == len(REQS)
    assert st["shed_rate"] == 0.0
    assert st["admitted_rps"] > 0
    assert st["transfer"]["transfers"] == len(REQS)
    assert st["transfer"]["wire_bytes_total"] == sum(
        transfer_wire_bytes(
            cl.prefill_workers[0].kv_cfg,
            cl.prefill_workers[0].kv_cfg.blocks_for_tokens(len(r.tokens)),
            "raw")
        for r in REQS)
    assert st["slo_report"]["completed"] == len(REQS)
    assert st["slo_report"]["good"] == len(REQS)
    assert st["goodput_rps"] > 0
    assert "ttft_ms_p50" in st and "transfer_ms_p50" in st
    # work actually spread over both decode hosts
    assert sum(h["completed"] for h in st["decode_hosts"]) == len(REQS)


def test_cluster_config_validation():
    with pytest.raises(ValueError, match="n_prefill"):
        ClusterConfig(n_prefill=0).validate()
    with pytest.raises(ValueError, match="wire_mode"):
        ClusterConfig(wire_mode="fp4").validate()
    with pytest.raises(ValueError, match="weight"):
        RouterConfig(tenant_weights={"a": -1.0}).validate()
    with pytest.raises(ValueError, match="shed_headroom"):
        RouterConfig(shed_headroom=0.0).validate()

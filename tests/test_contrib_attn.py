"""Contrib MHA + FMHA tests — ref apex/contrib/test/multihead_attn/* (fused
vs torch.nn.MultiheadAttention-style reference) and test/fmha/test_fmha.py
(packed varlen vs per-sequence dense attention)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.contrib.fmha import cu_seqlens_to_segment_ids, fmha_packed
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.ops.attention import attention_reference

B, S, E, H = 2, 16, 32, 4


def _mha_reference(x, params, num_heads, kpm=None, am=None, additive=False):
    """Dense reference with the same parameterization."""
    e = x.shape[-1]
    qkv = x @ params["in_proj_weight"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def split(t):
        b, s, _ = t.shape
        return t.reshape(b, s, num_heads, e // num_heads).transpose(0, 2, 1, 3)

    q, k, v = split(q), split(k), split(v)
    s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(e // num_heads)
    if additive and am is not None:
        s_ = s_ + am
    elif am is not None:
        s_ = jnp.where(am[None, None], -1e30, s_)
    if kpm is not None:
        s_ = jnp.where(kpm[:, None, None, :], -1e30, s_)
    p = jax.nn.softmax(s_, axis=-1)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    b, h, sq, d = ctx.shape
    out = ctx.transpose(0, 2, 1, 3).reshape(b, sq, h * d)
    return out @ params["out_proj_weight"]


def test_self_mha_matches_dense_reference():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, E))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    got = m.apply({"params": params}, x, is_training=False)
    want = _mha_reference(x, params, H)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_self_mha_key_padding_and_attn_mask():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(2), (B, S, E))
    params = m.init(jax.random.PRNGKey(3), x)["params"]
    kpm = jnp.arange(S)[None, :] >= jnp.asarray([[12], [9]])  # pads per batch
    am = jnp.triu(jnp.ones((S, S), bool), k=1)  # causal
    got = m.apply({"params": params}, x, key_padding_mask=kpm, attn_mask=am,
                  is_training=False)
    want = _mha_reference(x, params, H, kpm=kpm, am=am)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_self_mha_additive_mask():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, mask_additive=True)
    x = jax.random.normal(jax.random.PRNGKey(4), (B, S, E))
    params = m.init(jax.random.PRNGKey(5), x)["params"]
    am = jax.random.normal(jax.random.PRNGKey(6), (S, S)) * 0.5
    got = m.apply({"params": params}, x, attn_mask=am, is_training=False)
    want = _mha_reference(x, params, H, am=am, additive=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_self_mha_norm_add_residual():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
    x = jax.random.normal(jax.random.PRNGKey(7), (B, S, E))
    params = m.init(jax.random.PRNGKey(8), x)["params"]
    got = m.apply({"params": params}, x, is_training=False)
    # residual path: output must differ from x but correlate (x + attn(ln(x)))
    from apex_tpu.ops.layer_norm import layer_norm_reference

    ln = layer_norm_reference(x, params["ln_weight"], params["ln_bias"])
    want = x + _mha_reference(ln, params, H)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_encdec_mha_matches_dense():
    m = EncdecMultiheadAttn(embed_dim=E, num_heads=H)
    q = jax.random.normal(jax.random.PRNGKey(9), (B, 8, E))
    kv = jax.random.normal(jax.random.PRNGKey(10), (B, S, E))
    params = m.init(jax.random.PRNGKey(11), q, kv)["params"]
    got = m.apply({"params": params}, q, kv, is_training=False)

    qq = q @ params["q_weight"]
    k, v = jnp.split(kv @ params["kv_weight"], 2, axis=-1)

    def split(t):
        b, s, _ = t.shape
        return t.reshape(b, s, H, E // H).transpose(0, 2, 1, 3)

    o = attention_reference(split(qq), split(k), split(v))
    b, h, sq, d = o.shape
    want = o.transpose(0, 2, 1, 3).reshape(b, sq, h * d) @ params[
        "out_proj_weight"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_mha_dropout_only_when_training():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.5)
    x = jax.random.normal(jax.random.PRNGKey(12), (B, S, E))
    params = m.init({"params": jax.random.PRNGKey(13),
                     "dropout": jax.random.PRNGKey(14)}, x)["params"]
    eval_out = m.apply({"params": params}, x, is_training=False)
    train_out = m.apply({"params": params}, x, is_training=True,
                        rngs={"dropout": jax.random.PRNGKey(15)})
    assert not np.allclose(np.asarray(eval_out), np.asarray(train_out))
    # eval path is deterministic
    np.testing.assert_array_equal(
        np.asarray(eval_out),
        np.asarray(m.apply({"params": params}, x, is_training=False)))


# ---------------------------------------------------------------------------
# FMHA packed varlen


def test_cu_seqlens_to_segment_ids():
    cu = jnp.asarray([0, 3, 5, 9])
    seg = cu_seqlens_to_segment_ids(cu, 11)
    np.testing.assert_array_equal(
        np.asarray(seg), [0, 0, 0, 1, 1, 2, 2, 2, 2, -1, -1])


def test_fmha_packed_matches_per_sequence_dense():
    h, d = 2, 8
    lens = [5, 3, 8]
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    total = sum(lens)
    qkv = jax.random.normal(jax.random.PRNGKey(16), (total, 3, h, d))
    out = fmha_packed(qkv, cu)
    # compare each sequence against dense attention on its own slice
    start = 0
    for L in lens:
        sl = slice(start, start + L)
        q = qkv[sl, 0].transpose(1, 0, 2)[None]
        k = qkv[sl, 1].transpose(1, 0, 2)[None]
        v = qkv[sl, 2].transpose(1, 0, 2)[None]
        want = attention_reference(q, k, v)[0].transpose(1, 0, 2)
        np.testing.assert_allclose(
            np.asarray(out[sl]), np.asarray(want), atol=2e-5,
            err_msg=f"seq at {start}:{start+L}")
        start += L


def test_fmha_packed_causal_and_padding():
    h, d = 1, 4
    cu = jnp.asarray([0, 4, 6], jnp.int32)
    qkv = jax.random.normal(jax.random.PRNGKey(17), (8, 3, h, d))  # 2 pad toks
    out = fmha_packed(qkv, cu, causal=True)
    # token 0 attends only to itself -> output == v[0]
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(qkv[0, 2]), atol=2e-5)
    # grads never cross sequence boundaries
    g = jax.grad(lambda qkv: jnp.sum(fmha_packed(qkv, cu)[0:4]))(qkv)
    assert np.abs(np.asarray(g[4:6])).max() == 0.0

"""Flash-attention kernel tests — ref apex/contrib/test/fmha/test_fmha.py and
multihead_attn tests: fused kernel vs pure reference, fwd + bwd, causal and
masked, fp32 and bf16 (Pallas interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import (
    attention_reference,
    flash_attention,
    flash_attention_with_lse,
)


def _qkv(key, b, h, sq, sk, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, sq, d), dtype=jnp.float32)
    k = jax.random.normal(kk, (b, h, sk, d), dtype=jnp.float32)
    v = jax.random.normal(kv, (b, h, sk, d), dtype=jnp.float32)
    return q.astype(dtype), k.astype(dtype), v.astype(dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flash_forward_matches_reference(causal, dtype):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 3, 64, 64, 32, dtype)
    got = flash_attention(q, k, v, causal=causal, use_pallas=True)
    want = attention_reference(q, k, v, causal=causal)
    atol = 2e-5 if dtype == jnp.float32 else 3e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=atol
    )


def test_flash_cross_attention_rectangular():
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 2, 32, 128, 16)
    got = flash_attention(q, k, v, use_pallas=True, block_q=16, block_k=32)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_q,block_k", [(32, 32), (32, 16), (16, 32)])
def test_flash_backward_matches_reference(causal, block_q, block_k):
    # unequal blocks exercise both directions of the causal-diagonal index
    # clamp ((i*bq+bq-1)//bk forward, (j*bk)//bq in dK/dV)
    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 2, 64, 64, 32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, use_pallas=True,
                            block_q=block_q, block_k=block_k)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), atol=1e-4, err_msg=name
        )


def test_mask_path_falls_back_to_reference():
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 1, 16, 16, 8)
    # padding mask: last 5 keys masked out
    mask = jnp.arange(16)[None, None, None, :] >= 11
    got = flash_attention(q, k, v, mask=mask)
    want = attention_reference(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    # masked keys must not receive grad through v
    g = jax.grad(lambda v: jnp.sum(flash_attention(q, k, v, mask=mask)))(v)
    assert np.abs(np.asarray(g)[:, :, 11:, :]).max() == 0.0


def test_lse_variant_matches_log_sum_exp():
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 1, 32, 32, 16)
    scale = 1.0 / np.sqrt(16)
    o, lse = flash_attention_with_lse(
        q.reshape(1, 32, 16), k.reshape(1, 32, 16), v.reshape(1, 32, 16),
        scale, False, 16, 16, True)
    s = np.einsum("bqd,bkd->bqk", np.asarray(q[0]), np.asarray(k[0])) * scale
    want_lse = np.log(np.sum(np.exp(s), axis=-1))
    np.testing.assert_allclose(np.asarray(lse), want_lse, atol=1e-5)


def test_flash_is_jittable():
    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 2, 32, 32, 16)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                use_pallas=True))
    got = f(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bias_matches_reference(causal):
    """Additive (heads, sq, sk) logit bias (the T5 relative-position-bias
    contract) inside the Pallas kernels: fwd and all four grads (q, k, v,
    AND bias — the batch-reducing dbias kernel) vs the dense reference."""
    q, k, v = _qkv(jax.random.PRNGKey(6), 2, 3, 64, 64, 32)
    bias = jax.random.normal(jax.random.PRNGKey(7), (3, 64, 64)) * 2.0

    def loss_flash(q, k, v, bias):
        o = flash_attention(q, k, v, causal=causal, use_pallas=True,
                            block_q=32, block_k=32, bias=bias)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v, bias):
        return jnp.sum(jnp.sin(attention_reference(
            q, k, v, causal=causal, bias=bias)))

    np.testing.assert_allclose(float(loss_flash(q, k, v, bias)),
                               float(loss_ref(q, k, v, bias)), rtol=1e-5)
    g1 = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, e, name in zip(g1, g2, ("q", "k", "v", "bias")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), atol=2e-4,
            err_msg=f"d{name} mismatch (causal={causal})")


def test_flash_bias_rectangular_cross_attn_shape():
    """Bias on a rectangular (sq != sk) non-causal core — the enc-dec
    geometry — stays on the Pallas path and matches the reference."""
    q, k, v = _qkv(jax.random.PRNGKey(8), 1, 2, 32, 128, 16)
    bias = jax.random.normal(jax.random.PRNGKey(9), (2, 32, 128))
    got = flash_attention(q, k, v, use_pallas=True, block_q=16, block_k=32,
                          bias=bias)
    want = attention_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_bias_shape_validated():
    q, k, v = _qkv(jax.random.PRNGKey(10), 2, 2, 16, 16, 8)
    with pytest.raises(ValueError, match="batch-shared"):
        flash_attention(q, k, v, bias=jnp.zeros((2, 2, 16, 16)))


def test_flash_bwd_kernels_respect_global_offsets():
    """The [seed, q_off, k_off] operand in the BACKWARD kernels (reviewer
    find: only the forward had off-TPU offset coverage): chunked _fa_bwd
    calls against the global lse with per-chunk k offsets must reproduce
    the dense kernel's gradients — the ring-SP backward contract, in
    interpret mode."""
    from apex_tpu.ops.attention import _fa_bwd, _fa_fwd, flash_attention

    b, h, s, d = 1, 2, 256, 16
    rate, seed, scale = 0.3, 99, 1.0 / d ** 0.5
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)

    def loss(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=False, dropout_rate=rate,
            dropout_seed=jnp.int32(seed), use_pallas=True,
            interpret=True) ** 2)

    g_dense = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    bh, half = b * h, s // 2
    q3, k3, v3 = (x.reshape(bh, s, d) for x in (q, k, v))
    sv = lambda k_off: jnp.asarray([seed, 0, k_off], jnp.int32)
    o3, lse3 = _fa_fwd(q3, k3, v3, scale, False, 128, 128, interpret=True,
                       dropout_rate=rate, seed=sv(0))
    do3 = (2.0 * o3.astype(jnp.float32)).astype(o3.dtype)
    dq_sum, dks, dvs = 0.0, [], []
    for k_off in (0, half):
        dq_c, dk_c, dv_c, _ = _fa_bwd(
            q3, k3[:, k_off:k_off + half], v3[:, k_off:k_off + half],
            o3, lse3, do3, scale, False, 128, 128, interpret=True,
            dropout_rate=rate, seed=sv(k_off))
        dq_sum = dq_sum + dq_c
        dks.append(dk_c)
        dvs.append(dv_c)
    got = (dq_sum, jnp.concatenate(dks, 1), jnp.concatenate(dvs, 1))
    for a, e, name in zip(got, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a).reshape(b, h, s, d), np.asarray(e), atol=2e-4,
            err_msg=name)

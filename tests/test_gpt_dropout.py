"""Dropout-complete flagship GPT + in-kernel attention dropout.

Ref: ``standalone_gpt.py:285-735`` attention/hidden dropout sites and
``apex/contrib/csrc/multihead_attn`` / ``fmhalib`` fused (philox
counter-based) attention dropout; TP stream semantics from
``tensor_parallel/random.py`` (attention dropout differs per TP rank,
hidden dropout agrees across the TP group).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.attention import flash_attention
from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    replicate_loss,
)
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)

CFG = GPTConfig(vocab_size=256, max_seq=64, hidden=64, num_layers=2,
                num_heads=2, dtype=jnp.float32, remat=True,
                fused_loss=False, attention_dropout=0.1, hidden_dropout=0.1)


def _loss(cfg, tp=1, key=None):
    mesh = build_mesh(tp=tp, pp=1, sp=1,
                      devices=jax.devices()[:max(tp, 2) if tp > 1 else 1])
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)
    tgt = jnp.roll(tok, -1, 1)
    specs = gpt_param_specs(cfg)

    def body(p, tok, tgt):
        return replicate_loss(
            gpt_loss(p, tok, tgt, cfg, dropout_key=key), mesh,
            masked_axis=None)

    return float(jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(specs, P(), P()),
        out_specs=P()))(params, tok, tgt))


def test_dropout_train_step_deterministic_and_key_sensitive():
    k1, k2 = jax.random.split(jax.random.PRNGKey(42))
    a = _loss(CFG, key=k1)
    b = _loss(CFG, key=k1)
    c = _loss(CFG, key=k2)
    d = _loss(CFG, key=None)  # eval mode: dropout off
    assert np.isfinite([a, b, c, d]).all()
    assert a == b, "same dropout key must replay the same masks"
    assert a != c, "different dropout keys must differ"
    assert a != d, "dropout must change the loss vs eval mode"


def test_dropout_grads_flow_under_remat():
    cfg = CFG
    mesh = build_mesh(tp=1, pp=1, sp=1, devices=jax.devices()[:1])
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)
    tgt = jnp.roll(tok, -1, 1)
    specs = gpt_param_specs(cfg)
    key = jax.random.PRNGKey(7)

    def body(p, tok, tgt):
        return replicate_loss(
            gpt_loss(p, tok, tgt, cfg, dropout_key=key), mesh,
            masked_axis=None)

    f = jax.jit(jax.value_and_grad(lambda p: jax.shard_map(
        body, mesh=mesh, in_specs=(specs, P(), P()),
        out_specs=P())(p, tok, tgt)))
    (l1, g1), (l2, g2) = f(params), f(params)
    assert np.isfinite(float(l1))
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        assert jnp.all(jnp.isfinite(a))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dropout_tp2_runs_and_differs_from_tp1_masks():
    # tp=2 must execute (attention dropout seeds fold the TP rank); the
    # resulting loss differs from tp=1 because each rank drops its own
    # entries — while WITHOUT dropout tp=2 matches tp=1 exactly
    key = jax.random.PRNGKey(3)
    with_do_tp2 = _loss(CFG, tp=2, key=key)
    assert np.isfinite(with_do_tp2)
    # TP-rank-folded attention seeds: tp=2 drops different entries than tp=1
    assert with_do_tp2 != _loss(CFG, tp=1, key=key)
    nodrop = dataclasses.replace(CFG, attention_dropout=0.0,
                                 hidden_dropout=0.0)
    np.testing.assert_allclose(
        _loss(nodrop, tp=1), _loss(nodrop, tp=2), rtol=1e-3)


_SP_LOSS_CACHE = {}


def _sp_loss(cfg, key, sp=2):
    """Loss of the sp-sharded GPT; the jitted program is cached per
    (cfg, sp, dropout-on) so repeated calls with different key VALUES
    share one compile."""
    ck = (cfg, sp, key is not None)
    if ck not in _SP_LOSS_CACHE:
        mesh = build_mesh(tp=1, pp=1, sp=sp, devices=jax.devices()[:sp])
        specs = gpt_param_specs(cfg)

        if key is not None:
            def f(p, tok, tgt, key):
                def body(p, tok, tgt, key):
                    return replicate_loss(
                        gpt_loss(p, tok, tgt, cfg, dropout_key=key),
                        mesh, masked_axis=None)

                return jax.shard_map(
                    body, mesh=mesh,
                    in_specs=(specs, P(None, "sp"), P(None, "sp"), P()),
                    out_specs=P())(p, tok, tgt, key)
        else:
            def f(p, tok, tgt):
                def body(p, tok, tgt):
                    return replicate_loss(
                        gpt_loss(p, tok, tgt, cfg), mesh,
                        masked_axis=None)

                return jax.shard_map(
                    body, mesh=mesh,
                    in_specs=(specs, P(None, "sp"), P(None, "sp")),
                    out_specs=P())(p, tok, tgt)
        _SP_LOSS_CACHE[ck] = jax.jit(f)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)
    args = (params, tok, jnp.roll(tok, -1, 1))
    if key is not None:
        args += (key,)
    return float(_SP_LOSS_CACHE[ck](*args))


def test_sp_hidden_dropout_trains_and_is_key_sensitive():
    """Hidden dropout now runs under ring-SP (SP-rank-folded keys): the
    step executes, replays for a fixed key, and the masks are live."""
    cfg = dataclasses.replace(CFG, attention_dropout=0.0,
                              hidden_dropout=0.2)
    k1, k2 = jax.random.split(jax.random.PRNGKey(42))
    a, b, c = _sp_loss(cfg, k1), _sp_loss(cfg, k1), _sp_loss(cfg, k2)
    d = _sp_loss(cfg, None)  # eval mode
    assert np.isfinite([a, b, c, d]).all()
    assert a == b, "same dropout key must replay the same masks"
    assert a != c, "different dropout keys must differ"
    assert a != d, "dropout must change the loss vs eval mode"


def test_sp_hidden_dropout_shards_decorrelated():
    """The bug the old guard protected against: without the SP-rank fold
    every shard reuses ONE mask. Silence attention (zero out-proj) and
    feed identical activations to both shards — the only cross-shard
    difference left is the hidden-dropout mask, so differing shard
    outputs prove decorrelation (and the no-dropout control proves the
    harness: shards identical when masks are off)."""
    from apex_tpu.transformer.testing.standalone_gpt import _layer_stack

    cfg = dataclasses.replace(CFG, num_layers=1, attention_dropout=0.0,
                              hidden_dropout=0.5)
    mesh = build_mesh(tp=1, pp=1, sp=2, devices=jax.devices()[:2])
    layers = dict(init_gpt_params(jax.random.PRNGKey(0), cfg)["layers"])
    layers["out_kernel"] = jnp.zeros_like(layers["out_kernel"])
    layers["out_bias"] = jnp.zeros_like(layers["out_bias"])
    # same non-constant feature vector at every position (constant-vector
    # inputs would LN to zero and hide the masks behind a zero MLP output)
    x = jnp.broadcast_to(
        jax.random.normal(jax.random.PRNGKey(2), (cfg.hidden,)),
        (1, 64, cfg.hidden)).astype(jnp.float32)

    def run(key):
        def body(lp, x):
            out, _ = _layer_stack(lp, x, cfg, dropout_key=key)
            return out

        return np.asarray(jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(P(), P(None, "sp", None)),
            out_specs=P(None, "sp", None)))(layers, x))

    out = run(jax.random.PRNGKey(5))
    assert np.isfinite(out).all()
    assert not np.array_equal(out[:, :32], out[:, 32:]), \
        "sp shards must drop independent positions"
    control = run(None)
    np.testing.assert_array_equal(control[:, :32], control[:, 32:])


def test_sp_embedding_dropout_shards_decorrelated():
    """The embedding-site fold (_embed_with_dropout): identical tokens on
    both shards + zero position table -> identical embeddings per shard;
    distinct shard outputs isolate the embedding dropout mask."""
    from apex_tpu.transformer.testing.standalone_gpt import (
        _embed_with_dropout,
    )

    cfg = dataclasses.replace(CFG, attention_dropout=0.0,
                              hidden_dropout=0.5)
    mesh = build_mesh(tp=1, pp=1, sp=2, devices=jax.devices()[:2])
    embed = dict(init_gpt_params(jax.random.PRNGKey(0), cfg)["embed"])
    embed["pos"] = jnp.zeros_like(embed["pos"])
    tok = jnp.tile(jnp.arange(32, dtype=jnp.int32), 2)[None]  # shard halves equal

    def run(key):
        def body(e, tok):
            return _embed_with_dropout(e, tok, cfg, key)

        return np.asarray(jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp", None)))(embed, tok))

    out = run(jax.random.PRNGKey(9))
    assert not np.array_equal(out[:, :32], out[:, 32:]), \
        "sp shards must drop independent embedding positions"
    control = run(None)
    np.testing.assert_array_equal(control[:, :32], control[:, 32:])


def test_sp_full_dropout_config_trains():
    """Attention AND hidden dropout together under ring-SP (round 5: the
    attention guard fell to the global-position-keyed ring masks). The
    flagship training config — both rates active — must run, replay for a
    fixed key, and be key-sensitive at sp=2."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(21))
    a, b = _sp_loss(CFG, k1), _sp_loss(CFG, k1)
    c, d = _sp_loss(CFG, k2), _sp_loss(CFG, None)
    assert np.isfinite([a, b, c, d]).all()
    assert a == b, "same dropout key must replay the same masks"
    assert a != c, "different dropout keys must differ"
    assert a != d, "dropout must change the loss vs eval mode"
    # attention dropout alone must also be live under sp (not silently off)
    cfg_attn = dataclasses.replace(CFG, hidden_dropout=0.0)
    e = _sp_loss(cfg_attn, k1)
    assert np.isfinite(e) and e != _sp_loss(cfg_attn, None), \
        "attention dropout must actually drop under sp"


def test_sp_attention_dropout_layout_invariant():
    """The round-5 headline invariant at the MODEL level: with hidden
    dropout off, the attention dropout stream is keyed by global
    positions and an sp-invariant seed, so the sp=2 (ring) loss EQUALS
    the sp=1 (dense kernel) loss for the same key — sharding is
    invisible to the mask (reviewer find: an sp fold leaking into the
    attention seed silently broke this)."""
    cfg = dataclasses.replace(CFG, hidden_dropout=0.0, dtype=jnp.float32)
    key = jax.random.PRNGKey(33)
    np.testing.assert_allclose(_sp_loss(cfg, key, sp=2),
                               _sp_loss(cfg, key, sp=1), rtol=1e-5)


# ---------------------------------------------------------------------------
# kernel-level dropout (pallas interpret mode)

def test_flash_kernel_dropout_block_size_independent():
    key = jax.random.PRNGKey(0)
    q = (jax.random.normal(key, (2, 2, 256, 64)) * 0.5).astype(jnp.float32)
    f = lambda seed, bq, bk: flash_attention(
        q, q, q, causal=True, dropout_rate=0.1,
        dropout_seed=jnp.int32(seed), use_pallas=True,
        block_q=bq, block_k=bk)
    a, b = f(7, 256, 256), f(7, 128, 64)
    # identical masks (position-keyed hash); only accumulation-order noise
    np.testing.assert_allclose(a, b, atol=5e-3)
    c = f(8, 256, 256)
    assert float(jnp.max(jnp.abs(a - c))) > 0.05, "seed must change the mask"


def test_flash_kernel_dropout_grad_matches_finite_difference():
    key = jax.random.PRNGKey(1)
    q = (jax.random.normal(key, (1, 1, 64, 64)) * 0.5).astype(jnp.float32)

    def loss(qq):
        return jnp.sum(flash_attention(
            qq, qq, qq, causal=True, dropout_rate=0.2,
            dropout_seed=jnp.int32(3), use_pallas=True) ** 2)

    g = jax.grad(loss)(q)
    eps, idx = 1e-3, (0, 0, 5, 7)
    fd = (loss(q.at[idx].add(eps)) - loss(q.at[idx].add(-eps))) / (2 * eps)
    # same counter-based mask in fwd and both bwd kernels
    np.testing.assert_allclose(float(g[idx]), float(fd), rtol=5e-2)


def test_flash_kernel_dropout_keep_rate():
    # all-equal scores -> uniform attention; with v == 1 the output row is
    # (kept/(rows attended)) / (1-rate): its mean estimates keep probability
    s = 512
    q = jnp.zeros((1, 1, s, 64), jnp.float32)
    v = jnp.ones((1, 1, s, 64), jnp.float32)
    rate = 0.3
    o = flash_attention(q, q, v, causal=False, dropout_rate=rate,
                        dropout_seed=jnp.int32(11), use_pallas=True)
    # E[o] = 1 (inverted-dropout rescaling), variance ~ 1/(s * (1-r))
    assert abs(float(jnp.mean(o)) - 1.0) < 0.02
"""Dropout threading through the pipeline schedules.

Ref: Megatron's ParallelTransformer trains with dropout under every
schedule (stateful per-call RNG). Here the schedules route one derived
PRNG key per microbatch (interleaved: additionally folded by chunk) to
the spec's embed/stage functions; the routing must EQUAL a sequential
reference replaying the same key derivation, and the GPT fixture must
train under pp x sp with dropout active.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules import (
    PipelineSpec,
    build_model,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
)

HID = 8
B = 8
SEQ = 4
KEEP = 0.8


def _dropout_spec():
    """Toy spec whose embed/stage functions consume the routed key
    directly (bernoulli masks): schedule-level key routing is then
    testable EXACTLY; per-stage/axis decorrelation is the real model's
    job (tests/test_gpt_dropout.py)."""

    def embed_fn(ep, x, key):
        keep = jax.random.bernoulli(jax.random.fold_in(key, 1), KEEP,
                                    x.shape)
        return (x * keep) @ ep["w"]

    def stage_fn(sp, h, key):
        keep = jax.random.bernoulli(jax.random.fold_in(key, 2), KEEP,
                                    h.shape)
        return jnp.tanh((h * keep) @ sp["w"] + sp["b"])

    def loss_fn(hp, h, tgt):
        return jnp.mean((h @ hp["w"] - tgt) ** 2)

    return PipelineSpec(embed_fn, stage_fn, loss_fn,
                        takes_dropout_key=True)


def _params(rng, num_chunks, vp=None):
    k1, k2, k3 = jax.random.split(rng, 3)

    def stage_init(key, c):
        kw, kb = jax.random.split(key)
        return {
            "w": jax.random.normal(kw, (HID, HID)) * 0.3,
            "b": jax.random.normal(kb, (HID,)) * 0.1,
        }

    stages = build_model(stage_init, k1, num_chunks,
                         virtual_pipeline_size=vp)
    return {
        "embed": {"w": jax.random.normal(k2, (HID, HID)) * 0.3},
        "stages": stages,
        "head": {"w": jax.random.normal(k3, (HID, HID)) * 0.3},
    }


def _batch(rng, b=B):
    ki, kt = jax.random.split(rng)
    return (
        jax.random.normal(ki, (b, SEQ, HID)),
        jax.random.normal(kt, (b, SEQ, HID)),
    )


def _seq_reference(spec, params, batch, M, pp, key, vp=None):
    """Sequential ground truth replaying the schedules' key derivation:
    key_m = fold_in(key, m); interleaved chunks additionally fold r."""
    inputs, targets = batch

    def loss_of(p):
        def one_mb(x, t, m):
            key_m = jax.random.fold_in(key, m)
            h = spec.embed_fn(p["embed"], x, key_m)
            if vp is None:
                for s in range(pp):
                    sp = jax.tree.map(lambda a: a[s], p["stages"])
                    h = spec.stage_fn(sp, h, key_m)
            else:
                for v in range(vp):
                    for s in range(pp):
                        sp = jax.tree.map(lambda a: a[v, s], p["stages"])
                        h = spec.stage_fn(sp, h,
                                          jax.random.fold_in(key_m, v))
            return spec.loss_fn(p["head"], h, t)

        nb = inputs.shape[0]
        xs = inputs.reshape((M, nb // M) + inputs.shape[1:])
        ts = targets.reshape((M, nb // M) + targets.shape[1:])
        return jnp.mean(jax.vmap(one_mb)(xs, ts, jnp.arange(M)))

    return jax.jit(jax.value_and_grad(loss_of))(params)


def _assert_tree_close(a, b, atol=1e-5):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=atol, rtol=1e-4),
        a, b)


def test_no_pipelining_dropout_key_per_microbatch():
    spec = _dropout_spec()
    params = _params(jax.random.PRNGKey(0), 2)
    batch = _batch(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(5)

    def fwd(p, m, k):
        x, t = m
        h = spec.embed_fn(p["embed"], x, k)
        for s in range(2):
            h = spec.stage_fn(jax.tree.map(lambda a: a[s], p["stages"]),
                              h, k)
        return spec.loss_fn(p["head"], h, t)

    loss, grads = forward_backward_no_pipelining(
        fwd, batch, params, num_microbatches=4, dropout_key=key)
    want, gref = _seq_reference(spec, params, batch, 4, 2, key)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
    _assert_tree_close(grads, gref)


@pytest.mark.parametrize("M", [2, pytest.param(4, marks=pytest.mark.slow)])
def test_1f1b_dropout_matches_sequential(M):
    # (key-sensitivity is covered by the GPT integration test below — a
    # second uncached pipelined compile here would double the test cost)
    pp = 2
    mesh = build_mesh(tp=1, pp=pp, sp=1, devices=jax.devices()[:pp])
    spec = _dropout_spec()
    params = _params(jax.random.PRNGKey(0), pp)
    batch = _batch(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(7)
    loss, grads = forward_backward_pipelining_without_interleaving(
        spec, params, batch, num_microbatches=M, mesh=mesh,
        dropout_key=key)
    want, gref = _seq_reference(spec, params, batch, M, pp, key)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5,
                               atol=1e-6)
    _assert_tree_close(grads, gref)


@pytest.mark.slow
def test_interleaved_dropout_matches_sequential():
    # slow tier: the interleaved SCHEDULE parity (no dropout) runs in the
    # default tier (test_pipeline_parallel); this adds the chunk-fold
    # routing proof on top
    pp, vp, M = 2, 2, 4
    mesh = build_mesh(tp=1, pp=pp, sp=1, devices=jax.devices()[:pp])
    spec = _dropout_spec()
    params = _params(jax.random.PRNGKey(0), pp, vp=vp)
    batch = _batch(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(9)
    loss, grads = forward_backward_pipelining_with_interleaving(
        spec, params, batch, num_microbatches=M, virtual_pipeline_size=vp,
        mesh=mesh, dropout_key=key)
    want, gref = _seq_reference(spec, params, batch, M, pp, key, vp=vp)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5,
                               atol=1e-6)
    _assert_tree_close(grads, gref)


def test_dropout_key_spec_pairing_validated_both_ways():
    pp = 2
    mesh = build_mesh(tp=1, pp=pp, sp=1, devices=jax.devices()[:pp])
    params = _params(jax.random.PRNGKey(0), pp)
    batch = _batch(jax.random.PRNGKey(1))
    spec_plain = dataclasses.replace(_dropout_spec(),
                                     takes_dropout_key=False)
    with pytest.raises(ValueError, match="takes_dropout_key"):
        forward_backward_pipelining_without_interleaving(
            spec_plain, params, batch, num_microbatches=2, mesh=mesh,
            dropout_key=jax.random.PRNGKey(0))
    # the reverse mismatch must fail loudly too, not with an arity
    # TypeError deep in tracing
    with pytest.raises(ValueError, match="no dropout_key"):
        forward_backward_pipelining_without_interleaving(
            _dropout_spec(), params, batch, num_microbatches=2, mesh=mesh)


@pytest.mark.slow
def test_enc_dec_dropout_matches_sequential():
    """Enc-dec routing parity: both rings deliver the same per-microbatch
    key (side/stage folds are the model's job — the toy folds a side salt
    itself so encoder and decoder masks differ). Slow tier: the default
    tier's enc-dec dropout coverage is the T5 integration test below."""
    from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_enc_dec import (
        EncDecPipelineSpec,
        forward_backward_pipelining_enc_dec,
    )

    pp, M = 2, 4

    def enc_embed(ep, x, key):
        keep = jax.random.bernoulli(jax.random.fold_in(key, 4), KEEP,
                                    x.shape)
        return (x * keep) @ ep["w"]

    def enc_stage(sp_, h, key):
        keep = jax.random.bernoulli(jax.random.fold_in(key, 2), KEEP,
                                    h.shape)
        return jnp.tanh((h * keep) @ sp_["w"] + sp_["b"])

    def dec_embed(ep, x, key):
        keep = jax.random.bernoulli(jax.random.fold_in(key, 5), KEEP,
                                    x.shape)
        return (x * keep) @ ep["w"]

    def dec_stage(sp_, h, mem, key):
        keep = jax.random.bernoulli(jax.random.fold_in(key, 3), KEEP,
                                    h.shape)
        return jnp.tanh((h * keep + mem) @ sp_["w"] + sp_["b"])

    def loss_fn(hp, h, tgt):
        return jnp.mean((h @ hp["w"] - tgt) ** 2)

    spec = EncDecPipelineSpec(enc_embed, enc_stage, dec_embed, dec_stage,
                              loss_fn, takes_dropout_key=True)
    p_enc = _params(jax.random.PRNGKey(0), pp)
    p_dec = _params(jax.random.PRNGKey(1), pp)
    params = {"embed": p_enc["embed"], "enc_stages": p_enc["stages"],
              "dec_stages": p_dec["stages"], "head": p_dec["head"]}
    enc_in, _ = _batch(jax.random.PRNGKey(2))
    dec_in, tgt = _batch(jax.random.PRNGKey(3))
    key = jax.random.PRNGKey(13)
    mesh = build_mesh(tp=1, pp=pp, sp=1, devices=jax.devices()[:pp])
    loss, grads = forward_backward_pipelining_enc_dec(
        spec, params, (enc_in, dec_in, tgt), num_microbatches=M,
        mesh=mesh, dropout_key=key)

    def loss_of(p):
        def one_mb(ex, dx, t, m):
            key_m = jax.random.fold_in(key, m)
            h = enc_embed(p["embed"], ex, key_m)
            for s in range(pp):
                h = enc_stage(jax.tree.map(lambda a: a[s],
                                           p["enc_stages"]), h, key_m)
            mem = h
            h = dec_embed(p["embed"], dx, key_m)
            for s in range(pp):
                h = dec_stage(jax.tree.map(lambda a: a[s],
                                           p["dec_stages"]), h, mem, key_m)
            return loss_fn(p["head"], h, t)

        nb = enc_in.shape[0]
        sh = lambda a: a.reshape((M, nb // M) + a.shape[1:])
        return jnp.mean(jax.vmap(one_mb)(sh(enc_in), sh(dec_in), sh(tgt),
                                         jnp.arange(M)))

    want, gref = jax.jit(jax.value_and_grad(loss_of))(params)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5,
                               atol=1e-6)
    _assert_tree_close(grads, gref)


def test_t5_enc_dec_pipeline_trains_with_dropout():
    """T5 through the enc-dec schedule with hidden dropout: runs,
    deterministic replay, key-sensitive."""
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import (
        T5Config,
        t5_enc_dec_spec,
        t5_pipeline_params,
        t5_pipeline_specs_tree,
    )
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_enc_dec,
    )

    pp, M = 2, 2
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        pipeline_model_parallel_split_rank_=1,
        devices=jax.devices()[:2])
    try:
        cfg = T5Config(vocab_size=64, hidden=32, num_heads=4, enc_layers=2,
                       dec_layers=2, max_seq_enc=16, max_seq_dec=8,
                       dtype=jnp.float32, fused_loss=False,
                       hidden_dropout=0.2, attention_dropout=0.0)
        params = t5_pipeline_params(jax.random.PRNGKey(4), cfg, pp=pp)
        spec = t5_enc_dec_spec(cfg, dropout=True)
        st = t5_pipeline_specs_tree(cfg)
        k = jax.random.PRNGKey(5)
        enc_tok = jax.random.randint(k, (2 * M, cfg.max_seq_enc), 0,
                                     cfg.vocab_size)
        dec_tok = jax.random.randint(jax.random.fold_in(k, 1),
                                     (2 * M, cfg.max_seq_dec), 0,
                                     cfg.vocab_size)
        tgt = jnp.roll(dec_tok, -1, 1)

        @jax.jit
        def step(params, key):
            return forward_backward_pipelining_enc_dec(
                spec, params, (enc_tok, dec_tok, tgt), num_microbatches=M,
                mesh=mesh, params_specs=st, dropout_key=key)

        l1, g1 = step(params, jax.random.PRNGKey(6))
        l2, _ = step(params, jax.random.PRNGKey(6))
        l3, _ = step(params, jax.random.PRNGKey(7))
        assert np.isfinite(float(l1))
        assert float(l1) == float(l2)
        assert float(l3) != float(l1)
        assert any(np.abs(np.asarray(g)).max() > 0
                   for g in jax.tree.leaves(g1))
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_pipeline_trains_with_dropout_under_pp_sp():
    """The flagship fixture end-to-end: pp=2 x sp=2 1F1B with hidden
    dropout active — runs, deterministic for a fixed key, key-sensitive
    (the model's pp/sp folds compose with the schedule's mb keys)."""
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_pipeline_params,
        gpt_pipeline_spec,
        gpt_pipeline_specs_tree,
    )

    pp, sp, M = 2, 2, 2
    mesh = build_mesh(tp=1, pp=pp, sp=sp, devices=jax.devices()[:pp * sp])
    cfg = GPTConfig(vocab_size=64, max_seq=32, hidden=32, num_layers=4,
                    num_heads=4, dtype=jnp.float32, tie_embeddings=False,
                    remat=True, attention_dropout=0.0, hidden_dropout=0.2)
    params = gpt_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp)
    spec = gpt_pipeline_spec(cfg, dropout=True)
    specs_tree = gpt_pipeline_specs_tree(cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2 * M, cfg.max_seq),
                             0, cfg.vocab_size)
    tgt = jnp.roll(tok, -1, 1)

    @jax.jit
    def step(params, key):
        return forward_backward_pipelining_without_interleaving(
            spec, params, (tok, tgt), num_microbatches=M, mesh=mesh,
            params_specs=specs_tree, data_spec=P(None, "dp", "sp"),
            dropout_key=key)

    def run(key):
        loss, grads = step(params, key)
        return float(loss), grads

    l1, g1 = run(jax.random.PRNGKey(3))
    l2, g2 = run(jax.random.PRNGKey(3))
    assert np.isfinite(l1)
    assert l1 == l2, "same key must replay the same masks"
    _assert_tree_close(g1, g2, atol=0.0)
    l3, _ = run(jax.random.PRNGKey(4))
    assert l3 != l1, "different key must change the loss"
    assert any(np.abs(np.asarray(g)).max() > 0 for g in jax.tree.leaves(g1))


def test_no_pipelining_dropout_arity_checked():
    params = _params(jax.random.PRNGKey(0), 2)
    with pytest.raises(ValueError, match="third per-microbatch key"):
        forward_backward_no_pipelining(
            lambda p, m: jnp.zeros(()), _batch(jax.random.PRNGKey(1)),
            params, num_microbatches=2,
            dropout_key=jax.random.PRNGKey(0))

"""Standalone T5 (enc-dec) fixture tests on the virtual mesh.

Ref: ``ModelType.encoder_and_decoder`` consumers (common.py:72-103) — the
reference ships no T5 test fixture, so these tests specify the missing
consumer: TP parity, training, and the enc-dec pipeline schedule against
the sequential computation of the same stage stack.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_enc_dec,
)
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    replicate_loss,
)
from apex_tpu.transformer.testing.standalone_t5 import (
    T5Config,
    init_t5_params,
    t5_enc_dec_spec,
    t5_loss,
    t5_param_specs,
    t5_pipeline_params,
    t5_pipeline_specs_tree,
)

CFG = T5Config(vocab_size=96, hidden=32, num_heads=4, enc_layers=2,
               dec_layers=2, max_seq_enc=12, max_seq_dec=8,
               dtype=jnp.float32, fused_loss=False)


def _batch(rng, b=8):
    ke, kd = jax.random.split(rng)
    enc_tok = jax.random.randint(ke, (b, 12), 0, CFG.vocab_size)
    dec_tok = jax.random.randint(kd, (b, 8), 0, CFG.vocab_size)
    return enc_tok, dec_tok, jnp.roll(dec_tok, -1, 1)


_LG_CACHE = {}


def _loss_and_grads(mesh, cfg, params, batch):
    """value_and_grad of the sharded T5 loss; the jitted program is
    cached per (cfg, mesh shape) so training loops compile once."""
    ck = (cfg, tuple(mesh.shape.items()))
    if ck not in _LG_CACHE:
        def loss_fn(p, e, d, t):
            def body(p, e, d, t):
                return replicate_loss(t5_loss(p, e, d, t, cfg), mesh,
                                      masked_axis=None)

            return shard_map(
                body, mesh=mesh,
                in_specs=(t5_param_specs(cfg), P("dp"), P("dp"), P("dp")),
                out_specs=P())(p, e, d, t)

        _LG_CACHE[ck] = jax.jit(jax.value_and_grad(loss_fn))
    return _LG_CACHE[ck](params, *batch)


def test_t5_tp2_matches_tp1():
    params = init_t5_params(jax.random.PRNGKey(0), CFG)
    batch = _batch(jax.random.PRNGKey(1))
    l1, g1 = _loss_and_grads(build_mesh(tp=1), CFG, params, batch)
    l2, g2 = _loss_and_grads(build_mesh(tp=2), CFG, params, batch)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5), g2, g1)


def test_t5_trains():
    """Three Adam steps decrease the loss — grads reach every group
    (embed through cross-attention back into encoder layers)."""
    from apex_tpu.optimizers import FusedAdam

    mesh = build_mesh(tp=2)
    params = init_t5_params(jax.random.PRNGKey(0), CFG)
    batch = _batch(jax.random.PRNGKey(1))
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    losses = []
    for _ in range(3):
        loss, grads = _loss_and_grads(mesh, CFG, params, batch)
        updates, state = opt.update(grads, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    enc_g = sum(float(jnp.vdot(x, x))
                for x in jax.tree.leaves(grads["enc_layers"]))
    assert enc_g > 0, "no gradient reached the encoder through cross-attn"


def test_t5_fused_loss_matches_unfused():
    cfg_f = dataclasses.replace(CFG, fused_loss=True)
    params = init_t5_params(jax.random.PRNGKey(0), CFG)
    batch = _batch(jax.random.PRNGKey(1))
    mesh = build_mesh(tp=2)
    l0, g0 = _loss_and_grads(mesh, CFG, params, batch)
    l1, g1 = _loss_and_grads(mesh, cfg_f, params, batch)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5), g1, g0)


def test_t5_pipeline_matches_sequential():
    """The enc-dec schedule over T5 stages == the sequential ``t5_loss``
    computation of the same weights (loss AND grads), pp=2 × dp=4 vs a
    dp-only mesh. The pipeline fixture unties the LM head from the shared
    table, so the tied reference's embedding grad must equal the
    pipeline's embedding grad PLUS its head-rows grad — checking that
    identity exercises both grad paths."""
    pp = 2
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        pipeline_model_parallel_split_rank_=1,
    )
    cfg = CFG
    spec = t5_enc_dec_spec(cfg)
    params = t5_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp)
    enc_tok, dec_tok, tgt = _batch(jax.random.PRNGKey(1), b=16)
    M = 4

    # jit: the remat'd (closed_call) stage bodies can't run eagerly inside
    # shard_map
    loss, grads = jax.jit(lambda p: forward_backward_pipelining_enc_dec(
        spec, p, (enc_tok, dec_tok, tgt), num_microbatches=M,
        mesh=mesh, params_specs=t5_pipeline_specs_tree(cfg)))(params)

    # tied sequential reference on a dp-only mesh with the SAME weights
    flat_params = init_t5_params(jax.random.PRNGKey(0), cfg)
    ref_loss, ref_grads = _loss_and_grads(
        build_mesh(tp=1), cfg, flat_params, (enc_tok, dec_tok, tgt))

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)

    # layer grads: pipeline stages [pp, L/pp, ...] == flat [L, ...]
    for group, flat_group in (("enc_stages", "enc_layers"),
                              ("dec_stages", "dec_layers")):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b),
                rtol=2e-3, atol=1e-5),
            grads[group], ref_grads[flat_group])
    for k in ("pos_enc", "pos_dec"):
        np.testing.assert_allclose(np.asarray(grads["embed"][k]),
                                   np.asarray(ref_grads["embed"][k]),
                                   rtol=2e-3, atol=1e-5)
    for k in ("ln_w", "ln_b"):
        np.testing.assert_allclose(np.asarray(grads["head"][k]),
                                   np.asarray(ref_grads["head"][k]),
                                   rtol=2e-3, atol=1e-5)
    # the tying identity: d(tied tok) = d(untied tok) + d(head rows)
    np.testing.assert_allclose(
        np.asarray(grads["embed"]["tok"]) + np.asarray(grads["head"]["lm_rows"]),
        np.asarray(ref_grads["embed"]["tok"]), rtol=2e-3, atol=1e-5)


def test_t5_megatron_sp_matches_plain():
    """T5 with Megatron-SP (seq-sharded LN/residual regions, gather /
    reduce-scatter TP boundaries, cross-attention KV gathering the
    seq-sharded memory) == the plain TP path, loss AND grads, tp=2."""
    cfg_sp = dataclasses.replace(CFG, megatron_sp=True)
    params = init_t5_params(jax.random.PRNGKey(0), CFG)
    batch = _batch(jax.random.PRNGKey(1))
    mesh = build_mesh(tp=2)
    l0, g0 = _loss_and_grads(mesh, CFG, params, batch)
    l1, g1 = _loss_and_grads(mesh, cfg_sp, params, batch)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5), g1, g0)


def test_t5_pipeline_composes_with_megatron_sp():
    """enc-dec pipeline x Megatron-SP: the ring p2p tensors and the
    memory broadcast ride seq shards; loss matches the plain-SP pipeline
    run (pp=2 x tp=2 x dp=2)."""
    pp = 2
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=pp,
        pipeline_model_parallel_split_rank_=1)

    def run(cfg):
        spec = t5_enc_dec_spec(cfg)
        params = t5_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp)
        enc_tok, dec_tok, tgt = _batch(jax.random.PRNGKey(1), b=16)
        loss, grads = jax.jit(
            lambda p: forward_backward_pipelining_enc_dec(
                spec, p, (enc_tok, dec_tok, tgt), num_microbatches=4,
                mesh=mesh, params_specs=t5_pipeline_specs_tree(cfg)))(params)
        return float(loss), grads

    l0, g0 = run(CFG)
    l1, g1 = run(dataclasses.replace(CFG, megatron_sp=True))
    np.testing.assert_allclose(l1, l0, rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5), g1, g0)


def test_t5_ring_sp_matches_dense():
    """T5 over the sp (ring) axis: encoder self-attn, causal decoder
    self-attn, and the rectangular cross-attention all ride the K/V ring;
    loss+grads match the sp=1 run. Exercises the rectangular flash-ring
    (s_dec x s_enc chunks) end to end."""
    params = init_t5_params(jax.random.PRNGKey(0), CFG)
    batch = _batch(jax.random.PRNGKey(1))

    def run(mesh, sharded_seq):
        enc_tok, dec_tok, tgt = batch
        data_spec = P("dp", "sp") if sharded_seq else P("dp")

        def loss_fn(p):
            def body(p, e, d, t):
                return replicate_loss(t5_loss(p, e, d, t, CFG), mesh,
                                      masked_axis=None)

            return shard_map(
                body, mesh=mesh,
                in_specs=(t5_param_specs(CFG), data_spec, data_spec,
                          data_spec),
                out_specs=P())(p, enc_tok, dec_tok, tgt)

        return jax.jit(jax.value_and_grad(loss_fn))(params)

    l0, g0 = run(build_mesh(tp=1, sp=1), sharded_seq=False)
    l1, g1 = run(build_mesh(tp=1, sp=2), sharded_seq=True)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5), g1, g0)


def test_t5_dropout_deterministic_and_key_sensitive():
    """T5 dropout follows the GPT RNG policy: same key -> identical loss,
    different key -> different loss, no key == rate 0; rates actually
    drop (train loss differs from eval)."""
    cfg_d = dataclasses.replace(CFG, attention_dropout=0.2,
                                hidden_dropout=0.2)
    params = init_t5_params(jax.random.PRNGKey(0), cfg_d)
    enc_tok, dec_tok, tgt = _batch(jax.random.PRNGKey(1))
    mesh = build_mesh(tp=2)

    def loss(cfg, key):
        def body(p, e, d, t):
            return replicate_loss(
                t5_loss(p, e, d, t, cfg, dropout_key=key), mesh,
                masked_axis=None)

        return float(jax.jit(lambda p: shard_map(
            body, mesh=mesh,
            in_specs=(t5_param_specs(cfg), P("dp"), P("dp"), P("dp")),
            out_specs=P())(p, enc_tok, dec_tok, tgt))(params))

    k = jax.random.PRNGKey(7)
    l_a = loss(cfg_d, k)
    l_b = loss(cfg_d, k)
    l_c = loss(cfg_d, jax.random.PRNGKey(8))
    l_eval = loss(cfg_d, None)
    l_plain = loss(CFG, None)
    assert l_a == l_b, "same dropout key must be deterministic"
    assert l_a != l_c, "different dropout key must change the loss"
    assert l_a != l_eval, "dropout must actually drop in train mode"
    np.testing.assert_allclose(l_eval, l_plain, rtol=1e-6)


CFG_REL = dataclasses.replace(CFG, relative_position_bias=True)


def test_t5_relbias_buckets():
    """T5 bucketing invariants: distance 0 is bucket 0 (plus the sign half
    for bidirectional), buckets are monotone in |distance|, the two
    encoder sign halves are disjoint, and the causal scheme never spends
    buckets on the future."""
    from apex_tpu.transformer.testing.standalone_t5 import _rel_pos_bucket

    rel = jnp.arange(-256, 257)
    bi = np.asarray(_rel_pos_bucket(rel, bidirectional=True, num_buckets=32,
                                    max_distance=128))
    uni = np.asarray(_rel_pos_bucket(rel, bidirectional=False,
                                     num_buckets=32, max_distance=128))
    zero = 256
    assert bi[zero] == 0 and uni[zero] == 0
    # past (rel<0) monotone away from 0 for both schemes
    assert (np.diff(bi[:zero + 1]) <= 0).all()
    assert (np.diff(uni[:zero + 1]) <= 0).all()
    assert bi[:zero].max() < 16 and bi[zero + 1:].min() >= 16  # sign halves
    assert (uni[zero:] == 0).all(), "causal buckets must ignore the future"
    assert bi.max() < 32 and uni.max() < 32


def test_t5_relbias_tp2_matches_tp1():
    """Relative position bias under TP: each rank holds its own heads'
    table columns; loss and grads are TP-degree invariant."""
    params = init_t5_params(jax.random.PRNGKey(0), CFG_REL)
    assert "pos_enc" not in params["embed"]  # T5 proper: no absolute pos
    batch = _batch(jax.random.PRNGKey(1))
    l1, g1 = _loss_and_grads(build_mesh(tp=1), CFG_REL, params, batch)
    l2, g2 = _loss_and_grads(build_mesh(tp=2), CFG_REL, params, batch)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5), g2, g1)


def test_t5_relbias_trains_and_tables_get_grads():
    from apex_tpu.optimizers import FusedAdam

    mesh = build_mesh(tp=2)
    params = init_t5_params(jax.random.PRNGKey(0), CFG_REL)
    batch = _batch(jax.random.PRNGKey(1))
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    losses = []
    for _ in range(3):
        loss, grads = _loss_and_grads(mesh, CFG_REL, params, batch)
        updates, state = opt.update(grads, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    for k in ("rel_enc", "rel_dec"):
        assert float(jnp.vdot(grads["embed"][k], grads["embed"][k])) > 0, \
            f"no gradient reached {k}"


def test_t5_relbias_changes_the_function():
    """The bias must actually reach the logits: zero tables == bias off
    in the forward, trained tables != zero tables."""
    params = init_t5_params(jax.random.PRNGKey(0), CFG_REL)
    batch = _batch(jax.random.PRNGKey(1))
    mesh = build_mesh(tp=1)
    l_rand, _ = _loss_and_grads(mesh, CFG_REL, params, batch)
    z = dict(params)
    z["embed"] = {**params["embed"],
                  "rel_enc": jnp.zeros_like(params["embed"]["rel_enc"]),
                  "rel_dec": jnp.zeros_like(params["embed"]["rel_dec"])}
    l_zero, _ = _loss_and_grads(mesh, CFG_REL, z, batch)
    assert float(l_rand) != float(l_zero)


def test_t5_relbias_pipeline_matches_sequential():
    """Rel-bias wired through the enc-dec pipeline: each stage carries a
    copy of its stack's table (the untied-pipeline-param pattern, see
    t5_pipeline_params); the forward matches the sequential model exactly
    and the sequential table grad equals the SUM of the per-stage copies'
    grads."""
    pp = 2
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        pipeline_model_parallel_split_rank_=1,
    )
    cfg = CFG_REL
    spec = t5_enc_dec_spec(cfg)
    params = t5_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp)
    enc_tok, dec_tok, tgt = _batch(jax.random.PRNGKey(1), b=16)

    loss, grads = jax.jit(lambda p: forward_backward_pipelining_enc_dec(
        spec, p, (enc_tok, dec_tok, tgt), num_microbatches=4,
        mesh=mesh, params_specs=t5_pipeline_specs_tree(cfg)))(params)

    flat_params = init_t5_params(jax.random.PRNGKey(0), cfg)
    ref_loss, ref_grads = _loss_and_grads(
        build_mesh(tp=1), cfg, flat_params, (enc_tok, dec_tok, tgt))

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for group, flat_group in (("enc_stages", "enc_layers"),
                              ("dec_stages", "dec_layers")):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b),
                rtol=2e-3, atol=1e-5),
            grads[group]["layers"], ref_grads[flat_group])
    # per-stage table copies: grads sum to the shared-table grad
    for group, k in (("enc_stages", "rel_enc"), ("dec_stages", "rel_dec")):
        np.testing.assert_allclose(
            np.asarray(grads[group]["rel"]).sum(0),
            np.asarray(ref_grads["embed"][k]), rtol=2e-3, atol=1e-5)


def test_t5_relbias_ring_sp_matches_dense():
    """Relative position bias under ring SP: each shard builds its bias
    STRIP (its global Q rows x all key columns) and the ring slices the
    arriving chunk's columns; loss+grads (including the rel tables, whose
    grad crosses the custom_vjp strip) match the sp=1 run."""
    params = init_t5_params(jax.random.PRNGKey(0), CFG_REL)
    batch = _batch(jax.random.PRNGKey(1))

    def run(mesh, sharded_seq):
        enc_tok, dec_tok, tgt = batch
        data_spec = P("dp", "sp") if sharded_seq else P("dp")

        def loss_fn(p):
            def body(p, e, d, t):
                return replicate_loss(t5_loss(p, e, d, t, CFG_REL), mesh,
                                      masked_axis=None)

            return shard_map(
                body, mesh=mesh,
                in_specs=(t5_param_specs(CFG_REL), data_spec, data_spec,
                          data_spec),
                out_specs=P())(p, enc_tok, dec_tok, tgt)

        return jax.jit(jax.value_and_grad(loss_fn))(params)

    l0, g0 = run(build_mesh(tp=1, sp=1), sharded_seq=False)
    l1, g1 = run(build_mesh(tp=1, sp=2), sharded_seq=True)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5), g1, g0)
    for k in ("rel_enc", "rel_dec"):
        assert float(jnp.vdot(g1["embed"][k], g1["embed"][k])) > 0


def test_t5_encoder_final_ln_pipeline_matches_sequential():
    """encoder_final_ln: normalizing the broadcast memory in every decoder
    stage (per-stage LN copies) == the sequential encoder-exit LayerNorm;
    the sequential LN grad equals the sum of the per-stage copies' grads,
    and the LN actually changes the function."""
    cfg = dataclasses.replace(CFG, encoder_final_ln=True)
    pp = 2
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        pipeline_model_parallel_split_rank_=1,
    )
    spec = t5_enc_dec_spec(cfg)
    params = t5_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp)
    assert "enc_ln_w" not in params["embed"]  # untied into dec stages
    enc_tok, dec_tok, tgt = _batch(jax.random.PRNGKey(1), b=16)

    loss, grads = jax.jit(lambda p: forward_backward_pipelining_enc_dec(
        spec, p, (enc_tok, dec_tok, tgt), num_microbatches=4,
        mesh=mesh, params_specs=t5_pipeline_specs_tree(cfg)))(params)

    flat_params = init_t5_params(jax.random.PRNGKey(0), cfg)
    ref_loss, ref_grads = _loss_and_grads(
        build_mesh(tp=1), cfg, flat_params, (enc_tok, dec_tok, tgt))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ("enc_ln_w", "enc_ln_b"):
        np.testing.assert_allclose(
            np.asarray(grads["dec_stages"][k]).sum(0),
            np.asarray(ref_grads["embed"][k]), rtol=2e-3, atol=1e-5)

    # the LN must reach the function: plain CFG differs
    plain_loss, _ = _loss_and_grads(
        build_mesh(tp=1), CFG, init_t5_params(jax.random.PRNGKey(0), CFG),
        (enc_tok, dec_tok, tgt))
    assert float(ref_loss) != float(plain_loss)


# ---------------------------------------------------------------------------
# hidden-dropout shard decorrelation (round-5 fixes: unfolded keys reused
# one mask across seq shards under megatron_sp / ring-sp)


def _hidden_dropout_shards(cfg, mesh, axis):
    """Gather _maybe_hidden_dropout's output on identical per-shard inputs
    — differing shard halves prove decorrelated masks."""
    from apex_tpu.transformer.testing.standalone_t5 import (
        _maybe_hidden_dropout,
    )

    def body():
        x = jnp.broadcast_to(
            jax.random.normal(jax.random.PRNGKey(2), (cfg.hidden,)),
            (1, 16, cfg.hidden))
        return _maybe_hidden_dropout(x, cfg, jax.random.PRNGKey(0), 1)

    return np.asarray(jax.jit(shard_map(
        body, mesh=mesh, in_specs=(),
        out_specs=P(None, axis, None), check_vma=False))())


def test_t5_megatron_sp_hidden_dropout_decorrelated():
    cfg = dataclasses.replace(CFG, megatron_sp=True, hidden_dropout=0.5)
    out = _hidden_dropout_shards(cfg, build_mesh(tp=2), "tp")
    assert out.shape[1] == 32
    assert not np.array_equal(out[:, :16], out[:, 16:]), \
        "tp seq shards must drop independent positions under megatron_sp"


def test_t5_ring_sp_hidden_dropout_decorrelated():
    cfg = dataclasses.replace(CFG, hidden_dropout=0.5)
    out = _hidden_dropout_shards(cfg, build_mesh(tp=1, sp=2), "sp")
    assert not np.array_equal(out[:, :16], out[:, 16:]), \
        "sp seq shards must drop independent positions under ring-sp"


def test_t5_ring_sp_attention_dropout_trains():
    """Attention dropout under ring-SP (round 5): encoder, causal decoder,
    and the rectangular cross-attention rings all drop with the
    global-position-keyed masks — runs, replays, key-sensitive."""
    cfg = dataclasses.replace(CFG, attention_dropout=0.2,
                              hidden_dropout=0.1)
    params = init_t5_params(jax.random.PRNGKey(0), cfg)
    enc_tok, dec_tok, tgt = _batch(jax.random.PRNGKey(1))
    mesh = build_mesh(tp=1, sp=2)

    def loss(key):
        def body(p, e, d, t):
            return replicate_loss(
                t5_loss(p, e, d, t, cfg, dropout_key=key), mesh,
                masked_axis=None)

        return float(jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(t5_param_specs(cfg), P("dp", "sp"), P("dp", "sp"),
                      P("dp", "sp")),
            out_specs=P()))(params, enc_tok, dec_tok, tgt))

    k1, k2 = jax.random.split(jax.random.PRNGKey(8))
    a, b, c, d = loss(k1), loss(k1), loss(k2), loss(None)
    assert np.isfinite([a, b, c, d]).all()
    assert a == b and a != c and a != d

"""Regression bounds on the pipeline schedules' memory/recompute trade.

Ref context: Megatron 1F1B holds ≤pp in-flight microbatch activations
with no interior recompute; the ring-scan design here saves one boundary
tensor per tick and remats interiors (see PERF.md "Pipeline schedules:
measured memory/recompute trade"). These tests pin the two properties
that make the trade sound, using XLA's own buffer assignment/cost model
so a remat or scan-carry regression fails loudly:

* temp-memory growth in M is the boundary saves only (a broken remat
  stacking interiors would grow ~10x faster);
* the recompute factor stays under the "one extra forward" 4/3 bound.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "benchmarks"))

from pipeline_memory import B_PER_MB, HID, SEQ, measure  # noqa: E402


@pytest.fixture(scope="module")
def rows():
    return {
        "m4": measure(2, 4, remat=True),
        "m8": measure(2, 8, remat=True),
        "m4_noremat": measure(2, 4, remat=False),
    }


def test_temp_growth_is_boundary_only(rows):
    slope_mb = (rows["m8"]["temp_mb"] - rows["m4"]["temp_mb"]) / 4
    # whole-mesh bytes of one per-tick boundary save: [B_PER_MB, SEQ, HID]
    # f32 on each of the 8 virtual devices
    boundary_mb = B_PER_MB * SEQ * HID * 4 * 8 / 1e6
    assert slope_mb >= 0.0
    # measured 0.10 MB/mb vs 0.26 prediction; interiors would add several
    # boundary-multiples per tick — 2x headroom still catches that class
    assert slope_mb < 2.0 * boundary_mb, (
        f"temp grows {slope_mb:.3f} MB/microbatch, boundary-save bound is "
        f"{boundary_mb:.3f} MB — remat may be stacking stage interiors")


def test_recompute_factor_under_one_extra_forward(rows):
    factor = rows["m4"]["gflops"] / rows["m4_noremat"]["gflops"]
    # one extra forward over fwd+bwd is 4/3; measured 1.253
    assert 1.0 <= factor < 4.0 / 3.0 + 0.05, (
        f"remat recompute factor {factor:.3f} exceeds the one-extra-forward "
        f"bound")


def test_remat_reduces_temp_memory(rows):
    assert rows["m4"]["temp_mb"] < 0.5 * rows["m4_noremat"]["temp_mb"], (
        "ring-level remat no longer reduces temp memory materially")


@pytest.mark.slow
def test_flagship_shape_bounds():
    """The same two claims at the flagship shape (hidden=768, 12 layers —
    VERDICT r3: the boundary:interior ratio shifts with hidden, so the
    toy-shape bounds alone are not load-bearing). Buffer assignment only;
    no execution."""
    from pipeline_memory import flagship_rows

    rows, slope, boundary_mb, factor = flagship_rows()
    assert slope >= 0.0
    assert slope < 2.0 * boundary_mb, (
        f"flagship temp grows {slope:.2f} MB/microbatch, boundary bound "
        f"{boundary_mb:.2f} MB — remat may be stacking stage interiors")
    assert 1.0 <= factor < 4.0 / 3.0 + 0.05, (
        f"flagship recompute factor {factor:.3f} exceeds one-extra-forward")
    assert rows["m4"]["temp_mb"] < 0.5 * rows["m4_noremat"]["temp_mb"]

"""Compiled-program contract check — the ``apex_tpu.analyze`` bench.

One ``json_record`` line (the bench.py protocol) asserting the repo's
compiled-program contracts on THIS box's toolchain, staged as
``tpu_watch.sh`` stage 16 and regression-gated via ``monitor.regress
--tol 0.15`` like every banked artifact:

* **donation** — the flagship GPT train step's donated params and the
  serve decode step's donated KV pools are ALIASED in the compiled
  executables (``donated_copied`` must stay 0);
* **recompile** — 3 train steps reuse ONE compilation and a warmed serve
  engine runs a fresh mixed-length workload with ZERO new compiles
  (``analyze.recompile_guard``);
* **adapters** — the serve LoRA pool rides every jit site donated AND
  aliased (``analyze.adapters``, ``adapter_donated_copied`` stays 0) and
  an adapter swap on a warm engine compiles NOTHING new;
* **dtype** — the bf16 serve decode program's jaxpr profile:
  ``fp32_dots`` (the two fp32 attention-stability dots are the accepted
  level — regress flags growth) and ``convert_churn_ops`` (must stay 0);
* **host sync** — ``host_syncs`` reachable from the decode step: 0;
* **exposed collectives** — the FSDP-position gather-ring MLP (the
  stage-14 ring, recompiled) split hidden-vs-exposed by
  ``analyze.exposed_report`` over the compiled HLO (needs graft jax for
  ``shard_map``; the record says so honestly otherwise);
* **lint** — ``analyze.lint`` over ``apex_tpu/`` against the checked-in
  baseline (``lint_violations``: NEW violations, must stay 0).

CPU runs carry the ``_CPU_FALLBACK`` metric suffix and never promote
(the watcher rule); a record with ``ok: false`` never promotes either.

Run: ``python benchmarks/analyze_contracts.py [--out FILE]``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import (  # noqa: E402
    pin_cpu_if_requested,
    pin_cpu_if_tunnel_dead,
    pin_cpu_platform,
)

pin_cpu_if_requested()
pin_cpu_if_tunnel_dead()
if os.environ.get("JAX_PLATFORMS") == "cpu":
    pin_cpu_platform(virtual_devices=8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

ON_TPU = jax.default_backend() == "tpu"
MESH_OK = hasattr(jax, "shard_map") and hasattr(jax.lax, "axis_size")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _gpt_cfg(dtype):
    from apex_tpu.transformer.testing import GPTConfig

    return GPTConfig(vocab_size=97, max_seq=64, hidden=32, num_layers=2,
                     num_heads=4, dtype=dtype, fused_loss=False)


def _serve_fixture(dtype):
    from apex_tpu.serve import KVCacheConfig, init_kv_cache
    from apex_tpu.transformer.testing import init_gpt_params

    cfg = _gpt_cfg(dtype)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    kv = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                       num_blocks=8, block_size=8, dtype=dtype)
    return cfg, params, kv, init_kv_cache(kv)


def gpt_step_contracts() -> dict:
    """Donation + recompile + host-sync on the flagship GPT train step
    (the serve ``gpt_prefill`` forward — tp-optional, stock-safe)."""
    from apex_tpu import analyze
    from apex_tpu.serve.decode import gpt_prefill

    cfg, params, kv, cache = _serve_fixture(jnp.float32)
    toks = jnp.zeros((16,), jnp.int32).at[:9].set(
        jnp.arange(1, 10, dtype=jnp.int32))
    block_row = jnp.arange(2, dtype=jnp.int32)

    def train_step(p, toks, target):
        def loss_fn(p):
            _, logits = gpt_prefill(p, toks, jnp.int32(9), cache,
                                    block_row, cfg, kv)
            return -jax.nn.log_softmax(logits)[target]

        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(
            lambda a, b: a - 0.01 * b, p, g), loss

    rep = analyze.check_donation(train_step, params, toks, jnp.int32(7),
                                 donate_argnums=(0,))
    out = {f"gpt_{k}": v for k, v in rep.as_record().items()}
    step = jax.jit(train_step, donate_argnums=(0,))
    p = jax.tree_util.tree_map(jnp.copy, params)
    try:
        with analyze.recompile_guard(step):
            for _ in range(3):
                p, _loss = step(p, toks, jnp.int32(7))
        out["gpt_recompile_ok"] = True
    except analyze.RecompileError:
        out["gpt_recompile_ok"] = False
    sync = analyze.host_sync_report(train_step, params, toks, jnp.int32(7))
    out["gpt_host_syncs"] = sync.host_syncs
    return out


def serve_contracts() -> dict:
    """Donation + steady-state recompile + dtype/host-sync profile on the
    serve decode path (bf16 pools — the production dtype story)."""
    from apex_tpu import analyze
    from apex_tpu.serve import (
        InferenceEngine, Request, SamplingConfig, ServeConfig,
    )
    from apex_tpu.serve.decode import gpt_decode_step

    cfg, params, kv, cache = _serve_fixture(jnp.bfloat16)
    n = 3
    toks = jnp.zeros((n,), jnp.int32)
    lens = jnp.array([4, 2, 0], jnp.int32)
    active = jnp.array([True, True, False])
    bt = jnp.arange(n * 2, dtype=jnp.int32).reshape(n, 2)

    def decode(cache, toks, lens, active, bt):
        return gpt_decode_step(params, toks, lens, active, cache, bt,
                               cfg, kv, tp_axis=None, use_pallas=False)

    rep = analyze.check_donation(decode, cache, toks, lens, active, bt,
                                 donate_argnums=(0,))
    out = {f"decode_{k}": v for k, v in rep.as_record().items()}
    leak = analyze.dtype_leak_report(decode, cache, toks, lens, active,
                                     bt, policy=jnp.bfloat16)
    out["fp32_dots"] = leak.fp32_dots           # accepted: fp32 attention
    out["convert_churn_ops"] = leak.convert_churn_ops
    out["host_syncs"] = analyze.host_sync_report(
        decode, cache, toks, lens, active, bt).host_syncs

    eng = InferenceEngine(params, cfg, ServeConfig(
        num_slots=3, block_size=8, prefill_chunk=8,
        sampling=SamplingConfig()))
    eng.run([Request("warm1", [1, 2, 3], max_new_tokens=2),
             Request("warm2", list(range(12)), max_new_tokens=2)])
    try:
        with analyze.recompile_guard(eng.programs(), budget=0):
            eng.run([Request("a", [5, 6], max_new_tokens=3),
                     Request("b", list(range(17)), max_new_tokens=2)])
        out["serve_recompile_ok"] = True
    except analyze.RecompileError:
        out["serve_recompile_ok"] = False
    return out


def adapter_contracts() -> dict:
    """The serve LoRA contract (PR-16): the adapter pool rides every jit
    site donated-and-aliased (``analyze.adapters``), and swapping which
    adapters are resident is pure data — zero new compiles."""
    from apex_tpu import analyze
    from apex_tpu.serve import (
        InferenceEngine, Request, SamplingConfig, ServeConfig,
        make_adapter_weights,
    )

    cfg, params, _kv, _cache = _serve_fixture(jnp.float32)
    eng = InferenceEngine(params, cfg, ServeConfig(
        num_slots=3, block_size=8, prefill_chunk=8,
        sampling=SamplingConfig(), lora_rank=4, max_adapters=2))
    eng.load_adapter("t0", make_adapter_weights(
        cfg, 4, jax.random.PRNGKey(11)), scale=0.5)
    eng.run([Request("warm-base", [1, 2, 3], max_new_tokens=2),
             Request("warm-t0", list(range(12)), max_new_tokens=2,
                     adapter="t0")])
    out = analyze.adapter_contract_record(eng)
    try:
        # an adapter SWAP (unload + load into the freed slot) must not
        # retrace — residency is pool data, never a constant
        with analyze.recompile_guard(eng.programs(), budget=0):
            eng.unload_adapter("t0")
            eng.load_adapter("t1", make_adapter_weights(
                cfg, 4, jax.random.PRNGKey(12)), scale=0.5)
            eng.run([Request("a", [5, 6], max_new_tokens=3, adapter="t1"),
                     Request("b", list(range(17)), max_new_tokens=2)])
        out["adapter_recompile_ok"] = True
    except analyze.RecompileError:
        out["adapter_recompile_ok"] = False
    return out


def ring_exposed() -> dict:
    """The stage-14 gather-ring MLP recompiled, hidden/exposed split via
    ``analyze.exposed_report`` on the compiled HLO (all collective
    kinds — the generalized ``overlap_report``)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from apex_tpu.analyze import exposed_report
    from apex_tpu.fsdp import FSDP
    from apex_tpu.parallel.mesh import build_mesh

    fsdp = FSDP()
    mesh = build_mesh(tp=1, pp=1, sp=1)
    d_in, d_h = 256, 512
    x = jax.random.normal(jax.random.PRNGKey(2),
                          (len(jax.devices()), 8, d_in), jnp.float32)
    w1 = jax.random.normal(jax.random.PRNGKey(3), (d_in, d_h), jnp.float32)
    w2 = jax.random.normal(jax.random.PRNGKey(4), (d_h, d_in), jnp.float32)

    def loss(x, w1, w2):
        def body(x, w1s, w2s):
            h = jax.nn.gelu(fsdp.linear(x[0], w1s))
            y = fsdp.linear(h, w2s)
            return lax.psum(jnp.sum(y * y), "dp")

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("dp"), P(None, "dp"), P(None, "dp")),
            out_specs=P())(x, w1, w2)

    compiled = jax.jit(jax.value_and_grad(loss, argnums=(1, 2))).lower(
        x, w1, w2).compile()
    # ALL collective kinds (an exposed all-gather/reduce-scatter from a
    # future ring regression must show up in the banked record, not just
    # permutes); regress gates growth of exposed_bytes, not its absolute
    rep = exposed_report(compiled.as_text())
    return rep.as_record()


def lint_gate() -> dict:
    from apex_tpu.analyze import lint_paths, load_baseline, new_violations

    violations = lint_paths([os.path.join(ROOT, "apex_tpu")], root=ROOT)
    baseline = load_baseline(
        os.path.join(ROOT, "tests", "lint_baseline.json"))
    fresh = new_violations(violations, baseline)
    return {"lint_violations": len(fresh),
            "lint_total": len(violations),
            "lint_baselined": len(violations) - len(fresh)}


def main() -> int:
    import argparse

    from apex_tpu.monitor import json_record

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    name = "analyze_contracts"
    if not ON_TPU:
        name += "_CPU_FALLBACK"

    rec = {"metric": name, "backend": jax.default_backend(),
           "n_devices": len(jax.devices())}
    rec.update(gpt_step_contracts())
    rec.update(serve_contracts())
    rec.update(adapter_contracts())
    rec.update(lint_gate())
    if MESH_OK and len(jax.devices()) >= 2:
        rec.update(ring_exposed())
    else:
        rec["ring_exposed"] = ("needs graft jax" if not MESH_OK
                               else "needs a slice")
    rec["ok"] = bool(
        rec.get("gpt_donation_ok") and rec.get("decode_donation_ok")
        and rec.get("gpt_recompile_ok") and rec.get("serve_recompile_ok")
        and rec.get("adapter_donation_ok")
        and rec.get("adapter_recompile_ok")
        and rec.get("convert_churn_ops") == 0
        and rec.get("host_syncs") == 0 and rec.get("gpt_host_syncs") == 0
        and rec.get("lint_violations") == 0)
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

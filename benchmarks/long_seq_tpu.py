"""Long-sequence attention rows on the real chip (VERDICT r4 task 4).

The ring-SP memory study (``tests/test_ring_memory.py``, PERF.md) argues
32k-token attention fits per-device by buffer-assignment arithmetic; this
script converts that extrapolation into measurements. Single-chip scope
per the verdict: the ring collective itself is dryrun-covered, so the
chip evidence is the KERNEL at ring-shard shapes — causal flash and
varlen block-skip, compiled, long seq, fwd + bwd.

Rows:
- parity (tol-gated, scale-normalized error vs a matmul-precision-highest
  dense reference) at s=4096 — the longest shape where the dense
  reference's (s, s) score materialization is still reasonable;
- timed kernel-only rows at s=8192/16384/32768 (b=1, h=8, d=64, bf16,
  fwd+bwd, value-transfer fence) where the dense path cannot run at all —
  each reports wall ms, achieved TFLOP/s (accounting documented at
  ``_causal_flops``), and the device's ``peak_bytes_in_use``;
- a varlen block-skip row at s=32768 packed as 8x4096 segments: the
  skip must realize (within overheads) the 8x score-work reduction vs
  the causal full row.

Run: ``python benchmarks/long_seq_tpu.py [--out LONGSEQ_TPU.json]``.
Exit 0 all-ok on TPU, 1 on-chip failure, 2 off-chip rehearsal (reference
fallbacks exercise the harness but are never kernel evidence — same
contract as ``smoke_tpu.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import pin_cpu_if_requested

pin_cpu_if_requested()

import jax

TIMED_STEPS = 10


def _causal_flops(b, h, s, d):
    """Credited fwd+bwd flops of causal attention per (b, h): fwd runs two
    s x s x d matmuls (QK^T, PV) = 2 * 2*s^2*d flops, halved by causality;
    bwd recomputes scores and runs the dV/dP/dQ/dK matmuls, ~2.5x fwd
    (flash-attention standard accounting) -> total 3.5x fwd."""
    fwd = 2 * (2.0 * s * s * d) / 2.0  # two matmuls, causal half
    return 3.5 * fwd * b * h


def _mem_row():
    try:
        st = jax.local_devices()[0].memory_stats() or {}
        return {"bytes_in_use": int(st.get("bytes_in_use", -1)),
                "peak_bytes_in_use": int(st.get("peak_bytes_in_use", -1))}
    except Exception:
        return {}


def _results():
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops.attention import attention_reference, flash_attention
    from apex_tpu.ops.attention_varlen import (
        attention_varlen_reference,
        flash_attention_varlen,
    )

    on_tpu = jax.default_backend() == "tpu"
    force = True if on_tpu else None
    key = jax.random.PRNGKey(0)
    out = []

    def record(name, fn, tol=None):
        """tol=None: timed row (ok = ran + finite); else parity row."""
        t0 = time.perf_counter()
        try:
            row = fn()
            row.update(kernel=name,
                       seconds=round(time.perf_counter() - t0, 2))
            if tol is not None:
                err = row["max_err"]
                row["tol"] = tol
                row["ok"] = bool(np.isfinite(err) and 0.0 < err <= tol)
                if err == 0.0:
                    row["ok"] = False
                    row["error"] = ("err == 0.0: the Pallas path fell back "
                                    "(not kernel evidence)")
            else:
                row.setdefault("ok", True)
            if not on_tpu:
                row["ok"] = False
                row.setdefault("error", "CPU rehearsal: reference fallback, "
                                        "not kernel evidence")
            out.append(row)
        except Exception as e:  # noqa: BLE001 — record, keep going
            out.append({"kernel": name, "ok": False,
                        "error": f"{type(e).__name__}: {str(e)[:300]}",
                        "seconds": round(time.perf_counter() - t0, 2)})
        print(json.dumps(out[-1]), file=sys.stderr, flush=True)

    def qkv(b, h, s, d, kk=key):
        mk = lambda i: jax.random.normal(jax.random.fold_in(kk, i),
                                         (b, h, s, d), jnp.bfloat16)
        return mk(0), mk(1), mk(2)

    def nerr(got, want):
        return max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b_.astype(jnp.float32)))
                  / (jnp.max(jnp.abs(b_.astype(jnp.float32))) + 1e-12))
            for a, b_ in zip(got, want))

    # ---- parity at s=4096 (dense reference still materializes 64 MB/head)
    def causal_parity():
        b, h, s, d = 1, 2, 4096, 64
        q, k, v = qkv(b, h, s, d)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           use_pallas=force)
                           .astype(jnp.float32) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=True)
                           .astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        with jax.default_matmul_precision("highest"):
            gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        jax.block_until_ready(g)
        return {"max_err": nerr(g, gr)}

    record("flash_causal_s4096_parity_fwd_bwd", causal_parity, tol=2e-2)

    def varlen_parity():
        b, h, s, d = 1, 2, 4096, 64
        q, k, v = qkv(b, h, s, d, jax.random.fold_in(key, 7))
        seg = (jnp.arange(s) // 1024).astype(jnp.int32)[None]  # 4 segments

        def loss(q, k, v):
            return jnp.sum(flash_attention_varlen(
                q, k, v, seg, causal=True, use_pallas=force)
                .astype(jnp.float32) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_varlen_reference(q, k, v, seg,
                                                      causal=True)
                           .astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        with jax.default_matmul_precision("highest"):
            gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        jax.block_until_ready(g)
        return {"max_err": nerr(g, gr)}

    record("varlen_s4096_parity_fwd_bwd", varlen_parity, tol=2e-2)

    # ---- timed kernel-only rows (value-transfer fence, no dense possible)
    def timed(step_fn, flops):
        loss = step_fn()  # compile + warm
        float(loss)
        t0 = time.perf_counter()
        for _ in range(TIMED_STEPS):
            loss = step_fn()
        last = float(loss)  # the only trustworthy fence on this tunnel
        dt = (time.perf_counter() - t0) / TIMED_STEPS
        row = {"ms": round(dt * 1e3, 3),
               "tflops_per_s": round(flops / dt / 1e12, 2),
               "finite": bool(np.isfinite(last))}
        if not row["finite"]:
            row["ok"] = False
            row["error"] = "non-finite loss"
        row.update(_mem_row())
        return row

    def make_causal_timed(s):
        def run():
            b, h, d = 1, 8, 64
            q, k, v = qkv(b, h, s, d, jax.random.fold_in(key, s))

            def loss(q, k, v):
                return jnp.sum(flash_attention(q, k, v, causal=True,
                                               use_pallas=force)
                               .astype(jnp.float32) ** 2)

            g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
            return timed(lambda: g(q, k, v)[0],
                         _causal_flops(b, h, s, d))
        return run

    # off-chip the kernel rows fall back to the DENSE reference: a 32k
    # rehearsal would materialize a (32k, 32k) score matrix per head —
    # rehearse the harness at small shapes instead (rows are marked not-ok
    # off-chip either way)
    timed_shapes = (8192, 16384, 32768) if on_tpu else (512, 1024)
    for s in timed_shapes:
        record(f"flash_causal_s{s}_timed_fwd_bwd", make_causal_timed(s))
    full_name = f"flash_causal_s{timed_shapes[-1]}_timed_fwd_bwd"

    def varlen_skip_timed():
        b, h, d = 1, 8, 64
        s, seg_len = (32768, 4096) if on_tpu else (1024, 128)
        q, k, v = qkv(b, h, s, d, jax.random.fold_in(key, 99))
        seg = (jnp.arange(s) // seg_len).astype(jnp.int32)[None]

        def loss(q, k, v):
            return jnp.sum(flash_attention_varlen(
                q, k, v, seg, causal=True, use_pallas=force)
                .astype(jnp.float32) ** 2)

        g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
        # credited work: 8 independent causal segments of 4096 = 1/8 of
        # the full-causal score work at s=32k
        n_seg = s // seg_len
        row = timed(lambda: g(q, k, v)[0],
                    n_seg * _causal_flops(b, h, seg_len, d))
        full = next((r for r in out
                     if r["kernel"] == full_name and "ms" in r), None)
        if full:
            row["speedup_vs_causal_full"] = round(full["ms"] / row["ms"], 2)
        return row

    record("varlen_blockskip_8seg_timed_fwd_bwd", varlen_skip_timed)

    return {"backend": jax.default_backend(), "on_tpu": on_tpu,
            "timed_steps": TIMED_STEPS, "rows": out}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from apex_tpu.utils.platform import pin_cpu_if_tunnel_dead

    pin_cpu_if_tunnel_dead()

    t0 = time.perf_counter()
    res = _results()
    res["total_seconds"] = round(time.perf_counter() - t0, 1)
    text = json.dumps(res, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if all(r["ok"] for r in res["rows"]):
        return 0
    return 1 if res["on_tpu"] else 2


if __name__ == "__main__":
    sys.exit(main())

"""FSDP (ZeRO-3) vs DDP+ZeRO-1 A/B — step time, HBM and wire bytes.

One ``json_record`` line (the bench.py protocol): the pinned GPT fixture
trained with the ``zero1`` plan (``DistributedFusedAdam``: params
replicated, optimizer state sharded — the repo's pre-FSDP best) and with
the ``fsdp`` plan (``apex_tpu.fsdp``: params sharded too, gather-on-demand
forward, grads reduce-scattered into shard layout), both configured
through ``ParallelismPlan`` presets. Columns:

* ``step_ms_zero1`` / ``step_ms_fsdp`` — compiled train-step wall time;
* ``peak_hbm_bytes_*`` — ``device_memory_stats`` when the backend reports
  it (TPU), else the modeled ``hbm_params_bytes`` accounting
  (``fsdp/accounting.py``) with an honest ``hbm_source`` marker;
* ``hbm_params_bytes_*`` + ``hbm_reduction_vs_zero1``/``_vs_ddp`` — the
  modeled per-chip param+grad+optimizer-state story (the acceptance
  metric: the replicated-params term ZeRO-1 keeps is what FSDP deletes);
* ``wire_bytes_*`` — modeled step wire bytes (same ring models
  ``comm.accounting`` prices off compiled HLO);
* ``ring.hidden_fraction`` — the FSDP-position gather ring
  (``matmul_param_gather`` MLP, fwd+bwd) measured from its compiled HLO
  by ``accounting.overlap_report``: the share of ring bytes that travel
  behind a GEMM.

On the CPU sim the time columns are NOT the story (collectives are
memcpys) — the HBM/wire/hidden-fraction columns are; the record carries
the ``_CPU_FALLBACK`` suffix and ``tpu_watch.sh`` stage 14 re-runs it on
the next healthy tunnel window. A single chip has no dp axis to shard
(the record says so honestly, like bench_overlap).

Run: ``python benchmarks/bench_fsdp.py [--plan fsdp|fsdp+tp] [--out F]``.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import (
    pin_cpu_if_requested,
    pin_cpu_if_tunnel_dead,
    pin_cpu_platform,
)

pin_cpu_if_requested()
pin_cpu_if_tunnel_dead()  # don't hang the watcher on a dead tunnel
if os.environ.get("JAX_PLATFORMS") == "cpu":
    pin_cpu_platform(virtual_devices=8)

import jax

ON_TPU = jax.default_backend() == "tpu"

import dataclasses  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

# the pinned protocol (canary discipline, see bench_comm.py): one fixed
# model so the line is comparable round-over-round
BATCH_PER_RANK, SEQ, HIDDEN, LAYERS, HEADS, VOCAB = 2, 256, 128, 2, 8, 512
STEPS = 5
LR = 1e-3


def _gpt(plan):
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    cfg = GPTConfig(vocab_size=VOCAB, max_seq=SEQ, hidden=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS, dtype=jnp.bfloat16,
                    **plan.gpt_overrides())
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _build_zero1(mesh, dp):
    """The baseline: DDP-style replicated params + ZeRO-1 sharded state
    (DistributedFusedAdam — its reduce-scatter/all-gather IS the dp grad
    machinery)."""
    from apex_tpu.parallel import ParallelismPlan
    from apex_tpu.transformer.testing import gpt_loss

    plan = ParallelismPlan.preset("zero1")
    cfg, params = _gpt(plan)
    opt = plan.build_optimizer(lr=LR)

    def init_fn(p):
        return opt.init(p)

    pspecs = jax.tree_util.tree_map(lambda _: P(), params)
    shard = jax.tree_util.tree_map(lambda _: P("dp"), params)
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistAdamState,
    )

    sspec = DistAdamState(count=P(), master=shard, mu=shard, nu=shard)
    init = jax.jit(jax.shard_map(
        init_fn, mesh=mesh, in_specs=(pspecs,), out_specs=sspec,
        check_vma=False))

    def body(p, st, t):
        l, g = jax.value_and_grad(lambda p: gpt_loss(p, t, t, cfg))(p)
        p, st = opt.step(g, st, p)
        return p, st, lax.pmean(l, "dp")

    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(pspecs, sspec, P("dp")),
        out_specs=(pspecs, sspec, P()), check_vma=False))
    tok = jax.random.randint(jax.random.PRNGKey(1),
                             (dp * BATCH_PER_RANK, SEQ), 0, VOCAB)
    ostate = init(params)
    compiled = step.lower(params, ostate, tok).compile()
    return plan, params, compiled, (params, ostate, tok)


def _local_meta(params, specs, mesh):
    """FSDP LeafMeta of the IN-PROGRAM (tp-local) leaf shapes: each
    sharded dim divided by its mesh axis size."""
    from apex_tpu.fsdp import LeafMeta

    def one(p, spec):
        shape = list(jnp.shape(p))
        for d, axes in enumerate(tuple(spec)):
            if axes is None:
                continue
            for a in (axes if isinstance(axes, tuple) else (axes,)):
                shape[d] //= mesh.shape[a]
        return LeafMeta(tuple(shape), str(jnp.result_type(p)))

    return jax.tree_util.tree_map(one, params, specs)


def _build_fsdp(mesh, dp, preset):
    from apex_tpu.fsdp import FSDPAdamState
    from apex_tpu.parallel import ParallelismPlan
    from apex_tpu.transformer.testing import gpt_loss, gpt_param_specs

    plan = ParallelismPlan.preset(preset)
    cfg, params = _gpt(plan)
    fsdp = plan.fsdp()
    opt = plan.build_optimizer(lr=LR)
    pspecs = (gpt_param_specs(cfg) if plan.tp > 1
              else jax.tree_util.tree_map(lambda _: P(), params))
    # flat master shards: dp-sharded, and under tp ALSO tp-varying (each
    # tp rank shards its own tp-local weights) — stack both axes
    shard_axes = ("dp", "tp") if plan.tp > 1 else ("dp",)
    shard = jax.tree_util.tree_map(lambda _: P(shard_axes), params)
    # meta must describe the TP-LOCAL leaf shapes the gather restores
    meta = _local_meta(params, pspecs, mesh)
    sspec = FSDPAdamState(count=P(), master=shard, mu=shard, nu=shard)
    init = jax.jit(jax.shard_map(
        opt.init, mesh=mesh, in_specs=(pspecs,), out_specs=sspec,
        check_vma=False))

    def body(st, t):
        def loss_fn(master):
            return gpt_loss(fsdp.gather(master, meta), t, t, cfg)

        l, g = jax.value_and_grad(loss_fn)(st.master)
        st = opt.step(g, st)
        return st, lax.pmean(l, "dp")

    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(sspec, P("dp")),
        out_specs=(sspec, P()), check_vma=False))
    tok = jax.random.randint(jax.random.PRNGKey(1),
                             (dp * BATCH_PER_RANK, SEQ), 0, VOCAB)
    state = init(params)
    compiled = step.lower(state, tok).compile()
    return plan, params, meta, fsdp, compiled, (state, tok)


def _time(compiled, args) -> float:
    out = compiled(*args)  # one warm run beyond the AOT compile
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = compiled(*args)
    jax.tree_util.tree_leaves(out)[-1].block_until_ready()
    return (time.perf_counter() - t0) / STEPS * 1e3


def _peak_hbm():
    """(peak bytes, source) — measured when the backend reports it."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return float(stats["peak_bytes_in_use"]), "device_memory_stats"
    except Exception:
        pass
    return None, "modeled"


def _ring_report():
    """Compile the FSDP-position gather-ring MLP (matmul_param_gather,
    fwd+bwd) and measure its hidden/exposed split from the HLO."""
    from apex_tpu.comm import overlap_report
    from apex_tpu.fsdp import FSDP
    from apex_tpu.parallel.mesh import build_mesh

    fsdp = FSDP()
    mesh = build_mesh(tp=1, pp=1, sp=1)
    d_in, d_h = 256, 512
    x = jax.random.normal(jax.random.PRNGKey(2),
                          (len(jax.devices()), 8, d_in), jnp.float32)
    w1 = jax.random.normal(jax.random.PRNGKey(3), (d_in, d_h), jnp.float32)
    w2 = jax.random.normal(jax.random.PRNGKey(4), (d_h, d_in), jnp.float32)

    def loss(x, w1, w2):
        def body(x, w1s, w2s):
            h = jax.nn.gelu(fsdp.linear(x[0], w1s))
            y = fsdp.linear(h, w2s)
            return lax.psum(jnp.sum(y * y), "dp")

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("dp"), P(None, "dp"), P(None, "dp")),
            out_specs=P())(x, w1, w2)

    compiled = jax.jit(jax.value_and_grad(loss, argnums=(1, 2))).lower(
        x, w1, w2).compile()
    rep = overlap_report(compiled.as_text())
    return {"permutes": rep.permutes, "hidden": rep.hidden,
            "hidden_bytes": round(rep.hidden_wire_bytes),
            "exposed_bytes": round(rep.exposed_wire_bytes),
            "hidden_fraction": round(rep.hidden_fraction, 4)}


def main() -> int:
    import argparse

    from apex_tpu.monitor import json_record
    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())
    ap = argparse.ArgumentParser()
    ap.add_argument("--plan", default="fsdp", choices=["fsdp", "fsdp+tp"])
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    n_dev = len(jax.devices())
    name = "gpt_fsdp_vs_zero1_step"
    if not ON_TPU:
        name += "_CPU_FALLBACK"
    if n_dev < 2:
        line = json_record(
            metric=name, ok=False, n_devices=n_dev,
            reason="single device: no dp axis to shard; needs a slice")
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 2

    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        _zero_wire_bytes,
    )
    from apex_tpu.fsdp import fsdp_step_wire_bytes, hbm_params_bytes
    from apex_tpu.parallel import ParallelismPlan
    from apex_tpu.parallel.mesh import build_mesh

    fs_plan = ParallelismPlan.preset(args.plan)
    tp = fs_plan.tp
    dp = n_dev // tp
    mesh_base = build_mesh(tp=1, pp=1, sp=1)
    mesh_fs = fs_plan.mesh()

    # fsdp runs FIRST: ``peak_bytes_in_use`` is a process-lifetime
    # high-water mark, so the side the regress gate watches (fsdp,
    # lower-is-better) must be measured before the bigger zero1 program
    # raises the mark. z_peak is then max(fsdp, zero1) — zero1's own peak
    # whenever the claim under test holds.
    plan_f, f_params, meta, fsdp, f_compiled, f_args = _build_fsdp(
        mesh_fs, dp, args.plan)
    f_ms = _time(f_compiled, f_args)
    f_peak, f_src = _peak_hbm()

    plan_z, params, z_compiled, z_args = _build_zero1(mesh_base, n_dev)
    z_ms = _time(z_compiled, z_args)
    z_peak, _ = _peak_hbm()

    h_ddp = hbm_params_bytes(params, strategy="ddp", world=n_dev)
    h_z = hbm_params_bytes(params, strategy="zero1", world=n_dev)
    # per-chip: the fsdp side shards its TP-LOCAL leaves over dp
    h_f = hbm_params_bytes(meta, strategy="fsdp", world=dp)
    ring = _ring_report()

    record = dict(
        metric=name,
        ok=bool(ring["hidden_fraction"] >= 0.5),
        n_devices=n_dev, dp=dp, tp=tp, plan=args.plan,
        step_ms_zero1=round(z_ms, 3),
        step_ms_fsdp=round(f_ms, 3),
        hbm_source=f_src,
        peak_hbm_bytes_zero1=round(z_peak) if z_peak else round(
            h_z["total"]),
        peak_hbm_bytes_fsdp=round(f_peak) if f_peak else round(
            h_f["total"]),
        hbm_params_bytes_ddp=round(h_ddp["total"]),
        hbm_params_bytes_zero1=round(h_z["total"]),
        hbm_params_bytes_fsdp=round(h_f["total"]),
        hbm_reduction_vs_zero1=round(h_z["total"] / h_f["total"], 3),
        hbm_reduction_vs_ddp=round(h_ddp["total"] / h_f["total"], 3),
        wire_bytes_zero1=round(_zero_wire_bytes(
            jax.tree_util.tree_leaves(params), n_dev, None)),
        wire_bytes_fsdp=round(fsdp_step_wire_bytes(meta, dp)),
        ring=ring,
        config={"batch_per_rank": BATCH_PER_RANK, "seq": SEQ,
                "hidden": HIDDEN, "layers": LAYERS, "heads": HEADS,
                "vocab": VOCAB, "steps": STEPS,
                "zero1": plan_z.describe(), "fsdp": plan_f.describe()},
    )
    line = json_record(**record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    if not hasattr(jax, "shard_map"):
        # stock-jax box: the mesh program cannot build — fail loudly, do
        # not bank a fake artifact (the watcher retries next window)
        print('{"metric": "fsdp_vs_zero1_step", "ok": false, '
              '"reason": "jax.shard_map unavailable (stock jax)"}')
        raise SystemExit(2)
    raise SystemExit(main())

"""Sweep Pallas kernel block sizes on hardware at the bench shape.

Round-3 task: close the MFU gap by tuning the knobs the kernels expose —
flash attention ``block_q``/``block_k`` and fused LM-head
``block_n``/``block_v`` (plus ``scan_unroll`` at the step level, which
bench.py's remat auto-tune already covers). This script times each
candidate on the real chip with the value-transfer fence and prints the
winner as the GPTConfig overrides to commit.

Run: ``python benchmarks/tune_blocks.py [--steps N]``. Refuses to sweep
on a non-TPU backend (interpret-mode timings would be meaningless) and
prints the shapes it would have swept.
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# flagship bench shape (bench.py): GPT-2 124M, batch 32, seq 1024
B, S, HEADS, HEAD_DIM, HIDDEN, VOCAB = 32, 1024, 12, 64, 768, 50304


def _fence(x):
    leaves = jax.tree.leaves(x)
    jax.block_until_ready(leaves)
    float(jax.numpy.sum(leaves[0].ravel()[:1]))


def _time(fn, *args, steps=5):
    fn(*args)  # compile
    _fence(fn(*args))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    _fence(out)
    return (time.perf_counter() - t0) / steps


def sweep_attention(steps: int):
    import jax.numpy as jnp

    from apex_tpu.ops.attention import flash_attention

    k = jax.random.PRNGKey(0)
    q = jax.random.normal(k, (B, HEADS, S, HEAD_DIM), jnp.bfloat16)
    kk = jax.random.normal(jax.random.fold_in(k, 1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(k, 2), q.shape, jnp.bfloat16)

    results = []
    for bq, bk in itertools.product((128, 256, 512, 1024), repeat=2):
        def fwd_bwd(q, kk, v, bq=bq, bk=bk):
            def loss(q, kk, v):
                return jnp.sum(flash_attention(
                    q, kk, v, causal=True, use_pallas=True,
                    block_q=bq, block_k=bk).astype(jnp.float32) ** 2)

            return jax.grad(loss, argnums=(0, 1, 2))(q, kk, v)

        try:
            dt = _time(jax.jit(fwd_bwd), q, kk, v, steps=steps)
        except Exception as e:  # block combo invalid/OOM on this chip
            print(f"attn bq={bq:4d} bk={bk:4d}  FAILED "
                  f"{type(e).__name__}", flush=True)
            continue
        print(f"attn bq={bq:4d} bk={bk:4d}  {dt * 1e3:8.3f} ms", flush=True)
        results.append((dt, bq, bk))
    if results:
        dt, bq, bk = min(results)
        print(f"BEST attention: attn_block_q={bq}, attn_block_k={bk} "
              f"({dt * 1e3:.3f} ms fwd+bwd)")
    return results


def sweep_lm_head(steps: int):
    import jax.numpy as jnp

    from apex_tpu.ops.lm_head_loss import lm_head_loss

    k = jax.random.PRNGKey(0)
    n = B * S
    x = jax.random.normal(k, (n, HIDDEN), jnp.bfloat16) * 0.1
    w = jax.random.normal(jax.random.fold_in(k, 1), (VOCAB, HIDDEN),
                          jnp.bfloat16) * 0.02
    t = jax.random.randint(jax.random.fold_in(k, 2), (n,), 0, VOCAB)

    results = []
    for bn, bv in itertools.product((256, 512, 1024), (1024, 2048, 4096)):
        def fwd_bwd(x, w, bn=bn, bv=bv):
            def loss(x, w):
                return jnp.mean(lm_head_loss(x, w, t, use_pallas=True,
                                             block_n=bn, block_v=bv))

            return jax.grad(loss, argnums=(0, 1))(x, w)

        try:
            dt = _time(jax.jit(fwd_bwd), x, w, steps=steps)
        except Exception as e:
            print(f"lm_head bn={bn:4d} bv={bv:4d}  FAILED "
                  f"{type(e).__name__}", flush=True)
            continue
        print(f"lm_head bn={bn:4d} bv={bv:4d}  {dt * 1e3:8.3f} ms",
              flush=True)
        results.append((dt, bn, bv))
    if results:
        dt, bn, bv = min(results)
        print(f"BEST lm_head: lm_block_n={bn}, lm_block_v={bv} "
              f"({dt * 1e3:.3f} ms fwd+bwd)")

    # The head is ~30% of the flagship step's flops and XLA's native
    # (32768, 768) x (768, 50304) matmul is a near-peak MXU workload —
    # the fused kernel's win (never materializing the 3.2 GB logits)
    # only pays if its matmul efficiency is close. Time the REAL unfused
    # path (what GPTConfig.fused_loss=False runs: bf16 logits into
    # vocab_parallel_cross_entropy, standalone_gpt.py:666-668) at the
    # same shape so the comparison is on the record against the actual
    # alternative, not a heavier fp32 strawman.
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.mesh import build_mesh
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
    )

    # the real path runs under shard_map with a (size-1 here) tp axis —
    # vocab_parallel_cross_entropy's pmax needs the axis to exist
    mesh1 = build_mesh(tp=1, pp=1, sp=1, devices=jax.devices()[:1])

    def unfused(x, w):
        def body(x, w):
            def loss(x, w):
                lg = jnp.dot(x, w.T)  # model dtype; CE upcasts internally
                return jnp.mean(vocab_parallel_cross_entropy(lg, t))

            return jax.grad(loss, argnums=(0, 1))(x, w)

        return jax.shard_map(body, mesh=mesh1, in_specs=(P(), P()),
                             out_specs=(P(), P()), check_vma=False)(x, w)

    try:
        dt_un = _time(jax.jit(unfused), x, w, steps=steps)
        print(f"lm_head UNFUSED (XLA logits+CE)  {dt_un * 1e3:8.3f} ms",
              flush=True)
        if results and dt_un < min(results)[0]:
            print(f"NOTE: unfused beats the fused kernel by "
                  f"{min(results)[0] / dt_un:.2f}x — set "
                  f"GPTConfig.fused_loss=False", flush=True)
    except Exception as e:
        print(f"lm_head UNFUSED  FAILED {type(e).__name__} "
              f"(likely logits OOM — which is the fused kernel's point)",
              flush=True)
    return results


def _full_step_ab(steps: int, knob: str, values):
    """Full-step A/B of one GPTConfig knob at the quick-bench config,
    timed by bench._measure — ONE copy of the compile/warm/fence/timing
    protocol (the value-transfer fence has been fixed once already for
    the axon tunnel; a fix must not need re-applying in three sweeps)."""
    import bench

    results = []
    for v in values:
        tps, _, err = bench._measure(True, "full", bench.BATCH, bench.SEQ,
                                     steps, **{knob: v})
        if tps is None:
            print(f"{knob}={v}  FAILED {err}", flush=True)
            continue
        dt = bench.BATCH * bench.SEQ / tps
        print(f"{knob}={v}  {dt * 1e3:8.3f} ms/step", flush=True)
        results.append((dt, v))
    if results:
        dt, v = min(results)
        print(f"BEST {knob}: {v} ({dt * 1e3:.3f} ms/step)")
    return results


def sweep_fused_loss(steps: int):
    """Full-step A/B of GPTConfig.fused_loss — the in-context answer
    (interacts with remat and XLA's scheduling) to the same question
    sweep_lm_head's unfused row answers in isolation."""
    return _full_step_ab(steps, "fused_loss", (True, False))


def sweep_ln_impl(steps: int):
    """Full-step A/B of the LayerNorm implementation (GPTConfig.ln_pallas).

    Isolated LN timing cannot answer this one: a Pallas call is an XLA
    fusion barrier, so the kernel's fewer HBM passes compete against the
    fusions XLA gives up around it."""
    return _full_step_ab(steps, "ln_pallas", (True, False))


# the serve shapes the --megakernel-tiles sweep covers: the GPT-2-124M
# flagship layer plus its nearest production neighbours
MEGA_TILE_SHAPES = ((768, 4, 64), (512, 4, 64), (1024, 4, 64))


def sweep_megakernel_tiles(steps: int, out=None):
    """Time the fused decode block (serve.megakernel) at every VMEM-
    feasible lane-aligned weight tiling per serve shape and emit ONE
    ``json_record`` line naming the best tile config per (hidden,
    ffn_mult, head_dim). The greedy ``default_tiles`` pick is timed in
    the same sweep, so the record says whether the static heuristic
    left latency on the table (the knob to commit if it did:
    ``fused_layer_decode(..., tiles=...)``)."""
    import itertools as it

    import jax.numpy as jnp

    from apex_tpu.monitor import json_record
    from apex_tpu.monitor.sink import collect_provenance, set_provenance
    from apex_tpu.serve import KVCacheConfig, init_kv_cache
    from apex_tpu.serve.megakernel import (
        _VMEM_BUDGET_BYTES,
        _tiled_dims,
        _valid_tile_counts,
        default_tiles,
        fused_layer_decode,
        fused_live_bytes,
    )
    from apex_tpu.transformer.testing import GPTConfig

    set_provenance(collect_provenance())
    sweeps = []
    for hidden, ffn_mult, head_dim in MEGA_TILE_SHAPES:
        heads = hidden // head_dim
        cfg = GPTConfig(vocab_size=512, max_seq=1024, hidden=hidden,
                        num_layers=1, num_heads=heads, ffn_mult=ffn_mult,
                        dtype=jnp.bfloat16, fused_loss=False)
        kv = KVCacheConfig(num_layers=1, num_heads=heads,
                           head_dim=head_dim, num_blocks=16,
                           block_size=128, dtype=jnp.bfloat16)
        # every lane-aligned tiling whose live set fits the budget,
        # coarsest (fewest streaming DMAs) first
        cands = [t for t in it.product(*(
            _valid_tile_counts(d, True) for d in _tiled_dims(cfg)))
            if fused_live_bytes(cfg, kv, t) <= _VMEM_BUDGET_BYTES]
        cands.sort(key=lambda t: (t[0] * t[1] * t[2], t))
        cands = cands[:24]  # bound the sweep; coarse tilings dominate
        greedy = default_tiles(cfg, kv)
        h = cfg.hidden
        dt_ = jnp.bfloat16
        f3, hd, f = 3 * h, heads * head_dim, cfg.ffn_hidden
        k = jax.random.PRNGKey(0)
        lp = {
            "ln1_w": jnp.ones((h,), dt_), "ln1_b": jnp.zeros((h,), dt_),
            "qkv_kernel": jax.random.normal(k, (h, f3), dt_) * 0.02,
            "qkv_bias": jnp.zeros((f3,), dt_),
            "out_kernel": jax.random.normal(
                jax.random.fold_in(k, 1), (hd, h), dt_) * 0.02,
            "out_bias": jnp.zeros((h,), dt_),
            "ln2_w": jnp.ones((h,), dt_), "ln2_b": jnp.zeros((h,), dt_),
            "fc1_kernel": jax.random.normal(
                jax.random.fold_in(k, 2), (h, f), dt_) * 0.02,
            "fc1_bias": jnp.zeros((f,), dt_),
            "fc2_kernel": jax.random.normal(
                jax.random.fold_in(k, 3), (f, h), dt_) * 0.02,
            "fc2_bias": jnp.zeros((h,), dt_),
        }
        cl = {kk: v[0] for kk, v in init_kv_cache(kv).items()}
        x = jax.random.normal(jax.random.fold_in(k, 4),
                              (8, h), dt_) * 0.1
        bt = jnp.tile(jnp.arange(2, dtype=jnp.int32), (8, 1))
        lens = jnp.full((8,), 200, jnp.int32)
        rows = []
        for tiles in cands:
            def fn(x, lp, cl, bt, lens, tiles=tiles):
                return fused_layer_decode(x, lp, cl, cfg, kv, bt, lens,
                                          interpret=False, tiles=tiles)

            try:
                dt = _time(jax.jit(fn), x, lp, cl, bt, lens, steps=steps)
            except Exception as e:
                print(f"mega h={hidden} tiles={tiles}  FAILED "
                      f"{type(e).__name__}", flush=True)
                continue
            print(f"mega h={hidden} tiles={tiles}  {dt * 1e6:8.1f} us "
                  f"(live {fused_live_bytes(cfg, kv, tiles)} B)",
                  flush=True)
            rows.append((dt, tiles))
        if not rows:
            continue
        dt_best, best = min(rows)
        dt_greedy = next((d for d, t in rows if t == greedy), None)
        sweeps.append({
            "hidden": hidden, "ffn_mult": ffn_mult, "head_dim": head_dim,
            "best_tiles": list(best),
            "best_us": round(dt_best * 1e6, 1),
            "greedy_tiles": list(greedy) if greedy else None,
            "greedy_us": (round(dt_greedy * 1e6, 1)
                          if dt_greedy is not None else None),
            "live_bytes": fused_live_bytes(cfg, kv, best),
            "candidates_timed": len(rows),
        })
        print(f"BEST mega h={hidden} ffn_mult={ffn_mult} "
              f"hd={head_dim}: tiles={best} ({dt_best * 1e6:.1f} us)")
    line = json_record(metric="megakernel_tile_sweep",
                       ok=bool(sweeps), sweeps=sweeps,
                       vmem_budget_bytes=_VMEM_BUDGET_BYTES,
                       backend=jax.default_backend())
    print(line, flush=True)
    if out:
        with open(out, "w") as fh:
            fh.write(line + "\n")
    return 0 if sweeps else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out", default=None)
    ap.add_argument("--megakernel-tiles", action="store_true",
                    help="sweep fused-decode weight tilings instead of "
                         "the training-kernel block knobs")
    args = ap.parse_args()

    from apex_tpu.utils.platform import probe_backend

    if os.environ.get("JAX_PLATFORMS") == "cpu" or probe_backend() == 0:
        if args.megakernel_tiles:
            shapes = ", ".join(f"(h={h}, ffn={m}x, d={d})"
                               for h, m, d in MEGA_TILE_SHAPES)
            print(f"tune_blocks: needs the real TPU (would sweep "
                  f"megakernel weight tiles at {shapes}; backend "
                  f"unavailable)")
        else:
            print(f"tune_blocks: needs the real TPU (would sweep "
                  f"attention (b={B}, h={HEADS}, s={S}, d={HEAD_DIM}) "
                  f"bf16 and lm_head (n={B * S}, h={HIDDEN}, "
                  f"v={VOCAB}); backend unavailable)")
        return 0
    if jax.default_backend() != "tpu":
        print(f"tune_blocks: backend is {jax.default_backend()}, not tpu; "
              f"refusing to sweep (interpret timings are meaningless)")
        return 0
    if args.megakernel_tiles:
        return sweep_megakernel_tiles(args.steps, out=args.out)
    sweep_attention(args.steps)
    sweep_lm_head(args.steps)
    sweep_ln_impl(args.steps)
    sweep_fused_loss(args.steps)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Quantify the pipeline schedules' memory/recompute cost vs (M, pp).

The 1F1B ring-scan design (``fwd_bwd_pipelining_without_interleaving``)
deliberately trades the Megatron 1F1B memory property (≤ pp in-flight
microbatches, no interior recompute) for one-``lax.scan`` uniformity: it
saves ONE stage-boundary tensor per tick over ``M + pp - 1`` ticks and
remats stage interiors in the backward sweep. This script measures that
trade with XLA's own buffer assignment (``compiled.memory_analysis()``)
and cost model (``cost_analysis()``) instead of asserting it:

* temp bytes vs M at fixed pp → the O(M) boundary-save slope;
* temp bytes for interleaved (vp=2) vs 1F1B at the same (M, pp);
* flops(remat) / flops(no-remat) → the recompute factor (≤ one extra
  forward ≈ 4/3 of fwd+bwd);
* the pp=1, remat-off ring (≡ plain grad accumulation) as the ideal
  baseline.

Numbers are WHOLE-MESH totals over the 8 virtual CPU devices (virtual
devices share one buffer assignment); per-device HBM is total/8 for
evenly-sharded programs. Run: ``python benchmarks/pipeline_memory.py``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import pin_cpu_platform

pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_pipeline_params,
    gpt_pipeline_spec,
    gpt_pipeline_specs_tree,
)

HID, SEQ, HEADS, LAYERS = 64, 64, 4, 4
B_PER_MB = 2  # per-dp-shard microbatch rows: fixed as M varies

# flagship operating point (bench.py's GPT-2 124M-class architecture at a
# pipeline-able depth): the boundary:interior byte ratio shifts with
# hidden, so the O(M) slope and recompute-factor claims are also pinned
# here, not just at the toy shape (VERDICT r3 weak #5)
FLAGSHIP = dict(hid=768, seq=512, heads=12, layers=12, b_per_mb=1)


def build_case(pp: int, M: int, *, remat: bool, vp=None, hid=HID, seq=SEQ,
               heads=HEADS, layers=LAYERS, b_per_mb=B_PER_MB):
    """-> (compiled, meta) for one schedule config on the 8-device mesh."""
    dp = 8 // pp
    mesh = build_mesh(tp=1, pp=pp, sp=1, dp=dp)
    cfg = GPTConfig(vocab_size=64, max_seq=seq, hidden=hid,
                    num_layers=layers, num_heads=heads, dtype=jnp.float32,
                    tie_embeddings=False, remat=False)  # remat at ring level
    params = gpt_pipeline_params(jax.random.PRNGKey(0), cfg, pp=pp, vp=vp)
    spec = gpt_pipeline_spec(cfg)
    specs_tree = gpt_pipeline_specs_tree(cfg, interleaved=vp is not None)

    b_global = b_per_mb * dp * M
    tokens = jnp.zeros((b_global, seq), jnp.int32)
    targets = jnp.zeros((b_global, seq), jnp.int32)

    if vp is None:
        def step(params, tokens, targets):
            return forward_backward_pipelining_without_interleaving(
                spec, params, (tokens, targets), num_microbatches=M,
                mesh=mesh, params_specs=specs_tree, remat=remat)
    else:
        def step(params, tokens, targets):
            return forward_backward_pipelining_with_interleaving(
                spec, params, (tokens, targets), num_microbatches=M,
                virtual_pipeline_size=vp, mesh=mesh,
                params_specs=specs_tree, remat=remat)

    compiled = jax.jit(step).lower(params, tokens, targets).compile()
    return compiled


def measure(pp, M, *, remat=True, vp=None, **shape):
    c = build_case(pp, M, remat=remat, vp=vp, **shape)
    ma = c.memory_analysis()
    ca = c.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {
        "schedule": ("interleaved" if vp else
                     ("1F1B" if pp > 1 else "grad-accum")),
        "pp": pp, "vp": vp or 1, "M": M, "remat": remat,
        "shape": shape or None,
        "temp_mb": ma.temp_size_in_bytes / 1e6,
        "peak_mb": ma.peak_memory_in_bytes / 1e6,
        "arg_mb": ma.argument_size_in_bytes / 1e6,
        "gflops": float(ca.get("flops", 0.0)) / 1e9,
    }


GRID = [
    dict(pp=1, M=4, remat=False),            # ideal: grad accum, no remat
    dict(pp=1, M=4, remat=True),
    dict(pp=2, M=4, remat=False),
    dict(pp=2, M=4, remat=True),
    dict(pp=2, M=8, remat=True),
    dict(pp=2, M=16, remat=True),
    dict(pp=4, M=4, remat=True),
    dict(pp=4, M=8, remat=True),
    dict(pp=2, M=4, remat=True, vp=2),
    dict(pp=2, M=8, remat=True, vp=2),
]


def flagship_rows():
    """The flagship-shape leg (``--flagship``): slope and recompute factor
    at hidden=768/12-layer, buffer-assignment only (no execution)."""
    rows = {
        "m4": measure(2, 4, remat=True, **FLAGSHIP),
        "m8": measure(2, 8, remat=True, **FLAGSHIP),
        "m4_noremat": measure(2, 4, remat=False, **FLAGSHIP),
    }
    slope = (rows["m8"]["temp_mb"] - rows["m4"]["temp_mb"]) / 4
    boundary_mb = (FLAGSHIP["b_per_mb"] * FLAGSHIP["seq"] * FLAGSHIP["hid"]
                   * 4 * 8 / 1e6)
    factor = rows["m4"]["gflops"] / rows["m4_noremat"]["gflops"]
    for r in rows.values():
        print(f"flagship {r['schedule']:>9s} pp={r['pp']} M={r['M']:>2d} "
              f"remat={int(r['remat'])} | temp {r['temp_mb']:8.1f} MB | "
              f"peak {r['peak_mb']:8.1f} MB | {r['gflops']:8.2f} GFLOP",
              flush=True)
    print(f"flagship slope {slope:.2f} MB/mb (boundary prediction "
          f"{boundary_mb:.2f}), recompute factor {factor:.3f}")
    return rows, slope, boundary_mb, factor


def main() -> int:
    if "--flagship" in sys.argv:
        flagship_rows()
        return 0
    rows = []
    for kw in GRID:
        r = measure(**kw)
        rows.append(r)
        print(f"{r['schedule']:>11s} pp={r['pp']} vp={r['vp']} M={r['M']:>2d} "
              f"remat={int(r['remat'])} | temp {r['temp_mb']:8.1f} MB | "
              f"peak {r['peak_mb']:8.1f} MB | args {r['arg_mb']:6.1f} MB | "
              f"{r['gflops']:8.2f} GFLOP", flush=True)

    by = {(r["schedule"], r["pp"], r["M"], r["remat"], r["vp"]): r
          for r in rows}
    f11b_4 = by[("1F1B", 2, 4, True, 1)]
    f11b_8 = by[("1F1B", 2, 8, True, 1)]
    f11b_16 = by[("1F1B", 2, 16, True, 1)]
    slope_lo = (f11b_8["temp_mb"] - f11b_4["temp_mb"]) / 4
    slope_hi = (f11b_16["temp_mb"] - f11b_8["temp_mb"]) / 8
    # boundary tensor per tick per device: [B_PER_MB, SEQ, HID] f32; the
    # scan stacks M+pp-1 of them per device for the backward sweep, summed
    # over the 8 virtual devices in these whole-mesh numbers
    boundary_mb = B_PER_MB * SEQ * HID * 4 * 8 / 1e6
    ideal = by[("grad-accum", 1, 4, False, 1)]
    print()
    print(f"1F1B temp slope: {slope_lo:.2f} (M 4→8) / {slope_hi:.2f} "
          f"(M 8→16) MB per microbatch; boundary-save prediction "
          f"~{boundary_mb:.2f} MB/mb (whole mesh)")
    print(f"recompute factor pp=2 M=4: "
          f"{by[('1F1B', 2, 4, True, 1)]['gflops'] / by[('1F1B', 2, 4, False, 1)]['gflops']:.3f} "
          f"(remat on/off); ideal-vs-1F1B flops overhead: "
          f"{by[('1F1B', 2, 4, False, 1)]['gflops'] / ideal['gflops']:.3f} "
          f"(fill/drain ticks)")
    print(f"interleaved vp=2 vs 1F1B temp at pp=2 M=8: "
          f"{by[('interleaved', 2, 8, True, 2)]['temp_mb']:.1f} vs "
          f"{f11b_8['temp_mb']:.1f} MB")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Verify bench.py's MFU flops-per-token constant against XLA's own HLO
cost analysis (VERDICT r1 weak #9: the denominator was self-graded).

Compiles the exact bench train step (remat OFF, so HLO flops = algorithmic
flops with no recompute double-counting) at a reduced batch on the current
backend and compares ``cost_analysis()['flops']`` with the analytic
``6·N_params + 6·L·hidden·seq`` per-token model — both sides now come from
``apex_tpu.monitor.report`` (:func:`mfu_check` does the compile-side join,
:func:`gpt_analytic_flops_per_token` is the same constant ``bench.py``
divides by). Flops are linear in batch, so a small batch checks the same
constant the bench divides by.

Prints ONE schema-stamped JSON line (``monitor.sink.json_record``).

Run: JAX_PLATFORMS=cpu python benchmarks/check_mfu_accounting.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import pin_cpu_platform

pin_cpu_platform()
import jax

BATCH, SEQ = 4, 1024


def main() -> None:
    from bench import build_train_step, flagship_config
    from apex_tpu.monitor import (
        gpt_analytic_flops_per_token,
        json_record,
        mfu_check,
    )

    # remat=False: no recompute double-counting. scan_unroll=num_layers:
    # XLA cost analysis counts a rolled scan body ONCE (a while loop has no
    # static trip count), which under-reports by ~the layer count —
    # unrolling makes the HLO flops complete. Everything else is exactly
    # the model/step bench.py times (shared builder).
    import dataclasses

    cfg = flagship_config(SEQ, remat=False)
    cfg = dataclasses.replace(cfg, scan_unroll=cfg.num_layers)
    train_step, params, opt_state, tok, tgt = build_train_step(
        cfg, BATCH, SEQ)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    tokens = BATCH * SEQ
    analytic = gpt_analytic_flops_per_token(
        n_params, cfg.num_layers, cfg.hidden, SEQ) * tokens

    res = mfu_check(train_step, params, opt_state, tok, tgt,
                    analytic_flops=analytic)
    print(json_record(
        metric="mfu_denominator_check",
        hlo_flops=res["hlo_flops"],
        analytic_flops=analytic,
        hlo_over_analytic=res["hlo_over_analytic"],
        wire_bytes=res["wire_bytes"],
        batch=BATCH, seq=SEQ, n_params=n_params,
    ))


if __name__ == "__main__":
    main()

#!/bin/bash
# TPU tunnel watcher v3 — STAGED fire (VERDICT r3 task 2).
#
# Rounds 2 and 3 both died with a dead tunnel and no real-TPU number on
# disk. v3's contract: even a ~5-minute healthy window banks a headline —
# stage 1 is a no-tune quick bench that persists BENCH_watch.json before
# anything heavier starts, and every later stage writes its own artifact
# the moment it finishes. A kill mid-suite loses only the stages after it.
#
# Launch DETACHED (the Bash tool kills its own background children at the
# 10-min cap):   setsid nohup bash benchmarks/tpu_watch.sh &
#
# Stages on a healthy probe:
#   1 quick headline  bench.py --quick      -> BENCH_watch.json      (~3 min)
#   2 kernel smoke    smoke_tpu.py          -> SMOKE_TPU.json        (~2 min)
#   3 tuned headline  bench.py (full sweep) -> BENCH_watch.json      (~15 min)
#   4 step profile    profile_step.py       -> PROFILE_TPU.txt
#   5 block tuner     tune_blocks.py        -> TUNE_TPU.txt
#   6 baseline matrix bench_matrix.py       -> BENCH_MATRIX_TPU.txt
#   7 long-seq rows   long_seq_tpu.py       -> LONGSEQ_TPU.json
#   8 overlap A/B     bench_overlap.py      -> OVERLAP_TPU.json
#   9 serve engine    bench_serve.py        -> SERVE_TPU.json
#  10 serve SLO       bench_serve.py --loadgen -> SERVE_SLO_TPU.json
#  11 serve prefix    bench_serve.py --loadgen --prefix-pool --spec-k
#                                           -> SERVE_PREFIX_TPU.json
#  12 decode fused A/B bench_serve.py --megakernel-ab --spec-k 4
#                                           -> DECODE_FUSED_TPU.json
#  13 fused update    bench_fused_update.py -> FUSED_UPDATE_TPU.json
#  14 fsdp A/B        bench_fsdp.py         -> FSDP_TPU.json
#  15 serve multihost bench_serve_mh.py --hosts 2 -> SERVE_MH_TPU.json
#  16 contract check  analyze_contracts.py  -> ANALYZE_TPU.json
#  17 sub-8-bit tier  bench_serve_mh.py --kv-quant int4 + bench_comm.py
#                                           -> SERVE_KV4_TPU.json
#                                              + COMM_SUB8_TPU.json
#  18 serve chaos     bench_serve_mh.py --hosts 3 --chaos
#                                           -> SERVE_CHAOS_TPU.json
#  19 observe A/B     bench_observe.py      -> OBSERVE_TPU.json
#  20 LoRA serve A/B  bench_serve_mh.py --lora -> SERVE_LORA_TPU.json
#  21 forensics A/B   bench_attrib_cost.py  -> ATTRIB_COST_TPU.json
#  22 elastic train   bench_elastic.py      -> ELASTIC_TPU.json
#  23 mega tier-2 A/B bench_serve.py --megakernel-ab --spec-k 4
#                       --model flagship    -> DECODE_FUSED_T2_TPU.json
#  24 serve plan      bench_serve_mh.py --plan all -> SERVE_PLAN_TPU.json
# After the first seven, later healthy probes only refresh stage 1+3
# (hourly) so the banked number tracks the latest code; stages 8-24
# ride the same hourly cadence until banked (additive evidence that must
# never hold the suite out of refresh mode).
#
# Tier 4 (monitor.trend): every promoted JSON record ALSO appends a
# trend_point to TREND_HISTORY.jsonl and drift-checks the per-stage
# series — the longitudinal gate that catches 3%-per-hop drifts the
# pairwise 15% regress gate structurally cannot. The check runs NEXT TO
# the regress gates, never instead of them: drift notes loudly in the
# log but cannot un-promote a record that already passed its stage.
cd /root/repo || exit 1
export APEX_TPU_PROBE_NO_CACHE=1
LOG=/tmp/tpu_health.log
STATE=/tmp/tpu_watch_stage   # highest completed stage, survives restarts
[ -f "$STATE" ] || echo 0 > "$STATE"
last_refresh=0
last_longseq=-3600  # first stage-7 attempt immediate, retries hourly
last_overlap=-3600  # stage-8 (overlap A/B) same hourly retry contract
last_serve=-3600    # stage-9 (serve engine) same hourly retry contract
last_slo=-3600      # stage-10 (serve goodput-SLO) same hourly contract
last_prefix=-3600   # stage-11 (shared-prefix + speculative) same contract
last_mega=-3600     # stage-12 (megakernel decode A/B) same contract
last_fusedupd=-3600 # stage-13 (fused update tail) same contract
last_fsdp=-3600     # stage-14 (fsdp vs zero1 A/B) same contract
last_mh=-3600       # stage-15 (disaggregated serve cluster) same contract
last_analyze=-3600  # stage-16 (compiled-program contract check) same
last_sub8=-3600     # stage-17 (sub-8-bit: int4 KV + comm wire A/B) same
last_chaos=-3600    # stage-18 (elastic serve chaos: kill-and-migrate) same
last_observe=-3600  # stage-19 (fleet observability overhead A/B) same
last_lora=-3600     # stage-20 (per-tenant LoRA serve A/B) same
last_attrib=-3600   # stage-21 (attribution + cost forensics A/B) same
last_elastic=-3600  # stage-22 (elastic train: reshard + kill-resume) same
last_megat2=-3600   # stage-23 (megakernel tier-2 flagship A/B) same
last_serveplan=-3600 # stage-24 (plan-sharded serve residency) same

note() { echo "$(date '+%F %T') $*" >> "$LOG"; }

TREND=TREND_HISTORY.jsonl
trend_bank() {  # trend_bank <stage-name> <promoted-artifact>
  # tier-4 longitudinal gate: append the just-promoted record to the
  # per-stage history, then drift-check the series (monitor.trend:
  # median+MAD step changes, Theil-Sen slow drifts). Additive only —
  # a drift is loud evidence in the log, never a reason to claw back a
  # promotion that already passed its own CPU_FALLBACK/ok/regress gates.
  local stage=$1 art=$2
  python -m apex_tpu.monitor.trend append "$TREND" "$art" \
    --stage "$stage" >> /tmp/tpu_trend.out 2>> /tmp/tpu_trend.err
  if ! python -m apex_tpu.monitor.trend check "$TREND" --stage "$stage" \
      > "/tmp/tpu_trend_${stage}.json" 2>> /tmp/tpu_trend.err; then
    note "TREND DRIFT stage=$stage: $(cat "/tmp/tpu_trend_${stage}.json")"
  fi
}

run_stage() {  # run_stage <n> <timeout> <artifact-check-file> <cmd...>
  local n=$1 to=$2 art=$3; shift 3
  note "STAGE$n START: $*"
  timeout "$to" "$@" > "/tmp/tpu_stage$n.out" 2> "/tmp/tpu_stage$n.err"
  local rc=$?
  note "STAGE$n EXIT=$rc"
  if [ $rc -eq 0 ] && { [ -z "$art" ] || [ -s "$art" ]; }; then
    [ "$(cat "$STATE")" -lt "$n" ] && echo "$n" > "$STATE"
    return 0
  fi
  return 1
}

bench_stage() {  # bench_stage <n> <timeout> [extra bench.py args...]
  # Bench to a temp file; promote to BENCH_watch.json ONLY when the metric
  # is real-TPU — if the tunnel dies between our probe and bench.py's,
  # bench.py banks a CPU_FALLBACK line that must never clobber a banked
  # real-chip number. State advances only on promotion.
  local n=$1 to=$2; shift 2
  note "STAGE$n START: bench.py $*"
  rm -f /tmp/bench_try.json
  timeout "$to" python bench.py "$@" --out /tmp/bench_try.json \
    > "/tmp/tpu_stage$n.out" 2> "/tmp/tpu_stage$n.err"
  local rc=$?
  note "STAGE$n EXIT=$rc"
  [ -s /tmp/bench_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/bench_try.json; then
    note "STAGE$n got CPU_FALLBACK, not promoting"
    return 1
  fi
  # bench.py banks a best-so-far line after every improving candidate, so
  # even a timeout mid-sweep leaves a real (provisional) number. A clean
  # exit always promotes (tracks latest code); a partial only promotes if
  # it beats the banked number (never clobber a full result with a
  # truncated sweep's slower best-so-far).
  if [ $rc -ne 0 ] && [ -s BENCH_watch.json ]; then
    python - <<'PY' || { note "STAGE$n partial not better, keeping banked"; return 1; }
import json, sys
new = json.load(open("/tmp/bench_try.json"))
old = json.load(open("BENCH_watch.json"))
sys.exit(0 if new.get("value", 0) > old.get("value", 0) else 1)
PY
  fi
  cp /tmp/bench_try.json BENCH_watch.json
  note "STAGE$n PROMOTED $(cat BENCH_watch.json)"
  trend_bank bench BENCH_watch.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -lt "$n" ] && echo "$n" > "$STATE"
  return 0
}

smoke_green() {
  # banked smoke is real-TPU and all-pass
  [ -s SMOKE_TPU.json ] && grep -q '"on_tpu": true' SMOKE_TPU.json \
    && ! grep -q '"ok": false' SMOKE_TPU.json
}

longseq_stage() {
  # same promotion contract as smoke_stage: bank ANY on-chip artifact
  # (a failing kernel on the chip is evidence), never a CPU rehearsal;
  # state advances only on an all-pass run
  note "STAGE7 START: long_seq_tpu.py"
  rm -f /tmp/longseq_try.json
  timeout 1800 python benchmarks/long_seq_tpu.py --out /tmp/longseq_try.json \
    > /tmp/tpu_stage7.out 2> /tmp/tpu_stage7.err
  local rc=$?
  note "STAGE7 EXIT=$rc"
  [ -s /tmp/longseq_try.json ] || return 1
  if ! grep -q '"on_tpu": true' /tmp/longseq_try.json; then
    note "STAGE7 got CPU rehearsal, not promoting"
    return 1
  fi
  cp /tmp/longseq_try.json LONGSEQ_TPU.json
  note "STAGE7 PROMOTED (rc=$rc)"
  trend_bank longseq LONGSEQ_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -lt 7 ] && echo 7 > "$STATE"
  return 0
}

overlap_stage() {
  # same promotion contract as smoke/longseq: bank any real-TPU record —
  # including the honest single-chip "needs a slice" line — but never a
  # CPU rehearsal. The tunnel can die between our health probe and the
  # bench (pin_cpu_if_tunnel_dead would run the 8-device sim and exit 0),
  # and a CPU_FALLBACK line must neither become the permanent artifact
  # nor advance the stage.
  note "STAGE8 START: bench_overlap.py"
  rm -f /tmp/overlap_try.json
  timeout 1200 python benchmarks/bench_overlap.py \
    --out /tmp/overlap_try.json \
    > /tmp/tpu_stage8.out 2> /tmp/tpu_stage8.err
  local rc=$?
  note "STAGE8 EXIT=$rc"
  [ -s /tmp/overlap_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/overlap_try.json; then
    note "STAGE8 got CPU_FALLBACK, not promoting"
    return 1
  fi
  cp /tmp/overlap_try.json OVERLAP_TPU.json
  note "STAGE8 PROMOTED $(cat OVERLAP_TPU.json)"
  trend_bank overlap OVERLAP_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -lt 8 ] && echo 8 > "$STATE"
  return 0
}

serve_stage() {
  # stage 9: continuous-batching serve engine (tokens/s, TTFT, occupancy,
  # KV bytes). A single chip IS a real serving measurement — promote any
  # on-TPU record (the line itself says the TP-sharded path needs a
  # slice) — but a CPU_FALLBACK rehearsal must neither become the
  # permanent artifact nor advance the stage.
  note "STAGE9 START: bench_serve.py"
  rm -f /tmp/serve_try.json
  timeout 1200 python benchmarks/bench_serve.py \
    --out /tmp/serve_try.json \
    > /tmp/tpu_stage9.out 2> /tmp/tpu_stage9.err
  local rc=$?
  note "STAGE9 EXIT=$rc"
  [ -s /tmp/serve_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/serve_try.json; then
    note "STAGE9 got CPU_FALLBACK, not promoting"
    return 1
  fi
  cp /tmp/serve_try.json SERVE_TPU.json
  note "STAGE9 PROMOTED $(cat SERVE_TPU.json)"
  trend_bank serve SERVE_TPU.json
  [ $rc -eq 0 ] || return 1
  # advance only from exactly 8: jumping 7->9 would kill stage 8's
  # hourly retry gates before OVERLAP_TPU.json ever banks (the artifact
  # itself is already promoted above regardless of stage order)
  [ "$(cat "$STATE")" -eq 8 ] && echo 9 > "$STATE"
  return 0
}

slo_stage() {
  # stage 10: goodput-under-SLO serve bench (loadgen Poisson+burst ->
  # goodput req/s, TTFT/TPOT p50/p99 from histograms, violation counts).
  # Promotion adds a REGRESSION GATE: a fresh on-TPU record only replaces
  # the banked one if monitor.regress finds no >15% move in the bad
  # direction — a regressed record is logged as evidence, not banked.
  # CPU rehearsals never promote, matching stage 9.
  note "STAGE10 START: bench_serve.py --loadgen"
  rm -f /tmp/serve_slo_try.json
  timeout 1200 python benchmarks/bench_serve.py --loadgen \
    --out /tmp/serve_slo_try.json \
    > /tmp/tpu_stage10.out 2> /tmp/tpu_stage10.err
  local rc=$?
  note "STAGE10 EXIT=$rc"
  [ -s /tmp/serve_slo_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/serve_slo_try.json; then
    note "STAGE10 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if [ -s SERVE_SLO_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress SERVE_SLO_TPU.json \
        /tmp/serve_slo_try.json --tol 0.15 \
        > /tmp/tpu_stage10_regress.out 2>> /tmp/tpu_stage10.err; then
      note "STAGE10 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage10_regress.out)"
      return 1
    fi
  fi
  cp /tmp/serve_slo_try.json SERVE_SLO_TPU.json
  note "STAGE10 PROMOTED $(cat SERVE_SLO_TPU.json)"
  trend_bank serve_slo SERVE_SLO_TPU.json
  [ $rc -eq 0 ] || return 1
  # advance only from exactly 9 (same reasoning as stage 9's 8-gate)
  [ "$(cat "$STATE")" -eq 9 ] && echo 10 > "$STATE"
  return 0
}

prefix_stage() {
  # stage 11: shared-prefix + speculative serve bench — the loadgen
  # workload the prefix cache and drafter exist for (pool of shared
  # system prompts, spec-k 4). Record carries prefix-hit and acceptance
  # rates; promotion is REGRESSION-GATED via monitor.regress exactly
  # like stage 10 (tol 15%, bad-direction moves keep the banked record).
  # CPU rehearsals never promote.
  note "STAGE11 START: bench_serve.py --loadgen --prefix-pool 2 --spec-k 4"
  rm -f /tmp/serve_prefix_try.json
  timeout 1200 python benchmarks/bench_serve.py --loadgen \
    --prefix-pool 2 --prefix-len 64 --prefix-ratio 0.75 --spec-k 4 \
    --out /tmp/serve_prefix_try.json \
    > /tmp/tpu_stage11.out 2> /tmp/tpu_stage11.err
  local rc=$?
  note "STAGE11 EXIT=$rc"
  [ -s /tmp/serve_prefix_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/serve_prefix_try.json; then
    note "STAGE11 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if [ -s SERVE_PREFIX_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress SERVE_PREFIX_TPU.json \
        /tmp/serve_prefix_try.json --tol 0.15 \
        > /tmp/tpu_stage11_regress.out 2>> /tmp/tpu_stage11.err; then
      note "STAGE11 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage11_regress.out)"
      return 1
    fi
  fi
  cp /tmp/serve_prefix_try.json SERVE_PREFIX_TPU.json
  note "STAGE11 PROMOTED $(cat SERVE_PREFIX_TPU.json)"
  trend_bank serve_prefix SERVE_PREFIX_TPU.json
  [ $rc -eq 0 ] || return 1
  # advance only from exactly 10 (same reasoning as stage 9's 8-gate)
  [ "$(cat "$STATE")" -eq 10 ] && echo 11 > "$STATE"
  return 0
}

mega_stage() {
  # stage 12: megakernel decode A/B — the stage-9 serve workload run
  # fused-on AND fused-off in one record (decode_step_ms p50/p99 both
  # sides, speedup, stream-equality assertion, spec-k 4 so the verify
  # interplay is in the measurement). The fused-on decode-step p50 vs
  # fused-off is THE megakernel headline (ROADMAP item 4). Promotion is
  # REGRESSION-GATED via monitor.regress exactly like stages 10/11; CPU
  # rehearsals (interpret-mode Pallas) never promote.
  note "STAGE12 START: bench_serve.py --megakernel-ab --spec-k 4"
  rm -f /tmp/decode_fused_try.json
  timeout 1800 python benchmarks/bench_serve.py --megakernel-ab \
    --spec-k 4 --out /tmp/decode_fused_try.json \
    > /tmp/tpu_stage12.out 2> /tmp/tpu_stage12.err
  local rc=$?
  note "STAGE12 EXIT=$rc"
  [ -s /tmp/decode_fused_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/decode_fused_try.json; then
    note "STAGE12 got CPU_FALLBACK, not promoting"
    return 1
  fi
  # a diverged or failed A/B is a correctness failure, never a baseline
  # (monitor.regress only compares numeric fields, so gate it here)
  if grep -Eq '"(streams_equal|ok)": false' /tmp/decode_fused_try.json; then
    note "STAGE12 record has ok/streams_equal false, not promoting"
    return 1
  fi
  if [ -s DECODE_FUSED_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress DECODE_FUSED_TPU.json \
        /tmp/decode_fused_try.json --tol 0.15 \
        > /tmp/tpu_stage12_regress.out 2>> /tmp/tpu_stage12.err; then
      note "STAGE12 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage12_regress.out)"
      return 1
    fi
  fi
  cp /tmp/decode_fused_try.json DECODE_FUSED_TPU.json
  note "STAGE12 PROMOTED $(cat DECODE_FUSED_TPU.json)"
  trend_bank decode_fused DECODE_FUSED_TPU.json
  [ $rc -eq 0 ] || return 1
  # advance only from exactly 11 (same reasoning as stage 9's 8-gate)
  [ "$(cat "$STATE")" -eq 11 ] && echo 12 > "$STATE"
  return 0
}

fusedupd_stage() {
  # stage 13: fused optimizer update tail A/B (ops/fused_update.py) —
  # ref_ms vs fused_ms over GPT-2-124M ZeRO dp=8 shards. Same promote
  # rules: CPU rehearsals (interpret mode, honest _CPU_FALLBACK suffix)
  # never promote; regression-gated once banked.
  note "STAGE13 START: bench_fused_update.py"
  rm -f /tmp/fused_update_try.json
  timeout 1200 python benchmarks/bench_fused_update.py \
    --out /tmp/fused_update_try.json \
    > /tmp/tpu_stage13.out 2> /tmp/tpu_stage13.err
  local rc=$?
  note "STAGE13 EXIT=$rc"
  [ -s /tmp/fused_update_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/fused_update_try.json; then
    note "STAGE13 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if [ -s FUSED_UPDATE_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress FUSED_UPDATE_TPU.json \
        /tmp/fused_update_try.json --tol 0.15 \
        > /tmp/tpu_stage13_regress.out 2>> /tmp/tpu_stage13.err; then
      note "STAGE13 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage13_regress.out)"
      return 1
    fi
  fi
  cp /tmp/fused_update_try.json FUSED_UPDATE_TPU.json
  note "STAGE13 PROMOTED $(cat FUSED_UPDATE_TPU.json)"
  trend_bank fused_update FUSED_UPDATE_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 12 ] && echo 13 > "$STATE"
  return 0
}

fsdp_stage() {
  # stage 14: FSDP (ZeRO-3) vs DDP+ZeRO-1 A/B (benchmarks/bench_fsdp.py)
  # — step ms both sides, peak HBM (device_memory_stats on chip, modeled
  # hbm_params_bytes otherwise), wire bytes, and the gather ring's
  # HLO-proven hidden_fraction. Same promote rules as stages 10-13: CPU
  # rehearsals (_CPU_FALLBACK) never promote; REGRESSION-GATED via
  # monitor.regress --tol 0.15 once banked; hourly even after banked so
  # a step-time / HBM / hidden-fraction regression surfaces within an
  # hour.
  note "STAGE14 START: bench_fsdp.py"
  rm -f /tmp/fsdp_try.json
  timeout 1800 python benchmarks/bench_fsdp.py \
    --out /tmp/fsdp_try.json \
    > /tmp/tpu_stage14.out 2> /tmp/tpu_stage14.err
  local rc=$?
  note "STAGE14 EXIT=$rc"
  [ -s /tmp/fsdp_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/fsdp_try.json; then
    note "STAGE14 got CPU_FALLBACK, not promoting"
    return 1
  fi
  # an under-overlapped ring (ok=false: hidden_fraction < 0.5) is a
  # correctness-of-claim failure, never a baseline
  if grep -Eq '"ok": false' /tmp/fsdp_try.json; then
    note "STAGE14 record has ok false, not promoting"
    return 1
  fi
  if [ -s FSDP_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress FSDP_TPU.json \
        /tmp/fsdp_try.json --tol 0.15 \
        > /tmp/tpu_stage14_regress.out 2>> /tmp/tpu_stage14.err; then
      note "STAGE14 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage14_regress.out)"
      return 1
    fi
  fi
  cp /tmp/fsdp_try.json FSDP_TPU.json
  note "STAGE14 PROMOTED $(cat FSDP_TPU.json)"
  trend_bank fsdp FSDP_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 13 ] && echo 14 > "$STATE"
  return 0
}

mh_stage() {
  # stage 15: disaggregated prefill/decode cluster bench
  # (benchmarks/bench_serve_mh.py --hosts 2) — goodput-under-SLO, shed
  # rate, transfer wire bytes/ms and the disaggregated-vs-colocated A/B
  # at >= 2 simulated hosts. Same promote rules as stages 10-14: CPU
  # rehearsals (_CPU_FALLBACK) never promote; REGRESSION-GATED via
  # monitor.regress --tol 0.15 once banked (shed_rate/transfer_ms lower-
  # is-better, admitted_rps/goodput higher); hourly even after banked so
  # a routing/transfer regression surfaces within an hour.
  note "STAGE15 START: bench_serve_mh.py --hosts 2"
  rm -f /tmp/serve_mh_try.json
  timeout 1800 python benchmarks/bench_serve_mh.py --hosts 2 \
    --out /tmp/serve_mh_try.json \
    > /tmp/tpu_stage15.out 2> /tmp/tpu_stage15.err
  local rc=$?
  note "STAGE15 EXIT=$rc"
  [ -s /tmp/serve_mh_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/serve_mh_try.json; then
    note "STAGE15 got CPU_FALLBACK, not promoting"
    return 1
  fi
  # a record whose measured transfer bytes disagree with the wire model
  # (ok=false) is a correctness-of-claim failure, never a baseline
  if grep -Eq '"ok": false' /tmp/serve_mh_try.json; then
    note "STAGE15 record has ok false, not promoting"
    return 1
  fi
  if [ -s SERVE_MH_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress SERVE_MH_TPU.json \
        /tmp/serve_mh_try.json --tol 0.15 \
        > /tmp/tpu_stage15_regress.out 2>> /tmp/tpu_stage15.err; then
      note "STAGE15 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage15_regress.out)"
      return 1
    fi
  fi
  cp /tmp/serve_mh_try.json SERVE_MH_TPU.json
  note "STAGE15 PROMOTED $(cat SERVE_MH_TPU.json)"
  trend_bank serve_mh SERVE_MH_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 14 ] && echo 15 > "$STATE"
  return 0
}

analyze_stage() {
  # stage 16: compiled-program contract check (benchmarks/
  # analyze_contracts.py) — donation aliases + recompile budgets on the
  # flagship GPT/serve steps, the bf16 decode dtype profile (fp32_dots /
  # convert_churn_ops), host-sync count, the gather-ring exposed-
  # collective split over the banked bench HLO shapes, and the repo lint
  # gate, all in ONE json_record. Same promote rules as stages 10-15:
  # CPU rehearsals (_CPU_FALLBACK) never promote; a failed contract
  # (ok=false) is evidence, never a baseline; REGRESSION-GATED via
  # monitor.regress --tol 0.15 once banked (exposed_bytes / fp32_dots /
  # convert_churn_ops / host_syncs / lint_violations are lower-is-better
  # in the regress polarity tables); hourly even after banked so a new
  # silently-copied donation or exposed ring surfaces within an hour.
  note "STAGE16 START: analyze_contracts.py"
  rm -f /tmp/analyze_try.json
  timeout 1200 python benchmarks/analyze_contracts.py \
    --out /tmp/analyze_try.json \
    > /tmp/tpu_stage16.out 2> /tmp/tpu_stage16.err
  local rc=$?
  note "STAGE16 EXIT=$rc"
  [ -s /tmp/analyze_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/analyze_try.json; then
    note "STAGE16 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"ok": false' /tmp/analyze_try.json; then
    note "STAGE16 record has ok false, not promoting"
    return 1
  fi
  if [ -s ANALYZE_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress ANALYZE_TPU.json \
        /tmp/analyze_try.json --tol 0.15 \
        > /tmp/tpu_stage16_regress.out 2>> /tmp/tpu_stage16.err; then
      note "STAGE16 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage16_regress.out)"
      return 1
    fi
  fi
  cp /tmp/analyze_try.json ANALYZE_TPU.json
  note "STAGE16 PROMOTED $(cat ANALYZE_TPU.json)"
  trend_bank analyze ANALYZE_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 15 ] && echo 16 > "$STATE"
  return 0
}

sub8_stage() {
  # stage 17: the sub-8-bit tier — bench_serve_mh.py at --kv-quant int4
  # (the int4 KV pools + the int8-vs-int4 concurrency A/B sub-record:
  # kv_bits / contexts_max / wire_bytes_int4 / hbm_cut, with the measured
  # transfer bytes asserted against the packed-payload model into ok=)
  # plus the none-vs-int8-vs-int4 comm wire A/B from bench_comm.py
  # appended to the same artifact. Same promote rules as stages 10-16:
  # CPU rehearsals never promote, ok=false never promotes, REGRESSION-
  # GATED via monitor.regress --tol 0.15 once banked (kv_bits /
  # wire_bytes_int4 / fp8_overflow_rate lower-is-better, contexts_max
  # higher — the new polarity rows); hourly even after banked.
  note "STAGE17 START: bench_serve_mh.py --kv-quant int4 + bench_comm.py"
  rm -f /tmp/sub8_try.json
  timeout 1800 python benchmarks/bench_serve_mh.py --hosts 2 \
    --kv-quant int4 --out /tmp/sub8_try.json \
    > /tmp/tpu_stage17.out 2> /tmp/tpu_stage17.err
  local rc=$?
  note "STAGE17 EXIT=$rc"
  [ -s /tmp/sub8_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/sub8_try.json; then
    note "STAGE17 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"ok": false' /tmp/sub8_try.json; then
    note "STAGE17 record has ok false, not promoting"
    return 1
  fi
  # the none-vs-int8-vs-int4 comm wire A/B banks as its OWN artifact
  # (one json_record per file — monitor.regress reads last-line records,
  # so the two gates stay independent); its regression never blocks the
  # serve record, and vice versa
  if timeout 1200 python benchmarks/bench_comm.py \
      > /tmp/tpu_stage17_comm.out 2>> /tmp/tpu_stage17.err; then
    tail -n 1 /tmp/tpu_stage17_comm.out > /tmp/sub8_comm_try.json
    if [ -s COMM_SUB8_TPU.json ] && ! python -m apex_tpu.monitor.regress \
        COMM_SUB8_TPU.json /tmp/sub8_comm_try.json --tol 0.15 \
        >> /tmp/tpu_stage17_regress.out 2>> /tmp/tpu_stage17.err; then
      note "STAGE17 comm A/B regressed, keeping banked COMM_SUB8_TPU"
    else
      cp /tmp/sub8_comm_try.json COMM_SUB8_TPU.json
      note "STAGE17 banked COMM_SUB8_TPU $(cat COMM_SUB8_TPU.json)"
      trend_bank comm_sub8 COMM_SUB8_TPU.json
    fi
  fi
  if [ -s SERVE_KV4_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress SERVE_KV4_TPU.json \
        /tmp/sub8_try.json --tol 0.15 \
        > /tmp/tpu_stage17_regress.out 2>> /tmp/tpu_stage17.err; then
      note "STAGE17 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage17_regress.out)"
      return 1
    fi
  fi
  cp /tmp/sub8_try.json SERVE_KV4_TPU.json
  note "STAGE17 PROMOTED $(cat SERVE_KV4_TPU.json)"
  trend_bank serve_kv4 SERVE_KV4_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 16 ] && echo 17 > "$STATE"
  return 0
}

chaos_stage() {
  # stage 18: elastic fault-tolerant serving — bench_serve_mh.py --chaos
  # kills 1 of 2 decode hosts at 2x overload mid-run; the survivors
  # absorb the migrated live requests over the KV wire and the record
  # carries goodput_under_chaos_rps / survivor_good_fraction (higher-
  # better) plus the recovery-noise counters (migrations_total /
  # replayed_tokens / worker_deaths / heartbeat_misses /
  # transfer_retries, lower-better — the new regress polarity rows).
  # Same promote rules as stages 10-17: CPU rehearsals never promote,
  # ok=false (kill did not land / cluster failed to drain) never
  # promotes, REGRESSION-GATED via monitor.regress --tol 0.15 once
  # banked; hourly even after banked.
  note "STAGE18 START: bench_serve_mh.py --hosts 3 --chaos"
  rm -f /tmp/serve_chaos_try.json
  timeout 1800 python benchmarks/bench_serve_mh.py --hosts 3 --chaos \
    --out /tmp/serve_chaos_try.json \
    > /tmp/tpu_stage18.out 2> /tmp/tpu_stage18.err
  local rc=$?
  note "STAGE18 EXIT=$rc"
  [ -s /tmp/serve_chaos_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/serve_chaos_try.json; then
    note "STAGE18 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"ok": false' /tmp/serve_chaos_try.json; then
    note "STAGE18 record has ok false, not promoting"
    return 1
  fi
  if [ -s SERVE_CHAOS_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress SERVE_CHAOS_TPU.json \
        /tmp/serve_chaos_try.json --tol 0.15 \
        > /tmp/tpu_stage18_regress.out 2>> /tmp/tpu_stage18.err; then
      note "STAGE18 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage18_regress.out)"
      return 1
    fi
  fi
  cp /tmp/serve_chaos_try.json SERVE_CHAOS_TPU.json
  note "STAGE18 PROMOTED $(cat SERVE_CHAOS_TPU.json)"
  trend_bank serve_chaos SERVE_CHAOS_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 17 ] && echo 18 > "$STATE"
  return 0
}

observe_stage() {
  # stage 19: fleet observability overhead A/B — bench_observe.py runs
  # the loadgen workload through a disaggregated cluster twice (full
  # tracing + flight rings + FleetScraper + alert rules vs all off) and
  # records tokens/s both sides, observe_overhead_pct (ok=false past
  # the 5% budget), scrape_ms p50/p99, events/s, alerts_fired_total and
  # trace_stitch_failures (must be 0). Same promote rules as stages
  # 10-18: CPU rehearsals never promote (CPU decode steps flatter the
  # overhead ~10x), ok=false (overhead blown / stitching broken /
  # streams perturbed) never promotes, REGRESSION-GATED via
  # monitor.regress --tol 0.15 once banked (alerts_fired_total /
  # scrape_ms / trace_stitch_failures lower-is-better, scrape_coverage
  # / fleet_goodput_rps higher — the new polarity rows); hourly even
  # after banked so a creeping observability tax surfaces within an
  # hour.
  note "STAGE19 START: bench_observe.py"
  rm -f /tmp/observe_try.json
  timeout 1800 python benchmarks/bench_observe.py \
    --out /tmp/observe_try.json \
    > /tmp/tpu_stage19.out 2> /tmp/tpu_stage19.err
  local rc=$?
  note "STAGE19 EXIT=$rc"
  [ -s /tmp/observe_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/observe_try.json; then
    note "STAGE19 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"ok": false' /tmp/observe_try.json; then
    note "STAGE19 record has ok false, not promoting"
    return 1
  fi
  if [ -s OBSERVE_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress OBSERVE_TPU.json \
        /tmp/observe_try.json --tol 0.15 \
        > /tmp/tpu_stage19_regress.out 2>> /tmp/tpu_stage19.err; then
      note "STAGE19 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage19_regress.out)"
      return 1
    fi
  fi
  cp /tmp/observe_try.json OBSERVE_TPU.json
  note "STAGE19 PROMOTED $(cat OBSERVE_TPU.json)"
  trend_bank observe OBSERVE_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 18 ] && echo 19 > "$STATE"
  return 0
}

lora_stage() {
  # stage 20: per-tenant LoRA serve A/B — bench_serve_mh.py --lora runs
  # the same tenant mix adapter-free and adapter-bound (loadgen's fixed
  # t{i} -> ad{i % M} mapping) and records tokens/s + TTFT p99 both
  # sides, adapter_hit_rate and adapter_warm_dispatch_rate
  # (higher-better), adapter_load_ms / adapter_evictions (lower-better)
  # and streams_equal: the aid=0 cohort through both fleets must match
  # BITWISE (ok=false otherwise). Same promote rules as stages 10-19:
  # CPU rehearsals never promote, ok=false never promotes,
  # REGRESSION-GATED via monitor.regress --tol 0.15 once banked; hourly
  # even after banked so a fleet-mix placement regression surfaces
  # within an hour.
  note "STAGE20 START: bench_serve_mh.py --lora"
  rm -f /tmp/serve_lora_try.json
  timeout 1800 python benchmarks/bench_serve_mh.py --lora \
    --out /tmp/serve_lora_try.json \
    > /tmp/tpu_stage20.out 2> /tmp/tpu_stage20.err
  local rc=$?
  note "STAGE20 EXIT=$rc"
  [ -s /tmp/serve_lora_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/serve_lora_try.json; then
    note "STAGE20 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"ok": false' /tmp/serve_lora_try.json; then
    note "STAGE20 record has ok false, not promoting"
    return 1
  fi
  if [ -s SERVE_LORA_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress SERVE_LORA_TPU.json \
        /tmp/serve_lora_try.json --tol 0.15 \
        > /tmp/tpu_stage20_regress.out 2>> /tmp/tpu_stage20.err; then
      note "STAGE20 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage20_regress.out)"
      return 1
    fi
  fi
  cp /tmp/serve_lora_try.json SERVE_LORA_TPU.json
  note "STAGE20 PROMOTED $(cat SERVE_LORA_TPU.json)"
  trend_bank serve_lora SERVE_LORA_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 19 ] && echo 20 > "$STATE"
  return 0
}

attrib_stage() {
  # stage 21: forensics overhead A/B — bench_attrib_cost.py runs the
  # multi-tenant loadgen workload through a disaggregated cluster twice
  # (per-request attribution + per-tenant metering on vs off) and
  # records tokens/s both sides, forensics_overhead_pct (ok=false past
  # the 5% budget), attrib_coverage / meter_coverage (must be 1.0),
  # the queue/prefill/transfer/decode/stall component quantiles,
  # cost_per_token and the rollup-vs-totals identity. Same promote
  # rules as stages 10-20: CPU rehearsals never promote (CPU decode
  # steps flatter the overhead ~10x), ok=false (overhead blown /
  # coverage hole / rollup mismatch / streams perturbed) never
  # promotes, REGRESSION-GATED via monitor.regress --tol 0.15 once
  # banked (component ms / cost_per_token lower-is-better,
  # attrib_coverage / meter_coverage higher — the new polarity rows);
  # hourly even after banked so a creeping cost-per-token or a new
  # stall component surfaces within an hour.
  note "STAGE21 START: bench_attrib_cost.py"
  rm -f /tmp/attrib_cost_try.json
  timeout 1800 python benchmarks/bench_attrib_cost.py \
    --out /tmp/attrib_cost_try.json \
    > /tmp/tpu_stage21.out 2> /tmp/tpu_stage21.err
  local rc=$?
  note "STAGE21 EXIT=$rc"
  [ -s /tmp/attrib_cost_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/attrib_cost_try.json; then
    note "STAGE21 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"ok": false' /tmp/attrib_cost_try.json; then
    note "STAGE21 record has ok false, not promoting"
    return 1
  fi
  if [ -s ATTRIB_COST_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress ATTRIB_COST_TPU.json \
        /tmp/attrib_cost_try.json --tol 0.15 \
        > /tmp/tpu_stage21_regress.out 2>> /tmp/tpu_stage21.err; then
      note "STAGE21 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage21_regress.out)"
      return 1
    fi
  fi
  cp /tmp/attrib_cost_try.json ATTRIB_COST_TPU.json
  note "STAGE21 PROMOTED $(cat ATTRIB_COST_TPU.json)"
  trend_bank attrib_cost ATTRIB_COST_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 20 ] && echo 21 > "$STATE"
  return 0
}

elastic_stage() {
  # stage 22: elastic fault-tolerant training — bench_elastic.py runs
  # the full topology-elastic story (dp=4 checkpoint with the elastic
  # manifest restored onto a dp=2 layout, a chaos KillRankAtStep mid-run
  # and a supervisor resume at the new degree) and records reshard_ms /
  # reshard_ms_per_gb, kill_resume_wall_ms, loss_rejoin_delta (ok=false
  # past --rejoin-tol: a resume that drifted is corruption, not cost)
  # and sentinel_overhead_pct (ok=false past the 5% always-on budget or
  # on any straggler/SDC false positive on the clean run). Same promote
  # rules as stages 10-21: CPU rehearsals never promote (host-loop
  # timing flatters nothing on a TPU), ok=false never promotes,
  # REGRESSION-GATED via monitor.regress --tol 0.15 once banked
  # (reshard_ms / sentinel counters lower-is-better,
  # elastic_resumes_total informational); hourly even after banked so a
  # reshard slowdown or a sentinel noise storm surfaces within an hour.
  note "STAGE22 START: bench_elastic.py"
  rm -f /tmp/elastic_try.json
  timeout 1800 python benchmarks/bench_elastic.py \
    --out /tmp/elastic_try.json \
    > /tmp/tpu_stage22.out 2> /tmp/tpu_stage22.err
  local rc=$?
  note "STAGE22 EXIT=$rc"
  [ -s /tmp/elastic_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/elastic_try.json; then
    note "STAGE22 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"ok": false' /tmp/elastic_try.json; then
    note "STAGE22 record has ok false, not promoting"
    return 1
  fi
  if [ -s ELASTIC_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress ELASTIC_TPU.json \
        /tmp/elastic_try.json --tol 0.15 \
        > /tmp/tpu_stage22_regress.out 2>> /tmp/tpu_stage22.err; then
      note "STAGE22 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage22_regress.out)"
      return 1
    fi
  fi
  cp /tmp/elastic_try.json ELASTIC_TPU.json
  note "STAGE22 PROMOTED $(cat ELASTIC_TPU.json)"
  trend_bank elastic ELASTIC_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 21 ] && echo 22 > "$STATE"
  return 0
}

megat2_stage() {
  # stage 23: megakernel tier 2 — the stage-12 decode A/B rerun at the
  # GPT-2-124M flagship shape (768 hidden, 12 layers, 50304 vocab) that
  # tier 1's 10 MB full-residency gate refused. The record only counts
  # if BOTH jit sites actually took the fused path: the weight-streaming
  # decode block ("decode_kernel": "fused") AND the q_len=k+1 fused
  # verify step ("verify_kernel": "fused") — a silent auto-fallback to
  # the per-op body would otherwise bank an unfused number under the
  # tier-2 headline. Same promote rules as stages 10-22: CPU rehearsals
  # (honest _CPU_FALLBACK metric suffix) never promote, a diverged or
  # failed A/B (streams_equal/ok false) never promotes, REGRESSION-GATED
  # via monitor.regress --tol 0.15 once banked (verify_step_ms /
  # decode_step_ms lower-is-better, spec_acceptance_rate higher — the
  # stage-23 polarity entries); hourly even after banked so a fused
  # verify regression surfaces within an hour.
  note "STAGE23 START: bench_serve.py --megakernel-ab --spec-k 4 --model flagship"
  rm -f /tmp/decode_fused_t2_try.json
  timeout 1800 python benchmarks/bench_serve.py --megakernel-ab \
    --spec-k 4 --model flagship --out /tmp/decode_fused_t2_try.json \
    > /tmp/tpu_stage23.out 2> /tmp/tpu_stage23.err
  local rc=$?
  note "STAGE23 EXIT=$rc"
  [ -s /tmp/decode_fused_t2_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/decode_fused_t2_try.json; then
    note "STAGE23 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"(streams_equal|ok)": false' /tmp/decode_fused_t2_try.json; then
    note "STAGE23 record has ok/streams_equal false, not promoting"
    return 1
  fi
  # tier-2 specific: the flagship record must prove the VMEM gate really
  # lifted — both the decode and the verify jit site on the fused path
  if ! grep -q '"decode_kernel": "fused"' /tmp/decode_fused_t2_try.json \
      || ! grep -q '"verify_kernel": "fused"' /tmp/decode_fused_t2_try.json; then
    note "STAGE23 fused_on side not actually fused (gate refused or fell back), not promoting"
    return 1
  fi
  if [ -s DECODE_FUSED_T2_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress DECODE_FUSED_T2_TPU.json \
        /tmp/decode_fused_t2_try.json --tol 0.15 \
        > /tmp/tpu_stage23_regress.out 2>> /tmp/tpu_stage23.err; then
      note "STAGE23 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage23_regress.out)"
      return 1
    fi
  fi
  cp /tmp/decode_fused_t2_try.json DECODE_FUSED_T2_TPU.json
  note "STAGE23 PROMOTED $(cat DECODE_FUSED_T2_TPU.json)"
  trend_bank decode_fused_t2 DECODE_FUSED_T2_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 22 ] && echo 23 > "$STATE"
  return 0
}

serveplan_stage() {
  # stage 24: plan-sharded serving (apex_tpu.serve.sharded, ISSUE-20) —
  # one ParallelismPlan-driven engine per residency strategy (tp / pp /
  # fsdp) on the slice, goodput under the stage-10 SLO with the
  # >1-chip-HBM headline: hbm_model_bytes exceeding the simulated
  # per-chip budget while every strategy's resident bytes fit it. The
  # record only counts if every driven strategy drained, matched the
  # monolithic oracle's streams AND beat the budget (ok folds all of
  # that); same promote rules as stages 10-23: CPU rehearsals (honest
  # _CPU_FALLBACK suffix) never promote, ok:false never promotes,
  # REGRESSION-GATED via monitor.regress --tol 0.15 once banked
  # (weight_gather_ms / pp_bubble_fraction / hbm_model_bytes /
  # hbm_chip_bytes lower-is-better, goodput_rps higher — the stage-24
  # polarity entries); hourly even after banked so a residency or
  # gather regression surfaces within an hour.
  note "STAGE24 START: bench_serve_mh.py --plan all"
  rm -f /tmp/serve_plan_try.json
  timeout 1800 python benchmarks/bench_serve_mh.py --plan all \
    --out /tmp/serve_plan_try.json \
    > /tmp/tpu_stage24.out 2> /tmp/tpu_stage24.err
  local rc=$?
  note "STAGE24 EXIT=$rc"
  [ -s /tmp/serve_plan_try.json ] || return 1
  if grep -q CPU_FALLBACK /tmp/serve_plan_try.json; then
    note "STAGE24 got CPU_FALLBACK, not promoting"
    return 1
  fi
  if grep -Eq '"(streams_equal|ok)": false' /tmp/serve_plan_try.json; then
    note "STAGE24 record has ok/streams_equal false, not promoting"
    return 1
  fi
  if [ -s SERVE_PLAN_TPU.json ]; then
    if ! python -m apex_tpu.monitor.regress SERVE_PLAN_TPU.json \
        /tmp/serve_plan_try.json --tol 0.15 \
        > /tmp/tpu_stage24_regress.out 2>> /tmp/tpu_stage24.err; then
      note "STAGE24 REGRESSION vs banked, keeping banked record: \
$(cat /tmp/tpu_stage24_regress.out)"
      return 1
    fi
  fi
  cp /tmp/serve_plan_try.json SERVE_PLAN_TPU.json
  note "STAGE24 PROMOTED $(cat SERVE_PLAN_TPU.json)"
  trend_bank serve_plan SERVE_PLAN_TPU.json
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -eq 23 ] && echo 24 > "$STATE"
  return 0
}

smoke_stage() {
  # Smoke to a temp file; promote ANY real-TPU artifact (a failing kernel
  # on the chip is exactly the evidence we must bank) but never a CPU
  # rehearsal (whose fallback rows intentionally fail) — if the tunnel
  # dies between our probe and the smoke, SMOKE_TPU.json keeps the last
  # on-chip run. State advances only on an all-pass TPU run.
  note "STAGE2 START: smoke_tpu.py"
  rm -f /tmp/smoke_try.json
  timeout 900 python benchmarks/smoke_tpu.py --out /tmp/smoke_try.json \
    > /tmp/tpu_stage2.out 2> /tmp/tpu_stage2.err
  local rc=$?
  note "STAGE2 EXIT=$rc"
  [ -s /tmp/smoke_try.json ] || return 1
  if ! grep -q '"on_tpu": true' /tmp/smoke_try.json; then
    note "STAGE2 got CPU rehearsal, not promoting"
    return 1
  fi
  cp /tmp/smoke_try.json SMOKE_TPU.json
  note "STAGE2 PROMOTED (rc=$rc)"
  [ $rc -eq 0 ] || return 1
  [ "$(cat "$STATE")" -lt 2 ] && echo 2 > "$STATE"
  return 0
}

while true; do
  if timeout 240 python -c "import jax, jax.numpy as jnp; assert jax.default_backend()=='tpu'; x=jnp.ones((128,128),jnp.bfloat16); assert float((x@x).sum())>0" > /tmp/tpu_watch_probe.log 2>&1; then
    note HEALTHY
    done_stage=$(cat "$STATE")
    now=$(date +%s)
    if [ "$done_stage" -ge 7 ]; then
      # full suite already banked: refresh the headline at most hourly.
      # A non-green smoke retries on the same hourly cadence (kernel
      # fixes land while the tunnel is down, so a failed on-chip smoke
      # must not be the permanent record — but a genuinely failing
      # kernel must not burn every 120 s iteration re-proving it)
      if [ $((now - last_refresh)) -ge 3600 ]; then
        smoke_green || smoke_stage
        bench_stage 1 600 --quick
        bench_stage 3 2400
        # stage 8 (overlap A/B, additive): retries on the same hourly
        # cadence until banked. Deliberately NOT part of the -ge gate
        # above — a box where bench_overlap cannot run (stock jax exits
        # 2) must keep its hourly refresh mode, not fall back into the
        # catch-up branch's 120 s smoke loop
        if [ "$(cat "$STATE")" -lt 8 ] \
            && [ $((now - last_overlap)) -ge 3600 ]; then
          overlap_stage
          last_overlap=$now
        fi
        # stage 9 (serve engine, additive): same hourly-until-banked
        # contract as stage 8, same reason for sitting outside the gate
        if [ "$(cat "$STATE")" -lt 9 ] \
            && [ $((now - last_serve)) -ge 3600 ]; then
          serve_stage
          last_serve=$now
        fi
        # stage 10 (serve goodput-SLO, additive): hourly even AFTER
        # banking — the regression gate is the point: every healthy
        # window re-measures goodput-under-SLO against the banked record
        # so a serving-latency regression surfaces within an hour
        if [ $((now - last_slo)) -ge 3600 ]; then
          slo_stage
          last_slo=$now
        fi
        # stage 11 (shared-prefix + speculative loadgen): same hourly
        # re-measure-after-banked contract as stage 10 — a prefix-cache
        # or acceptance-rate regression must surface within an hour
        if [ $((now - last_prefix)) -ge 3600 ]; then
          prefix_stage
          last_prefix=$now
        fi
        # stage 12 (megakernel decode A/B): same hourly re-measure-after-
        # banked contract — a fused decode-step regression must surface
        # within an hour
        if [ $((now - last_mega)) -ge 3600 ]; then
          mega_stage
          last_mega=$now
        fi
        # stage 13 (fused optimizer update tail): same contract
        if [ $((now - last_fusedupd)) -ge 3600 ]; then
          fusedupd_stage
          last_fusedupd=$now
        fi
        # stage 14 (FSDP vs ZeRO-1 A/B): same hourly re-measure-after-
        # banked contract — an HBM/step-time/hidden-fraction regression
        # must surface within an hour
        if [ $((now - last_fsdp)) -ge 3600 ]; then
          fsdp_stage
          last_fsdp=$now
        fi
        # stage 15 (disaggregated serve cluster): same hourly re-measure-
        # after-banked contract — a goodput/shed/transfer regression must
        # surface within an hour
        if [ $((now - last_mh)) -ge 3600 ]; then
          mh_stage
          last_mh=$now
        fi
        # stage 16 (compiled-program contract check): same contract — a
        # lost donation alias, a new exposed ring, or a fresh lint
        # violation must surface within an hour
        if [ $((now - last_analyze)) -ge 3600 ]; then
          analyze_stage
          last_analyze=$now
        fi
        # stage 17 (sub-8-bit tier: int4 KV serve + comm wire A/B):
        # same contract — a lost HBM cut or wire-byte regression must
        # surface within an hour
        if [ $((now - last_sub8)) -ge 3600 ]; then
          sub8_stage
          last_sub8=$now
        fi
        # stage 18 (elastic serve chaos: kill-and-migrate at overload):
        # same contract — a goodput-under-chaos collapse or a recovery-
        # noise storm must surface within an hour
        if [ $((now - last_chaos)) -ge 3600 ]; then
          chaos_stage
          last_chaos=$now
        fi
        # stage 19 (fleet observability overhead A/B): same contract —
        # an observability tax past 5% or broken trace stitching must
        # surface within an hour
        if [ $((now - last_observe)) -ge 3600 ]; then
          observe_stage
          last_observe=$now
        fi
        # stage 20 (per-tenant LoRA serve A/B): same contract — a
        # broken aid=0 transparency, a collapsing adapter hit rate or
        # a cold-dispatching router must surface within an hour
        if [ $((now - last_lora)) -ge 3600 ]; then
          lora_stage
          last_lora=$now
        fi
        # stage 21 (attribution + cost forensics A/B): same contract —
        # a forensics tax past 5%, an attribution coverage hole or a
        # rollup-vs-totals mismatch must surface within an hour
        if [ $((now - last_attrib)) -ge 3600 ]; then
          attrib_stage
          last_attrib=$now
        fi
        # stage 22 (elastic train: reshard + kill-resume + sentinels):
        # same contract — a reshard slowdown, a resume that drifts or a
        # sentinel noise storm must surface within an hour
        if [ $((now - last_elastic)) -ge 3600 ]; then
          elastic_stage
          last_elastic=$now
        fi
        # stage 23 (megakernel tier-2 flagship A/B): same contract — a
        # fused verify/decode regression at the 124M shape, or a gate
        # that quietly stopped lifting, must surface within an hour
        if [ $((now - last_megat2)) -ge 3600 ]; then
          megat2_stage
          last_megat2=$now
        fi
        # stage 24 (plan-sharded serve residency): same contract — a
        # strategy that stopped fitting the chip budget, a gather/bubble
        # regression or a stream divergence must surface within an hour
        if [ $((now - last_serveplan)) -ge 3600 ]; then
          serveplan_stage
          last_serveplan=$now
        fi
        last_refresh=$now
      fi
    else
      [ "$done_stage" -lt 1 ] && bench_stage 1 600 --quick
      [ "$(cat "$STATE")" -ge 1 ] && ! smoke_green && smoke_stage
      [ "$(cat "$STATE")" -ge 1 ] && [ "$done_stage" -lt 3 ] && \
        bench_stage 3 2400
      # each catch-up stage gates on its OWN completion too (reviewer
      # find: a later stage failing must not re-run hours of finished
      # profile/tune/matrix work every 120 s iteration)
      [ "$(cat "$STATE")" -eq 3 ] && run_stage 4 1200 PROFILE_TPU.txt \
        bash -c "python benchmarks/profile_step.py --steps 5 > PROFILE_TPU.txt"
      [ "$(cat "$STATE")" -eq 4 ] && run_stage 5 1800 TUNE_TPU.txt \
        bash -c "python benchmarks/tune_blocks.py > TUNE_TPU.txt"
      [ "$(cat "$STATE")" -eq 5 ] && run_stage 6 3600 BENCH_MATRIX_TPU.txt \
        bash -c "python benchmarks/bench_matrix.py > BENCH_MATRIX_TPU.txt"
      # a failing on-chip long-seq run retries hourly, not every 120 s
      if [ "$(cat "$STATE")" -eq 6 ] \
          && [ $((now - last_longseq)) -ge 3600 ]; then
        longseq_stage
        last_longseq=$now
      fi
      # stage 8: overlap_comm A/B (comm.overlap decomposed rings). On the
      # single-chip tunnel the bench exits 0 with an honest "needs a
      # slice" record — still banked: it documents what this window could
      # and could not measure. Hourly retry like stage 7; CPU rehearsals
      # never promote (overlap_stage).
      if [ "$(cat "$STATE")" -eq 7 ] \
          && [ $((now - last_overlap)) -ge 3600 ]; then
        overlap_stage
        last_overlap=$now
      fi
      # stage 9: serve-engine bench (tokens/s + TTFT + occupancy + KV
      # bytes). Hourly retry like stages 7/8; CPU rehearsals never
      # promote (serve_stage).
      if [ "$(cat "$STATE")" -eq 8 ] \
          && [ $((now - last_serve)) -ge 3600 ]; then
        serve_stage
        last_serve=$now
      fi
      # stage 10: goodput-under-SLO loadgen bench, regression-gated
      # against the banked record. Hourly retry; CPU rehearsals never
      # promote (slo_stage).
      if [ "$(cat "$STATE")" -eq 9 ] \
          && [ $((now - last_slo)) -ge 3600 ]; then
        slo_stage
        last_slo=$now
      fi
      # stage 11: shared-prefix + speculative loadgen bench, regression-
      # gated like stage 10. Hourly retry; CPU rehearsals never promote
      # (prefix_stage).
      if [ "$(cat "$STATE")" -eq 10 ] \
          && [ $((now - last_prefix)) -ge 3600 ]; then
        prefix_stage
        last_prefix=$now
      fi
      # stage 12: megakernel decode A/B (serve bench with the fused
      # per-layer block forced on), regression-gated like stages 10/11.
      if [ "$(cat "$STATE")" -eq 11 ] \
          && [ $((now - last_mega)) -ge 3600 ]; then
        mega_stage
        last_mega=$now
      fi
      # stage 13: fused optimizer update tail A/B, same contract.
      if [ "$(cat "$STATE")" -eq 12 ] \
          && [ $((now - last_fusedupd)) -ge 3600 ]; then
        fusedupd_stage
        last_fusedupd=$now
      fi
      # stage 14: FSDP vs ZeRO-1 A/B, same contract.
      if [ "$(cat "$STATE")" -eq 13 ] \
          && [ $((now - last_fsdp)) -ge 3600 ]; then
        fsdp_stage
        last_fsdp=$now
      fi
      # stage 15: disaggregated serve cluster, same contract.
      if [ "$(cat "$STATE")" -eq 14 ] \
          && [ $((now - last_mh)) -ge 3600 ]; then
        mh_stage
        last_mh=$now
      fi
      # stage 16: compiled-program contract check, same contract.
      if [ "$(cat "$STATE")" -eq 15 ] \
          && [ $((now - last_analyze)) -ge 3600 ]; then
        analyze_stage
        last_analyze=$now
      fi
      # stage 17: sub-8-bit tier (int4 KV + comm wire A/B), same contract.
      if [ "$(cat "$STATE")" -eq 16 ] \
          && [ $((now - last_sub8)) -ge 3600 ]; then
        sub8_stage
        last_sub8=$now
      fi
      # stage 18: elastic serve chaos (kill-and-migrate), same contract.
      if [ "$(cat "$STATE")" -eq 17 ] \
          && [ $((now - last_chaos)) -ge 3600 ]; then
        chaos_stage
        last_chaos=$now
      fi
      # stage 19: fleet observability overhead A/B, same contract.
      if [ "$(cat "$STATE")" -eq 18 ] \
          && [ $((now - last_observe)) -ge 3600 ]; then
        observe_stage
        last_observe=$now
      fi
      # stage 20: per-tenant LoRA serve A/B, same contract.
      if [ "$(cat "$STATE")" -eq 19 ] \
          && [ $((now - last_lora)) -ge 3600 ]; then
        lora_stage
        last_lora=$now
      fi
      # stage 21: attribution + cost forensics A/B, same contract.
      if [ "$(cat "$STATE")" -eq 20 ] \
          && [ $((now - last_attrib)) -ge 3600 ]; then
        attrib_stage
        last_attrib=$now
      fi
      # stage 22: elastic train (reshard + kill-resume), same contract.
      if [ "$(cat "$STATE")" -eq 21 ] \
          && [ $((now - last_elastic)) -ge 3600 ]; then
        elastic_stage
        last_elastic=$now
      fi
      # stage 23: megakernel tier-2 flagship A/B, same contract.
      if [ "$(cat "$STATE")" -eq 22 ] \
          && [ $((now - last_megat2)) -ge 3600 ]; then
        megat2_stage
        last_megat2=$now
      fi
      # stage 24: plan-sharded serve residency, same contract.
      if [ "$(cat "$STATE")" -eq 23 ] \
          && [ $((now - last_serveplan)) -ge 3600 ]; then
        serveplan_stage
        last_serveplan=$now
      fi
      last_refresh=$now
    fi
    sleep 120
  else
    note DEAD
    sleep 240
  fi
done
